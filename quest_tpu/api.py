"""The public QuEST-compatible API surface.

Implements every user-facing function of the reference's public header
(``QuEST.h``; inventory in SURVEY.md §2.6) with the same names, argument
orders, and numerical conventions, dispatching to the pure-functional TPU ops.
Each function follows the reference's 3-step shape (``QuEST.c``):
validate -> apply -> record QASM.

Density-matrix handling improves on the reference: where ``QuEST.c:175-658``
issues *two* sequential statevector calls per gate (U on targets, conj(U) on
targets+n), we apply the single combined operator ``conj(U) (x) U`` on
``(targets, targets+n)`` — one fused pass over the 4^n amplitudes instead of
two.

Scalars returned by calc* functions are Python floats/complex (device sync);
gate application stays asynchronous on device.
"""

from __future__ import annotations

import functools
import math
import numbers
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import validation as val
from .config import Precision
from .core import matrices as mats
from .core.apply import apply_diagonal, apply_unitary, split_shape
from .env import QuESTEnv, create_quest_env, destroy_quest_env
from .ops import channels as chan
from .ops import densmatr as dm
from .ops import initstates as ist
from .ops import reductions as red
from .ops import statevec as sv
from .qureg import Qureg
from .types import PauliOpType, QuESTError

__all__ = [
    # env
    "createQuESTEnv", "destroyQuESTEnv", "syncQuESTEnv", "syncQuESTSuccess",
    "reportQuESTEnv", "getEnvironmentString", "seedQuEST", "seedQuESTDefault",
    "createSimulationService",       # serving runtime (TPU-native addition)
    "createServiceRouter",           # replicated serving (TPU-native)
    "createVariationalProblem",      # optimizer-in-the-loop (TPU-native)
    # registers
    "createQureg", "createDensityQureg", "createCloneQureg", "destroyQureg",
    "createComplexMatrixN", "destroyComplexMatrixN", "initComplexMatrixN",
    "copyStateToGPU", "copyStateFromGPU",
    # init
    "initBlankState", "initZeroState", "initPlusState", "initClassicalState",
    "initPureState", "initDebugState", "initStateFromAmps", "setAmps",
    "setDensityAmps", "cloneQureg", "setWeightedQureg",
    "initStateOfSingleQubit",
    # 1q gates
    "phaseShift", "sGate", "tGate", "pauliX", "pauliY", "pauliZ", "hadamard",
    "compactUnitary", "unitary", "rotateX", "rotateY", "rotateZ",
    "rotateAroundAxis",
    # controlled / multi-qubit
    "controlledPhaseShift", "multiControlledPhaseShift", "controlledPhaseFlip",
    "multiControlledPhaseFlip", "controlledNot", "controlledPauliY",
    "controlledRotateX", "controlledRotateY", "controlledRotateZ",
    "controlledRotateAroundAxis", "controlledCompactUnitary",
    "controlledUnitary", "multiControlledUnitary", "multiStateControlledUnitary",
    "swapGate", "sqrtSwapGate", "multiRotateZ", "multiRotatePauli",
    "twoQubitUnitary", "controlledTwoQubitUnitary",
    "multiControlledTwoQubitUnitary", "multiQubitUnitary",
    "controlledMultiQubitUnitary", "multiControlledMultiQubitUnitary",
    "applyPauliSum",
    # measurement
    "calcProbOfOutcome", "collapseToOutcome", "measure", "measureWithStats",
    "sampleOutcomes",                # TPU-native addition (no ref counterpart)
    # calculations
    "getNumQubits", "getNumAmps", "getAmp", "getRealAmp", "getImagAmp",
    "getProbAmp", "getDensityAmp", "calcTotalProb", "calcInnerProduct",
    "calcDensityInnerProduct", "calcPurity", "calcFidelity",
    "calcExpecPauliProd", "calcExpecPauliSum", "calcHilbertSchmidtDistance",
    # decoherence
    "mixDephasing", "mixTwoQubitDephasing", "mixDepolarising", "mixDamping",
    "mixTwoQubitDepolarising", "mixPauli", "mixDensityMatrix", "mixKrausMap",
    "mixTwoQubitKrausMap", "mixMultiQubitKrausMap",
    # imperative gate fusion (TPU-native addition, no ref counterpart)
    "startGateFusion", "stopGateFusion", "fusedGates",
    # QASM
    "startRecordingQASM", "stopRecordingQASM", "clearRecordedQASM",
    "printRecordedQASM", "writeRecordedQASMToFile",
    # debug / report
    "reportState", "reportStateToScreen", "reportQuregParams", "compareStates",
    "initStateFromSingleFile", "getQuEST_PREC",
]


# ---------------------------------------------------------------------------
# jitted dispatch kernels (cached per static signature)
#
# All state and matrix arguments cross the jit boundary as packed (2, ...)
# float planes (core/packing.py): the TPU backend forbids complex buffers
# between executables, so complex exists only inside the compiled programs.
# ---------------------------------------------------------------------------

from .core.packing import pack, unpack, pack_host, unpack_host  # noqa: E402


def _state_kernel(static_argnums=(), donate=True):
    """jit a packed-state kernel, appending a trailing static ``sharding``
    argument: the output keeps the amplitude sharding so GSPMD never decays a
    cross-shard gate into full replication (the pair-exchange stays a
    collective, as the reference's ``exchangeStateVectors`` does).

    ``donate``: True donates arg 0 (the in-place state update), False
    donates nothing, an int donates that argument index (kernels whose
    output replaces a non-leading register buffer)."""
    def deco(fn):
        def with_constraint(*args):
            *real, sharding = args
            out = fn(*real)
            if sharding is not None:
                out = jax.lax.with_sharding_constraint(out, sharding)
            return out

        n_args = fn.__code__.co_argcount
        if donate is True:
            donate_argnums = (0,)
        elif donate is False:
            donate_argnums = ()
        else:
            donate_argnums = (int(donate),)
        return jax.jit(with_constraint,
                       static_argnums=tuple(static_argnums) + (n_args,),
                       donate_argnums=donate_argnums)
    return deco


@_state_kernel(static_argnums=(1, 3, 4, 5))
def _jit_unitary(state_f, num_qubits, u_f, targets, ctrl_mask, flip_mask):
    out = apply_unitary(unpack(state_f), num_qubits, unpack(u_f),
                        targets, ctrl_mask, flip_mask)
    return pack(out)


@_state_kernel(static_argnums=(1, 3))
def _jit_diag(state_f, num_qubits, tensor_f, qubits_desc):
    out = apply_diagonal(unpack(state_f), num_qubits, qubits_desc,
                         unpack(tensor_f))
    return pack(out)


@_state_kernel(static_argnums=(1, 2, 3))
def _jit_swap(state_f, num_qubits, q1, q2):
    return pack(sv.swap_amps(unpack(state_f), num_qubits, q1, q2))


@_state_kernel(donate=False)
def _jit_outer(pure_f):
    """rho = |psi><psi| as a packed flat vector."""
    return pack(dm.init_pure_state(unpack(pure_f)))


def _weighted_impl(f1_f, s1_f, f2_f, s2_f, fo_f, out_f):
    return pack(sv.set_weighted(unpack(f1_f), unpack(s1_f), unpack(f2_f),
                                unpack(s2_f), unpack(fo_f), unpack(out_f)))


def _mix_linear_impl(p, a_f, b_f):
    """(1-p)*a + p*b on packed states (real p)."""
    return pack(dm.mix_density_matrix(unpack(a_f), p, unpack(b_f)))


# out-buffer donation (VERDICT r3 Weak #6): the result replaces ``out``
# (arg 5) / the mixed register (arg 1), so XLA writes in place like the
# reference (``QuEST_cpu.c:3585``) instead of materialising an extra
# register-sized buffer. The non-donating variants serve calls where the
# output register aliases an input register.
_jit_weighted = _state_kernel(donate=5)(_weighted_impl)
_jit_weighted_nodonate = _state_kernel(donate=False)(_weighted_impl)
_jit_mix_linear = _state_kernel(donate=1)(_mix_linear_impl)
_jit_mix_linear_nodonate = _state_kernel(donate=False)(_mix_linear_impl)


@_state_kernel(static_argnums=(1, 2, 3))
def _jit_mix_dephasing(state_f, num_qubits, target, prob):
    return pack(dm.mix_dephasing(unpack(state_f), num_qubits, target, prob))


@_state_kernel(static_argnums=(1, 2, 3, 4))
def _jit_mix_two_qubit_dephasing(state_f, num_qubits, q1, q2, prob):
    return pack(dm.mix_two_qubit_dephasing(unpack(state_f), num_qubits,
                                           q1, q2, prob))


@_state_kernel(static_argnums=(1, 2))
def _jit_kraus_superop(state_f, num_qubits, targets, superop_f):
    return pack(dm.apply_kraus_superoperator(
        unpack(state_f), num_qubits, targets, unpack(superop_f)))


@jax.jit
def _jit_total_prob_sv(state_f):
    return jnp.sum(state_f * state_f)


@functools.partial(jax.jit, static_argnums=(1,))
def _jit_total_prob_dm(state_f, num_qubits):
    return dm.calc_total_prob(unpack(state_f), num_qubits)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _jit_prob_outcome_sv(state_f, num_qubits, qubit, outcome):
    return sv.calc_prob_of_outcome(unpack(state_f), num_qubits, qubit, outcome)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _jit_prob_outcome_dm(state_f, num_qubits, qubit, outcome):
    return dm.calc_prob_of_outcome(unpack(state_f), num_qubits, qubit, outcome)


# -- compensated (pair-returning) variants: error-free reductions whose
# (sum, err) output is combined by the caller in host double precision —
# the float32-register route to the reference's 1e-10 scalar tolerances
# (Kahan analogue, ``QuEST_cpu_distributed.c:87-109``; ops/reductions.py)

def _pair(pair) -> float:
    s, e = pair
    return float(s) + float(e)


@jax.jit
def _jit_pair_sum_sq(state_f):
    return red.dot_pair(state_f, state_f)


def _dm_diag_real(state_f, num_qubits):
    dim = 1 << num_qubits
    return jnp.diagonal(state_f[0].reshape(dim, dim))


@functools.partial(jax.jit, static_argnums=(1,))
def _jit_pair_total_prob_dm(state_f, num_qubits):
    return red.sum_pair(_dm_diag_real(state_f, num_qubits))


@functools.partial(jax.jit, static_argnums=(1, 2))
def _jit_pair_prob_zero_sv(state_f, num_qubits, qubit):
    # outcome-1 probability is derived host-side as 1 - P0, matching the
    # reference (``statevec_calcProbOfOutcome`` QuEST_cpu_local.c:279-285)
    pre, _, post = split_shape(num_qubits, (qubit,))
    sub = state_f.reshape(2, pre, 2, post)[:, :, 0, :]
    return red.dot_pair(sub, sub)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _jit_pair_prob_zero_dm(state_f, num_qubits, qubit):
    diag = _dm_diag_real(state_f, num_qubits)
    return red.sum_pair(diag.reshape(split_shape(num_qubits, (qubit,)))[:, 0, :])


@jax.jit
def _jit_pair_inner_product(bra_f, ket_f):
    return red.vdot_pair(unpack(bra_f), unpack(ket_f))


@jax.jit
def _jit_pair_dm_inner(a_f, b_f):
    re_pair, _ = red.vdot_pair(unpack(a_f), unpack(b_f))
    return re_pair


@jax.jit
def _jit_pair_hs_sq(a_f, b_f):
    d = a_f - b_f
    return red.dot_pair(d, d)


@functools.partial(jax.jit, static_argnums=(1,))
def _jit_pair_fidelity_dm(state_f, num_qubits, pure_f):
    # rho|psi> via the MXU (f32 matvec rounding remains), then an
    # error-free final dot
    flat, psi = unpack(state_f), unpack(pure_f)
    dim = 1 << num_qubits
    rho_psi = jnp.einsum("cr,r->c", flat.reshape(dim, dim), psi,
                         precision=jax.lax.Precision.HIGHEST)
    re_pair, _ = red.vdot_pair(psi, rho_psi)
    return re_pair


@_state_kernel(static_argnums=(1, 2, 3))
def _jit_collapse_sv(state_f, num_qubits, qubit, outcome, prob):
    return pack(sv.collapse_to_known_prob_outcome(
        unpack(state_f), num_qubits, qubit, outcome, prob))


@_state_kernel(static_argnums=(1, 2, 3))
def _jit_collapse_dm(state_f, num_qubits, qubit, outcome, prob):
    return pack(dm.collapse_to_known_prob_outcome(
        unpack(state_f), num_qubits, qubit, outcome, prob))


@jax.jit
def _jit_inner_product(bra_f, ket_f):
    ip = sv.calc_inner_product(unpack(bra_f), unpack(ket_f))
    return jnp.real(ip), jnp.imag(ip)


@jax.jit
def _jit_purity(state_f):
    return jnp.sum(state_f * state_f)


@functools.partial(jax.jit, static_argnums=(1,))
def _jit_fidelity_dm(state_f, num_qubits, pure_f):
    return dm.calc_fidelity(unpack(state_f), num_qubits, unpack(pure_f))


@jax.jit
def _jit_dm_inner(a_f, b_f):
    return dm.calc_inner_product(unpack(a_f), unpack(b_f))


@jax.jit
def _jit_hs_dist(a_f, b_f):
    return dm.calc_hilbert_schmidt_distance(unpack(a_f), unpack(b_f))


from .core.apply import bitmask as _bitmask  # noqa: E402


def _packed(qureg: Qureg, mat: np.ndarray) -> jnp.ndarray:
    return jnp.asarray(pack_host(mat, qureg.real_dtype))


def _shard(qureg: Qureg):
    """Amplitude sharding for this register's env (None on single device or
    when the register is too small to split across the mesh)."""
    return qureg.sharding()


from .parallel import pergate as _pg  # noqa: E402
from .ops import doubledouble as ddm  # noqa: E402


def _canon(*quregs) -> None:
    """Restore canonical qubit layout on each register (no-op off the
    sharded per-gate path) — required before positional state reads or
    register-to-register operations."""
    for q in quregs:
        q.ensure_canonical()


def _fresh(qureg: Qureg) -> None:
    """The register's state is being fully overwritten: drop any lazy
    layout so the new array is read canonically."""
    qureg.layout = None


def _apply_gate(qureg: Qureg, u: np.ndarray, targets: Sequence[int],
                controls: Sequence[int] = (), flips: Sequence[int] = ()) -> None:
    """Apply u (with controls) to a register; density registers get the
    combined conj(u) (x) u on (targets, targets+n) in one pass.

    On a mesh this routes per gate through the lazy-layout shard_map path
    (``parallel/pergate.py``): local targets run on the chunk, a sharded
    1q target runs as the role-split pair exchange, and multi-qubit
    sharded targets cost ONE batched swap-to-local whose swap-back is
    deferred — strictly less data movement than the reference's per-gate
    exchange-or-swap routing (``QuEST_cpu_distributed.c:843-878,
    1420-1461``)."""
    n = qureg.num_qubits_represented
    targets = tuple(int(t) for t in targets)
    ctrl_mask, flip_mask = _bitmask(controls), _bitmask(flips)
    if qureg.is_quad:
        return _dd_gate(qureg, u, targets, ctrl_mask, flip_mask)
    buf = qureg._fusion_buffer
    if buf is not None and not buf.flushing:
        # opt-in imperative fusion (startGateFusion): record the LOGICAL
        # gate; the buffer contracts and dispatches at the next state read
        buf.add_gate(u, targets, ctrl_mask, flip_mask)
        return
    lazy = _pg.use_lazy(qureg)
    if qureg.is_density_matrix and not ctrl_mask:
        # fused single pass: conj(U) (x) U on (targets, targets+n)
        u2 = np.kron(np.conj(u), u)
        targets2 = targets + tuple(t + n for t in targets)
        if lazy and not _pg.fits_local(qureg, len(targets2)):
            lazy = False
            _canon(qureg)     # register too small for the mesh: GSPMD path
        if lazy:
            _pg.sharded_unitary(qureg, _packed(qureg, u2), targets2, 0, 0)
        else:
            qureg.state = _jit_unitary(qureg.state, 2 * n, _packed(qureg, u2),
                                       targets2, 0, 0, _shard(qureg))
    elif qureg.is_density_matrix:
        # row- and column-side controls condition independently, so a
        # controlled gate needs the reference's two-pass form
        # (``QuEST.c:352-357``): U on (targets | controls), then conj(U) on
        # the shifted copies
        if lazy and not _pg.fits_local(qureg, len(targets)):
            lazy = False
            _canon(qureg)
        if lazy:
            _pg.sharded_unitary(qureg, _packed(qureg, u), targets,
                                ctrl_mask, flip_mask)
            _pg.sharded_unitary(qureg, _packed(qureg, np.conj(u)),
                                tuple(t + n for t in targets),
                                ctrl_mask << n, flip_mask << n)
        else:
            qureg.state = _jit_unitary(qureg.state, 2 * n, _packed(qureg, u),
                                       targets, ctrl_mask, flip_mask,
                                       _shard(qureg))
            qureg.state = _jit_unitary(qureg.state, 2 * n,
                                       _packed(qureg, np.conj(u)),
                                       tuple(t + n for t in targets),
                                       ctrl_mask << n, flip_mask << n,
                                       _shard(qureg))
    elif lazy and _pg.fits_local(qureg, len(targets)):
        _pg.sharded_unitary(qureg, _packed(qureg, u), targets,
                            ctrl_mask, flip_mask)
    else:
        if lazy:
            _canon(qureg)
        qureg.state = _jit_unitary(qureg.state, n, _packed(qureg, u),
                                   targets, ctrl_mask, flip_mask,
                                   _shard(qureg))


def _dd_gate(qureg: Qureg, u: np.ndarray, targets: tuple,
             ctrl_mask: int, flip_mask: int) -> None:
    """QUAD-register gate application: dense k-qubit dd kernels
    (``ops/doubledouble.py``) with the same density-matrix dispatch shapes
    as the native-precision path."""
    n = qureg.num_qubits_represented
    if qureg.is_density_matrix and not ctrl_mask:
        u2 = np.kron(np.conj(u), u)
        t2 = targets + tuple(t + n for t in targets)
        qureg.state = ddm.dd_apply_kq(qureg.state, 2 * n, u2, t2)
    elif qureg.is_density_matrix:
        qureg.state = ddm.dd_apply_kq(qureg.state, 2 * n, u, targets,
                                      ctrl_mask, flip_mask)
        qureg.state = ddm.dd_apply_kq(qureg.state, 2 * n, np.conj(u),
                                      tuple(t + n for t in targets),
                                      ctrl_mask << n, flip_mask << n)
    else:
        qureg.state = ddm.dd_apply_kq(qureg.state, n, u, targets,
                                      ctrl_mask, flip_mask)


def _apply_diag_gate(qureg: Qureg, tensor: np.ndarray,
                     qubits: Sequence[int]) -> None:
    """Apply a diagonal factor tensor (axis i = i-th qubit of ``qubits``
    sorted descending); density registers get conj on the column side.
    On a mesh, diagonals run at ANY physical position with zero
    communication (the ``statevec_phaseShiftByTerm`` no-pairing property),
    so they never disturb the lazy layout."""
    n = qureg.num_qubits_represented
    qs = tuple(sorted((int(q) for q in qubits), reverse=True))
    tensor = np.asarray(tensor, dtype=np.complex128)
    if not qureg.is_quad:
        buf = qureg._fusion_buffer
        if buf is not None and not buf.flushing:
            buf.add_diag(tensor, qs)
            return
    if qureg.is_density_matrix:
        tensor = np.multiply.outer(np.conj(tensor), tensor)
        qs = tuple(q + n for q in qs) + qs
    if qureg.is_quad:
        qureg.state = ddm.dd_apply_diag(
            qureg.state, qureg.num_qubits_in_state_vec, tensor, qs)
        return
    if _pg.use_lazy(qureg):
        _pg.sharded_diag(qureg, tensor, qs)
        return
    qureg.state = _jit_diag(qureg.state, qureg.num_qubits_in_state_vec,
                            _packed(qureg, tensor), qs, _shard(qureg))


def _dispatch_fused_op(qureg: Qureg, op) -> None:
    """Apply one fused-group record from the imperative fusion buffer
    through the regular per-gate dispatch (called with the buffer's
    ``flushing`` flag set, so the recursion bottoms out)."""
    if op.kind == "u":
        controls = tuple(q for q in range(qureg.num_qubits_represented)
                         if (op.ctrl_mask >> q) & 1)
        flips = tuple(c for c in controls if (op.flip_mask >> c) & 1)
        _apply_gate(qureg, op.mat, op.targets, controls, flips)
    else:
        _apply_diag_gate(qureg, op.diag, op.targets)


def startGateFusion(qureg: Qureg, max_qubits: int = 3) -> None:
    """Buffer subsequent imperative gate calls and dispatch them as fused
    groups of combined support <= ``max_qubits`` (the compiled pipeline's
    gate-fusion engine, :mod:`quest_tpu.core.fusion`, applied to the
    per-gate path). Flushing is automatic at any state read (measure,
    calc*, get*, compiled run, host copy) and at :func:`stopGateFusion`.
    No reference counterpart; QUAD registers are unsupported (their
    double-double kernels dispatch eagerly)."""
    if qureg.is_quad:
        raise QuESTError("gate fusion is not supported on QUAD registers")
    new = _pg.GateFusionBuffer(qureg, max_qubits)
    buf = qureg._fusion_buffer
    if buf is not None:
        if buf.max_k == new.max_k:
            return                      # already active at this budget
        buf.flush()                     # re-arm at the new support cap
    qureg._fusion_buffer = new


def stopGateFusion(qureg: Qureg) -> None:
    """Flush any buffered gates and return to eager per-gate dispatch."""
    buf = qureg._fusion_buffer
    if buf is not None:
        buf.flush()
        qureg._fusion_buffer = None


class fusedGates:
    """Context manager form of :func:`startGateFusion` ::

        with qt.fusedGates(qureg, max_qubits=3):
            for q in range(n):
                qt.hadamard(qureg, q)      # buffered, dispatched fused

    Contexts nest: the inner block flushes on exit and the outer
    buffer resumes (where a bare ``stopGateFusion`` turns fusion off
    entirely).
    """

    def __init__(self, qureg: Qureg, max_qubits: int = 3):
        self.qureg = qureg
        self.max_qubits = max_qubits

    def __enter__(self):
        self._prev = self.qureg._fusion_buffer
        startGateFusion(self.qureg, self.max_qubits)
        return self.qureg

    def __exit__(self, *exc):
        buf = self.qureg._fusion_buffer
        if buf is not None:
            buf.flush()
        self.qureg._fusion_buffer = self._prev
        return False


# ---------------------------------------------------------------------------
# environment (QuEST.h:785-832)
# ---------------------------------------------------------------------------

def createQuESTEnv(num_devices: Optional[int] = None,
                   precision: Optional[Precision] = None,
                   seed: Optional[Sequence[int]] = None,
                   compensated: Optional[bool] = None) -> QuESTEnv:
    return create_quest_env(num_devices=num_devices, precision=precision,
                            seed=seed, compensated=compensated)


def destroyQuESTEnv(env: QuESTEnv) -> None:
    destroy_quest_env(env)


def syncQuESTEnv(env: QuESTEnv) -> None:
    env.sync()


def syncQuESTSuccess(success_code: int) -> int:
    """Logical-AND agreement across ranks (``QuEST_cpu_distributed.c:163``);
    SPMD programs agree by construction."""
    return int(bool(success_code))


def reportQuESTEnv(env: QuESTEnv) -> None:
    print(env.report())


def getEnvironmentString(env: QuESTEnv) -> str:
    """Backend capability summary (``getEnvironmentString`` ``QuEST.h:832``,
    which reports CUDA/OpenMP/MPI flags): reports the backend actually
    carrying the computation, not a hardcoded assumption."""
    mode = "mesh" if env.mesh is not None else "local"
    platforms = {d.platform for d in jax.devices()}
    on_tpu = 1 if platforms & {"tpu", "axon"} else 0
    return (f"CUDA=0 OpenMP=0 MPI=0 TPU={on_tpu} backend="
            f"{jax.default_backend()} mode={mode} "
            f"threads=1 ranks={env.num_ranks}")


def seedQuEST(env: QuESTEnv, seeds: Sequence[int]) -> None:
    env.seed(seeds)


def seedQuESTDefault(env: QuESTEnv) -> None:
    env.seed_default()


def createServiceRouter(envs=None, **kwargs):
    """Create a replicated serving front end — N
    :class:`quest_tpu.serve.SimulationService` replicas behind one
    ``submit()`` with health-aware routing, replica failover with
    supervised restart, and the persistent warm-start compile cache
    (:class:`quest_tpu.serve.router.ServiceRouter`; TPU-native
    addition, no reference counterpart). Pass ``envs`` (one
    ``QuESTEnv`` per replica, e.g. from
    :func:`quest_tpu.serve.replica_envs`) or ``num_replicas=`` /
    ``devices_per_replica=`` to slice ``jax.devices()``; remaining
    keyword arguments are the per-replica service knobs plus
    ``supervisor`` (a :class:`quest_tpu.resilience.SupervisorPolicy`),
    ``max_failovers``, ``hedge_after_s``, and ``warm_cache``. Destroy
    with ``router.close()`` (or use it as a context manager)."""
    from .serve import ServiceRouter
    return ServiceRouter(envs, **kwargs)


def createVariationalProblem(circuit, observables, x0, **kwargs):
    """Name a variational workload for the optimizer-in-the-loop
    serving API (:class:`quest_tpu.serve.optimize.VariationalProblem`;
    TPU-native addition, no reference counterpart): ``circuit`` (a
    recorded :class:`~quest_tpu.circuits.Circuit` with Param angles),
    the ``(pauli_terms, coeffs)`` objective, and the starting point
    ``x0`` (name->angle dict or ordered vector). Keyword arguments:
    ``trajectories``/``sampling_budget`` (noisy objectives through the
    differentiable trajectory wave loop) and ``tier``. Run it with
    ``service.optimize(problem, ...)`` or ``router.optimize(...)`` —
    each iterate is one coalesced ``kind="gradient"`` dispatch, and
    the returned handle streams iterates as incremental results."""
    from .serve import VariationalProblem
    return VariationalProblem(circuit, observables, x0, **kwargs)


def createSimulationService(env: QuESTEnv, **kwargs):
    """Create an asynchronous serving runtime over ``env`` — the
    request-coalescing front end for many-caller workloads
    (:class:`quest_tpu.serve.SimulationService`; TPU-native addition,
    no reference counterpart). Keyword arguments are the service knobs:
    ``max_queue``, ``max_batch``, ``max_wait_s``, ``request_timeout_s``,
    ``max_retries``, ``resilience`` (a
    :class:`quest_tpu.resilience.ResiliencePolicy` — retry backoff,
    circuit breaker, batch quarantine, watchdog), and
    ``trace_sample_rate`` (request-scoped tracing,
    :mod:`quest_tpu.telemetry`). Destroy with ``service.close()`` (or
    use it as a context manager)."""
    from .serve import SimulationService
    return SimulationService(env, **kwargs)


# ---------------------------------------------------------------------------
# register management (QuEST.h:224-292)
# ---------------------------------------------------------------------------

def createQureg(num_qubits: int, env: QuESTEnv) -> Qureg:
    val.validate_num_qubits(num_qubits, "createQureg")
    q = Qureg(num_qubits, env, is_density=False)
    initZeroState(q)
    return q


def createDensityQureg(num_qubits: int, env: QuESTEnv) -> Qureg:
    val.validate_num_qubits(num_qubits, "createDensityQureg")
    q = Qureg(num_qubits, env, is_density=True)
    initZeroState(q)
    return q


def createCloneQureg(qureg: Qureg, env: QuESTEnv) -> Qureg:
    new = Qureg(qureg.num_qubits_represented, env,
                is_density=qureg.is_density_matrix)
    # deep copy: gate kernels donate their input buffer, so clones must not
    # alias the source register's storage
    _canon(qureg)
    new.state = jnp.array(qureg.state, copy=True)
    return new


def destroyQureg(qureg: Qureg, env: QuESTEnv = None) -> None:
    qureg.state = None


def createComplexMatrixN(num_qubits: int) -> np.ndarray:
    val.validate_num_qubits(num_qubits, "createComplexMatrixN")
    d = 1 << num_qubits
    return np.zeros((d, d), dtype=np.complex128)


def destroyComplexMatrixN(m: np.ndarray) -> None:
    pass  # numpy arrays are GC-managed; kept for API parity


def initComplexMatrixN(m: np.ndarray, re, im) -> None:
    m[...] = np.asarray(re, dtype=np.float64) + 1j * np.asarray(im, dtype=np.float64)


def copyStateToGPU(qureg: Qureg) -> None:
    """No-op: amplitudes already live on device (``copyStateToGPU``
    ``QuEST.h:855`` exists because the reference mirrors host/device copies)."""
    jax.block_until_ready(qureg.state)


def copyStateFromGPU(qureg: Qureg) -> None:
    jax.block_until_ready(qureg.state)


# ---------------------------------------------------------------------------
# state initialisation (QuEST.h:383-506)
# ---------------------------------------------------------------------------

def initBlankState(qureg: Qureg) -> None:
    _fresh(qureg)
    qureg.state = ist.blank(qureg.num_amps_total, qureg.real_dtype,
                            qureg.sharding(), quad=qureg.is_quad)
    qureg.qasm_log.record_comment(
        "the register was set to the unphysical all-zero-amplitudes state")


def initZeroState(qureg: Qureg) -> None:
    _fresh(qureg)
    qureg.state = ist.zero(qureg.num_amps_total, qureg.real_dtype,
                           qureg.sharding(), quad=qureg.is_quad)
    qureg.qasm_log.record_init_zero()


def initPlusState(qureg: Qureg) -> None:
    n = qureg.num_qubits_represented
    amp = (1.0 / (1 << n)) if qureg.is_density_matrix \
        else (1.0 / np.sqrt(1 << n))
    _fresh(qureg)
    qureg.state = ist.plus(qureg.num_amps_total, qureg.real_dtype,
                           qureg.sharding(), amp, quad=qureg.is_quad)
    qureg.qasm_log.record_init_plus()


def initClassicalState(qureg: Qureg, state_ind: int) -> None:
    val.validate_state_index(qureg.num_qubits_represented, state_ind,
                             "initClassicalState")
    idx = state_ind * ((1 << qureg.num_qubits_represented) + 1) \
        if qureg.is_density_matrix else state_ind
    _fresh(qureg)
    qureg.state = ist.classical(qureg.num_amps_total, qureg.real_dtype,
                                qureg.sharding(), idx, quad=qureg.is_quad)
    qureg.qasm_log.record_init_classical(state_ind)


def initPureState(qureg: Qureg, pure: Qureg) -> None:
    val.validate_second_qureg_state_vec(pure.is_density_matrix, "initPureState")
    val.validate_matching_precision(qureg.env.precision.quest_prec,
                                    pure.env.precision.quest_prec,
                                    "initPureState")
    val.validate_matching_dims(qureg.num_qubits_represented,
                               pure.num_qubits_represented, "initPureState")
    _canon(pure)
    _fresh(qureg)
    if qureg.is_quad:
        if qureg.is_density_matrix:
            # |psi><psi| as a dd outer product on device — the lo planes
            # survive, so QUAD64 keeps its ~106-bit envelope
            qureg.state = ddm.dd_outer(pure.state, conj_left=False)
        else:
            qureg.state = jnp.array(pure.state, copy=True)
    elif qureg.is_density_matrix:
        qureg.state = _jit_outer(pure.state, _shard(qureg))
    else:
        qureg.state = jnp.array(pure.state, copy=True)
    qureg.qasm_log.record_comment(
        "the register was initialised to an undisclosed pure state")


def initDebugState(qureg: Qureg) -> None:
    _fresh(qureg)
    qureg.state = ist.debug(qureg.num_amps_total, qureg.real_dtype,
                            qureg.sharding(), quad=qureg.is_quad)


def initStateFromAmps(qureg: Qureg, reals, imags) -> None:
    val.validate_state_vec(qureg.is_density_matrix, "initStateFromAmps")
    arr = np.asarray(reals, dtype=np.float64) + 1j * np.asarray(imags, np.float64)
    val.validate_num_amps(qureg.num_amps_total, 0, arr.size, "initStateFromAmps")
    if arr.size != qureg.num_amps_total:
        val._fail("the amplitude arrays must cover the full register",
                  "initStateFromAmps", val.ErrorCode.E_INVALID_NUM_AMPS)
    qureg.device_put(arr)
    qureg.qasm_log.record_comment(
        "the register was initialised to an undisclosed pure state")


def setAmps(qureg: Qureg, start_ind: int, reals, imags, num_amps: int) -> None:
    val.validate_state_vec(qureg.is_density_matrix, "setAmps")
    val.validate_num_amps(qureg.num_amps_total, start_ind, num_amps, "setAmps")
    re64 = np.asarray(reals, np.float64)[:num_amps]
    im64 = np.asarray(imags, np.float64)[:num_amps]
    _canon(qureg)
    if qureg.is_quad:
        from .ops.doubledouble import _dd_split_host
        vals = _dd_split_host(re64 + 1j * im64, qureg.real_dtype)
    else:
        vals = np.stack([re64, im64])
    qureg.state = qureg.state.at[:, start_ind:start_ind + num_amps].set(
        jnp.asarray(vals, qureg.real_dtype))
    qureg.qasm_log.record_comment("amplitudes were manually edited")


def setDensityAmps(qureg: Qureg, reals, imags) -> None:
    arr = np.asarray(reals, np.float64).reshape(-1) \
        + 1j * np.asarray(imags, np.float64).reshape(-1)
    if arr.size != qureg.num_amps_total:
        val._fail("the amplitude arrays must cover the full density matrix",
                  "setDensityAmps", val.ErrorCode.E_INVALID_NUM_AMPS)
    qureg.device_put(arr)
    qureg.qasm_log.record_comment("density-matrix amplitudes were manually edited")


def cloneQureg(target: Qureg, copy: Qureg) -> None:
    val.validate_matching_types(target.is_density_matrix,
                                copy.is_density_matrix, "cloneQureg")
    val.validate_matching_precision(target.env.precision.quest_prec,
                                    copy.env.precision.quest_prec,
                                    "cloneQureg")
    val.validate_matching_dims(target.num_qubits_represented,
                               copy.num_qubits_represented, "cloneQureg")
    _canon(copy)
    _fresh(target)
    target.state = jnp.array(copy.state, copy=True)


def setWeightedQureg(fac1, qureg1: Qureg, fac2, qureg2: Qureg,
                     fac_out, out: Qureg) -> None:
    val.validate_matching_types(qureg1.is_density_matrix,
                                qureg2.is_density_matrix, "setWeightedQureg")
    val.validate_matching_precision(qureg1.env.precision.quest_prec,
                                    qureg2.env.precision.quest_prec,
                                    "setWeightedQureg")
    val.validate_matching_precision(qureg1.env.precision.quest_prec,
                                    out.env.precision.quest_prec,
                                    "setWeightedQureg")
    val.validate_matching_types(qureg1.is_density_matrix,
                                out.is_density_matrix, "setWeightedQureg")
    val.validate_matching_dims(qureg1.num_qubits_represented,
                               qureg2.num_qubits_represented, "setWeightedQureg")
    val.validate_matching_dims(qureg1.num_qubits_represented,
                               out.num_qubits_represented, "setWeightedQureg")
    rd = out.real_dtype
    if out.is_quad:
        out.state = ddm.dd_weighted(fac1, qureg1.state, fac2, qureg2.state,
                                    fac_out, out.state)
        out.qasm_log.record_comment(
            "the register was set to a weighted combination "
            "(possibly unphysical)")
        return
    _canon(qureg1, qureg2, out)
    # donate out's buffer unless it aliases an input register's storage
    kernel = _jit_weighted if (out.state is not qureg1.state
                               and out.state is not qureg2.state) \
        else _jit_weighted_nodonate
    out.state = kernel(
        jnp.asarray(pack_host(np.asarray(fac1, np.complex128), rd)),
        qureg1.state,
        jnp.asarray(pack_host(np.asarray(fac2, np.complex128), rd)),
        qureg2.state,
        jnp.asarray(pack_host(np.asarray(fac_out, np.complex128), rd)),
        out.state, _shard(out))
    out.qasm_log.record_comment(
        "the register was set to a weighted combination (possibly unphysical)")


def initStateOfSingleQubit(qureg: Qureg, qubit: int, outcome: int) -> None:
    val.validate_state_vec(qureg.is_density_matrix, "initStateOfSingleQubit")
    val.validate_target(qureg.num_qubits_represented, qubit,
                        "initStateOfSingleQubit")
    val.validate_outcome(outcome, "initStateOfSingleQubit")
    _fresh(qureg)
    qureg.state = ist.single_qubit_outcome(
        qureg.num_amps_total, qureg.real_dtype, qureg.sharding(),
        qubit, outcome, quad=qureg.is_quad)


# ---------------------------------------------------------------------------
# single-qubit gates (QuEST.h:540-1583)
# ---------------------------------------------------------------------------

def hadamard(qureg: Qureg, target: int) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "hadamard")
    _apply_gate(qureg, mats.hadamard(), (target,))
    qureg.qasm_log.record_gate("hadamard", target)


def pauliX(qureg: Qureg, target: int) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "pauliX")
    _apply_gate(qureg, mats.pauli_x(), (target,))
    qureg.qasm_log.record_gate("sigma_x", target)


def pauliY(qureg: Qureg, target: int) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "pauliY")
    _apply_gate(qureg, mats.pauli_y(), (target,))
    qureg.qasm_log.record_gate("sigma_y", target)


def pauliZ(qureg: Qureg, target: int) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "pauliZ")
    _apply_diag_gate(qureg, np.array([1.0, -1.0]), (target,))
    qureg.qasm_log.record_gate("sigma_z", target)


def sGate(qureg: Qureg, target: int) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "sGate")
    _apply_diag_gate(qureg, np.array([1.0, 1j]), (target,))
    qureg.qasm_log.record_gate("s", target)


def tGate(qureg: Qureg, target: int) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "tGate")
    _apply_diag_gate(qureg, np.array([1.0, np.exp(1j * np.pi / 4)]), (target,))
    qureg.qasm_log.record_gate("t", target)


def phaseShift(qureg: Qureg, target: int, angle: float) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "phaseShift")
    _apply_diag_gate(qureg, np.array([1.0, np.exp(1j * angle)]), (target,))
    qureg.qasm_log.record_param_gate("phase_shift", target, angle)


def compactUnitary(qureg: Qureg, target: int, alpha, beta) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "compactUnitary")
    val.validate_unitary_complex_pair(alpha, beta, "compactUnitary",
                                      qureg.env.precision.eps)
    _apply_gate(qureg, mats.compact_unitary(alpha, beta), (target,))
    qureg.qasm_log.record_compact_unitary(alpha, beta, target)


def unitary(qureg: Qureg, target: int, u) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "unitary")
    u = mats.matrix2(u)
    val.validate_unitary(u, "unitary", qureg.env.precision.eps)
    _apply_gate(qureg, u, (target,))
    qureg.qasm_log.record_unitary(u, target)


def rotateX(qureg: Qureg, target: int, angle: float) -> None:
    rotateAroundAxis(qureg, target, angle, (1.0, 0.0, 0.0), _label="rotate_x",
                     _angle=angle)


def rotateY(qureg: Qureg, target: int, angle: float) -> None:
    rotateAroundAxis(qureg, target, angle, (0.0, 1.0, 0.0), _label="rotate_y",
                     _angle=angle)


def rotateZ(qureg: Qureg, target: int, angle: float) -> None:
    rotateAroundAxis(qureg, target, angle, (0.0, 0.0, 1.0), _label="rotate_z",
                     _angle=angle)


def rotateAroundAxis(qureg: Qureg, target: int, angle: float, axis,
                     _label: Optional[str] = None,
                     _angle: Optional[float] = None) -> None:
    val.validate_target(qureg.num_qubits_represented, target, "rotateAroundAxis")
    val.validate_vector(axis, "rotateAroundAxis",
                        qureg.env.precision.eps)
    _apply_gate(qureg, mats.rotation(angle, axis), (target,))
    if _label is not None:
        qureg.qasm_log.record_param_gate(_label, target, _angle)
    else:
        qureg.qasm_log.record_axis_rotation(angle, axis, target)


# ---------------------------------------------------------------------------
# controlled gates (QuEST.h:583-1669)
# ---------------------------------------------------------------------------

def controlledNot(qureg: Qureg, control: int, target: int) -> None:
    val.validate_control_target(qureg.num_qubits_represented, control, target,
                                "controlledNot")
    _apply_gate(qureg, mats.pauli_x(), (target,), (control,))
    qureg.qasm_log.record_gate("sigma_x", target, (control,))


def controlledPauliY(qureg: Qureg, control: int, target: int) -> None:
    val.validate_control_target(qureg.num_qubits_represented, control, target,
                                "controlledPauliY")
    _apply_gate(qureg, mats.pauli_y(), (target,), (control,))
    qureg.qasm_log.record_gate("sigma_y", target, (control,))


def controlledPhaseShift(qureg: Qureg, q1: int, q2: int, angle: float) -> None:
    val.validate_control_target(qureg.num_qubits_represented, q1, q2,
                                "controlledPhaseShift")
    tensor = np.ones((2, 2), dtype=np.complex128)
    tensor[1, 1] = np.exp(1j * angle)
    _apply_diag_gate(qureg, tensor, (q1, q2))
    qureg.qasm_log.record_param_gate("phase_shift", q2, angle, (q1,))


def multiControlledPhaseShift(qureg: Qureg, qubits: Sequence[int],
                              angle: float) -> None:
    val.validate_multi_qubits(qureg.num_qubits_represented, qubits,
                              "multiControlledPhaseShift")
    k = len(qubits)
    tensor = np.ones((2,) * k, dtype=np.complex128)
    tensor[(1,) * k] = np.exp(1j * angle)
    _apply_diag_gate(qureg, tensor, qubits)
    qureg.qasm_log.record_param_gate("phase_shift", qubits[-1], angle,
                                     tuple(qubits[:-1]),
                                     kind="multicontrolled")


def controlledPhaseFlip(qureg: Qureg, q1: int, q2: int) -> None:
    val.validate_control_target(qureg.num_qubits_represented, q1, q2,
                                "controlledPhaseFlip")
    tensor = np.ones((2, 2), dtype=np.complex128)
    tensor[1, 1] = -1.0
    _apply_diag_gate(qureg, tensor, (q1, q2))
    qureg.qasm_log.record_gate("sigma_z", q2, (q1,))


def multiControlledPhaseFlip(qureg: Qureg, qubits: Sequence[int]) -> None:
    val.validate_multi_qubits(qureg.num_qubits_represented, qubits,
                              "multiControlledPhaseFlip")
    k = len(qubits)
    tensor = np.ones((2,) * k, dtype=np.complex128)
    tensor[(1,) * k] = -1.0
    _apply_diag_gate(qureg, tensor, qubits)
    qureg.qasm_log.record_gate("sigma_z", qubits[-1], tuple(qubits[:-1]))


def controlledRotateX(qureg, control, target, angle):
    controlledRotateAroundAxis(qureg, control, target, angle, (1, 0, 0),
                               _label="rotate_x", _angle=angle)


def controlledRotateY(qureg, control, target, angle):
    controlledRotateAroundAxis(qureg, control, target, angle, (0, 1, 0),
                               _label="rotate_y", _angle=angle)


def controlledRotateZ(qureg, control, target, angle):
    controlledRotateAroundAxis(qureg, control, target, angle, (0, 0, 1),
                               _label="rotate_z", _angle=angle)


def controlledRotateAroundAxis(qureg: Qureg, control: int, target: int,
                               angle: float, axis,
                               _label: Optional[str] = None,
                               _angle: Optional[float] = None) -> None:
    val.validate_control_target(qureg.num_qubits_represented, control, target,
                                "controlledRotateAroundAxis")
    val.validate_vector(axis, "controlledRotateAroundAxis",
                        qureg.env.precision.eps)
    _apply_gate(qureg, mats.rotation(angle, axis), (target,), (control,))
    if _label is not None:
        qureg.qasm_log.record_param_gate(_label, target, _angle, (control,))
    else:
        qureg.qasm_log.record_axis_rotation(angle, axis, target, (control,))


def controlledCompactUnitary(qureg: Qureg, control: int, target: int,
                             alpha, beta) -> None:
    val.validate_control_target(qureg.num_qubits_represented, control, target,
                                "controlledCompactUnitary")
    val.validate_unitary_complex_pair(alpha, beta, "controlledCompactUnitary",
                                      qureg.env.precision.eps)
    _apply_gate(qureg, mats.compact_unitary(alpha, beta), (target,), (control,))
    qureg.qasm_log.record_compact_unitary(alpha, beta, target, (control,))


def controlledUnitary(qureg: Qureg, control: int, target: int, u) -> None:
    val.validate_control_target(qureg.num_qubits_represented, control, target,
                                "controlledUnitary")
    u = mats.matrix2(u)
    val.validate_unitary(u, "controlledUnitary", qureg.env.precision.eps)
    _apply_gate(qureg, u, (target,), (control,))
    qureg.qasm_log.record_unitary(u, target, (control,))


def multiControlledUnitary(qureg: Qureg, controls: Sequence[int],
                           target: int, u) -> None:
    val.validate_multi_controls_target(
        qureg.num_qubits_represented, controls, target,
        "multiControlledUnitary")
    u = mats.matrix2(u)
    val.validate_unitary(u, "multiControlledUnitary", qureg.env.precision.eps)
    _apply_gate(qureg, u, (target,), tuple(controls))
    qureg.qasm_log.record_unitary(u, target, tuple(controls),
                                  kind="multicontrolled")


def multiStateControlledUnitary(qureg: Qureg, controls: Sequence[int],
                                control_state: Sequence[int],
                                target: int, u) -> None:
    val.validate_multi_controls_target(
        qureg.num_qubits_represented, controls, target,
        "multiStateControlledUnitary")
    val.validate_control_state(control_state, len(controls),
                               "multiStateControlledUnitary")
    u = mats.matrix2(u)
    val.validate_unitary(u, "multiStateControlledUnitary",
                         qureg.env.precision.eps)
    flips = tuple(c for c, s in zip(controls, control_state) if s == 0)
    _apply_gate(qureg, u, (target,), tuple(controls), flips)
    qureg.qasm_log.record_multi_state_controlled_unitary(
        u, tuple(controls), tuple(control_state), target)


# ---------------------------------------------------------------------------
# two-/multi-qubit gates (QuEST.h:2232-3043)
# ---------------------------------------------------------------------------

def swapGate(qureg: Qureg, q1: int, q2: int) -> None:
    val.validate_unique_targets(qureg.num_qubits_represented, q1, q2, "swapGate")
    n = qureg.num_qubits_represented
    if qureg.is_quad:
        # dense dd application of the permutation matrix: multiplies by
        # exact 0/1 entries, so it stays error-free
        _dd_gate(qureg, mats.swap(), (int(q1), int(q2)), 0, 0)
        qureg.qasm_log.record_gate("swap", q2, (q1,))
        return
    buf = qureg._fusion_buffer
    if buf is not None and not buf.flushing:
        # fusion active: the swap must keep program order with buffered
        # gates, so it rides the buffer as a dense 2q member (and fuses)
        # rather than mutating layout metadata underneath them
        _apply_gate(qureg, mats.swap(), (int(q1), int(q2)))
    elif _pg.use_lazy(qureg):
        # on a mesh a SWAP is pure layout metadata — zero data movement
        # (the reference exchanges chunks, ``statevec_swapQubitAmps``
        # ``QuEST_cpu_distributed.c:1355-1371``)
        _pg.metadata_swap(qureg, q1, q2)
        if qureg.is_density_matrix:
            _pg.metadata_swap(qureg, q1 + n, q2 + n)
    elif qureg.is_density_matrix:
        qureg.state = _jit_swap(qureg.state, 2 * n, q1, q2, _shard(qureg))
        qureg.state = _jit_swap(qureg.state, 2 * n, q1 + n, q2 + n, _shard(qureg))
    else:
        qureg.state = _jit_swap(qureg.state, n, q1, q2, _shard(qureg))
    qureg.qasm_log.record_gate("swap", q2, (q1,))


def sqrtSwapGate(qureg: Qureg, q1: int, q2: int) -> None:
    val.validate_unique_targets(qureg.num_qubits_represented, q1, q2,
                                "sqrtSwapGate")
    _apply_gate(qureg, mats.sqrt_swap(), (q1, q2))
    qureg.qasm_log.record_gate("sqrt_swap", q2, (q1,))


def multiRotateZ(qureg: Qureg, qubits: Sequence[int], angle: float) -> None:
    val.validate_multi_targets(qureg.num_qubits_represented, qubits,
                               "multiRotateZ")
    k = len(qubits)
    _apply_diag_gate(qureg, sv.multi_rotate_z_diag(k, angle), qubits)
    qureg.qasm_log.record_comment(
        f"a {k}-qubit multiRotateZ of angle {angle:g} was applied")


def multiRotatePauli(qureg: Qureg, targets: Sequence[int],
                     paulis: Sequence[int], angle: float) -> None:
    """exp(-i angle/2 P1 (x) P2 ...) via basis rotation to Z then multiRotateZ
    (``statevec_multiRotatePauli`` ``QuEST_common.c:410-447``). Composed from
    density-aware primitives, so the conj side is handled per-gate."""
    val.validate_multi_targets(qureg.num_qubits_represented, targets,
                               "multiRotatePauli")
    val.validate_pauli_codes(paulis, "multiRotatePauli")
    fac = 1.0 / np.sqrt(2.0)
    u_rx = mats.compact_unitary(fac, -1j * fac)    # rotates Z -> Y
    u_ry = mats.compact_unitary(fac, -fac)         # rotates Z -> X
    z_targets = []
    for t, p in zip(targets, paulis):
        p = int(p)
        if p == PauliOpType.PAULI_X:
            _apply_gate(qureg, u_ry, (t,))
        elif p == PauliOpType.PAULI_Y:
            _apply_gate(qureg, u_rx, (t,))
        if p != PauliOpType.PAULI_I:
            z_targets.append(t)
    if z_targets:
        _apply_diag_gate(qureg, sv.multi_rotate_z_diag(len(z_targets), angle),
                         z_targets)
    for t, p in zip(targets, paulis):
        p = int(p)
        if p == PauliOpType.PAULI_X:
            _apply_gate(qureg, u_ry.conj().T, (t,))
        elif p == PauliOpType.PAULI_Y:
            _apply_gate(qureg, u_rx.conj().T, (t,))
    qureg.qasm_log.record_comment(
        f"a {len(targets)}-qubit multiRotatePauli of angle {angle:g} was applied")


def twoQubitUnitary(qureg: Qureg, t1: int, t2: int, u) -> None:
    val.validate_multi_targets(qureg.num_qubits_represented, (t1, t2),
                               "twoQubitUnitary")
    u = mats.matrix4(u)
    val.validate_unitary(u, "twoQubitUnitary", qureg.env.precision.eps)
    _apply_gate(qureg, u, (t1, t2))
    qureg.qasm_log.record_comment("an undisclosed 2-qubit unitary was applied")


def controlledTwoQubitUnitary(qureg: Qureg, control: int, t1: int, t2: int,
                              u) -> None:
    val.validate_multi_controls_multi_targets(
        qureg.num_qubits_represented, (control,), (t1, t2),
        "controlledTwoQubitUnitary")
    u = mats.matrix4(u)
    val.validate_unitary(u, "controlledTwoQubitUnitary",
                         qureg.env.precision.eps)
    _apply_gate(qureg, u, (t1, t2), (control,))
    qureg.qasm_log.record_comment(
        "an undisclosed controlled 2-qubit unitary was applied")


def multiControlledTwoQubitUnitary(qureg: Qureg, controls: Sequence[int],
                                   t1: int, t2: int, u) -> None:
    val.validate_multi_controls_multi_targets(
        qureg.num_qubits_represented, controls, (t1, t2),
        "multiControlledTwoQubitUnitary")
    u = mats.matrix4(u)
    val.validate_unitary(u, "multiControlledTwoQubitUnitary",
                         qureg.env.precision.eps)
    _apply_gate(qureg, u, (t1, t2), tuple(controls))
    qureg.qasm_log.record_comment(
        "an undisclosed multi-controlled 2-qubit unitary was applied")


def multiQubitUnitary(qureg: Qureg, targets: Sequence[int], u) -> None:
    val.validate_multi_targets(qureg.num_qubits_represented, targets,
                               "multiQubitUnitary")
    u = np.asarray(u, dtype=np.complex128)
    val.validate_matrix_dim(u, len(targets), "multiQubitUnitary")
    val.validate_unitary(u, "multiQubitUnitary", qureg.env.precision.eps)
    _apply_gate(qureg, u, tuple(targets))
    qureg.qasm_log.record_comment(
        "an undisclosed multi-qubit unitary was applied")


def controlledMultiQubitUnitary(qureg: Qureg, control: int,
                                targets: Sequence[int], u) -> None:
    multiControlledMultiQubitUnitary(qureg, (control,), targets, u)


def multiControlledMultiQubitUnitary(qureg: Qureg, controls: Sequence[int],
                                     targets: Sequence[int], u) -> None:
    val.validate_multi_controls_multi_targets(
        qureg.num_qubits_represented, controls, targets,
        "multiControlledMultiQubitUnitary")
    u = np.asarray(u, dtype=np.complex128)
    val.validate_matrix_dim(u, len(targets), "multiControlledMultiQubitUnitary")
    val.validate_unitary(u, "multiControlledMultiQubitUnitary",
                         qureg.env.precision.eps)
    _apply_gate(qureg, u, tuple(targets), tuple(controls))
    qureg.qasm_log.record_comment(
        "an undisclosed multi-controlled multi-qubit unitary was applied")


# ---------------------------------------------------------------------------
# Pauli sums (QuEST.h:2454-3151)
# ---------------------------------------------------------------------------

def _pauli_prod_state(state, num_qubits_in_vec, targets, codes):
    """paulis |state> (complex, jit-internal), acting on the raw vector
    (row side for densities)."""
    for t, p in zip(targets, codes):
        p = int(p)
        if p == PauliOpType.PAULI_I:
            continue
        state = apply_unitary(state, num_qubits_in_vec, mats.PAULI_MATS[p],
                              (int(t),))
    return state


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _jit_expec_pauli_sv(state_f, num_qubits, targets, codes):
    z = unpack(state_f)
    return jnp.real(jnp.vdot(z, _pauli_prod_state(z, num_qubits, targets, codes)))


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _jit_expec_pauli_dm(state_f, num_qubits_vec, num_qubits, targets, codes):
    z = unpack(state_f)
    return dm.calc_total_prob(
        _pauli_prod_state(z, num_qubits_vec, targets, codes), num_qubits)


@_state_kernel(static_argnums=(1, 2, 3), donate=False)
def _jit_apply_pauli_sum(state_f, num_qubits_vec, num_qubits, codes_flat,
                         coeffs_f):
    z = unpack(state_f)
    targets = tuple(range(num_qubits))
    acc = jnp.zeros_like(z)
    num_terms = len(codes_flat) // num_qubits
    for t in range(num_terms):
        codes = codes_flat[t * num_qubits:(t + 1) * num_qubits]
        acc = acc + coeffs_f[t].astype(z.dtype) * _pauli_prod_state(
            z, num_qubits_vec, targets, codes)
    return pack(acc)


@jax.jit
def _jit_expec_pauli_sum_sv(state_f, xmask, ymask, zmask, coeffs_f):
    """sum_t c_t <psi|P_t|psi> in ONE executable with ONE scalar transfer
    — the reference pays one dispatch + host sync per term
    (``QuEST_common.c:464-491``); a 50-term molecular Hamiltonian cost 50
    round-trips. Terms are bit masks (DATA, ``ops/reductions.py``), so
    one compile serves every Hamiltonian of a bucketed term count — the
    round-7 code unrolled a Python loop over static codes, which forced
    48-term compile chunks and one host sync per chunk."""
    return red.pauli_sum_total_sv(unpack(state_f), xmask, ymask, zmask,
                                  coeffs_f)


@functools.partial(jax.jit, static_argnums=(1,))
def _jit_expec_pauli_sum_dm(state_f, n, xmask, ymask, zmask, coeffs_f):
    """sum_t c_t Tr(P_t rho), device-accumulated, one scalar transfer.
    Each term reads only the 2^n paired-diagonal entries (an xor-gather,
    ``ops/reductions.py``) instead of streaming the 2^(2n) flat vector
    through per-qubit Pauli kernels."""
    return red.pauli_sum_total_dm(unpack(state_f), n, xmask, ymask, zmask,
                                  coeffs_f)


def calcExpecPauliProd(qureg: Qureg, targets: Sequence[int],
                       codes: Sequence[int], num_targets: int = None,
                       workspace: Qureg = None) -> float:
    """C-signature parity: the 4th positional argument is numTargets
    (``QuEST.h:2454``); in Python it may be omitted (inferred from lengths)."""
    if num_targets is not None and not isinstance(num_targets, numbers.Integral):
        workspace, num_targets = num_targets, None
    if num_targets is not None:
        num_targets = int(num_targets)
    if num_targets is not None:
        targets = tuple(targets)[:num_targets]
        codes = tuple(codes)[:num_targets]
    val.validate_multi_targets(qureg.num_qubits_represented, targets,
                               "calcExpecPauliProd")
    val.validate_pauli_codes(codes, "calcExpecPauliProd")
    targets = tuple(int(t) for t in targets)
    codes = tuple(int(c) for c in codes)
    if qureg.layout is not None:
        if qureg.is_density_matrix:
            _canon(qureg)    # row/col pairing is positional
        else:
            # <psi|P|psi> only cares where the TARGETS live: probe the
            # physical positions, no exchange
            targets = _pg.phys_targets(qureg, targets)
    if qureg.is_quad:
        phi = qureg.state
        nv = qureg.num_qubits_in_state_vec
        for q, code in zip(targets, codes):
            if code:
                phi = ddm.dd_apply_kq(phi, nv, mats.PAULI_MATS[code], (q,))
        if qureg.is_density_matrix:
            return float(ddm.dd_total_prob_dm(
                phi, qureg.num_qubits_represented))
        return float(ddm.dd_vdot(qureg.state, phi).real)
    if qureg.is_density_matrix:
        value = _jit_expec_pauli_dm(qureg.state, qureg.num_qubits_in_state_vec,
                                    qureg.num_qubits_represented, targets, codes)
    else:
        value = _jit_expec_pauli_sv(qureg.state, qureg.num_qubits_in_state_vec,
                                    targets, codes)
    return float(value)




def calcExpecPauliSum(qureg: Qureg, all_codes: Sequence[int],
                      coeffs: Sequence[float], num_sum_terms: int = None,
                      workspace: Qureg = None) -> float:
    """C-signature parity: the 4th positional argument is numSumTerms
    (``QuEST.h:2504``); in Python it may be omitted (inferred)."""
    if num_sum_terms is not None and not isinstance(num_sum_terms, numbers.Integral):
        workspace, num_sum_terms = num_sum_terms, None
    n = qureg.num_qubits_represented
    num_terms = int(num_sum_terms) if num_sum_terms is not None else len(coeffs)
    val.validate_num_pauli_sum_terms(num_terms, "calcExpecPauliSum")
    val.validate_pauli_codes(all_codes, "calcExpecPauliSum")
    codes_flat = tuple(int(c) for c in all_codes[:num_terms * n])
    if qureg.is_quad:
        # inline dd loop: no per-term public-API re-entry or revalidation
        nv = qureg.num_qubits_in_state_vec
        value = 0.0
        for t in range(num_terms):
            phi = qureg.state
            for q, code in enumerate(codes_flat[t * n:(t + 1) * n]):
                if code:
                    phi = ddm.dd_apply_kq(phi, nv, mats.PAULI_MATS[code],
                                          (q,))
            if qureg.is_density_matrix:
                value += float(coeffs[t]) * ddm.dd_total_prob_dm(phi, n)
            else:
                value += float(coeffs[t]) * ddm.dd_vdot(qureg.state,
                                                        phi).real
        return value
    if qureg.layout is not None:
        if qureg.is_density_matrix:
            _canon(qureg)    # row/col pairing is positional
        else:
            # permute each term's codes to the physical positions — the
            # expectation probes targets in place, no exchange. Codes are
            # DATA (bit masks) now, so the remap never recompiles and is
            # worth it at ANY term count (the old static-codes path
            # canonicalised above 8 terms to avoid per-permutation
            # executables)
            lay = qureg.layout
            remapped = list(codes_flat)
            for t in range(num_terms):
                for q_l in range(n):
                    remapped[t * n + int(lay[q_l])] = codes_flat[t * n + q_l]
            codes_flat = tuple(remapped)
    # term-batched device-resident reduction (ops/reductions.py): the
    # terms become xor/sign mask ARRAYS, padded to a power-of-two bucket
    # (zero-coefficient identity terms) so one executable serves every
    # Hamiltonian in the band — no per-chunk compiles, no per-chunk (or
    # per-term) host syncs on either the statevector or density path;
    # the single float() below is the only device->host transfer.
    xm, ym, zm, coeffs_np = red.pauli_sum_operands(
        codes_flat, n, np.asarray(coeffs[:num_terms], np.float64))
    coeffs_f = jnp.asarray(coeffs_np, qureg.real_dtype)
    if qureg.is_density_matrix:
        value = _jit_expec_pauli_sum_dm(
            qureg.state, n, jnp.asarray(xm), jnp.asarray(ym),
            jnp.asarray(zm), coeffs_f)
    else:
        value = _jit_expec_pauli_sum_sv(
            qureg.state, jnp.asarray(xm), jnp.asarray(ym), jnp.asarray(zm),
            coeffs_f)
    return float(value)


def applyPauliSum(in_qureg: Qureg, all_codes: Sequence[int],
                  coeffs: Sequence[float], num_terms: int,
                  out_qureg: Qureg) -> None:
    """out = sum_t c_t P_t |in> (``statevec_applyPauliSum``
    ``QuEST_common.c:494-514``)."""
    val.validate_matching_types(in_qureg.is_density_matrix,
                                out_qureg.is_density_matrix, "applyPauliSum")
    val.validate_matching_precision(in_qureg.env.precision.quest_prec,
                                    out_qureg.env.precision.quest_prec,
                                    "applyPauliSum")
    val.validate_matching_dims(in_qureg.num_qubits_represented,
                               out_qureg.num_qubits_represented, "applyPauliSum")
    val.validate_num_pauli_sum_terms(num_terms, "applyPauliSum")
    val.validate_pauli_codes(all_codes, "applyPauliSum")
    n = in_qureg.num_qubits_represented
    codes_flat = tuple(int(c) for c in all_codes[:num_terms * n])
    if in_qureg.is_quad:
        nv = in_qureg.num_qubits_in_state_vec
        acc = None
        for t in range(num_terms):
            phi = in_qureg.state
            for q, code in enumerate(codes_flat[t * n:(t + 1) * n]):
                if code:
                    phi = ddm.dd_apply_kq(phi, nv, mats.PAULI_MATS[code],
                                          (q,))
            acc = ddm.dd_weighted(float(coeffs[t]), phi, 0.0, phi, 0.0,
                                  phi) if acc is None else \
                ddm.dd_weighted(1.0, acc, float(coeffs[t]), phi, 0.0, acc)
        _fresh(out_qureg)
        out_qureg.state = acc
        out_qureg.qasm_log.record_comment(
            "the register was set to a Pauli-sum image "
            "(possibly unphysical)")
        return
    coeffs_f = jnp.asarray(np.asarray(coeffs[:num_terms], np.float64),
                           in_qureg.real_dtype)
    _canon(in_qureg)
    _fresh(out_qureg)
    out_qureg.state = _jit_apply_pauli_sum(
        in_qureg.state, in_qureg.num_qubits_in_state_vec, n, codes_flat,
        coeffs_f, _shard(out_qureg))
    out_qureg.qasm_log.record_comment(
        "the register was set to a Pauli-sum image (possibly unphysical)")


# ---------------------------------------------------------------------------
# measurement & collapse (QuEST.h:1694-1753)
# ---------------------------------------------------------------------------

def calcProbOfOutcome(qureg: Qureg, qubit: int, outcome: int) -> float:
    val.validate_target(qureg.num_qubits_represented, qubit, "calcProbOfOutcome")
    val.validate_outcome(outcome, "calcProbOfOutcome")
    if qureg.layout is not None:
        if qureg.is_density_matrix:
            _canon(qureg)    # the diagonal view needs canonical order
        else:
            qubit = int(qureg.layout[qubit])   # probe the physical position
    if qureg.is_quad:
        if qureg.is_density_matrix:
            p0 = ddm.dd_prob_zero_dm(qureg.state,
                                     qureg.num_qubits_represented, qubit)
        else:
            p0 = ddm.dd_prob_zero_sv(qureg.state,
                                     qureg.num_qubits_in_state_vec, qubit)
        return p0 if outcome == 0 else 1.0 - p0
    if qureg.env.compensated:
        if qureg.is_density_matrix:
            p0 = _pair(_jit_pair_prob_zero_dm(
                qureg.state, qureg.num_qubits_represented, qubit))
        else:
            p0 = _pair(_jit_pair_prob_zero_sv(
                qureg.state, qureg.num_qubits_in_state_vec, qubit))
        return p0 if outcome == 0 else 1.0 - p0
    if qureg.is_density_matrix:
        p = _jit_prob_outcome_dm(qureg.state, qureg.num_qubits_represented,
                                 qubit, outcome)
    else:
        p = _jit_prob_outcome_sv(qureg.state, qureg.num_qubits_in_state_vec,
                                 qubit, outcome)
    return float(p)


def _collapse(qureg: Qureg, qubit: int, outcome: int, prob: float) -> None:
    if qureg.is_quad:
        qureg.state = ddm.dd_collapse(
            qureg.state, qureg.num_qubits_in_state_vec, qubit, outcome,
            float(prob), density=qureg.is_density_matrix)
        return
    prob = jnp.asarray(prob, qureg.real_dtype)
    if qureg.layout is not None:
        if qureg.is_density_matrix:
            _canon(qureg)
        else:
            qubit = int(qureg.layout[qubit])
    if qureg.is_density_matrix:
        qureg.state = _jit_collapse_dm(
            qureg.state, qureg.num_qubits_represented, qubit, outcome, prob,
            _shard(qureg))
    else:
        qureg.state = _jit_collapse_sv(
            qureg.state, qureg.num_qubits_in_state_vec, qubit, outcome, prob,
            _shard(qureg))


def collapseToOutcome(qureg: Qureg, qubit: int, outcome: int) -> float:
    val.validate_target(qureg.num_qubits_represented, qubit, "collapseToOutcome")
    val.validate_outcome(outcome, "collapseToOutcome")
    prob = calcProbOfOutcome(qureg, qubit, outcome)
    val.validate_measurement_prob(prob, qureg.env.precision.eps,
                                  "collapseToOutcome")
    _collapse(qureg, qubit, outcome, prob)
    qureg.qasm_log.record_measurement(qubit)
    return prob


def measureWithStats(qureg: Qureg, qubit: int):
    """Returns (outcome, outcome_prob). RNG = jax.random key stream held by
    the env (replacing mt19937, ``generateMeasurementOutcome``
    ``QuEST_common.c:154-169``)."""
    val.validate_target(qureg.num_qubits_represented, qubit, "measureWithStats")
    zero_prob = calcProbOfOutcome(qureg, qubit, 0)
    eps = qureg.env.precision.eps
    if zero_prob < eps:
        outcome = 1
    elif 1.0 - zero_prob < eps:
        outcome = 0
    else:
        r = float(jax.random.uniform(qureg.env.next_key()))
        outcome = int(r > zero_prob)
    prob = zero_prob if outcome == 0 else 1.0 - zero_prob
    _collapse(qureg, qubit, outcome, prob)
    qureg.qasm_log.record_measurement(qubit)
    return outcome, prob


def measure(qureg: Qureg, qubit: int) -> int:
    outcome, _ = measureWithStats(qureg, qubit)
    return outcome


@jax.jit
def _jit_dd_combine(planes4):
    """(4, N) dd planes -> (2, N) hi-precision-collapsed planes (sampling
    tolerance does not need the lo bits)."""
    return jnp.stack([planes4[0] + planes4[1], planes4[2] + planes4[3]])


@functools.partial(jax.jit, static_argnums=(2, 3))
def _jit_sample(state_f, key, num_samples, density):
    """Inverse-CDF sampling of basis indices: one cumsum pass + a
    searchsorted per shot, all on device (sharded states included — XLA
    lowers the scan/gather with collectives). Statevector planes sample
    |amp|^2; density input is the diagonal, whose REAL parts already ARE
    the probabilities (same convention as ``densmatr`` reductions) —
    clipped at 0 against round-off. Normalises by the total so norm
    drift cannot bias the tail bin, and clips the result so a draw that
    rounds up to exactly the total cannot index past the register."""
    if density:
        probs = jnp.maximum(state_f[0], 0.0)
    else:
        probs = state_f[0] * state_f[0] + state_f[1] * state_f[1]
    cum = jnp.cumsum(probs)
    draws = jax.random.uniform(key, (num_samples,), dtype=cum.dtype)
    idx = jnp.searchsorted(cum, draws * cum[-1], side="right")
    return jnp.minimum(idx, probs.shape[0] - 1), cum[-1]


def sampleOutcomes(qureg: Qureg, num_samples: int, qubits=None) -> np.ndarray:
    """Draw ``num_samples`` computational-basis outcomes from the state's
    probability distribution WITHOUT collapsing it — M measurement shots
    in one device pass. TPU-native addition: the reference can only
    measure-and-collapse, so M shots there cost M register copies and
    M full measurement passes (``measure``, ``QuEST_common.c:360-374``).

    Statevector registers sample ``|amp|^2``; density registers sample
    the diagonal (the outcome distribution of a full measurement).
    Returns an int64 array of basis indices, or — when ``qubits`` is
    given — the outcomes of those qubits packed little-endian (bit ``j``
    = ``qubits[j]``). The register is untouched; the env RNG stream
    advances once.
    """
    if int(num_samples) < 1:
        val._fail("num_samples must be >= 1", "sampleOutcomes",
                  val.ErrorCode.E_INVALID_NUM_AMPS)
    n = qureg.num_qubits_represented
    if qubits is not None:
        qubits = [int(q) for q in qubits]
        val.validate_multi_targets(n, qubits, "sampleOutcomes")
    _canon(qureg)
    src_planes = _jit_dd_combine(qureg.state) if qureg.is_quad \
        else qureg.state
    if _shard(qureg) is not None and (1 << n) >= qureg.env.num_devices:
        # sharded registers: shard-local two-stage inverse CDF — the
        # GSPMD lowering of the full-vector cumsum all-gathers the state
        # (measured 2x-state buffers at 20q/8dev), which cannot scale.
        # Needs >=1 OUTCOME per shard (2^n >= D): a density register can
        # be amp-sharded (2^2n >= D) while its 2^n-entry diagonal is
        # still thinner than the mesh — those fall through to GSPMD
        from .parallel.sampling import sample_sharded
        idx_dev, total = sample_sharded(
            src_planes, qureg.env.next_key(), int(num_samples),
            qureg.is_density_matrix, n, qureg.env.mesh)
    else:
        if qureg.is_density_matrix:
            # diagonal of the flat density vector via a reshape view (no
            # index vector: a materialised arange would overflow int32 on
            # x64-disabled backends once n >= 16)
            planes = jnp.diagonal(src_planes.reshape(2, 1 << n, 1 << n),
                                  axis1=1, axis2=2)
        else:
            planes = src_planes
        idx_dev, total = _jit_sample(planes, qureg.env.next_key(),
                                     int(num_samples),
                                     qureg.is_density_matrix)
    if float(total) < qureg.env.precision.eps:
        # an (unnormalised) zero-norm register has no distribution to
        # sample; without this the clamp would return the last basis
        # index for every shot — valid-looking garbage. The total comes
        # back from the same fused pass, so the guard costs nothing.
        val._fail("cannot sample a zero-probability register",
                  "sampleOutcomes", val.ErrorCode.E_COLLAPSE_STATE_ZERO_PROB)
    idx = np.asarray(idx_dev, dtype=np.int64)
    if qubits is None:
        return idx
    out = np.zeros_like(idx)
    for j, q in enumerate(qubits):
        out |= ((idx >> q) & 1) << j
    return out


# ---------------------------------------------------------------------------
# amplitude access & calculations (QuEST.h:366-944, 971-2504, 3071)
# ---------------------------------------------------------------------------

def getNumQubits(qureg: Qureg) -> int:
    return qureg.num_qubits_represented


def getNumAmps(qureg: Qureg) -> int:
    val.validate_state_vec(qureg.is_density_matrix, "getNumAmps")
    return qureg.num_amps_total


@jax.jit
def _jit_take_amp(state_f, idx):
    """Read one (re, im) pair from the (possibly sharded) state — the
    analogue of the owner-rank read + broadcast in ``statevec_getRealAmp``
    (``QuEST_cpu_distributed.c:195-203``): a dynamic-index gather that XLA's
    SPMD partitioner serves from the owning shard, transferring 2 floats to
    host, never the register. One executable serves every index."""
    return jax.lax.dynamic_slice_in_dim(state_f, idx, 1, axis=1)[:, 0]


def _get_amp_pair(qureg: Qureg, index: int) -> complex:
    # under a lazy layout the logical basis index maps bit-by-bit to a
    # physical one — a host-side remap, never a collective
    index = _pg.phys_index(qureg, index)
    idx_dt = jnp.int64 if (index > np.iinfo(np.int32).max
                           and jax.config.jax_enable_x64) else jnp.int32
    pair = np.asarray(_jit_take_amp(qureg.state, jnp.asarray(index, idx_dt)),
                      dtype=np.float64)
    if qureg.is_quad:
        return complex(pair[0] + pair[1], pair[2] + pair[3])
    return complex(pair[0], pair[1])


def getAmp(qureg: Qureg, index: int) -> complex:
    val.validate_state_vec(qureg.is_density_matrix, "getAmp")
    val.validate_amp_index(qureg.num_amps_total, index, "getAmp")
    return _get_amp_pair(qureg, index)


def getRealAmp(qureg: Qureg, index: int) -> float:
    return getAmp(qureg, index).real


def getImagAmp(qureg: Qureg, index: int) -> float:
    return getAmp(qureg, index).imag


def getProbAmp(qureg: Qureg, index: int) -> float:
    a = getAmp(qureg, index)
    return a.real * a.real + a.imag * a.imag


def getDensityAmp(qureg: Qureg, row: int, col: int) -> complex:
    val.validate_density_matr(qureg.is_density_matrix, "getDensityAmp")
    dim = 1 << qureg.num_qubits_represented
    val.validate_amp_index(dim, row, "getDensityAmp")
    val.validate_amp_index(dim, col, "getDensityAmp")
    return _get_amp_pair(qureg, row + col * dim)


def calcTotalProb(qureg: Qureg) -> float:
    if qureg.is_density_matrix:
        _canon(qureg)    # the trace pairs row/column bits positionally
    if qureg.is_quad:
        if qureg.is_density_matrix:
            return ddm.dd_total_prob_dm(qureg.state,
                                        qureg.num_qubits_represented)
        return ddm.dd_total_prob(qureg.state)
    if qureg.env.compensated:
        if qureg.is_density_matrix:
            return _pair(_jit_pair_total_prob_dm(
                qureg.state, qureg.num_qubits_represented))
        return _pair(_jit_pair_sum_sq(qureg.state))
    if qureg.is_density_matrix:
        return float(_jit_total_prob_dm(qureg.state,
                                        qureg.num_qubits_represented))
    return float(_jit_total_prob_sv(qureg.state))


def calcInnerProduct(bra: Qureg, ket: Qureg) -> complex:
    val.validate_state_vec(bra.is_density_matrix, "calcInnerProduct")
    val.validate_state_vec(ket.is_density_matrix, "calcInnerProduct")
    val.validate_matching_dims(bra.num_qubits_represented,
                               ket.num_qubits_represented, "calcInnerProduct")
    val.validate_matching_precision(bra.env.precision.quest_prec,
                                    ket.env.precision.quest_prec,
                                    "calcInnerProduct")
    _canon(bra, ket)
    if bra.is_quad:
        return ddm.dd_vdot(bra.state, ket.state)
    if bra.env.compensated:
        re_pair, im_pair = _jit_pair_inner_product(bra.state, ket.state)
        return complex(_pair(re_pair), _pair(im_pair))
    re, im = _jit_inner_product(bra.state, ket.state)
    return complex(float(re), float(im))


def calcDensityInnerProduct(rho1: Qureg, rho2: Qureg) -> float:
    val.validate_density_matr(rho1.is_density_matrix, "calcDensityInnerProduct")
    val.validate_density_matr(rho2.is_density_matrix, "calcDensityInnerProduct")
    val.validate_matching_dims(rho1.num_qubits_represented,
                               rho2.num_qubits_represented,
                               "calcDensityInnerProduct")
    val.validate_matching_precision(rho1.env.precision.quest_prec,
                                    rho2.env.precision.quest_prec,
                                    "calcDensityInnerProduct")
    _canon(rho1, rho2)
    if rho1.is_quad:
        return ddm.dd_vdot(rho1.state, rho2.state).real
    if rho1.env.compensated:
        return _pair(_jit_pair_dm_inner(rho1.state, rho2.state))
    return float(_jit_dm_inner(rho1.state, rho2.state))


def calcPurity(qureg: Qureg) -> float:
    val.validate_density_matr(qureg.is_density_matrix, "calcPurity")
    if qureg.is_quad:
        return ddm.dd_total_prob(qureg.state)
    if qureg.env.compensated:
        return _pair(_jit_pair_sum_sq(qureg.state))
    return float(_jit_purity(qureg.state))


def calcFidelity(qureg: Qureg, pure_state: Qureg) -> float:
    val.validate_second_qureg_state_vec(pure_state.is_density_matrix,
                                        "calcFidelity")
    val.validate_matching_dims(qureg.num_qubits_represented,
                               pure_state.num_qubits_represented,
                               "calcFidelity")
    val.validate_matching_precision(qureg.env.precision.quest_prec,
                                    pure_state.env.precision.quest_prec,
                                    "calcFidelity")
    _canon(qureg, pure_state)
    if qureg.is_quad:
        if qureg.is_density_matrix:
            # <psi|rho|psi> = sum_rc rho[r,c] conj(psi_r) psi_c: a plain
            # dd dot with the dd outer-product weights (lo planes kept)
            w_planes = ddm.dd_outer(pure_state.state, conj_left=True)
            return ddm.dd_vdot(w_planes, qureg.state, conj_a=False).real
        return abs(ddm.dd_vdot(qureg.state, pure_state.state)) ** 2
    if qureg.is_density_matrix:
        if qureg.env.compensated:
            return _pair(_jit_pair_fidelity_dm(
                qureg.state, qureg.num_qubits_represented, pure_state.state))
        return float(_jit_fidelity_dm(qureg.state,
                                      qureg.num_qubits_represented,
                                      pure_state.state))
    if qureg.env.compensated:
        re_pair, im_pair = _jit_pair_inner_product(qureg.state,
                                                   pure_state.state)
        return _pair(re_pair) ** 2 + _pair(im_pair) ** 2
    re, im = _jit_inner_product(qureg.state, pure_state.state)
    return float(re) ** 2 + float(im) ** 2


def calcHilbertSchmidtDistance(a: Qureg, b: Qureg) -> float:
    val.validate_density_matr(a.is_density_matrix, "calcHilbertSchmidtDistance")
    val.validate_density_matr(b.is_density_matrix, "calcHilbertSchmidtDistance")
    val.validate_matching_dims(a.num_qubits_represented,
                               b.num_qubits_represented,
                               "calcHilbertSchmidtDistance")
    val.validate_matching_precision(a.env.precision.quest_prec,
                                    b.env.precision.quest_prec,
                                    "calcHilbertSchmidtDistance")
    _canon(a, b)
    if a.is_quad:
        diff = ddm.dd_weighted(1.0, a.state, -1.0, b.state, 0.0, a.state)
        return math.sqrt(max(0.0, ddm.dd_total_prob(diff)))
    if a.env.compensated:
        return math.sqrt(max(0.0, _pair(_jit_pair_hs_sq(a.state, b.state))))
    return float(_jit_hs_dist(a.state, b.state))


# ---------------------------------------------------------------------------
# decoherence (QuEST.h:1929-3043)
# ---------------------------------------------------------------------------

def _apply_kraus(qureg: Qureg, targets: Sequence[int], ops) -> None:
    """Superoperator on (targets, targets+n) of the flat density vector
    (``densmatr_applyMultiQubitKrausSuperoperator``
    ``QuEST_common.c:598-604``)."""
    superop = dm.kraus_superoperator(ops)
    if qureg.is_quad:
        n = qureg.num_qubits_represented
        t2 = tuple(int(t) for t in targets) \
            + tuple(int(t) + n for t in targets)
        qureg.state = ddm.dd_apply_kq(qureg.state, 2 * n, superop, t2)
        return
    if _pg.use_lazy(qureg):
        n = qureg.num_qubits_represented
        t2 = tuple(int(t) for t in targets) \
            + tuple(int(t) + n for t in targets)
        if _pg.fits_local(qureg, len(t2)):
            _pg.sharded_unitary(qureg, _packed(qureg, superop), t2, 0, 0)
            return
        _canon(qureg)
    qureg.state = _jit_kraus_superop(
        qureg.state, qureg.num_qubits_represented,
        tuple(int(t) for t in targets), _packed(qureg, superop),
        _shard(qureg))


def mixDephasing(qureg: Qureg, target: int, prob: float) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixDephasing")
    val.validate_target(qureg.num_qubits_represented, target, "mixDephasing")
    val.validate_prob(prob, "mixDephasing", 0.5, "dephasing probability",
                      code=val.ErrorCode.E_INVALID_ONE_QUBIT_DEPHASE_PROB)
    if qureg.is_quad:
        n = qureg.num_qubits_represented
        qureg.state = ddm.dd_apply_diag(qureg.state, 2 * n,
                                        dm.dephasing_factors(float(prob)),
                                        (target + n, target))
        qureg.qasm_log.record_comment(
            f"a phase (Z) error occurred on qubit {target} "
            f"with probability {prob:g}")
        return
    if _pg.use_lazy(qureg):
        # dephasing is diagonal on (target+n, target): position-free
        n = qureg.num_qubits_represented
        _pg.sharded_diag(qureg, dm.dephasing_factors(float(prob)),
                         (target + n, target))
    else:
        qureg.state = _jit_mix_dephasing(
            qureg.state, qureg.num_qubits_represented,
            target, float(prob), _shard(qureg))
    qureg.qasm_log.record_comment(
        f"a phase (Z) error occurred on qubit {target} with probability {prob:g}")


def mixTwoQubitDephasing(qureg: Qureg, q1: int, q2: int, prob: float) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixTwoQubitDephasing")
    val.validate_unique_targets(qureg.num_qubits_represented, q1, q2,
                                "mixTwoQubitDephasing")
    val.validate_prob(prob, "mixTwoQubitDephasing", 0.75,
                      "two-qubit dephasing probability",
                      code=val.ErrorCode.E_INVALID_TWO_QUBIT_DEPHASE_PROB)
    if qureg.is_quad or _pg.use_lazy(qureg):
        # diagonal on (q1, q2, q1+n, q2+n): position-free, zero comm
        n = qureg.num_qubits_represented
        fac = dm.two_qubit_dephasing_factors(float(prob))
        hi, lo = max(q1, q2), min(q1, q2)
        if qureg.is_quad:
            qureg.state = ddm.dd_apply_diag(qureg.state, 2 * n, fac,
                                            (hi + n, lo + n, hi, lo))
        else:
            _pg.sharded_diag(qureg, fac, (hi + n, lo + n, hi, lo))
        qureg.qasm_log.record_comment(
            f"a phase (Z) error occurred on qubits {q1} and/or {q2} "
            f"with total probability {prob:g}")
        return
    qureg.state = _jit_mix_two_qubit_dephasing(
        qureg.state, qureg.num_qubits_represented, q1, q2, float(prob),
        _shard(qureg))
    qureg.qasm_log.record_comment(
        f"a phase (Z) error occurred on qubits {q1} and/or {q2} "
        f"with total probability {prob:g}")


def mixDepolarising(qureg: Qureg, target: int, prob: float) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixDepolarising")
    val.validate_target(qureg.num_qubits_represented, target, "mixDepolarising")
    val.validate_prob(prob, "mixDepolarising", 0.75, "depolarising probability",
                      code=val.ErrorCode.E_INVALID_ONE_QUBIT_DEPOL_PROB)
    _apply_kraus(qureg, (target,), chan.depolarising_kraus(prob))
    qureg.qasm_log.record_comment(
        f"a depolarising error occurred on qubit {target} "
        f"with total probability {prob:g}")


def mixDamping(qureg: Qureg, target: int, prob: float) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixDamping")
    val.validate_target(qureg.num_qubits_represented, target, "mixDamping")
    val.validate_prob(prob, "mixDamping", 1.0, "damping probability")
    _apply_kraus(qureg, (target,), chan.damping_kraus(prob))


def mixTwoQubitDepolarising(qureg: Qureg, q1: int, q2: int, prob: float) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixTwoQubitDepolarising")
    val.validate_unique_targets(qureg.num_qubits_represented, q1, q2,
                                "mixTwoQubitDepolarising")
    val.validate_prob(prob, "mixTwoQubitDepolarising", 15.0 / 16.0,
                      "two-qubit depolarising probability",
                      code=val.ErrorCode.E_INVALID_TWO_QUBIT_DEPOL_PROB)
    _apply_kraus(qureg, (q1, q2), chan.two_qubit_depolarising_kraus(prob))
    qureg.qasm_log.record_comment(
        f"a depolarising error occurred on qubits {q1} and {q2} "
        f"with total probability {prob:g}")


def mixPauli(qureg: Qureg, qubit: int, prob_x: float, prob_y: float,
             prob_z: float) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixPauli")
    val.validate_target(qureg.num_qubits_represented, qubit, "mixPauli")
    val.validate_one_qubit_pauli_probs(prob_x, prob_y, prob_z, "mixPauli")
    _apply_kraus(qureg, (qubit,), chan.pauli_kraus(prob_x, prob_y, prob_z))
    qureg.qasm_log.record_comment(
        f"X, Y and Z errors occurred on qubit {qubit} with probabilities "
        f"{prob_x:g}, {prob_y:g} and {prob_z:g} respectively")


def mixDensityMatrix(qureg: Qureg, other_prob: float, other: Qureg) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixDensityMatrix")
    val.validate_density_matr(other.is_density_matrix, "mixDensityMatrix")
    val.validate_matching_dims(qureg.num_qubits_represented,
                               other.num_qubits_represented,
                               "mixDensityMatrix")
    val.validate_prob(other_prob, "mixDensityMatrix")
    val.validate_matching_precision(qureg.env.precision.quest_prec,
                                    other.env.precision.quest_prec,
                                    "mixDensityMatrix")
    if qureg.is_quad:
        qureg.state = ddm.dd_weighted(1.0 - float(other_prob), qureg.state,
                                      float(other_prob), other.state,
                                      0.0, qureg.state)
        return
    _canon(qureg, other)
    kernel = _jit_mix_linear if qureg.state is not other.state \
        else _jit_mix_linear_nodonate
    qureg.state = kernel(
        jnp.asarray(other_prob, qureg.real_dtype), qureg.state, other.state,
        _shard(qureg))


def mixKrausMap(qureg: Qureg, target: int, ops, num_ops: int = None) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixKrausMap")
    val.validate_target(qureg.num_qubits_represented, target, "mixKrausMap")
    ops = list(ops)[:num_ops] if num_ops is not None else list(ops)
    val.validate_kraus_ops(ops, 1, "mixKrausMap", qureg.env.precision.eps)
    _apply_kraus(qureg, (target,), ops)
    qureg.qasm_log.record_comment(
        f"an undisclosed Kraus map was applied to qubit {target}")


def mixTwoQubitKrausMap(qureg: Qureg, t1: int, t2: int, ops,
                        num_ops: int = None) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixTwoQubitKrausMap")
    val.validate_multi_targets(qureg.num_qubits_represented, (t1, t2),
                               "mixTwoQubitKrausMap")
    ops = list(ops)[:num_ops] if num_ops is not None else list(ops)
    val.validate_kraus_ops(ops, 2, "mixTwoQubitKrausMap",
                           qureg.env.precision.eps)
    _apply_kraus(qureg, (t1, t2), ops)
    qureg.qasm_log.record_comment(
        f"an undisclosed two-qubit Kraus map was applied to qubits {t1}, {t2}")


def mixMultiQubitKrausMap(qureg: Qureg, targets: Sequence[int], ops,
                          num_ops: int = None) -> None:
    val.validate_density_matr(qureg.is_density_matrix, "mixMultiQubitKrausMap")
    val.validate_multi_targets(qureg.num_qubits_represented, targets,
                               "mixMultiQubitKrausMap")
    ops = list(ops)[:num_ops] if num_ops is not None else list(ops)
    val.validate_kraus_ops(ops, len(targets), "mixMultiQubitKrausMap",
                           qureg.env.precision.eps)
    _apply_kraus(qureg, tuple(targets), ops)
    qureg.qasm_log.record_comment(
        f"an undisclosed {len(targets)}-qubit Kraus map was applied")


# ---------------------------------------------------------------------------
# QASM recording (QuEST.h:1868-1906)
# ---------------------------------------------------------------------------

def startRecordingQASM(qureg: Qureg) -> None:
    qureg.qasm_log.is_logging = True


def stopRecordingQASM(qureg: Qureg) -> None:
    qureg.qasm_log.is_logging = False


def clearRecordedQASM(qureg: Qureg) -> None:
    qureg.qasm_log.clear()


def printRecordedQASM(qureg: Qureg) -> None:
    print(qureg.qasm_log.text(), end="")


def writeRecordedQASMToFile(qureg: Qureg, filename: str) -> None:
    try:
        qureg.qasm_log.write_to_file(filename)
    except OSError:
        val.validate_file_opened(False, "writeRecordedQASMToFile")


# ---------------------------------------------------------------------------
# debug / reporting (QuEST.h:319-359, QuEST_debug.h)
# ---------------------------------------------------------------------------

def reportState(qureg: Qureg, filename: str = "state_rank_0.csv") -> None:
    """Dump amplitudes as 'real, imag' CSV (``reportState``
    ``QuEST_common.c:215-231``)."""
    amps = qureg.to_numpy()
    with open(filename, "w") as f:
        f.write("real, imag\n")
        for a in amps:
            f.write(f"{a.real:.12e}, {a.imag:.12e}\n")


def reportStateToScreen(qureg: Qureg, env: QuESTEnv = None,
                        report_rank: int = 0) -> None:
    # the reference silently skips large registers rather than erroring
    # (guard on the STATE-VECTOR qubit count, QuEST_cpu.c:1343); the
    # E_SYS_TOO_BIG_TO_PRINT code is dead there too — see validation.SUBSUMED
    if qureg.num_qubits_in_state_vec > 5:
        return
    amps = qureg.to_numpy()
    print("Reporting state from rank 0 of 1")
    for a in amps:
        print(f"{a.real:.12f}, {a.imag:.12f}")


def reportQuregParams(qureg: Qureg) -> None:
    print(f"QUBITS: {qureg.num_qubits_represented}")
    print(f"TOTAL AMPS: {qureg.num_amps_total}")
    print(f"AMPS PER DEVICE: {qureg.num_amps_per_chunk}")
    mem = qureg.num_amps_total * np.dtype(qureg.dtype).itemsize
    print(f"DEVICE MEMORY: {mem / 2**20:.1f} MiB")


def compareStates(q1: Qureg, q2: Qureg, precision: float) -> bool:
    val.validate_matching_dims(q1.num_qubits_represented,
                               q2.num_qubits_represented, "compareStates")
    a, b = q1.to_numpy(), q2.to_numpy()
    return bool(np.all(np.abs(a.real - b.real) < precision)
                and np.all(np.abs(a.imag - b.imag) < precision))


def initStateFromSingleFile(qureg: Qureg, filename: str,
                            env: QuESTEnv = None) -> None:
    """Load a state previously written by :func:`reportState`."""
    rows = []
    try:
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("real"):
                    continue
                re_s, im_s = line.split(",")
                rows.append(complex(float(re_s), float(im_s)))
    except OSError:
        val.validate_file_opened(False, "initStateFromSingleFile")
    if len(rows) != qureg.num_amps_total:
        val._fail("the state file does not match the register dimension",
                  "initStateFromSingleFile",
                  val.ErrorCode.E_INVALID_NUM_AMPS)
    qureg.device_put(np.asarray(rows, dtype=np.complex128))


def getQuEST_PREC() -> int:
    from .config import default_precision
    return default_precision().quest_prec
