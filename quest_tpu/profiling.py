"""Tracing and profiling hooks.

The reference has none built in (SURVEY.md §5: only the timing harness in
`tests/benchmarks/rotate_benchmark.test` and the env reports). The TPU build
adds:

- :func:`trace` — context manager around the JAX profiler; the resulting
  trace opens in TensorBoard/Perfetto and shows every gate as a named XLA
  region;
- :class:`GateStats` — lightweight host-side counters: per-gate-name call
  counts and wall time of the (async-dispatched) API calls, plus a
  rotate-benchmark-style ``probe`` that times a gate across every target
  qubit (mean/std/min/max — the reference benchmark's statistics,
  `rotate_benchmark.test:40-60`).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import defaultdict
from typing import Callable, Optional

import jax

__all__ = ["trace", "GateStats", "DispatchStats", "probe_gate",
           "CommCostModel", "DEFAULT_COMM_MODEL", "comm_model",
           "measure_comm_model"]


# ---------------------------------------------------------------------------
# collective cost model (the layout planner's objective function)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CommCostModel:
    """Linear latency/bandwidth model for one mesh collective:
    ``seconds = alpha + beta * bytes_on_the_wire`` per device.

    The layout planner (:mod:`quest_tpu.parallel.layout`) prices every
    candidate data movement with this model and minimizes modeled comm
    TIME rather than relayout count:

    - a relayout trading ``k`` device-index bits against ``k`` chunk-local
      bits is one ``all_to_all`` over groups of ``2^k`` devices — each
      device keeps ``1/2^k`` of its chunk and ships the rest, so
      ``bytes = chunk_bytes * (2^k - 1) / 2^k`` (plus a full-chunk
      ``ppermute`` when a residual device-bit permutation remains);
    - a cross-shard 1q pair exchange (``apply_1q_cross_shard``) ships the
      whole chunk once: ``bytes = chunk_bytes``.

    ``alpha``/``beta`` default to a conservative interconnect model
    (:data:`DEFAULT_COMM_MODEL`); :func:`measure_comm_model` calibrates
    them per mesh with a tiny collective microbenchmark and caches the
    fit. Decisions only depend on cost *ratios*, so plans stay
    deterministic for any non-degenerate (alpha >= 0, beta > 0) fit.
    """

    alpha_s: float              # per-collective launch latency (seconds)
    beta_s_per_byte: float      # per-byte transfer time (seconds/byte)
    source: str = "default"     # "default" | "measured"

    @staticmethod
    def all_to_all_bytes(chunk_bytes: float, k: int) -> float:
        """Per-device bytes shipped by a k-bit relayout exchange."""
        if k <= 0:
            return 0.0
        return chunk_bytes * ((1 << k) - 1) / float(1 << k)

    @staticmethod
    def ppermute_bytes(chunk_bytes: float) -> float:
        """Per-device bytes shipped by a whole-chunk pair exchange."""
        return float(chunk_bytes)

    def all_to_all_seconds(self, chunk_bytes: float, k: int) -> float:
        if k <= 0:
            return 0.0
        return self.alpha_s + self.beta_s_per_byte * \
            self.all_to_all_bytes(chunk_bytes, k)

    def ppermute_seconds(self, chunk_bytes: float) -> float:
        return self.alpha_s + self.beta_s_per_byte * \
            self.ppermute_bytes(chunk_bytes)


# ~50 GB/s per-link bandwidth with a few-microsecond launch cost: the
# shape of both ICI links and a shared-memory host "mesh". The planner's
# decisions are ratio-based, so the default is safe wherever no
# measurement has run.
DEFAULT_COMM_MODEL = CommCostModel(alpha_s=5e-6, beta_s_per_byte=2e-11)

_COMM_MODEL_CACHE: dict = {}


def _mesh_cache_key(mesh) -> tuple:
    devs = mesh.devices.reshape(-1)
    return (len(devs), devs[0].platform,
            getattr(devs[0], "device_kind", ""))


def measure_comm_model(mesh, probe_bytes=(1 << 14, 1 << 19),
                       trials: int = 5) -> CommCostModel:
    """Fit (alpha, beta) from a tiny ``ppermute`` ring microbenchmark at
    two payload sizes on ``mesh``; the result is cached per mesh
    fingerprint so the calibration runs once per process. Falls back to
    :data:`DEFAULT_COMM_MODEL` (uncached) if the measurement fails or
    produces a degenerate fit."""
    import numpy as np
    key = _mesh_cache_key(mesh)
    if key in _COMM_MODEL_CACHE:
        return _COMM_MODEL_CACHE[key]
    try:
        from jax.sharding import PartitionSpec as P
        from .compat import shard_map
        from .env import AMP_AXIS
        n_dev = int(np.prod(mesh.devices.shape))
        pairs = tuple((i, (i + 1) % n_dev) for i in range(n_dev))

        times = []
        for nbytes in probe_bytes:
            n_f32 = max(n_dev, (nbytes // 4) * n_dev)
            x = jax.device_put(
                np.zeros(n_f32, dtype=np.float32),
                jax.sharding.NamedSharding(mesh, P(AMP_AXIS)))

            def body(local):
                return jax.lax.ppermute(local, AMP_AXIS, pairs)

            fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(AMP_AXIS),),
                                   out_specs=P(AMP_AXIS), check_vma=False))
            fn(x).block_until_ready()          # compile + warm-up
            t0 = time.perf_counter()
            for _ in range(trials):
                x = fn(x)
            x.block_until_ready()
            times.append((time.perf_counter() - t0) / trials)
        b0, b1 = (float(b) for b in probe_bytes)
        t0_, t1_ = times
        beta = (t1_ - t0_) / (b1 - b0)
        alpha = t0_ - beta * b0
        if beta <= 0.0 or not np.isfinite(alpha) or not np.isfinite(beta):
            return DEFAULT_COMM_MODEL
        model = CommCostModel(alpha_s=max(alpha, 0.0),
                              beta_s_per_byte=beta, source="measured")
        _COMM_MODEL_CACHE[key] = model
        return model
    except Exception:
        return DEFAULT_COMM_MODEL


def comm_model(env=None, measure: Optional[bool] = None) -> CommCostModel:
    """The cost model for ``env``'s mesh: the cached per-mesh calibration
    when one exists, measuring one when asked, else
    :data:`DEFAULT_COMM_MODEL`.

    ``measure=None`` (the compile path's default) auto-calibrates on
    TPU-class meshes — real interconnects whose alpha/beta the default
    model cannot know — and keeps the default on host (CPU) meshes,
    where the virtual devices timeshare one memory system and a timing
    fit adds cross-process nondeterminism for no information.
    ``QUEST_TPU_COMM_CALIBRATE=1``/``0`` overrides either way; the fit
    runs once per process per mesh fingerprint (cached)."""
    import os
    mesh = getattr(env, "mesh", None) if env is not None else None
    if mesh is None:
        return DEFAULT_COMM_MODEL
    key = _mesh_cache_key(mesh)
    if key in _COMM_MODEL_CACHE:
        return _COMM_MODEL_CACHE[key]
    if measure is None:
        flag = os.environ.get("QUEST_TPU_COMM_CALIBRATE")
        if flag is not None:
            measure = flag not in ("0", "", "off")
        else:
            measure = mesh.devices.reshape(-1)[0].platform in (
                "tpu", "axon")
    if measure:
        return measure_comm_model(mesh)
    return DEFAULT_COMM_MODEL


@dataclasses.dataclass
class DispatchStats:
    """Compile-time dispatch accounting for one compiled program: how
    many recorded gates went in, how many kernels (fused groups, folded
    diagonals, layers, relayouts) the final plan dispatches. Produced by
    :meth:`CompiledCircuit.dispatch_stats`; ``bench.py`` machine-emits
    these fields next to gates/sec so the fusion win is parseable."""

    gates_in: int            # ops recorded on the circuit
    kernels_out: int         # op items in the final plan
    relayouts: int           # planned all-to-all relayouts
    fused_groups: int = 0    # dense fusion groups of >= 2 gates
    diag_folds: int = 0      # diagonal gates folded into shared factors
    commuted_diagonals: int = 0  # diagonals deferred past a dense run
    max_group_gates: int = 0     # largest gates-per-group count
    # communication-planner accounting (quest_tpu/parallel/layout.py):
    cross_shard_exchanges: int = 0  # 1q pair-exchange items in the plan
    swaps_absorbed: int = 0      # SWAP gates composed into the layout perm
    collectives_fused: int = 0   # relayout pairs merged into one exchange
    comm_bytes_planned: float = 0.0  # mesh-total collective bytes per run
    comm_bytes_saved: float = 0.0    # vs the count-based planner's plan
    # batched ensemble engine accounting (set by the last sweep /
    # expectation_sweep / sample_sweep on the compiled circuit):
    batch_size: int = 0              # points in the last batched run
    host_syncs_avoided: int = 0      # device->host transfers vs per-point
    batch_sharding_mode: str = "none"  # "none" | "batch" | "amp"
    # keyed executable cache accounting (serving workloads cycle
    # (form, donation, mode, dtype) keys; the cache is LRU-bounded —
    # QUEST_TPU_BATCH_CACHE — so long-lived services can't pin one
    # executable per key forever):
    batched_cache_size: int = 0        # live entries in the bounded cache
    batched_cache_evictions: int = 0   # executables dropped by the bound

    @property
    def dispatches(self) -> int:
        """Kernels the device runs per program execution (op passes plus
        relayout and pair exchanges) — the number the fusion pass and the
        communication planner exist to shrink."""
        return self.kernels_out + self.relayouts + self.cross_shard_exchanges

    @property
    def collective_launches(self) -> int:
        """Collectives issued per program execution (relayout exchanges
        plus cross-shard pair exchanges) — the communication planner's
        primary observable."""
        return self.relayouts + self.cross_shard_exchanges

    def as_dict(self) -> dict:
        return {"gates_in": self.gates_in,
                "kernels_out": self.kernels_out,
                "relayouts": self.relayouts,
                "dispatches": self.dispatches,
                "fused_groups": self.fused_groups,
                "diag_folds": self.diag_folds,
                "commuted_diagonals": self.commuted_diagonals,
                "max_group_gates": self.max_group_gates,
                "cross_shard_exchanges": self.cross_shard_exchanges,
                "swaps_absorbed": self.swaps_absorbed,
                "collectives_fused": self.collectives_fused,
                "collective_launches": self.collective_launches,
                "comm_bytes_planned": self.comm_bytes_planned,
                "comm_bytes_saved": self.comm_bytes_saved,
                "batch_size": self.batch_size,
                "host_syncs_avoided": self.host_syncs_avoided,
                "batch_sharding_mode": self.batch_sharding_mode,
                "batched_cache_size": self.batched_cache_size,
                "batched_cache_evictions": self.batched_cache_evictions}


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Profile everything inside the block to ``logdir``."""
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclasses.dataclass
class _Entry:
    calls: int = 0
    seconds: float = 0.0


class GateStats:
    """Count and time API-level gate calls.

    Use as a context manager around user code; it monkey-wraps the public
    gate functions of :mod:`quest_tpu.api` for the duration. Times are
    dispatch times (JAX is async); call :meth:`synced` around a block to
    include device completion.
    """

    GATE_NAMES = (
        "hadamard", "pauliX", "pauliY", "pauliZ", "sGate", "tGate",
        "phaseShift", "rotateX", "rotateY", "rotateZ", "rotateAroundAxis",
        "compactUnitary", "unitary", "controlledNot", "controlledPauliY",
        "controlledPhaseShift", "controlledPhaseFlip", "controlledRotateX",
        "controlledRotateY", "controlledRotateZ", "controlledCompactUnitary",
        "controlledUnitary", "multiControlledUnitary", "swapGate",
        "sqrtSwapGate", "multiRotateZ", "twoQubitUnitary", "multiQubitUnitary",
        "measure", "collapseToOutcome",
    )

    def __init__(self):
        self.entries: dict[str, _Entry] = defaultdict(_Entry)
        self._saved: dict[str, Callable] = {}

    def __enter__(self):
        import quest_tpu
        from . import api
        for name in self.GATE_NAMES:
            fn = getattr(api, name)
            self._saved[name] = fn

            def wrapped(*args, _fn=fn, _name=name, **kw):
                t0 = time.perf_counter()
                out = _fn(*args, **kw)
                e = self.entries[_name]
                e.calls += 1
                e.seconds += time.perf_counter() - t0
                return out

            setattr(api, name, wrapped)
            setattr(quest_tpu, name, wrapped)
        return self

    def __exit__(self, *exc):
        import quest_tpu
        from . import api
        for name, fn in self._saved.items():
            setattr(api, name, fn)
            setattr(quest_tpu, name, fn)
        self._saved.clear()
        return False

    @property
    def total_calls(self) -> int:
        return sum(e.calls for e in self.entries.values())

    def report(self) -> str:
        lines = [f"{'gate':<28}{'calls':>8}{'total s':>12}{'per call us':>14}"]
        for name, e in sorted(self.entries.items(),
                              key=lambda kv: -kv[1].seconds):
            per = e.seconds / e.calls * 1e6 if e.calls else 0.0
            lines.append(f"{name:<28}{e.calls:>8}{e.seconds:>12.4f}{per:>14.1f}")
        return "\n".join(lines)


def probe_gate(qureg, gate_fn: Callable, num_trials: int = 20,
               targets: Optional[range] = None) -> dict:
    """rotate_benchmark-equivalent: time ``gate_fn(qureg, target)`` over every
    target qubit, ``num_trials`` each; returns per-target mean/std/min/max
    seconds (device-synced)."""
    import numpy as np
    targets = targets or range(qureg.num_qubits_represented)
    results = {}
    for t in targets:
        gate_fn(qureg, t)                      # warm the compile cache
        qureg.state.block_until_ready()
        times = []
        for _ in range(num_trials):
            t0 = time.perf_counter()
            gate_fn(qureg, t)
            qureg.state.block_until_ready()
            times.append(time.perf_counter() - t0)
        arr = np.asarray(times)
        results[int(t)] = {"mean": float(arr.mean()), "std": float(arr.std()),
                           "min": float(arr.min()), "max": float(arr.max())}
    return results
