"""Tracing and profiling hooks.

The reference has none built in (SURVEY.md §5: only the timing harness in
`tests/benchmarks/rotate_benchmark.test` and the env reports). The TPU build
adds:

- :func:`trace` — context manager around the JAX profiler; the resulting
  trace opens in TensorBoard/Perfetto and shows every gate as a named XLA
  region;
- :class:`GateStats` — lightweight host-side counters: per-gate-name call
  counts and wall time of the (async-dispatched) API calls, plus a
  rotate-benchmark-style ``probe`` that times a gate across every target
  qubit (mean/std/min/max — the reference benchmark's statistics,
  `rotate_benchmark.test:40-60`).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import defaultdict
from typing import Callable, Optional, Sequence

import jax

__all__ = ["trace", "GateStats", "DispatchStats", "probe_gate",
           "CommCostModel", "DEFAULT_COMM_MODEL", "comm_model",
           "measure_comm_model", "invalidate_comm_model",
           "TierErrorModel", "DEFAULT_TIER_MODEL",
           "tier_error_model", "measure_tier_model", "modeled_tier_error",
           "engine_tiers", "choose_tier", "tier_runtime_tol"]


# ---------------------------------------------------------------------------
# collective cost model (the layout planner's objective function)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CommCostModel:
    """Two-tier linear latency/bandwidth model for one mesh collective:
    ``seconds = alpha + beta * bytes_on_the_wire`` per device, with a
    separate (alpha, beta) for collectives that cross the HOST boundary.

    The layout planner (:mod:`quest_tpu.parallel.layout`) prices every
    candidate data movement with this model and minimizes modeled comm
    TIME rather than relayout count:

    - a relayout trading ``k`` device-index bits against ``k`` chunk-local
      bits is one ``all_to_all`` over groups of ``2^k`` devices — each
      device keeps ``1/2^k`` of its chunk and ships the rest, so
      ``bytes = chunk_bytes * (2^k - 1) / 2^k`` (plus a full-chunk
      ``ppermute`` when a residual device-bit permutation remains);
    - a cross-shard 1q pair exchange (``apply_1q_cross_shard``) ships the
      whole chunk once: ``bytes = chunk_bytes``.

    **Tiers**: intra-host collectives ride ICI/shared memory
    (``alpha_s``/``beta_s_per_byte``); any collective whose exchanged
    device bits include an *inter-host* bit (the top ``host_bits``
    positions — :mod:`quest_tpu.parallel.multihost`) rides DCN and is
    priced with ``inter_alpha_s``/``inter_beta_s_per_byte``. The inter
    fields default to ``None`` = same as intra, so every single-host
    model (and every pre-two-tier caller) behaves exactly as before.

    ``alpha``/``beta`` default to a conservative interconnect model
    (:data:`DEFAULT_COMM_MODEL`); :func:`measure_comm_model` calibrates
    each tier per mesh with a tiny collective microbenchmark and caches
    the fit per ``(mesh fingerprint, tier)``. Decisions only depend on
    cost *ratios*, so plans stay deterministic for any non-degenerate
    (alpha >= 0, beta > 0) fit.
    """

    alpha_s: float              # per-collective launch latency (seconds)
    beta_s_per_byte: float      # per-byte transfer time (seconds/byte)
    source: str = "default"     # "default" | "measured"
    # inter-host (DCN) tier; None = fall back to the intra values, which
    # keeps every single-tier construction/call site bit-identical
    inter_alpha_s: Optional[float] = None
    inter_beta_s_per_byte: Optional[float] = None

    def tier(self, inter: bool = False) -> tuple[float, float]:
        """(alpha, beta) of one tier; the inter tier falls back to intra
        when uncalibrated."""
        if inter and self.inter_alpha_s is not None:
            return (self.inter_alpha_s,
                    self.inter_beta_s_per_byte
                    if self.inter_beta_s_per_byte is not None
                    else self.beta_s_per_byte)
        if inter and self.inter_beta_s_per_byte is not None:
            return (self.alpha_s, self.inter_beta_s_per_byte)
        return (self.alpha_s, self.beta_s_per_byte)

    @staticmethod
    def all_to_all_bytes(chunk_bytes: float, k: int) -> float:
        """Per-device bytes shipped by a k-bit relayout exchange."""
        if k <= 0:
            return 0.0
        return chunk_bytes * ((1 << k) - 1) / float(1 << k)

    @staticmethod
    def ppermute_bytes(chunk_bytes: float) -> float:
        """Per-device bytes shipped by a whole-chunk pair exchange."""
        return float(chunk_bytes)

    def all_to_all_seconds(self, chunk_bytes: float, k: int,
                           inter: bool = False) -> float:
        if k <= 0:
            return 0.0
        alpha, beta = self.tier(inter)
        return alpha + beta * self.all_to_all_bytes(chunk_bytes, k)

    def ppermute_seconds(self, chunk_bytes: float,
                         inter: bool = False) -> float:
        alpha, beta = self.tier(inter)
        return alpha + beta * self.ppermute_bytes(chunk_bytes)


# ~50 GB/s per-link bandwidth with a few-microsecond launch cost: the
# shape of both ICI links and a shared-memory host "mesh". The inter-host
# tier models DCN: ~25 GB/s effective per host pair with tens of
# microseconds of launch+routing latency — the order-of-magnitude gap
# mpiQulacs measures between Tofu-D intra-group and inter-group hops
# (arXiv:2203.16044 §IV). The planner's decisions are ratio-based, so the
# default is safe wherever no measurement has run.
DEFAULT_COMM_MODEL = CommCostModel(alpha_s=5e-6, beta_s_per_byte=2e-11,
                                   inter_alpha_s=5e-5,
                                   inter_beta_s_per_byte=4e-10)

# calibration cache, keyed (mesh device fingerprint, tier). A FAILED or
# degenerate fit caches the default-tier values too — the microbenchmark
# must never silently re-run on every compile (the pre-two-tier code
# returned the default UNCACHED on failure, re-paying the bench each
# call on boxes where the fit degenerates).
_COMM_MODEL_CACHE: dict = {}


def _mesh_cache_key(mesh, tier: str = "intra") -> tuple:
    devs = mesh.devices.reshape(-1)
    return (len(devs), devs[0].platform,
            getattr(devs[0], "device_kind", ""), tier)


def _model_pinned() -> bool:
    """``QUEST_TPU_COMM_MODEL=default`` pins :data:`DEFAULT_COMM_MODEL`
    deterministically — no microbenchmark ever runs (the escape hatch
    for test processes and reproducible planning)."""
    import os
    return os.environ.get("QUEST_TPU_COMM_MODEL", "") == "default"


def _measure_tier(mesh, pairs, probe_bytes, trials) -> Optional[tuple]:
    """(alpha, beta) fitted from a ppermute microbench over ``pairs``,
    or None on failure/degenerate fit."""
    import numpy as np
    try:
        from jax.sharding import PartitionSpec as P
        from .compat import shard_map
        from .env import AMP_AXIS
        n_dev = int(np.prod(mesh.devices.shape))
        times = []
        for nbytes in probe_bytes:
            n_f32 = max(n_dev, (nbytes // 4) * n_dev)
            x = jax.device_put(
                np.zeros(n_f32, dtype=np.float32),
                jax.sharding.NamedSharding(mesh, P(AMP_AXIS)))

            def body(local):
                return jax.lax.ppermute(local, AMP_AXIS, pairs)

            fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(AMP_AXIS),),
                                   out_specs=P(AMP_AXIS), check_vma=False))
            fn(x).block_until_ready()          # compile + warm-up
            t0 = time.perf_counter()
            for _ in range(trials):
                x = fn(x)
            x.block_until_ready()
            times.append((time.perf_counter() - t0) / trials)
        b0, b1 = (float(b) for b in probe_bytes)
        t0_, t1_ = times
        beta = (t1_ - t0_) / (b1 - b0)
        alpha = t0_ - beta * b0
        if beta <= 0.0 or not np.isfinite(alpha) or not np.isfinite(beta):
            return None
        return (max(alpha, 0.0), beta)
    # quest: allow-broad-except(calibration boundary: a failed or
    # degenerate microbench fit must fall back to the default model,
    # never break compile)
    except Exception:
        return None


def measure_comm_model(mesh, probe_bytes=(1 << 14, 1 << 19),
                       trials: int = 5) -> CommCostModel:
    """Fit (alpha, beta) per interconnect tier from tiny ``ppermute``
    microbenchmarks on ``mesh``.

    The *intra* tier times a neighbour ring inside each host group; when
    the mesh spans processes (:func:`quest_tpu.parallel.multihost.
    host_topology`), the *inter* tier additionally times a cross-host
    pairing. Each tier's fit is cached per ``(mesh fingerprint, tier)``
    — including failed fits, which pin that tier's DEFAULT values — so
    the microbenchmark runs at most once per process per tier, never
    again. ``QUEST_TPU_COMM_MODEL=default`` skips measurement entirely
    and returns :data:`DEFAULT_COMM_MODEL`."""
    import numpy as np
    if _model_pinned():
        return DEFAULT_COMM_MODEL
    from .parallel.multihost import host_topology
    n_dev = int(np.prod(mesh.devices.shape))
    topo = host_topology(mesh)
    per_host = max(1, topo.devices_per_host)
    # the host grouping shapes both the pairings and which tiers exist,
    # so it is part of every cache key — flipping QUEST_TPU_FORCE_HOSTS
    # mid-process must not serve a stale single-tier model
    hosttag = f":h{topo.num_hosts}"
    mkey = _mesh_cache_key(mesh, "model" + hosttag)
    if mkey in _COMM_MODEL_CACHE:
        return _COMM_MODEL_CACHE[mkey]

    ikey = _mesh_cache_key(mesh, "intra" + hosttag)
    if ikey not in _COMM_MODEL_CACHE:
        if per_host > 1:
            # neighbour ring inside each host group: (i -> i+1) mod group
            pairs = tuple(
                (i, (i // per_host) * per_host + (i + 1) % per_host)
                for i in range(n_dev))
            fit = _measure_tier(mesh, pairs, probe_bytes, trials)
        else:
            # one device per host: every link crosses hosts, there is
            # nothing intra to time (and host_bits == shard bits means
            # the intra tier is never consulted) — pin the default
            fit = None
        _COMM_MODEL_CACHE[ikey] = fit if fit is not None else (
            DEFAULT_COMM_MODEL.alpha_s, DEFAULT_COMM_MODEL.beta_s_per_byte,
            "default")
    intra = _COMM_MODEL_CACHE[ikey]

    inter = None
    if topo.is_multihost and topo.num_hosts > 1:
        xkey = _mesh_cache_key(mesh, "inter" + hosttag)
        if xkey not in _COMM_MODEL_CACHE:
            pairs = tuple((i, (i + per_host) % n_dev) for i in range(n_dev))
            fit = _measure_tier(mesh, pairs, probe_bytes, trials)
            if fit is None:
                # derive the pinned inter tier FROM the intra fit at the
                # default DCN/ICI ratios rather than using the absolute
                # default values: a measured intra alpha above the
                # default inter alpha would otherwise invert the tiers
                # and make the planner PREFER host-crossing collectives
                ra = DEFAULT_COMM_MODEL.inter_alpha_s \
                    / DEFAULT_COMM_MODEL.alpha_s
                rb = DEFAULT_COMM_MODEL.inter_beta_s_per_byte \
                    / DEFAULT_COMM_MODEL.beta_s_per_byte
                fit_d = (intra[0] * ra, intra[1] * rb, "default")
                _COMM_MODEL_CACHE[xkey] = fit_d
            else:
                # clamp a measured inter fit to no FASTER than intra —
                # timing noise must never invert the tier ordering
                _COMM_MODEL_CACHE[xkey] = (max(fit[0], intra[0]),
                                           max(fit[1], intra[1]))
        inter = _COMM_MODEL_CACHE[xkey]

    measured = len(intra) == 2 or (inter is not None and len(inter) == 2)
    if not measured:
        model = DEFAULT_COMM_MODEL
    else:
        model = CommCostModel(
            alpha_s=intra[0], beta_s_per_byte=intra[1],
            source="measured",
            inter_alpha_s=inter[0] if inter is not None else None,
            inter_beta_s_per_byte=inter[1] if inter is not None else None)
    _COMM_MODEL_CACHE[mkey] = model
    return model


def invalidate_comm_model() -> int:
    """Drop every cached :func:`measure_comm_model` fit so the next
    plan recalibrates — the drift monitor's opt-in recalibration hook
    (:func:`quest_tpu.telemetry.profile.enable_recalibration`): when
    measured collective time departs the modeled cost by more than the
    drift threshold, the cached fit is the stale thing to throw away.
    Returns the number of cache entries dropped."""
    n = len(_COMM_MODEL_CACHE)
    _COMM_MODEL_CACHE.clear()
    return n


def comm_model(env=None, measure: Optional[bool] = None) -> CommCostModel:
    """The cost model for ``env``'s mesh: the cached per-mesh calibration
    when one exists, measuring one when asked, else
    :data:`DEFAULT_COMM_MODEL`.

    ``measure=None`` (the compile path's default) auto-calibrates on
    TPU-class meshes — real interconnects whose alpha/beta the default
    model cannot know — and keeps the default on host (CPU) meshes,
    where the virtual devices timeshare one memory system and a timing
    fit adds cross-process nondeterminism for no information.
    ``QUEST_TPU_COMM_CALIBRATE=1``/``0`` overrides either way;
    ``QUEST_TPU_COMM_MODEL=default`` pins the default model
    unconditionally (tests, reproducible planning). The fit runs once
    per process per ``(mesh fingerprint, tier)`` (cached, failures
    included)."""
    import os
    mesh = getattr(env, "mesh", None) if env is not None else None
    if mesh is None:
        return DEFAULT_COMM_MODEL
    if _model_pinned():
        return DEFAULT_COMM_MODEL
    from .parallel.multihost import host_topology
    mkey = _mesh_cache_key(
        mesh, f"model:h{host_topology(mesh).num_hosts}")
    if mkey in _COMM_MODEL_CACHE:
        return _COMM_MODEL_CACHE[mkey]
    if measure is None:
        flag = os.environ.get("QUEST_TPU_COMM_CALIBRATE")
        if flag is not None:
            measure = flag not in ("0", "", "off")
        else:
            measure = mesh.devices.reshape(-1)[0].platform in (
                "tpu", "axon")
    if measure:
        return measure_comm_model(mesh)
    return DEFAULT_COMM_MODEL


# ---------------------------------------------------------------------------
# precision-tier error model (the budget API's objective function)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class TierErrorModel:
    """Calibrated per-tier drift model: the modeled max amplitude error
    of one program execution at a tier is ``drift_per_gate[tier] *
    num_gates`` (floored at ``floor`` — shallow circuits still carry one
    rounding). Linear-in-depth is deliberately conservative: the
    measured tables (docs/accuracy.md) grow sublinearly because
    rotation-phase errors largely cancel.

    ``drift_per_gate`` maps tier name -> per-gate constant, seeded from
    the ladder's measured figures (:data:`quest_tpu.config.TIER_LADDER`)
    and refined per backend by :func:`measure_tier_model` (a cached
    microbenchmark, the :func:`measure_comm_model` pattern). A refined
    fit is clamped to never fall BELOW the measurement — the model may
    over-estimate error (choosing a slower tier than strictly needed)
    but must never promise accuracy the backend cannot deliver.
    """

    drift_per_gate: dict
    floor: float = 1e-15
    source: str = "default"      # "default" | "measured"
    # silicon-calibrated per-tier execution cost (seconds per gate pass
    # of the calibration workload, measured on the LIVE backend — the
    # MXU pass count each tier actually pays, including the compensated
    # tiers' extra reduction traffic). Empty = unmeasured; the CPU
    # proxy never fills it.
    cost_per_gate: dict = dataclasses.field(default_factory=dict)
    cost_source: str = "none"    # "none" | "silicon"

    def error(self, tier, num_gates: int) -> float:
        from .config import tier_by_name
        tier = tier_by_name(tier)
        per_gate = self.drift_per_gate.get(tier.name,
                                           tier.drift_per_gate)
        return max(per_gate * max(int(num_gates), 1), self.floor)

    def cost_ratio(self, tier) -> float:
        """Measured cost of one gate pass at ``tier`` relative to the
        FAST rung (1.0 when uncalibrated) — the reduction trade priced
        by measured silicon instead of a CPU proxy."""
        from .config import tier_by_name
        tier = tier_by_name(tier)
        base = self.cost_per_gate.get("fast")
        mine = self.cost_per_gate.get(tier.name)
        if not base or not mine:
            return 1.0
        return mine / base


def _default_tier_model() -> TierErrorModel:
    from .config import TIER_LADDER
    return TierErrorModel(
        drift_per_gate={t.name: t.drift_per_gate for t in TIER_LADDER})


DEFAULT_TIER_MODEL = _default_tier_model()

# calibration cache, keyed on the backend fingerprint — the microbench
# must run at most once per process per backend (failed fits pin the
# default seeds, the _COMM_MODEL_CACHE discipline). Locked: unlike the
# comm-model cache (compile-time only), this one is reachable from
# SimulationService.submit(error_budget=...) — a documented thread-safe
# entry — so concurrent first submits must not each pay the bench
import threading as _threading
_TIER_MODEL_CACHE: dict = {}
_TIER_MODEL_LOCK = _threading.Lock()


def _tier_model_pinned() -> bool:
    """``QUEST_TPU_TIER_MODEL=default`` pins the seed constants
    deterministically — no microbenchmark ever runs (tests,
    reproducible tier selection)."""
    import os
    return os.environ.get("QUEST_TPU_TIER_MODEL", "") == "default"


def _tier_silicon_auto() -> bool:
    """Silicon cost calibration defaults ON for accelerator backends
    (real MXUs whose pass counts a CPU proxy cannot price) and OFF on
    hosts; ``QUEST_TPU_TIER_SILICON=1/0`` overrides."""
    import os
    import jax as jax_
    flag = os.environ.get("QUEST_TPU_TIER_SILICON")
    if flag is not None:
        return flag not in ("0", "", "off")
    return jax_.default_backend() in ("tpu", "axon")


def _mesh_fingerprint(env) -> tuple:
    """The env's device fingerprint — backend, device kind, device
    count — the :func:`measure_comm_model` cache-key discipline, so a
    model measured on one mesh shape is never served to another."""
    import jax as jax_
    try:
        dev = jax_.devices()[0]
        kind = getattr(dev, "device_kind", "")
    except (RuntimeError, IndexError):
        kind = ""
    return (jax_.default_backend(), kind,
            int(getattr(env, "num_devices", 1)))


def measure_tier_model(env, num_qubits: int = 8, layers: int = 4,
                       silicon: Optional[bool] = None) -> TierErrorModel:
    """Refine the per-tier drift constants with a tiny fixed-workload
    microbenchmark: a seeded brickwork circuit runs at each
    engine-executable tier and its state is compared against the most
    accurate tier available; the measured max|Δ|/gate refines each
    tier's constant (never below the measurement; never below the
    model floor).

    ``silicon`` (default: auto — on for accelerator backends, off on
    hosts; ``QUEST_TPU_TIER_SILICON`` overrides) additionally TIMES
    each tier's executable on the live backend — device-synced
    best-of-trials seconds per gate pass — so the reduction trade
    (compensated pair-path tiers pay real extra passes, the FAST rung's
    bf16 matmuls pay fewer MXU passes than HIGHEST's six-pass form) is
    priced by measured silicon rather than a CPU proxy; the figures
    land in :attr:`TierErrorModel.cost_per_gate` /
    :meth:`~TierErrorModel.cost_ratio`.

    Cached per mesh fingerprint (backend, device kind, device count,
    storage dtype, silicon flag — the :func:`measure_comm_model`
    discipline), failures included (they pin the seeds), so the bench
    runs at most once per process per fingerprint."""
    import numpy as np_
    if _tier_model_pinned():
        return DEFAULT_TIER_MODEL
    if silicon is None:
        silicon = _tier_silicon_auto()
    key = _mesh_fingerprint(env) + (
        str(np_.dtype(env.precision.real_dtype)), bool(silicon))
    with _TIER_MODEL_LOCK:
        if key in _TIER_MODEL_CACHE:
            return _TIER_MODEL_CACHE[key]
        return _measure_tier_model_locked(env, key, num_qubits, layers,
                                          silicon)


def _measure_tier_model_locked(env, key, num_qubits, layers, silicon):
    import numpy as np_
    try:
        from .circuits import Circuit
        from .config import TIER_LADDER
        rng = np_.random.default_rng(20260803)
        c = Circuit(num_qubits)
        n_gates = 0
        for _ in range(layers):
            for q in range(num_qubits):
                c.ry(q, float(rng.uniform(0, 2 * np_.pi)))
                n_gates += 1
            for q in range(0, num_qubits - 1, 2):
                c.cnot(q, q + 1)
                n_gates += 1
        cc = c.compile(env, pallas=False)
        tiers = engine_tiers(env)
        pm = np_.zeros((1, 0))
        states = {t.name: np_.asarray(cc.sweep(pm, tier=t))[0]
                  for t in tiers}
        oracle = states[tiers[-1].name]
        drift = dict(DEFAULT_TIER_MODEL.drift_per_gate)
        for t in tiers[:-1]:
            meas = float(np_.max(np_.abs(states[t.name] - oracle)))
            # 4x headroom over the measurement; never promise better
            # than the seed claims the hardware can do... the seed may
            # only be LOWERED when the backend measures cleaner by a
            # decade (e.g. FAST on CPU, where DEFAULT matmuls stay f32)
            refined = max(4.0 * meas / n_gates, DEFAULT_TIER_MODEL.floor)
            drift[t.name] = max(refined, drift[t.name] / 10.0) \
                if refined < drift[t.name] else refined
        cost: dict = {}
        if silicon:
            import jax as jax_
            trials = 3
            for t in tiers:
                # warmed above (the drift sweep compiled each tier);
                # time device-synced best-of-trials on the LIVE backend
                best = None
                for _ in range(trials):
                    t0 = time.perf_counter()
                    out = cc.sweep(pm, tier=t)
                    jax_.block_until_ready(out)
                    dt = time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                cost[t.name] = best / max(n_gates, 1)
        model = TierErrorModel(
            drift_per_gate=drift, source="measured",
            cost_per_gate=cost,
            cost_source="silicon" if cost else "none")
    # quest: allow-broad-except(calibration boundary: tier-model
    # measurement failure falls back to the conservative default)
    except Exception:
        model = DEFAULT_TIER_MODEL
    _TIER_MODEL_CACHE[key] = model
    return model


def tier_error_model(env=None, measure: Optional[bool] = None
                     ) -> TierErrorModel:
    """The tier error model for ``env``: the cached per-backend
    calibration when one exists, measuring one when asked, else the
    seed constants. ``measure=None`` auto-calibrates only on TPU-class
    backends (real MXUs whose bf16 drift the seeds cannot know exactly);
    host (CPU) runs keep the deterministic defaults.
    ``QUEST_TPU_TIER_MODEL=default`` pins the seeds unconditionally."""
    import os
    import jax as jax_
    if env is None or _tier_model_pinned():
        return DEFAULT_TIER_MODEL
    if measure is None:
        flag = os.environ.get("QUEST_TPU_TIER_CALIBRATE")
        if flag is not None:
            measure = flag not in ("0", "", "off")
        else:
            measure = jax_.default_backend() in ("tpu", "axon")
    if measure:
        return measure_tier_model(env)
    return DEFAULT_TIER_MODEL


def modeled_tier_error(tier, num_gates: int, model: Optional[
        TierErrorModel] = None) -> float:
    """Modeled max amplitude error of one ``num_gates``-gate program
    execution at ``tier``."""
    return (model or DEFAULT_TIER_MODEL).error(tier, num_gates)


def engine_tiers(env) -> tuple:
    """The ladder rungs the BATCHED ENGINE can execute on this env, in
    rank order. FAST and SINGLE always run (f32 planes); DOUBLE and
    QUAD need x64 (without it JAX would silently downcast the f64
    planes — the same guard as the QUAD64 env check) AND an f64 STORAGE
    precision — results leave the engine as env-dtype planes, so on an
    f32 env a DOUBLE execution would round straight back to f32 on exit
    (and QUAD's ~48-bit dd significand would too) and silently violate
    the budget that selected the tier. QUAD executes through the
    engine's double-double runner (``CompiledCircuit.
    _dd_batched_runner``) as a per-dispatch tier, so the serving
    ladder's escalation tops out at the genuinely highest rung instead
    of silently excluding it."""
    import jax as jax_
    import numpy as np_
    from .config import DOUBLE_TIER, FAST_TIER, QUAD_TIER, SINGLE_TIER
    tiers = [FAST_TIER, SINGLE_TIER]
    if jax_.config.jax_enable_x64 and env is not None and \
            np_.dtype(env.precision.real_dtype) == np_.dtype(np_.float64):
        tiers.append(DOUBLE_TIER)
        tiers.append(QUAD_TIER)
    return tuple(tiers)


def choose_tier(error_budget: float, num_gates: int, env=None,
                model: Optional[TierErrorModel] = None,
                tiers: Optional[Sequence] = None):
    """The budget API's selector: the CHEAPEST (lowest-rank) tier whose
    modeled error fits ``error_budget``, over the engine-executable
    ladder for ``env`` (or an explicit ``tiers`` subset).

    Monotone by construction: the ladder is rank-ordered with
    non-increasing drift, so a tighter budget can only move the choice
    UP the ladder, never to a faster tier. Raises ``ValueError`` when
    no available tier fits — an unmeetable budget is a caller error the
    submit/compile boundary must surface, not a silently-wrong answer."""
    if not (error_budget > 0.0):
        raise ValueError(f"error_budget must be > 0, got {error_budget!r}")
    model = model or (tier_error_model(env) if env is not None
                      else DEFAULT_TIER_MODEL)
    ladder = tuple(tiers) if tiers is not None else engine_tiers(env)
    for t in sorted(ladder, key=lambda t: t.rank):
        if model.error(t, num_gates) <= error_budget:
            return t
    best = min((model.error(t, num_gates) for t in ladder), default=None)
    raise ValueError(
        f"error budget {error_budget:g} is unmeetable on this "
        f"environment: the most accurate available tier models "
        f"{best:g} over {num_gates} gates (enable x64 for the DOUBLE "
        f"tier, or use the double-double compile_dd path)")


def tier_runtime_tol(tier, num_gates: int,
                     model: Optional[TierErrorModel] = None,
                     headroom: float = 8.0) -> float:
    """The runtime fidelity monitor's norm/trace drift threshold for one
    tier: ``headroom`` times the modeled per-run error, floored at the
    health guard's default 1e-6 (shallow f64 programs must not trip on
    benign rounding) and capped at 2e-2 (a drift past two percent is
    never in-budget at ANY tier — it is a numerical fault whatever the
    model says)."""
    err = modeled_tier_error(tier, num_gates, model)
    return float(min(max(headroom * err, 1e-6), 2e-2))


@dataclasses.dataclass
class DispatchStats:
    """Compile-time dispatch accounting for one compiled program: how
    many recorded gates went in, how many kernels (fused groups, folded
    diagonals, layers, relayouts) the final plan dispatches. Produced by
    :meth:`CompiledCircuit.dispatch_stats`; ``bench.py`` machine-emits
    these fields next to gates/sec so the fusion win is parseable."""

    gates_in: int            # ops recorded on the circuit
    kernels_out: int         # op items in the final plan
    relayouts: int           # planned all-to-all relayouts
    fused_groups: int = 0    # dense fusion groups of >= 2 gates
    diag_folds: int = 0      # diagonal gates folded into shared factors
    commuted_diagonals: int = 0  # diagonals deferred past a dense run
    max_group_gates: int = 0     # largest gates-per-group count
    # communication-planner accounting (quest_tpu/parallel/layout.py):
    cross_shard_exchanges: int = 0  # 1q pair-exchange items in the plan
    swaps_absorbed: int = 0      # SWAP gates composed into the layout perm
    collectives_fused: int = 0   # relayout pairs merged into one exchange
    comm_bytes_planned: float = 0.0  # mesh-total collective bytes per run
    comm_bytes_saved: float = 0.0    # vs the count-based planner's plan
    # multi-host (two-tier) accounting (quest_tpu/parallel/multihost.py):
    num_hosts: int = 1               # controller processes the mesh spans
    inter_host_collectives: int = 0  # planned collectives crossing hosts
    comm_bytes_inter_planned: float = 0.0  # mesh-total DCN bytes per run
    comm_bytes_inter_saved: float = 0.0    # vs the reordering-off plan
    # batched ensemble engine accounting (set by the last sweep /
    # expectation_sweep / sample_sweep on the compiled circuit):
    batch_size: int = 0              # points in the last batched run
    host_syncs_avoided: int = 0      # device->host transfers vs per-point
    batch_sharding_mode: str = "none"  # "none" | "batch" | "amp"
    # device-resident dynamics accounting (evolve_sweep/ground_sweep):
    # Trotter/imaginary-time steps the last dynamics dispatch iterated
    # inside ONE executable (batch x steps; 0 for non-dynamics runs)
    evolve_steps_fused: int = 0
    # keyed executable cache accounting (serving workloads cycle
    # (form, donation, mode, dtype, tier) keys; the cache is LRU-bounded
    # — QUEST_TPU_BATCH_CACHE — so long-lived services can't pin one
    # executable per key forever):
    batched_cache_size: int = 0        # live entries in the bounded cache
    batched_cache_evictions: int = 0   # executables dropped by the bound
    # precision-tier accounting (config.PrecisionTier; "env" = the
    # legacy per-environment precision, no tier selected):
    precision_tier: str = "env"        # compile-time tier of this program
    modeled_tier_error: float = 0.0    # the budget model's per-run bound

    @property
    def dispatches(self) -> int:
        """Kernels the device runs per program execution (op passes plus
        relayout and pair exchanges) — the number the fusion pass and the
        communication planner exist to shrink."""
        return self.kernels_out + self.relayouts + self.cross_shard_exchanges

    @property
    def collective_launches(self) -> int:
        """Collectives issued per program execution (relayout exchanges
        plus cross-shard pair exchanges) — the communication planner's
        primary observable."""
        return self.relayouts + self.cross_shard_exchanges

    def as_dict(self) -> dict:
        return {"gates_in": self.gates_in,
                "kernels_out": self.kernels_out,
                "relayouts": self.relayouts,
                "dispatches": self.dispatches,
                "fused_groups": self.fused_groups,
                "diag_folds": self.diag_folds,
                "commuted_diagonals": self.commuted_diagonals,
                "max_group_gates": self.max_group_gates,
                "cross_shard_exchanges": self.cross_shard_exchanges,
                "swaps_absorbed": self.swaps_absorbed,
                "collectives_fused": self.collectives_fused,
                "collective_launches": self.collective_launches,
                "comm_bytes_planned": self.comm_bytes_planned,
                "comm_bytes_saved": self.comm_bytes_saved,
                "num_hosts": self.num_hosts,
                "inter_host_collectives": self.inter_host_collectives,
                "comm_bytes_inter_planned": self.comm_bytes_inter_planned,
                "comm_bytes_inter_saved": self.comm_bytes_inter_saved,
                "batch_size": self.batch_size,
                "host_syncs_avoided": self.host_syncs_avoided,
                "batch_sharding_mode": self.batch_sharding_mode,
                "evolve_steps_fused": self.evolve_steps_fused,
                "batched_cache_size": self.batched_cache_size,
                "batched_cache_evictions": self.batched_cache_evictions,
                "precision_tier": self.precision_tier,
                "modeled_tier_error": self.modeled_tier_error}


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Profile everything inside the block to ``logdir``."""
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclasses.dataclass
class _Entry:
    calls: int = 0
    seconds: float = 0.0


class GateStats:
    """Count and time API-level gate calls.

    Use as a context manager around user code; it monkey-wraps the public
    gate functions of :mod:`quest_tpu.api` for the duration. Times are
    dispatch times (JAX is async); call :meth:`synced` around a block to
    include device completion.
    """

    GATE_NAMES = (
        "hadamard", "pauliX", "pauliY", "pauliZ", "sGate", "tGate",
        "phaseShift", "rotateX", "rotateY", "rotateZ", "rotateAroundAxis",
        "compactUnitary", "unitary", "controlledNot", "controlledPauliY",
        "controlledPhaseShift", "controlledPhaseFlip", "controlledRotateX",
        "controlledRotateY", "controlledRotateZ", "controlledCompactUnitary",
        "controlledUnitary", "multiControlledUnitary", "swapGate",
        "sqrtSwapGate", "multiRotateZ", "twoQubitUnitary", "multiQubitUnitary",
        "measure", "collapseToOutcome",
    )

    def __init__(self):
        self.entries: dict[str, _Entry] = defaultdict(_Entry)
        self._saved: dict[str, Callable] = {}

    def __enter__(self):
        import quest_tpu
        from . import api
        for name in self.GATE_NAMES:
            fn = getattr(api, name)
            self._saved[name] = fn

            def wrapped(*args, _fn=fn, _name=name, **kw):
                t0 = time.perf_counter()
                out = _fn(*args, **kw)
                e = self.entries[_name]
                e.calls += 1
                e.seconds += time.perf_counter() - t0
                return out

            setattr(api, name, wrapped)
            setattr(quest_tpu, name, wrapped)
        return self

    def __exit__(self, *exc):
        import quest_tpu
        from . import api
        for name, fn in self._saved.items():
            setattr(api, name, fn)
            setattr(quest_tpu, name, fn)
        self._saved.clear()
        return False

    @property
    def total_calls(self) -> int:
        return sum(e.calls for e in self.entries.values())

    def report(self) -> str:
        lines = [f"{'gate':<28}{'calls':>8}{'total s':>12}{'per call us':>14}"]
        for name, e in sorted(self.entries.items(),
                              key=lambda kv: -kv[1].seconds):
            per = e.seconds / e.calls * 1e6 if e.calls else 0.0
            lines.append(f"{name:<28}{e.calls:>8}{e.seconds:>12.4f}{per:>14.1f}")
        return "\n".join(lines)


def probe_gate(qureg, gate_fn: Callable, num_trials: int = 20,
               targets: Optional[range] = None) -> dict:
    """rotate_benchmark-equivalent: time ``gate_fn(qureg, target)`` over every
    target qubit, ``num_trials`` each; returns per-target mean/std/min/max
    seconds (device-synced)."""
    import numpy as np
    targets = targets or range(qureg.num_qubits_represented)
    results = {}
    for t in targets:
        gate_fn(qureg, t)                      # warm the compile cache
        qureg.state.block_until_ready()
        times = []
        for _ in range(num_trials):
            t0 = time.perf_counter()
            gate_fn(qureg, t)
            qureg.state.block_until_ready()
            times.append(time.perf_counter() - t0)
        arr = np.asarray(times)
        results[int(t)] = {"mean": float(arr.mean()), "std": float(arr.std()),
                           "min": float(arr.min()), "max": float(arr.max())}
    return results
