"""Tracing and profiling hooks.

The reference has none built in (SURVEY.md §5: only the timing harness in
`tests/benchmarks/rotate_benchmark.test` and the env reports). The TPU build
adds:

- :func:`trace` — context manager around the JAX profiler; the resulting
  trace opens in TensorBoard/Perfetto and shows every gate as a named XLA
  region;
- :class:`GateStats` — lightweight host-side counters: per-gate-name call
  counts and wall time of the (async-dispatched) API calls, plus a
  rotate-benchmark-style ``probe`` that times a gate across every target
  qubit (mean/std/min/max — the reference benchmark's statistics,
  `rotate_benchmark.test:40-60`).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import defaultdict
from typing import Callable, Optional

import jax

__all__ = ["trace", "GateStats", "DispatchStats", "probe_gate"]


@dataclasses.dataclass
class DispatchStats:
    """Compile-time dispatch accounting for one compiled program: how
    many recorded gates went in, how many kernels (fused groups, folded
    diagonals, layers, relayouts) the final plan dispatches. Produced by
    :meth:`CompiledCircuit.dispatch_stats`; ``bench.py`` machine-emits
    these fields next to gates/sec so the fusion win is parseable."""

    gates_in: int            # ops recorded on the circuit
    kernels_out: int         # op items in the final plan
    relayouts: int           # planned all-to-all relayouts
    fused_groups: int = 0    # dense fusion groups of >= 2 gates
    diag_folds: int = 0      # diagonal gates folded into shared factors
    commuted_diagonals: int = 0  # diagonals deferred past a dense run
    max_group_gates: int = 0     # largest gates-per-group count

    @property
    def dispatches(self) -> int:
        """Kernels the device runs per program execution (op passes plus
        relayout exchanges) — the number the fusion pass exists to
        shrink."""
        return self.kernels_out + self.relayouts

    def as_dict(self) -> dict:
        return {"gates_in": self.gates_in,
                "kernels_out": self.kernels_out,
                "relayouts": self.relayouts,
                "dispatches": self.dispatches,
                "fused_groups": self.fused_groups,
                "diag_folds": self.diag_folds,
                "commuted_diagonals": self.commuted_diagonals,
                "max_group_gates": self.max_group_gates}


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    """Profile everything inside the block to ``logdir``."""
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@dataclasses.dataclass
class _Entry:
    calls: int = 0
    seconds: float = 0.0


class GateStats:
    """Count and time API-level gate calls.

    Use as a context manager around user code; it monkey-wraps the public
    gate functions of :mod:`quest_tpu.api` for the duration. Times are
    dispatch times (JAX is async); call :meth:`synced` around a block to
    include device completion.
    """

    GATE_NAMES = (
        "hadamard", "pauliX", "pauliY", "pauliZ", "sGate", "tGate",
        "phaseShift", "rotateX", "rotateY", "rotateZ", "rotateAroundAxis",
        "compactUnitary", "unitary", "controlledNot", "controlledPauliY",
        "controlledPhaseShift", "controlledPhaseFlip", "controlledRotateX",
        "controlledRotateY", "controlledRotateZ", "controlledCompactUnitary",
        "controlledUnitary", "multiControlledUnitary", "swapGate",
        "sqrtSwapGate", "multiRotateZ", "twoQubitUnitary", "multiQubitUnitary",
        "measure", "collapseToOutcome",
    )

    def __init__(self):
        self.entries: dict[str, _Entry] = defaultdict(_Entry)
        self._saved: dict[str, Callable] = {}

    def __enter__(self):
        import quest_tpu
        from . import api
        for name in self.GATE_NAMES:
            fn = getattr(api, name)
            self._saved[name] = fn

            def wrapped(*args, _fn=fn, _name=name, **kw):
                t0 = time.perf_counter()
                out = _fn(*args, **kw)
                e = self.entries[_name]
                e.calls += 1
                e.seconds += time.perf_counter() - t0
                return out

            setattr(api, name, wrapped)
            setattr(quest_tpu, name, wrapped)
        return self

    def __exit__(self, *exc):
        import quest_tpu
        from . import api
        for name, fn in self._saved.items():
            setattr(api, name, fn)
            setattr(quest_tpu, name, fn)
        self._saved.clear()
        return False

    @property
    def total_calls(self) -> int:
        return sum(e.calls for e in self.entries.values())

    def report(self) -> str:
        lines = [f"{'gate':<28}{'calls':>8}{'total s':>12}{'per call us':>14}"]
        for name, e in sorted(self.entries.items(),
                              key=lambda kv: -kv[1].seconds):
            per = e.seconds / e.calls * 1e6 if e.calls else 0.0
            lines.append(f"{name:<28}{e.calls:>8}{e.seconds:>12.4f}{per:>14.1f}")
        return "\n".join(lines)


def probe_gate(qureg, gate_fn: Callable, num_trials: int = 20,
               targets: Optional[range] = None) -> dict:
    """rotate_benchmark-equivalent: time ``gate_fn(qureg, target)`` over every
    target qubit, ``num_trials`` each; returns per-target mean/std/min/max
    seconds (device-synced)."""
    import numpy as np
    targets = targets or range(qureg.num_qubits_represented)
    results = {}
    for t in targets:
        gate_fn(qureg, t)                      # warm the compile cache
        qureg.state.block_until_ready()
        times = []
        for _ in range(num_trials):
            t0 = time.perf_counter()
            gate_fn(qureg, t)
            qureg.state.block_until_ready()
            times.append(time.perf_counter() - t0)
        arr = np.asarray(times)
        results[int(t)] = {"mean": float(arr.mean()), "std": float(arr.std()),
                           "min": float(arr.min()), "max": float(arr.max())}
    return results
