from . import apply, matrices  # noqa: F401
