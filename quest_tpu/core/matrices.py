"""Standard gate matrices and diagonal factors (host-side, numpy).

Conventions match the reference exactly:

- ``compact_unitary(alpha, beta)`` = ``[[a, -conj(b)], [b, conj(a)]]``
  (``QuEST_cpu.c:1662-1719`` pair update).
- ``rotation(angle, axis)`` = ``exp(-i angle/2 n.sigma)`` via the
  (alpha, beta) map of ``getComplexPairFromRotation``
  (``QuEST_common.c:113-120``).
- ``sqrt_swap`` entries per ``statevec_sqrtSwapGate``
  (``QuEST_common.c:383-394``).
- Two-/multi-qubit matrices index bit ``j`` of the row by ``targets[j]``
  (ComplexMatrixN convention, gather order of ``QuEST_cpu.c:1820-1901``).

Everything here is tiny and host-side; matrices are built in float64/complex128
numpy and cast to the register dtype at application time.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "PAULI_MATS",
    "hadamard",
    "pauli_x",
    "pauli_y",
    "pauli_z",
    "s_gate",
    "t_gate",
    "compact_unitary",
    "rotation_pair",
    "rotation",
    "swap",
    "sqrt_swap",
    "matrix2",
    "matrix4",
    "unit_vector",
    "embed_in_support",
    "diag_in_support",
]

_I = np.eye(2, dtype=np.complex128)
_X = np.array([[0, 1], [1, 0]], dtype=np.complex128)
_Y = np.array([[0, -1j], [1j, 0]], dtype=np.complex128)
_Z = np.array([[1, 0], [0, -1]], dtype=np.complex128)

# indexed by PauliOpType value (I=0, X=1, Y=2, Z=3)
PAULI_MATS = (_I, _X, _Y, _Z)


def hadamard() -> np.ndarray:
    return np.array([[1, 1], [1, -1]], dtype=np.complex128) / np.sqrt(2.0)


def pauli_x() -> np.ndarray:
    return _X.copy()


def pauli_y(conj: bool = False) -> np.ndarray:
    return _Y.conj().copy() if conj else _Y.copy()


def pauli_z() -> np.ndarray:
    return _Z.copy()


def s_gate(conj: bool = False) -> np.ndarray:
    return np.diag([1.0, -1j if conj else 1j]).astype(np.complex128)


def t_gate(conj: bool = False) -> np.ndarray:
    ph = np.exp(-1j * np.pi / 4) if conj else np.exp(1j * np.pi / 4)
    return np.diag([1.0, ph]).astype(np.complex128)


def compact_unitary(alpha: complex, beta: complex) -> np.ndarray:
    """U = [[alpha, -conj(beta)], [beta, conj(alpha)]]."""
    a = complex(alpha)
    b = complex(beta)
    return np.array([[a, -np.conj(b)], [b, np.conj(a)]], dtype=np.complex128)


def unit_vector(axis) -> np.ndarray:
    v = np.asarray(axis, dtype=np.float64)
    return v / np.linalg.norm(v)


def rotation_pair(angle: float, axis) -> tuple[complex, complex]:
    """(alpha, beta) of exp(-i angle/2 n.sigma), per getComplexPairFromRotation."""
    n = unit_vector(axis)
    c, s = np.cos(angle / 2.0), np.sin(angle / 2.0)
    alpha = complex(c, -s * n[2])
    beta = complex(s * n[1], -s * n[0])
    return alpha, beta


def rotation(angle: float, axis, conj: bool = False) -> np.ndarray:
    alpha, beta = rotation_pair(angle, axis)
    if conj:
        alpha, beta = np.conj(alpha), np.conj(beta)
    return compact_unitary(alpha, beta)


def swap() -> np.ndarray:
    m = np.zeros((4, 4), dtype=np.complex128)
    m[0, 0] = m[3, 3] = 1
    m[1, 2] = m[2, 1] = 1
    return m


def sqrt_swap(conj: bool = False) -> np.ndarray:
    m = np.zeros((4, 4), dtype=np.complex128)
    m[0, 0] = m[3, 3] = 1
    m[1, 1] = m[2, 2] = 0.5 + 0.5j
    m[1, 2] = m[2, 1] = 0.5 - 0.5j
    return m.conj() if conj else m


def embed_in_support(u: np.ndarray, targets, support,
                     ctrl_mask: int = 0, flip_mask: int = 0) -> np.ndarray:
    """Embed a (controlled) gate into the full operator over ``support``.

    ``support`` lists qubits; bit ``j`` of the output matrix index addresses
    ``support[j]`` (same ComplexMatrixN convention as gate targets). All of
    ``targets`` and the control qubits must be members of ``support``.
    Controls condition on 1 unless their bit is set in ``flip_mask``.
    """
    support = list(support)
    pos = {q: j for j, q in enumerate(support)}
    k = len(support)
    dim = 1 << k
    t_local = [pos[t] for t in targets]
    c_local = 0
    f_local = 0
    m, q = ctrl_mask, 0
    while m:
        if m & 1:
            c_local |= 1 << pos[q]
            if (flip_mask >> q) & 1:
                f_local |= 1 << pos[q]
        m >>= 1
        q += 1
    t_mask = 0
    for t in t_local:
        t_mask |= 1 << t
    want = c_local & ~f_local
    full = np.zeros((dim, dim), dtype=np.complex128)
    for col in range(dim):
        if (col & c_local) != want:
            full[col, col] = 1.0
            continue
        m_in = 0
        for j, t in enumerate(t_local):
            if (col >> t) & 1:
                m_in |= 1 << j
        base = col & ~t_mask
        for m_out in range(1 << len(t_local)):
            row = base
            for j, t in enumerate(t_local):
                if (m_out >> j) & 1:
                    row |= 1 << t
            full[row, col] += u[m_out, m_in]
    return full


def diag_in_support(tensor: np.ndarray, qubits_desc, support) -> np.ndarray:
    """Embed a diagonal factor ((2,)*k tensor, axes = qubits sorted desc)
    as a diagonal operator over ``support`` (bit j <-> support[j])."""
    support = list(support)
    dim = 1 << len(support)
    pos = {q: j for j, q in enumerate(support)}
    d = np.ones(dim, dtype=np.complex128)
    for idx in range(dim):
        key = tuple((idx >> pos[q]) & 1 for q in qubits_desc)
        d[idx] = tensor[key]
    return np.diag(d)


def matrix2(u) -> np.ndarray:
    """Coerce a 2x2 matrix-like (nested list / ndarray) to complex128."""
    m = np.asarray(u, dtype=np.complex128)
    if m.shape != (2, 2):
        raise ValueError(f"expected 2x2 matrix, got shape {m.shape}")
    return m


def matrix4(u) -> np.ndarray:
    m = np.asarray(u, dtype=np.complex128)
    if m.shape != (4, 4):
        raise ValueError(f"expected 4x4 matrix, got shape {m.shape}")
    return m
