"""Core gate-application engine.

This single module replaces all three of the reference's backend kernel
families (the OpenMP block-stride pair loops of ``QuEST_cpu.c:1662-1901``, the
CUDA per-amplitude kernels of ``QuEST_gpu.cu:667-1246``, and the MPI
exchange-and-combine kernels of ``QuEST_cpu_distributed.c``): on TPU a gate is
an axis contraction that XLA vectorises, fuses, and — when the amplitude axis
is sharded over a mesh — lowers to ICI collectives automatically.

State layout
------------
A register of ``N`` qubits is one flat complex ``jax.Array`` of ``2**N``
amplitudes, where bit ``q`` of the amplitude index is the computational-basis
value of qubit ``q`` (identical indexing to the reference, ``QuEST.h:161-192``).
Viewed as a tensor of shape ``(2,)*N`` in C order, qubit ``q`` is axis
``N-1-q``.

Applying a k-qubit operator ``u`` to targets ``(t_0 … t_{k-1})`` (bit ``j`` of
``u``'s index addresses target ``t_j``, the reference's ComplexMatrixN
convention) is:

1. reshape to split out the target (and control) axes — rank ``2(k+c)+1``,
   never rank ``N``, so XLA sees small static shapes;
2. transpose those axes to the front (one fused copy);
3. a ``(2^k, 2^k) @ (2^k, 2^(N-k))`` matmul — MXU-shaped for big ``k``;
4. inverse transpose and flatten.

Controls are *sliced*, not masked: the control axes are indexed at their
required bit, so only the controlled subspace is touched — the same work
saving as the reference's ctrlMask skip (``QuEST_cpu.c:2146-2210``) without
any per-amplitude branching.

Diagonal operators (phase gates, multiRotateZ, dephasing) never pair
amplitudes; they are broadcast elementwise multiplies (`apply_diagonal`),
which XLA fuses into a single memory pass — the analogue of
``statevec_phaseShiftByTerm`` (``QuEST_cpu.c:2946-2985``).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "apply_unitary",
    "apply_diagonal",
    "bitmask",
    "permutation_to_order",
    "permutation_to_sorted_desc",
    "split_shape",
]


def bitmask(qubits: Sequence[int]) -> int:
    """OR of ``1 << q`` (the reference's ``getQubitBitMask``,
    ``QuEST_common.c:43-51``)."""
    m = 0
    for q in qubits:
        m |= 1 << int(q)
    return m


def split_shape(num_qubits: int, positions_desc: Sequence[int]) -> tuple[int, ...]:
    """Shape that splits the flat amplitude axis at each qubit position.

    ``positions_desc`` must be strictly descending qubit indices. The returned
    shape interleaves block axes with the 2-sized qubit axes; the axis of the
    i-th position is ``2*i + 1``.
    """
    shape = []
    upper = num_qubits
    for p in positions_desc:
        shape.append(1 << (upper - p - 1))
        shape.append(2)
        upper = p
    shape.append(1 << upper)
    return tuple(shape)


def permutation_to_order(targets: Sequence[int],
                         order: Sequence[int]) -> np.ndarray:
    """Index permutation re-expressing a gate matrix in a new bit order.

    The input matrix indexes bit ``j`` by ``targets[j]``; the output indexes
    bit ``i`` by ``order[i]`` (same qubit set). ``perm[m_new] = m_old``.
    """
    targets = tuple(targets)
    k = len(targets)
    perm = np.zeros(1 << k, dtype=np.int64)
    for mp in range(1 << k):
        m = 0
        for i, q in enumerate(order):
            if (mp >> i) & 1:
                m |= 1 << targets.index(q)
        perm[mp] = m
    return perm


def permutation_to_sorted_desc(targets: Sequence[int]) -> np.ndarray:
    """Index permutation mapping sorted-descending bit order to user order.

    The engine flattens target axes with the highest qubit as the most
    significant bit; the user matrix indexes bit ``j`` by ``targets[j]``.
    Returns ``perm`` with ``perm[m_sorted] = m_user``.
    """
    targets = tuple(targets)
    k = len(targets)
    desc = sorted(targets, reverse=True)
    perm = np.zeros(1 << k, dtype=np.int64)
    for mp in range(1 << k):
        m = 0
        for i, q in enumerate(desc):
            if (mp >> (k - 1 - i)) & 1:
                m |= 1 << targets.index(q)
        perm[mp] = m
    return perm


def apply_unitary(
    state: jnp.ndarray,
    num_qubits: int,
    u: jnp.ndarray,
    targets: Sequence[int],
    ctrl_mask: int = 0,
    flip_mask: int = 0,
    precision=None,
) -> jnp.ndarray:
    """Apply a ``2^k x 2^k`` operator to target qubits of a flat state.

    ``ctrl_mask`` selects control qubits; a control conditions on bit value 1
    unless its bit is also set in ``flip_mask`` (then it conditions on 0) —
    the mask/flip-mask semantics of ``statevec_multiControlledUnitary``
    (``QuEST_cpu.c:2146``) and multiStateControlledUnitary.

    ``precision`` sets the matmul precision of the contraction (default
    ``HIGHEST``, the full-f32 MXU passes; the FAST precision tier passes
    ``Precision.DEFAULT`` — bf16 MXU inputs — through the compiled-
    circuit executors, trading the ~1e-4/gate drift the tier error
    model budgets for one MXU pass instead of six).

    All arguments except ``state`` and ``u`` must be static under jit.
    """
    # HIGHEST keeps the MXU in full-f32 passes: the TPU default (bf16
    # operands) loses ~1e-3 per gate worst case, far outside simulation
    # tolerance unless a caller-stated error budget opted into it
    prec = jax.lax.Precision.HIGHEST if precision is None else precision
    targets = tuple(int(t) for t in targets)
    k = len(targets)
    controls = tuple(q for q in range(num_qubits) if (ctrl_mask >> q) & 1)

    with jax.named_scope(
            f"gate_u{k}q_t{'_'.join(map(str, targets))}"
            + (f"_c{len(controls)}" if controls else "")):
        # --- no-transpose fast paths (uncontrolled, contiguous ends) ------
        # A gate on the lowest k qubits is a plain right-matmul on the
        # (rest, 2^k) view; on the highest k, a left-matmul on (2^k, rest).
        # Either costs exactly one read+write pass — the generic path below
        # pays materialised transposes around the matmul.
        if not controls and set(targets) == set(range(k)):
            u = jnp.asarray(u, dtype=state.dtype)
            if targets != tuple(range(k)):
                perm_asc = permutation_to_order(targets, tuple(range(k)))
                u = u[perm_asc][:, perm_asc]
            s = state.reshape(-1, 1 << k)
            out = jnp.matmul(s, u.T, precision=prec)
            return out.reshape(-1)
        lo = min(targets) if targets else 0
        if not controls and set(targets) == set(range(lo, lo + k)):
            # contiguous block [lo, lo+k): batched matmul on the
            # (pre, 2^k, post) view — bit i of the middle index is qubit
            # lo+i. pre==1 and post==1 degenerate to plain left-matmuls.
            u = jnp.asarray(u, dtype=state.dtype)
            order = tuple(range(lo, lo + k))
            if targets != order:
                perm_o = permutation_to_order(targets, order)
                u = u[perm_o][:, perm_o]
            s = state.reshape(-1, 1 << k, 1 << lo)
            out = jnp.matmul(u, s, precision=prec)
            return out.reshape(-1)

        pos_desc = tuple(sorted(targets + controls, reverse=True))
        shape = split_shape(num_qubits, pos_desc)
        axis_of = {p: 2 * i + 1 for i, p in enumerate(pos_desc)}

        ctrl_axes = [axis_of[c] for c in controls]
        targ_axes = [axis_of[t] for t in sorted(targets, reverse=True)]
        moved = set(ctrl_axes) | set(targ_axes)
        rest_axes = [ax for ax in range(len(shape)) if ax not in moved]
        perm = ctrl_axes + targ_axes + rest_axes

        arr = state.reshape(shape).transpose(perm)
        ctrl_idx = tuple(0 if (flip_mask >> c) & 1 else 1 for c in controls)

        sub = arr[ctrl_idx] if controls else arr
        rest_shape = sub.shape[k:]

        u = jnp.asarray(u, dtype=state.dtype)
        row_perm = permutation_to_sorted_desc(targets)
        if not np.array_equal(row_perm, np.arange(1 << k)):
            u = u[row_perm][:, row_perm]

        new = jnp.matmul(u, sub.reshape(1 << k, -1), precision=prec)
        new = new.reshape((2,) * k + rest_shape)
        arr = arr.at[ctrl_idx].set(new) if controls else new

        inv = np.argsort(perm)
        return arr.transpose(inv).reshape(-1)


def apply_diagonal(
    state: jnp.ndarray,
    num_qubits: int,
    qubits: Sequence[int],
    diag_tensor: jnp.ndarray,
) -> jnp.ndarray:
    """Elementwise-multiply amplitudes by a per-bit-pattern factor.

    ``diag_tensor`` has shape ``(2,)*k``; axis ``i`` is indexed by the bit of
    the i-th qubit of ``qubits`` *sorted descending*. One fused memory pass,
    no amplitude pairing — the fast path for every phase-family gate and for
    dephasing channels.
    """
    pos_desc = tuple(sorted((int(q) for q in qubits), reverse=True))
    with jax.named_scope(f"gate_diag_q{'_'.join(map(str, pos_desc))}"):
        shape = split_shape(num_qubits, pos_desc)
        bshape = [1] * len(shape)
        for i in range(len(pos_desc)):
            bshape[2 * i + 1] = 2
        factor = jnp.asarray(diag_tensor, dtype=state.dtype).reshape(bshape)
        return (state.reshape(shape) * factor).reshape(-1)
