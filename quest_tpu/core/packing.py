"""Split real/imag state representation.

The register state is stored as a float array of shape ``(2, 2^N)`` — a
real plane and an imaginary plane — mirroring the reference's split
``stateVec.real`` / ``stateVec.imag`` storage (``QuEST_cpu.c:1284-1320``),
and required on TPU: the PJRT backend rejects complex-typed device buffers
at executable boundaries, while complex arithmetic *inside* a compiled
program lowers fine. Every kernel therefore unpacks floats -> complex at
trace time, computes, and packs back; XLA fuses the (de)interleaving into
the surrounding ops for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["pack", "unpack", "pack_host", "unpack_host"]


def unpack(state_f: jnp.ndarray) -> jnp.ndarray:
    """(2, ...) float planes -> complex array (jit-internal only)."""
    return jax.lax.complex(state_f[0], state_f[1])


def pack(z: jnp.ndarray) -> jnp.ndarray:
    """complex array -> (2, ...) float planes (jit-internal only)."""
    return jnp.stack([jnp.real(z), jnp.imag(z)])


def pack_host(z: np.ndarray, real_dtype) -> np.ndarray:
    z = np.asarray(z)
    return np.stack([np.real(z), np.imag(z)]).astype(real_dtype)


def unpack_host(f: np.ndarray) -> np.ndarray:
    f = np.asarray(f)
    cdtype = np.complex64 if f.dtype == np.float32 else np.complex128
    return (f[0] + 1j * f[1]).astype(cdtype)
