"""Circuit-level gate fusion: runs of adjacent gates collapse into one kernel.

The reference applies every gate as its own full-state pass
(``QuEST_gpu.cu:722-728``: one kernel launch per gate); distributed
simulators in the mpiQulacs lineage (2203.16044) win by merging runs of
adjacent gates whose combined support stays small into single dense
unitaries, so one data move — and one kernel — serves many gates. This
module is that pass for the compiled pipeline: it rewrites the recorded
op stream BETWEEN recording and layout planning, so the layout planner
(:mod:`quest_tpu.parallel.layout`) chooses relayouts per fused *group*
rather than per gate, and XLA receives one fat contraction where it used
to receive a ladder of thin ones.

Three rewrites, in one linear scan:

1. **dense fusion** — consecutive static gates (dense or diagonal) whose
   combined support (targets + controls) fits in ``max_k`` qubits compose
   into ONE ``2^k x 2^k`` unitary (`embed_in_support` per member, matrix
   product in program order);
2. **diagonal folding** — runs of diagonal/phase gates merge into one
   elementwise factor over the union of their qubits (never densified:
   a diagonal run of any length stays one broadcast multiply);
3. **diagonal commuting** — a diagonal that would overflow an open dense
   run is *deferred* past it instead of breaking it: diagonals commute
   with each other always and with dense gates on disjoint qubits, so
   the deferred factor simply re-emerges after the run (or seeds the
   next one). Phase ladders (QFT's bulk) therefore never fence dense
   fusion.

Soundness of the reorder: a deferred factor is only carried past ops
that join a group *after* its defer point, and every such dense join is
gated on disjointness from all deferred supports (diagonal joins need no
gate — diagonals commute pairwise). Ops already in a group at defer time
keep their original order relative to the factor, because the group is
emitted before it.

Ops are :class:`quest_tpu.circuits._Op` records; the pass is agnostic to
that class (it rebuilds merged ops with :func:`dataclasses.replace`, so
any dataclass with the same field protocol works). Parameterized ops,
channels, and anything matching ``barrier`` flush all pending state and
pass through unchanged — fusion never reorders across them.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as np

from . import matrices as mats

__all__ = ["FusionStats", "fuse_ops", "op_support", "resolve_fusion_k",
           "compose_in_support"]


def compose_in_support(members: Sequence, sup: tuple) -> np.ndarray:
    """Left-to-right product of static ops embedded over ``sup`` (bit j
    of the result indexes ``sup[j]``) — the one place the group-collapse
    math lives, shared by this pass and the post-plan super-gate
    grouping (``circuits._group_supergates``)."""
    m = np.eye(1 << len(sup), dtype=np.complex128)
    for op in members:
        if op.kind == "u":
            e = mats.embed_in_support(op.mat, op.targets, sup,
                                      op.ctrl_mask, op.flip_mask)
        else:
            e = mats.diag_in_support(np.asarray(op.diag), op.targets, sup)
        m = e @ m
    return m


@dataclasses.dataclass
class FusionStats:
    """Per-pass fusion accounting, surfaced through
    :meth:`CompiledCircuit.dispatch_stats` (``profiling.DispatchStats``
    owns the serialized form)."""
    gates_in: int = 0            # ops entering the pass
    kernels_out: int = 0         # ops leaving the pass
    fused_groups: int = 0        # dense groups of >= 2 members emitted
    diag_folds: int = 0          # diagonal ops merged into a factor
    commuted_diagonals: int = 0  # diagonals deferred past an open group
    group_sizes: list = dataclasses.field(default_factory=list)

    @property
    def max_group_gates(self) -> int:
        return max(self.group_sizes, default=0)


def op_support(op) -> frozenset:
    """Qubits a dense op occupies: targets plus control bits."""
    qs = set(op.targets)
    m, q = op.ctrl_mask, 0
    while m:
        if m & 1:
            qs.add(q)
        m >>= 1
        q += 1
    return frozenset(qs)


def resolve_fusion_k(fusion, num_local: int, default: int = 3) -> int:
    """Resolve the user-facing ``fusion=`` knob to an effective support
    cap: ``None``/``True`` -> the default k, ``False``/``0`` -> off, an
    int -> that k — always clamped to the chunk-local qubit count
    (``num_local``) so a
    fused gate never outgrows what one device can gather locally (the
    ``fits_local`` predicate of :mod:`quest_tpu.parallel.pergate`,
    mirroring ``validateMultiQubitMatrixFitsInNode``)."""
    if fusion is None or fusion is True:
        k = default
    elif fusion is False:
        k = 0
    else:
        k = int(fusion)
    return min(k, num_local)


@dataclasses.dataclass
class _DiagChunk:
    """One deferred (or accumulating) diagonal factor: axes of ``tensor``
    follow ``support`` sorted descending. ``template`` is a source op the
    emitted record is rebuilt from (field protocol, not content)."""
    tensor: np.ndarray
    support: frozenset
    template: object
    n_src: int = 1

    @property
    def union_desc(self) -> tuple:
        return tuple(sorted(self.support, reverse=True))

    def merged(self, tensor: np.ndarray, qubits_desc: tuple,
               n_src: int = 1) -> "_DiagChunk":
        support = self.support | frozenset(qubits_desc)
        union = tuple(sorted(support, reverse=True))

        def expand(t, qs):
            shape = tuple(2 if q in qs else 1 for q in union)
            return np.asarray(t).reshape(shape)

        return _DiagChunk(expand(self.tensor, self.union_desc)
                          * expand(tensor, qubits_desc),
                          support, self.template, self.n_src + n_src)


def fuse_ops(ops: Sequence, max_k: int = 3, diag_max: int = 12,
             diag_row_cap: int = -1,
             barrier: Optional[Callable] = None):
    """Fuse an op stream; returns ``(fused_ops, FusionStats)``.

    ``max_k``: support cap for dense groups (gates + absorbed diagonals
    compose into one ``2^max_k``-dim unitary at most). ``diag_max`` caps
    the qubit union of a folded diagonal factor — a folded factor is ONE
    elementwise pass whatever its union, so the cap is generous (2^12
    tensor entries; measured on QFT-18/8dev: raising it from 6 to 12
    cut kernels 39 -> 20 and took the fusion speedup from 1.15x to
    ~1.75x median). ``diag_row_cap >= 0`` additionally caps its row-bit
    count
    (qubits >= 7) so folded factors stay eligible for the Pallas layer
    kernel (see ``Circuit._fused_ops``). ``barrier(op) -> True`` fences
    an op from fusion entirely (used to keep Pallas-layer-eligible runs
    intact).
    """
    stats = FusionStats(gates_in=len(ops))
    if max_k < 2:
        out = list(ops)
        stats.kernels_out = len(out)
        return out, stats

    out: list = []
    group: list = []                  # ops / chunks, in program order
    gsupport: frozenset = frozenset()
    gsrc = 0                          # source gates inside the group
    trailing: list[_DiagChunk] = []   # deferred diag factors, defer order

    def diag_fits(support: frozenset) -> bool:
        if len(support) > diag_max:
            return False
        if diag_row_cap >= 0 and sum(q >= 7 for q in support) > diag_row_cap:
            return False
        return True

    def chunk_op(chunk: _DiagChunk):
        return dataclasses.replace(
            chunk.template, kind="diag", targets=chunk.union_desc,
            ctrl_mask=0, flip_mask=0, mat=None, mat_fn=None,
            diag=chunk.tensor, diag_fn=None, kraus=None)

    def emit_group():
        nonlocal group, gsupport, gsrc
        if not group:
            return
        if len(group) == 1:
            m = group[0]
            out.append(chunk_op(m) if isinstance(m, _DiagChunk) else m)
        else:
            sup = tuple(sorted(gsupport))
            members = [chunk_op(g) if isinstance(g, _DiagChunk) else g
                       for g in group]
            m = compose_in_support(members, sup)
            out.append(dataclasses.replace(
                members[0], kind="u", targets=sup, ctrl_mask=0,
                flip_mask=0, mat=m, mat_fn=None, diag=None, diag_fn=None,
                kraus=None))
            stats.fused_groups += 1
            stats.group_sizes.append(gsrc)
        group = []
        gsupport = frozenset()
        gsrc = 0

    def emit_chunks(chunks):
        out.extend(chunk_op(c) for c in chunks)

    def flush_all():
        nonlocal trailing
        emit_group()
        emit_chunks(trailing)
        trailing = []

    for op in ops:
        kind = getattr(op, "kind", None)
        if (kind not in ("u", "diag") or not op.is_static
                or (barrier is not None and barrier(op))):
            flush_all()
            out.append(op)
            continue

        if kind == "diag":
            ds = frozenset(op.targets)
            # absorbing into the open dense run keeps the factor ahead of
            # every deferred chunk — valid: diagonals commute pairwise
            if group and len(gsupport | ds) <= max_k:
                group.append(op)
                gsupport |= ds
                gsrc += 1
                continue
            tensor = np.asarray(op.diag)
            # best-fit fold: diagonals commute pairwise, so ANY deferred
            # chunk is a valid home — pick the one whose union grows
            # least (fewest standalone factor passes at flush time)
            best, best_grow = None, None
            for ci, c in enumerate(trailing):
                u = c.support | ds
                if diag_fits(u):
                    grow = len(u) - len(c.support)
                    if best is None or grow < best_grow:
                        best, best_grow = ci, grow
            if best is not None:
                trailing[best] = trailing[best].merged(tensor, op.targets)
                stats.diag_folds += 1
            else:
                trailing.append(_DiagChunk(tensor, ds, op))
                if group:
                    stats.commuted_diagonals += 1
            continue

        # dense static op
        qs = op_support(op)
        if len(qs) > max_k:
            flush_all()
            out.append(op)
            continue
        tsupport = frozenset().union(*(c.support for c in trailing)) \
            if trailing else frozenset()
        if group and len(gsupport | qs) <= max_k and not (qs & tsupport):
            group.append(op)
            gsupport |= qs
            gsrc += 1
            continue
        # close the open run; deferred chunks overlapping this gate must
        # land before it — as leading members of the NEXT run when they
        # fit, standalone factors otherwise. Disjoint chunks stay
        # deferred across the boundary (the "commute" in the module doc).
        emit_group()
        overlapping = [c for c in trailing if c.support & qs]
        disjoint = [c for c in trailing if not (c.support & qs)]
        seed_support = qs.union(*(c.support for c in overlapping))
        if overlapping and len(seed_support) <= max_k:
            group = list(overlapping) + [op]
            gsupport = seed_support
            gsrc = sum(c.n_src for c in overlapping) + 1
        else:
            emit_chunks(overlapping)
            group = [op]
            gsupport = qs
            gsrc = 1
        trailing = disjoint

    flush_all()
    stats.kernels_out = len(out)
    return out, stats
