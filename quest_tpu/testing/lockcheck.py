"""Runtime lock-order validation for the quest_tpu thread soup.

19 locks across 11 modules guard the dispatcher/supervisor/watchdog
threads, and nothing enforced a consistent acquisition order — an
inversion (thread 1 takes A then B, thread 2 takes B then A) deadlocks
a replica only under production interleavings. This module turns the
invariant into a *deterministic test failure*:

- under ``QUEST_TPU_LOCKCHECK=1`` (tier-1 conftest enables it),
  :func:`install` wraps ``threading.Lock`` / ``threading.RLock`` /
  ``threading.Condition`` so every lock **created from quest_tpu
  code** is a tracked proxy tagged with its creation site
  (``module:line`` — one graph node per site, shared by every instance,
  so replica 0 and replica 1 teach the same ordering rules);
- each thread keeps its held-set; every acquisition of B while holding
  A records the edge ``A -> B`` in a process-global acquisition-order
  graph (with the acquire site of first observation);
- an acquisition that closes a cycle raises a typed
  :class:`LockOrderViolation` naming BOTH lock sites and both acquire
  sites — the would-be deadlock, surfaced on the first run that
  exercises either order, not the unlucky one that interleaves them;
- every violation is also recorded process-globally
  (:func:`violations`), so a violation swallowed by a recovery path's
  broad handler still fails the suite (the conftest asserts the list
  is empty at session end).

Reentrant acquisition of the same lock (RLock, the Condition idiom,
and the shared-instance Counter-family lock in ``serve/metrics.py``)
never adds edges. Overhead is a dict update per acquisition — noise
against an engine dispatch.
"""

from __future__ import annotations

import contextlib as _contextlib
import os
import threading

__all__ = ["LockOrderViolation", "install", "uninstall", "installed",
           "suspended",
           "tracked_lock", "graph", "violations", "clear",
           "assert_clean", "find_cycle"]


class LockOrderViolation(RuntimeError):
    """Two lock sites were acquired in both orders: a latent deadlock.

    ``site_a`` / ``site_b`` name the lock CREATION sites
    (``module.py:line``); the message carries the acquire sites of both
    directions."""

    def __init__(self, msg: str, site_a: str = "", site_b: str = ""):
        super().__init__(msg)
        self.site_a = site_a
        self.site_b = site_b


# ALL mutable state is anchored on the threading module itself, so the
# conftest (which loads this file standalone, BEFORE any quest_tpu
# import can create untracked locks) and the package import
# (quest_tpu.testing.lockcheck) share one graph, one violation list,
# one held-set — whichever copy of the module touches them.
_STATE = getattr(threading, "_quest_tpu_lockcheck", None)
if _STATE is None:
    _STATE = {
        "state_lock": threading.Lock(),   # guards graph + violations
        "edges": {},                      # site -> {site: acquire_site}
        "violations": [],
        "installed": False,
        "real": {},                       # saved threading factories
        "tls": threading.local(),
    }
    threading._quest_tpu_lockcheck = _STATE

# the exception CLASS is anchored too: the conftest's standalone load
# and the package import must raise/catch the SAME type, or a
# `pytest.raises(quest_tpu.testing.LockOrderViolation)` around a real
# inversion (raised by the other copy's factory) would not catch
LockOrderViolation = _STATE.setdefault("exc_class", LockOrderViolation)

_state_lock = _STATE["state_lock"]
_edges: dict = _STATE["edges"]
_violations: list = _STATE["violations"]
_real: dict = _STATE["real"]
_tls = _STATE["tls"]
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF = os.path.abspath(__file__)


def _held() -> list:
    """This thread's held stack (innermost last)."""
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _caller_site(depth_limit: int = 12):
    """The first stack frame inside quest_tpu (excluding this module):
    the lock's creation/acquire site. None when the creation is not
    quest_tpu code (those locks stay untracked raw locks)."""
    import sys
    frame = sys._getframe(2)
    for _ in range(depth_limit):
        if frame is None:
            return None
        fn = frame.f_code.co_filename
        af = os.path.abspath(fn)
        if af != _SELF and af.startswith(_PKG_DIR + os.sep) \
                and "threading" not in os.path.basename(fn):
            rel = os.path.relpath(af, os.path.dirname(_PKG_DIR))
            return f"{rel.replace(os.sep, '/')}:{frame.f_lineno}"
        frame = frame.f_back
    return None


def _reach(src: str, dst: str) -> bool:
    """DFS reachability in the order graph (caller holds _state_lock)."""
    seen = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(_edges.get(n, ()))
    return False


def _path(src: str, dst: str) -> list:
    """One path src -> dst (caller holds _state_lock; assumes one
    exists)."""
    seen = {src: None}
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            out = [n]
            while seen[n] is not None:
                n = seen[n]
                out.append(n)
            return list(reversed(out))
        for m in _edges.get(n, {}):
            if m not in seen:
                seen[m] = n
                stack.append(m)
    return [src, dst]


class _HeldEntry:
    __slots__ = ("site", "proxy", "count")

    def __init__(self, site, proxy):
        self.site = site
        self.proxy = proxy
        self.count = 1


class _TrackedLock:
    """Order-tracking proxy around a real lock primitive.

    Forwards everything it does not intercept (``_is_owned``,
    ``_release_save``... — the Condition protocol) to the wrapped lock,
    so it composes with ``threading.Condition`` built on either side.
    All hold bookkeeping is PER-THREAD (a Condition ``wait`` releases
    the raw lock underneath while other threads acquire through the
    proxy — a shared owner field would corrupt; per-thread held entries
    stay consistent at the wait's entry and exit).
    """

    __slots__ = ("_lock", "site")

    def __init__(self, raw, site: str):
        self._lock = raw
        self.site = site

    # -- bookkeeping -------------------------------------------------------

    def _note_acquired(self):
        held = _held()
        for e in held:
            if e.proxy is self:
                e.count += 1     # reentrant (RLock): no new edges
                return
        if held:
            # the acquire-site stack walk is LAZY: only a first-time
            # edge (or a violation) pays it — the steady state costs a
            # dict probe, keeping the checker invisible next to the
            # serving path's tracing overhead budget
            acq = None
            with _state_lock:
                for e in held:
                    site = e.site
                    if site == self.site:
                        # same creation site: distinct instances of one
                        # class's lock held together (instance
                        # hierarchies order themselves)
                        continue
                    fwd = _edges.setdefault(site, {})
                    if self.site in fwd:
                        continue
                    if acq is None:
                        acq = _caller_site() or "<non-quest_tpu frame>"
                    if _reach(self.site, site):
                        cyc = _path(self.site, site)
                        first = _edges.get(cyc[0], {}).get(cyc[1], "?")
                        msg = (
                            f"lock-order inversion: acquiring "
                            f"{self.site} (at {acq}) while holding "
                            f"{site}, but the reverse order "
                            f"{' -> '.join(cyc)} was already recorded "
                            f"(first at {first}) — these locks "
                            f"deadlock under the wrong interleaving")
                        v = LockOrderViolation(msg, site_a=site,
                                               site_b=self.site)
                        _violations.append(v)
                        raise v
                    fwd[self.site] = acq
        held.append(_HeldEntry(self.site, self))

    def _note_released(self):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            e = held[i]
            if e.proxy is self:
                e.count -= 1
                if e.count <= 0:
                    del held[i]
                return

    # -- lock protocol -----------------------------------------------------

    def acquire(self, *a, **k):
        got = self._lock.acquire(*a, **k)
        if got:
            try:
                self._note_acquired()
            except LockOrderViolation:
                # leave the lock the way a failed acquire leaves it:
                # unheld — the raiser must not wedge everyone else
                self._lock.release()
                raise
        return got

    def release(self):
        self._note_released()
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    def __getattr__(self, name):
        # Condition protocol (_is_owned/_acquire_restore/_release_save)
        # and anything else forwards to the raw lock. A Condition wait
        # releases/reacquires the RAW lock underneath — the held-set
        # deliberately keeps the lock "held" across the wait, which is
        # consistent at entry and exit of the wait.
        return getattr(self._lock, name)


def _factory(kind: str):
    real = _real[kind]

    def make(*args, **kwargs):
        raw = real(*args, **kwargs)
        site = _caller_site()
        if site is None:
            return raw           # not quest_tpu code: leave untouched
        return _TrackedLock(raw, f"{site}")

    make.__name__ = f"lockcheck_{kind}"
    return make


def install() -> None:
    """Wrap the ``threading`` lock factories (idempotent). Only locks
    created from quest_tpu modules AFTER this call are tracked."""
    if _STATE["installed"]:
        return
    _real["Lock"] = threading.Lock
    _real["RLock"] = threading.RLock
    _STATE["installed"] = True
    threading.Lock = _factory("Lock")
    threading.RLock = _factory("RLock")
    # threading.Condition(None) builds its RLock via threading.RLock —
    # already routed through the patched factory; no separate wrap.


def uninstall() -> None:
    """Restore the real factories (tracked locks already handed out
    keep tracking — they are still valid locks)."""
    if not _STATE["installed"]:
        return
    threading.Lock = _real.pop("Lock")
    threading.RLock = _real.pop("RLock")
    _STATE["installed"] = False


def installed() -> bool:
    return bool(_STATE["installed"])


@_contextlib.contextmanager
def suspended():
    """Temporarily restore the raw ``threading`` factories: locks
    CREATED inside the block are untracked. For perf-measurement
    scopes (bench.py's tracing-overhead rows) whose contract is the
    production runtime's cost — the validator is a test-tier
    instrument, and a benchmark must not measure it. Locks created
    before the block keep tracking; no-op when not installed."""
    was = bool(_STATE["installed"])
    if was:
        uninstall()
    try:
        yield
    finally:
        if was:
            install()


def enabled_by_env() -> bool:
    """The conftest knob: ``QUEST_TPU_LOCKCHECK=1`` (default OFF
    outside the test tiers)."""
    return os.environ.get("QUEST_TPU_LOCKCHECK", "0") \
        not in ("0", "", "off")


def tracked_lock(site: str, rlock: bool = False) -> _TrackedLock:
    """A tracked lock with an EXPLICIT site label — the test hook
    (tests are outside quest_tpu, so the creation-site filter would
    skip their locks)."""
    real = _real.get("RLock" if rlock else "Lock")
    if real is None:
        real = threading.RLock if rlock else threading.Lock
    return _TrackedLock(real(), site)


# -- inspection -------------------------------------------------------------

def graph() -> dict:
    """A copy of the acquisition-order graph:
    ``{site: {site: first_acquire_site}}``."""
    with _state_lock:
        return {a: dict(b) for a, b in _edges.items()}


def find_cycle():
    """A cycle in the current graph (``[site, ..., site]``), or None.
    The edge-insertion check should make this impossible — this is the
    session-end double-entry bookkeeping."""
    with _state_lock:
        edges = {a: list(b) for a, b in _edges.items()}
    color: dict = {}
    stack: list = []

    def dfs(n):
        color[n] = 1
        stack.append(n)
        for m in edges.get(n, ()):
            if color.get(m, 0) == 1:
                return stack[stack.index(m):] + [m]
            if color.get(m, 0) == 0:
                hit = dfs(m)
                if hit:
                    return hit
        stack.pop()
        color[n] = 2
        return None

    for n in sorted(edges):
        if color.get(n, 0) == 0:
            hit = dfs(n)
            if hit:
                return hit
    return None


def violations() -> list:
    """Every :class:`LockOrderViolation` raised so far — including ones
    swallowed by broad exception handlers downstream (the conftest
    asserts this is empty at session end)."""
    with _state_lock:
        return list(_violations)


def clear(site_prefix: str = "") -> None:
    """Drop recorded violations and graph nodes whose site starts with
    ``site_prefix`` (everything when empty) — the cleanup hook for
    tests that PROVE a deliberate inversion raises."""
    with _state_lock:
        if not site_prefix:
            _violations.clear()
            _edges.clear()
            return
        _violations[:] = [
            v for v in _violations
            if not (v.site_a.startswith(site_prefix)
                    or v.site_b.startswith(site_prefix))]
        for a in list(_edges):
            if a.startswith(site_prefix):
                del _edges[a]
                continue
            for b in list(_edges[a]):
                if b.startswith(site_prefix):
                    del _edges[a][b]


def assert_clean() -> None:
    """Raise if any violation was recorded or the graph holds a cycle
    (the tier-1 session-end gate)."""
    vs = violations()
    if vs:
        raise AssertionError(
            f"{len(vs)} LockOrderViolation(s) were raised during the "
            f"run (possibly swallowed downstream): "
            + "; ".join(str(v) for v in vs[:3]))
    cyc = find_cycle()
    if cyc is not None:
        raise AssertionError(
            f"lock acquisition graph holds a cycle: "
            f"{' -> '.join(cyc)}")
