"""Reader/runner for the reference's SHIPPED golden corpus.

The reference ships ~87 ``.test`` fixture files under
``/root/reference/tests/{essential,unit,algor}`` whose format is defined by
its Python harness (``utilities/QuESTTest/QuESTCore.py:380-496``):

    # <functionName>
    <nTests>
    <quregType>[-<checks>] <numQubits> <arg> <arg> ...
    ... expected lines per check letter ...

- ``quregType``: z=zero p=plus d=debug c=custom b=bitstring; lowercase =
  state-vector, uppercase = density matrix (``QuESTCore.py:382-403``).
- ``checks``: P total probability (1 line), M per-qubit outcome
  probabilities (n lines of ``P(q=0) P(q=1)``), S full state (2^n or 4^n
  complex lines).  Omitted for value-returning functions, which instead
  read ONE expected-value line (``QuESTCore.py:473-489``).
- argument tokenisation deletes the characters ``[{()}]_|><`` and splits
  on whitespace (``QuESTCore.py:214-217``), so arrays/matrices arrive as
  single comma-joined tokens.

This module replays those files through quest_tpu's public API — the
last oracle seam VERDICT r4 flagged: the corpus the reference itself
ships, consumed unmodified.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Callable, Optional

import numpy as np

import quest_tpu as qt

__all__ = ["run_shipped_file", "shipped_standard_files", "SHIPPED_ROOT",
           "ShippedFailure"]

SHIPPED_ROOT = "/root/reference/tests"

# exact analogue of QuESTCore.py:214-217 (maketrans with a deletion set)
_DELETE = str.maketrans("", "", "[{()}]_|><")


class ShippedFailure(AssertionError):
    pass


class _TestFile:
    """Line reader with the reference's comment/blank-skipping semantics
    (``QuESTCore.py:190-207``)."""

    def __init__(self, path: str):
        self.path = path
        with open(path) as f:
            self._lines = f.readlines()
        self.n_line = 0

    def readline(self) -> str:
        while self.n_line < len(self._lines):
            line = self._lines[self.n_line]
            self.n_line += 1
            cut = line.find("#")
            if cut > -1:
                line = line[:cut]
            line = line.strip()
            if line:
                return line
        raise ShippedFailure(f"{self.path}: unexpected end of file")

    def parse_args(self, line: str) -> list[str]:
        return line.translate(_DELETE).split()

    def title(self) -> str:
        # first comment line names the function (QuESTCore.py:246-252)
        for line in self._lines:
            t = line.lstrip("# ").strip()
            if t:
                return t
        raise ShippedFailure(f"{self.path}: empty file")


def _floats(token: str) -> list[float]:
    return [float(x) for x in token.strip(",").split(",") if x]


def _complex(token: str) -> complex:
    re, im = _floats(token)
    return complex(re, im)


def _matrix2(token: str) -> np.ndarray:
    v = _floats(token)
    if len(v) != 8:
        raise ShippedFailure(f"matrix token has {len(v)} floats, want 8")
    amps = [complex(v[i], v[i + 1]) for i in range(0, 8, 2)]
    return np.array([[amps[0], amps[1]], [amps[2], amps[3]]],
                    dtype=np.complex128)


def _init_qureg(env, n_bits: int, qubit_type: str, den_mat: bool,
                custom_token: Optional[str]):
    """``argQureg`` analogue (``QuESTCore.py:762-860``)."""
    q = (qt.createDensityQureg(n_bits, env) if den_mat
         else qt.createQureg(n_bits, env))
    kind = qubit_type.upper()
    if kind == "Z":
        qt.initZeroState(q)
    elif kind == "P":
        qt.initPlusState(q)
    elif kind == "D":
        qt.initDebugState(q)
    elif kind == "B":
        qt.initClassicalState(q, int(custom_token, 2))
    elif kind == "C":
        v = _floats(custom_token)
        reals, imags = v[0::2], v[1::2]
        if den_mat:
            qt.setDensityAmps(q, reals, imags)
        else:
            qt.setAmps(q, 0, reals, imags, len(reals))
    else:
        raise ShippedFailure(f"unknown qureg type {qubit_type!r}")
    return q


# ---------------------------------------------------------------------------
# per-function adapters: (tokens) -> API call.  ``ret`` is None for void
# functions (P/M/S checked) or the kind of the single expected value line.
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Adapter:
    call: Callable            # (qureg, tokens) -> result
    ret: Optional[str] = None  # None | "real" | "complex" | "int"


def _a(fn, *kinds, ret=None):
    """Build an adapter whose positional args are parsed per ``kinds``:
    i=int r=real c=complex m=ComplexMatrix2 v=real-list l=int-list
    n=consume a count token (validated against the preceding list)."""
    def call(q, tokens):
        args = []
        it = iter(tokens)
        for k in kinds:
            tok = next(it)
            if k == "i":
                args.append(int(tok))
            elif k == "r":
                args.append(float(tok))
            elif k == "c":
                args.append(_complex(tok))
            elif k == "m":
                args.append(_matrix2(tok))
            elif k == "v":
                args.append(_floats(tok))
            elif k == "l":
                args.append([int(x) for x in tok.strip(",").split(",")])
            elif k == "n":
                if int(tok) != len(args[-1]):
                    raise ShippedFailure(
                        f"count {tok} != list len {len(args[-1])}")
            else:
                raise ValueError(k)
        return fn(q, *args)
    return _Adapter(call, ret)


def _setamps(q, tokens):
    # setAmps.test: startInd, one real, one imag, numAmps (essential tier)
    start, re, im, n = int(tokens[0]), _floats(tokens[1]), \
        _floats(tokens[2]), int(tokens[3])
    qt.setAmps(q, start, re, im, n)


_ADAPTERS: dict[str, _Adapter] = {
    # --- 1q gates -----------------------------------------------------
    "hadamard": _a(qt.hadamard, "i"),
    "pauliX": _a(qt.pauliX, "i"),
    "pauliY": _a(qt.pauliY, "i"),
    "pauliZ": _a(qt.pauliZ, "i"),
    "sGate": _a(qt.sGate, "i"),
    "tGate": _a(qt.tGate, "i"),
    "phaseShift": _a(qt.phaseShift, "i", "r"),
    "rotateX": _a(qt.rotateX, "i", "r"),
    "rotateY": _a(qt.rotateY, "i", "r"),
    "rotateZ": _a(qt.rotateZ, "i", "r"),
    "rotateAroundAxis": _a(qt.rotateAroundAxis, "i", "r", "v"),
    "compactUnitary": _a(qt.compactUnitary, "i", "c", "c"),
    "unitary": _a(qt.unitary, "i", "m"),
    # --- controlled ---------------------------------------------------
    "controlledNot": _a(qt.controlledNot, "i", "i"),
    "controlledPauliY": _a(qt.controlledPauliY, "i", "i"),
    "controlledPhaseFlip": _a(qt.controlledPhaseFlip, "i", "i"),
    "controlledPhaseShift": _a(qt.controlledPhaseShift, "i", "i", "r"),
    "controlledRotateX": _a(qt.controlledRotateX, "i", "i", "r"),
    "controlledRotateY": _a(qt.controlledRotateY, "i", "i", "r"),
    "controlledRotateZ": _a(qt.controlledRotateZ, "i", "i", "r"),
    "controlledRotateAroundAxis": _a(
        qt.controlledRotateAroundAxis, "i", "i", "r", "v"),
    "controlledCompactUnitary": _a(
        qt.controlledCompactUnitary, "i", "i", "c", "c"),
    "controlledUnitary": _a(qt.controlledUnitary, "i", "i", "m"),
    "multiControlledPhaseFlip": _a(qt.multiControlledPhaseFlip, "l", "n"),
    "multiControlledPhaseShift": _a(
        qt.multiControlledPhaseShift, "l", "n", "r"),
    "multiControlledUnitary": _a(qt.multiControlledUnitary, "l", "n",
                                 "i", "m"),
    # --- collapse / noise --------------------------------------------
    "collapseToOutcome": _a(qt.collapseToOutcome, "i", "i"),
    "mixDamping": _a(qt.mixDamping, "i", "r"),
    "mixDephasing": _a(qt.mixDephasing, "i", "r"),
    "mixDepolarising": _a(qt.mixDepolarising, "i", "r"),
    "mixTwoQubitDephasing": _a(qt.mixTwoQubitDephasing, "i", "i", "r"),
    "mixTwoQubitDepolarising": _a(qt.mixTwoQubitDepolarising,
                                  "i", "i", "r"),
    # --- value-returning ---------------------------------------------
    "calcTotalProb": _a(qt.calcTotalProb, ret="real"),
    "calcPurity": _a(qt.calcPurity, ret="real"),
    "calcProbOfOutcome": _a(qt.calcProbOfOutcome, "i", "i", ret="real"),
    "getAmp": _a(qt.getAmp, "i", ret="complex"),
    "getDensityAmp": _a(qt.getDensityAmp, "i", "i", ret="complex"),
    "getRealAmp": _a(qt.getRealAmp, "i", ret="real"),
    "getImagAmp": _a(qt.getImagAmp, "i", ret="real"),
    "getProbAmp": _a(qt.getProbAmp, "i", ret="real"),
    "getNumAmps": _a(qt.getNumAmps, ret="int"),
    "getNumQubits": _a(qt.getNumQubits, ret="int"),
    # --- init (argQureg already pre-initialises; the call re-applies,
    #     matching the harness which calls the function on top) --------
    "initZeroState": _a(qt.initZeroState),
    "initPlusState": _a(qt.initPlusState),
    "initStateDebug": _a(qt.initDebugState),
    "initClassicalState": _a(qt.initClassicalState, "i"),
    "setAmps": _Adapter(_setamps),
}


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

def _check_P(tf: _TestFile, q, tol: float, errs: list[str]) -> None:
    expect = float(tf.readline())
    got = qt.calcTotalProb(q)
    if abs(got - expect) > tol:
        errs.append(f"P: got {got!r}, want {expect!r}")


def _check_M(tf: _TestFile, q, n_bits: int, tol: float,
             errs: list[str]) -> None:
    for qubit in range(n_bits):
        p0, p1 = (float(x) for x in tf.readline().split())
        g0 = qt.calcProbOfOutcome(q, qubit, 0)
        g1 = qt.calcProbOfOutcome(q, qubit, 1)
        if abs(g0 - p0) > tol or abs(g1 - p1) > tol:
            errs.append(f"M q{qubit}: got ({g0!r},{g1!r}), "
                        f"want ({p0!r},{p1!r})")


def _check_S(tf: _TestFile, q, n_bits: int, den_mat: bool, tol: float,
             errs: list[str]) -> None:
    dim = 1 << n_bits
    n_states = dim * dim if den_mat else dim
    expect = [_complex(tf.readline().translate(_DELETE))
              for _ in range(n_states)]
    if den_mat:
        # flat order = row + col*dim, the reference's column-major
        # density flattening (QuEST.c:8-10 via read_state_vec)
        for col in range(dim):
            for row in range(dim):
                g = qt.getDensityAmp(q, row, col)
                e = expect[row + col * dim]
                if abs(g - e) > tol:
                    errs.append(f"S [{row},{col}]: got {g!r}, want {e!r}")
                    if len(errs) > 8:
                        return
    else:
        for i in range(dim):
            g = qt.getAmp(q, i)
            if abs(g - expect[i]) > tol:
                errs.append(f"S [{i}]: got {g!r}, want {expect[i]!r}")
                if len(errs) > 8:
                    return


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

def run_shipped_file(path: str, tol: float = 1e-10) -> int:
    """Replay one shipped standard-format ``.test`` file; raises
    ``ShippedFailure`` on any mismatch, returns the number of test
    vectors exercised."""
    tf = _TestFile(path)
    title = tf.title()
    adapter = _ADAPTERS.get(title)
    if adapter is None:
        raise ShippedFailure(f"{path}: no adapter for {title!r}")
    n_tests = int(tf.readline())
    env = qt.createQuESTEnv()
    ran = 0
    try:
        for case in range(n_tests):
            line = tf.readline()
            tokens = tf.parse_args(line)
            test_string, n_bits_s, *args = tokens
            qubit_type, *test_type = test_string.split("-")
            n_bits = int(n_bits_s)
            if n_bits == 0:
                continue
            den_mat = qubit_type.isupper()
            custom = None
            if qubit_type in "CBcb":
                custom = args.pop(0)
            q = _init_qureg(env, n_bits, qubit_type, den_mat, custom)
            errs: list[str] = []
            if adapter.ret is None:
                adapter.call(q, args)
                checks = test_type[0] if test_type else "S"
                for c in checks:
                    if c in "Pp":
                        _check_P(tf, q, tol, errs)
                    elif c in "Mm":
                        _check_M(tf, q, n_bits, tol, errs)
                    elif c in "Ss":
                        _check_S(tf, q, n_bits, den_mat, tol, errs)
                    else:
                        raise ShippedFailure(
                            f"{path}: unknown check {c!r}")
            else:
                result = adapter.call(q, args)
                if adapter.ret == "complex":
                    expect = _complex(tf.readline().translate(_DELETE))
                    if abs(result - expect) > tol:
                        errs.append(f"ret: got {result!r}, want {expect!r}")
                elif adapter.ret == "real":
                    expect = float(tf.readline())
                    if abs(result - expect) > tol:
                        errs.append(f"ret: got {result!r}, want {expect!r}")
                else:
                    expect = int(tf.readline())
                    if int(result) != expect:
                        errs.append(f"ret: got {result!r}, want {expect!r}")
            if errs:
                raise ShippedFailure(
                    f"{path} case {case + 1}/{n_tests} "
                    f"({line}): " + "; ".join(errs))
            ran += 1
    finally:
        qt.destroyQuESTEnv(env)
    return ran


def shipped_standard_files(root: str = SHIPPED_ROOT) -> list[str]:
    """All shipped ``.test`` files in the standard (non-Python-driver)
    format, discovered the same way the reference harness does."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if not name.endswith(".test"):
                continue
            path = os.path.join(dirpath, name)
            if _TestFile(path).title() != "Python":
                out.append(path)
    return sorted(out)
