"""Golden-file generator and runner.

File format (one file per API function, text, reference-style semantics —
quregType letter + check letters + expected values; written from scratch):

    # golden <function>
    <numTests>
    <quregType>-<checks> <numQubits> <arg> <arg> ...
    P <totalProb>
    M <P(q0=0)> <P(q1=0)> ...
    S
    <re> <im>
    ...

- quregType: z=zero p=plus d=debug b=bitstring(0b101) r=random;
  lowercase = state-vector, uppercase = density matrix (the reference's
  case convention, `QuESTCore.py:382-403`).
- checks: P total probability, M per-qubit zero-outcome probabilities,
  S full state amplitudes, R scalar return value(s) of the function.
- args: floats/ints space-separated; matrix/vector args are expanded inline
  (re im pairs) and reconstructed by the runner from the function's spec.

Functions and their argument schemas live in GATE_SPECS; argument sweeps are
deterministic (fixed angles, seeded unitaries), so generated files are
reproducible byte-for-byte.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Optional, Sequence

import numpy as np

import quest_tpu as qt

__all__ = ["GATE_SPECS", "generate_files", "run_file", "GoldenFailure"]


# ---------------------------------------------------------------------------
# argument schemas
# ---------------------------------------------------------------------------

def _unitary(k: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(1000 + seed)
    m = rng.normal(size=(1 << k, 1 << k)) + 1j * rng.normal(size=(1 << k, 1 << k))
    u, _ = np.linalg.qr(m)
    return u


def _kraus_pair(seed: int) -> list[np.ndarray]:
    p = 0.1 + 0.05 * (seed % 3)
    flip = _unitary(1, seed)
    return [np.sqrt(1 - p) * np.eye(2, dtype=np.complex128),
            np.sqrt(p) * flip.astype(np.complex128)]


@dataclasses.dataclass
class Spec:
    """How to sweep and encode one API function's arguments.

    ``cases(n)`` yields argument tuples (python values, matrices included);
    ``encode``/``decode`` map them to/from flat text tokens; ``density_only``
    restricts to density registers (noise channels); ``returns`` marks
    value-returning functions (checked with R); ``aux`` names a deterministic
    auxiliary-register builder (appended as the trailing argument and NOT
    encoded — rebuilt identically at replay): one of ``"pure_plus"``,
    ``"pure_debug"``, ``"same_kind_debug"``, ``"density_plus"``."""
    cases: Callable[[int], list[tuple]]
    encode: Callable[[tuple], list[str]]
    decode: Callable[[list[str]], tuple]
    density_only: bool = False
    statevec_only: bool = False
    returns: bool = False
    aux: Optional[str] = None
    # deterministically re-seed the env's RNG before each call — makes
    # sampling functions (measure/measureWithStats) golden-testable, the
    # reference's broadcast-seeded-mt19937 strategy (`QuEST_common.c:181`).
    # NOTE: reseed-spec goldens are CONSISTENCY tests of the framework's
    # own threefry key stream, not cross-implementation oracles — any
    # key-splitting change legitimately invalidates them (regenerate),
    # and they are deliberately absent from tests/golden_ref/
    # (docs/accuracy.md)
    reseed: bool = False


def _build_aux(kind: str, qtype: str, n: int, env):
    """Deterministic auxiliary register per Spec.aux."""
    if kind == "pure_plus":
        p = qt.createQureg(n, env)
        qt.initPlusState(p)
        return p
    if kind == "pure_debug":
        p = qt.createQureg(n, env)
        qt.initDebugState(p)
        return p
    if kind == "same_kind_debug":
        p = qt.createDensityQureg(n, env) if qtype.isupper() \
            else qt.createQureg(n, env)
        qt.initDebugState(p)
        return p
    if kind == "density_plus":
        p = qt.createDensityQureg(n, env)
        qt.initPlusState(p)
        return p
    raise ValueError(kind)


def _enc_simple(args: tuple) -> list[str]:
    out = []
    for a in args:
        if isinstance(a, (list, tuple, np.ndarray)):
            arr = np.asarray(a)
            if np.iscomplexobj(arr):
                flat = arr.astype(np.complex128).reshape(-1)
                out.append(f"[{len(flat)}")
                for z in flat:
                    out += [repr(float(z.real)), repr(float(z.imag))]
            elif arr.dtype.kind == "f":
                flat = arr.reshape(-1)
                out.append(f"f{len(flat)}")
                out += [repr(float(v)) for v in flat]
            else:
                flat = arr.reshape(-1)
                out.append(f"i{len(flat)}")
                out += [str(int(v)) for v in flat]
        elif isinstance(a, complex):
            out += ["(", repr(a.real), repr(a.imag)]
        elif isinstance(a, float):
            out.append(repr(a))
        else:
            out.append(str(int(a)))
    return out


def _dec_simple(tokens: list[str]) -> tuple:
    args = []
    i = 0
    while i < len(tokens):
        t = tokens[i]
        if t.startswith("["):
            count = int(t[1:])
            vals = np.array([complex(float(tokens[i + 1 + 2 * j]),
                                     float(tokens[i + 2 + 2 * j]))
                             for j in range(count)])
            dim = int(round(np.sqrt(count)))
            if dim * dim == count and dim >= 2:
                vals = vals.reshape(dim, dim)
            args.append(vals)
            i += 1 + 2 * count
        elif t.startswith("f") and t[1:].isdigit():
            count = int(t[1:])
            args.append(tuple(float(x) for x in tokens[i + 1:i + 1 + count]))
            i += 1 + count
        elif t.startswith("i") and t[1:].isdigit():
            count = int(t[1:])
            args.append(tuple(int(x) for x in tokens[i + 1:i + 1 + count]))
            i += 1 + count
        elif t == "(":
            args.append(complex(float(tokens[i + 1]), float(tokens[i + 2])))
            i += 3
        elif ("." in t or "e" in t or "inf" in t) and not t.lstrip("-").isdigit():
            args.append(float(t))
            i += 1
        else:
            args.append(int(t))
            i += 1
    return tuple(args)


def _spec(cases, **kw) -> Spec:
    return Spec(cases=cases, encode=_enc_simple, decode=_dec_simple, **kw)


_ANGLE = 0.37
_AXIS = (1.0, -2.0, 0.5)


def _targets(n):
    return [(t,) for t in range(n)]


def _target_angle(n):
    return [(t, _ANGLE + 0.1 * t) for t in range(n)]


def _ctrl_target(n):
    return [(c, t) for c in range(n) for t in range(n) if c != t]


def _ctrl_target_angle(n):
    return [(c, t, _ANGLE + 0.05 * (c + n * t))
            for c in range(n) for t in range(n) if c != t]


def _pairs(n):
    return [(a, b) for a in range(n) for b in range(n) if a != b]


def _amp_indices(n):
    return [(i,) for i in range(1 << n)]


GATE_SPECS: dict[str, Spec] = {
    # 1-qubit gates
    "hadamard": _spec(_targets),
    "pauliX": _spec(_targets),
    "pauliY": _spec(_targets),
    "pauliZ": _spec(_targets),
    "sGate": _spec(_targets),
    "tGate": _spec(_targets),
    "phaseShift": _spec(_target_angle),
    "rotateX": _spec(_target_angle),
    "rotateY": _spec(_target_angle),
    "rotateZ": _spec(_target_angle),
    "rotateAroundAxis": _spec(
        lambda n: [(t, _ANGLE + 0.1 * t, _AXIS) for t in range(n)]),
    "compactUnitary": _spec(
        lambda n: [(t, complex(0.6, 0.0), complex(0.0, 0.8)) for t in range(n)]),
    "unitary": _spec(
        lambda n: [(t, _unitary(1, t)) for t in range(n)]),
    # controlled
    "controlledNot": _spec(_ctrl_target),
    "controlledPauliY": _spec(_ctrl_target),
    "controlledPhaseShift": _spec(_ctrl_target_angle),
    "controlledPhaseFlip": _spec(_pairs),
    "controlledRotateX": _spec(_ctrl_target_angle),
    "controlledRotateY": _spec(_ctrl_target_angle),
    "controlledRotateZ": _spec(_ctrl_target_angle),
    "controlledRotateAroundAxis": _spec(
        lambda n: [(c, t, _ANGLE, _AXIS)
                   for c in range(n) for t in range(n) if c != t]),
    "controlledCompactUnitary": _spec(
        lambda n: [(c, t, complex(0.6, 0.0), complex(0.0, 0.8))
                   for c in range(n) for t in range(n) if c != t]),
    "controlledUnitary": _spec(
        lambda n: [(c, t, _unitary(1, c + n * t))
                   for c in range(n) for t in range(n) if c != t]),
    "multiControlledUnitary": _spec(
        lambda n: [(tuple(c for c in range(n) if c != t), t, _unitary(1, t))
                   for t in range(n)]),
    "multiStateControlledUnitary": _spec(
        lambda n: [(tuple(c for c in range(n) if c != t),
                    tuple((c + t) % 2 for c in range(n) if c != t),
                    t, _unitary(1, t))
                   for t in range(n)]),
    "multiControlledPhaseShift": _spec(
        lambda n: [(tuple(range(n)), _ANGLE)]),
    "multiControlledPhaseFlip": _spec(
        lambda n: [(tuple(range(n)),)]),
    # swaps / multi-qubit
    "swapGate": _spec(lambda n: [(a, b) for a in range(n)
                                 for b in range(a + 1, n)]),
    "sqrtSwapGate": _spec(lambda n: [(a, b) for a in range(n)
                                     for b in range(a + 1, n)]),
    "multiRotateZ": _spec(
        lambda n: [(tuple(range(n)), _ANGLE), ((0, n - 1), 0.8)]),
    "multiRotatePauli": _spec(
        lambda n: [(tuple(range(3)), (1, 2, 3), _ANGLE)]),
    "twoQubitUnitary": _spec(
        lambda n: [(a, b, _unitary(2, a + n * b)) for a, b in _pairs(n)]),
    "controlledTwoQubitUnitary": _spec(
        lambda n: [(2, 0, 1, _unitary(2, 5))]),
    "multiQubitUnitary": _spec(
        lambda n: [((0, 1, 2), _unitary(3, 9))]),
    "multiControlledMultiQubitUnitary": _spec(
        lambda n: [((2,), (0, 1), _unitary(2, 11))]),
    # measurement-adjacent (deterministic only)
    "collapseToOutcome": _spec(
        lambda n: [(t, 0) for t in range(n)] + [(t, 1) for t in range(n)],
        returns=True),
    "calcProbOfOutcome": _spec(
        lambda n: [(t, o) for t in range(n) for o in (0, 1)], returns=True),
    # calculations
    "calcTotalProb": _spec(lambda n: [()], returns=True),
    "calcPurity": _spec(lambda n: [()], returns=True, density_only=True),
    "calcExpecPauliProd": _spec(
        lambda n: [((0, 1), (1, 3)), ((0, 1, 2), (2, 2, 1))], returns=True),
    "calcExpecPauliSum": _spec(
        lambda n: [((1, 0, 0, 3, 3, 0), (0.3, -0.7))], returns=True),
    # noise channels (density only)
    "mixDephasing": _spec(
        lambda n: [(t, 0.2) for t in range(n)], density_only=True),
    "mixDepolarising": _spec(
        lambda n: [(t, 0.2) for t in range(n)], density_only=True),
    "mixDamping": _spec(
        lambda n: [(t, 0.3) for t in range(n)], density_only=True),
    "mixTwoQubitDephasing": _spec(
        lambda n: [(a, b, 0.25) for a, b in _pairs(n)], density_only=True),
    "mixTwoQubitDepolarising": _spec(
        lambda n: [(a, b, 0.4) for a, b in _pairs(n)], density_only=True),
    "mixPauli": _spec(
        lambda n: [(t, 0.1, 0.05, 0.15) for t in range(n)],
        density_only=True),
    "mixKrausMap": _spec(
        lambda n: [(t, _kraus_pair(t)) for t in range(n)],
        density_only=True),
}

# Kraus-map functions take a *list* of matrices after some plain int/tuple
# args: encode the leading args normally, then a "k<count>" marker and the
# matrices; decode re-splits.
def _kraus_codec(n_lead: int):
    def enc(args):
        lead, ops = args[:n_lead], args[n_lead]
        out = _enc_simple(lead) + [f"k{len(ops)}"]
        for m in ops:
            out += _enc_simple((m,))
        return out

    def dec(tokens):
        ki = next(i for i, t in enumerate(tokens)
                  if t.startswith("k") and t[1:].isdigit())
        lead = _dec_simple(tokens[:ki])
        count = int(tokens[ki][1:])
        rest = tokens[ki + 1:]
        ops = []
        for _ in range(count):
            n_ent = int(rest[0][1:])
            (m,) = _dec_simple(rest[:1 + 2 * n_ent])
            ops.append(m)
            rest = rest[1 + 2 * n_ent:]
        return lead + (ops,)

    return enc, dec


_enc_k1, _dec_k1 = _kraus_codec(1)
GATE_SPECS["mixKrausMap"] = dataclasses.replace(
    GATE_SPECS["mixKrausMap"], encode=_enc_k1, decode=_dec_k1)


def _kraus_4(seed: int) -> list[np.ndarray]:
    xx = np.kron(mats_pauli_x(), mats_pauli_x())
    p = 0.1 + 0.02 * (seed % 3)
    return [np.sqrt(1 - p) * np.eye(4, dtype=np.complex128),
            np.sqrt(p) * xx.astype(np.complex128)]


def _kraus_8() -> list[np.ndarray]:
    x = mats_pauli_x()
    xxx = np.kron(x, np.kron(x, x))
    return [np.sqrt(0.8) * np.eye(8, dtype=np.complex128),
            np.sqrt(0.2) * xxx.astype(np.complex128)]


def mats_pauli_x() -> np.ndarray:
    return np.array([[0.0, 1.0], [1.0, 0.0]], dtype=np.complex128)


_enc_k2, _dec_k2 = _kraus_codec(2)
_enc_kN, _dec_kN = _kraus_codec(1)

GATE_SPECS.update({
    "mixTwoQubitKrausMap": Spec(
        cases=lambda n: [(a, b, _kraus_4(a + n * b)) for a, b in _pairs(n)],
        encode=_enc_k2, decode=_dec_k2, density_only=True),
    "mixMultiQubitKrausMap": Spec(
        cases=lambda n: [((0, 1, 2), _kraus_8())],
        encode=_enc_kN, decode=_dec_kN, density_only=True),
    # two-register functions: the trailing register is rebuilt from Spec.aux
    "calcFidelity": _spec(lambda n: [()], returns=True, aux="pure_plus"),
    "calcInnerProduct": _spec(lambda n: [()], returns=True,
                              statevec_only=True, aux="pure_debug"),
    "calcDensityInnerProduct": _spec(lambda n: [()], returns=True,
                                     density_only=True, aux="density_plus"),
    "calcHilbertSchmidtDistance": _spec(lambda n: [()], returns=True,
                                        density_only=True, aux="density_plus"),
    "mixDensityMatrix": _spec(lambda n: [(0.3,)], density_only=True,
                              aux="density_plus"),
    "initPureState": _spec(lambda n: [()], aux="pure_plus"),
    # getter tier (reference goldens: tests/unit/state_vector/maths/getAmp*
    # and friends)
    "getAmp": _spec(_amp_indices, returns=True, statevec_only=True),
    "getRealAmp": _spec(_amp_indices, returns=True, statevec_only=True),
    "getImagAmp": _spec(_amp_indices, returns=True, statevec_only=True),
    "getProbAmp": _spec(_amp_indices, returns=True, statevec_only=True),
    "getDensityAmp": _spec(
        lambda n: [(r, c) for r in range(1 << n) for c in (0, (1 << n) - 1)],
        returns=True, density_only=True),
    "getNumAmps": _spec(lambda n: [()], returns=True, statevec_only=True),
    "getNumQubits": _spec(lambda n: [()], returns=True),
    # seeded-sampling tier (reference goldens: measure.test,
    # measureWithStats.test — deterministic via the broadcast seed)
    "measure": _spec(lambda n: [(t,) for t in range(n)],
                     returns=True, reseed=True),
    "measureWithStats": _spec(lambda n: [(t,) for t in range(n)],
                              returns=True, reseed=True),
})


# ---------------------------------------------------------------------------
# register preparation
# ---------------------------------------------------------------------------

_BITSTRING = 0b101


def _prepare(qtype: str, n: int, env) -> "qt.Qureg":
    is_density = qtype.isupper()
    t = qtype.lower()
    q = qt.createDensityQureg(n, env) if is_density else qt.createQureg(n, env)
    if t == "z":
        qt.initZeroState(q)
    elif t == "p":
        qt.initPlusState(q)
    elif t == "d":
        qt.initDebugState(q)
    elif t == "b":
        qt.initClassicalState(q, _BITSTRING & ((1 << n) - 1))
    elif t == "r":
        rng = np.random.default_rng(42 + n)
        amps = rng.normal(size=1 << n) + 1j * rng.normal(size=1 << n)
        amps /= np.linalg.norm(amps)
        if is_density:
            pure = qt.createQureg(n, env)
            qt.initStateFromAmps(pure, amps.real, amps.imag)
            qt.initPureState(q, pure)
        else:
            qt.initStateFromAmps(q, amps.real, amps.imag)
    else:
        raise ValueError(f"unknown qureg type {qtype!r}")
    return q


def _apply(fn_name: str, q, args: tuple, spec: "Spec", qtype: str,
           n: int, env):
    """Call the API function (building the aux register if the spec has
    one); returns its value (or None)."""
    if spec.aux is not None:
        args = args + (_build_aux(spec.aux, qtype, n, env),)
    if spec.reseed:
        env.seed([51966, n, ord(qtype)]
                 + [int(a) for a in args if isinstance(a, (int, np.integer))])
    return getattr(qt, fn_name)(q, *args)


def _ret_values(ret) -> np.ndarray:
    """Flatten a scalar/complex/sequence return into comparable floats."""
    arr = np.atleast_1d(np.asarray(ret))
    if np.iscomplexobj(arr):
        arr = np.stack([arr.real, arr.imag], -1).reshape(-1)
    return arr.astype(np.float64)


def _measurements(q, n: int) -> list[float]:
    return [qt.calcProbOfOutcome(q, t, 0) for t in range(n)]


# ---------------------------------------------------------------------------
# generation
# ---------------------------------------------------------------------------

def generate_files(outdir: str, env, names: Optional[Sequence[str]] = None,
                   num_qubits: int = 3, qureg_types: str = "zpdb",
                   checks: str = "PMS") -> list[str]:
    """Write one golden file per function using the current build as the
    trusted generator (run on the single-device float64 path)."""
    os.makedirs(outdir, exist_ok=True)
    written = []
    for name in (names or sorted(GATE_SPECS)):
        spec = GATE_SPECS[name]
        lines_out: list[str] = [f"# golden {name}"]
        tests = []
        for qtype in qureg_types:
            variants = [qtype.upper()] if spec.density_only else (
                [qtype] if spec.statevec_only else [qtype, qtype.upper()])
            for qt_variant in variants:
                for args in spec.cases(num_qubits):
                    tests.append((qt_variant, args))
        lines_out.append(str(len(tests)))
        for qt_variant, args in tests:
            use_checks = checks if not spec.returns else checks + "R"
            q = _prepare(qt_variant, num_qubits, env)
            try:
                ret = _apply(name, q, args, spec, qt_variant, num_qubits, env)
            except qt.QuESTError:
                # validation rejections (e.g. collapse to a zero-probability
                # outcome) are themselves golden: every config must reject
                lines_out.append(" ".join(
                    [f"{qt_variant}-E", str(num_qubits)] + spec.encode(args)))
                continue
            head = [f"{qt_variant}-{use_checks}", str(num_qubits)]
            head += spec.encode(args)
            lines_out.append(" ".join(head))
            if "P" in use_checks:
                lines_out.append(f"P {qt.calcTotalProb(q)!r}")
            if "M" in use_checks:
                probs = _measurements(q, num_qubits)
                lines_out.append("M " + " ".join(repr(p) for p in probs))
            if "S" in use_checks:
                amps = q.to_numpy()
                lines_out.append("S")
                for a in amps:
                    lines_out.append(f"{float(a.real)!r} {float(a.imag)!r}")
            if "R" in use_checks:
                vals = _ret_values(ret)
                lines_out.append("R " + " ".join(repr(float(v)) for v in vals))
        path = os.path.join(outdir, f"{name}.test")
        with open(path, "w") as f:
            f.write("\n".join(lines_out) + "\n")
        written.append(path)
    return written


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GoldenFailure:
    function: str
    test_index: int
    check: str
    detail: str


def run_file(path: str, env, tol: float = 1e-10) -> list[GoldenFailure]:
    """Replay a golden file on ``env``; return failures (empty = pass)."""
    with open(path) as f:
        lines = [ln.rstrip("\n") for ln in f]
    assert lines[0].startswith("# golden ")
    name = lines[0].split()[-1]
    spec = GATE_SPECS[name]
    num_tests = int(lines[1])
    i = 2
    failures: list[GoldenFailure] = []
    for test_idx in range(num_tests):
        head = lines[i].split()
        i += 1
        qt_variant, use_checks = head[0].split("-")
        n = int(head[1])
        args = spec.decode(head[2:])
        q = _prepare(qt_variant, n, env)

        def fail(check, detail):
            failures.append(GoldenFailure(name, test_idx, check, detail))

        if use_checks == "E":
            try:
                _apply(name, q, args, spec, qt_variant, n, env)
                fail("E", "expected QuESTError, none raised")
            except qt.QuESTError:
                pass
            continue
        ret = _apply(name, q, args, spec, qt_variant, n, env)

        for check in use_checks:
            if check == "P":
                want = float(lines[i].split()[1]); i += 1
                got = qt.calcTotalProb(q)
                if abs(got - want) > tol:
                    fail("P", f"totalProb {got} != {want}")
            elif check == "M":
                want = [float(x) for x in lines[i].split()[1:]]; i += 1
                got = _measurements(q, n)
                if np.max(np.abs(np.array(got) - np.array(want))) > tol:
                    fail("M", f"outcome probs {got} != {want}")
            elif check == "S":
                i += 1  # "S" line
                dim = q.num_amps_total
                want = np.empty(dim, dtype=np.complex128)
                for j in range(dim):
                    re, im = lines[i + j].split()
                    want[j] = complex(float(re), float(im))
                i += dim
                got = q.to_numpy()
                err = np.max(np.abs(got - want))
                if err > tol:
                    fail("S", f"state max|Δ|={err:.3e}")
            elif check == "R":
                want = [float(x) for x in lines[i].split()[1:]]; i += 1
                got = _ret_values(ret)
                if np.max(np.abs(got - np.array(want))) > tol:
                    fail("R", f"return {got} != {want}")
    return failures
