"""Golden-file test machinery (the reference's cross-configuration oracle).

The reference tests every API function black-box through golden files: a
trusted serial build *generates* expected probabilities/outcome
distributions/states, and every other configuration (OpenMP/MPI/GPU) *replays*
them (`utilities/QuESTTest/QuESTCore.py:380-496`, generator `:738`; format
described in SURVEY.md §4). This package is that workflow rebuilt for the TPU
framework: generate on the single-device float64 CPU path (cross-checked
against the dense analytic oracle), replay under a sharded mesh or on a real
TPU chip at its precision's tolerance.
"""

from .golden import (
    GATE_SPECS, generate_files, run_file, GoldenFailure,
)
from .lockcheck import LockOrderViolation

__all__ = ["GATE_SPECS", "generate_files", "run_file", "GoldenFailure",
           "LockOrderViolation"]
