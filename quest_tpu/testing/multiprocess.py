"""Multi-process CPU test harness: hermetic child spawning for the
multi-controller (``jax.distributed``) and virtual-mesh paths.

The reference proves its distributed build by launching the SAME test
suite under ``mpiexec`` (``utilities/CMakeLists.txt:40-42``); our
analogue launches real OS processes — each a separate JAX controller —
that rendezvous through a ``jax.distributed`` coordinator and build ONE
global mesh spanning every process's CPU devices
(:func:`quest_tpu.parallel.multihost.bootstrap`). This module owns the
mechanics every such test needs and previously hand-rolled:

- **Hermetic child environments** (:func:`hermetic_child_env`): the
  parent's ``JAX_*`` / ``QUEST_TPU_*`` / ``XLA_FLAGS`` state must not
  leak into children — a parent pinned to an 8-device virtual mesh (the
  test suite's conftest) or carrying ``QUEST_TPU_FORCE_HOSTS`` from a
  planner test would silently reshape every child mesh. Children start
  from a scrubbed environment with exactly the platform/device-count
  variables the caller asked for.
- **Coordinator port picking** (:func:`free_port`): each
  ``jax.distributed`` rendezvous needs a fresh localhost port; binding
  port 0 and reading the assignment back avoids collisions between
  concurrently running tests.
- **Worker fan-out** (:func:`spawn_workers`): N coordinator-connected
  children running one worker script, each handed ``(process_id,
  num_processes, port, *extra)`` on ``argv``, results collected from
  per-process ``RESULT {json}`` lines. On ANY failure every remaining
  worker is killed — a crashed rank must not leave its peers blocked in
  the ``jax.distributed`` barrier.
- **Single-child re-exec** (:func:`run_child`): the one-process variant
  ``__graft_entry__.dryrun_multichip`` uses to get a fresh interpreter
  whose CPU device count is set *before the first JAX import*.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from typing import Optional, Sequence

__all__ = ["hermetic_child_env", "free_port", "spawn_workers",
           "run_child", "repo_root"]

# parent state that must never leak into a hermetically spawned child:
# backend selection, virtual device counts, multihost forcing, planner
# pins, dry-run child markers
_SCRUB_PREFIXES = ("JAX_", "QUEST_TPU_", "_QUEST_")
_SCRUB_EXACT = ("XLA_FLAGS", "XLA_PYTHON_CLIENT_PREALLOCATE",
                "XLA_PYTHON_CLIENT_MEM_FRACTION")


def repo_root() -> str:
    """The directory containing the ``quest_tpu`` package — children
    spawned with ``python -c`` need it on ``PYTHONPATH`` regardless of
    the parent's CWD."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def hermetic_child_env(num_devices: int,
                       extra: Optional[dict] = None) -> dict:
    """A child-process environment with the CPU platform and
    ``num_devices`` virtual devices selected BEFORE the child's first
    JAX import, and no inherited ``JAX_*`` / ``QUEST_TPU_*`` /
    ``XLA_FLAGS`` state.

    Both ``JAX_NUM_CPU_DEVICES`` (jax>=0.4.34) and the older
    ``XLA_FLAGS --xla_force_host_platform_device_count`` are set so the
    child works across the JAX versions this repo supports. ``extra``
    entries are applied last (a caller CAN reintroduce a scrubbed
    variable deliberately, e.g. ``QUEST_TPU_COMM_MODEL=default`` for
    deterministic planning in workers)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(_SCRUB_PREFIXES) and k not in _SCRUB_EXACT}
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_NUM_CPU_DEVICES"] = str(num_devices)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={num_devices}"
    root = repo_root()
    pp = env.get("PYTHONPATH", "")
    if root not in pp.split(os.pathsep):
        env["PYTHONPATH"] = root + (os.pathsep + pp if pp else "")
    if extra:
        env.update({k: str(v) for k, v in extra.items()})
    return env


def free_port() -> int:
    """A currently free localhost TCP port for the ``jax.distributed``
    coordinator rendezvous."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def spawn_workers(worker: str, num_processes: int,
                  devices_per_process: int,
                  extra_argv: Sequence = (),
                  extra_env: Optional[dict] = None,
                  timeout_s: float = 420.0) -> list[dict]:
    """Launch ``num_processes`` coordinator-connected workers and
    collect one ``RESULT {json}`` line from each.

    ``worker`` is a Python source string executed as ``python -c``; it
    receives ``argv = [process_id, num_processes, coordinator_port,
    *extra_argv]`` and is expected to call ``quest_tpu.
    initialize_multihost(f"localhost:{port}", num_processes=...,
    process_id=...)`` before creating an env, then print exactly one
    ``RESULT``-prefixed JSON line. Each child gets a hermetic
    environment (:func:`hermetic_child_env`) with
    ``devices_per_process`` CPU devices, so the global mesh spans
    ``num_processes * devices_per_process`` devices.

    On ANY failure (crash, timeout, nonzero exit, missing RESULT line)
    every remaining worker is killed before the error propagates — and
    promptly: a monitor loop kills the peers the moment ANY rank exits
    nonzero, so a crashed rank fails the spawn in seconds instead of
    leaving its peers wedged in the ``jax.distributed`` barrier for the
    full timeout. Every worker's pipes are drained CONCURRENTLY — a
    sequential drain would let a not-yet-waited rank fill its 64KB
    stderr pipe (verbose XLA warnings) and block mid-run."""
    import threading
    import time

    port = free_port()
    env = hermetic_child_env(devices_per_process, extra=extra_env)
    procs = [subprocess.Popen(
        [sys.executable, "-c", worker, str(i), str(num_processes),
         str(port), *map(str, extra_argv)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(num_processes)]
    outs: list = [None] * num_processes

    def drain(i: int) -> None:
        try:
            outs[i] = procs[i].communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            pass                          # outs[i] stays None -> failure

    threads = [threading.Thread(target=drain, args=(i,), daemon=True)
               for i in range(num_processes)]
    results = []
    try:
        for t in threads:
            t.start()
        crashed = None                    # first rank to die nonzero
        deadline = time.monotonic() + timeout_s + 30.0
        while any(t.is_alive() for t in threads) \
                and time.monotonic() < deadline:
            if crashed is None:
                for i, p in enumerate(procs):
                    rc = p.poll()
                    if rc is not None and rc != 0:
                        crashed = i       # fail fast: release the peers
                        for pp in procs:
                            if pp.poll() is None:
                                pp.kill()
                        break
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=5.0)
        if crashed is not None:
            _, err = outs[crashed] or ("", "")
            raise AssertionError(
                f"worker {crashed} rc={procs[crashed].returncode} "
                f"(peers killed):\n{(err or '')[-3000:]}")
        for i, p in enumerate(procs):
            if outs[i] is None:
                raise AssertionError(
                    f"worker {i} timed out after {timeout_s:.0f}s "
                    "(rank wedged in the distributed barrier?)")
            out, err = outs[i]
            if p.returncode != 0:
                raise AssertionError(
                    f"worker rc={p.returncode}:\n{err[-3000:]}")
            line = next((l for l in out.splitlines()
                         if l.startswith("RESULT ")), None)
            if line is None:
                raise AssertionError(
                    f"worker produced no RESULT line:\n{out[-1000:]}\n"
                    f"{err[-2000:]}")
            results.append(json.loads(line[len("RESULT "):]))
    finally:
        for pp in procs:
            if pp.poll() is None:
                pp.kill()
    return results


def run_child(code: str, num_devices: int, timeout_s: float = 900.0,
              extra_env: Optional[dict] = None) -> None:
    """Run ``code`` in ONE fresh interpreter whose CPU device count is
    set before the first JAX import (hermetic environment). Raises
    ``RuntimeError`` on timeout or nonzero exit — the single-process
    analogue of :func:`spawn_workers`, kept for
    ``__graft_entry__.dryrun_multichip``."""
    env = hermetic_child_env(num_devices, extra=extra_env)
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              timeout=timeout_s, capture_output=True,
                              text=True)
    except subprocess.TimeoutExpired as e:
        raise RuntimeError(
            f"multiprocess child (n={num_devices}) timed out after "
            f"{timeout_s:.0f}s (backend hang?)") from e
    if proc.returncode != 0:
        raise RuntimeError(
            f"multiprocess child (n={num_devices}) failed "
            f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}")
