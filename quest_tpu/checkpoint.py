"""Checkpoint / resume for register state.

The reference's story is debug-grade: per-rank CSV dumps (``reportState``,
``QuEST_common.c:215-231``) reloadable via ``initStateFromSingleFile``
(``QuEST_cpu.c:1599``). Here checkpointing is first-class: the whole register
is one (possibly mesh-sharded) ``jax.Array`` of packed float planes, saved
with orbax (per-shard parallel IO, multi-host safe) together with the
register metadata needed to restore onto any mesh shape — the state can be
saved from an 8-device run and restored onto 1 device or vice versa.

A numpy ``.npz`` fallback covers environments without orbax.
"""

from __future__ import annotations

import json
import os

import jax
import numpy as np

from .qureg import Qureg

__all__ = ["save", "load", "save_npz", "load_npz"]

_META_NAME = "quest_meta.json"


def _meta(qureg: Qureg) -> dict:
    return {
        "num_qubits_represented": qureg.num_qubits_represented,
        "is_density_matrix": qureg.is_density_matrix,
        "precision": qureg.env.precision.name,
    }


def _check_meta(meta: dict, qureg: Qureg) -> None:
    if (meta["num_qubits_represented"] != qureg.num_qubits_represented
            or meta["is_density_matrix"] != qureg.is_density_matrix):
        raise ValueError(
            f"checkpoint holds a "
            f"{meta['num_qubits_represented']}-qubit "
            f"{'density' if meta['is_density_matrix'] else 'statevector'} "
            f"register; target register is "
            f"{qureg.num_qubits_represented}-qubit "
            f"{'density' if qureg.is_density_matrix else 'statevector'}")
    saved_prec = meta.get("precision")
    if saved_prec is not None and saved_prec != qureg.env.precision.name:
        raise ValueError(
            f"checkpoint was saved in {saved_prec} precision; target "
            f"register uses {qureg.env.precision.name} — create the env "
            f"with precision={saved_prec} (or re-save) to restore")


def save(qureg: Qureg, path: str) -> None:
    """Checkpoint a register to ``path`` (a directory; orbax format)."""
    qureg.ensure_canonical()     # checkpoints store canonical bit order
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        save_npz(qureg, path + ".npz")
        return
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, {"state": qureg.state})
    ckptr.wait_until_finished()
    with open(os.path.join(path, _META_NAME), "w") as f:
        json.dump(_meta(qureg), f)


def load(qureg: Qureg, path: str) -> None:
    """Restore a checkpoint into ``qureg`` (re-sharding onto its env's mesh
    as needed)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        if os.path.exists(path + ".npz"):
            load_npz(qureg, path + ".npz")
            return
        raise FileNotFoundError(path)
    import orbax.checkpoint as ocp
    with open(os.path.join(path, _META_NAME)) as f:
        _check_meta(json.load(f), qureg)
    shape = (4 if qureg.is_quad else 2, qureg.num_amps_total)
    # the register's own sharding decision (falls back to replicated for
    # registers smaller than the mesh — mirrors Qureg.device_put)
    sharding = qureg.sharding()
    if sharding is None:
        sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    target = jax.ShapeDtypeStruct(shape, qureg.real_dtype, sharding=sharding)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path, {"state": target})
    qureg.layout = None
    qureg.state = restored["state"]


def save_npz(qureg: Qureg, filename: str) -> None:
    """Single-host fallback: gather to host and save as .npz."""
    qureg.ensure_canonical()
    np.savez(filename, state=np.asarray(qureg.state),
             meta=json.dumps(_meta(qureg)))


def load_npz(qureg: Qureg, filename: str) -> None:
    with np.load(filename, allow_pickle=False) as data:
        _check_meta(json.loads(str(data["meta"])), qureg)
        host = data["state"].astype(qureg.real_dtype)
    if qureg.is_quad:
        # restore the (4, 2^n) dd planes verbatim — recombining through a
        # complex vector would misread re_lo as the imaginary part
        if host.shape[0] != 4:
            raise ValueError(
                "checkpoint holds 2-plane state but the register is a "
                "quad (4-plane) register")
        qureg.layout = None
        sharding = qureg.sharding()
        arr = jax.numpy.asarray(host)
        qureg.state = jax.device_put(arr, sharding) \
            if sharding is not None else arr
        return
    qureg.device_put((host[0] + 1j * host[1]).astype(qureg.dtype))
