"""Checkpoint / resume for register state.

The reference's story is debug-grade: per-rank CSV dumps (``reportState``,
``QuEST_common.c:215-231``) reloadable via ``initStateFromSingleFile``
(``QuEST_cpu.c:1599``). Here checkpointing is first-class: the whole register
is one (possibly mesh-sharded) ``jax.Array`` of packed float planes, saved
with orbax (per-shard parallel IO, multi-host safe) together with the
register metadata needed to restore onto any mesh shape — the state can be
saved from an 8-device run and restored onto 1 device or vice versa.

A numpy ``.npz`` fallback covers environments without orbax.
"""

from __future__ import annotations

import json
import os
import tempfile

import jax
import numpy as np

from .qureg import Qureg

__all__ = ["save", "load", "save_npz", "load_npz", "atomic_savez",
           "atomic_write_json", "CheckpointMismatch"]

_META_NAME = "quest_meta.json"


def atomic_savez(path: str, **arrays) -> None:
    """``np.savez`` with crash-safe replace semantics: the archive is
    written to a temp file in the SAME directory, fsynced, then
    ``os.replace``d over ``path`` — a crash mid-write leaves the last
    good file intact instead of a torn half-archive that corrupts the
    next recovery. ``path`` must already carry its ``.npz`` suffix
    (``np.savez`` would silently append one to the temp name and the
    replace would miss it)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz",
                               prefix=os.path.basename(path) + ".tmp.")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    # quest: allow-broad-except(cleanup-and-reraise: the temp file must
    # be unlinked on ANY interruption, including KeyboardInterrupt --
    # the exception always propagates)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, doc: dict) -> None:
    """:func:`atomic_savez`'s crash-safe replace semantics for a JSON
    document (same-directory temp + fsync + ``os.replace``) — the
    persistence primitive for small host-side state tables (the
    netserve drain snapshot). A crash mid-write leaves the previous
    file intact; a torn half-document is never observable."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json",
                               prefix=os.path.basename(path) + ".tmp.")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    # quest: allow-broad-except(cleanup-and-reraise: the temp file must
    # be unlinked on ANY interruption, including KeyboardInterrupt --
    # the exception always propagates)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class CheckpointMismatch(ValueError):
    """The checkpoint's metadata does not match the target register —
    qubit count, register kind, precision, plane layout, or dtype. A
    subclass of ``ValueError`` (existing handlers keep working) carrying
    ``field``: which metadata check failed."""

    def __init__(self, message: str, field: str = ""):
        super().__init__(message)
        self.field = field


def _meta(qureg: Qureg) -> dict:
    return {
        "num_qubits_represented": qureg.num_qubits_represented,
        "is_density_matrix": qureg.is_density_matrix,
        "precision": qureg.env.precision.name,
        # plane layout + dtype: a QUAD (4-plane double-double) state and
        # a float32 state are both silently corruptible by a cast-only
        # restore; record enough to refuse loudly
        "num_planes": 4 if qureg.is_quad else 2,
        "real_dtype": str(np.dtype(qureg.real_dtype)),
    }


def _check_meta(meta: dict, qureg: Qureg) -> None:
    if (meta["num_qubits_represented"] != qureg.num_qubits_represented
            or meta["is_density_matrix"] != qureg.is_density_matrix):
        raise CheckpointMismatch(
            f"checkpoint holds a "
            f"{meta['num_qubits_represented']}-qubit "
            f"{'density' if meta['is_density_matrix'] else 'statevector'} "
            f"register; target register is "
            f"{qureg.num_qubits_represented}-qubit "
            f"{'density' if qureg.is_density_matrix else 'statevector'}",
            field="register")
    saved_prec = meta.get("precision")
    if saved_prec is not None and saved_prec != qureg.env.precision.name:
        raise CheckpointMismatch(
            f"checkpoint was saved in {saved_prec} precision; target "
            f"register uses {qureg.env.precision.name} — create the env "
            f"with precision={saved_prec} (or re-save) to restore",
            field="precision")
    saved_planes = meta.get("num_planes")
    want_planes = 4 if qureg.is_quad else 2
    if saved_planes is not None and int(saved_planes) != want_planes:
        raise CheckpointMismatch(
            f"checkpoint holds {saved_planes}-plane state but the target "
            f"register packs {want_planes} planes "
            f"({'QUAD double-double' if qureg.is_quad else 'real/imag'})",
            field="num_planes")
    saved_dtype = meta.get("real_dtype")
    if saved_dtype is not None and \
            np.dtype(saved_dtype) != np.dtype(qureg.real_dtype):
        raise CheckpointMismatch(
            f"checkpoint planes are {saved_dtype}; target register uses "
            f"{np.dtype(qureg.real_dtype)} — restoring through a silent "
            f"cast would corrupt precision", field="real_dtype")


def save(qureg: Qureg, path: str) -> None:
    """Checkpoint a register to ``path`` (a directory; orbax format)."""
    qureg.ensure_canonical()     # checkpoints store canonical bit order
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        save_npz(qureg, path + ".npz")
        return
    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, {"state": qureg.state})
    ckptr.wait_until_finished()
    with open(os.path.join(path, _META_NAME), "w") as f:
        json.dump(_meta(qureg), f)


def load(qureg: Qureg, path: str) -> None:
    """Restore a checkpoint into ``qureg`` (re-sharding onto its env's mesh
    as needed)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        if os.path.exists(path + ".npz"):
            load_npz(qureg, path + ".npz")
            return
        raise FileNotFoundError(path)
    import orbax.checkpoint as ocp
    with open(os.path.join(path, _META_NAME)) as f:
        _check_meta(json.load(f), qureg)
    shape = (4 if qureg.is_quad else 2, qureg.num_amps_total)
    # the register's own sharding decision (falls back to replicated for
    # registers smaller than the mesh — mirrors Qureg.device_put)
    sharding = qureg.sharding()
    if sharding is None:
        sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    target = jax.ShapeDtypeStruct(shape, qureg.real_dtype, sharding=sharding)
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path, {"state": target})
    qureg.layout = None
    qureg.state = restored["state"]


def save_npz(qureg: Qureg, filename: str) -> None:
    """Single-host fallback: gather to host and save as .npz (atomic —
    a crash mid-write cannot corrupt the previous checkpoint)."""
    qureg.ensure_canonical()
    if not filename.endswith(".npz"):
        filename += ".npz"     # np.savez would append it past the replace
    atomic_savez(filename, state=np.asarray(qureg.state),
                 meta=json.dumps(_meta(qureg)))


def load_npz(qureg: Qureg, filename: str) -> None:
    with np.load(filename, allow_pickle=False) as data:
        _check_meta(json.loads(str(data["meta"])), qureg)
        host = data["state"].astype(qureg.real_dtype)
    if host.shape != ((4 if qureg.is_quad else 2), qureg.num_amps_total):
        raise CheckpointMismatch(
            f"checkpoint state has shape {host.shape}; target register "
            f"expects ({4 if qureg.is_quad else 2}, "
            f"{qureg.num_amps_total})", field="shape")
    if qureg.is_quad:
        # restore the (4, 2^n) dd planes verbatim — recombining through a
        # complex vector would misread re_lo as the imaginary part
        qureg.layout = None
        sharding = qureg.sharding()
        arr = jax.numpy.asarray(host)
        qureg.state = jax.device_put(arr, sharding) \
            if sharding is not None else arr
        return
    qureg.device_put((host[0] + 1j * host[1]).astype(qureg.dtype))
