"""Network front door for the serving stack (ROADMAP item 1).

An asyncio HTTP/1.1 JSON façade in front of
:class:`~quest_tpu.serve.router.ServiceRouter` /
:class:`~quest_tpu.serve.engine.SimulationService` — stdlib-only on the
server side, like the telemetry loopback exporter it shares endpoint
plumbing with:

- :mod:`quest_tpu.netserve.wire` — the versioned ``quest_tpu.wire/1``
  form: recorded circuits (builder-call journal replay), Param
  bindings, observables-as-Pauli-terms, every request kind, canonical
  JSON, and a content digest that matches
  :func:`quest_tpu.serve.warmcache.circuit_digest`;
- :mod:`quest_tpu.netserve.session` — authn tokens -> tenants through a
  pluggable :class:`AuthHook` (quota/priority ride the WFQ
  :class:`~quest_tpu.serve.sched.TenantPolicy` contract) and the
  digest-keyed program registry that pins a session's compiled
  programs to warm replicas;
- :mod:`quest_tpu.netserve.server` — the server: request/stream/
  observability endpoints, chunked-transfer streaming of optimizer
  iterates, dynamics segments, and trajectory wave progress;
- :mod:`quest_tpu.netserve.client` — the stdlib sync client with the
  same ``submit() -> Future`` shape as the in-process service.
"""

from .errors import (WireError, WireFormatError, DigestMismatch,
                     UnknownProgram, AuthError, SessionExpired,
                     RequestTimeout, RateLimited, ServerOverloaded,
                     UnknownStream, StreamUnsupported, http_status,
                     error_body, retry_after_s)
from .wire import (WIRE_SCHEMA, REQUEST_KINDS, canonical_json,
                   encode_circuit, decode_circuit, encode_request,
                   decode_request, encode_result, parse_result,
                   WireRequest)
from .session import (AuthHook, StaticTokenAuth, OpenAuth, SessionGrant,
                      Session, SessionManager, ProgramRegistry)
from .robust import (TokenBucket, DedupWindow, ResumableStream,
                     backlog_estimate)
from .server import NetServer
from .client import NetClient

__all__ = [
    "WIRE_SCHEMA", "REQUEST_KINDS", "canonical_json",
    "encode_circuit", "decode_circuit", "encode_request",
    "decode_request", "encode_result", "parse_result", "WireRequest",
    "WireError", "WireFormatError", "DigestMismatch", "UnknownProgram",
    "AuthError", "SessionExpired", "RequestTimeout", "RateLimited",
    "ServerOverloaded", "UnknownStream", "StreamUnsupported",
    "http_status", "error_body", "retry_after_s",
    "AuthHook", "StaticTokenAuth", "OpenAuth", "SessionGrant",
    "Session", "SessionManager", "ProgramRegistry",
    "TokenBucket", "DedupWindow", "ResumableStream",
    "backlog_estimate",
    "NetServer", "NetClient",
]
