"""The versioned wire form: ``quest_tpu.wire/1``.

Circuits travel as a **builder-call journal** — the high-level calls
that recorded them (``["rx", q, {"param": "t0"}]``), not pickled
closures. Decoding replays the journal through the same
:class:`~quest_tpu.circuits.Circuit` builders, so the decoded circuit
reproduces the exact op stream — parameterized closures land on the
SAME code objects — and therefore the exact
:func:`~quest_tpu.serve.warmcache.circuit_digest`. That digest is the
wire form's content address: submissions carry it, the server recomputes
it after decode, and a mismatch rejects typed
(:class:`~quest_tpu.netserve.errors.DigestMismatch`) instead of serving
a mis-assembled program. Static matrices travel as exact ``repr``
floats (canonical JSON round-trips them bit-for-bit).

Versioning rules (``docs/tpu.md`` "Network serving"):

- the envelope names its schema; an unknown schema string rejects 400;
- **unknown top-level keys reject** in v1 (strict — a typo'd knob must
  not silently serve defaults); additive evolution bumps the version;
- deadlines are RELATIVE (``timeout_s``) only: absolute client
  timestamps are rejected by name — client clocks are not trusted.

Requests: ``kind`` in :data:`REQUEST_KINDS`, a program as exactly one
of ``circuit`` (full wire form), ``circuit_ref`` (a digest the server
already holds), or ``qasm`` (OpenQASM 2.0 via
:mod:`quest_tpu.qasm_import`), plus the kind's knobs. Results mirror
the in-process future values shape-for-shape.
"""

from __future__ import annotations

import json
from typing import Optional

import numpy as np

from .errors import WireFormatError, DigestMismatch

__all__ = ["WIRE_SCHEMA", "REQUEST_KINDS", "canonical_json", "jsonable",
           "encode_circuit", "decode_circuit", "encode_request",
           "decode_request", "encode_result", "parse_result",
           "WireRequest"]

WIRE_SCHEMA = "quest_tpu.wire/1"

#: wire kind token -> the in-process submit() surface it maps onto
REQUEST_KINDS = ("sweep", "expectation", "shots", "trajectory",
                 "gradient", "evolve", "ground")

#: absolute-deadline key names rejected by NAME: a skewed client clock
#: must never extend (or shrink) a server-side deadline
_FORBIDDEN_DEADLINE_KEYS = ("deadline", "deadline_s", "deadline_epoch",
                            "expires_at", "deadline_wall")

_REQUEST_KEYS = frozenset({
    "schema", "kind", "circuit", "circuit_ref", "qasm", "params",
    "observables", "shots", "trajectories", "sampling_budget", "tier",
    "priority", "timeout_s", "evolve", "ground", "init_state",
    "optimizer", "request_id", "resumable",
})

#: client-chosen idempotency keys are opaque but bounded: the server's
#: dedup window stores them verbatim, so a pathological id must not be
#: able to balloon it
_MAX_REQUEST_ID_LEN = 128


def jsonable(obj):
    """Recursively coerce a result/iterate payload (numpy arrays and
    scalars included) into plain JSON types — the stream-event encoder.
    Unknown objects degrade to ``repr`` rather than failing the
    stream."""
    if isinstance(obj, dict):
        return {str(k): jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.bool_):
        return bool(obj)
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, (bool, int, float, str)) or obj is None:
        return obj
    return repr(obj)


def canonical_json(doc) -> str:
    """The one serialization of a wire document: sorted keys, no
    whitespace, NaN/Inf rejected (they are not JSON)."""
    try:
        return json.dumps(doc, sort_keys=True, separators=(",", ":"),
                          allow_nan=False)
    except ValueError as e:
        raise WireFormatError(f"document is not canonical-JSON-able: {e}")


# ---------------------------------------------------------------------------
# circuits
# ---------------------------------------------------------------------------


def _mat(doc) -> np.ndarray:
    # assign the planes, never `re + 1j*im`: complex multiplication
    # flips signed zeros, and the content digest hashes exact BYTES
    re_l = np.asarray(doc["re"], dtype=np.float64)
    im_l = np.asarray(doc["im"], dtype=np.float64)
    out = np.empty(re_l.shape, dtype=np.complex128)
    out.real = re_l
    out.imag = im_l
    return out


def _angle(doc):
    from ..circuits import Param
    if isinstance(doc, dict):
        return Param(str(doc["param"]))
    return float(doc)


# journal row replay table: row[0] names the builder, row[1:] its args.
# Every entry funnels through the SAME Circuit builders that recorded
# it — that is what makes the decode digest-stable.
_REPLAY = {
    "gate": lambda c, m, tg, ct, st: c.gate(_mat(m), tg, ct, st),
    "diagonal": lambda c, m, qs: c.diagonal(_mat(m), qs),
    "kraus": lambda c, ms, tg: c.kraus([_mat(m) for m in ms], tg),
    "phase": lambda c, q, a: c.phase(int(q), _angle(a)),
    "rot": lambda c, q, a, axis, ct: c._rot(
        int(q), _angle(a), tuple(float(x) for x in axis),
        tuple(int(x) for x in ct)),
    "rz": lambda c, q, a: c.rz(int(q), _angle(a)),
    "cphase": lambda c, ctl, tgt, a: c.cphase(int(ctl), int(tgt),
                                              _angle(a)),
    "crz": lambda c, ctl, tgt, a: c.crz(int(ctl), int(tgt), _angle(a)),
    "multi_rotate_z": lambda c, qs, a: c.multi_rotate_z(
        [int(q) for q in qs], _angle(a)),
    "dephase": lambda c, q, a: c.dephase(int(q), _angle(a)),
    "depolarise": lambda c, q, a: c.depolarise(int(q), _angle(a)),
    "damp": lambda c, q, a: c.damp(int(q), _angle(a)),
    "pauli_channel": lambda c, q, ax, ay, az: c.pauli_channel(
        int(q), _angle(ax), _angle(ay), _angle(az)),
}


def encode_circuit(circuit) -> dict:
    """The wire form of a recorded circuit: qubit count, declared
    parameter names (registration order — it is part of the digest),
    the builder-call journal, and the content digest. Raises
    :class:`WireFormatError` naming the first op that resists content
    addressing (user-supplied callable payloads, inverted circuits)."""
    rows = circuit._wire_rows()
    for i, (row, op) in enumerate(zip(rows, circuit.ops)):
        if row is None:
            raise WireFormatError(
                f"op {i} (kind {op.kind!r}) is not wire-serializable: "
                "callable payloads and journal-bypassing mutations "
                "(inverse, direct op edits) have no stable wire form — "
                "record the circuit through the builder API",
                detail={"op_index": i, "op_kind": op.kind})
    from ..serve.warmcache import circuit_digest
    return {"qubits": int(circuit.num_qubits),
            "params": list(circuit.param_names),
            "ops": rows,
            "digest": circuit_digest(circuit)}


def decode_circuit(doc: dict, *, verify_digest: bool = True):
    """Replay a wire circuit back into a recorded
    :class:`~quest_tpu.circuits.Circuit`; with ``verify_digest`` the
    recomputed content digest must match the document's claim."""
    from ..circuits import Circuit
    from ..serve.warmcache import circuit_digest
    if not isinstance(doc, dict) or "qubits" not in doc:
        raise WireFormatError("circuit document needs a 'qubits' field")
    c = Circuit(int(doc["qubits"]))
    # pre-register declared parameters: registration ORDER is part of
    # the digest and of the param-vector layout
    for nm in doc.get("params", []):
        c.parameter(str(nm))
    for i, row in enumerate(doc.get("ops", [])):
        try:
            fn = _REPLAY[row[0]]
        except (KeyError, IndexError, TypeError):
            raise WireFormatError(
                f"op {i}: unknown wire op "
                f"{row[0] if isinstance(row, list) and row else row!r}")
        try:
            fn(c, *row[1:])
        except WireFormatError:
            raise
        # quest: allow-broad-except(replay failures must reject typed
        # at the wire boundary, whatever the builder raised)
        except Exception as e:
            raise WireFormatError(
                f"op {i} ({row[0]!r}) failed to replay: "
                f"{type(e).__name__}: {e}")
    want = doc.get("digest")
    if verify_digest and want is not None:
        have = circuit_digest(c)
        if have != want:
            raise DigestMismatch(
                "decoded circuit's content digest does not match the "
                "submission's claim — rejecting rather than serving a "
                "mis-assembled program",
                detail={"claimed": want, "computed": have})
    return c


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------


class WireRequest:
    """One decoded wire request, normalized: the server resolves
    ``circuit``/``circuit_ref``/``qasm`` to a program and passes
    :meth:`submit_kwargs` straight to the backend's ``submit``."""

    __slots__ = ("kind", "circuit_doc", "circuit_ref", "qasm", "params",
                 "observables", "shots", "trajectories",
                 "sampling_budget", "tier", "priority", "timeout_s",
                 "evolve", "ground", "init_state", "optimizer",
                 "request_id", "resumable")

    def __init__(self, **kw):
        for name in self.__slots__:
            setattr(self, name, kw.get(name))

    def submit_kwargs(self) -> dict:
        """The backend ``submit()`` kwargs this request maps onto
        (program and deadline are supplied by the server)."""
        kw = {}
        if self.params is not None:
            kw["params"] = self.params
        if self.observables is not None:
            kw["observables"] = self.observables
        if self.kind == "shots":
            kw["shots"] = self.shots
        if self.kind in ("trajectory", "gradient") \
                and self.trajectories is not None:
            kw["trajectories"] = self.trajectories
            if self.sampling_budget is not None:
                kw["sampling_budget"] = self.sampling_budget
        if self.kind == "gradient":
            kw["gradient"] = True
        if self.kind == "evolve":
            kw["evolve"] = self.evolve
        if self.kind == "ground":
            kw["ground_state"] = self.ground
        if self.init_state is not None:
            kw["init_state"] = self.init_state
        if self.tier is not None:
            kw["tier"] = self.tier
        if self.priority is not None:
            kw["priority"] = self.priority
        return kw


def _decode_observables(doc):
    if doc is None:
        return None
    try:
        terms = [[(int(q), int(code)) for q, code in term]
                 for term in doc["terms"]]
        coeffs = [float(c) for c in doc["coeffs"]]
    except (KeyError, TypeError, ValueError) as e:
        raise WireFormatError(
            f"observables must be {{'terms': [[[qubit, pauli_code], "
            f"...], ...], 'coeffs': [...]}}: {e}")
    return (terms, coeffs)


def _encode_observables(observables):
    if observables is None:
        return None
    terms, coeffs = observables
    return {"terms": [[[int(q), int(code)] for q, code in term]
                      for term in terms],
            "coeffs": [float(c) for c in coeffs]}


def encode_request(kind: str, *, circuit=None, circuit_ref=None,
                   qasm=None, params=None, observables=None, shots=None,
                   trajectories=None, sampling_budget=None, tier=None,
                   priority=None, timeout_s=None, evolve=None,
                   ground=None, init_state=None, optimizer=None,
                   request_id=None, resumable=None) -> dict:
    """Build one canonical wire request document. ``circuit`` is a
    recorded Circuit (encoded inline), ``circuit_ref`` a digest the
    server already registered, ``qasm`` an OpenQASM 2.0 source string —
    exactly one of the three."""
    if kind not in REQUEST_KINDS:
        raise WireFormatError(
            f"unknown request kind {kind!r}; expected one of "
            f"{REQUEST_KINDS}")
    programs = [p for p in (circuit, circuit_ref, qasm) if p is not None]
    if len(programs) != 1:
        raise WireFormatError(
            "a request names its program as exactly ONE of circuit= "
            "(wire form), circuit_ref= (registered digest), or qasm= "
            "(OpenQASM 2.0 source)")
    doc = {"schema": WIRE_SCHEMA, "kind": kind}
    if circuit is not None:
        doc["circuit"] = circuit if isinstance(circuit, dict) \
            else encode_circuit(circuit)
    if circuit_ref is not None:
        doc["circuit_ref"] = str(circuit_ref)
    if qasm is not None:
        doc["qasm"] = str(qasm)
    if params is not None:
        doc["params"] = {str(k): float(v) for k, v in dict(params).items()}
    if observables is not None:
        doc["observables"] = _encode_observables(observables)
    if shots is not None:
        doc["shots"] = int(shots)
    if trajectories is not None:
        doc["trajectories"] = int(trajectories)
    if sampling_budget is not None:
        doc["sampling_budget"] = float(sampling_budget)
    if tier is not None:
        doc["tier"] = getattr(tier, "name", str(tier))
    if priority is not None:
        doc["priority"] = int(priority)
    if timeout_s is not None:
        doc["timeout_s"] = float(timeout_s)
    if evolve is not None:
        doc["evolve"] = {"t": float(evolve.t), "steps": int(evolve.steps),
                         "order": int(evolve.order)} \
            if not isinstance(evolve, dict) else dict(evolve)
    if ground is not None:
        doc["ground"] = {"steps": int(ground.steps),
                         "tau": float(ground.tau),
                         "method": str(ground.method),
                         "tol": float(ground.tol)} \
            if not isinstance(ground, dict) else dict(ground)
    if init_state is not None:
        st = np.asarray(init_state, dtype=np.float64)
        doc["init_state"] = {"planes": st.tolist()}
    if optimizer is not None:
        doc["optimizer"] = dict(optimizer)
    if request_id is not None:
        doc["request_id"] = str(request_id)
    if resumable:
        doc["resumable"] = True
    return doc


def decode_request(doc: dict) -> WireRequest:
    """Validate + normalize one wire request document (strict v1: an
    unknown schema, kind, or top-level key rejects typed)."""
    if not isinstance(doc, dict):
        raise WireFormatError("request body must be a JSON object")
    schema = doc.get("schema")
    if schema != WIRE_SCHEMA:
        raise WireFormatError(
            f"unknown wire schema {schema!r}; this server speaks "
            f"{WIRE_SCHEMA}")
    for key in _FORBIDDEN_DEADLINE_KEYS:
        if key in doc:
            raise WireFormatError(
                f"{key!r} is not part of the wire form: deadlines are "
                "RELATIVE (timeout_s, seconds from server receipt) — "
                "client clocks are not trusted")
    unknown = sorted(set(doc) - _REQUEST_KEYS)
    if unknown:
        raise WireFormatError(
            f"unknown request keys {unknown}: quest_tpu.wire/1 is "
            "strict — a typo'd knob must not silently serve defaults")
    kind = doc.get("kind")
    if kind not in REQUEST_KINDS:
        raise WireFormatError(
            f"unknown request kind {kind!r}; expected one of "
            f"{REQUEST_KINDS}")
    programs = [k for k in ("circuit", "circuit_ref", "qasm")
                if doc.get(k) is not None]
    if len(programs) != 1:
        raise WireFormatError(
            f"a request names exactly ONE program source; got "
            f"{programs or 'none'}")
    params = doc.get("params")
    if params is not None:
        if not isinstance(params, dict):
            raise WireFormatError("params must be a name->angle object")
        params = {str(k): float(v) for k, v in params.items()}
    request_id = doc.get("request_id")
    if request_id is not None:
        if not isinstance(request_id, str) or not request_id:
            raise WireFormatError(
                "request_id must be a non-empty string — it is the "
                "idempotency key the dedup window stores verbatim")
        if len(request_id) > _MAX_REQUEST_ID_LEN:
            raise WireFormatError(
                f"request_id exceeds {_MAX_REQUEST_ID_LEN} chars")
    resumable = doc.get("resumable")
    if resumable is not None and not isinstance(resumable, bool):
        raise WireFormatError("resumable must be a JSON boolean")
    timeout_s = doc.get("timeout_s")
    if timeout_s is not None:
        timeout_s = float(timeout_s)
        if not (timeout_s > 0.0 and np.isfinite(timeout_s)):
            raise WireFormatError(
                f"timeout_s must be a finite positive relative budget; "
                f"got {timeout_s!r}")
    evolve = ground = None
    if kind == "evolve":
        spec = doc.get("evolve")
        if not isinstance(spec, dict):
            raise WireFormatError(
                "evolve requests carry evolve={'t', 'steps', 'order'}")
        from ..ops.dynamics import EvolveSpec
        try:
            evolve = EvolveSpec(t=float(spec["t"]),
                                steps=int(spec["steps"]),
                                order=int(spec.get("order", 2)))
        except (KeyError, TypeError, ValueError) as e:
            raise WireFormatError(f"bad evolve spec: {e}")
    if kind == "ground":
        spec = doc.get("ground")
        if not isinstance(spec, dict):
            raise WireFormatError(
                "ground requests carry ground={'steps', 'tau', "
                "'method', 'tol'}")
        from ..ops.dynamics import GroundSpec
        try:
            ground = GroundSpec(steps=int(spec.get("steps", 16)),
                                tau=float(spec.get("tau", 0.1)),
                                method=str(spec.get("method", "power")),
                                tol=float(spec.get("tol", 1e-9)))
        except (TypeError, ValueError) as e:
            raise WireFormatError(f"bad ground spec: {e}")
    init_state = None
    st = doc.get("init_state")
    if st is not None:
        try:
            init_state = np.asarray(st["planes"], dtype=np.float64)
        except (KeyError, TypeError, ValueError) as e:
            raise WireFormatError(
                f"init_state must be {{'planes': [[...], [...]]}}: {e}")
    return WireRequest(
        kind=kind,
        circuit_doc=doc.get("circuit"),
        circuit_ref=doc.get("circuit_ref"),
        qasm=doc.get("qasm"),
        params=params,
        observables=_decode_observables(doc.get("observables")),
        shots=int(doc["shots"]) if doc.get("shots") is not None else None,
        trajectories=int(doc["trajectories"])
        if doc.get("trajectories") is not None else None,
        sampling_budget=float(doc["sampling_budget"])
        if doc.get("sampling_budget") is not None else None,
        tier=str(doc["tier"]) if doc.get("tier") is not None else None,
        priority=int(doc["priority"])
        if doc.get("priority") is not None else None,
        timeout_s=timeout_s,
        evolve=evolve, ground=ground, init_state=init_state,
        optimizer=doc.get("optimizer"),
        request_id=request_id, resumable=bool(resumable))


# ---------------------------------------------------------------------------
# results
# ---------------------------------------------------------------------------


def encode_result(kind: str, value) -> dict:
    """The JSON form of one resolved in-process future, per kind.
    Mirrors the shapes :meth:`SimulationService.submit` documents."""
    if kind == "sweep":
        planes = np.asarray(value, dtype=np.float64)
        return {"planes": planes.tolist()}
    if kind == "expectation":
        return {"value": float(value)}
    if kind == "shots":
        outcomes, total = value
        return {"outcomes": [int(x) for x in np.asarray(outcomes)],
                "total_norm": float(total)}
    if kind == "trajectory":
        mean, stderr = value
        return {"mean": float(mean), "stderr": float(stderr)}
    if kind == "gradient":
        if len(value) == 3:              # trajectory gradient
            v, grad, stderr = value
            return {"value": float(v),
                    "grad": np.asarray(grad, dtype=np.float64).tolist(),
                    "stderr": np.asarray(stderr,
                                         dtype=np.float64).tolist()}
        v, grad = value
        return {"value": float(v),
                "grad": np.asarray(grad, dtype=np.float64).tolist()}
    if kind in ("evolve", "ground"):
        # the packed per-row dynamics block, verbatim: callers decode
        # with ops.dynamics.unpack_evolve_block / unpack_ground_block
        return {"block": np.asarray(value, dtype=np.float64).tolist()}
    raise WireFormatError(f"unknown result kind {kind!r}")


def parse_result(kind: str, doc: dict):
    """Client side: the wire result back into the exact value shape the
    in-process future resolves with."""
    if kind == "sweep":
        return np.asarray(doc["planes"], dtype=np.float64)
    if kind == "expectation":
        return float(doc["value"])
    if kind == "shots":
        return (np.asarray(doc["outcomes"], dtype=np.int64),
                float(doc["total_norm"]))
    if kind == "trajectory":
        return (float(doc["mean"]), float(doc["stderr"]))
    if kind == "gradient":
        if "stderr" in doc:
            return (float(doc["value"]),
                    np.asarray(doc["grad"], dtype=np.float64),
                    np.asarray(doc["stderr"], dtype=np.float64))
        return (float(doc["value"]),
                np.asarray(doc["grad"], dtype=np.float64))
    if kind in ("evolve", "ground"):
        return np.asarray(doc["block"], dtype=np.float64)
    raise WireFormatError(f"unknown result kind {kind!r}")
