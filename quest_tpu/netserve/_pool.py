"""A fixed-size worker pool over a ``queue.SimpleQueue``.

Deliberately NOT ``concurrent.futures.ThreadPoolExecutor`` (and on the
server side, deliberately NOT asyncio's default executor, which IS
one): the executor's internal locks — shutdown lock, idle semaphore,
worker-thread start events, and the module-global shutdown lock — all
alias to single creation sites under the repo's runtime lock-order
validator (``quest_tpu/testing/lockcheck.py`` attributes a lock to the
first quest_tpu frame that created it). ``submit()`` holds the
shutdown lock while acquiring the module-global lock and the new
worker's start event, so two executors created from DIFFERENT
quest_tpu sites (e.g. the netserve event loop's and one a checkpoint
library created) read as a site-level lock-order inversion the first
time both are live in one process. This pool never holds one lock
while acquiring another — ``SimpleQueue`` is C-implemented and the
``Future`` handoff is lock-at-a-time — so its order graph is empty by
construction.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future

__all__ = ["WorkerPool"]


class WorkerPool:
    """``submit(fn, *args) -> Future`` over ``max_workers`` daemon
    threads. No work queue bound, no idle reaping — workers live for
    the pool's lifetime and exit on :meth:`shutdown`."""

    def __init__(self, max_workers: int, name: str):
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._threads = []
        for i in range(max_workers):
            t = threading.Thread(target=self._run, daemon=True,
                                 name=f"{name}-{i}")
            t.start()
            self._threads.append(t)

    def submit(self, fn, *args) -> Future:
        fut: Future = Future()
        self._q.put((fut, fn, args))
        return fut

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn, args = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as exc:  # quest: allow-broad-except(the exception belongs to the Future's waiter, not this worker)
                fut.set_exception(exc)

    def shutdown(self, wait: bool = True) -> None:
        for _ in self._threads:
            self._q.put(None)
        if wait:
            for t in self._threads:
                t.join()
