"""Typed wire errors and their HTTP mapping.

Every error crossing the wire carries three things: an HTTP status, the
exception TYPE name (clients dispatch on it the way in-process callers
``except QueueFull``), and the resilience classification
(:func:`quest_tpu.resilience.recovery.classify` — ``transient`` errors
are retryable, ``fatal`` ones are caller bugs). The mapping table is
the contract documented in ``docs/tpu.md``:

===============================  ======  ==============
exception                        status  classification
===============================  ======  ==============
``WireFormatError`` (bad form)   400     fatal
``AuthError``                    401     fatal
``SessionExpired`` (TTL evict)   401     transient
``UnknownProgram``               404     transient
``UnknownStream``                404     fatal
``RequestTimeout`` (slow loris)  408     transient
``DigestMismatch``               409     fatal
``QueueFull`` / ``QuotaExceeded``  429   transient
``RateLimited`` / ``ServerOverloaded``  429  transient
``NumericalFault`` (poison)      500     poison
``StreamUnsupported``            501     fatal
``CircuitBreakerOpen`` etc.      503     transient
``DeadlineExceeded``             504     transient
===============================  ======  ==============

The 429 family and ``RequestTimeout`` carry ``retry_after_s`` in their
``detail`` (and the server mirrors it into an HTTP ``Retry-After``
header) so a well-behaved client backs off by the server's estimate of
when capacity returns, not by a blind exponential guess.
"""

from __future__ import annotations

__all__ = ["WireError", "WireFormatError", "DigestMismatch",
           "UnknownProgram", "UnknownStream", "AuthError",
           "SessionExpired", "RequestTimeout", "RateLimited",
           "ServerOverloaded", "StreamUnsupported",
           "http_status", "error_body", "retry_after_s", "raise_typed"]


class WireError(Exception):
    """Base class for wire-protocol errors; ``status`` is the HTTP
    code the server answers with."""

    status = 400
    classification = "fatal"     # a malformed submission never retries

    def __init__(self, message: str, detail: dict = None):
        super().__init__(message)
        self.detail = dict(detail or {})


class WireFormatError(WireError):
    """The request body is not a valid ``quest_tpu.wire/1`` document
    (unknown schema/kind, malformed circuit row, absolute deadline,
    un-serializable op)."""

    status = 400


class AuthError(WireError):
    """Unknown token or session — the authn hook rejected it."""

    status = 401


class SessionExpired(AuthError):
    """A session the TTL sweep evicted for idleness. Transient by
    contract: re-opening the session (POST /v1/session) and replaying
    the request resolves it — the client's retry loop does both."""

    classification = "transient"


class RequestTimeout(WireError):
    """The peer failed to deliver a complete request within the
    server's read deadline (the slow-loris guard). The connection is
    closed after this answer; a healthy client retries promptly on a
    fresh connection."""

    status = 408
    classification = "transient"


class RateLimited(WireError):
    """The session's token bucket is empty — the per-session request
    rate exceeded the server's ``rate_limit``. ``detail`` carries
    ``retry_after_s``: when the next token lands."""

    status = 429
    classification = "transient"


class ServerOverloaded(WireError):
    """Priority-aware load shed: the backend queue depth crossed the
    server's watermark and this request's priority class is sheddable.
    ``detail`` carries ``retry_after_s``, derived from the WFQ backlog
    estimate (queue depth x per-request service time)."""

    status = 429
    classification = "transient"


class UnknownProgram(WireError):
    """A ``circuit_ref`` digest the server has no registered program
    for (evicted or never sent): re-submit the full circuit."""

    status = 404
    classification = "transient"   # the full-circuit retry resolves it


class DigestMismatch(WireError):
    """The decoded circuit's content digest does not match the digest
    the submission claimed — a corrupted or mis-assembled wire form is
    rejected, never silently served."""

    status = 409


class UnknownStream(WireError):
    """A stream-resume request named a stream id this server does not
    hold (never opened, expired past its resume TTL, or the requested
    cursor fell off the bounded replay buffer). Fatal for the RESUME
    attempt: start a fresh stream instead of retrying the resume."""

    status = 404


class StreamUnsupported(WireError):
    """The backend behind this server cannot stream the requested
    kind (e.g. a bare router with no ``evolve()``)."""

    status = 501


def http_status(exc: BaseException) -> int:
    """HTTP status for ANY exception crossing the wire boundary."""
    if isinstance(exc, WireError):
        return exc.status
    from ..serve.engine import (QueueFull, QuotaExceeded,
                                DeadlineExceeded, ServeError)
    if isinstance(exc, (QueueFull, QuotaExceeded)):
        return 429
    if isinstance(exc, DeadlineExceeded):
        return 504
    if isinstance(exc, ServeError):
        # ServiceClosed, CircuitBreakerOpen, AllReplicasUnavailable, …
        return 503
    if isinstance(exc, (ValueError, TypeError, KeyError)):
        return 400       # caller errors reject typed at admission
    return 500


def error_body(exc: BaseException) -> dict:
    """The JSON error envelope: type name + message + resilience
    classification (+ any typed detail)."""
    from ..resilience.recovery import classify
    body = {"error": {
        "type": type(exc).__name__,
        "message": str(exc),
        "classification": getattr(exc, "classification", None)
        or classify(exc),
    }}
    detail = getattr(exc, "detail", None)
    if detail:
        body["error"]["detail"] = dict(detail)
    return body


def retry_after_s(exc: BaseException):
    """The server's backoff estimate riding a typed error (the
    ``retry_after_s`` detail of the 429 family), or None."""
    detail = getattr(exc, "detail", None)
    if isinstance(detail, dict):
        ra = detail.get("retry_after_s")
        if isinstance(ra, (int, float)) and ra >= 0:
            return ra
    return None


_CLIENT_TYPES = None


def raise_typed(status: int, err: dict) -> None:
    """Client side of the mapping: re-raise the server's error envelope
    as the SAME typed exception family the in-process API raises, so
    ``except QueueFull`` works identically over the socket."""
    global _CLIENT_TYPES
    if _CLIENT_TYPES is None:
        from ..serve.engine import (QueueFull, QuotaExceeded,
                                    DeadlineExceeded, ServiceClosed,
                                    CircuitBreakerOpen)
        _CLIENT_TYPES = {
            "QueueFull": QueueFull,
            "QuotaExceeded": QuotaExceeded,
            "DeadlineExceeded": DeadlineExceeded,
            "ServiceClosed": ServiceClosed,
            "CircuitBreakerOpen": CircuitBreakerOpen,
            "WireFormatError": WireFormatError,
            "DigestMismatch": DigestMismatch,
            "UnknownProgram": UnknownProgram,
            "UnknownStream": UnknownStream,
            "AuthError": AuthError,
            "SessionExpired": SessionExpired,
            "RequestTimeout": RequestTimeout,
            "RateLimited": RateLimited,
            "ServerOverloaded": ServerOverloaded,
            "StreamUnsupported": StreamUnsupported,
            "ValueError": ValueError,
            "TypeError": TypeError,
        }
    info = dict(err.get("error", {}))
    name = str(info.get("type", "WireError"))
    msg = str(info.get("message", f"HTTP {status}"))
    exc_type = _CLIENT_TYPES.get(name)
    if exc_type is None:
        e = WireError(f"{name}: {msg} (HTTP {status})")
        e.status = status
        raise e
    if issubclass(exc_type, WireError):
        # typed detail survives the wire: the client retry loop reads
        # retry_after_s off the re-raised exception exactly as an
        # in-process caller would
        raise exc_type(msg, detail=info.get("detail"))
    raise exc_type(msg)
