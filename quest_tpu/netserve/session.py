"""Sessions: authn tokens -> tenants, and the digest-keyed program
registry that pins a session's compiled programs to warm replicas.

The authn surface is a single pluggable hook: :class:`AuthHook`
``.authenticate(token)`` returns a :class:`SessionGrant` (tenant name
plus an optional WFQ :class:`~quest_tpu.serve.sched.TenantPolicy`) or
``None`` to reject. The server installs the grant's policy on the
backend via ``set_tenant`` when the session opens, so quota/priority
admission (429 ``QuotaExceeded``/``QueueFull``) is enforced by the SAME
WFQ layer that guards in-process callers — the wire adds no second
quota system.

Programs are content-addressed: the first submission of a circuit
registers it under its :func:`~quest_tpu.serve.warmcache.circuit_digest`
and warms the backend's replicas; later submissions send only the
digest (``circuit_ref``) and skip re-serialization, re-decode, and
re-compile entirely. Hit rates are tracked per session — they are the
signal ``tools/wire_trace.py`` reports.
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional

from .errors import AuthError, UnknownProgram

__all__ = ["SessionGrant", "AuthHook", "OpenAuth", "StaticTokenAuth",
           "Session", "SessionManager", "ProgramRegistry"]

DEFAULT_TENANT = "default"


class SessionGrant:
    """What an authn hook vouches for: the tenant this token serves
    under, optionally the WFQ policy to install for it."""

    __slots__ = ("tenant", "policy", "meta")

    def __init__(self, tenant: str, policy=None, meta: dict = None):
        self.tenant = str(tenant)
        self.policy = policy
        self.meta = dict(meta or {})


class AuthHook:
    """Pluggable authn: map a bearer token to a :class:`SessionGrant`
    (or ``None`` to reject). Subclass and hand an instance to
    :class:`~quest_tpu.netserve.server.NetServer`."""

    def authenticate(self, token: Optional[str]) -> Optional[SessionGrant]:
        raise NotImplementedError


class OpenAuth(AuthHook):
    """Accept everything; every caller lands on one tenant. The default
    for loopback/dev servers, mirroring the telemetry exporter."""

    def __init__(self, tenant: str = DEFAULT_TENANT):
        self._tenant = tenant

    def authenticate(self, token):
        return SessionGrant(self._tenant)


class StaticTokenAuth(AuthHook):
    """A fixed token table: ``{token: SessionGrant | tenant_name}``.
    Unknown tokens reject (401)."""

    def __init__(self, tokens: dict):
        self._tokens = {}
        for token, grant in dict(tokens).items():
            if not isinstance(grant, SessionGrant):
                grant = SessionGrant(str(grant))
            self._tokens[str(token)] = grant

    def authenticate(self, token):
        return self._tokens.get(token)


class Session:
    """One authenticated wire session: identity plus per-session
    program-registry hit accounting."""

    __slots__ = ("id", "tenant", "grant", "hits", "misses", "requests")

    def __init__(self, sid: str, grant: SessionGrant):
        self.id = sid
        self.tenant = grant.tenant
        self.grant = grant
        self.hits = 0
        self.misses = 0
        self.requests = 0

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def snapshot(self) -> dict:
        return {"session": self.id, "tenant": self.tenant,
                "requests": self.requests, "program_hits": self.hits,
                "program_misses": self.misses,
                "program_hit_rate": round(self.hit_rate(), 4)}


class ProgramRegistry:
    """Digest-keyed store of decoded circuits. ``lookup`` raises typed
    :class:`UnknownProgram` (404 — transient: re-sending the full
    circuit resolves it) for digests this server never saw or evicted."""

    def __init__(self, max_programs: int = 256):
        self._lock = threading.Lock()
        self._programs: dict = {}       # digest -> Circuit (insertion order)
        self._max = int(max_programs)

    def register(self, digest: str, circuit) -> bool:
        """Store a decoded program; returns True when it was new (the
        caller then warms replicas exactly once per digest)."""
        with self._lock:
            if digest in self._programs:
                return False
            while len(self._programs) >= self._max:
                self._programs.pop(next(iter(self._programs)))
            self._programs[digest] = circuit
            return True

    def get(self, digest: str):
        with self._lock:
            return self._programs.get(digest)

    def lookup(self, digest: str):
        c = self.get(digest)
        if c is None:
            raise UnknownProgram(
                f"no program registered under digest {digest!r} "
                "(never sent, or evicted) — re-submit the full circuit",
                detail={"digest": str(digest)})
        return c

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)


class SessionManager:
    """Open/resolve sessions against an :class:`AuthHook` and install
    each grant's tenant policy on the backend (once per tenant)."""

    def __init__(self, auth: Optional[AuthHook] = None, backend=None,
                 allow_anonymous: bool = True):
        self._auth = auth
        self._backend = backend
        self._allow_anonymous = bool(allow_anonymous)
        self._lock = threading.Lock()
        self._sessions: dict = {}
        self._ids = itertools.count(1)
        self._policies_installed: set = set()
        self._anon: Optional[Session] = None

    def open(self, token: Optional[str]) -> Session:
        if self._auth is not None:
            grant = self._auth.authenticate(token)
            if grant is None:
                raise AuthError("unknown token: the authn hook rejected "
                                "this credential")
        elif token is not None or self._allow_anonymous:
            grant = SessionGrant(DEFAULT_TENANT)
        else:
            raise AuthError("this server requires a token")
        with self._lock:
            sid = f"s{next(self._ids):06d}"
            sess = Session(sid, grant)
            self._sessions[sid] = sess
        self._install_policy(grant)
        return sess

    def _install_policy(self, grant: SessionGrant) -> None:
        if grant.policy is None or self._backend is None:
            return
        set_tenant = getattr(self._backend, "set_tenant", None)
        if set_tenant is None:
            return
        with self._lock:
            if grant.tenant in self._policies_installed:
                return
            self._policies_installed.add(grant.tenant)
        set_tenant(grant.tenant, grant.policy)

    def resolve(self, sid: Optional[str]) -> Session:
        """Session id -> Session; unknown ids reject 401. A missing id
        opens an implicit anonymous session when allowed."""
        if sid is None:
            if self._auth is None and self._allow_anonymous:
                # ONE shared implicit session, not one per request: the
                # hit-rate accounting stays meaningful for sessionless
                # callers
                with self._lock:
                    anon = self._anon
                if anon is not None:
                    return anon
                sess = self.open(None)
                with self._lock:
                    if self._anon is None:
                        self._anon = sess
                    sess = self._anon
                return sess
            raise AuthError("no session: POST /v1/session first")
        with self._lock:
            sess = self._sessions.get(sid)
        if sess is None:
            raise AuthError(f"unknown session {sid!r}: it was never "
                            "opened here, or the server restarted")
        return sess

    def snapshot(self) -> list:
        with self._lock:
            return [s.snapshot() for s in self._sessions.values()]
