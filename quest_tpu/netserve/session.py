"""Sessions: authn tokens -> tenants, and the digest-keyed program
registry that pins a session's compiled programs to warm replicas.

The authn surface is a single pluggable hook: :class:`AuthHook`
``.authenticate(token)`` returns a :class:`SessionGrant` (tenant name
plus an optional WFQ :class:`~quest_tpu.serve.sched.TenantPolicy`) or
``None`` to reject. The server installs the grant's policy on the
backend via ``set_tenant`` when the session opens, so quota/priority
admission (429 ``QuotaExceeded``/``QueueFull``) is enforced by the SAME
WFQ layer that guards in-process callers — the wire adds no second
quota system.

Programs are content-addressed: the first submission of a circuit
registers it under its :func:`~quest_tpu.serve.warmcache.circuit_digest`
and warms the backend's replicas; later submissions send only the
digest (``circuit_ref``) and skip re-serialization, re-decode, and
re-compile entirely. Hit rates are tracked per session — they are the
signal ``tools/wire_trace.py`` reports.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

from .errors import AuthError, SessionExpired, UnknownProgram

__all__ = ["SessionGrant", "AuthHook", "OpenAuth", "StaticTokenAuth",
           "Session", "SessionManager", "ProgramRegistry"]

DEFAULT_TENANT = "default"


class SessionGrant:
    """What an authn hook vouches for: the tenant this token serves
    under, optionally the WFQ policy to install for it."""

    __slots__ = ("tenant", "policy", "meta")

    def __init__(self, tenant: str, policy=None, meta: dict = None):
        self.tenant = str(tenant)
        self.policy = policy
        self.meta = dict(meta or {})


class AuthHook:
    """Pluggable authn: map a bearer token to a :class:`SessionGrant`
    (or ``None`` to reject). Subclass and hand an instance to
    :class:`~quest_tpu.netserve.server.NetServer`."""

    def authenticate(self, token: Optional[str]) -> Optional[SessionGrant]:
        raise NotImplementedError


class OpenAuth(AuthHook):
    """Accept everything; every caller lands on one tenant. The default
    for loopback/dev servers, mirroring the telemetry exporter."""

    def __init__(self, tenant: str = DEFAULT_TENANT):
        self._tenant = tenant

    def authenticate(self, token):
        return SessionGrant(self._tenant)


class StaticTokenAuth(AuthHook):
    """A fixed token table: ``{token: SessionGrant | tenant_name}``.
    Unknown tokens reject (401)."""

    def __init__(self, tokens: dict):
        self._tokens = {}
        for token, grant in dict(tokens).items():
            if not isinstance(grant, SessionGrant):
                grant = SessionGrant(str(grant))
            self._tokens[str(token)] = grant

    def authenticate(self, token):
        return self._tokens.get(token)


class Session:
    """One authenticated wire session: identity plus per-session
    program-registry hit accounting. ``last_seen`` feeds the idle-TTL
    sweep; ``bucket`` is the lazily-created per-session rate limiter
    (:class:`~quest_tpu.netserve.robust.TokenBucket`) when the server
    enforces one."""

    __slots__ = ("id", "tenant", "grant", "hits", "misses", "requests",
                 "last_seen", "bucket")

    def __init__(self, sid: str, grant: SessionGrant):
        self.id = sid
        self.tenant = grant.tenant
        self.grant = grant
        self.hits = 0
        self.misses = 0
        self.requests = 0
        self.last_seen = time.monotonic()
        self.bucket = None

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def snapshot(self) -> dict:
        return {"session": self.id, "tenant": self.tenant,
                "requests": self.requests, "program_hits": self.hits,
                "program_misses": self.misses,
                "program_hit_rate": round(self.hit_rate(), 4)}


class ProgramRegistry:
    """Digest-keyed store of decoded circuits. ``lookup`` raises typed
    :class:`UnknownProgram` (404 — transient: re-sending the full
    circuit resolves it) for digests this server never saw or evicted."""

    def __init__(self, max_programs: int = 256):
        self._lock = threading.Lock()
        self._programs: dict = {}       # digest -> Circuit (insertion order)
        self._max = int(max_programs)

    def register(self, digest: str, circuit) -> bool:
        """Store a decoded program; returns True when it was new (the
        caller then warms replicas exactly once per digest)."""
        with self._lock:
            if digest in self._programs:
                return False
            while len(self._programs) >= self._max:
                self._programs.pop(next(iter(self._programs)))
            self._programs[digest] = circuit
            return True

    def get(self, digest: str):
        with self._lock:
            return self._programs.get(digest)

    def evict(self, digest: str) -> bool:
        """Drop one program (operator tooling + the ``stale_ref`` chaos
        kind); returns whether it was present. The next ``circuit_ref``
        naming it answers 404 and the client self-heals with a full
        resend."""
        with self._lock:
            return self._programs.pop(digest, None) is not None

    def items(self) -> list:
        """``[(digest, circuit), ...]`` in insertion order — the drain
        persistence walk."""
        with self._lock:
            return list(self._programs.items())

    def lookup(self, digest: str):
        c = self.get(digest)
        if c is None:
            raise UnknownProgram(
                f"no program registered under digest {digest!r} "
                "(never sent, or evicted) — re-submit the full circuit",
                detail={"digest": str(digest)})
        return c

    def __len__(self) -> int:
        with self._lock:
            return len(self._programs)


class SessionManager:
    """Open/resolve sessions against an :class:`AuthHook` and install
    each grant's tenant policy on the backend (once per tenant).

    ``ttl_s`` enables idle eviction: a session unseen for that long is
    swept (lazily, on the next open/resolve), its program-registry
    hit/miss counters folded into the preserved :meth:`evicted_summary`
    aggregate so the registry's hit-rate accounting survives the
    eviction. Resolving an evicted id raises the typed
    :class:`~quest_tpu.netserve.errors.SessionExpired` (401) — the
    client re-authenticates and retries; ``on_evict`` (if given) is
    called with the number of sessions each sweep evicted (the server
    wires its ``sessions_expired`` counter here)."""

    #: remember at most this many evicted ids (FIFO) so an expired
    #: session answers the typed 401 instead of a generic unknown-id one
    MAX_EXPIRED_IDS = 4096

    def __init__(self, auth: Optional[AuthHook] = None, backend=None,
                 allow_anonymous: bool = True,
                 ttl_s: Optional[float] = None, on_evict=None,
                 clock=time.monotonic):
        self._auth = auth
        self._backend = backend
        self._allow_anonymous = bool(allow_anonymous)
        self._ttl_s = ttl_s
        self._on_evict = on_evict
        self._clock = clock
        self._lock = threading.Lock()
        self._sessions: dict = {}
        self._ids = itertools.count(1)
        self._policies_installed: set = set()
        self._anon: Optional[Session] = None
        self._expired: dict = {}       # sid -> True (bounded FIFO)
        self._evicted_sessions = 0
        self._evicted_hits = 0
        self._evicted_misses = 0
        self._evicted_requests = 0

    def open(self, token: Optional[str]) -> Session:
        if self._auth is not None:
            grant = self._auth.authenticate(token)
            if grant is None:
                raise AuthError("unknown token: the authn hook rejected "
                                "this credential")
        elif token is not None or self._allow_anonymous:
            grant = SessionGrant(DEFAULT_TENANT)
        else:
            raise AuthError("this server requires a token")
        evicted = 0
        with self._lock:
            evicted = self._sweep_locked()
            sid = f"s{next(self._ids):06d}"
            sess = Session(sid, grant)
            sess.last_seen = self._clock()
            self._sessions[sid] = sess
        if evicted and self._on_evict is not None:
            self._on_evict(evicted)
        self._install_policy(grant)
        return sess

    def _sweep_locked(self) -> int:
        """Evict idle sessions past the TTL; caller holds ``_lock``.
        Returns how many were evicted."""
        if self._ttl_s is None:
            return 0
        now = self._clock()
        stale = [sid for sid, s in self._sessions.items()
                 if (now - s.last_seen) > self._ttl_s]
        for sid in stale:
            s = self._sessions.pop(sid)
            # the hit-rate accounting survives the eviction as an
            # aggregate — tools/wire_trace.py still reports a truthful
            # registry hit rate after idle sessions age out
            self._evicted_sessions += 1
            self._evicted_hits += s.hits
            self._evicted_misses += s.misses
            self._evicted_requests += s.requests
            self._expired[sid] = True
            if self._anon is s:
                self._anon = None
        while len(self._expired) > self.MAX_EXPIRED_IDS:
            self._expired.pop(next(iter(self._expired)))
        return len(stale)

    def _install_policy(self, grant: SessionGrant) -> None:
        if grant.policy is None or self._backend is None:
            return
        set_tenant = getattr(self._backend, "set_tenant", None)
        if set_tenant is None:
            return
        with self._lock:
            if grant.tenant in self._policies_installed:
                return
            self._policies_installed.add(grant.tenant)
        set_tenant(grant.tenant, grant.policy)

    def resolve(self, sid: Optional[str]) -> Session:
        """Session id -> Session; unknown ids reject 401 (evicted ones
        with the typed :class:`SessionExpired`). A missing id opens an
        implicit anonymous session when allowed."""
        if sid is None:
            if self._auth is None and self._allow_anonymous:
                # ONE shared implicit session, not one per request: the
                # hit-rate accounting stays meaningful for sessionless
                # callers
                with self._lock:
                    anon = self._anon
                if anon is not None:
                    anon.last_seen = self._clock()
                    return anon
                sess = self.open(None)
                with self._lock:
                    if self._anon is None:
                        self._anon = sess
                    sess = self._anon
                return sess
            raise AuthError("no session: POST /v1/session first")
        evicted = 0
        with self._lock:
            evicted = self._sweep_locked()
            sess = self._sessions.get(sid)
            expired = sid in self._expired if sess is None else False
        if evicted and self._on_evict is not None:
            self._on_evict(evicted)
        if sess is None:
            if expired:
                raise SessionExpired(
                    f"session {sid!r} expired after "
                    f"{self._ttl_s}s idle — re-open it "
                    "(POST /v1/session) and retry",
                    detail={"session": str(sid)})
            raise AuthError(f"unknown session {sid!r}: it was never "
                            "opened here, or the server restarted")
        sess.last_seen = self._clock()
        return sess

    def snapshot(self) -> list:
        with self._lock:
            return [s.snapshot() for s in self._sessions.values()]

    def evicted_summary(self) -> dict:
        """The preserved aggregate of every TTL-evicted session's
        accounting (hit-rate truth survives eviction)."""
        with self._lock:
            total = self._evicted_hits + self._evicted_misses
            return {"sessions": self._evicted_sessions,
                    "program_hits": self._evicted_hits,
                    "program_misses": self._evicted_misses,
                    "requests": self._evicted_requests,
                    "program_hit_rate":
                        round(self._evicted_hits / total, 4)
                        if total else 0.0}

    # -- drain persistence -------------------------------------------------

    def persist(self) -> dict:
        """The JSON-ready session table for the drain snapshot: ids,
        tenants, and accounting (grants beyond the tenant name — WFQ
        policies, token meta — are re-derived on the next authenticate,
        not persisted)."""
        with self._lock:
            rows = [{"session": s.id, "tenant": s.tenant,
                     "requests": s.requests, "hits": s.hits,
                     "misses": s.misses}
                    for s in self._sessions.values()]
            return {"rows": rows,
                    "anon": self._anon.id if self._anon else None,
                    "next_id": next(self._ids)}

    def restore(self, doc: dict) -> int:
        """Readmit a persisted session table: every persisted id
        resolves again (no re-auth storm after a warm handover), and
        the id counter advances past the restored ids so new sessions
        never collide. Returns how many sessions were readmitted."""
        rows = doc.get("rows", [])
        anon_id = doc.get("anon")
        with self._lock:
            for row in rows:
                sid = str(row["session"])
                sess = Session(sid, SessionGrant(str(row["tenant"])))
                sess.requests = int(row.get("requests", 0))
                sess.hits = int(row.get("hits", 0))
                sess.misses = int(row.get("misses", 0))
                sess.last_seen = self._clock()
                self._sessions[sid] = sess
                if sid == anon_id:
                    self._anon = sess
            self._ids = itertools.count(int(doc.get("next_id", 1)))
            return len(rows)
