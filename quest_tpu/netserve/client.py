"""The stdlib sync client: ``submit() -> Future`` over a socket.

:class:`NetClient` mirrors the in-process
:meth:`~quest_tpu.serve.engine.SimulationService.submit` shape — pass a
recorded circuit plus the kind's knobs, get a
:class:`concurrent.futures.Future` resolving with the SAME value shape
the in-process future resolves with (planes array, ``(mean, stderr)``,
``(value, grad)``, …). Server errors re-raise as the SAME typed
exception family (``except QueueFull`` works identically over the
socket, :func:`~quest_tpu.netserve.errors.raise_typed`).

The client is content-address aware: the first submission of a circuit
ships the full wire form; repeats ship only its digest
(``circuit_ref``), falling back to a one-shot full resend when the
server answers 404 ``UnknownProgram`` (evicted or restarted). Deadlines
are RELATIVE (``timeout_s``) by protocol — there is no way to send an
absolute timestamp, so a skewed client clock cannot extend one.

:meth:`NetClient.stream` yields the server's ndjson events (optimizer
iterates, dynamics segments, trajectory waves) as plain dicts; closing
the generator closes the socket, which cancels the server-side handle.
"""

from __future__ import annotations

import http.client
import json
import threading
from concurrent.futures import Future
from typing import Optional

from . import wire
from ._pool import WorkerPool
from .errors import UnknownProgram, raise_typed
from .server import SESSION_HEADER

__all__ = ["NetClient"]


def _infer_kind(observables, shots, trajectories, gradient, evolve,
                ground) -> str:
    if evolve is not None:
        return "evolve"
    if ground is not None:
        return "ground"
    if gradient:
        return "gradient"
    if shots is not None:
        return "shots"
    if trajectories is not None:
        return "trajectory"
    if observables is not None:
        return "expectation"
    return "sweep"


class NetClient:
    """One server endpoint, many concurrent requests.

    Each request rides its own ``http.client.HTTPConnection`` on a
    small thread pool — the stdlib connection is not thread-safe, and
    per-request connections keep the client dependency-free while the
    server side multiplexes fine.
    """

    def __init__(self, host: str, port: int, *,
                 token: Optional[str] = None, timeout: float = 300.0,
                 max_workers: int = 8):
        self.host = host
        self.port = int(port)
        self._token = token
        self._timeout = float(timeout)
        self._lock = threading.Lock()
        self._session_lock = threading.Lock()
        self._session: Optional[str] = None
        self.tenant: Optional[str] = None
        self._programs: dict = {}      # digest -> full circuit doc
        self._confirmed: set = set()   # digests the server acked
        self._pool = WorkerPool(int(max_workers), "quest-netclient")

    # -- plumbing ----------------------------------------------------------

    def _request(self, method: str, path: str, body: bytes = None,
                 headers: dict = None,
                 timeout: Optional[float] = None):
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self._timeout if timeout is None else timeout)
        try:
            hdrs = {"Content-Type": "application/json"}
            if headers:
                hdrs.update(headers)
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    @staticmethod
    def _payload(status: int, data: bytes) -> dict:
        try:
            return json.loads(data.decode("utf-8"))
        except ValueError:
            return {"error": {"type": "WireError",
                              "message": f"non-JSON body (HTTP "
                                         f"{status}): {data[:200]!r}"}}

    # -- sessions ----------------------------------------------------------

    def open_session(self) -> str:
        """Open (or return) this client's session; called lazily by the
        first submit."""
        # one session per client: serialize creation so concurrent
        # first submits don't each open their own
        with self._session_lock:
            if self._session is not None:
                return self._session
            doc = {} if self._token is None else {"token": self._token}
            status, data = self._request(
                "POST", "/v1/session", json.dumps(doc).encode())
            payload = self._payload(status, data)
            if status != 200:
                raise_typed(status, payload)
            self._session = str(payload["session"])
            self.tenant = payload.get("tenant")
            return self._session

    @property
    def session(self) -> Optional[str]:
        return self._session

    # -- submit ------------------------------------------------------------

    def submit(self, circuit=None, params=None, *, kind=None,
               circuit_ref=None, qasm=None, observables=None,
               shots=None, trajectories=None, sampling_budget=None,
               gradient: bool = False, evolve=None, ground=None,
               ground_state=None, init_state=None, tier=None,
               priority=None, timeout_s=None) -> Future:
        """Submit one request; returns a Future resolving with the same
        value shape the in-process API resolves with."""
        ground = ground if ground is not None else ground_state
        wk = kind or _infer_kind(observables, shots, trajectories,
                                 gradient, evolve, ground)
        cdoc = None
        if circuit is not None:
            cdoc = circuit if isinstance(circuit, dict) \
                else wire.encode_circuit(circuit)
            digest = cdoc.get("digest")
            with self._lock:
                # ref only digests the server ACKED (a 200 with this
                # program): switching on first SEND would race our own
                # in-flight full submission to the server
                known = digest in self._confirmed
                if digest is not None:
                    self._programs[digest] = cdoc
            if known:
                circuit_ref, cdoc_sent = digest, None
            else:
                cdoc_sent = cdoc
        else:
            cdoc_sent = None
        doc = wire.encode_request(
            wk, circuit=cdoc_sent, circuit_ref=circuit_ref, qasm=qasm,
            params=params, observables=observables, shots=shots,
            trajectories=trajectories, sampling_budget=sampling_budget,
            tier=tier, priority=priority, timeout_s=timeout_s,
            evolve=evolve, ground=ground, init_state=init_state)
        return self._pool.submit(self._roundtrip, wk, doc)

    def submit_wire(self, doc: dict) -> Future:
        """Submit a raw wire document verbatim (tests, tooling)."""
        kind = doc.get("kind")
        return self._pool.submit(self._roundtrip, kind, dict(doc))

    def _roundtrip(self, kind: str, doc: dict):
        sid = self.open_session()
        body = wire.canonical_json(doc).encode()
        status, data = self._request(
            "POST", "/v1/submit", body, headers={SESSION_HEADER: sid})
        payload = self._payload(status, data)
        if status == 200:
            program = payload.get("program")
            if program is not None:
                with self._lock:
                    self._confirmed.add(program)
            self.last_program = program
            return wire.parse_result(kind, payload["result"])
        ref = doc.get("circuit_ref")
        if status == 404 and ref is not None:
            # evicted/restarted server forgot the program: one full
            # resend re-registers it
            with self._lock:
                self._confirmed.discard(ref)
                full = self._programs.get(ref)
            if full is not None:
                retry = {k: v for k, v in doc.items()
                         if k != "circuit_ref"}
                retry["circuit"] = full
                status2, data2 = self._request(
                    "POST", "/v1/submit", wire.canonical_json(
                        retry).encode(),
                    headers={SESSION_HEADER: sid})
                payload2 = self._payload(status2, data2)
                if status2 == 200:
                    program = payload2.get("program")
                    if program is not None:
                        with self._lock:
                            self._confirmed.add(program)
                    self.last_program = program
                    return wire.parse_result(kind, payload2["result"])
                raise_typed(status2, payload2)
            raise UnknownProgram(
                f"server forgot program {ref!r} and this client holds "
                "no full wire form for it")
        raise_typed(status, payload)

    # -- streaming ---------------------------------------------------------

    def stream(self, circuit=None, params=None, *, kind=None,
               circuit_ref=None, qasm=None, observables=None,
               trajectories=None, sampling_budget=None, evolve=None,
               ground=None, ground_state=None, init_state=None,
               tier=None, optimizer=None, timeout_s=None,
               timeout: Optional[float] = None):
        """Stream one run's events as dicts (``event`` in
        ``{"stream.open", "iterate", "segment", "wave", "result",
        "error"}``). Closing the generator closes the socket, which
        cancels the server-side handle."""
        ground = ground if ground is not None else ground_state
        if kind is None:
            if optimizer is not None:
                kind = "gradient"
            else:
                kind = _infer_kind(observables, None, trajectories,
                                   False, evolve, ground)
        if circuit is not None and not isinstance(circuit, dict):
            circuit = wire.encode_circuit(circuit)
        doc = wire.encode_request(
            kind, circuit=circuit, circuit_ref=circuit_ref, qasm=qasm,
            params=params, observables=observables,
            trajectories=trajectories, sampling_budget=sampling_budget,
            tier=tier, timeout_s=timeout_s, evolve=evolve,
            ground=ground, init_state=init_state, optimizer=optimizer)
        sid = self.open_session()
        body = wire.canonical_json(doc).encode()
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self._timeout if timeout is None else timeout)
        try:
            conn.request("POST", "/v1/stream", body=body,
                         headers={"Content-Type": "application/json",
                                  SESSION_HEADER: sid})
            resp = conn.getresponse()
            if resp.status != 200:
                raise_typed(resp.status,
                            self._payload(resp.status, resp.read()))
            while True:
                line = resp.readline()
                if not line:
                    return
                yield json.loads(line)
        finally:
            conn.close()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self) -> "NetClient":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
