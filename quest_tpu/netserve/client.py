"""The stdlib sync client: ``submit() -> Future`` over a socket.

:class:`NetClient` mirrors the in-process
:meth:`~quest_tpu.serve.engine.SimulationService.submit` shape — pass a
recorded circuit plus the kind's knobs, get a
:class:`concurrent.futures.Future` resolving with the SAME value shape
the in-process future resolves with (planes array, ``(mean, stderr)``,
``(value, grad)``, …). Server errors re-raise as the SAME typed
exception family (``except QueueFull`` works identically over the
socket, :func:`~quest_tpu.netserve.errors.raise_typed`).

The client is content-address aware: the first submission of a circuit
ships the full wire form; repeats ship only its digest
(``circuit_ref``), falling back to a one-shot full resend when the
server answers 404 ``UnknownProgram`` (evicted or restarted). Deadlines
are RELATIVE (``timeout_s``) by protocol — there is no way to send an
absolute timestamp, so a skewed client clock cannot extend one.

Retries are built in and SAFE: every submission carries a
client-generated ``request_id``, which the server deduplicates in a
bounded idempotency window — so the retry loop (exponential backoff
with jitter, honoring the server's ``Retry-After`` on 429/408) can
never double-dispatch, even when a connection reset or torn response
body hides whether the original executed. The ORIGINAL relative
deadline budget is preserved across attempts (each retry ships the
remaining ``timeout_s``, mirroring router failover); an exhausted
budget raises :class:`~quest_tpu.serve.engine.DeadlineExceeded`. A 401
``SessionExpired`` (the server's idle-TTL sweep evicted the session)
transparently re-opens the session and replays.

:meth:`NetClient.stream` yields the server's ndjson events (optimizer
iterates, dynamics segments, trajectory waves) as plain dicts; closing
the generator closes the socket, which cancels the server-side handle.
With ``resumable=True`` the server instead keeps the run alive across
disconnects, every event carries a monotone ``cursor``, and the client
auto-reconnects via ``POST /v1/resume`` from the last event it saw —
replay overlap is deduplicated by cursor, so the yielded sequence is
identical to an uninterrupted run.
"""

from __future__ import annotations

import http.client
import itertools
import json
import random
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Optional

from . import wire
from ._pool import WorkerPool
from .errors import UnknownProgram, raise_typed
from .server import SESSION_HEADER

__all__ = ["NetClient"]

# statuses the retry loop may replay (the request_id makes it safe):
# 408 slow-loris kill, 429 rate-limit/shed/queue-full, 503 draining/
# breaker/unavailable. 500s replay only when the server classified the
# failure transient. 504 (DeadlineExceeded) never replays: the budget
# is already spent.
_RETRYABLE = (408, 429, 503)


def _infer_kind(observables, shots, trajectories, gradient, evolve,
                ground) -> str:
    if evolve is not None:
        return "evolve"
    if ground is not None:
        return "ground"
    if gradient:
        return "gradient"
    if shots is not None:
        return "shots"
    if trajectories is not None:
        return "trajectory"
    if observables is not None:
        return "expectation"
    return "sweep"


class NetClient:
    """One server endpoint, many concurrent requests.

    Each request rides its own ``http.client.HTTPConnection`` on a
    small thread pool — the stdlib connection is not thread-safe, and
    per-request connections keep the client dependency-free while the
    server side multiplexes fine.

    ``retries`` bounds the replay loop (0 restores fail-fast
    single-shot behavior); ``backoff_s``/``backoff_max_s`` shape the
    jittered exponential backoff; ``retry_seed`` pins the jitter for
    deterministic tests. :attr:`stats` counts retries, program resends,
    session re-opens, and stream resumes.
    """

    def __init__(self, host: str, port: int, *,
                 token: Optional[str] = None, timeout: float = 300.0,
                 max_workers: int = 8, retries: int = 4,
                 backoff_s: float = 0.05, backoff_max_s: float = 2.0,
                 retry_seed: Optional[int] = None):
        self.host = host
        self.port = int(port)
        self._token = token
        self._timeout = float(timeout)
        self._retries = int(retries)
        self._backoff_s = float(backoff_s)
        self._backoff_max_s = float(backoff_max_s)
        self._rng = random.Random(retry_seed)
        self._lock = threading.Lock()
        self._session_lock = threading.Lock()
        self._session: Optional[str] = None
        self.tenant: Optional[str] = None
        self._programs: dict = {}      # digest -> full circuit doc
        self._confirmed: set = set()   # digests the server acked
        self._rid_prefix = uuid.uuid4().hex[:10]
        self._rid_counter = itertools.count(1)
        self._stats_lock = threading.Lock()
        self._stats = {"retries": 0, "resends": 0,
                       "session_reopens": 0, "resumes": 0}
        self._pool = WorkerPool(int(max_workers), "quest-netclient")

    # -- plumbing ----------------------------------------------------------

    def _request(self, method: str, path: str, body: bytes = None,
                 headers: dict = None,
                 timeout: Optional[float] = None):
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self._timeout if timeout is None else timeout)
        try:
            hdrs = {"Content-Type": "application/json"}
            if headers:
                hdrs.update(headers)
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
            rhdrs = {k.lower(): v for k, v in resp.getheaders()}
            return resp.status, resp.read(), rhdrs
        finally:
            conn.close()

    @staticmethod
    def _payload(status: int, data: bytes) -> dict:
        try:
            return json.loads(data.decode("utf-8"))
        except ValueError:
            return {"error": {"type": "WireError",
                              "message": f"non-JSON body (HTTP "
                                         f"{status}): {data[:200]!r}"}}

    @property
    def stats(self) -> dict:
        """Resilience accounting: retries, program resends, session
        re-opens, stream resumes this client performed."""
        with self._stats_lock:
            return dict(self._stats)

    def _count(self, name: str) -> None:
        with self._stats_lock:
            self._stats[name] += 1

    def _next_request_id(self) -> str:
        return f"{self._rid_prefix}-{next(self._rid_counter)}"

    # -- sessions ----------------------------------------------------------

    def open_session(self) -> str:
        """Open (or return) this client's session; called lazily by the
        first submit."""
        # one session per client: serialize creation so concurrent
        # first submits don't each open their own
        with self._session_lock:
            if self._session is not None:
                return self._session
            doc = {} if self._token is None else {"token": self._token}
            status, data, _hdrs = self._request(
                "POST", "/v1/session", json.dumps(doc).encode())
            payload = self._payload(status, data)
            if status != 200:
                raise_typed(status, payload)
            self._session = str(payload["session"])
            self.tenant = payload.get("tenant")
            return self._session

    def _drop_session(self) -> None:
        """Forget an expired session so the next attempt re-opens."""
        with self._session_lock:
            self._session = None
        self._count("session_reopens")

    @property
    def session(self) -> Optional[str]:
        return self._session

    # -- submit ------------------------------------------------------------

    def submit(self, circuit=None, params=None, *, kind=None,
               circuit_ref=None, qasm=None, observables=None,
               shots=None, trajectories=None, sampling_budget=None,
               gradient: bool = False, evolve=None, ground=None,
               ground_state=None, init_state=None, tier=None,
               priority=None, timeout_s=None,
               request_id: Optional[str] = None) -> Future:
        """Submit one request; returns a Future resolving with the same
        value shape the in-process API resolves with."""
        ground = ground if ground is not None else ground_state
        wk = kind or _infer_kind(observables, shots, trajectories,
                                 gradient, evolve, ground)
        cdoc = None
        if circuit is not None:
            cdoc = circuit if isinstance(circuit, dict) \
                else wire.encode_circuit(circuit)
            digest = cdoc.get("digest")
            with self._lock:
                # ref only digests the server ACKED (a 200 with this
                # program): switching on first SEND would race our own
                # in-flight full submission to the server
                known = digest in self._confirmed
                if digest is not None:
                    self._programs[digest] = cdoc
            if known:
                circuit_ref, cdoc_sent = digest, None
            else:
                cdoc_sent = cdoc
        else:
            cdoc_sent = None
        doc = wire.encode_request(
            wk, circuit=cdoc_sent, circuit_ref=circuit_ref, qasm=qasm,
            params=params, observables=observables, shots=shots,
            trajectories=trajectories, sampling_budget=sampling_budget,
            tier=tier, priority=priority, timeout_s=timeout_s,
            evolve=evolve, ground=ground, init_state=init_state,
            request_id=request_id)
        return self._pool.submit(self._roundtrip, wk, doc)

    def submit_wire(self, doc: dict) -> Future:
        """Submit a raw wire document verbatim (tests, tooling)."""
        kind = doc.get("kind")
        return self._pool.submit(self._roundtrip, kind, dict(doc))

    def _accept(self, kind: str, payload: dict):
        program = payload.get("program")
        if program is not None:
            with self._lock:
                self._confirmed.add(program)
        self.last_program = program
        return wire.parse_result(kind, payload["result"])

    def _backoff(self, attempt: int, retry_after, deadline) -> None:
        """Jittered exponential backoff, floored by the server's
        Retry-After estimate, capped by the remaining deadline."""
        sleep = min(self._backoff_max_s,
                    self._backoff_s * (2 ** max(0, attempt - 1)))
        sleep *= 0.5 + self._rng.random()          # jitter in [0.5, 1.5)
        if retry_after is not None:
            sleep = max(sleep, retry_after)
        if deadline is not None:
            sleep = min(sleep, max(0.0, deadline - time.monotonic()))
        self._count("retries")
        if sleep > 0:
            time.sleep(sleep)

    @staticmethod
    def _retry_after(hdrs: dict, err: dict):
        ra = hdrs.get("retry-after")
        if ra is None:
            detail = err.get("detail")
            if isinstance(detail, dict):
                ra = detail.get("retry_after_s")
        try:
            return max(0.0, float(ra)) if ra is not None else None
        except (TypeError, ValueError):
            return None

    def _roundtrip(self, kind: str, doc: dict):
        doc = dict(doc)
        if self._retries > 0 and "request_id" not in doc:
            # idempotency key: the server's dedup window guarantees at
            # most one successful dispatch for it, making every retry
            # below safe even when the response was lost in flight
            doc["request_id"] = self._next_request_id()
        budget = doc.get("timeout_s")
        deadline = None if budget is None \
            else time.monotonic() + budget
        attempt = 0
        healed = False
        last_error = None            # (status, payload) or exception
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._raise_exhausted(budget, attempt, last_error)
                # the ORIGINAL relative budget shrinks across attempts
                # — a retry can never extend the caller's deadline
                doc["timeout_s"] = max(remaining, 1e-3)
            sid = self.open_session()
            body = wire.canonical_json(doc).encode()
            status = None
            retry_after = None
            try:
                # socket timeout = remaining budget + grace: the
                # server expires the dispatch at ITS deadline and
                # answers typed 504 — give that answer time to arrive
                # rather than tearing the socket at the exact budget
                status, data, hdrs = self._request(
                    "POST", "/v1/submit", body,
                    headers={SESSION_HEADER: sid},
                    timeout=None if remaining is None
                    else min(self._timeout, remaining + 5.0))
            except (OSError, http.client.HTTPException) as e:
                # reset / refused / torn body: the server may or may
                # not have executed — only the request_id knows
                if self._retries == 0:
                    raise
                last_error = e
            if status == 200:
                try:
                    payload = json.loads(data.decode("utf-8"))
                except ValueError as e:
                    # a torn 200: retry replays the cached response
                    last_error = e
                    status = None
                else:
                    return self._accept(kind, payload)
            if status is not None:
                payload = self._payload(status, data)
                err = payload.get("error", {})
                if status == 404 and doc.get("circuit_ref") is not None \
                        and not healed:
                    # evicted/restarted server forgot the program: one
                    # full resend re-registers it (same request_id —
                    # the failed ref attempt was not cached)
                    ref = doc["circuit_ref"]
                    with self._lock:
                        self._confirmed.discard(ref)
                        full = self._programs.get(ref)
                    if full is None:
                        raise UnknownProgram(
                            f"server forgot program {ref!r} and this "
                            "client holds no full wire form for it")
                    doc = {k: v for k, v in doc.items()
                           if k != "circuit_ref"}
                    doc["circuit"] = full
                    healed = True
                    self._count("resends")
                    continue
                if status == 401 and err.get("type") == "SessionExpired":
                    # idle-TTL eviction: re-open and replay — typed
                    # transient by contract
                    self._drop_session()
                    if self._retries == 0:
                        raise_typed(status, payload)
                elif status in _RETRYABLE or (
                        status == 500
                        and err.get("classification") == "transient"):
                    retry_after = self._retry_after(hdrs, err)
                else:
                    raise_typed(status, payload)
                last_error = (status, payload)
            attempt += 1
            if attempt > self._retries:
                self._raise_exhausted(budget, attempt, last_error)
            self._backoff(attempt, retry_after, deadline)

    def _raise_exhausted(self, budget, attempt, last_error):
        """Surface the LAST failure once the budget or attempts run
        out; a spent deadline raises typed DeadlineExceeded."""
        if isinstance(last_error, tuple):
            status, payload = last_error
            raise_typed(status, payload)
        from ..serve.engine import DeadlineExceeded
        if budget is not None:
            raise DeadlineExceeded(
                f"retry budget of {budget}s exhausted after "
                f"{attempt} attempts") from (
                last_error if isinstance(last_error, BaseException)
                else None)
        if isinstance(last_error, BaseException):
            raise last_error
        raise ConnectionError(
            f"request failed after {attempt} attempts with no "
            "response from the server")

    # -- streaming ---------------------------------------------------------

    def stream(self, circuit=None, params=None, *, kind=None,
               circuit_ref=None, qasm=None, observables=None,
               trajectories=None, sampling_budget=None, evolve=None,
               ground=None, ground_state=None, init_state=None,
               tier=None, optimizer=None, timeout_s=None,
               timeout: Optional[float] = None,
               resumable: bool = False):
        """Stream one run's events as dicts (``event`` in
        ``{"stream.open", "iterate", "segment", "wave", "result",
        "error"}``, each carrying a monotone ``cursor``). Closing the
        generator closes the socket, which cancels the server-side
        handle — unless ``resumable=True``, in which case the run
        survives disconnects and this generator transparently
        reconnects via ``POST /v1/resume`` from the last event it saw,
        yielding a sequence identical to an uninterrupted run."""
        ground = ground if ground is not None else ground_state
        if kind is None:
            if optimizer is not None:
                kind = "gradient"
            else:
                kind = _infer_kind(observables, None, trajectories,
                                   False, evolve, ground)
        if circuit is not None and not isinstance(circuit, dict):
            circuit = wire.encode_circuit(circuit)
        doc = wire.encode_request(
            kind, circuit=circuit, circuit_ref=circuit_ref, qasm=qasm,
            params=params, observables=observables,
            trajectories=trajectories, sampling_budget=sampling_budget,
            tier=tier, timeout_s=timeout_s, evolve=evolve,
            ground=ground, init_state=init_state, optimizer=optimizer,
            resumable=True if resumable else None)
        sid = self.open_session()
        body = wire.canonical_json(doc).encode()
        if not resumable:
            yield from self._stream_socket("/v1/stream", body, sid,
                                           timeout)
            return
        state = {"stream": None, "cursor": -1}
        attempts = 0
        path, payload = "/v1/stream", body
        while True:
            last_exc = None
            done = False
            try:
                for ev in self._stream_socket(path, payload, sid,
                                              timeout):
                    cur = ev.get("cursor")
                    if cur is not None:
                        if cur <= state["cursor"]:
                            continue       # replay overlap: already seen
                        state["cursor"] = cur
                    if ev.get("event") == "stream.open" \
                            and ev.get("stream"):
                        state["stream"] = str(ev["stream"])
                    if ev.get("event") in ("result", "error"):
                        done = True
                    yield ev
                if done:
                    return                 # terminal event: clean end
                # the socket ended WITHOUT a terminal event. A torn
                # chunked body reads as a clean EOF through
                # http.client (its peek swallows IncompleteRead), so
                # only the protocol contract — every stream ends with
                # "result" or "error" — can tell a tear from the end
            except (OSError, http.client.HTTPException,
                    ValueError) as e:
                # reset or a line torn mid-event: same recovery
                last_exc = e
            if state["stream"] is None:
                if last_exc is not None:
                    raise last_exc     # died before the id arrived
                raise ConnectionError(
                    "stream ended before a stream id arrived")
            attempts += 1
            if attempts > max(1, self._retries):
                if last_exc is not None:
                    raise last_exc
                raise ConnectionError(
                    f"stream still truncated after {attempts - 1} "
                    "resume attempts")
            self._count("resumes")
            self._backoff(attempts, None, None)
            path = "/v1/resume"
            payload = json.dumps(
                {"stream": state["stream"],
                 "cursor": state["cursor"]}).encode()

    def resume_stream(self, stream_id: str, cursor: int = -1,
                      timeout: Optional[float] = None):
        """Reattach to a resumable stream by id: replays every buffered
        event after ``cursor``, then continues live (the raw surface
        under :meth:`stream`'s auto-resume; 404 ``UnknownStream`` when
        the stream is gone or the cursor fell off the buffer)."""
        sid = self.open_session()
        body = json.dumps({"stream": str(stream_id),
                           "cursor": int(cursor)}).encode()
        yield from self._stream_socket("/v1/resume", body, sid, timeout)

    def _stream_socket(self, path: str, body: bytes, sid: str,
                       timeout: Optional[float]):
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=self._timeout if timeout is None else timeout)
        try:
            conn.request("POST", path, body=body,
                         headers={"Content-Type": "application/json",
                                  SESSION_HEADER: sid})
            resp = conn.getresponse()
            if resp.status != 200:
                raise_typed(resp.status,
                            self._payload(resp.status, resp.read()))
            while True:
                line = resp.readline()
                if not line:
                    return
                yield json.loads(line)
        finally:
            conn.close()

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self) -> "NetClient":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
