"""The netserve front door: a stdlib asyncio HTTP/1.1 JSON server.

One daemon-thread event loop accepts connections and parses requests;
every blocking step (decode, backend ``submit``, ``future.result()``,
encode) runs on the server's own worker pool
(:class:`~quest_tpu.netserve._pool.WorkerPool` — NOT the loop's
default ``ThreadPoolExecutor``; see ``_pool.py``) so slow dispatches
never stall the acceptor. Routes:

- ``POST /v1/session`` — open a session: ``{"token": ...}`` through the
  :class:`~quest_tpu.netserve.session.AuthHook` to a tenant (401 on
  rejection); the grant's WFQ policy is installed on the backend.
- ``POST /v1/submit`` — one wire request
  (:mod:`quest_tpu.netserve.wire`), one JSON result. The program is
  resolved through the digest-keyed registry (first submission warms
  the backend; repeats skip decode entirely), the session's tenant
  rides into the SAME WFQ admission as in-process callers, and the
  relative ``timeout_s`` is converted to an absolute deadline at
  SERVER receipt — client clocks never extend a deadline.
- ``POST /v1/stream`` — chunked-transfer ndjson events
  (:data:`~quest_tpu.telemetry.events.EVENT_SCHEMA` shape): optimizer
  iterates (``kind="gradient"`` + ``optimizer``), dynamics segments
  (``evolve``/``ground``), trajectory wave progress (``trajectory``).
  Client disconnect cancels the underlying handle.
- ``GET /metrics``, ``/metrics.json``, ``/healthz`` — the shared
  observability resolver (:class:`~quest_tpu.telemetry.endpoints.
  ObservabilityEndpoints`), identical to the telemetry exporter's; and
  ``GET /v1/sessions`` — per-session program-registry hit rates (the
  ``tools/wire_trace.py`` signal).

Request handling is traced (``quest_tpu.trace/1``) when
``trace_sample_rate`` samples it: ``parse`` -> ``queue`` ->
``dispatch`` -> ``serialize`` spans per request.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Optional

from ..telemetry.endpoints import ObservabilityEndpoints
from ..telemetry.events import make_event
from ..telemetry.metrics import metrics_registry
from ..telemetry.tracing import Tracer
from . import wire
from ._pool import WorkerPool
from .errors import (AuthError, StreamUnsupported, WireFormatError,
                     error_body, http_status)
from .session import ProgramRegistry, SessionManager

__all__ = ["NetServer"]

_SERVER_NAME = "quest-tpu-netserve"
SESSION_HEADER = "x-quest-session"

_REASONS = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
            404: "Not Found", 409: "Conflict",
            429: "Too Many Requests", 500: "Internal Server Error",
            501: "Not Implemented", 503: "Service Unavailable",
            504: "Gateway Timeout"}

_NOT_FOUND = (b'{"error": {"type": "NotFound", "message": '
              b'"unknown route", "classification": "fatal"}}')


def _response(status: int, body: bytes,
              ctype: str = "application/json",
              keep_alive: bool = True) -> bytes:
    reason = _REASONS.get(status, "Error")
    conn = "keep-alive" if keep_alive else "close"
    head = (f"HTTP/1.1 {status} {reason}\r\n"
            f"Server: {_SERVER_NAME}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {conn}\r\n\r\n")
    return head.encode("latin-1") + body


class NetServer:
    """The network front door over one backend (a
    :class:`~quest_tpu.serve.router.ServiceRouter` or a bare
    :class:`~quest_tpu.serve.engine.SimulationService`).

    ``port=0`` (the default) binds a free loopback port — read it back
    from ``server.port``. The server is a context manager; ``close()``
    cancels live stream handles, stops the loop, and unregisters the
    wire metrics provider.
    """

    def __init__(self, backend, *, auth=None, allow_anonymous: bool = True,
                 host: str = "127.0.0.1", port: int = 0,
                 max_body: int = 16 << 20, max_programs: int = 256,
                 registry=None, trace_sample_rate: float = 0.0,
                 warm_on_register: bool = True, max_workers: int = 16):
        from ..serve.metrics import WireMetrics
        self.backend = backend
        # NOT the loop's default executor (a ThreadPoolExecutor): see
        # netserve/_pool.py. Every blocking step — session open, wire
        # decode, backend submit + future.result(), stream pump — runs
        # here; each in-flight request occupies one worker for its
        # whole dispatch, so max_workers bounds server-side concurrency
        self._pool = WorkerPool(int(max_workers), "quest-netserve")
        self.metrics = WireMetrics()
        self.sessions = SessionManager(auth, backend,
                                       allow_anonymous=allow_anonymous)
        self.programs = ProgramRegistry(max_programs=max_programs)
        self.tracer = Tracer(sample_rate=trace_sample_rate,
                             name="netserve")
        self._max_body = int(max_body)
        self._warm_on_register = bool(warm_on_register)
        self._registry = registry if registry is not None \
            else metrics_registry()
        self._endpoints = ObservabilityEndpoints(
            self._registry,
            backend if hasattr(backend, "dispatch_stats") else None)
        self._metrics_name = self._registry.unique_name("netserve")
        self._registry.register(self._metrics_name, self.metrics.snapshot,
                                kind="netserve", owner=self)
        self._handles_lock = threading.Lock()
        self._handles: set = set()
        self._debug_last_handle = None      # tests poke at this
        self._closed = False
        self._server = None
        self._start_exc: Optional[BaseException] = None
        self._started = threading.Event()
        self._loop = asyncio.new_event_loop()
        self.host = host
        self.port = int(port)
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"quest-tpu-netserve-{host}")
        self._thread.start()
        self._started.wait(30.0)
        if self._start_exc is not None:
            exc, self._start_exc = self._start_exc, None
            self._registry.unregister(self._metrics_name)
            raise exc
        if not self._started.is_set():
            raise RuntimeError("netserve event loop failed to start")

    # -- lifecycle ---------------------------------------------------------

    def _run(self) -> None:
        asyncio.set_event_loop(self._loop)
        try:
            self._server = self._loop.run_until_complete(
                asyncio.start_server(self._handle_conn, self.host,
                                     self.port))
            sockname = self._server.sockets[0].getsockname()
            self.host, self.port = sockname[0], int(sockname[1])
        # quest: allow-broad-except(boot failure propagates to the
        # constructor through _start_exc, whatever the bind raised)
        except Exception as e:
            self._start_exc = e
            self._started.set()
            return
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            try:
                self._server.close()
                self._loop.run_until_complete(
                    self._server.wait_closed())
                self._loop.run_until_complete(
                    self._loop.shutdown_asyncgens())
            # quest: allow-broad-except(shutdown best-effort: the
            # daemon loop thread must exit cleanly regardless)
            except Exception:
                pass
            self._loop.close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._handles_lock:
            handles = list(self._handles)
            self._handles.clear()
        for h in handles:
            self._cancel_handle(h)
        if self._started.is_set() and self._start_exc is None:
            try:
                self._loop.call_soon_threadsafe(self._loop.stop)
            except RuntimeError:
                pass                      # loop already gone
            self._thread.join(10.0)
        self._pool.shutdown(wait=False)
        self._registry.unregister(self._metrics_name)

    def __enter__(self) -> "NetServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    @staticmethod
    def _cancel_handle(handle) -> None:
        try:
            handle.cancel()
        # quest: allow-broad-except(cancel is best-effort teardown; a
        # handle mid-completion may legally refuse)
        except Exception:
            pass

    def _track(self, handle) -> None:
        self._debug_last_handle = handle
        with self._handles_lock:
            self._handles.add(handle)

    def _untrack(self, handle) -> None:
        with self._handles_lock:
            self._handles.discard(handle)

    # -- connection handling -----------------------------------------------

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line or line in (b"\r\n", b"\n"):
            return None
        parts = line.decode("latin-1").strip().split()
        if len(parts) != 3:
            raise WireFormatError(f"malformed request line {line!r}")
        method, path, _version = parts
        headers = {}
        while True:
            hline = await reader.readline()
            if hline in (b"\r\n", b"\n", b""):
                break
            name, sep, value = hline.decode("latin-1").partition(":")
            if not sep:
                raise WireFormatError(f"malformed header {hline!r}")
            headers[name.strip().lower()] = value.strip()
        body = b""
        length = int(headers.get("content-length", "0") or "0")
        if length:
            if length > self._max_body:
                raise WireFormatError(
                    f"request body of {length} bytes exceeds the "
                    f"server's max_body of {self._max_body}")
            body = await reader.readexactly(length)
        return method, path, headers, body

    async def _handle_conn(self, reader, writer) -> None:
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                except WireFormatError as e:
                    writer.write(_response(
                        400, wire.canonical_json(error_body(e)).encode(),
                        keep_alive=False))
                    await writer.drain()
                    break
                if req is None:
                    break
                method, path, headers, body = req
                keep = headers.get("connection", "").lower() != "close"
                if method == "GET":
                    resolved = await asyncio.wrap_future(
                        self._pool.submit(self._get_blocking, path))
                    status, ctype, payload = resolved
                    writer.write(_response(status, payload, ctype,
                                           keep_alive=keep))
                    await writer.drain()
                elif method == "POST" and path.startswith("/v1/session"):
                    status, payload = await asyncio.wrap_future(
                        self._pool.submit(self._open_session_blocking,
                                          body))
                    writer.write(_response(status, payload,
                                           keep_alive=keep))
                    await writer.drain()
                elif method == "POST" and path.startswith("/v1/submit"):
                    status, payload = await asyncio.wrap_future(
                        self._pool.submit(self._submit_blocking,
                                          headers, body))
                    writer.write(_response(status, payload,
                                           keep_alive=keep))
                    await writer.drain()
                elif method == "POST" and path.startswith("/v1/stream"):
                    await self._handle_stream(headers, body, reader,
                                              writer)
                    break             # streams own (and end) the socket
                else:
                    writer.write(_response(404, _NOT_FOUND,
                                           keep_alive=keep))
                    await writer.drain()
                if not keep:
                    break
        # quest: allow-broad-except(connection boundary: one sick
        # socket must never take down the acceptor loop)
        except Exception:
            pass
        finally:
            try:
                writer.close()
            # quest: allow-broad-except(double-close on a reset socket
            # is not an event)
            except Exception:
                pass

    # -- GET ---------------------------------------------------------------

    def _get_blocking(self, path: str):
        try:
            if path.startswith("/v1/sessions"):
                body = wire.canonical_json(
                    {"sessions": self.sessions.snapshot(),
                     "programs": len(self.programs)}).encode()
                return 200, "application/json", body
            resolved = self._endpoints.resolve(path)
            if resolved is None:
                return 404, "application/json", _NOT_FOUND
            return resolved
        # quest: allow-broad-except(observability boundary: a failing
        # provider answers 500, it must not kill the connection loop)
        except Exception as e:
            return (500, "application/json",
                    json.dumps(error_body(e)).encode())

    # -- sessions ----------------------------------------------------------

    def _open_session_blocking(self, body: bytes):
        try:
            doc = json.loads(body.decode("utf-8")) if body else {}
            token = doc.get("token")
            sess = self.sessions.open(
                str(token) if token is not None else None)
            self.metrics.incr("sessions_opened")
            payload = wire.canonical_json(
                {"session": sess.id, "tenant": sess.tenant}).encode()
            return 200, payload
        # quest: allow-broad-except(wire boundary: every failure
        # answers typed — AuthError 401, bad JSON 400)
        except Exception as e:
            self.metrics.incr("errors_total")
            if isinstance(e, AuthError):
                self.metrics.incr("auth_rejections")
            return http_status(e), json.dumps(error_body(e)).encode()

    # -- submit ------------------------------------------------------------

    def _submit_blocking(self, headers: dict, body: bytes):
        t0 = time.perf_counter()
        ctx = self.tracer.start(endpoint="submit")
        self.metrics.incr("bytes_in", len(body))
        try:
            sess = self.sessions.resolve(headers.get(SESSION_HEADER))
            sess.requests += 1
            sp = ctx.begin("parse") if ctx else None
            p0 = time.perf_counter()
            wr = wire.decode_request(json.loads(body.decode("utf-8")))
            circuit, digest = self._resolve_program(sess, wr, ctx)
            self.metrics.record_parse(time.perf_counter() - p0)
            if ctx:
                ctx.end(sp, kind=wr.kind, program=digest,
                        session=sess.id)
            kw = wr.submit_kwargs()
            kw["tenant"] = sess.tenant
            if wr.timeout_s is not None:
                # RELATIVE budget: the backend anchors it to ITS clock
                # at receipt (min with the service policy's own cap)
                kw["deadline"] = wr.timeout_s
            sp = ctx.begin("queue") if ctx else None
            fut = self.backend.submit(circuit, **kw)
            if ctx:
                ctx.end(sp)
            sp = ctx.begin("dispatch") if ctx else None
            value = fut.result()
            if ctx:
                ctx.end(sp)
            sp = ctx.begin("serialize") if ctx else None
            s0 = time.perf_counter()
            payload = wire.canonical_json(
                {"schema": wire.WIRE_SCHEMA, "kind": wr.kind,
                 "program": digest,
                 "result": wire.encode_result(wr.kind, value)}).encode()
            self.metrics.record_serialize(time.perf_counter() - s0)
            if ctx:
                ctx.end(sp)
                ctx.finish("ok")
            self.metrics.incr("requests_total")
            self.metrics.incr("requests_" + wr.kind)
            self.metrics.incr("bytes_out", len(payload))
            self.metrics.record_request(time.perf_counter() - t0)
            return 200, payload
        # quest: allow-broad-except(wire boundary: EVERY failure maps
        # to a typed JSON error envelope + HTTP status — the socket
        # never sees a traceback)
        except Exception as e:
            self.metrics.incr("errors_total")
            if isinstance(e, AuthError):
                self.metrics.incr("auth_rejections")
            if ctx:
                ctx.add("error", type=type(e).__name__)
                ctx.finish("error")
            return http_status(e), json.dumps(error_body(e)).encode()

    def _resolve_program(self, sess, wr, ctx):
        """``circuit_ref``/``circuit``/``qasm`` -> (Circuit, digest),
        with per-session hit accounting. First sight of a digest
        registers AND warms; repeats skip decode entirely."""
        if wr.circuit_ref is not None:
            c = self.programs.lookup(str(wr.circuit_ref))
            sess.hits += 1
            self.metrics.incr("program_hits")
            return c, str(wr.circuit_ref)
        if wr.qasm is not None:
            from ..qasm_import import parse_qasm
            from ..serve.warmcache import circuit_digest
            self.metrics.incr("qasm_submissions")
            c = parse_qasm(wr.qasm, dialect="quest").circuit
            digest = circuit_digest(c)
            existing = self.programs.get(digest)
            if existing is not None:
                sess.hits += 1
                self.metrics.incr("program_hits")
                return existing, digest
            self._register_and_warm(digest, c, wr, ctx)
            sess.misses += 1
            self.metrics.incr("program_misses")
            return c, digest
        doc = wr.circuit_doc
        claimed = doc.get("digest") if isinstance(doc, dict) else None
        if claimed is not None:
            existing = self.programs.get(claimed)
            if existing is not None:
                # a full resend of a known program: the digest IS the
                # content address, so skip the replay entirely
                sess.hits += 1
                self.metrics.incr("program_hits")
                return existing, claimed
        c = wire.decode_circuit(doc)          # verifies the digest claim
        if claimed is None:
            from ..serve.warmcache import circuit_digest
            claimed = circuit_digest(c)
        self._register_and_warm(claimed, c, wr, ctx)
        sess.misses += 1
        self.metrics.incr("program_misses")
        return c, claimed

    def _register_and_warm(self, digest, circuit, wr, ctx=None) -> None:
        if not self.programs.register(digest, circuit):
            return
        self.metrics.incr("programs_registered")
        if not self._warm_on_register:
            return
        warm = getattr(self.backend, "warm", None)
        if warm is None:
            return
        if ctx:
            ctx.add("warm", program=digest, kind=wr.kind)
        obs = wr.observables
        try:
            if wr.kind == "expectation" and obs is not None:
                warm(circuit, observables=obs)
            elif wr.kind == "shots" and wr.shots is not None:
                warm(circuit, shots=wr.shots)
            elif wr.kind == "gradient" and obs is not None \
                    and wr.trajectories is None:
                try:
                    warm(circuit, observables=obs, gradient=True)
                except TypeError:
                    # routers warm observables only; the gradient
                    # executable compiles on first dispatch
                    warm(circuit, observables=obs)
            elif wr.kind == "trajectory" and obs is not None:
                try:
                    warm(circuit, observables=obs,
                         trajectories=wr.trajectories or 1)
                except TypeError:
                    pass   # no trajectory warm surface on this backend
            elif wr.kind == "sweep":
                warm(circuit)
            # evolve/ground compile per-segment executables — no
            # submit-shaped warm form exists for them
        # quest: allow-broad-except(warming is an optimization: a warm
        # failure must never fail the request that triggered it)
        except Exception:
            pass

    # -- streaming ---------------------------------------------------------

    async def _handle_stream(self, headers, body, reader, writer) -> None:
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()
        t0 = time.monotonic()
        done = object()
        self.metrics.incr("bytes_in", len(body))

        def emit(name: str, **detail) -> None:
            ev = make_event(name, t0, **wire.jsonable(detail))
            try:
                loop.call_soon_threadsafe(queue.put_nowait, ev)
            except RuntimeError:
                pass                        # loop closed mid-stream

        setup = await asyncio.wrap_future(
            self._pool.submit(self._stream_setup_blocking, headers,
                              body, emit))
        status, err_payload, mode, handle, digest, kind = setup
        if err_payload is not None:
            writer.write(_response(status, err_payload,
                                   keep_alive=False))
            await writer.drain()
            return
        writer.write((f"HTTP/1.1 200 OK\r\n"
                      f"Server: {_SERVER_NAME}\r\n"
                      "Content-Type: application/x-ndjson\r\n"
                      "Transfer-Encoding: chunked\r\n"
                      "Connection: close\r\n\r\n").encode("latin-1"))
        await writer.drain()
        self.metrics.incr("streams_opened")
        emit("stream.open", kind=kind, program=digest)

        def pump() -> None:
            try:
                if mode == "handle":
                    name = "segment" if kind in ("evolve", "ground") \
                        else "iterate"
                    for it in handle.iterates():
                        emit(name, **it)
                    emit("result", kind=kind, result=handle.result())
                else:
                    # a trajectory future: wave events already ride the
                    # _progress callback; just resolve the value
                    value = handle.result()
                    emit("result", kind=kind,
                         result=wire.encode_result(kind, value))
            # quest: allow-broad-except(stream boundary: a failing run
            # becomes a terminal "error" event, never a half-closed
            # socket with no explanation)
            except Exception as e:
                emit("error", **error_body(e)["error"])
            finally:
                self._untrack(handle)
                try:
                    loop.call_soon_threadsafe(queue.put_nowait, done)
                except RuntimeError:
                    pass

        pump_fut = asyncio.wrap_future(self._pool.submit(pump))

        disconnected = asyncio.Event()

        async def watch_disconnect() -> None:
            # the client sends nothing after the request: the next
            # read resolving (EOF or reset) means the peer went away
            try:
                await reader.read(1)
            except (ConnectionError, asyncio.CancelledError):
                pass
            disconnected.set()
            if not pump_fut.done():
                self._cancel_handle(handle)
                self.metrics.incr("stream_cancels")

        watcher = asyncio.ensure_future(watch_disconnect())
        try:
            while True:
                ev = await queue.get()
                if ev is done:
                    break
                line = (json.dumps(ev, sort_keys=True, default=str)
                        + "\n").encode("utf-8")
                chunk = (f"{len(line):x}\r\n".encode("latin-1") + line
                         + b"\r\n")
                try:
                    writer.write(chunk)
                    await writer.drain()
                except (ConnectionError, ConnectionResetError):
                    if not disconnected.is_set():
                        disconnected.set()
                        self._cancel_handle(handle)
                        self.metrics.incr("stream_cancels")
                    break
                self.metrics.incr("stream_events")
                self.metrics.incr("bytes_out", len(chunk))
            if not disconnected.is_set():
                try:
                    writer.write(b"0\r\n\r\n")
                    await writer.drain()
                except (ConnectionError, ConnectionResetError):
                    pass
        finally:
            watcher.cancel()
            try:
                await pump_fut
            # quest: allow-broad-except(the pump already reported its
            # failure as an "error" event)
            except Exception:
                pass

    def _stream_setup_blocking(self, headers, body, emit):
        """Resolve the request into a streamable handle BEFORE any bytes
        go out, so typed failures still answer as plain HTTP errors."""
        try:
            sess = self.sessions.resolve(headers.get(SESSION_HEADER))
            sess.requests += 1
            wr = wire.decode_request(json.loads(body.decode("utf-8")))
            circuit, digest = self._resolve_program(sess, wr, None)
            kind = wr.kind
            if kind == "gradient" and wr.optimizer is not None:
                from ..serve.optimize import VariationalProblem
                opt = dict(wr.optimizer)
                problem = VariationalProblem(
                    circuit=circuit, observables=wr.observables,
                    x0=wr.params if wr.params is not None else {},
                    trajectories=wr.trajectories,
                    sampling_budget=wr.sampling_budget, tier=wr.tier)
                handle = self.backend.optimize(
                    problem, opt.get("name", "adam"),
                    max_iters=int(opt.get("max_iters", 100)),
                    tol=opt.get("tol", 1e-6),
                    learning_rate=opt.get("learning_rate"),
                    tenant=sess.tenant)
                mode = "handle"
            elif kind in ("evolve", "ground"):
                fn = getattr(self.backend,
                             "evolve" if kind == "evolve"
                             else "ground_state", None)
                if fn is None:
                    raise StreamUnsupported(
                        f"this backend has no streaming {kind!r} "
                        "surface — POST /v1/submit runs it as one "
                        "request instead")
                if wr.observables is None:
                    raise WireFormatError(
                        f"{kind} requests carry the Hamiltonian as "
                        "observables={'terms': ..., 'coeffs': ...}")
                if kind == "evolve":
                    handle = fn(circuit, wr.params,
                                hamiltonian=wr.observables,
                                t=wr.evolve.t, steps=wr.evolve.steps,
                                order=wr.evolve.order,
                                init_state=wr.init_state, tier=wr.tier,
                                tenant=sess.tenant)
                else:
                    handle = fn(circuit, wr.params,
                                hamiltonian=wr.observables,
                                steps=wr.ground.steps,
                                tau=wr.ground.tau,
                                method=wr.ground.method,
                                tol=wr.ground.tol,
                                init_state=wr.init_state, tier=wr.tier,
                                tenant=sess.tenant)
                mode = "handle"
            elif kind == "trajectory":
                kw = wr.submit_kwargs()
                kw["tenant"] = sess.tenant
                if wr.timeout_s is not None:
                    kw["deadline"] = wr.timeout_s
                handle = self.backend.submit(
                    circuit,
                    _progress=lambda info: emit("wave", **info), **kw)
                mode = "future"
            else:
                raise StreamUnsupported(
                    f"kind {kind!r} has no streaming form — "
                    "POST /v1/submit")
            self._track(handle)
            self.metrics.incr("requests_total")
            self.metrics.incr("requests_" + kind)
            return 200, None, mode, handle, digest, kind
        # quest: allow-broad-except(wire boundary: setup failures
        # answer as typed plain-HTTP errors BEFORE streaming starts)
        except Exception as e:
            self.metrics.incr("errors_total")
            if isinstance(e, AuthError):
                self.metrics.incr("auth_rejections")
            return (http_status(e), json.dumps(error_body(e)).encode(),
                    None, None, None, None)
