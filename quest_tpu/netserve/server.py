"""The netserve front door: a stdlib asyncio HTTP/1.1 JSON server.

One daemon-thread event loop accepts connections and parses requests;
every blocking step (decode, backend ``submit``, ``future.result()``,
encode) runs on the server's own worker pool
(:class:`~quest_tpu.netserve._pool.WorkerPool` — NOT the loop's
default ``ThreadPoolExecutor``; see ``_pool.py``) so slow dispatches
never stall the acceptor. Routes:

- ``POST /v1/session`` — open a session: ``{"token": ...}`` through the
  :class:`~quest_tpu.netserve.session.AuthHook` to a tenant (401 on
  rejection); the grant's WFQ policy is installed on the backend.
- ``POST /v1/submit`` — one wire request
  (:mod:`quest_tpu.netserve.wire`), one JSON result. The program is
  resolved through the digest-keyed registry (first submission warms
  the backend; repeats skip decode entirely), the session's tenant
  rides into the SAME WFQ admission as in-process callers, and the
  relative ``timeout_s`` is converted to an absolute deadline at
  SERVER receipt — client clocks never extend a deadline.
- ``POST /v1/stream`` — chunked-transfer ndjson events
  (:data:`~quest_tpu.telemetry.events.EVENT_SCHEMA` shape, each
  stamped with a monotone ``cursor``): optimizer iterates
  (``kind="gradient"`` + ``optimizer``), dynamics segments
  (``evolve``/``ground``), trajectory wave progress (``trajectory``).
  Client disconnect cancels the underlying handle — UNLESS the request
  carried ``resumable: true``, in which case the run keeps going and
  its events buffer server-side for ``resume_ttl_s``.
- ``POST /v1/resume`` — ``{"stream": id, "cursor": n}`` reattaches to a
  resumable stream: every buffered event after the last-acked cursor
  replays, then live events continue (404
  :class:`~quest_tpu.netserve.errors.UnknownStream` when the stream is
  gone or the cursor fell off the bounded replay buffer).
- ``GET /metrics``, ``/metrics.json``, ``/healthz`` — the shared
  observability resolver (:class:`~quest_tpu.telemetry.endpoints.
  ObservabilityEndpoints`), identical to the telemetry exporter's,
  plus ``/healthz/live`` (pure liveness) and ``/healthz/ready``
  (readiness — flips 503 while draining); and ``GET /v1/sessions`` —
  per-session program-registry hit rates, TTL-eviction aggregates, and
  the dedup-window snapshot (the ``tools/wire_trace.py`` signal).

Hardening (the overload/retry/drain contract — ``docs/tpu.md``
"Network resilience"):

- **read deadline** — a request that dribbles in slower than
  ``read_timeout_s`` answers 408 and loses the connection (slow-loris
  guard); an IDLE keep-alive peer is closed silently.
- **connection cap** — past ``max_connections`` concurrent sockets,
  new connections answer 503 immediately.
- **per-session rate limit** — ``rate_limit=(rate, burst)`` token
  buckets answer 429 ``RateLimited`` with ``Retry-After`` = when the
  next token lands.
- **priority-aware shedding** — past ``shed_watermark`` of backend
  queue depth, requests with priority > 0 answer 429
  ``ServerOverloaded`` with ``Retry-After`` derived from the WFQ
  backlog estimate; priority-0 (ui-class) traffic is never shed.
- **idempotency** — a client-supplied ``request_id`` deduplicates in a
  bounded window: a retried id that already succeeded replays the
  cached response (at most ONE successful dispatch per id); a
  duplicate of an in-flight id joins the original's result.
- **drain** — :meth:`NetServer.drain` stops accepting, finishes
  in-flight work, and atomically persists the program registry +
  session table to ``state_path``; a restarted server readmits the
  sessions and serves ``circuit_ref`` submissions without a resend
  storm.
- **chaos** — the ``netserve.request``/``netserve.stream`` fault sites
  fire the wire kinds (:data:`~quest_tpu.resilience.faults.WIRE_KINDS`)
  at this boundary: connection resets, stalled reads, torn response
  bodies, duplicate deliveries, stale program refs.

Request handling is traced (``quest_tpu.trace/1``) when
``trace_sample_rate`` samples it: ``parse`` -> ``queue`` ->
``dispatch`` -> ``serialize`` spans per request.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import uuid
from typing import Optional

from ..resilience import faults as _faults
from ..telemetry import profile as _profile
from ..telemetry.endpoints import ObservabilityEndpoints
from ..telemetry.events import make_event
from ..telemetry.metrics import metrics_registry
from ..telemetry.tracing import Tracer, dispatch_annotation
from . import robust, wire
from ._pool import WorkerPool
from .errors import (AuthError, RateLimited, RequestTimeout,
                     ServerOverloaded, StreamUnsupported, UnknownStream,
                     WireError, WireFormatError, error_body, http_status,
                     retry_after_s)
from .session import ProgramRegistry, SessionManager

__all__ = ["NetServer"]

_SERVER_NAME = "quest-tpu-netserve"
SESSION_HEADER = "x-quest-session"
NETSTATE_SCHEMA = "quest_tpu.netstate/1"

_REASONS = {200: "OK", 400: "Bad Request", 401: "Unauthorized",
            404: "Not Found", 408: "Request Timeout", 409: "Conflict",
            429: "Too Many Requests", 500: "Internal Server Error",
            501: "Not Implemented", 503: "Service Unavailable",
            504: "Gateway Timeout"}

_NOT_FOUND = (b'{"error": {"type": "NotFound", "message": '
              b'"unknown route", "classification": "fatal"}}')

_BUSY = (b'{"error": {"type": "ServerOverloaded", "message": '
         b'"connection limit reached", '
         b'"classification": "transient"}}')

_DRAINING = (b'{"error": {"type": "ServiceClosed", "message": '
             b'"server is draining", '
             b'"classification": "transient"}}')


class _SlowLoris(Exception):
    """Internal marker: the peer dribbled a request past the read
    deadline (never crosses the wire — mapped to a 408 answer)."""


def _response(status: int, body: bytes,
              ctype: str = "application/json",
              keep_alive: bool = True,
              extra_headers: Optional[dict] = None) -> bytes:
    reason = _REASONS.get(status, "Error")
    conn = "keep-alive" if keep_alive else "close"
    extra = ""
    if extra_headers:
        extra = "".join(f"{k}: {v}\r\n" for k, v in extra_headers.items())
    head = (f"HTTP/1.1 {status} {reason}\r\n"
            f"Server: {_SERVER_NAME}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"{extra}"
            f"Connection: {conn}\r\n\r\n")
    return head.encode("latin-1") + body


class NetServer:
    """The network front door over one backend (a
    :class:`~quest_tpu.serve.router.ServiceRouter` or a bare
    :class:`~quest_tpu.serve.engine.SimulationService`).

    ``port=0`` (the default) binds a free loopback port — read it back
    from ``server.port``. The server is a context manager; ``close()``
    cancels live stream handles, stops the loop, and unregisters the
    wire metrics provider.

    Hardening knobs (all off/permissive by default so an un-configured
    server behaves exactly like the pre-hardening one):

    - ``max_connections`` — concurrent-socket cap (None = unlimited).
    - ``read_timeout_s`` — per-request read deadline (None = never).
    - ``rate_limit`` — ``(rate, burst)`` per-session token bucket.
    - ``shed_watermark`` — backend queue depth past which priority > 0
      requests shed with 429 + Retry-After.
    - ``dedup_window`` — size of the request_id idempotency window.
    - ``session_ttl_s`` — idle sessions evict after this long; expired
      ids answer typed 401 ``SessionExpired``.
    - ``resume_ttl_s`` / ``resume_buffer`` — how long a disconnected
      resumable stream keeps absorbing events, and how many it buffers.
    - ``state_path`` — where :meth:`drain` persists the warm state; a
      file already there at boot is restored (sessions + programs).
    """

    def __init__(self, backend, *, auth=None, allow_anonymous: bool = True,
                 host: str = "127.0.0.1", port: int = 0,
                 max_body: int = 16 << 20, max_programs: int = 256,
                 registry=None, trace_sample_rate: float = 0.0,
                 warm_on_register: bool = True, max_workers: int = 16,
                 max_connections: Optional[int] = None,
                 read_timeout_s: Optional[float] = 30.0,
                 rate_limit: Optional[tuple] = None,
                 shed_watermark: Optional[int] = None,
                 dedup_window: int = 4096,
                 session_ttl_s: Optional[float] = None,
                 resume_ttl_s: float = 30.0,
                 resume_buffer: int = 4096,
                 state_path: Optional[str] = None):
        from ..serve.metrics import WireMetrics
        self.backend = backend
        # NOT the loop's default executor (a ThreadPoolExecutor): see
        # netserve/_pool.py. Every blocking step — session open, wire
        # decode, backend submit + future.result(), stream pump — runs
        # here; each in-flight request occupies one worker for its
        # whole dispatch, so max_workers bounds server-side concurrency
        self._pool = WorkerPool(int(max_workers), "quest-netserve")
        self.metrics = WireMetrics()
        self.sessions = SessionManager(
            auth, backend, allow_anonymous=allow_anonymous,
            ttl_s=session_ttl_s,
            on_evict=lambda n: self.metrics.incr("sessions_expired", n))
        self.programs = ProgramRegistry(max_programs=max_programs)
        self.dedup = robust.DedupWindow(max_entries=int(dedup_window))
        self.tracer = Tracer(sample_rate=trace_sample_rate,
                             name="netserve")
        self._max_body = int(max_body)
        self._warm_on_register = bool(warm_on_register)
        self._max_connections = max_connections
        self._read_timeout_s = read_timeout_s
        if rate_limit is not None:
            rate, burst = rate_limit
            rate_limit = (rate, int(burst))
        self._rate_limit = rate_limit
        self._rl_lock = threading.Lock()     # lazy per-session buckets
        self._shed_watermark = shed_watermark
        self._resume_ttl_s = resume_ttl_s
        self._resume_buffer = int(resume_buffer)
        self._state_path = state_path
        self._draining = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._streams: dict = {}             # stream id -> ResumableStream
        self._streams_lock = threading.Lock()
        self._conn_open = 0                  # touched only on the loop thread
        self._registry = registry if registry is not None \
            else metrics_registry()
        self._endpoints = ObservabilityEndpoints(
            self._registry,
            backend if hasattr(backend, "dispatch_stats") else None,
            readiness=self._readiness)
        self._metrics_name = self._registry.unique_name("netserve")
        self._registry.register(self._metrics_name, self.metrics.snapshot,
                                kind="netserve", owner=self)
        self._handles_lock = threading.Lock()
        self._handles: set = set()
        self._debug_last_handle = None      # tests poke at this
        self._closed = False
        self._server = None
        self._start_exc: Optional[BaseException] = None
        self._started = threading.Event()
        self._loop = asyncio.new_event_loop()
        self.host = host
        self.port = int(port)
        self.restored = {"sessions": 0, "programs": 0}
        if state_path is not None:
            self._restore_state()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"quest-tpu-netserve-{host}")
        self._thread.start()
        self._started.wait(30.0)
        if self._start_exc is not None:
            exc, self._start_exc = self._start_exc, None
            self._registry.unregister(self._metrics_name)
            raise exc
        if not self._started.is_set():
            raise RuntimeError("netserve event loop failed to start")

    # -- lifecycle ---------------------------------------------------------

    def _run(self) -> None:
        asyncio.set_event_loop(self._loop)
        try:
            self._server = self._loop.run_until_complete(
                asyncio.start_server(self._handle_conn, self.host,
                                     self.port))
            sockname = self._server.sockets[0].getsockname()
            self.host, self.port = sockname[0], int(sockname[1])
        # quest: allow-broad-except(boot failure propagates to the
        # constructor through _start_exc, whatever the bind raised)
        except Exception as e:
            self._start_exc = e
            self._started.set()
            return
        self._started.set()
        try:
            self._loop.run_forever()
        finally:
            try:
                self._server.close()
                self._loop.run_until_complete(
                    self._server.wait_closed())
                self._loop.run_until_complete(
                    self._loop.shutdown_asyncgens())
            # quest: allow-broad-except(shutdown best-effort: the
            # daemon loop thread must exit cleanly regardless)
            except Exception:
                pass
            self._loop.close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _readiness(self) -> dict:
        """/healthz/ready's local admission signal: a draining server
        is alive but must not receive new traffic."""
        return {"ready": not self._draining, "draining": self._draining}

    def drain(self, timeout: float = 30.0) -> dict:
        """Graceful drain: stop accepting connections, let in-flight
        requests and live streams finish (up to ``timeout`` seconds),
        then atomically persist the program registry + session table to
        ``state_path`` (crash-safe temp + fsync + replace — a
        restarted server readmits the sessions and serves
        ``circuit_ref`` submissions with zero program misses).
        Idempotent; flips ``/healthz/ready`` to 503 immediately.
        Returns a summary dict."""
        self._draining = True
        if self._started.is_set() and self._start_exc is None \
                and self._server is not None:
            try:
                self._loop.call_soon_threadsafe(self._server.close)
            except RuntimeError:
                pass                      # loop already gone
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._inflight_lock:
                busy = self._inflight
            with self._handles_lock:
                busy += len(self._handles)
            if busy == 0:
                break
            time.sleep(0.005)
        summary = {"persisted": False, "sessions": 0, "programs": 0}
        if self._state_path is not None:
            summary = self._persist_state()
        self.metrics.incr("drains")
        return summary

    def _persist_state(self) -> dict:
        from ..checkpoint import atomic_write_json
        programs = []
        for digest, circuit in self.programs.items():
            try:
                programs.append({"digest": str(digest),
                                 "circuit": wire.encode_circuit(circuit)})
            except WireError:
                # a program that cannot round-trip the wire form is
                # skipped: its clients self-heal via the 404 resend path
                continue
        doc = {"schema": NETSTATE_SCHEMA,
               "sessions": self.sessions.persist(),
               "programs": programs}
        atomic_write_json(self._state_path, doc)
        return {"persisted": True, "path": self._state_path,
                "sessions": len(doc["sessions"]["rows"]),
                "programs": len(programs)}

    def _restore_state(self) -> None:
        """Warm handover: readmit a drained predecessor's sessions and
        programs from ``state_path`` (missing/torn/mismatched files are
        ignored — a cold start is always safe)."""
        try:
            with open(self._state_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(doc, dict) or doc.get("schema") != NETSTATE_SCHEMA:
            return
        n_sessions = self.sessions.restore(doc.get("sessions") or {})
        n_programs = 0
        for row in doc.get("programs") or []:
            try:
                c = wire.decode_circuit(row.get("circuit"),
                                        verify_digest=True)
            except WireError:
                continue          # one bad row never blocks the rest
            if self.programs.register(str(row.get("digest")), c):
                n_programs += 1
        if n_programs:
            self.metrics.incr("programs_restored", n_programs)
        self.restored = {"sessions": n_sessions, "programs": n_programs}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._handles_lock:
            handles = list(self._handles)
            self._handles.clear()
        for h in handles:
            self._cancel_handle(h)
        with self._streams_lock:
            self._streams.clear()
        if self._started.is_set() and self._start_exc is None:
            try:
                self._loop.call_soon_threadsafe(self._loop.stop)
            except RuntimeError:
                pass                      # loop already gone
            self._thread.join(10.0)
        self._pool.shutdown(wait=False)
        self._registry.unregister(self._metrics_name)

    def __enter__(self) -> "NetServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    @staticmethod
    def _cancel_handle(handle) -> None:
        try:
            handle.cancel()
        # quest: allow-broad-except(cancel is best-effort teardown; a
        # handle mid-completion may legally refuse)
        except Exception:
            pass

    def _track(self, handle) -> None:
        self._debug_last_handle = handle
        with self._handles_lock:
            self._handles.add(handle)

    def _untrack(self, handle) -> None:
        with self._handles_lock:
            self._handles.discard(handle)

    # -- connection handling -----------------------------------------------

    async def _read_request(self, reader):
        timeout = self._read_timeout_s
        if timeout is None:
            line = await reader.readline()
        else:
            try:
                line = await asyncio.wait_for(reader.readline(), timeout)
            except asyncio.TimeoutError:
                return None    # idle keep-alive peer: close silently
        if not line or line in (b"\r\n", b"\n"):
            return None
        # the WHOLE request (headers + body) shares ONE read deadline
        # anchored at the request line: a peer dribbling bytes cannot
        # hold a connection slot open (slow-loris guard -> 408)
        deadline = None if timeout is None \
            else time.monotonic() + timeout

        async def _within(coro):
            if deadline is None:
                return await coro
            left = deadline - time.monotonic()
            if left <= 0:
                coro.close()
                raise _SlowLoris()
            try:
                return await asyncio.wait_for(coro, left)
            except asyncio.TimeoutError:
                raise _SlowLoris()

        parts = line.decode("latin-1").strip().split()
        if len(parts) != 3:
            raise WireFormatError(f"malformed request line {line!r}")
        method, path, _version = parts
        headers = {}
        while True:
            hline = await _within(reader.readline())
            if hline in (b"\r\n", b"\n", b""):
                break
            name, sep, value = hline.decode("latin-1").partition(":")
            if not sep:
                raise WireFormatError(f"malformed header {hline!r}")
            headers[name.strip().lower()] = value.strip()
        body = b""
        length = int(headers.get("content-length", "0") or "0")
        if length:
            if length > self._max_body:
                raise WireFormatError(
                    f"request body of {length} bytes exceeds the "
                    f"server's max_body of {self._max_body}")
            body = await _within(reader.readexactly(length))
        return method, path, headers, body

    async def _handle_conn(self, reader, writer) -> None:
        self._conn_open += 1
        try:
            if self._draining:
                writer.write(_response(503, _DRAINING, keep_alive=False))
                await writer.drain()
                return
            if self._max_connections is not None \
                    and self._conn_open > self._max_connections:
                self.metrics.incr("conn_rejected")
                writer.write(_response(503, _BUSY, keep_alive=False))
                await writer.drain()
                return
            while True:
                try:
                    req = await self._read_request(reader)
                except _SlowLoris:
                    self.metrics.incr("read_timeouts")
                    self.metrics.incr("errors_total")
                    e = RequestTimeout(
                        "request not completed within read_timeout_s="
                        f"{self._read_timeout_s}s (slow-loris guard) — "
                        "retry promptly on a fresh connection",
                        detail={"read_timeout_s": self._read_timeout_s})
                    writer.write(_response(
                        408, json.dumps(error_body(e)).encode(),
                        keep_alive=False,
                        extra_headers={"Retry-After": "0.0"}))
                    await writer.drain()
                    break
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                except WireFormatError as e:
                    writer.write(_response(
                        400, wire.canonical_json(error_body(e)).encode(),
                        keep_alive=False))
                    await writer.drain()
                    break
                if req is None:
                    break
                method, path, headers, body = req
                keep = headers.get("connection", "").lower() != "close"
                if self._draining and method != "GET":
                    # keep-alive conns learn about the drain on their
                    # next submission; probes (GET) still answer
                    writer.write(_response(503, _DRAINING,
                                           keep_alive=False))
                    await writer.drain()
                    break
                if method == "GET":
                    resolved = await asyncio.wrap_future(
                        self._pool.submit(self._get_blocking, path))
                    status, ctype, payload = resolved
                    writer.write(_response(status, payload, ctype,
                                           keep_alive=keep))
                    await writer.drain()
                elif method == "POST" and path.startswith("/v1/session"):
                    status, payload = await asyncio.wrap_future(
                        self._pool.submit(self._open_session_blocking,
                                          body))
                    writer.write(_response(status, payload,
                                           keep_alive=keep))
                    await writer.drain()
                elif method == "POST" and path.startswith("/v1/submit"):
                    status, payload, extra, wfault = \
                        await asyncio.wrap_future(
                            self._pool.submit(self._submit_blocking,
                                              headers, body))
                    if wfault == "conn_reset":
                        # injected wire fault: the request may have
                        # EXECUTED, but the peer sees a bare reset —
                        # its retry must dedup, not double-dispatch
                        transport = writer.transport
                        if transport is not None:
                            transport.abort()
                        return
                    if wfault == "torn_body":
                        # injected wire fault: declared Content-Length,
                        # half the bytes, then close — the peer's read
                        # fails mid-body and its retry must dedup
                        resp = _response(status, payload,
                                         keep_alive=False,
                                         extra_headers=extra)
                        cut = max(1, len(payload) // 2 + 1)
                        writer.write(resp[:len(resp) - cut])
                        await writer.drain()
                        break
                    writer.write(_response(status, payload,
                                           keep_alive=keep,
                                           extra_headers=extra))
                    await writer.drain()
                elif method == "POST" and path.startswith("/v1/resume"):
                    await self._handle_resume(headers, body, reader,
                                              writer)
                    break             # streams own (and end) the socket
                elif method == "POST" and path.startswith("/v1/stream"):
                    await self._handle_stream(headers, body, reader,
                                              writer)
                    break             # streams own (and end) the socket
                else:
                    writer.write(_response(404, _NOT_FOUND,
                                           keep_alive=keep))
                    await writer.drain()
                if not keep:
                    break
        # quest: allow-broad-except(connection boundary: one sick
        # socket must never take down the acceptor loop)
        except Exception:
            pass
        finally:
            self._conn_open -= 1
            try:
                writer.close()
            # quest: allow-broad-except(double-close on a reset socket
            # is not an event)
            except Exception:
                pass

    # -- GET ---------------------------------------------------------------

    def _get_blocking(self, path: str):
        try:
            if path.startswith("/v1/sessions"):
                with self._streams_lock:
                    n_streams = len(self._streams)
                body = wire.canonical_json(
                    {"sessions": self.sessions.snapshot(),
                     "programs": len(self.programs),
                     "evicted": self.sessions.evicted_summary(),
                     "dedup": self.dedup.snapshot(),
                     "resumable_streams": n_streams,
                     "draining": self._draining}).encode()
                return 200, "application/json", body
            resolved = self._endpoints.resolve(path)
            if resolved is None:
                return 404, "application/json", _NOT_FOUND
            return resolved
        # quest: allow-broad-except(observability boundary: a failing
        # provider answers 500, it must not kill the connection loop)
        except Exception as e:
            return (500, "application/json",
                    json.dumps(error_body(e)).encode())

    # -- sessions ----------------------------------------------------------

    def _open_session_blocking(self, body: bytes):
        try:
            doc = json.loads(body.decode("utf-8")) if body else {}
            token = doc.get("token")
            sess = self.sessions.open(
                str(token) if token is not None else None)
            self.metrics.incr("sessions_opened")
            payload = wire.canonical_json(
                {"session": sess.id, "tenant": sess.tenant}).encode()
            return 200, payload
        # quest: allow-broad-except(wire boundary: every failure
        # answers typed — AuthError 401, bad JSON 400)
        except Exception as e:
            self.metrics.incr("errors_total")
            if isinstance(e, AuthError):
                self.metrics.incr("auth_rejections")
            return http_status(e), json.dumps(error_body(e)).encode()

    # -- submit ------------------------------------------------------------

    def _submit_blocking(self, headers: dict, body: bytes):
        """One hardened wire submission. Returns ``(status, payload,
        extra_headers, wire_fault)`` — the connection handler applies
        ``conn_reset``/``torn_body`` wire faults at the socket, since
        only it owns the writer."""
        with self._inflight_lock:
            self._inflight += 1
        self.metrics.incr("bytes_in", len(body))
        # QL004 trio (fault hook + trace annotation + profiler): the
        # profile span opens BEFORE the fault hook so injected stalls
        # land inside the measured wall-to-ready time
        sp = _profile.profile_dispatch("netserve.request")
        try:
            try:
                wf = _faults.fire_wire("netserve.request")
            # quest: allow-broad-except(wire boundary: a RAISING
            # injected fault (transient/oom) answers typed like any
            # other dispatch failure)
            except Exception as e:
                return self._error_response(None, e) + (None,)
            if wf is not None:
                self.metrics.incr("wire_faults")
                if wf == "slow_read":
                    # the backend stalls mid-read: the peer's deadline
                    # budget, not ours, decides whether this is fatal
                    inj = _faults.active()
                    time.sleep(inj.stall_s if inj is not None else 0.05)
            with dispatch_annotation("quest_tpu.netserve.request"):
                if wf == "dup_delivery":
                    # the same body delivered twice back-to-back: the
                    # dedup window must collapse the second delivery
                    # into the first's cached result
                    self._submit_once(headers, body, None)
                    status, payload, extra = self._submit_once(
                        headers, body, None)
                else:
                    status, payload, extra = self._submit_once(
                        headers, body, wf)
            wire_fault = wf if wf in ("conn_reset", "torn_body") else None
            return status, payload, extra, wire_fault
        finally:
            if sp is not None:
                sp.done(kind="netserve")
            with self._inflight_lock:
                self._inflight -= 1

    def _submit_once(self, headers: dict, body: bytes, wf):
        """Session + idempotency gate around one execution. A
        ``request_id`` goes through the dedup window: replays answer
        from cache, duplicates of in-flight originals join their
        result, and exactly one ``dispatch`` per id ever reaches
        :meth:`_execute_submit`."""
        ctx = self.tracer.start(endpoint="submit")
        t0 = time.perf_counter()
        try:
            sess = self.sessions.resolve(headers.get(SESSION_HEADER))
        # quest: allow-broad-except(wire boundary: session failures —
        # AuthError, SessionExpired — answer typed)
        except Exception as e:
            return self._error_response(ctx, e)
        sess.requests += 1
        try:
            doc = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            return self._error_response(
                ctx, WireFormatError(f"request body is not valid "
                                     f"JSON: {e}"))
        rid = doc.get("request_id") if isinstance(doc, dict) else None
        if not (isinstance(rid, str) and rid):
            return self._execute_submit(sess, doc, ctx, t0, wf)
        key = (sess.id, rid)
        state, entry = self.dedup.begin(key)
        if state == "replay":
            self.metrics.incr("dedup_hits")
            if ctx:
                ctx.add("dedup", state="replay", request_id=rid)
                ctx.finish("ok")
            return entry.status, entry.payload, {"x-quest-dedup": "replay"}
        if state == "join":
            self.metrics.incr("dedup_joins")
            res = self.dedup.wait(entry)
            if ctx:
                ctx.add("dedup", state="join", request_id=rid)
                ctx.finish("ok" if res else "error")
            if res is None:
                e = ServerOverloaded(
                    "the in-flight original for this request_id did "
                    "not complete within the dedup wait window — retry",
                    detail={"retry_after_s": 1.0})
                return self._error_response(None, e)
            return res[0], res[1], {"x-quest-dedup": "join"}
        try:
            status, payload, extra = self._execute_submit(
                sess, doc, ctx, t0, wf)
        # quest: allow-broad-except(re-raised unmodified — this belt
        # only wakes dedup joiners so they can never wedge on a lost
        # completion; _execute_submit answers typed for everything)
        except BaseException:
            self.dedup.complete(key, entry, 500, b"")
            raise
        self.dedup.complete(key, entry, status, payload)
        return status, payload, extra

    def _execute_submit(self, sess, doc, ctx, t0, wf):
        """Admission (rate limit, shed) + program resolution + backend
        dispatch for exactly one wire request."""
        try:
            if self._rate_limit is not None:
                bucket = sess.bucket
                if bucket is None:
                    with self._rl_lock:
                        if sess.bucket is None:
                            sess.bucket = robust.TokenBucket(
                                *self._rate_limit)
                        bucket = sess.bucket
                wait = bucket.acquire()
                if wait > 0:
                    self.metrics.incr("rate_limited")
                    raise RateLimited(
                        f"session {sess.id} exceeded "
                        f"{self._rate_limit[0]} requests/s (burst "
                        f"{self._rate_limit[1]}) — back off "
                        "retry_after_s before retrying",
                        detail={"retry_after_s": round(wait, 4)})
            sp = ctx.begin("parse") if ctx else None
            p0 = time.perf_counter()
            wr = wire.decode_request(doc)
            if wf == "stale_ref" and wr.circuit_ref is not None:
                # injected wire fault: the referenced program vanishes
                # (evicted/restarted server) — the request answers 404
                # UnknownProgram and the client self-heals via resend
                self.programs.evict(str(wr.circuit_ref))
            self._shed_check(sess, wr)
            circuit, digest = self._resolve_program(sess, wr, ctx)
            self.metrics.record_parse(time.perf_counter() - p0)
            if ctx:
                ctx.end(sp, kind=wr.kind, program=digest,
                        session=sess.id)
            kw = wr.submit_kwargs()
            kw["tenant"] = sess.tenant
            if wr.timeout_s is not None:
                # RELATIVE budget: the backend anchors it to ITS clock
                # at receipt (min with the service policy's own cap)
                kw["deadline"] = wr.timeout_s
            sp = ctx.begin("queue") if ctx else None
            fut = self.backend.submit(circuit, **kw)
            if ctx:
                ctx.end(sp)
            sp = ctx.begin("dispatch") if ctx else None
            value = fut.result()
            if ctx:
                ctx.end(sp)
            sp = ctx.begin("serialize") if ctx else None
            s0 = time.perf_counter()
            payload = wire.canonical_json(
                {"schema": wire.WIRE_SCHEMA, "kind": wr.kind,
                 "program": digest,
                 "result": wire.encode_result(wr.kind, value)}).encode()
            self.metrics.record_serialize(time.perf_counter() - s0)
            if ctx:
                ctx.end(sp)
                ctx.finish("ok")
            self.metrics.incr("requests_total")
            self.metrics.incr("requests_" + wr.kind)
            self.metrics.incr("bytes_out", len(payload))
            self.metrics.record_request(time.perf_counter() - t0)
            return 200, payload, None
        # quest: allow-broad-except(wire boundary: EVERY failure maps
        # to a typed JSON error envelope + HTTP status — the socket
        # never sees a traceback)
        except Exception as e:
            return self._error_response(ctx, e)

    def _error_response(self, ctx, e):
        """Typed error -> ``(status, payload, extra_headers)``; every
        429/408 carries a ``Retry-After`` header (the typed
        ``retry_after_s`` detail, or the WFQ backlog estimate)."""
        self.metrics.incr("errors_total")
        if isinstance(e, AuthError):
            self.metrics.incr("auth_rejections")
        if ctx:
            ctx.add("error", type=type(e).__name__)
            ctx.finish("error")
        status = http_status(e)
        extra = None
        if status in (408, 429):
            ra = retry_after_s(e)
            if ra is None:
                depth, est = robust.backlog_estimate(self.backend)
                ra = min(max(depth * est, 0.05), 30.0)
            extra = {"Retry-After": f"{ra:.3f}"}
        return status, json.dumps(error_body(e)).encode(), extra

    def _shed_check(self, sess, wr) -> None:
        """Priority-aware load shedding: past the backend queue-depth
        watermark, sheddable (priority > 0) requests answer 429 with a
        ``Retry-After`` derived from the WFQ backlog estimate.
        Priority 0 — the ui class — is NEVER shed: under a 4x overload
        burst, interactive traffic keeps flowing while batch backs
        off."""
        if self._shed_watermark is None:
            return
        depth, est = robust.backlog_estimate(self.backend)
        if depth < self._shed_watermark:
            return
        prio = wr.priority
        if prio is None:
            policy = getattr(sess.grant, "policy", None)
            prio = policy.priority if policy is not None else 1
        if prio <= 0:
            return
        retry = min(max(depth * est, 0.05), 30.0)
        self.metrics.incr("load_shed")
        raise ServerOverloaded(
            f"backend queue depth {depth} crossed the shed watermark "
            f"{self._shed_watermark} and priority {prio} is sheddable "
            "— retry after the backlog drains",
            detail={"retry_after_s": round(retry, 3),
                    "queue_depth": depth, "priority": int(prio)})

    def _resolve_program(self, sess, wr, ctx):
        """``circuit_ref``/``circuit``/``qasm`` -> (Circuit, digest),
        with per-session hit accounting. First sight of a digest
        registers AND warms; repeats skip decode entirely."""
        if wr.circuit_ref is not None:
            c = self.programs.lookup(str(wr.circuit_ref))
            sess.hits += 1
            self.metrics.incr("program_hits")
            return c, str(wr.circuit_ref)
        if wr.qasm is not None:
            from ..qasm_import import parse_qasm
            from ..serve.warmcache import circuit_digest
            self.metrics.incr("qasm_submissions")
            c = parse_qasm(wr.qasm, dialect="quest").circuit
            digest = circuit_digest(c)
            existing = self.programs.get(digest)
            if existing is not None:
                sess.hits += 1
                self.metrics.incr("program_hits")
                return existing, digest
            self._register_and_warm(digest, c, wr, ctx)
            sess.misses += 1
            self.metrics.incr("program_misses")
            return c, digest
        doc = wr.circuit_doc
        claimed = doc.get("digest") if isinstance(doc, dict) else None
        if claimed is not None:
            existing = self.programs.get(claimed)
            if existing is not None:
                # a full resend of a known program: the digest IS the
                # content address, so skip the replay entirely
                sess.hits += 1
                self.metrics.incr("program_hits")
                return existing, claimed
        c = wire.decode_circuit(doc)          # verifies the digest claim
        if claimed is None:
            from ..serve.warmcache import circuit_digest
            claimed = circuit_digest(c)
        self._register_and_warm(claimed, c, wr, ctx)
        sess.misses += 1
        self.metrics.incr("program_misses")
        return c, claimed

    def _register_and_warm(self, digest, circuit, wr, ctx=None) -> None:
        if not self.programs.register(digest, circuit):
            return
        self.metrics.incr("programs_registered")
        if not self._warm_on_register:
            return
        warm = getattr(self.backend, "warm", None)
        if warm is None:
            return
        if ctx:
            ctx.add("warm", program=digest, kind=wr.kind)
        obs = wr.observables
        try:
            if wr.kind == "expectation" and obs is not None:
                warm(circuit, observables=obs)
            elif wr.kind == "shots" and wr.shots is not None:
                warm(circuit, shots=wr.shots)
            elif wr.kind == "gradient" and obs is not None \
                    and wr.trajectories is None:
                try:
                    warm(circuit, observables=obs, gradient=True)
                except TypeError:
                    # routers warm observables only; the gradient
                    # executable compiles on first dispatch
                    warm(circuit, observables=obs)
            elif wr.kind == "trajectory" and obs is not None:
                try:
                    warm(circuit, observables=obs,
                         trajectories=wr.trajectories or 1)
                except TypeError:
                    pass   # no trajectory warm surface on this backend
            elif wr.kind == "sweep":
                warm(circuit)
            # evolve/ground compile per-segment executables — no
            # submit-shaped warm form exists for them
        # quest: allow-broad-except(warming is an optimization: a warm
        # failure must never fail the request that triggered it)
        except Exception:
            pass

    # -- streaming ---------------------------------------------------------

    def _sweep_streams(self) -> None:
        """Drop resumable streams whose resume TTL lapsed with no
        consumer attached; a still-live run is cancelled then (nobody
        is coming back for it)."""
        now = time.monotonic()
        doomed = []
        with self._streams_lock:
            for sid in list(self._streams):
                rs = self._streams[sid]
                if rs.expired(now):
                    del self._streams[sid]
                    doomed.append(rs)
        for rs in doomed:
            if not rs.done and rs.handle is not None:
                self._cancel_handle(rs.handle)
                self.metrics.incr("stream_cancels")

    async def _relay_events(self, queue, reader, writer, on_disconnect,
                            torn: bool = False):
        """Relay events from ``queue`` to the chunked socket until the
        ``None`` end-of-stream sentinel. ``on_disconnect`` fires once
        if the peer goes away first. Returns ``"done"`` (terminal chunk
        written), ``"disconnect"``, or ``"torn"`` (injected torn_body:
        the stream is abandoned mid-flight without the terminal
        chunk)."""
        disconnected = asyncio.Event()

        async def watch_disconnect() -> None:
            # the client sends nothing after the request: the next
            # read resolving (EOF or reset) means the peer went away
            try:
                await reader.read(1)
            except (ConnectionError, asyncio.CancelledError):
                pass
            if not disconnected.is_set():
                disconnected.set()
                on_disconnect()

        watcher = asyncio.ensure_future(watch_disconnect())
        wrote = 0
        try:
            while True:
                ev = await queue.get()
                if ev is None:
                    break
                line = (json.dumps(ev, sort_keys=True, default=str)
                        + "\n").encode("utf-8")
                chunk = (f"{len(line):x}\r\n".encode("latin-1") + line
                         + b"\r\n")
                try:
                    writer.write(chunk)
                    await writer.drain()
                except (ConnectionError, ConnectionResetError):
                    if not disconnected.is_set():
                        disconnected.set()
                        on_disconnect()
                    return "disconnect"
                self.metrics.incr("stream_events")
                self.metrics.incr("bytes_out", len(chunk))
                wrote += 1
                if torn and wrote >= 2:
                    # injected torn_body: a couple of events went out,
                    # then the body tears with no terminal chunk — the
                    # client must resume from its last-acked cursor
                    return "torn"
            if disconnected.is_set():
                return "disconnect"
            try:
                writer.write(b"0\r\n\r\n")
                await writer.drain()
            except (ConnectionError, ConnectionResetError):
                pass
            return "done"
        finally:
            watcher.cancel()

    async def _handle_stream(self, headers, body, reader, writer) -> None:
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()
        t0 = time.monotonic()
        self.metrics.incr("bytes_in", len(body))
        # the emit sink: events route into the ResumableStream once the
        # setup publishes one (its buffer owns cursor stamping), else
        # straight onto the loop's queue with a local cursor counter
        state = {"rs": None, "cursor": 0}

        def emit(name: str, **detail) -> None:
            ev = make_event(name, t0, **wire.jsonable(detail))
            rs = state["rs"]
            if rs is not None:
                rs.append(ev)
                return
            ev["cursor"] = state["cursor"]
            state["cursor"] += 1
            try:
                loop.call_soon_threadsafe(queue.put_nowait, ev)
            except RuntimeError:
                pass                        # loop closed mid-stream

        setup = await asyncio.wrap_future(
            self._pool.submit(self._stream_setup_blocking, headers,
                              body, emit, state))
        if setup.get("fault") == "conn_reset":
            # injected wire fault: the peer sees a reset before any
            # response bytes — it reconnects and resumes or restarts
            transport = writer.transport
            if transport is not None:
                transport.abort()
            return
        if setup["err"] is not None:
            writer.write(_response(setup["status"], setup["err"],
                                   keep_alive=False))
            await writer.drain()
            return
        mode, handle = setup["mode"], setup["handle"]
        digest, kind, rs = setup["digest"], setup["kind"], setup["rs"]
        writer.write((f"HTTP/1.1 200 OK\r\n"
                      f"Server: {_SERVER_NAME}\r\n"
                      "Content-Type: application/x-ndjson\r\n"
                      "Transfer-Encoding: chunked\r\n"
                      "Connection: close\r\n\r\n").encode("latin-1"))
        await writer.drain()
        self.metrics.incr("streams_opened")
        if rs is not None:
            # attach BEFORE stream.open so the replay (any events the
            # run emitted during setup) orders ahead of live relays;
            # attach runs here, on the loop thread, by design
            rs.attach(-1, loop, queue)
            emit("stream.open", kind=kind, program=digest, stream=rs.id,
                 resumable=True)
        else:
            emit("stream.open", kind=kind, program=digest)

        def pump() -> None:
            try:
                if mode == "handle":
                    name = "segment" if kind in ("evolve", "ground") \
                        else "iterate"
                    for it in handle.iterates():
                        emit(name, **it)
                    emit("result", kind=kind, result=handle.result())
                else:
                    # a trajectory future: wave events already ride the
                    # _progress callback; just resolve the value
                    value = handle.result()
                    emit("result", kind=kind,
                         result=wire.encode_result(kind, value))
            # quest: allow-broad-except(stream boundary: a failing run
            # becomes a terminal "error" event, never a half-closed
            # socket with no explanation)
            except Exception as e:
                emit("error", **error_body(e)["error"])
            finally:
                self._untrack(handle)
                if rs is not None:
                    rs.finish()
                else:
                    try:
                        loop.call_soon_threadsafe(queue.put_nowait, None)
                    except RuntimeError:
                        pass

        pump_fut = asyncio.wrap_future(self._pool.submit(pump))

        def on_disconnect() -> None:
            if rs is not None:
                # resumable: the run KEEPS GOING — events buffer for
                # resume_ttl_s awaiting a /v1/resume reattach
                rs.detach()
                queue.put_nowait(None)
            elif not pump_fut.done():
                self._cancel_handle(handle)
                self.metrics.incr("stream_cancels")

        torn = setup.get("fault") == "torn_body"
        try:
            await self._relay_events(queue, reader, writer,
                                     on_disconnect, torn=torn)
        finally:
            if rs is not None:
                rs.detach()
            else:
                try:
                    await pump_fut
                # quest: allow-broad-except(the pump already reported
                # its failure as an "error" event)
                except Exception:
                    pass

    def _stream_setup_blocking(self, headers, body, emit, state):
        """Resolve the request into a streamable handle BEFORE any bytes
        go out, so typed failures still answer as plain HTTP errors.
        Returns a dict: status/err (error path), mode/handle/digest/
        kind/rs (success), fault (wire-fault directive for the
        socket-owning caller)."""
        fail = {"status": 500, "err": b"", "mode": None, "handle": None,
                "digest": None, "kind": None, "rs": None, "fault": None}
        # QL004 trio (fault hook + trace annotation + profiler), as in
        # _submit_blocking: the span opens before the fault hook
        sp = _profile.profile_dispatch("netserve.stream")
        try:
            try:
                wf = _faults.fire_wire("netserve.stream")
            # quest: allow-broad-except(wire boundary: a RAISING
            # injected fault answers typed before streaming starts)
            except Exception as e:
                st, payload, _extra = self._error_response(None, e)
                return dict(fail, status=st, err=payload)
            if wf is not None:
                self.metrics.incr("wire_faults")
                if wf == "conn_reset":
                    return dict(fail, err=None, fault="conn_reset")
                if wf == "slow_read":
                    inj = _faults.active()
                    time.sleep(inj.stall_s if inj is not None else 0.05)
                # dup_delivery has no stream meaning (a second identical
                # stream would be a second run): dropped here
            with dispatch_annotation("quest_tpu.netserve.stream"):
                return self._stream_setup_inner(headers, body, emit,
                                                state, wf, fail)
        finally:
            if sp is not None:
                sp.done(kind="netserve")

    def _stream_setup_inner(self, headers, body, emit, state, wf, fail):
        try:
            sess = self.sessions.resolve(headers.get(SESSION_HEADER))
            sess.requests += 1
            wr = wire.decode_request(json.loads(body.decode("utf-8")))
            if wf == "stale_ref" and wr.circuit_ref is not None:
                self.programs.evict(str(wr.circuit_ref))
            self._shed_check(sess, wr)
            circuit, digest = self._resolve_program(sess, wr, None)
            kind = wr.kind
            rs = None
            if wr.resumable:
                self._sweep_streams()
                rs = robust.ResumableStream(
                    f"st-{uuid.uuid4().hex[:12]}", None, sess.id,
                    kind=kind, max_buffer=self._resume_buffer,
                    ttl_s=self._resume_ttl_s)
                # publish BEFORE the handle exists: progress callbacks
                # can fire during submit and must land in the buffer
                state["rs"] = rs
            if kind == "gradient" and wr.optimizer is not None:
                from ..serve.optimize import VariationalProblem
                opt = dict(wr.optimizer)
                problem = VariationalProblem(
                    circuit=circuit, observables=wr.observables,
                    x0=wr.params if wr.params is not None else {},
                    trajectories=wr.trajectories,
                    sampling_budget=wr.sampling_budget, tier=wr.tier)
                handle = self.backend.optimize(
                    problem, opt.get("name", "adam"),
                    max_iters=int(opt.get("max_iters", 100)),
                    tol=opt.get("tol", 1e-6),
                    learning_rate=opt.get("learning_rate"),
                    tenant=sess.tenant)
                mode = "handle"
            elif kind in ("evolve", "ground"):
                fn = getattr(self.backend,
                             "evolve" if kind == "evolve"
                             else "ground_state", None)
                if fn is None:
                    raise StreamUnsupported(
                        f"this backend has no streaming {kind!r} "
                        "surface — POST /v1/submit runs it as one "
                        "request instead")
                if wr.observables is None:
                    raise WireFormatError(
                        f"{kind} requests carry the Hamiltonian as "
                        "observables={'terms': ..., 'coeffs': ...}")
                if kind == "evolve":
                    handle = fn(circuit, wr.params,
                                hamiltonian=wr.observables,
                                t=wr.evolve.t, steps=wr.evolve.steps,
                                order=wr.evolve.order,
                                init_state=wr.init_state, tier=wr.tier,
                                tenant=sess.tenant)
                else:
                    handle = fn(circuit, wr.params,
                                hamiltonian=wr.observables,
                                steps=wr.ground.steps,
                                tau=wr.ground.tau,
                                method=wr.ground.method,
                                tol=wr.ground.tol,
                                init_state=wr.init_state, tier=wr.tier,
                                tenant=sess.tenant)
                mode = "handle"
            elif kind == "trajectory":
                kw = wr.submit_kwargs()
                kw["tenant"] = sess.tenant
                if wr.timeout_s is not None:
                    kw["deadline"] = wr.timeout_s
                handle = self.backend.submit(
                    circuit,
                    _progress=lambda info: emit("wave", **info), **kw)
                mode = "future"
            else:
                raise StreamUnsupported(
                    f"kind {kind!r} has no streaming form — "
                    "POST /v1/submit")
            if rs is not None:
                rs.handle = handle
                with self._streams_lock:
                    self._streams[rs.id] = rs
            self._track(handle)
            self.metrics.incr("requests_total")
            self.metrics.incr("requests_" + kind)
            return {"status": 200, "err": None, "mode": mode,
                    "handle": handle, "digest": digest, "kind": kind,
                    "rs": rs, "fault": "torn_body"
                    if wf == "torn_body" else None}
        # quest: allow-broad-except(wire boundary: setup failures
        # answer as typed plain-HTTP errors BEFORE streaming starts)
        except Exception as e:
            state["rs"] = None          # never leave a dead buffer wired
            st, payload, _extra = self._error_response(None, e)
            return dict(fail, status=st, err=payload)

    # -- resume ------------------------------------------------------------

    async def _handle_resume(self, headers, body, reader, writer) -> None:
        loop = asyncio.get_running_loop()
        self.metrics.incr("bytes_in", len(body))
        setup = await asyncio.wrap_future(
            self._pool.submit(self._resume_setup_blocking, headers,
                              body))
        status, err_payload, rs, cursor = setup
        if err_payload is not None:
            writer.write(_response(status, err_payload,
                                   keep_alive=False))
            await writer.drain()
            return
        queue: asyncio.Queue = asyncio.Queue()
        # attach on the loop thread: the buffered replay (everything
        # after the client's last-acked cursor) orders ahead of any
        # live relay callback by construction
        if not rs.attach(cursor, loop, queue):
            e = UnknownStream(
                f"cursor {cursor} fell off stream {rs.id!r}'s bounded "
                "replay buffer — a gap-free resume is impossible; "
                "restart the stream")
            self.metrics.incr("errors_total")
            writer.write(_response(404,
                                   json.dumps(error_body(e)).encode(),
                                   keep_alive=False))
            await writer.drain()
            return
        self.metrics.incr("streams_resumed")
        writer.write((f"HTTP/1.1 200 OK\r\n"
                      f"Server: {_SERVER_NAME}\r\n"
                      "Content-Type: application/x-ndjson\r\n"
                      "Transfer-Encoding: chunked\r\n"
                      "Connection: close\r\n\r\n").encode("latin-1"))
        await writer.drain()

        def on_disconnect() -> None:
            rs.detach()
            queue.put_nowait(None)

        try:
            await self._relay_events(queue, reader, writer,
                                     on_disconnect)
        finally:
            rs.detach()

    def _resume_setup_blocking(self, headers, body):
        """Validate a resume request -> ``(status, err_payload, rs,
        cursor)``; the socket-owning caller performs the attach."""
        try:
            sess = self.sessions.resolve(headers.get(SESSION_HEADER))
            doc = json.loads(body.decode("utf-8"))
            if not isinstance(doc, dict):
                raise WireFormatError(
                    "resume body must be a JSON object: "
                    '{"stream": id, "cursor": n}')
            stream_id = str(doc.get("stream") or "")
            try:
                cursor = int(doc.get("cursor", -1))
            except (TypeError, ValueError):
                raise WireFormatError(
                    f"cursor must be an integer, got "
                    f"{doc.get('cursor')!r}")
            self._sweep_streams()
            with self._streams_lock:
                rs = self._streams.get(stream_id)
            if rs is None:
                raise UnknownStream(
                    f"no resumable stream {stream_id!r} on this server "
                    "(never opened, finished and swept, or expired "
                    f"past resume_ttl_s={self._resume_ttl_s}) — "
                    "restart the stream")
            if rs.session_id != sess.id:
                raise AuthError(
                    f"stream {stream_id!r} belongs to another session")
            if rs.attached():
                e = WireError(
                    f"stream {stream_id!r} already has a live consumer "
                    "attached — one consumer at a time")
                e.status = 409
                raise e
            return 200, None, rs, cursor
        # quest: allow-broad-except(wire boundary: resume failures
        # answer typed — UnknownStream 404, AuthError 401, bad JSON
        # 400 — before any streaming bytes)
        except Exception as e:
            self.metrics.incr("errors_total")
            if isinstance(e, AuthError):
                self.metrics.incr("auth_rejections")
            return (http_status(e), json.dumps(error_body(e)).encode(),
                    None, None)
