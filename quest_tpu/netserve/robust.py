"""Wire-robustness primitives: the token bucket, the idempotency
window, the WFQ backlog estimate, and the resumable-stream buffer.

These are the host-side building blocks behind the front door's
overload and retry contract (``docs/tpu.md`` "Network resilience"):

- :class:`TokenBucket` — per-session request-rate limiting. An empty
  bucket answers 429 :class:`~quest_tpu.netserve.errors.RateLimited`
  with ``retry_after_s`` = when the next token lands, so a compliant
  client backs off by the server's own estimate.
- :class:`DedupWindow` — the bounded server-side idempotency window.
  Client-supplied ``request_id``s deduplicate here, which is what makes
  the client's retry loop safe: a retried request that already
  SUCCEEDED replays the cached response instead of dispatching again
  (at-most-one successful dispatch per id); a duplicate of an
  IN-FLIGHT request joins the original's result. Failed attempts are
  deliberately NOT pinned — a retry after a transient failure must
  re-execute, and re-executing a failure is not a double dispatch.
- :func:`backlog_estimate` — a cheap (lock-free attribute probe, never
  ``dispatch_stats()``) read of the backend's queue depth and
  per-request service time, for the load-shedding watermark and the
  ``Retry-After`` estimate on every 429.
- :class:`ResumableStream` — the server-side buffer behind resumable
  ndjson streams: every event is stamped with a monotone ``cursor``;
  a disconnected client's stream keeps absorbing events for a grace
  TTL, and a reconnect replays everything after the last-acked cursor
  then continues live.

Locks here are leaves: none of these primitives acquires another lock
while holding its own (the delivery callbacks in
:class:`ResumableStream` run outside the lock), so they add no edges
to the runtime lock-order graph (``QUEST_TPU_LOCKCHECK=1``).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["TokenBucket", "DedupWindow", "ResumableStream",
           "backlog_estimate"]


class TokenBucket:
    """A classic token bucket: ``rate`` tokens/second refill, ``burst``
    capacity. :meth:`acquire` spends one token and returns 0.0, or —
    when the bucket is empty — returns the seconds until the next token
    lands (the ``Retry-After`` the caller surfaces)."""

    __slots__ = ("rate", "burst", "_tokens", "_last", "_lock")

    def __init__(self, rate, burst):
        if rate <= 0 or burst < 1:
            raise ValueError(
                f"rate must be > 0 and burst >= 1; got rate={rate!r} "
                f"burst={burst!r}")
        self.rate = rate
        self.burst = burst
        self._tokens = burst * 1.0
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self, now: Optional[float] = None):
        """Spend one token. Returns 0.0 (admitted) or the seconds until
        a token is available (rejected — the caller answers 429)."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            elapsed = now - self._last
            if elapsed > 0:
                self._tokens = min(self.burst * 1.0,
                                   self._tokens + elapsed * self.rate)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return 0.0
            return (1.0 - self._tokens) / self.rate


class _DedupEntry:
    """One in-flight-or-cached request: joiners wait on ``event``;
    ``status``/``payload`` are the completed response."""

    __slots__ = ("event", "status", "payload")

    def __init__(self):
        self.event = threading.Event()
        self.status = None
        self.payload = None


class DedupWindow:
    """The bounded idempotency window, keyed by ``(session_id,
    request_id)``.

    Contract (the invariant the chaos storm audits): at most ONE
    successful dispatch per key. :meth:`begin` answers one of

    - ``("dispatch", entry)`` — first sight: the caller executes and
      MUST call :meth:`complete`;
    - ``("join", entry)`` — the original is still in flight: the caller
      waits on it via :meth:`wait` and relays its response;
    - ``("replay", entry)`` — the original already succeeded: the
      caller relays the cached ``(status, payload)`` without touching
      the backend.

    Completions with status 200 stay cached (bounded FIFO — oldest
    completed entries evict first; in-flight entries are pinned).
    Non-200 completions wake their joiners with the failure, then DROP
    the entry so a client retry re-executes fresh.
    """

    def __init__(self, max_entries: int = 4096, wait_s: float = 300.0):
        self._lock = threading.Lock()
        self._entries: dict = {}      # key -> _DedupEntry (insertion order)
        self._max = int(max_entries)
        self._wait_s = wait_s
        self._hits = 0
        self._joins = 0
        self._dispatches = 0
        self._double_dispatches = 0   # the invariant counter: stays 0

    def begin(self, key):
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                if e.event.is_set():
                    # only status-200 completions remain cached
                    self._hits += 1
                    return "replay", e
                self._joins += 1
                return "join", e
            e = _DedupEntry()
            if len(self._entries) >= self._max:
                for k in list(self._entries):
                    if self._entries[k].event.is_set():
                        del self._entries[k]
                        if len(self._entries) < self._max:
                            break
            self._entries[key] = e
            self._dispatches += 1
            return "dispatch", e

    def complete(self, key, entry: _DedupEntry, status: int,
                 payload) -> None:
        """Record the dispatch's response and wake joiners. Failures
        (non-200) are handed to current joiners but not cached — the
        next retry of this id dispatches fresh."""
        with self._lock:
            if entry.event.is_set() and entry.status == 200:
                # a second completion for an id that already succeeded
                # would mean the window granted two dispatches: the
                # zero this counter must stay at is the storm's proof
                self._double_dispatches += 1
            entry.status = int(status)
            entry.payload = payload
            if status != 200 and self._entries.get(key) is entry:
                del self._entries[key]
        entry.event.set()

    def wait(self, entry: _DedupEntry):
        """Block until the in-flight original completes; returns
        ``(status, payload)`` or None on timeout."""
        if not entry.event.wait(self._wait_s):
            return None
        return entry.status, entry.payload

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "max_entries": self._max,
                    "dispatches": self._dispatches,
                    "replays": self._hits,
                    "joins": self._joins,
                    "double_dispatches": self._double_dispatches}

    @property
    def double_dispatches(self) -> int:
        with self._lock:
            return self._double_dispatches


def backlog_estimate(backend):
    """``(queue_depth, est_service_s)`` for a backend — a
    :class:`~quest_tpu.serve.engine.SimulationService` (its
    ``_backlog``/``_inflight`` counters) or a
    :class:`~quest_tpu.serve.router.ServiceRouter` (summed over ready
    replicas, with their routing EMA as the service time). Deliberately
    attribute probes, not ``dispatch_stats()``: this runs on the
    admission path of EVERY request under overload, where taking the
    backend's stats locks would turn the shed check into contention."""
    est = 0.05                       # conservative cold default
    replicas = getattr(backend, "_replicas", None)
    if replicas is not None:
        depth = 0
        emas = []
        for h in list(replicas):
            svc = getattr(h, "service", None)
            if svc is None:
                continue
            depth += getattr(svc, "_backlog", 0) \
                + getattr(svc, "_inflight", 0)
            ema = getattr(h, "ema_request_s", 0.0)
            if ema > 0:
                emas.append(ema)
        if emas:
            est = sum(emas) / len(emas)
        return depth, est
    depth = getattr(backend, "_backlog", 0) \
        + getattr(backend, "_inflight", 0)
    return depth, est


class ResumableStream:
    """Server-side state for one resumable ndjson stream.

    The pump thread calls :meth:`append` for every event; each event is
    stamped with the next monotone ``cursor`` and retained in a bounded
    replay buffer (drop-oldest — :attr:`truncated` records when the
    window slid). At most one consumer (an asyncio queue on the
    server's loop) is attached at a time; live events are relayed to it
    thread-safely, and ``None`` is the end-of-stream sentinel.

    On disconnect the consumer detaches and the stream keeps absorbing
    events; :meth:`expired` turns true ``ttl_s`` after the last detach
    (or after completion with no consumer), at which point the server
    sweeps it — cancelling the handle if the run is still live.
    """

    def __init__(self, stream_id: str, handle, session_id: str,
                 kind: str, max_buffer: int = 4096, ttl_s: float = 30.0):
        self.id = str(stream_id)
        self.handle = handle
        self.session_id = session_id
        self.kind = kind
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._events: list = []
        self._base = 0                 # cursor of _events[0]
        self._next = 0                 # next cursor to assign
        self._max = int(max_buffer)
        self._sink = None              # (loop, queue) while attached
        self.done = False
        self.truncated = False
        self._detached_at = time.monotonic()

    def append(self, ev: dict) -> dict:
        """Stamp + buffer one event and relay it to the attached
        consumer (if any). Returns the stamped event."""
        with self._lock:
            ev = dict(ev)
            ev["cursor"] = self._next
            self._next += 1
            self._events.append(ev)
            if len(self._events) > self._max:
                self._events.pop(0)
                self._base += 1
                self.truncated = True
            sink = self._sink
        if sink is not None:
            loop, q = sink
            try:
                loop.call_soon_threadsafe(q.put_nowait, ev)
            except RuntimeError:
                pass                   # loop closed mid-stream
        return ev

    def finish(self) -> None:
        """Mark the run complete and wake the attached consumer with
        the end-of-stream sentinel."""
        with self._lock:
            self.done = True
            sink = self._sink
            if sink is None:
                self._detached_at = time.monotonic()
        if sink is not None:
            loop, q = sink
            try:
                loop.call_soon_threadsafe(q.put_nowait, None)
            except RuntimeError:
                pass

    def attach(self, cursor: int, loop, q) -> bool:
        """Replay every buffered event with ``cursor`` greater than the
        client's last-acked one into ``q``, then attach for live
        events. MUST run on the consumer's loop thread: the replay puts
        are synchronous, so they order before any live relay callback.
        Returns False when the requested cursor fell off the bounded
        buffer (the resume cannot be gap-free)."""
        with self._lock:
            if cursor + 1 < self._base:
                return False
            replay = [e for e in self._events if e["cursor"] > cursor]
            self._sink = (loop, q)
            done = self.done
        for e in replay:
            q.put_nowait(e)
        if done:
            q.put_nowait(None)
        return True

    def detach(self) -> None:
        with self._lock:
            self._sink = None
            self._detached_at = time.monotonic()

    def attached(self) -> bool:
        with self._lock:
            return self._sink is not None

    def expired(self, now: Optional[float] = None) -> bool:
        if now is None:
            now = time.monotonic()
        with self._lock:
            if self._sink is not None:
                return False
            return (now - self._detached_at) > self.ttl_s

    def last_cursor(self) -> int:
        with self._lock:
            return self._next - 1
