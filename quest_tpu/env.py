"""Execution environment: device mesh, precision, randomness.

TPU-native replacement for ``QuESTEnv`` (``QuEST.h:200-204``) and the
per-backend ``createQuESTEnv`` implementations (MPI init
``QuEST_cpu_distributed.c:128-157``, GPU probe ``QuEST_gpu.cu:353-367``):
there is no build-time backend fork — one environment object carries

- a :class:`jax.sharding.Mesh` over the amplitude axis (``None`` = single
  device), replacing rank/numRanks bookkeeping;
- the numeric :class:`~quest_tpu.config.Precision` (runtime, not compile-time);
- a single ``jax.random`` key, split per draw — the analogue of the
  rank-0-seeded, broadcast mt19937 stream (``QuEST_cpu_distributed.c:1318-1329``):
  in SPMD there is one logical program, so agreement is automatic.
"""

from __future__ import annotations

import dataclasses
import time
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .config import Precision, default_precision

__all__ = ["QuESTEnv", "create_quest_env", "destroy_quest_env",
           "initialize_multihost", "default_compensated"]

AMP_AXIS = "amps"


@dataclasses.dataclass
class QuESTEnv:
    """Runtime environment handle (mesh + precision + RNG)."""

    precision: Precision
    mesh: Optional[Mesh] = None
    key: jax.Array = None  # type: ignore[assignment]
    # error-compensated scalar reductions (TwoSum cascade,
    # ops/reductions.py) — the runtime analogue of the reference's Kahan
    # summation (``QuEST_cpu_distributed.c:87-109``); restores
    # 1e-10-class totals/inner-products for single-precision registers
    compensated: bool = False

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape)) if self.mesh is not None else 1

    @property
    def rank(self) -> int:
        """Process index (0 on single-host; mirrors QuESTEnv.rank)."""
        return jax.process_index()

    @property
    def is_multihost(self) -> bool:
        """True when the mesh spans more than one controller process —
        the TPU-pod analogue of the reference's multi-node MPI run
        (``QuEST_cpu_distributed.c:128-157``). Data paths switch to
        shard-local construction + allgather reads (qureg.py) and the
        default seed is agreed by rank-0 broadcast (:meth:`seed_default`)."""
        return jax.process_count() > 1

    @property
    def num_ranks(self) -> int:
        return self.num_devices

    def sharding(self, sharded: bool = True) -> Optional[NamedSharding]:
        """NamedSharding for a packed (2, 2^N) state array: the amplitude
        axis is split on its leading (high-qubit) bits — the chunkId-prefix
        layout of ``QuEST.h:169-177`` — and the re/im plane axis is
        replicated."""
        if self.mesh is None:
            return None
        spec = PartitionSpec(None, AMP_AXIS) if sharded else PartitionSpec()
        return NamedSharding(self.mesh, spec)

    def sharding_flat(self) -> Optional[NamedSharding]:
        """NamedSharding for a flat (2^N,) amplitude vector (jit-internal
        complex form): leading bits over the mesh axis."""
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, PartitionSpec(AMP_AXIS))

    def seed(self, seeds: Sequence[int]) -> None:
        """Re-seed the measurement RNG (``seedQuEST`` ``QuEST.h:1858``)."""
        key = jax.random.key(int(seeds[0]) & 0xFFFFFFFF)
        for s in seeds[1:]:
            key = jax.random.fold_in(key, int(s) & 0xFFFFFFFF)
        self.key = key

    def seed_default(self) -> None:
        """Seed from time and pid (``seedQuESTDefault``
        ``QuEST_common.c:181-213``). Multi-host: every process must hold
        the SAME key (one logical SPMD program), so rank 0's seed is
        broadcast — the reference's ``MPI_Bcast`` of the mt19937 key
        (``QuEST_cpu_distributed.c:1318-1329``)."""
        seeds = [int(time.time() * 1e6) & 0xFFFFFFFF, os.getpid()]
        if self.is_multihost:
            from jax.experimental import multihost_utils
            seeds = [int(s) for s in
                     multihost_utils.broadcast_one_to_all(np.asarray(seeds))]
        self.seed(seeds)

    def next_key(self) -> jax.Array:
        self.key, sub = jax.random.split(self.key)
        return sub

    def sync(self) -> None:
        """Barrier analogue (``syncQuESTEnv``): SPMD programs need no explicit
        barrier; block until async dispatch drains instead."""
        jax.effects_barrier()

    def report(self) -> str:
        plats = {d.platform for d in jax.devices()}
        lines = [
            "QuEST-TPU execution environment:",
            f"  backend devices: {len(jax.devices())} ({', '.join(sorted(plats))})",
            f"  mesh: {'none (single device)' if self.mesh is None else str(self.mesh.shape)}",
            f"  precision: {self.precision.name} ({self.precision.complex_dtype})",
        ]
        return "\n".join(lines)


def default_compensated(precision: Precision) -> bool:
    """The ONE definition of the compensated-reductions default: on for
    single precision (where naive f32 accumulation falls ~5 decades
    short of the reference's 1e-10 scalar tolerance), off for double
    and the dd tiers (already exact enough). Shared by
    :func:`create_quest_env` and the router's replica-env builder
    (:func:`quest_tpu.serve.router.replica_envs`) so replica
    environments can never drift from the primary's default."""
    return precision.quest_prec == 1


def create_quest_env(
    num_devices: Optional[int] = None,
    precision: Optional[Precision] = None,
    seed: Optional[Sequence[int]] = None,
    compensated: Optional[bool] = None,
) -> QuESTEnv:
    """Create the execution environment (``createQuESTEnv`` ``QuEST.h:785``).

    ``num_devices=None`` uses all local devices when more than one is present
    (as the reference's MPI build uses all ranks), else single-device.
    ``compensated=None`` enables TwoSum-compensated scalar reductions
    automatically for single precision (where naive float32 accumulation
    falls ~5 decades short of the reference's 1e-10 tolerance) and disables
    them for double.
    """
    precision = precision or default_precision()
    if (precision.quest_prec == 4 and precision.real_dtype == "float64"
            and not jax.config.jax_enable_x64):
        raise ValueError(
            "QUAD64 needs jax_enable_x64; without it JAX silently "
            "downcasts the f64 planes and the quad tier quietly "
            "degrades — use QUAD (f32 planes) on x64-less backends")
    if compensated is None:
        compensated = default_compensated(precision)
    devices = jax.devices()
    n = len(devices) if num_devices is None else num_devices
    if n > len(devices):
        raise ValueError(f"requested {n} devices but only {len(devices)} available")
    mesh = None
    if n > 1:
        if n & (n - 1):
            raise ValueError("the device count must be a power of 2 "
                             "(amplitude sharding halves per device)")
        mesh = Mesh(np.asarray(devices[:n]), (AMP_AXIS,))
    env = QuESTEnv(precision=precision, mesh=mesh, compensated=compensated)
    if seed is not None:
        env.seed(seed)
    else:
        env.seed_default()
    return env


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Join a multi-controller (multi-host) run BEFORE creating the env —
    the analogue of ``MPI_Init`` (``QuEST_cpu_distributed.c:128-157``).

    Thin wrapper over :func:`quest_tpu.parallel.multihost.bootstrap`
    (``jax.distributed.initialize``): on TPU pods all arguments
    auto-detect from the runtime; on CPU/GPU clusters pass the
    coordinator endpoint and process coordinates. After this,
    ``jax.devices()`` spans every host's chips, ``create_quest_env()``
    meshes over all of them, and the amplitude axis shards across the pod
    with XLA collectives riding ICI/DCN — no further code changes; the
    same SPMD program runs on every process, and the layout planner
    prices each collective by the interconnect tier it crosses
    (``parallel/multihost.py`` + the two-tier
    :class:`~quest_tpu.profiling.CommCostModel`). Exercised end-to-end by
    ``tests/test_multihost.py``: 2- and 4-process coordinator-connected
    CPU runs building one global mesh (sharded circuit, psum reductions,
    broadcast seed agreement, allgathered reads)."""
    from .parallel.multihost import bootstrap
    bootstrap(coordinator_address, num_processes=num_processes,
              process_id=process_id)


def destroy_quest_env(env: QuESTEnv) -> None:
    """No-op (buffers are GC-managed); kept for API parity
    (``destroyQuESTEnv`` ``QuEST.h:795``)."""
