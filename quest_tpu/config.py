"""Precision configuration.

TPU-native analogue of the reference's compile-time precision switch
(``QuEST_precision.h:28-65``, macro ``QuEST_PREC``): instead of rebuilding the
library per precision, precision is a runtime property of the environment.

On TPU the natural dtype is complex64 (pairs of f32 riding the VPU/MXU);
complex128 is available on CPU (and via slow emulation elsewhere) for
golden-accuracy parity testing against the reference's 1e-10 tolerance.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["Precision", "SINGLE", "DOUBLE", "default_precision"]


@dataclasses.dataclass(frozen=True)
class Precision:
    """Numeric precision bundle (mirrors qreal/REAL_EPS of the reference)."""

    quest_prec: int  # 1 = single, 2 = double (reference QuEST_PREC values)
    real_dtype: jnp.dtype
    complex_dtype: jnp.dtype
    # REAL_EPS analogue (QuEST_precision.h: 1e-5 single / 1e-13 double)
    eps: float

    @property
    def name(self) -> str:
        return {1: "single", 2: "double"}[self.quest_prec]


SINGLE = Precision(1, jnp.dtype("float32"), jnp.dtype("complex64"), 1e-5)
DOUBLE = Precision(2, jnp.dtype("float64"), jnp.dtype("complex128"), 1e-13)


def default_precision() -> Precision:
    """DOUBLE when x64 is enabled (CPU test rigs), else SINGLE (TPU)."""
    return DOUBLE if jax.config.jax_enable_x64 else SINGLE
