"""Precision configuration.

TPU-native analogue of the reference's compile-time precision switch
(``QuEST_precision.h:28-65``, macro ``QuEST_PREC``): instead of rebuilding the
library per precision, precision is a runtime property of the environment.

On TPU the natural dtype is complex64 (pairs of f32 riding the VPU/MXU);
complex128 is available on CPU (and via slow emulation elsewhere) for
golden-accuracy parity testing against the reference's 1e-10 tolerance.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["Precision", "SINGLE", "DOUBLE", "QUAD", "QUAD64",
           "default_precision", "PrecisionTier", "FAST_TIER",
           "SINGLE_TIER", "DOUBLE_TIER", "QUAD_TIER", "TIER_LADDER",
           "tier_by_name"]


@dataclasses.dataclass(frozen=True)
class Precision:
    """Numeric precision bundle (mirrors qreal/REAL_EPS of the reference)."""

    quest_prec: int  # 1=single, 2=double, 4=quad (reference QuEST_PREC)
    real_dtype: jnp.dtype
    complex_dtype: jnp.dtype
    # REAL_EPS analogue (QuEST_precision.h: 1e-5 single / 1e-13 double /
    # 1e-14 quad)
    eps: float

    @property
    def name(self) -> str:
        if self.quest_prec == 4:
            # the two dd tiers have incompatible on-disk plane formats
            return "quad" if self.real_dtype == jnp.dtype("float32") \
                else "quad64"
        return {1: "single", 2: "double"}[self.quest_prec]


SINGLE = Precision(1, jnp.dtype("float32"), jnp.dtype("complex64"), 1e-5)
DOUBLE = Precision(2, jnp.dtype("float64"), jnp.dtype("complex128"), 1e-13)
# QUAD: the ``QuEST_PREC=4`` analogue for hardware without an f64 ALU —
# registers hold DOUBLE-DOUBLE amplitudes, four float planes
# ``(4, 2^n) = [re_hi, re_lo, im_hi, im_lo]`` (~48-bit significand from
# pure-f32 arithmetic; ops/doubledouble.py). ``real_dtype`` is the plane
# dtype; host-visible amplitudes combine to complex128.
QUAD = Precision(4, jnp.dtype("float32"), jnp.dtype("complex128"), 1e-13)
# QUAD64: dd over float64 planes (~106-bit significand) — the full
# quad-precision tier on x64-capable backends, REAL_EPS-class 1e-14
# (``QuEST_precision.h:53-65``). Requires jax_enable_x64.
QUAD64 = Precision(4, jnp.dtype("float64"), jnp.dtype("complex128"), 1e-14)


def default_precision() -> Precision:
    """DOUBLE when x64 is enabled (CPU test rigs), else SINGLE (TPU)."""
    return DOUBLE if jax.config.jax_enable_x64 else SINGLE


# ---------------------------------------------------------------------------
# precision tiers (the per-REQUEST performance dial; ROADMAP item 4)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class PrecisionTier:
    """One rung of the execution-precision ladder.

    Where :class:`Precision` is the REGISTER's storage format (a
    per-environment choice, the ``QuEST_PREC`` analogue), a tier is a
    per-request EXECUTION mode: it decides the matmul precision the gate
    contractions run at, whether scalar/observable reductions use the
    compensated (TwoSum/Veltkamp pair) path, and the plane dtype the
    engine computes in. The ladder is ordered by ``rank`` — higher rank
    = more accurate and slower — and the budget API
    (:func:`quest_tpu.profiling.choose_tier`) picks the LOWEST rank
    whose modeled error fits a caller-stated budget.

    ``drift_per_gate`` seeds the tier error model: the worst-case max
    amplitude deviation one gate pass adds at this tier (measured
    constants, docs/accuracy.md — the bf16 MXU figure for FAST, the f32
    rounding envelope for SINGLE).
    """

    name: str                # "fast" | "single" | "double" | "quad"
    rank: int                # ladder position (0 = fastest)
    drift_per_gate: float    # seed error-model constant (docs/accuracy.md)
    matmul_precision: str    # "default" (bf16 MXU inputs) | "highest"
    compensated: bool        # compensated (pair-path) reductions
    real_dtype: jnp.dtype    # plane dtype the tier executes in


# FAST: Precision.DEFAULT matmuls — on the TPU MXU that is ONE bf16-input
# pass where HIGHEST pays six — with bf16-split compensated f32 lane
# accumulation in the Pallas layer kernel (ops/pallas_kernels.py).
# Seeded WELL ABOVE every measured figure (3.3e-5 per lane matmul,
# 7.0e-5 per layer on r5 silicon — docs/accuracy.md) because FAST
# dispatches are not all compensated lane matmuls: plain dense gates on
# the XLA path run raw Precision.DEFAULT, whose uncompensated worst
# case approaches ~1e-3/gate (core/apply.py). 5e-4 covers both forms on
# every backend; the per-backend microbench can only tighten it.
FAST_TIER = PrecisionTier("fast", 0, 5e-4, "default", False,
                          jnp.dtype("float32"))
# SINGLE-compensated: full-f32 (HIGHEST) matmuls plus the compensated
# pair-path reductions (ops/reductions.py) for scalar observables — the
# ~1e-7/gate worst-case f32 envelope (observed ~5e-9, docs/accuracy.md).
SINGLE_TIER = PrecisionTier("single", 1, 1e-7, "highest", True,
                            jnp.dtype("float32"))
# DOUBLE: f64 planes (x64-capable backends only).
DOUBLE_TIER = PrecisionTier("double", 2, 1e-15, "highest", False,
                            jnp.dtype("float64"))
# QUAD: double-double planes (ops/doubledouble.py) — measured 6.3e-15
# over 1000 gates on dd-f32 (docs/accuracy.md), i.e. ~1e-17/gate. Rides
# the DDProgram path (static circuits), not the batched engine.
QUAD_TIER = PrecisionTier("quad", 3, 1e-17, "highest", True,
                          jnp.dtype("float32"))

TIER_LADDER = (FAST_TIER, SINGLE_TIER, DOUBLE_TIER, QUAD_TIER)


def tier_by_name(name) -> PrecisionTier:
    """Resolve a tier by its name (accepts a PrecisionTier unchanged)."""
    if isinstance(name, PrecisionTier):
        return name
    for t in TIER_LADDER:
        if t.name == str(name).lower():
            return t
    raise ValueError(f"unknown precision tier {name!r}; expected one of "
                     f"{[t.name for t in TIER_LADDER]}")
