"""Precision configuration.

TPU-native analogue of the reference's compile-time precision switch
(``QuEST_precision.h:28-65``, macro ``QuEST_PREC``): instead of rebuilding the
library per precision, precision is a runtime property of the environment.

On TPU the natural dtype is complex64 (pairs of f32 riding the VPU/MXU);
complex128 is available on CPU (and via slow emulation elsewhere) for
golden-accuracy parity testing against the reference's 1e-10 tolerance.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

__all__ = ["Precision", "SINGLE", "DOUBLE", "QUAD", "QUAD64",
           "default_precision"]


@dataclasses.dataclass(frozen=True)
class Precision:
    """Numeric precision bundle (mirrors qreal/REAL_EPS of the reference)."""

    quest_prec: int  # 1=single, 2=double, 4=quad (reference QuEST_PREC)
    real_dtype: jnp.dtype
    complex_dtype: jnp.dtype
    # REAL_EPS analogue (QuEST_precision.h: 1e-5 single / 1e-13 double /
    # 1e-14 quad)
    eps: float

    @property
    def name(self) -> str:
        if self.quest_prec == 4:
            # the two dd tiers have incompatible on-disk plane formats
            return "quad" if self.real_dtype == jnp.dtype("float32") \
                else "quad64"
        return {1: "single", 2: "double"}[self.quest_prec]


SINGLE = Precision(1, jnp.dtype("float32"), jnp.dtype("complex64"), 1e-5)
DOUBLE = Precision(2, jnp.dtype("float64"), jnp.dtype("complex128"), 1e-13)
# QUAD: the ``QuEST_PREC=4`` analogue for hardware without an f64 ALU —
# registers hold DOUBLE-DOUBLE amplitudes, four float planes
# ``(4, 2^n) = [re_hi, re_lo, im_hi, im_lo]`` (~48-bit significand from
# pure-f32 arithmetic; ops/doubledouble.py). ``real_dtype`` is the plane
# dtype; host-visible amplitudes combine to complex128.
QUAD = Precision(4, jnp.dtype("float32"), jnp.dtype("complex128"), 1e-13)
# QUAD64: dd over float64 planes (~106-bit significand) — the full
# quad-precision tier on x64-capable backends, REAL_EPS-class 1e-14
# (``QuEST_precision.h:53-65``). Requires jax_enable_x64.
QUAD64 = Precision(4, jnp.dtype("float64"), jnp.dtype("complex128"), 1e-14)


def default_precision() -> Precision:
    """DOUBLE when x64 is enabled (CPU test rigs), else SINGLE (TPU)."""
    return DOUBLE if jax.config.jax_enable_x64 else SINGLE
