"""JAX version compatibility shims.

The distributed executor is written against the modern spelling
``jax.shard_map(..., check_vma=False)``; older installed JAX versions
(e.g. 0.4.x, the version this image bakes in) only ship
``jax.experimental.shard_map.shard_map`` and call the replication-check
kwarg ``check_rep``.  This module resolves ONE ``shard_map`` callable at
import time — signature-sniffed, not version-string-matched, so
intermediate releases that renamed the kwarg before promoting the API
still resolve correctly — and every quest_tpu call site imports it from
here instead of from ``jax``.
"""

from __future__ import annotations

import inspect

import jax

__all__ = ["shard_map"]


def _resolve():
    if hasattr(jax, "shard_map"):
        fn = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as fn
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):      # C-accelerated wrapper, no sig
        params = {}
    if "check_vma" in params:
        kwarg = "check_vma"
    elif "check_rep" in params:
        kwarg = "check_rep"
    else:
        kwarg = None
    return fn, kwarg


_SHARD_MAP, _CHECK_KWARG = _resolve()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the replication-check kwarg mapped to
    whatever the installed JAX calls it (``check_vma`` on current
    releases, ``check_rep`` on 0.4.x experimental). ``check_vma=None``
    omits the kwarg entirely (the version default)."""
    kw = {}
    if check_vma is not None and _CHECK_KWARG is not None:
        kw[_CHECK_KWARG] = check_vma
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
