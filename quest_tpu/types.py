"""Public enums and exception types.

Mirrors the reference's public type surface (``QuEST.h:97`` pauliOpType and the
fatal-error channel ``QuEST_validation.c:126-137``) in Python-native form: the
overridable weak symbol ``invalidQuESTInputError`` becomes an exception class
plus a swappable module-level handler hook.
"""

from __future__ import annotations

import enum

__all__ = [
    "PauliOpType",
    "PAULI_I",
    "PAULI_X",
    "PAULI_Y",
    "PAULI_Z",
    "QuESTError",
    "invalid_quest_input_error",
    "invalidQuESTInputError",
    "set_input_error_handler",
]


class PauliOpType(enum.IntEnum):
    """Pauli operator codes (value-compatible with the reference enum)."""

    PAULI_I = 0
    PAULI_X = 1
    PAULI_Y = 2
    PAULI_Z = 3


PAULI_I = PauliOpType.PAULI_I
PAULI_X = PauliOpType.PAULI_X
PAULI_Y = PauliOpType.PAULI_Y
PAULI_Z = PauliOpType.PAULI_Z


class QuESTError(ValueError):
    """Raised on invalid user input (analogue of exitWithError, but
    catchable). ``code`` is the reference taxonomy code
    (:class:`quest_tpu.validation.ErrorCode`) when the failure came from the
    validation layer, else 0."""

    def __init__(self, message: str, func_name: str = "", code: int = 0):
        self.func_name = func_name
        self.code = code
        super().__init__(
            f"QuEST error in {func_name}: {message}" if func_name else message
        )


def _default_handler(message: str, func_name: str, code: int = 0) -> None:
    raise QuESTError(message, func_name, code)


_handler = _default_handler


def invalid_quest_input_error(message: str, func_name: str,
                              code: int = 0) -> None:
    """Dispatch an input-validation failure to the current handler.

    The reference exposes this as an overridable weak symbol
    (``QuEST_validation.c:134-137``) so embedders/tests can intercept
    validation failures; here tests can simply catch :class:`QuESTError`
    or install a custom hook via :func:`set_input_error_handler`. The
    reference requires the override not to return; if a custom handler does
    return, we still raise so invalid inputs can never reach the kernels.
    """
    if _handler is _default_handler:
        _default_handler(message, func_name, code)
    else:
        # custom handlers keep the reference's 2-arg weak-symbol signature
        _handler(message, func_name)
        raise QuESTError(message, func_name, code)


def set_input_error_handler(handler) -> None:
    """Replace the validation-failure handler (None restores the default)."""
    global _handler
    _handler = handler if handler is not None else _default_handler


# exact-name alias for the reference's overridable weak symbol
# (``invalidQuESTInputError``, ``QuEST.h:3191``) so a grep-level port of a
# reference embedder finds it under the name it knows
invalidQuESTInputError = invalid_quest_input_error
