"""Input validation layer.

Python-native port of the reference's validation taxonomy
(``QuEST_validation.c:25-124``): each check raises through
:func:`quest_tpu.types.invalid_quest_input_error`, which by default throws a
catchable :class:`~quest_tpu.types.QuESTError` (replacing the reference's
fatal ``exitWithError``; the overridable handler plays the role of the weak
``invalidQuESTInputError`` symbol).

Numerical checks (unitarity, CPTP, norms) run host-side on numpy inputs; they
guard user-supplied matrices, not traced arrays.
"""

from __future__ import annotations

import numpy as np

from .types import invalid_quest_input_error, PauliOpType

# tolerance for unitarity/CPTP/norm checks, per precision eps at call sites
_DEFAULT_EPS = 1e-10


def _fail(msg: str, func: str) -> None:
    invalid_quest_input_error(msg, func)


def validate_num_qubits(num_qubits: int, func: str) -> None:
    if num_qubits < 1:
        _fail("the register must contain at least one qubit", func)
    if num_qubits > 62:
        _fail("the number of qubits exceeds the indexable amplitude range", func)


def validate_target(num_qubits: int, target: int, func: str) -> None:
    if not 0 <= target < num_qubits:
        _fail(f"qubit index {target} is outside [0, {num_qubits})", func)


def validate_control_target(num_qubits: int, control: int, target: int, func: str) -> None:
    validate_target(num_qubits, target, func)
    validate_target(num_qubits, control, func)
    if control == target:
        _fail("the control qubit must differ from the target qubit", func)


def validate_unique_targets(num_qubits: int, q1: int, q2: int, func: str) -> None:
    validate_target(num_qubits, q1, func)
    validate_target(num_qubits, q2, func)
    if q1 == q2:
        _fail("the two target qubits must be distinct", func)


def validate_multi_targets(num_qubits: int, targets, func: str) -> None:
    if len(targets) < 1:
        _fail("at least one target qubit is required", func)
    if len(targets) > num_qubits:
        _fail("the number of targets exceeds the register size", func)
    for t in targets:
        validate_target(num_qubits, t, func)
    if len(set(targets)) != len(targets):
        _fail("target qubits must be unique", func)


def validate_multi_controls_multi_targets(num_qubits: int, controls, targets, func: str) -> None:
    validate_multi_targets(num_qubits, targets, func)
    for c in controls:
        validate_target(num_qubits, c, func)
    if len(set(controls)) != len(controls):
        _fail("control qubits must be unique", func)
    if set(controls) & set(targets):
        _fail("control qubits may not also be targets", func)


def validate_control_state(control_state, num_controls: int, func: str) -> None:
    if len(control_state) != num_controls:
        _fail("one control-state bit is required per control qubit", func)
    for b in control_state:
        if b not in (0, 1):
            _fail("control-state bits must be 0 or 1", func)


def validate_outcome(outcome: int, func: str) -> None:
    if outcome not in (0, 1):
        _fail("the measurement outcome must be 0 or 1", func)


def validate_measurement_prob(prob: float, func: str) -> None:
    if prob <= 0:
        _fail("the probability of the chosen outcome is zero; collapse is impossible", func)


def validate_state_index(num_qubits: int, state_ind: int, func: str) -> None:
    if not 0 <= state_ind < (1 << num_qubits):
        _fail(f"basis-state index {state_ind} is outside the register dimension", func)


def validate_amp_index(num_amps: int, index: int, func: str) -> None:
    if not 0 <= index < num_amps:
        _fail(f"amplitude index {index} is outside [0, {num_amps})", func)


def validate_num_amps(num_amps_total: int, start: int, num: int, func: str) -> None:
    if start < 0 or num < 0 or start + num > num_amps_total:
        _fail("the amplitude range exceeds the register dimension", func)


def validate_prob(prob: float, func: str, max_prob: float = 1.0, name: str = "probability") -> None:
    if prob < 0:
        _fail(f"the {name} must be non-negative", func)
    if prob > max_prob:
        _fail(f"the {name} exceeds its physical maximum of {max_prob}", func)


def _num_tol(eps: float, dim: int) -> float:
    """Absolute tolerance for matrix checks: the precision eps (REAL_EPS
    analogue) with headroom for accumulation over the matrix dimension."""
    return eps * dim * 10.0


def validate_unitary(u: np.ndarray, func: str, eps: float = _DEFAULT_EPS) -> None:
    u = np.asarray(u)
    d = u.shape[0]
    if u.shape != (d, d):
        _fail("the matrix is not square", func)
    if not np.allclose(u.conj().T @ u, np.eye(d), atol=_num_tol(eps, d)):
        _fail("the matrix is not unitary", func)


def validate_matrix_dim(u: np.ndarray, num_targets: int, func: str) -> None:
    d = 1 << num_targets
    u = np.asarray(u)
    if u.shape != (d, d):
        _fail(f"the matrix dimension {u.shape} does not match {num_targets} target qubits", func)


def validate_unitary_complex_pair(alpha: complex, beta: complex, func: str,
                                  eps: float = _DEFAULT_EPS) -> None:
    norm = abs(alpha) ** 2 + abs(beta) ** 2
    if abs(norm - 1.0) > _num_tol(eps, 2):
        _fail("|alpha|^2 + |beta|^2 must equal 1 for a unitary", func)


def validate_vector(v, func: str) -> None:
    if np.linalg.norm(np.asarray(v, dtype=np.float64)) < 1e-15:
        _fail("the rotation axis vector must not be the zero vector", func)


def validate_kraus_ops(ops, num_targets: int, func: str, eps: float = _DEFAULT_EPS) -> None:
    d = 1 << num_targets
    if len(ops) < 1:
        _fail("at least one Kraus operator is required", func)
    if len(ops) > d * d:
        _fail(f"a {num_targets}-qubit channel admits at most {d*d} Kraus operators", func)
    acc = np.zeros((d, d), dtype=np.complex128)
    for op in ops:
        op = np.asarray(op, dtype=np.complex128)
        if op.shape != (d, d):
            _fail("each Kraus operator must match the target dimension", func)
        acc += op.conj().T @ op
    if not np.allclose(acc, np.eye(d), atol=_num_tol(eps, d)):
        _fail("the Kraus operators do not form a completely positive "
              "trace-preserving map", func)


def validate_one_qubit_pauli_probs(prob_x: float, prob_y: float, prob_z: float,
                                   func: str) -> None:
    """Each Pauli error must be no likelier than no-error — the channel-mixing
    bound of ``validateOneQubitPauliProbs`` (``QuEST_validation.c:447-456``)."""
    for p in (prob_x, prob_y, prob_z):
        validate_prob(p, func, 1.0, "Pauli error probability")
    no_error = 1.0 - prob_x - prob_y - prob_z
    if prob_x > no_error or prob_y > no_error or prob_z > no_error:
        _fail("each Pauli error probability may not exceed the "
              "no-error probability 1-px-py-pz", func)


def validate_pauli_codes(codes, func: str) -> None:
    for c in codes:
        if int(c) not in (0, 1, 2, 3):
            _fail("Pauli codes must be 0 (I), 1 (X), 2 (Y) or 3 (Z)", func)
    _ = PauliOpType  # codes are value-compatible with the enum


def validate_num_pauli_sum_terms(n: int, func: str) -> None:
    if n < 1:
        _fail("the Pauli sum must contain at least one term", func)


def validate_density_matr(is_density: bool, func: str) -> None:
    if not is_density:
        _fail("this operation is defined only for density matrices", func)


def validate_state_vec(is_density: bool, func: str) -> None:
    if is_density:
        _fail("this operation is defined only for state-vectors", func)


def validate_matching_types(a_density: bool, b_density: bool, func: str) -> None:
    if a_density != b_density:
        _fail("the registers must both be state-vectors or both be density matrices", func)


def validate_matching_dims(a_qubits: int, b_qubits: int, func: str) -> None:
    if a_qubits != b_qubits:
        _fail("the registers must represent equal numbers of qubits", func)
