"""Input validation layer — the complete reference error taxonomy.

Python-native port of the reference's validation layer: the full 47-code
``ErrorCode`` enum (``QuEST_validation.c:25-73``) is mirrored as
:class:`ErrorCode`, every raised failure carries its code (inspect
``QuESTError.code``), and each check raises through
:func:`quest_tpu.types.invalid_quest_input_error`, which by default throws a
catchable :class:`~quest_tpu.types.QuESTError` (replacing the reference's
fatal ``exitWithError``; the overridable handler plays the role of the weak
``invalidQuESTInputError`` symbol).

Codes with no reachable failure mode in this architecture are documented in
:data:`SUBSUMED` (e.g. ``E_COMPLEX_MATRIX_NOT_INIT`` cannot occur because
numpy allocation failures raise ``MemoryError`` before the API is reached).

Numerical checks (unitarity, CPTP, norms) run host-side on numpy inputs; they
guard user-supplied matrices, not traced arrays. Their tolerance comes from
the *environment precision* (``env.precision.eps``, the REAL_EPS analogue,
``QuEST_precision.h:28-65``) — call sites must pass it; there is no module
default (VERDICT r2 Weak #7).
"""

from __future__ import annotations

import enum

import numpy as np

from .types import invalid_quest_input_error, PauliOpType


class ErrorCode(enum.IntEnum):
    """Value-compatible mirror of the reference's ErrorCode enum
    (``QuEST_validation.c:25-73``)."""

    E_SUCCESS = 0
    E_INVALID_NUM_CREATE_QUBITS = 1
    E_INVALID_QUBIT_INDEX = 2
    E_INVALID_TARGET_QUBIT = 3
    E_INVALID_CONTROL_QUBIT = 4
    E_INVALID_STATE_INDEX = 5
    E_INVALID_AMP_INDEX = 6
    E_INVALID_NUM_AMPS = 7
    E_INVALID_OFFSET_NUM_AMPS = 8
    E_TARGET_IS_CONTROL = 9
    E_TARGET_IN_CONTROLS = 10
    E_CONTROL_TARGET_COLLISION = 11
    E_QUBITS_NOT_UNIQUE = 12
    E_TARGETS_NOT_UNIQUE = 13
    E_CONTROLS_NOT_UNIQUE = 14
    E_INVALID_NUM_QUBITS = 15
    E_INVALID_NUM_TARGETS = 16
    E_INVALID_NUM_CONTROLS = 17
    E_NON_UNITARY_MATRIX = 18
    E_NON_UNITARY_COMPLEX_PAIR = 19
    E_ZERO_VECTOR = 20
    E_SYS_TOO_BIG_TO_PRINT = 21
    E_COLLAPSE_STATE_ZERO_PROB = 22
    E_INVALID_QUBIT_OUTCOME = 23
    E_CANNOT_OPEN_FILE = 24
    E_SECOND_ARG_MUST_BE_STATEVEC = 25
    E_MISMATCHING_QUREG_DIMENSIONS = 26
    E_MISMATCHING_QUREG_TYPES = 27
    E_DEFINED_ONLY_FOR_STATEVECS = 28
    E_DEFINED_ONLY_FOR_DENSMATRS = 29
    E_INVALID_PROB = 30
    E_UNNORM_PROBS = 31
    E_INVALID_ONE_QUBIT_DEPHASE_PROB = 32
    E_INVALID_TWO_QUBIT_DEPHASE_PROB = 33
    E_INVALID_ONE_QUBIT_DEPOL_PROB = 34
    E_INVALID_TWO_QUBIT_DEPOL_PROB = 35
    E_INVALID_ONE_QUBIT_PAULI_PROBS = 36
    E_INVALID_CONTROLS_BIT_STATE = 37
    E_INVALID_PAULI_CODE = 38
    E_INVALID_NUM_SUM_TERMS = 39
    E_CANNOT_FIT_MULTI_QUBIT_MATRIX = 40
    E_INVALID_UNITARY_SIZE = 41
    E_COMPLEX_MATRIX_NOT_INIT = 42
    E_INVALID_NUM_ONE_QUBIT_KRAUS_OPS = 43
    E_INVALID_NUM_TWO_QUBIT_KRAUS_OPS = 44
    E_INVALID_NUM_N_QUBIT_KRAUS_OPS = 45
    E_INVALID_KRAUS_OPS = 46
    E_MISMATCHING_NUM_TARGS_KRAUS_SIZE = 47


#: Codes with no reachable failure path in this architecture, and why.
SUBSUMED: dict[ErrorCode, str] = {
    ErrorCode.E_SUCCESS: "not an error",
    ErrorCode.E_COMPLEX_MATRIX_NOT_INIT:
        "createComplexMatrixN returns a numpy array; allocation failure "
        "raises MemoryError before any API call can receive a half-built "
        "matrix (reference: NULL real/imag pointers, "
        "QuEST_validation.c:360)",
    ErrorCode.E_SYS_TOO_BIG_TO_PRINT:
        "dead in the reference as well: no validator raises it; "
        "statevec_reportStateToScreen silently skips registers whose "
        "state vector exceeds 5 qubits (QuEST_cpu.c:1343) and this port "
        "does the same. :func:`validate_sys_printable` is provided for "
        "embedders but not wired into any API path",
    ErrorCode.E_CANNOT_FIT_MULTI_QUBIT_MATRIX:
        "the reference's swap-to-local scheme physically requires a "
        "2^k-amplitude batch to fit in one node's chunk "
        "(QuEST_validation.c:340-342); the TPU engine has no such bound — "
        "the XLA SPMD partitioner relocalises arbitrary target sets with "
        "collectives (verified by the 3-qubit-register-on-8-device golden "
        "suite where chunks hold a single amplitude). "
        ":func:`validate_fits_in_node` is provided for embedders that want "
        "reference-strict behaviour but is not wired into any API path",
}


def _fail(msg: str, func: str, code: ErrorCode = ErrorCode.E_SUCCESS) -> None:
    invalid_quest_input_error(msg, func, code=int(code))


# --------------------------------------------------------------------------
# register / index domain
# --------------------------------------------------------------------------

def validate_num_qubits(num_qubits: int, func: str) -> None:
    if num_qubits < 1:
        _fail("the register must contain at least one qubit", func,
              ErrorCode.E_INVALID_NUM_CREATE_QUBITS)
    if num_qubits > 62:
        _fail("the number of qubits exceeds the indexable amplitude range",
              func, ErrorCode.E_INVALID_NUM_CREATE_QUBITS)


def validate_target(num_qubits: int, target: int, func: str) -> None:
    if not 0 <= target < num_qubits:
        _fail(f"target qubit {target} is outside [0, {num_qubits})", func,
              ErrorCode.E_INVALID_TARGET_QUBIT)


def validate_control(num_qubits: int, control: int, func: str) -> None:
    if not 0 <= control < num_qubits:
        _fail(f"control qubit {control} is outside [0, {num_qubits})", func,
              ErrorCode.E_INVALID_CONTROL_QUBIT)


def validate_qubit_index(num_qubits: int, qubit: int, func: str) -> None:
    if not 0 <= qubit < num_qubits:
        _fail(f"qubit index {qubit} is outside [0, {num_qubits})", func,
              ErrorCode.E_INVALID_QUBIT_INDEX)


def validate_control_target(num_qubits: int, control: int, target: int,
                            func: str) -> None:
    validate_target(num_qubits, target, func)
    validate_control(num_qubits, control, func)
    if control == target:
        _fail("the control qubit must differ from the target qubit", func,
              ErrorCode.E_TARGET_IS_CONTROL)


def validate_unique_targets(num_qubits: int, q1: int, q2: int, func: str) -> None:
    validate_target(num_qubits, q1, func)
    validate_target(num_qubits, q2, func)
    if q1 == q2:
        _fail("the two target qubits must be distinct", func,
              ErrorCode.E_TARGETS_NOT_UNIQUE)


def validate_num_targets(num_qubits: int, num_targets: int, func: str) -> None:
    if not 0 < num_targets <= num_qubits:
        _fail(f"the number of target qubits must be in (0, {num_qubits}]",
              func, ErrorCode.E_INVALID_NUM_TARGETS)


def validate_num_controls(num_qubits: int, num_controls: int, func: str) -> None:
    if not 0 < num_controls < num_qubits:
        _fail(f"the number of control qubits must be in (0, {num_qubits})",
              func, ErrorCode.E_INVALID_NUM_CONTROLS)


def validate_num_qubits_in_list(num_qubits: int, count: int, func: str) -> None:
    if not 0 < count <= num_qubits:
        _fail(f"the number of qubits must be in (0, {num_qubits}]", func,
              ErrorCode.E_INVALID_NUM_QUBITS)


def validate_multi_qubits(num_qubits: int, qubits, func: str) -> None:
    """``validateMultiQubits`` (``QuEST_validation.c:311-317``) — the
    undifferentiated qubit-list form used by the multi-controlled phase
    family, where every listed qubit plays the same (control) role."""
    validate_num_qubits_in_list(num_qubits, len(qubits), func)
    for q in qubits:
        validate_qubit_index(num_qubits, q, func)
    if len(set(qubits)) != len(qubits):
        _fail("the qubits must be unique", func,
              ErrorCode.E_QUBITS_NOT_UNIQUE)


def validate_multi_targets(num_qubits: int, targets, func: str) -> None:
    validate_num_targets(num_qubits, len(targets), func)
    for t in targets:
        validate_target(num_qubits, t, func)
    if len(set(targets)) != len(targets):
        _fail("target qubits must be unique", func,
              ErrorCode.E_TARGETS_NOT_UNIQUE)


def _validate_multi_controls(num_qubits: int, controls, func: str) -> None:
    validate_num_controls(num_qubits, len(controls), func)
    for c in controls:
        validate_control(num_qubits, c, func)
    if len(set(controls)) != len(controls):
        _fail("control qubits must be unique", func,
              ErrorCode.E_CONTROLS_NOT_UNIQUE)


def validate_multi_controls_target(num_qubits: int, controls, target: int,
                                   func: str) -> None:
    """``validateMultiControlsTarget`` (``QuEST_validation.c:319-324``):
    target first, then controls, then the membership check."""
    validate_target(num_qubits, target, func)
    _validate_multi_controls(num_qubits, controls, func)
    if target in set(controls):
        _fail("the control qubits may not include the target qubit", func,
              ErrorCode.E_TARGET_IN_CONTROLS)


def validate_multi_controls_multi_targets(num_qubits: int, controls, targets,
                                          func: str) -> None:
    # controls are validated before targets, as in the reference
    # (validateMultiControlsMultiTargets, QuEST_validation.c:326-333)
    _validate_multi_controls(num_qubits, controls, func)
    validate_multi_targets(num_qubits, targets, func)
    if set(controls) & set(targets):
        _fail("control and target qubits must be disjoint", func,
              ErrorCode.E_CONTROL_TARGET_COLLISION)


def validate_control_state(control_state, num_controls: int, func: str) -> None:
    if len(control_state) != num_controls:
        _fail("one control-state bit is required per control qubit", func,
              ErrorCode.E_INVALID_CONTROLS_BIT_STATE)
    for b in control_state:
        if b not in (0, 1):
            _fail("control-state bits must be 0 or 1", func,
                  ErrorCode.E_INVALID_CONTROLS_BIT_STATE)


def validate_state_index(num_qubits: int, state_ind: int, func: str) -> None:
    if not 0 <= state_ind < (1 << num_qubits):
        _fail(f"basis-state index {state_ind} is outside the register "
              f"dimension", func, ErrorCode.E_INVALID_STATE_INDEX)


def validate_amp_index(num_amps: int, index: int, func: str) -> None:
    if not 0 <= index < num_amps:
        _fail(f"amplitude index {index} is outside [0, {num_amps})", func,
              ErrorCode.E_INVALID_AMP_INDEX)


def validate_num_amps(num_amps_total: int, start: int, num: int, func: str) -> None:
    """``validateNumAmps`` (``QuEST_validation.c:260-265``): start index in
    range, count in range, and the window must fit from the offset."""
    validate_amp_index(num_amps_total, start, func)
    if not 0 <= num <= num_amps_total:
        _fail("the number of amplitudes must be in [0, the register "
              "dimension]", func, ErrorCode.E_INVALID_NUM_AMPS)
    if start + num > num_amps_total:
        _fail("more amplitudes given than exist in the register from the "
              "given starting index", func,
              ErrorCode.E_INVALID_OFFSET_NUM_AMPS)


# --------------------------------------------------------------------------
# measurement / probabilities
# --------------------------------------------------------------------------

def validate_outcome(outcome: int, func: str) -> None:
    if outcome not in (0, 1):
        _fail("the measurement outcome must be 0 or 1", func,
              ErrorCode.E_INVALID_QUBIT_OUTCOME)


def validate_measurement_prob(prob: float, eps: float, func: str) -> None:
    """``validateMeasurementProb`` (``QuEST_validation.c:390-392``): the
    outcome probability must exceed REAL_EPS, not merely zero — collapse
    renormalises by 1/prob, which is numerically meaningless below eps."""
    if not prob > eps:
        _fail("the probability of the chosen outcome is zero; collapse is "
              "impossible", func, ErrorCode.E_COLLAPSE_STATE_ZERO_PROB)


def validate_prob(prob: float, func: str, max_prob: float = 1.0,
                  name: str = "probability",
                  code: ErrorCode | None = None) -> None:
    # the reference checks the [0,1] bound first (validateProb,
    # QuEST_validation.c:410-412), then the channel-specific ceiling
    # (callers pass the ceiling's code explicitly)
    if not 0.0 <= prob <= 1.0:
        _fail(f"the {name} must lie in [0, 1]", func,
              ErrorCode.E_INVALID_PROB)
    if prob > max_prob:
        _fail(f"the {name} exceeds its physical maximum of {max_prob}",
              func, code or ErrorCode.E_INVALID_PROB)


def validate_norm_probs(prob1: float, prob2: float, eps: float,
                        func: str) -> None:
    """``validateNormProbs`` (``QuEST_validation.c:414-420``)."""
    validate_prob(prob1, func)
    validate_prob(prob2, func)
    if abs(1.0 - (prob1 + prob2)) >= eps:
        _fail("the probabilities must sum to ~1", func,
              ErrorCode.E_UNNORM_PROBS)


def validate_one_qubit_pauli_probs(prob_x: float, prob_y: float, prob_z: float,
                                   func: str) -> None:
    """Each Pauli error must be no likelier than no-error — the channel-mixing
    bound of ``validateOneQubitPauliProbs`` (``QuEST_validation.c:447-456``)."""
    for p in (prob_x, prob_y, prob_z):
        validate_prob(p, func, 1.0, "Pauli error probability")
    no_error = 1.0 - prob_x - prob_y - prob_z
    if prob_x > no_error or prob_y > no_error or prob_z > no_error:
        _fail("each Pauli error probability may not exceed the "
              "no-error probability 1-px-py-pz", func,
              ErrorCode.E_INVALID_ONE_QUBIT_PAULI_PROBS)


def validate_partial_pauli_probs(statics, func: str) -> None:
    """The record-time-enforceable piece of the reference's pairwise
    bound (each prob <= 1-px-py-pz, ``QuEST_validation.c:447``) when some
    channel components are run-time Params: a bound component can only
    LOWER the no-error probability, so any static prob already exceeding
    ``1 - sum(statics)`` (the Param-at-zero best case) can never satisfy
    the reference for any bound value and is rejected now instead of
    surfacing as NaN planes at run time."""
    total = sum(statics)
    for v in statics:
        if v > 1.0 - total:
            _fail("a static Pauli error probability exceeds the best-case "
                  "no-error probability 1-(sum of static probabilities); "
                  "no run-time value of the bound component(s) can make "
                  "this channel valid", func,
                  ErrorCode.E_INVALID_ONE_QUBIT_PAULI_PROBS)


# --------------------------------------------------------------------------
# matrices / operators (numeric, env-precision tolerance)
# --------------------------------------------------------------------------

def _num_tol(eps: float, dim: int) -> float:
    """Absolute tolerance for matrix checks: the precision eps (REAL_EPS
    analogue) with headroom for accumulation over the matrix dimension."""
    return eps * dim * 10.0


def validate_unitary(u: np.ndarray, func: str, eps: float) -> None:
    u = np.asarray(u)
    d = u.shape[0]
    if u.ndim != 2 or u.shape != (d, d):
        _fail("the matrix is not square", func,
              ErrorCode.E_INVALID_UNITARY_SIZE)
    if not np.allclose(u.conj().T @ u, np.eye(d), atol=_num_tol(eps, d)):
        _fail("the matrix is not unitary", func,
              ErrorCode.E_NON_UNITARY_MATRIX)


def validate_matrix_dim(u: np.ndarray, num_targets: int, func: str) -> None:
    d = 1 << num_targets
    u = np.asarray(u)
    if u.shape != (d, d):
        _fail(f"the matrix dimension {u.shape} does not match "
              f"{num_targets} target qubits", func,
              ErrorCode.E_INVALID_UNITARY_SIZE)


def validate_unitary_complex_pair(alpha: complex, beta: complex, func: str,
                                  eps: float) -> None:
    norm = abs(alpha) ** 2 + abs(beta) ** 2
    if abs(norm - 1.0) > _num_tol(eps, 2):
        _fail("|alpha|^2 + |beta|^2 must equal 1 for a unitary", func,
              ErrorCode.E_NON_UNITARY_COMPLEX_PAIR)


def validate_vector(v, func: str, eps: float) -> None:
    """``validateVector`` (``QuEST_validation.c:374-376``): magnitude must
    exceed the environment REAL_EPS."""
    if not np.linalg.norm(np.asarray(v, dtype=np.float64)) > eps:
        _fail("the rotation axis vector must not be the zero vector", func,
              ErrorCode.E_ZERO_VECTOR)


def validate_fits_in_node(num_amps_per_chunk: int, num_targets: int,
                          func: str) -> None:
    """``validateMultiQubitMatrixFitsInNode`` (``QuEST_validation.c:340-342``):
    a k-target dense update gathers 2^k-amplitude batches; in the reference
    every batch must lie within one node's chunk. NOT wired into the API
    paths here (see :data:`SUBSUMED`): the XLA partitioner has no such
    limit. Available for embedders wanting reference-strict checking."""
    if num_amps_per_chunk < (1 << num_targets):
        _fail(f"the {num_targets}-target matrix cannot fit: amplitude "
              f"batches of 2^{num_targets} exceed one device's "
              f"{num_amps_per_chunk}-amplitude shard", func,
              ErrorCode.E_CANNOT_FIT_MULTI_QUBIT_MATRIX)


def validate_kraus_ops(ops, num_targets: int, func: str, eps: float) -> None:
    d = 1 << num_targets
    count_code = {1: ErrorCode.E_INVALID_NUM_ONE_QUBIT_KRAUS_OPS,
                  2: ErrorCode.E_INVALID_NUM_TWO_QUBIT_KRAUS_OPS}.get(
        num_targets, ErrorCode.E_INVALID_NUM_N_QUBIT_KRAUS_OPS)
    if len(ops) < 1:
        _fail("at least one Kraus operator is required", func, count_code)
    if len(ops) > d * d:
        _fail(f"a {num_targets}-qubit channel admits at most {d*d} Kraus "
              f"operators", func, count_code)
    acc = np.zeros((d, d), dtype=np.complex128)
    for op in ops:
        op = np.asarray(op, dtype=np.complex128)
        if op.shape != (d, d):
            _fail("every Kraus operator must act on the same number of "
                  "qubits as the number of targets", func,
                  ErrorCode.E_MISMATCHING_NUM_TARGS_KRAUS_SIZE)
        acc += op.conj().T @ op
    if not np.allclose(acc, np.eye(d), atol=_num_tol(eps, d)):
        _fail("the Kraus operators do not form a completely positive "
              "trace-preserving map", func, ErrorCode.E_INVALID_KRAUS_OPS)


def validate_pauli_codes(codes, func: str) -> None:
    for c in codes:
        if int(c) not in (0, 1, 2, 3):
            _fail("Pauli codes must be 0 (I), 1 (X), 2 (Y) or 3 (Z)", func,
                  ErrorCode.E_INVALID_PAULI_CODE)
    _ = PauliOpType  # codes are value-compatible with the enum


def validate_num_pauli_sum_terms(n: int, func: str) -> None:
    if n < 1:
        _fail("the Pauli sum must contain at least one term", func,
              ErrorCode.E_INVALID_NUM_SUM_TERMS)


# --------------------------------------------------------------------------
# register kinds / pairings / IO
# --------------------------------------------------------------------------

def validate_density_matr(is_density: bool, func: str) -> None:
    if not is_density:
        _fail("this operation is defined only for density matrices", func,
              ErrorCode.E_DEFINED_ONLY_FOR_DENSMATRS)


def validate_state_vec(is_density: bool, func: str) -> None:
    if is_density:
        _fail("this operation is defined only for state-vectors", func,
              ErrorCode.E_DEFINED_ONLY_FOR_STATEVECS)


def validate_second_qureg_state_vec(is_density: bool, func: str) -> None:
    """``validateSecondQuregStateVec`` (``QuEST_validation.c:402-404``)."""
    if is_density:
        _fail("the second register must be a state-vector", func,
              ErrorCode.E_SECOND_ARG_MUST_BE_STATEVEC)


def validate_matching_types(a_density: bool, b_density: bool, func: str) -> None:
    if a_density != b_density:
        _fail("the registers must both be state-vectors or both be density "
              "matrices", func, ErrorCode.E_MISMATCHING_QUREG_TYPES)


def validate_matching_dims(a_qubits: int, b_qubits: int, func: str) -> None:
    if a_qubits != b_qubits:
        _fail("the registers must represent equal numbers of qubits", func,
              ErrorCode.E_MISMATCHING_QUREG_DIMENSIONS)


def validate_matching_precision(a_prec: int, b_prec: int, func: str) -> None:
    """Framework extension (no reference analogue — a QuEST build is one
    precision throughout, `QuEST_precision.h:28-65`): register-pair
    kernels assume both operands share a plane layout, and a (2,N)
    native-tier partner inside a (4,N) quad-tier op would fail only later
    with an unrelated shape error (advisor r4)."""
    if a_prec != b_prec:
        _fail("the registers must share a precision tier (QUEST_PREC "
              f"{a_prec} vs {b_prec})", func,
              ErrorCode.E_MISMATCHING_QUREG_TYPES)


def validate_sys_printable(num_qubits: int, func: str) -> None:
    """``E_SYS_TOO_BIG_TO_PRINT`` (``QuEST_validation.c:97``): terminal
    report functions refuse registers above 5 qubits."""
    if num_qubits > 5:
        _fail("cannot print systems greater than 5 qubits", func,
              ErrorCode.E_SYS_TOO_BIG_TO_PRINT)


def validate_file_opened(opened: bool, func: str) -> None:
    if not opened:
        _fail("could not open file", func, ErrorCode.E_CANNOT_OPEN_FILE)


def validate_prob_sum(total: float, context: str) -> None:
    """The statically-known error probabilities of a channel must not
    already exceed 1 (the per-component checks cannot see their sum)."""
    if total > 1.0:
        _fail(f"static error probabilities sum to {total:g} > 1",
              context, ErrorCode.E_INVALID_PROB)
