"""Checkpoint-backed segment recovery for long executions.

``checkpoint.py`` can already save/restore a register onto any mesh
shape, but nothing in the execution path ever used it — a transient
fault (or NaN poisoning) 90% through a long run threw the whole
computation away. Here:

- :func:`checkpointed_run` splits a recorded :class:`Circuit` into
  segments, snapshots the register between them (via
  :mod:`quest_tpu.checkpoint` — orbax when available, ``.npz``
  otherwise), and on a transient/poison fault restores the LAST GOOD
  snapshot and re-executes only the failed segment (bounded restart
  budget; fatal caller errors re-raise immediately);
- :func:`checkpointed_sweep` does the same for the batched engine along
  the BATCH axis: row segments execute through ``CompiledCircuit.
  sweep``, completed segments append to an on-disk ``.npz`` progress
  file, and a faulted (or NaN-screened) segment re-executes without
  touching finished rows. The progress file makes the sweep resumable
  across PROCESS restarts too (``resume=True`` picks up where a killed
  run stopped, guarded by a parameter-matrix digest).

Both return recovery accounting (segments run, restarts, checkpoint
count) so chaos tests can assert the machinery actually engaged.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tempfile
import time
from typing import Callable, Optional

import numpy as np

from .health import HealthConfig, check_planes, bad_plane_rows, NumericalFault
from .recovery import classify, FATAL

__all__ = ["split_circuit", "checkpointed_run", "checkpointed_sweep",
           "opt_progress_save", "opt_progress_load",
           "dyn_progress_save", "dyn_progress_load"]


def split_circuit(circuit, num_segments: int) -> list:
    """Slice a recorded circuit into ``num_segments`` contiguous
    sub-circuits (op granularity, even split; empty tails dropped).
    Every sub-circuit carries the FULL parameter registry, so one
    ``params`` dict drives all segments."""
    from ..circuits import Circuit
    if num_segments < 1:
        raise ValueError("num_segments must be >= 1")
    ops = list(circuit.ops)
    num_segments = min(num_segments, max(1, len(ops)))
    per = -(-len(ops) // num_segments)       # ceil
    out = []
    for lo in range(0, len(ops), per):
        seg = Circuit(circuit.num_qubits)
        seg.ops = ops[lo:lo + per]
        seg._params = list(circuit._params)
        out.append(seg)
    return out or [circuit]


def _snap_path(ckpt_dir: str, k: int) -> str:
    return os.path.join(ckpt_dir, f"seg-{k:04d}")


def checkpointed_run(circuit, qureg, params: Optional[dict] = None, *,
                     num_segments: int = 4, ckpt_dir: Optional[str] = None,
                     max_restarts: int = 3,
                     health: Optional[HealthConfig] = None,
                     keep_checkpoints: bool = False, **compile_kwargs
                     ) -> dict:
    """Run ``circuit`` on ``qureg`` in checkpointed segments.

    Each segment compiles against ``qureg.env`` and runs through the
    normal compiled path; the register is snapshotted before segment 0
    and after every completed segment. A transient executor fault (see
    :func:`quest_tpu.resilience.recovery.classify`) or a failed
    inter-segment health check restores the last good snapshot and
    re-executes the segment, up to ``max_restarts`` total; fatal errors
    re-raise with the snapshot intact. ``health`` (a
    :class:`HealthConfig`) enables an invariant check after EVERY
    segment regardless of the global cadence.

    Returns ``{"segments", "restarts", "checkpoints", "ckpt_dir"}``
    (``ckpt_dir`` survives only with ``keep_checkpoints=True``)."""
    from .. import checkpoint as ckpt
    own_dir = ckpt_dir is None
    if own_dir:
        ckpt_dir = tempfile.mkdtemp(prefix="quest_tpu_segrun_")
    os.makedirs(ckpt_dir, exist_ok=True)
    segs = split_circuit(circuit, num_segments)
    compiled = [s.compile(qureg.env, **compile_kwargs) for s in segs]
    restarts = 0
    checkpoints = 0
    try:
        ckpt.save(qureg, _snap_path(ckpt_dir, 0))
        checkpoints += 1
        k = 0
        while k < len(compiled):
            try:
                compiled[k].run(qureg, params)
                if health is not None:
                    nq = qureg.num_qubits_represented
                    qureg.state = check_planes(
                        qureg.state, is_density=qureg.is_density_matrix,
                        num_qubits=nq, config=health,
                        where=f"segment {k}")
            # quest: allow-broad-except(classified barrier: classify()
            # re-raises FATAL; everything else restores the last good
            # snapshot and re-executes the segment)
            except Exception as e:
                if classify(e) == FATAL or restarts >= max_restarts:
                    raise
                restarts += 1
                ckpt.load(qureg, _snap_path(ckpt_dir, k))
                continue                      # re-execute this segment
            k += 1
            ckpt.save(qureg, _snap_path(ckpt_dir, k))
            checkpoints += 1
    finally:
        if not keep_checkpoints:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
    return {"segments": len(compiled), "restarts": restarts,
            "checkpoints": checkpoints,
            "ckpt_dir": ckpt_dir if keep_checkpoints else None}


def _pm_digest(pm: np.ndarray) -> str:
    return hashlib.sha256(
        np.ascontiguousarray(pm, dtype=np.float64).tobytes()).hexdigest()


# ---------------------------------------------------------------------------
# optimizer-in-the-loop progress (serve/optimize.py; ISSUE 15)
# ---------------------------------------------------------------------------
#
# The optimization handle checkpoints every completed iterate the same
# way checkpointed_sweep checkpoints row segments: one atomic .npz
# (checkpoint.atomic_savez — a crash mid-write leaves the previous
# progress whole) guarded by a PROBLEM digest, so a resumed run
# continues a killed optimization only when the circuit + observables +
# optimizer configuration actually match. Mismatch or torn files mean
# "start clean", never a crash and never the wrong problem's iterates.


def opt_progress_save(path: str, *, digest: str, iteration: int,
                      x: np.ndarray, value: float,
                      opt_state: Optional[dict] = None) -> None:
    """Atomically persist one completed optimizer iterate: the iterate
    index, the parameter vector, its measured objective value, and the
    optimizer's own state arrays (Adam moments etc., saved under
    ``opt_<name>`` keys)."""
    from .. import checkpoint as ckpt
    arrays = {"digest": np.asarray(digest),
              "iteration": np.asarray(int(iteration)),
              "x": np.ascontiguousarray(x, dtype=np.float64),
              "value": np.asarray(float(value))}
    for k, v in (opt_state or {}).items():
        arrays[f"opt_{k}"] = np.asarray(v)
    ckpt.atomic_savez(path, **arrays)


def opt_progress_load(path: str, digest: str) -> Optional[dict]:
    """Read a saved optimizer iterate back, or None when the file is
    missing, torn, or belongs to a different problem (digest
    mismatch — silently resuming someone else's iterates would walk
    the WRONG energy surface). Returns ``{"iteration", "x", "value",
    "opt_state"}``."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path, allow_pickle=False) as f:
            if str(f["digest"]) != digest:
                return None
            out = {"iteration": int(f["iteration"]),
                   "x": np.asarray(f["x"], dtype=np.float64),
                   "value": float(f["value"]),
                   "opt_state": {k[len("opt_"):]: np.asarray(f[k])
                                 for k in f.files
                                 if k.startswith("opt_")}}
        return out
    # quest: allow-broad-except(torn-archive boundary: a corrupt
    # progress file must mean "start clean", never a crash)
    except Exception:
        return None


def dyn_progress_save(path: str, *, digest: str, segment: int,
                      planes: np.ndarray, energies: np.ndarray,
                      welford: np.ndarray,
                      residual: Optional[float] = None) -> None:
    """Atomically persist one completed Hamiltonian-dynamics SEGMENT
    (an ``evolve``/``ground_state`` run's checkpoint boundary): the
    segment index, the packed ``(2, 2^n)`` state planes the next
    segment seeds from, the per-step energies accumulated so far, the
    pooled Welford ``(count, mean, M2)`` carry, and (ground runs) the
    last device-computed convergence residual. The planes ARE the
    resume state — a run killed mid-segment restarts bit-exactly from
    here, because segment boundaries are the only host-visible points
    of the whole evolution."""
    from .. import checkpoint as ckpt
    arrays = {"digest": np.asarray(digest),
              "segment": np.asarray(int(segment)),
              "planes": np.ascontiguousarray(planes, dtype=np.float64),
              "energies": np.ascontiguousarray(energies,
                                               dtype=np.float64),
              "welford": np.ascontiguousarray(welford,
                                              dtype=np.float64)}
    if residual is not None:
        arrays["residual"] = np.asarray(float(residual))
    ckpt.atomic_savez(path, **arrays)


def dyn_progress_load(path: str, digest: str) -> Optional[dict]:
    """Read a saved dynamics segment back, or None when the file is
    missing, torn, or belongs to a different run (digest mismatch — a
    different Hamiltonian, spec contract, start state, or tier must
    start clean, never continue someone else's trajectory). Returns
    ``{"segment", "planes", "energies", "welford", "residual"}``."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path, allow_pickle=False) as f:
            if str(f["digest"]) != digest:
                return None
            out = {"segment": int(f["segment"]),
                   "planes": np.asarray(f["planes"], dtype=np.float64),
                   "energies": np.asarray(f["energies"],
                                          dtype=np.float64),
                   "welford": np.asarray(f["welford"],
                                         dtype=np.float64),
                   "residual": (float(f["residual"])
                                if "residual" in f.files else None)}
        return out
    # quest: allow-broad-except(torn-archive boundary: a corrupt
    # progress file must mean "start clean", never a crash)
    except Exception:
        return None


def checkpointed_sweep(cc, param_matrix, *, segment_rows: int = 64,
                       ckpt_path: Optional[str] = None,
                       max_restarts: int = 3, resume: bool = True,
                       keep_checkpoint: bool = False,
                       yield_to: Optional[Callable[[], bool]] = None,
                       yield_hold_s: float = 5.0):
    """A :meth:`CompiledCircuit.sweep` that survives faults and process
    restarts: the ``(B, P)`` parameter matrix executes in row segments
    of ``segment_rows``, each completed segment's planes are written to
    their own ``.npy`` sidecar next to the ``.npz`` metadata file at
    ``ckpt_path`` (per-segment I/O stays O(segment), not O(rows done)),
    and a faulted or NaN-screened segment re-executes from the last
    good row (bounded by ``max_restarts``). With ``resume=True`` an
    existing progress file whose parameter digest matches continues
    where it stopped.

    ``yield_to`` enables cooperative preemption at the segment
    boundary (the checkpoint boundary, so a preempted sweep that dies
    mid-hold still resumes bit-exactly): a zero-argument callable —
    e.g. a :class:`~quest_tpu.serve.SimulationService`'s
    ``interactive_pressure`` — polled before each segment; while it
    returns truthy the sweep holds the mesh for the interactive burst,
    at most ``yield_hold_s`` seconds per preemption.

    Returns ``(planes, stats)``: the full ``(B, 2, 2^n)`` result and
    ``{"segments", "restarts", "resumed_rows", "preemptions"}``."""
    from .. import checkpoint as ckpt
    pm = np.asarray(param_matrix, dtype=np.float64)
    if pm.ndim != 2:
        raise ValueError(f"param_matrix must be 2-D; got shape {pm.shape}")
    if segment_rows < 1:
        raise ValueError("segment_rows must be >= 1")
    B = pm.shape[0]
    own_path = ckpt_path is None
    if own_path:
        fd, ckpt_path = tempfile.mkstemp(suffix=".npz",
                                         prefix="quest_tpu_segsweep_")
        os.close(fd)
        os.unlink(ckpt_path)      # mkstemp created it; savez rewrites
    elif not ckpt_path.endswith(".npz"):
        # np.savez appends ".npz" to a bare path; normalize up front or
        # the resume check and cleanup would look at the wrong file
        ckpt_path += ".npz"

    def _seg_path(i: int) -> str:
        return f"{ckpt_path}.seg{i:04d}.npy"

    def _cleanup(n_segs: int) -> None:
        for p in [ckpt_path] + [_seg_path(i) for i in range(n_segs)]:
            try:
                os.unlink(p)
            except OSError:
                pass

    digest = _pm_digest(pm)
    done = 0
    chunks: list = []
    n_saved = 0
    if resume and os.path.exists(ckpt_path):
        try:
            with np.load(ckpt_path, allow_pickle=False) as f:
                # a digest mismatch silently restarting would return
                # planes for the WRONG parameters; start clean instead
                if str(f["digest"]) == digest and int(f["batch"]) == B:
                    done = int(f["done"])
                    n_saved = int(f["segments"])
        # quest: allow-broad-except(torn-archive boundary: a corrupt
        # progress file must mean "start clean", never a crash)
        except Exception:
            # torn/truncated archive (crash mid-write before the atomic
            # rename landed, or pre-atomic leftovers): a corrupt
            # progress file must mean "start clean", never a crash here
            done, n_saved = 0, 0
        try:
            chunks = [np.load(_seg_path(i)) for i in range(n_saved)]
        except (OSError, ValueError):
            done, n_saved, chunks = 0, 0, []   # sidecars gone/torn: restart
        if chunks and sum(c.shape[0] for c in chunks) != done:
            done, n_saved, chunks = 0, 0, []   # torn progress: restart
    resumed = done
    restarts = 0
    segments = 0
    preemptions = 0
    try:
        while done < B:
            if yield_to is not None and yield_to():
                # segment boundary == checkpoint boundary: the hold
                # can't corrupt progress, only delay it
                preemptions += 1
                t0 = time.monotonic()
                while (time.monotonic() - t0 < yield_hold_s
                       and yield_to()):
                    time.sleep(2e-3)
            hi = min(B, done + segment_rows)
            try:
                planes = np.asarray(cc.sweep(pm[done:hi]))
                bad = bad_plane_rows(planes)
                if bad.size:
                    raise NumericalFault(
                        f"non-finite planes in sweep rows "
                        f"{[int(done + r) for r in bad]}", kind="nan",
                        rows=tuple(int(done + r) for r in bad))
            # quest: allow-broad-except(classified barrier: classify()
            # re-raises FATAL; transient faults re-execute the segment
            # from the on-disk progress file)
            except Exception as e:
                if classify(e) == FATAL or restarts >= max_restarts:
                    raise
                restarts += 1
                continue                      # re-execute this segment
            segments += 1
            chunks.append(planes)
            done = hi
            np.save(_seg_path(n_saved), planes)
            n_saved += 1
            # atomic: the metadata commits AFTER its sidecar exists, and
            # a crash mid-write leaves the previous progress file whole
            # (a torn .npz would otherwise poison the next resume)
            ckpt.atomic_savez(ckpt_path, done=done, batch=B,
                              digest=digest, segments=n_saved)
        out = np.concatenate(chunks, axis=0) if chunks \
            else np.zeros((0,), dtype=np.float64)
    finally:
        if own_path and not keep_checkpoint:
            _cleanup(n_saved)
    if not own_path and not keep_checkpoint:
        _cleanup(n_saved)
    return out, {"segments": segments, "restarts": restarts,
                 "resumed_rows": resumed, "preemptions": preemptions}
