"""quest_tpu.resilience — fault-tolerant execution.

The failure modes a production simulator meets at scale (ROADMAP north
star; mpiQulacs arXiv:2203.16044, QuEST arXiv:1802.08032), made
testable and survivable:

- :mod:`~quest_tpu.resilience.faults` — deterministic, seedable fault
  injection at the dispatch boundaries (transient errors, simulated
  OOM, NaN poisoning, slow-device stalls);
- :mod:`~quest_tpu.resilience.health` — cheap on-device numerical
  invariant checks (NaN/Inf, norm drift, density trace) raising a typed
  :class:`NumericalFault` or renormalizing in the opt-in degraded mode;
- :mod:`~quest_tpu.resilience.recovery` — the typed exception
  classifier, retry backoff, and per-program circuit breaker the
  serving runtime's recovery path runs on;
- :mod:`~quest_tpu.resilience.segments` — checkpoint-backed segment
  recovery for long runs and sweeps (snapshots via
  :mod:`quest_tpu.checkpoint`, re-execution from the last good
  segment, process-restart resumability).

See ``docs/tpu.md`` ("Fault tolerance & health checks").
"""

from .faults import (FaultInjector, FaultSpec, InjectedFault, SimulatedOOM,
                     SITES as FAULT_SITES, REPLICA_KINDS,
                     active as active_injector, fire, fire_router, inject,
                     install, uninstall)
from .health import (HealthConfig, NumericalFault, check_planes, configure,
                     get_config, guarded, health_stats, reset_stats)
from .recovery import (FATAL, POISON, TRANSIENT, AutoscalePolicy,
                       CircuitBreaker, ResiliencePolicy,
                       SupervisorPolicy, classify)

__all__ = [
    # faults
    "FaultInjector", "FaultSpec", "InjectedFault", "SimulatedOOM",
    "FAULT_SITES", "REPLICA_KINDS", "inject", "install", "uninstall",
    "active_injector", "fire", "fire_router",
    # health
    "HealthConfig", "NumericalFault", "check_planes", "configure",
    "get_config", "guarded", "health_stats", "reset_stats",
    # recovery
    "ResiliencePolicy", "SupervisorPolicy", "AutoscalePolicy",
    "CircuitBreaker", "classify",
    "TRANSIENT", "POISON", "FATAL",
    # segments (lazy — they import circuits/checkpoint)
    "split_circuit", "checkpointed_run", "checkpointed_sweep",
]

_SEGMENT_NAMES = {"split_circuit", "checkpointed_run", "checkpointed_sweep"}


def __getattr__(name):
    # segments imports quest_tpu.circuits; loading it lazily keeps this
    # package importable from inside circuits.py (the fault hooks)
    if name in _SEGMENT_NAMES:
        from . import segments
        return getattr(segments, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
