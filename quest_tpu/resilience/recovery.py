"""Recovery policy: fault classification, backoff, and circuit breaking.

The serving runtime's original fault story was one blind ``except
Exception`` retry — a ``ValueError`` burned the retry budget exactly
like a genuine executor hiccup, and a persistently broken program
re-failed every batch forever. This module is the typed replacement:

- :func:`classify` splits exceptions into **transient** (retry may
  succeed: runtime/OOM/timeout shapes, injected faults), **poison**
  (:class:`~quest_tpu.resilience.health.NumericalFault` — the result is
  numerically wrong; retrying the same binding is pointless, the
  request gets a typed failure), and **fatal** (caller errors —
  ``ValueError``/``TypeError``/validation ``QuESTError`` — fail fast
  with the ORIGINAL exception, never burn a retry);
- :class:`ResiliencePolicy` is the serving config surface: retry
  backoff (exponential + seeded jitter), circuit-breaker thresholds,
  quarantine, output guarding, degraded sequential mode, and the
  dispatcher watchdog timeout;
- :class:`CircuitBreaker` trips per compiled program after
  ``threshold`` failures inside ``window_s``, fast-failing new batches
  for ``cooldown_s`` (then half-opens: one probe batch decides).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Optional

from .faults import InjectedFault, SimulatedOOM
from .health import NumericalFault

__all__ = ["TRANSIENT", "POISON", "FATAL", "PRECISION", "classify",
           "ResiliencePolicy", "SupervisorPolicy", "AutoscalePolicy",
           "CircuitBreaker"]

TRANSIENT = "transient"
POISON = "poison"
FATAL = "fatal"
# the precision-tier fidelity monitor's class (NumericalFault with
# kind="precision"): the result drifted past the TIER's error budget —
# retrying the same rung is pointless, but unlike POISON the request is
# salvageable: the recovery policy re-executes it one tier UP the
# ladder (bounded by the top available rung)
PRECISION = "precision"

# caller errors: retrying cannot help and hides the bug from the caller
_FATAL_TYPES = (ValueError, TypeError, KeyError, IndexError,
                AttributeError, AssertionError, NotImplementedError,
                ArithmeticError)


def classify(exc: BaseException) -> str:
    """``"transient"`` | ``"poison"`` | ``"fatal"`` for one executor
    exception. Unknown ``Exception`` subclasses default to transient —
    the runtime's failure modes (XLA ``XlaRuntimeError``, RPC resets on
    tunneled backends) are RuntimeError-shaped, while the fatal set is
    the closed family of caller errors."""
    if isinstance(exc, NumericalFault):
        return PRECISION if exc.kind == "precision" else POISON
    if isinstance(exc, (InjectedFault, SimulatedOOM)):
        return TRANSIENT
    if isinstance(exc, _FATAL_TYPES):
        return FATAL
    return TRANSIENT


@dataclasses.dataclass(frozen=True)
class ResiliencePolicy:
    """The serving runtime's fault-tolerance knobs (one object so the
    ``SimulationService`` constructor doesn't sprout ten parameters).

    Backoff for retry attempt k (1-based) is
    ``min(backoff_cap_s, backoff_base_s * 2^(k-1))`` scaled by a seeded
    jitter in ``[1, 1 + backoff_jitter]`` — retried requests re-enter
    the queue after the delay and may coalesce differently.
    ``degrade_after`` consecutive faulted dispatches of one program put
    it in sequential per-request mode for ``degrade_cooldown_s`` (a
    poisoned batch member can't keep failing its companions);
    ``watchdog_timeout_s`` bounds how long the dispatcher may go
    without a heartbeat before the watchdog thread counts a stall
    (0 disables the thread). ``escalate_tiers`` gates the precision-
    tier recovery move: a request whose result violates its tier's
    runtime fidelity tolerance re-executes one tier up the ladder
    (off: the violation fails typed like any poison)."""

    backoff_base_s: float = 2e-3
    backoff_cap_s: float = 0.25
    backoff_jitter: float = 0.25
    seed: int = 0
    breaker_threshold: int = 5
    breaker_window_s: float = 30.0
    breaker_cooldown_s: float = 2.0
    quarantine: bool = True
    guard_outputs: bool = True
    degrade_after: int = 3
    degrade_cooldown_s: float = 5.0
    watchdog_timeout_s: float = 30.0
    escalate_tiers: bool = True

    def __post_init__(self):
        if self.backoff_base_s < 0 or self.backoff_cap_s < 0:
            raise ValueError("backoff delays must be >= 0")
        if self.backoff_jitter < 0:
            raise ValueError("backoff_jitter must be >= 0")
        if self.breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if self.degrade_after < 0:
            raise ValueError("degrade_after must be >= 0 (0 disables)")

    def backoff(self, attempt: int, rng) -> float:
        """Delay before retry ``attempt`` (1-based); ``rng`` supplies
        the jitter draw (the service owns one seeded generator)."""
        base = min(self.backoff_cap_s,
                   self.backoff_base_s * (2.0 ** max(0, attempt - 1)))
        return base * (1.0 + self.backoff_jitter * float(rng.random()))


@dataclasses.dataclass(frozen=True)
class SupervisorPolicy:
    """The replica supervisor's knobs (:class:`quest_tpu.serve.router.
    ServiceRouter`): when to quarantine a replica, how to restart it,
    and what a half-open readmission probe must pass.

    A replica is quarantined when its dispatcher thread dies, when its
    dispatcher heartbeat goes quiet for ``stall_timeout_s`` with work
    pending (``stall_quarantine``; the heartbeat cannot tick DURING a
    dispatch, so set this above the worst-case single dispatch —
    including a cold compile — or warm the buckets traffic will hit),
    or when its executor-fault count grows by
    ``fault_quarantine_threshold`` inside one supervisor poll window. Restart attempts are bounded
    (``max_restart_attempts`` per quarantine episode) and spaced by
    exponential backoff from ``restart_backoff_s``. A restarted replica
    is readmitted only after a ``probe_batch``-request half-open probe
    whose every result matches the reference recorded at warm time to
    ``probe_tol`` (oracle-grade: NaN, norm drift, or a wrong energy all
    fail the probe and send the replica back to quarantine)."""

    poll_s: float = 0.02
    stall_quarantine: bool = True
    stall_timeout_s: float = 5.0
    fault_quarantine_threshold: int = 8
    probe_batch: int = 2
    probe_timeout_s: float = 60.0
    probe_tol: float = 1e-9
    max_restart_attempts: int = 5
    restart_backoff_s: float = 0.05
    # the router's per-replica service-time EMA decay: each completed
    # hop blends as (1 - ema_decay) * measured + ema_decay * ema. 0.8
    # (the old hardcoded blend) weights ~the last 5 requests; raise it
    # for steadier placement under bursty latency, lower it to track
    # regime changes faster. The ledger warm-start seeds the EMA's
    # initial value; this knob sets how fast live traffic overrides it.
    ema_decay: float = 0.8

    def __post_init__(self):
        if self.poll_s <= 0:
            raise ValueError("poll_s must be > 0")
        if self.probe_batch < 1:
            raise ValueError("probe_batch must be >= 1")
        if self.max_restart_attempts < 1:
            raise ValueError("max_restart_attempts must be >= 1")
        if not (0.0 <= self.ema_decay < 1.0):
            raise ValueError("ema_decay must be in [0, 1) — 1.0 would "
                             "never admit a measurement")

    def restart_delay(self, attempt: int) -> float:
        """Backoff before restart ``attempt`` (1-based)."""
        return self.restart_backoff_s * (2.0 ** max(0, attempt - 1))


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """When the router's replica pool grows and shrinks.

    The decision is priced from the perf ledger: the backlog is
    converted to a drain-time estimate ``backlog * mean_request_s /
    replicas`` (``mean_request_s`` comes from
    :meth:`~quest_tpu.telemetry.PerfLedger.mean_request_s` — measured
    per-program cost history, not a guess), and the pool grows by
    ``step`` whenever that estimate exceeds ``scale_up_drain_s``. It
    shrinks only after the pool has been fully idle (no backlog, no
    in-flight work) for ``scale_down_idle_s``. ``cooldown_s`` spaces
    consecutive decisions so a scale-up's own warm-up latency can't
    trigger a second one. :meth:`decide` is pure — the router and the
    ``tools/sched_trace.py`` replay drive the SAME function, so the
    dumped schedule is the schedule the live pool would follow."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_drain_s: float = 0.5
    scale_down_idle_s: float = 5.0
    cooldown_s: float = 2.0
    step: int = 1

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.step < 1:
            raise ValueError("step must be >= 1")
        if self.scale_up_drain_s <= 0:
            raise ValueError("scale_up_drain_s must be > 0")

    def decide(self, *, now: float, replicas: int, backlog: int,
               inflight: int, mean_request_s: float,
               last_scale_t: float, idle_since) -> int:
        """Replica-count delta for the current instant: positive to
        grow, negative to shrink, 0 to hold. ``idle_since`` is the
        monotonic time the pool last became fully idle (None while any
        work is queued or in flight)."""
        if now - last_scale_t < self.cooldown_s:
            return 0
        n = max(1, int(replicas))
        est = mean_request_s if mean_request_s > 0 else 0.0
        drain_s = backlog * est / n
        if drain_s > self.scale_up_drain_s and n < self.max_replicas:
            return min(self.step, self.max_replicas - n)
        if (backlog == 0 and inflight == 0 and idle_since is not None
                and now - idle_since >= self.scale_down_idle_s
                and n > self.min_replicas):
            return -min(self.step, n - self.min_replicas)
        return 0


class CircuitBreaker:
    """Per-key failure breaker (keys are compiled-program labels).

    Closed: everything flows, failures are recorded in a sliding
    ``window_s``. ``threshold`` failures in the window trip it OPEN:
    ``allow`` answers False (the caller fast-fails with a typed error)
    until ``cooldown_s`` passes, then HALF-OPEN: one batch may probe;
    success closes the breaker, failure re-opens it for another
    cooldown. Thread-safe; ``trips`` counts open transitions."""

    def __init__(self, threshold: int = 5, window_s: float = 30.0,
                 cooldown_s: float = 2.0, clock=time.monotonic):
        self.threshold = int(threshold)
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures: dict = {}      # key -> deque of failure times
        self._open_until: dict = {}    # key -> reopen time
        self._half_open: set = set()   # keys probing after cooldown
        self.trips = 0

    def _prune(self, key, now: float):
        dq = self._failures.get(key)
        while dq and now - dq[0] > self.window_s:
            dq.popleft()

    def allow(self, key) -> bool:
        now = self._clock()
        with self._lock:
            until = self._open_until.get(key)
            if until is None:
                return True
            if now < until:
                return False
            # cooldown over: half-open — one probe through
            self._half_open.add(key)
            del self._open_until[key]
            return True

    def record_failure(self, key) -> bool:
        """Record one failed dispatch; returns True when this failure
        TRIPS the breaker open (new trip, not an already-open state)."""
        now = self._clock()
        with self._lock:
            if key in self._half_open:
                # the probe failed: straight back to open
                self._half_open.discard(key)
                self._open_until[key] = now + self.cooldown_s
                self.trips += 1
                return True
            dq = self._failures.setdefault(key, deque())
            dq.append(now)
            self._prune(key, now)
            if len(dq) >= self.threshold and key not in self._open_until:
                self._open_until[key] = now + self.cooldown_s
                dq.clear()
                self.trips += 1
                return True
            return False

    def record_success(self, key) -> None:
        with self._lock:
            self._half_open.discard(key)
            self._failures.pop(key, None)
            self._open_until.pop(key, None)

    def release(self, key) -> None:
        """An INCONCLUSIVE half-open probe (e.g. it died on a caller
        error before exercising the executor): return the key to OPEN
        for another cooldown so a future batch gets the probe slot —
        without counting a trip or a failure. No-op unless half-open."""
        now = self._clock()
        with self._lock:
            if key in self._half_open:
                self._half_open.discard(key)
                self._open_until[key] = now + self.cooldown_s

    def state(self, key) -> str:
        now = self._clock()
        with self._lock:
            if key in self._half_open:
                return "half-open"
            until = self._open_until.get(key)
            if until is not None and now < until:
                return "open"
            return "closed"

    def snapshot(self) -> dict:
        now = self._clock()
        with self._lock:
            keys = set(self._failures) | set(self._open_until) \
                | self._half_open
            per_key = {}
            for key in keys:
                self._prune(key, now)
                until = self._open_until.get(key)
                per_key[str(key)] = {
                    "state": ("half-open" if key in self._half_open else
                              "open" if until is not None and now < until
                              else "closed"),
                    "recent_failures": len(self._failures.get(key, ())),
                }
            return {"trips": self.trips, "programs": per_key}
