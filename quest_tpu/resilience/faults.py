"""Deterministic, seedable fault injection at the dispatch boundaries.

Distributed simulators meet real failure modes at scale — transient XLA
runtime errors, device OOM, NaN-poisoned buffers, and wedged/slow
collectives (the failure classes mpiQulacs, arXiv:2203.16044, and the
QuEST whitepaper, arXiv:1802.08032, engineer around) — but none of them
can be provoked on demand in CI. This module makes them reproducible:
a :class:`FaultInjector` carries a seeded schedule of faults, and the
execution layers call :func:`fire` at their dispatch boundaries
(:data:`SITES`), which is a no-op unless an injector is installed.

Fault kinds:

- ``"transient"`` — raises :class:`InjectedFault` (a ``RuntimeError``,
  the shape of a transient executor failure; the recovery layer must
  absorb it with a retry);
- ``"oom"`` — raises :class:`SimulatedOOM` (message styled like XLA's
  ``RESOURCE_EXHAUSTED``; recovery may succeed at a smaller batch, which
  is exactly what the serving layer's quarantine bisection produces);
- ``"nan"`` — the dispatch RUNS, then its output is NaN-poisoned in one
  deterministic row (:meth:`FaultInjector.poison_array`) — the silent
  corruption the numerical health guards exist to catch;
- ``"precision"`` — the dispatch runs, then its output is NORM-DRIFTED
  (uniformly scaled by a few percent,
  :meth:`FaultInjector.drift_array`) — the in-budget-looking-but-wrong
  result the precision-tier fidelity monitor exists to catch; the
  serving recovery must re-execute the affected requests one tier up,
  not retry the same rung;
- ``"stall"`` — the dispatch runs after sleeping ``stall_s`` seconds (a
  slow device / wedged collective; the serving watchdog's prey);
- ``"replica_crash"`` / ``"replica_stall"`` — replica-level failure
  domains (a SIGKILLed service process / a wedged dispatcher that stops
  heartbeating). These fire only at the ROUTER boundary
  (``"router.route"``, :func:`fire_router`): the router applies them to
  the replica it was about to pick, then must fail traffic over. At the
  intra-service boundaries they are no-ops — a single service cannot
  kill itself meaningfully.
- ``"conn_reset"`` / ``"slow_read"`` / ``"torn_body"`` /
  ``"dup_delivery"`` / ``"stale_ref"`` — WIRE-level failure domains
  (:data:`WIRE_KINDS`): a socket reset before the response, a
  slow-loris peer, a response truncated mid-body, the same request
  delivered twice, and a ``circuit_ref`` whose program the server
  evicted. These fire only at the netserve boundaries
  (``"netserve.*"``, :func:`fire_wire`): the front door applies them to
  the connection it is serving, and the client's idempotent retry loop
  must absorb them. At the engine and router boundaries they are
  no-ops — there is no socket to corrupt below the wire.

Determinism: given the same specs, seed, and sequence of ``fire`` calls,
the injected schedule is identical — ``at_calls`` schedules are exact,
and probabilistic draws come from one seeded ``numpy`` Generator. All
counters are thread-safe (the serving dispatcher fires from its own
thread while callers run warmups).
"""

from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import threading
import time
from typing import Optional, Sequence

import numpy as np

__all__ = ["InjectedFault", "SimulatedOOM", "FaultSpec", "FaultInjector",
           "install", "uninstall", "active", "inject", "fire",
           "fire_router", "fire_wire", "poison_output", "SITES",
           "KINDS", "REPLICA_KINDS", "POISON_KINDS", "WIRE_KINDS"]

# the dispatch boundaries that call fire() (site names are stable API —
# tools/chaos_trace.py and the chaos tests target them by pattern)
SITES = (
    "circuits.run",                # CompiledCircuit.run / apply dispatch
    "circuits.sweep",              # batched ensemble sweep dispatch
    "circuits.expectation_sweep",  # batched energy dispatch
    "circuits.grad_sweep",         # batched value-and-grad dispatch
    "pergate.gate",                # imperative sharded gate dispatch
    "pergate.relayout",            # imperative relayout exchange
    "serve.execute",               # serving dispatcher batch execution
    "serve.optimize",              # optimizer-in-the-loop iterate step
    "serve.evolve",                # Hamiltonian-dynamics segment dispatch
    "serve.preempt",               # checkpointed-run mesh yield boundary
    "serve.scale",                 # autoscaler replica-pool resize
    "router.route",                # ServiceRouter placement decision
    "netserve.request",            # wire front-door request dispatch
    "netserve.stream",             # wire front-door stream setup
)

KINDS = ("transient", "oom", "nan", "precision", "stall",
         "replica_crash", "replica_stall",
         "conn_reset", "slow_read", "torn_body", "dup_delivery",
         "stale_ref")

# the output-corrupting subset: fire() returns the kind for the caller
# to apply to its dispatch RESULT via poison_output()
POISON_KINDS = ("nan", "precision")

# the replica-scoped subset: returned by fire_router() for the router
# to apply to its chosen replica, inert at every other boundary
REPLICA_KINDS = ("replica_crash", "replica_stall")

# the wire-scoped subset: returned by fire_wire() for the netserve
# front door to apply to the connection it serves, inert everywhere else
WIRE_KINDS = ("conn_reset", "slow_read", "torn_body", "dup_delivery",
              "stale_ref")


class InjectedFault(RuntimeError):
    """A deliberately injected transient executor fault."""


class SimulatedOOM(RuntimeError):
    """A deliberately injected device out-of-memory failure (styled like
    XLA's ``RESOURCE_EXHAUSTED`` so classifiers treat it as the real
    thing)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault class.

    ``kind`` is one of :data:`KINDS`; ``site`` is an ``fnmatch`` pattern
    over :data:`SITES` (``"*"`` hits every boundary). A spec triggers at
    the exact per-site call indices in ``at_calls`` (0-based,
    deterministic) and/or independently with ``probability`` per
    eligible call (drawn from the injector's seeded generator).
    """

    kind: str
    site: str = "*"
    probability: float = 0.0
    at_calls: tuple = ()

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"expected one of {KINDS}")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        object.__setattr__(self, "at_calls",
                           tuple(int(i) for i in self.at_calls))


class FaultInjector:
    """A seeded fault schedule plus its accounting.

    ``max_faults`` caps total injections (a chaos run that must end);
    ``stall_s`` is the sleep for ``"stall"`` faults. ``snapshot()``
    returns the full accounting — the serving runtime folds it into
    ``dispatch_stats()`` so every injected fault is accounted for next
    to the recovery counters it caused.
    """

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0,
                 max_faults: Optional[int] = None, stall_s: float = 0.05):
        self.specs = tuple(specs)
        for spec in self.specs:
            if not isinstance(spec, FaultSpec):
                raise TypeError(f"expected FaultSpec, got {type(spec)}")
        self.seed = int(seed)
        self.max_faults = None if max_faults is None else int(max_faults)
        self.stall_s = float(stall_s)
        self._rng = np.random.default_rng(self.seed)
        self._lock = threading.Lock()
        self._calls: dict = {}       # site -> fire() count
        self._injected: dict = {}    # (site, kind) -> count
        self._total = 0

    # -- scheduling --------------------------------------------------------

    def draw(self, site: str) -> Optional[str]:
        """Advance the site's call counter and return the fault kind to
        inject at this call (None for a clean dispatch)."""
        with self._lock:
            idx = self._calls.get(site, 0)
            self._calls[site] = idx + 1
            if self.max_faults is not None and self._total >= self.max_faults:
                return None
            for spec in self.specs:
                if not fnmatch.fnmatchcase(site, spec.site):
                    continue
                hit = idx in spec.at_calls
                if not hit and spec.probability > 0.0:
                    hit = float(self._rng.random()) < spec.probability
                if hit:
                    key = (site, spec.kind)
                    self._injected[key] = self._injected.get(key, 0) + 1
                    self._total += 1
                    return spec.kind
            return None

    def poison_array(self, arr):
        """Return ``arr`` with one element of a seeded-random leading row
        set to NaN — the minimal corruption that makes the whole row's
        result wrong while leaving its shape intact. Works on numpy and
        jax arrays (functional update)."""
        if getattr(arr, "ndim", 0) == 0 or arr.shape[0] == 0:
            return arr
        with self._lock:
            row = int(self._rng.integers(arr.shape[0]))
        idx = (row,) + (0,) * (arr.ndim - 1)
        if isinstance(arr, np.ndarray):
            out = arr.copy()
            out[idx] = np.nan
            return out
        return arr.at[idx].set(np.nan)

    DRIFT_SCALE = 1.05   # 5% norm inflation: outside every tier budget

    def drift_array(self, arr):
        """Return the WHOLE ``arr`` scaled by :data:`DRIFT_SCALE` — a
        finite, plausible-looking result whose norm/trace violates every
        tier's runtime tolerance (the fidelity-monitor analogue of
        :meth:`poison_array`'s NaN). Uniform on purpose: this boundary
        cannot know which axis (if any) is a batch axis, and a per-row
        scale on packed ``(2, 2^n)`` planes or a flat state could land
        on an all-zero plane and silently inject NOTHING — a chaos run
        must never count a fault that produced no corruption."""
        return arr * self.DRIFT_SCALE

    # -- accounting --------------------------------------------------------

    @property
    def total_injected(self) -> int:
        with self._lock:
            return self._total

    def counts(self, kind: Optional[str] = None) -> int:
        """Total injections, optionally of one kind."""
        with self._lock:
            if kind is None:
                return self._total
            return sum(n for (_, k), n in self._injected.items()
                       if k == kind)

    def snapshot(self) -> dict:
        """JSON-ready accounting: per-site call counts, injections by
        site/kind, and totals."""
        with self._lock:
            by_kind: dict = {}
            by_site: dict = {}
            for (site, kind), n in self._injected.items():
                by_kind[kind] = by_kind.get(kind, 0) + n
                by_site.setdefault(site, {})[kind] = n
            return {"seed": self.seed,
                    "total_calls": sum(self._calls.values()),
                    "calls_by_site": dict(self._calls),
                    "total_injected": self._total,
                    "injected_by_kind": by_kind,
                    "injected_by_site": by_site}


# ---------------------------------------------------------------------------
# the active-injector hook the dispatch boundaries consult
# ---------------------------------------------------------------------------

_ACTIVE: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> None:
    """Install ``injector`` globally (all dispatch boundaries consult
    it). Prefer the :func:`inject` context manager."""
    global _ACTIVE
    if not isinstance(injector, FaultInjector):
        raise TypeError("install() takes a FaultInjector")
    _ACTIVE = injector


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultInjector]:
    return _ACTIVE


@contextlib.contextmanager
def inject(injector: FaultInjector):
    """Scope an injector: ``with faults.inject(inj): ...`` — guaranteed
    uninstall on exit, so a failing chaos test can't poison the suite."""
    install(injector)
    try:
        yield injector
    finally:
        uninstall()


def fire(site: str):
    """The dispatch-boundary hook. No-op (falsy) when no injector is
    installed. Otherwise: raises for ``transient``/``oom`` faults,
    sleeps for ``stall`` faults, and returns the corruption KIND
    (``"nan"`` | ``"precision"``, truthy) when the CALLER must corrupt
    this dispatch's output via :func:`poison_output` (output faults
    poison results, not inputs — the corruption the health guards and
    the tier fidelity monitor must catch)."""
    inj = _ACTIVE
    if inj is None:
        return False
    kind = inj.draw(site)
    if kind is None:
        return False
    if kind == "transient":
        raise InjectedFault(f"injected transient fault at {site}")
    if kind == "oom":
        raise SimulatedOOM(
            f"RESOURCE_EXHAUSTED: injected simulated OOM at {site}")
    if kind == "stall":
        time.sleep(inj.stall_s)
        return False
    if kind in REPLICA_KINDS or kind in WIRE_KINDS:
        # replica faults only mean something to the router, wire faults
        # only to the netserve front door
        return False
    return kind     # "nan"/"precision": caller corrupts its output


def fire_router(site: str) -> Optional[str]:
    """The ROUTER-boundary hook. Replica-scoped kinds are not raised —
    only the router knows its replicas, so ``"replica_crash"`` /
    ``"replica_stall"`` are RETURNED for the caller to apply to the
    replica it was about to pick. Every other kind behaves exactly as
    at the engine boundaries (transient/oom raise, stall sleeps); the
    output-corrupting kinds (nan/precision) have no router meaning and
    are dropped. None = clean routing."""
    inj = _ACTIVE
    if inj is None:
        return None
    kind = inj.draw(site)
    if kind is None or kind in POISON_KINDS or kind in WIRE_KINDS:
        return None
    if kind in REPLICA_KINDS:
        return kind
    if kind == "transient":
        raise InjectedFault(f"injected transient fault at {site}")
    if kind == "oom":
        raise SimulatedOOM(
            f"RESOURCE_EXHAUSTED: injected simulated OOM at {site}")
    time.sleep(inj.stall_s)     # "stall"
    return None


def fire_wire(site: str) -> Optional[str]:
    """The NETSERVE-boundary hook. Wire-scoped kinds are not raised —
    only the front door owns the socket, so :data:`WIRE_KINDS` are
    RETURNED for the server to apply to the connection it is serving
    (reset it, trickle it, tear the body, re-deliver the request, or
    evict the referenced program first). Every other kind behaves
    exactly as at the engine boundaries (transient/oom raise — they
    surface as typed 500s the client may retry — and stall sleeps); the
    output-corrupting and replica-scoped kinds have no wire meaning and
    are dropped. None = a clean request."""
    inj = _ACTIVE
    if inj is None:
        return None
    kind = inj.draw(site)
    if kind is None or kind in POISON_KINDS or kind in REPLICA_KINDS:
        return None
    if kind in WIRE_KINDS:
        return kind
    if kind == "transient":
        raise InjectedFault(f"injected transient fault at {site}")
    if kind == "oom":
        raise SimulatedOOM(
            f"RESOURCE_EXHAUSTED: injected simulated OOM at {site}")
    time.sleep(inj.stall_s)     # "stall"
    return None


def poison_output(poison, arr):
    """Apply a drawn output fault to a dispatch output: pass
    :func:`fire`'s return value (``"nan"`` | ``"precision"`` | falsy)
    and the output array. One helper so every boundary shares the same
    semantics — including the edge where the injector was uninstalled
    between ``fire()`` and the dispatch completing (the chaos scope
    ended: the poison is dropped)."""
    inj = _ACTIVE
    if poison and inj is not None:
        if poison == "precision":
            return inj.drift_array(arr)
        return inj.poison_array(arr)
    return arr
