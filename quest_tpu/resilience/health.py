"""Numerical health guards: cheap on-device invariant checks.

A long sharded run has three silent ways to rot: NaN/Inf poisoning (one
bad kernel output propagates to the whole register), statevector norm
drift (accumulated rounding, or a genuinely non-unitary bug), and
density-matrix trace drift. The reference aborts only on *input*
validation; nothing watches the state itself. Here
:func:`check_planes` computes the invariants as ONE tiny jitted
reduction per check (two scalars per state — the device does the O(2^n)
work, the host reads bytes) and either raises a typed
:class:`NumericalFault` or — in the opt-in degraded mode —
renormalizes and warns.

The check cadence is configurable (:func:`configure`, or the
``QUEST_TPU_HEALTH_EVERY`` / ``QUEST_TPU_HEALTH_MODE`` /
``QUEST_TPU_HEALTH_TOL`` environment knobs read at import): cadence 0
(default) is off, cadence k checks every k-th guarded dispatch.
``CompiledCircuit.run`` and the sweep family consult the active config;
the serving runtime additionally screens every batch result row
host-side (:func:`bad_plane_rows` / :func:`bad_value_rows`) so one
poisoned request gets a typed failure instead of poisoning its batch.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import threading
import warnings
from typing import Optional

import numpy as np

__all__ = ["NumericalFault", "HealthConfig", "configure", "get_config",
           "guarded", "check_planes", "bad_plane_rows", "bad_value_rows",
           "plane_norms", "drifted_rows", "health_stats", "reset_stats"]


class NumericalFault(RuntimeError):
    """A state invariant failed: NaN/Inf amplitudes, statevector norm
    drift, or density-matrix trace drift. ``kind`` is one of
    ``("nan", "norm", "trace", "precision")``; ``rows`` names the
    offending batch rows (empty for an unbatched state).

    ``"precision"`` is the precision-tier fidelity monitor's kind: the
    drift exceeded the TIER's runtime tolerance (:func:`quest_tpu.
    profiling.tier_runtime_tol`) — the result is outside the error
    budget the caller stated, and the recovery policy answers by
    re-executing one tier up the ladder rather than retrying the same
    rung (:mod:`quest_tpu.serve.engine`)."""

    def __init__(self, message: str, kind: str = "nan", rows: tuple = ()):
        super().__init__(message)
        self.kind = kind
        self.rows = tuple(int(r) for r in rows)


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """The guard knobs. ``cadence`` — check every k-th guarded dispatch
    (0 disables). ``norm_tol`` — allowed |norm - 1| (trace for density
    registers). ``mode`` — ``"raise"`` (typed :class:`NumericalFault`)
    or ``"renormalize"`` (degraded: rescale drifting states and warn;
    NaN/Inf still raises — there is nothing to rescale)."""

    cadence: int = 1
    norm_tol: float = 1e-6
    mode: str = "raise"

    def __post_init__(self):
        if self.cadence < 0:
            raise ValueError("cadence must be >= 0")
        if not self.norm_tol > 0.0:
            raise ValueError("norm_tol must be > 0")
        if self.mode not in ("raise", "renormalize"):
            raise ValueError("mode must be 'raise' or 'renormalize'")


_config = HealthConfig(
    cadence=int(os.environ.get("QUEST_TPU_HEALTH_EVERY", "0")),
    norm_tol=float(os.environ.get("QUEST_TPU_HEALTH_TOL", "1e-6")),
    mode=os.environ.get("QUEST_TPU_HEALTH_MODE", "raise"))

_stats_lock = threading.Lock()
_stats = {"checks": 0, "failures": 0, "renormalized": 0}


def configure(config: Optional[HealthConfig] = None, **kwargs
              ) -> HealthConfig:
    """Install a new global guard config (a :class:`HealthConfig`, or
    field overrides on the current one). Returns the PREVIOUS config so
    callers can restore it."""
    global _config
    prev = _config
    _config = config if config is not None \
        else dataclasses.replace(_config, **kwargs)
    return prev


def get_config() -> HealthConfig:
    return _config


@contextlib.contextmanager
def guarded(config: Optional[HealthConfig] = None, **kwargs):
    """Scope a guard config: ``with health.guarded(cadence=1): ...``."""
    prev = configure(config, **kwargs)
    try:
        yield _config
    finally:
        configure(prev)


def health_stats() -> dict:
    with _stats_lock:
        return dict(_stats)


def reset_stats() -> None:
    with _stats_lock:
        for k in _stats:
            _stats[k] = 0


def _count(key: str, n: int = 1) -> None:
    with _stats_lock:
        _stats[key] += n


# ---------------------------------------------------------------------------
# the invariant reductions (jitted; host reads two scalars per state)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _invariant_fn(is_density: bool, nq: int, batched: bool):
    import jax
    import jax.numpy as jnp

    def one(planes):
        finite = jnp.all(jnp.isfinite(planes))
        if is_density:
            # trace of the flattened (2^nq x 2^nq) matrix: real plane at
            # the paired-diagonal indices d*(2^nq + 1)
            diag = jnp.arange(1 << nq) * ((1 << nq) + 1)
            norm = jnp.sum(planes[0, diag])
        else:
            norm = jnp.sum(planes * planes)
        return finite, norm

    fn = jax.vmap(one) if batched else one
    return jax.jit(fn)


def check_planes(planes, *, is_density: bool = False,
                 num_qubits: Optional[int] = None,
                 config: Optional[HealthConfig] = None,
                 where: str = "state", drift_kind: Optional[str] = None):
    """Verify the invariants of packed float planes — ``(2, 2^n)`` or a
    batched ``(B, 2, 2^n)`` — and return them (possibly renormalized in
    degraded mode). ``num_qubits`` is the LOGICAL qubit count for
    density registers (their planes hold 4^nq amplitudes).

    Raises :class:`NumericalFault` on NaN/Inf always, and on norm/trace
    drift beyond ``config.norm_tol`` unless ``config.mode ==
    "renormalize"`` (then the drifting states are rescaled and a
    ``UserWarning`` names the drift). ``drift_kind`` overrides the
    fault kind a drift raises with (the precision-tier fidelity monitor
    passes ``"precision"`` so its violations classify for tier
    escalation, not quarantine)."""
    cfg = config or _config
    batched = getattr(planes, "ndim", 2) == 3
    if is_density and num_qubits is None:
        raise ValueError("density-plane checks need num_qubits (logical)")
    nq = int(num_qubits or 0)
    finite, norm = _invariant_fn(bool(is_density), nq, batched)(planes)
    finite = np.atleast_1d(np.asarray(finite))
    norm = np.atleast_1d(np.asarray(norm))
    if not is_density:
        # the device reduction is the SQUARED 2-norm; the documented
        # contract (|norm - 1| <= norm_tol) is on the norm itself, and
        # the density path's trace is linear — take the root so both
        # register kinds honour the same tolerance
        norm = np.sqrt(np.maximum(norm, 0.0))
    _count("checks")
    nan_rows = np.nonzero(~finite)[0]
    drift = np.abs(norm - 1.0) > cfg.norm_tol
    drift_rows = np.nonzero(drift & finite)[0]
    if nan_rows.size == 0 and drift_rows.size == 0:
        return planes
    _count("failures")
    label = "trace" if is_density else "norm"
    if nan_rows.size:
        rows = tuple(int(r) for r in nan_rows) if batched else ()
        raise NumericalFault(
            f"non-finite amplitudes in {where}"
            + (f" (batch rows {list(rows)})" if rows else ""),
            kind="nan", rows=rows)
    if cfg.mode == "renormalize":
        _count("renormalized", int(drift_rows.size))
        warnings.warn(
            f"{where}: {label} drifted to "
            f"{[round(float(norm[r]), 12) for r in drift_rows[:4]]}"
            f"{'...' if drift_rows.size > 4 else ''} "
            f"(tol {cfg.norm_tol}); renormalizing (degraded mode)",
            UserWarning, stacklevel=3)
        scale = np.ones_like(norm)
        safe = np.where(norm <= 0.0, 1.0, norm)
        # norm is now linear in the state for BOTH kinds (2-norm for
        # statevectors, trace for densities): planes scale by 1/norm
        scale = np.where(drift, 1.0 / safe, scale)
        import jax.numpy as jnp
        s = jnp.asarray(scale, dtype=planes.dtype)
        return planes * (s.reshape((-1, 1, 1)) if batched else s[0])
    rows = tuple(int(r) for r in drift_rows) if batched else ()
    vals = [float(norm[r]) for r in (drift_rows if batched else [0])]
    raise NumericalFault(
        f"{where}: {label} drifted to {vals[:4]} (tol {cfg.norm_tol})"
        + (f" in batch rows {list(rows)}" if rows else ""),
        kind=(drift_kind or ("trace" if is_density else "norm")),
        rows=rows)


# ---------------------------------------------------------------------------
# host-side row screens (serving results are already numpy)
# ---------------------------------------------------------------------------

def bad_plane_rows(planes: np.ndarray) -> np.ndarray:
    """Row indices of a host ``(B, 2, 2^n)`` plane batch holding any
    non-finite value (the serving engine's per-request poison screen)."""
    flat = np.asarray(planes).reshape(planes.shape[0], -1)
    return np.nonzero(~np.isfinite(flat).all(axis=1))[0]


def bad_value_rows(values) -> np.ndarray:
    """Indices of non-finite scalars in a 1-D result vector (energies,
    sampling norms)."""
    return np.nonzero(~np.isfinite(np.asarray(values, dtype=np.float64)))[0]


def plane_norms(planes: np.ndarray, is_density: bool = False,
                num_qubits: Optional[int] = None) -> np.ndarray:
    """Per-row norm (statevector 2-norm) or trace of a host
    ``(B, 2, 2^n)`` plane batch — the serving layer's tier fidelity
    observable (non-finite rows report NaN; screen those with
    :func:`bad_plane_rows` first)."""
    p = np.asarray(planes)
    if is_density:
        if num_qubits is None:
            raise ValueError("density-plane norms need num_qubits "
                             "(logical)")
        diag = np.arange(1 << num_qubits) * ((1 << num_qubits) + 1)
        return p[:, 0, diag].sum(axis=1, dtype=np.float64)
    # einsum with a forced f64 accumulator: no full-size f64 copy of
    # the batch (a 25q x16 batch would spike ~17 GB of temporaries the
    # upcast-then-square form allocates to produce 16 scalars)
    flat = p.reshape(p.shape[0], -1)
    return np.sqrt(np.einsum("bi,bi->b", flat, flat,
                             dtype=np.float64))


def drifted_rows(values, tol: float) -> np.ndarray:
    """Indices of FINITE entries in a 1-D norm/trace vector that drift
    from 1 by more than ``tol`` (the per-request precision-violation
    screen; NaN rows are the NaN screen's business, not this one's)."""
    v = np.asarray(values, dtype=np.float64)
    return np.nonzero(np.isfinite(v) & (np.abs(v - 1.0) > float(tol)))[0]
