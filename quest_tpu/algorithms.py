"""Standard quantum algorithms as :class:`~quest_tpu.circuits.Circuit` builders.

The reference ships these as user programs (`examples/tutorial_example.c`,
`examples/bernstein_vazirani_circuit.c`, `examples/damping_example.c`) and as
algorithm-level tests (`tests/algor/QFT.test`). Here they are library
functions producing whole-circuit programs that compile to single XLA
executables — also the workloads of the BASELINE.json benchmark configs
(QFT-30, Grover-30, random Clifford+T circuits).
"""

from __future__ import annotations

import numpy as np

from .circuits import Circuit

__all__ = [
    "qft",
    "inverse_qft",
    "grover",
    "bernstein_vazirani",
    "ghz",
    "random_circuit",
]


def qft(num_qubits: int, swap_order: bool = True) -> Circuit:
    """Quantum Fourier transform (the reference's `tests/algor/QFT.test`
    workload): H + controlled phase ladder, optional bit-reversal swaps."""
    c = Circuit(num_qubits)
    for q in range(num_qubits - 1, -1, -1):
        c.h(q)
        for k, ctrl in enumerate(range(q - 1, -1, -1), start=2):
            c.cphase(ctrl, q, 2.0 * np.pi / (1 << k))
    if swap_order:
        for q in range(num_qubits // 2):
            c.swap(q, num_qubits - 1 - q)
    return c


def inverse_qft(num_qubits: int, swap_order: bool = True) -> Circuit:
    return qft(num_qubits, swap_order).inverse()


def grover(num_qubits: int, marked: int, num_iterations: int | None = None) -> Circuit:
    """Grover search for basis state ``marked``: uniform superposition, then
    round(pi/4 sqrt(2^n)) iterations of oracle + diffusion. The oracle is a
    multi-controlled phase flip with flipped controls on the 0-bits of
    ``marked``; diffusion is H^n · (2|0><0| - 1) · H^n."""
    n = num_qubits
    if not 0 <= marked < (1 << n):
        raise ValueError(f"marked state {marked} out of range [0, {1 << n})")
    if num_iterations is None:
        num_iterations = max(1, int(round(np.pi / 4.0 * np.sqrt(1 << n))))
    c = Circuit(n)
    for q in range(n):
        c.h(q)

    def phase_on(index: int):
        """-1 phase on exactly |index>: a 1-qubit phase conditioned on every
        other qubit being at its bit of ``index`` — O(1) memory at any n
        (the reference's multiControlledPhaseFlip with flipped controls)."""
        target_diag = np.array([1.0, -1.0]) if (index >> (n - 1)) & 1 \
            else np.array([-1.0, 1.0])
        controls = tuple(range(n - 1))
        states = tuple((index >> q) & 1 for q in controls)
        c.gate(np.diag(target_diag), (n - 1,), controls, states)

    for _ in range(num_iterations):
        phase_on(marked)
        for q in range(n):
            c.h(q)
        phase_on(0)
        for q in range(n):
            c.h(q)
    return c


def bernstein_vazirani(num_qubits: int, secret: int) -> Circuit:
    """Phase-oracle Bernstein–Vazirani (one query recovers ``secret``), the
    workload of `examples/bernstein_vazirani_circuit.c`: H^n, Z on secret
    bits, H^n — final state = |secret>."""
    c = Circuit(num_qubits)
    for q in range(num_qubits):
        c.h(q)
    for q in range(num_qubits):
        if (secret >> q) & 1:
            c.z(q)
    for q in range(num_qubits):
        c.h(q)
    return c


def ghz(num_qubits: int) -> Circuit:
    c = Circuit(num_qubits)
    c.h(0)
    for q in range(1, num_qubits):
        c.cnot(q - 1, q)
    return c


def random_circuit(num_qubits: int, depth: int, seed: int = 0,
                   gate_set: str = "clifford+t") -> Circuit:
    """Layered random circuit (the BASELINE.json "20-qubit random Clifford+T"
    / "34–38 qubit random circuit" configs): each layer applies a random
    1-qubit gate to every qubit then entangles a random brickwork pairing."""
    rng = np.random.default_rng(seed)
    c = Circuit(num_qubits)
    if gate_set == "clifford+t":
        one_q = ("h", "s", "t", "x", "y", "z")
    elif gate_set == "haar":
        one_q = ("rot",)
    else:
        raise ValueError(f"unknown gate_set {gate_set!r}")
    for _ in range(depth):
        for q in range(num_qubits):
            g = one_q[rng.integers(len(one_q))]
            if g == "rot":
                axis = rng.normal(size=3)
                c.rotate(q, float(rng.uniform(0, 2 * np.pi)), axis)
            else:
                getattr(c, g)(q)
        offset = int(rng.integers(2))
        for q in range(offset, num_qubits - 1, 2):
            if rng.uniform() < 0.5:
                c.cnot(q, q + 1)
            else:
                c.cz(q, q + 1)
    return c
