"""Standard quantum algorithms as :class:`~quest_tpu.circuits.Circuit` builders.

The reference ships these as user programs (`examples/tutorial_example.c`,
`examples/bernstein_vazirani_circuit.c`, `examples/damping_example.c`) and as
algorithm-level tests (`tests/algor/QFT.test`). Here they are library
functions producing whole-circuit programs that compile to single XLA
executables — also the workloads of the BASELINE.json benchmark configs
(QFT-30, Grover-30, random Clifford+T circuits).
"""

from __future__ import annotations

import numpy as np

from .circuits import Circuit

__all__ = [
    "qft",
    "inverse_qft",
    "grover",
    "bernstein_vazirani",
    "ghz",
    "random_circuit",
    "phase_estimation",
    "trotter_evolution",
    "modular_multiplication_unitary",
    "order_finding",
    "order_from_phase",
    "qaoa_maxcut",
    "qaoa_maxcut_terms",
]


def _append_qft(c: Circuit, qubits, inverse: bool = False,
                swap_order: bool = True) -> None:
    """Emit the QFT gate ladder onto ``qubits`` of an existing circuit
    (single source of the gate ordering/angle convention, shared by
    :func:`qft` and :func:`phase_estimation`)."""
    qubits = list(qubits)
    nq = len(qubits)
    ops = []
    for i in range(nq - 1, -1, -1):
        ops.append(("h", qubits[i], None, None))
        for k, j in enumerate(range(i - 1, -1, -1), start=2):
            ops.append(("cphase", qubits[j], qubits[i],
                        2.0 * np.pi / (1 << k)))
    if swap_order:
        for i in range(nq // 2):
            ops.append(("swap", qubits[i], qubits[nq - 1 - i], None))
    if inverse:
        # h and swap are self-inverse; cphase inverts by angle negation
        ops = [(o[0], o[1], o[2], -o[3] if o[0] == "cphase" else None)
               for o in reversed(ops)]
    for kind, a, b, angle in ops:
        if kind == "h":
            c.h(a)
        elif kind == "swap":
            c.swap(a, b)
        else:
            c.cphase(a, b, angle)


def qft(num_qubits: int, swap_order: bool = True) -> Circuit:
    """Quantum Fourier transform (the reference's `tests/algor/QFT.test`
    workload): H + controlled phase ladder, optional bit-reversal swaps."""
    c = Circuit(num_qubits)
    _append_qft(c, range(num_qubits), swap_order=swap_order)
    return c


def inverse_qft(num_qubits: int, swap_order: bool = True) -> Circuit:
    return qft(num_qubits, swap_order).inverse()


def grover(num_qubits: int, marked: int, num_iterations: int | None = None) -> Circuit:
    """Grover search for basis state ``marked``: uniform superposition, then
    round(pi/4 sqrt(2^n)) iterations of oracle + diffusion. The oracle is a
    multi-controlled phase flip with flipped controls on the 0-bits of
    ``marked``; diffusion is H^n · (2|0><0| - 1) · H^n."""
    n = num_qubits
    if not 0 <= marked < (1 << n):
        raise ValueError(f"marked state {marked} out of range [0, {1 << n})")
    if num_iterations is None:
        num_iterations = max(1, int(round(np.pi / 4.0 * np.sqrt(1 << n))))
    c = Circuit(n)
    for q in range(n):
        c.h(q)

    def phase_on(index: int):
        """-1 phase on exactly |index>: a 1-qubit phase conditioned on every
        other qubit being at its bit of ``index`` — O(1) memory at any n
        (the reference's multiControlledPhaseFlip with flipped controls)."""
        target_diag = np.array([1.0, -1.0]) if (index >> (n - 1)) & 1 \
            else np.array([-1.0, 1.0])
        controls = tuple(range(n - 1))
        states = tuple((index >> q) & 1 for q in controls)
        c.gate(np.diag(target_diag), (n - 1,), controls, states)

    for _ in range(num_iterations):
        phase_on(marked)
        for q in range(n):
            c.h(q)
        phase_on(0)
        for q in range(n):
            c.h(q)
    return c


def bernstein_vazirani(num_qubits: int, secret: int) -> Circuit:
    """Phase-oracle Bernstein–Vazirani (one query recovers ``secret``), the
    workload of `examples/bernstein_vazirani_circuit.c`: H^n, Z on secret
    bits, H^n — final state = |secret>."""
    c = Circuit(num_qubits)
    for q in range(num_qubits):
        c.h(q)
    for q in range(num_qubits):
        if (secret >> q) & 1:
            c.z(q)
    for q in range(num_qubits):
        c.h(q)
    return c


def ghz(num_qubits: int) -> Circuit:
    c = Circuit(num_qubits)
    c.h(0)
    for q in range(1, num_qubits):
        c.cnot(q - 1, q)
    return c


def random_circuit(num_qubits: int, depth: int, seed: int = 0,
                   gate_set: str = "clifford+t") -> Circuit:
    """Layered random circuit (the BASELINE.json "20-qubit random Clifford+T"
    / "34–38 qubit random circuit" configs): each layer applies a random
    1-qubit gate to every qubit then entangles a random brickwork pairing."""
    rng = np.random.default_rng(seed)
    c = Circuit(num_qubits)
    if gate_set == "clifford+t":
        one_q = ("h", "s", "t", "x", "y", "z")
    elif gate_set == "haar":
        one_q = ("rot",)
    else:
        raise ValueError(f"unknown gate_set {gate_set!r}")
    for _ in range(depth):
        for q in range(num_qubits):
            g = one_q[rng.integers(len(one_q))]
            if g == "rot":
                axis = rng.normal(size=3)
                c.rotate(q, float(rng.uniform(0, 2 * np.pi)), axis)
            else:
                getattr(c, g)(q)
        offset = int(rng.integers(2))
        for q in range(offset, num_qubits - 1, 2):
            if rng.uniform() < 0.5:
                c.cnot(q, q + 1)
            else:
                c.cz(q, q + 1)
    return c


def phase_estimation(num_counting: int, unitary: np.ndarray,
                     num_target: int | None = None) -> Circuit:
    """Quantum phase estimation: ``num_counting`` counting qubits estimate
    the eigenphase of ``unitary`` applied to the high ``num_target`` qubits.

    Layout: qubits ``[0, num_counting)`` are the counting register (the
    estimate ends up bit-reversed-free after the inverse QFT with swaps);
    qubits ``[num_counting, num_counting+num_target)`` hold the eigenstate,
    which the caller prepares before running. Controlled powers ``U^(2^j)``
    are formed by repeated host-side squaring (exact for the matrix sizes
    QPE uses) and applied through the engine's controlled dense path. No
    reference counterpart — the compiled-circuit fast path makes whole-QPE
    a single executable.
    """
    u = np.asarray(unitary, dtype=np.complex128)
    k = int(np.log2(u.shape[0]))
    if num_target is None:
        num_target = k
    if u.shape != (1 << num_target, 1 << num_target):
        raise ValueError("unitary dimension does not match num_target")
    n = num_counting + num_target
    targets = tuple(range(num_counting, n))
    c = Circuit(n)
    for q in range(num_counting):
        c.h(q)
    u_pow = u
    for j in range(num_counting):
        c.gate(u_pow, targets, controls=(j,))
        u_pow = u_pow @ u_pow
    # inverse QFT on the counting register (phases accumulate as
    # |x> -> e^{2 pi i phi x}, little-endian in counting qubit index)
    _append_qft(c, range(num_counting), inverse=True)
    return c


def trotter_evolution(num_qubits: int, pauli_terms, coeffs, time: float,
                      num_steps: int, order: int = 1) -> Circuit:
    """First- or second-order Trotterised ``exp(-i H t)`` for
    ``H = sum_j coeffs[j] * P_j`` (each ``pauli_terms[j]`` a sequence of
    ``(qubit, code)`` with codes 1=X, 2=Y, 3=Z).

    Each Pauli-product exponential is basis-rotated to Z...Z, applied as a
    parity-phase diagonal (the engine's communication-free fast path — the
    ``multiRotateZ`` machinery), and rotated back; the whole evolution
    compiles to one executable. No reference counterpart (the reference
    offers only ``multiRotatePauli`` as the single-term primitive).
    """
    terms = []
    for t in pauli_terms:
        term = tuple((int(q), int(code)) for q, code in t
                     if int(code) != 0)      # identity factors drop out
        for q, code in term:
            if code not in (1, 2, 3):
                raise ValueError(f"invalid Pauli code {code} "
                                 "(0=I, 1=X, 2=Y, 3=Z)")
        if not term:
            raise ValueError(
                "an all-identity Pauli term contributes only a global "
                "phase, which a gate circuit cannot represent; fold it "
                "into the observable instead")
        terms.append(term)
    coeffs = [float(x) for x in coeffs]
    if len(terms) != len(coeffs):
        raise ValueError("one coefficient per Pauli term is required")
    if num_steps < 1:
        raise ValueError("num_steps must be >= 1")
    if order not in (1, 2):
        raise ValueError("order must be 1 or 2")
    c = Circuit(num_qubits)

    def apply_term(term, angle):
        if not term:
            return                      # identity term: global phase only
        qubits = [q for q, _ in term]
        # basis rotation: X -> H, Y -> Rx(pi/2), Z -> nothing
        for q, code in term:
            if code == 1:
                c.h(q)
            elif code == 2:
                c.rx(q, np.pi / 2.0)
        c.multi_rotate_z(qubits, angle)
        for q, code in term:
            if code == 1:
                c.h(q)
            elif code == 2:
                c.rx(q, -np.pi / 2.0)

    dt = time / num_steps
    for _ in range(num_steps):
        if order == 1:
            for term, w in zip(terms, coeffs):
                apply_term(term, 2.0 * w * dt)
        else:
            for term, w in zip(terms, coeffs):
                apply_term(term, w * dt)
            for term, w in zip(reversed(terms), reversed(coeffs)):
                apply_term(term, w * dt)
    return c


def modular_multiplication_unitary(a: int, modulus: int,
                                   num_bits: int | None = None) -> np.ndarray:
    """Permutation matrix ``U|y> = |a*y mod modulus>`` (identity for
    ``y >= modulus``) — the arithmetic primitive of Shor order finding.

    Requires ``gcd(a, modulus) == 1`` so the map is a bijection (else it
    is not unitary). ``num_bits`` defaults to ``modulus.bit_length()``.
    """
    import math
    if modulus < 2:
        raise ValueError("modulus must be >= 2")
    a %= modulus
    if math.gcd(a, modulus) != 1:
        raise ValueError(f"gcd({a}, {modulus}) != 1: the modular "
                         "multiplication map is not a permutation")
    if num_bits is None:
        num_bits = modulus.bit_length()
    if (1 << num_bits) < modulus:
        raise ValueError(f"{num_bits} bits cannot hold values mod {modulus}")
    dim = 1 << num_bits
    u = np.zeros((dim, dim), dtype=np.complex128)
    for y in range(dim):
        u[(a * y) % modulus if y < modulus else y, y] = 1.0
    return u


def order_finding(a: int, modulus: int,
                  num_counting: int | None = None) -> Circuit:
    """Shor order finding: QPE over ``U_a`` with eigenstate register |1>.

    Layout: counting qubits ``[0, num_counting)`` (default ``2 *
    modulus.bit_length()``), work register above holding ``|1>`` — an
    equal superposition of the order-r eigenstates of ``U_a``, so the
    measured counting value concentrates on multiples of ``2^nc / r``.
    Feed the measured integer to :func:`order_from_phase`. Controlled
    powers ``U^(2^j)`` come from the shared QPE builder (host-side
    squaring of the permutation matrix — exact, it stays a permutation).
    """
    k = modulus.bit_length()
    if num_counting is None:
        num_counting = 2 * k
    u = modular_multiplication_unitary(a, modulus, k)
    c = Circuit(num_counting + k)
    c.x(num_counting)                      # work register |0..01> = |1>
    return c.extend(phase_estimation(num_counting, u))


def order_from_phase(measured: int, num_counting: int, modulus: int) -> int:
    """Classical post-processing: continued-fraction expansion of the
    measured phase ``measured / 2^num_counting`` with denominator capped
    at ``modulus`` — the order candidate (verify ``a^r = 1 mod N``; re-run
    on failure, as Shor's algorithm prescribes)."""
    from fractions import Fraction
    if not 0 <= measured < (1 << num_counting):
        raise ValueError("measured value outside the counting register")
    if measured == 0:
        return 1
    frac = Fraction(measured, 1 << num_counting).limit_denominator(modulus)
    return frac.denominator


def qaoa_maxcut(num_qubits: int, edges, num_layers: int) -> Circuit:
    """QAOA ansatz for MaxCut on the graph ``edges`` (iterable of
    ``(u, v)`` pairs): uniform superposition, then ``num_layers`` rounds
    of cost phases ``exp(-i gamma_l Z_u Z_v / 2)`` per edge and mixer
    rotations ``Rx(beta_l)`` on every qubit.

    Parameters are registered as ``gamma0..`` / ``beta0..`` — bind them
    at run time and optimise with ``CompiledCircuit.expectation_fn`` +
    ``jax.grad`` over the cut Hamiltonian (see :func:`qaoa_maxcut_terms`).
    The cost phases ride the engine's communication-free diagonal path
    (`multiRotateZ` machinery), so deep QAOA stays relayout-free on a
    mesh.
    """
    edges = [(int(u), int(v)) for u, v in edges]
    for u, v in edges:
        if not (0 <= u < num_qubits and 0 <= v < num_qubits) or u == v:
            raise ValueError(f"bad edge ({u}, {v})")
    if num_layers < 1:
        raise ValueError("num_layers must be >= 1")
    c = Circuit(num_qubits)
    for q in range(num_qubits):
        c.h(q)
    for layer in range(num_layers):
        gamma = c.parameter(f"gamma{layer}")
        beta = c.parameter(f"beta{layer}")
        for u, v in edges:
            c.multi_rotate_z([u, v], gamma)
        for q in range(num_qubits):
            c.rx(q, beta)
    return c


def qaoa_maxcut_terms(edges):
    """(pauli_terms, coeffs) of the MaxCut cost ``C = sum_{(u,v)}
    (1 - Z_u Z_v) / 2`` **dropping the constant** |E|/2 term — feed to
    ``CompiledCircuit.expectation_fn`` and MINIMISE (the expectation is
    then -cut_size + |E|/2, so its minimum is the maximum cut)."""
    terms = [[(int(u), 3), (int(v), 3)] for u, v in edges]
    coeffs = [0.5] * len(terms)
    return terms, coeffs
