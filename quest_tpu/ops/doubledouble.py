"""Double-double (two-float32) amplitude arithmetic — high-precision mode.

The reference offers double and quad-precision builds (``QuEST_PREC`` ∈
{1,2,4}, ``QuEST_precision.h:28-65``) because deep circuits accumulate
per-gate rounding without bound. TPU hardware has no f64 ALU, so the
high-precision amplitude story is *double-double*: each amplitude component
is an unevaluated sum ``hi + lo`` of two float32 (~48 significand bits,
unit roundoff ~2^-49 ≈ 1.8e-15), stored as four planes
``(4, 2^n) = [re_hi, re_lo, im_hi, im_lo]``.

All primitives are branch-free elementwise VPU ops (Dekker/Knuth
error-free transformations, same family as ops/reductions.py):

- ``_two_sum``      exact a+b -> (fl(a+b), rounding error)
- ``_two_prod``     exact a*b via Veltkamp split partial products
- ``_dd_add/_dd_mul`` renormalising double-double add / multiply

Scope: the FULL gate set and calculation surface — dense k-qubit gates
with arbitrary control/flip masks (``dd_apply_kq``), diagonals, collapse,
inner products/fidelity/purity, weighted combinations — so a ``QUAD``
(f32 planes) or ``QUAD64`` (f64 planes, ~106-bit — the reference
``QuEST_PREC=4`` build analogue, ``QuEST_precision.h:53-65``) register
runs every public API function on dd planes; the whole golden corpus
replays in both tiers (``tests/test_doubledouble.py::TestQuadTier``).
Whole-circuit compilation on dd planes is :class:`DDProgram`
(``Circuit.compile_dd``). Measured: after 1000 random 1q gates at f32
storage, max amplitude error vs an f64 oracle is ~6e-15 (plain f32:
~1.4e-7); the reference's double-build envelope reached with pure-f32
hardware arithmetic at ~6x the flop count of the plain kernel (still
memory-bound: 2x the bytes of a complex64 state).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .reductions import sum_pair, _split, _two_sum

__all__ = ["dd_pack", "dd_unpack", "dd_apply_1q", "dd_apply_perm_1q",
           "dd_apply_diag", "dd_total_prob", "DDProgram",
           "dd_split_traceable", "dd_join_traceable",
           "dd_apply_kq_traced", "dd_apply_diag_traced", "dd_relayout"]


def _quick_two_sum(a, b):
    """Assumes |a| >= |b| (holds for renormalisation: b is an error term)."""
    s = a + b
    return s, b - (s - a)


def _two_prod(a, b):
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def _dd_add(xh, xl, yh, yl):
    s, e = _two_sum(xh, yh)
    e = e + (xl + yl)
    return _quick_two_sum(s, e)


def _dd_mul(xh, xl, yh, yl):
    p, e = _two_prod(xh, yh)
    e = e + (xh * yl + xl * yh)
    return _quick_two_sum(p, e)


def _dd_neg(xh, xl):
    return -xh, -xl


# --- packing ---------------------------------------------------------------

def dd_pack(z: np.ndarray, dtype=np.float32) -> jnp.ndarray:
    """complex128 host vector -> (4, n) dd planes.

    ``dtype=float32`` (default): ~48-bit significand on TPU hardware.
    ``dtype=float64`` (CPU/x64): double-double over f64 — a ~106-bit
    significand, the analogue of the reference's quad-precision build
    (``QuEST_PREC=4``, ``QuEST_precision.h:53-65``). Note a float64
    ``hi`` already captures a complex128 input exactly, so the extra
    precision manifests during gate arithmetic, not at packing."""
    return jnp.asarray(_dd_split_host(z, dtype))


def dd_unpack(planes) -> np.ndarray:
    p = np.asarray(planes, dtype=np.float64)
    return (p[0] + p[1]) + 1j * (p[2] + p[3])


def dd_split_traceable(z, dtype=jnp.float32):
    """Traceable dd split: a complex128 jnp array (a TRACER — the
    batched QUAD engine's per-row entry, or a bound parameterised
    matrix) -> (4, ...) dd planes. The hi/lo split is error-free by the
    same argument as the host split: ``hi = fl32(x)`` and ``lo = x -
    hi`` is exact in f64."""
    re = jnp.real(z)
    im = jnp.imag(z)
    rh = re.astype(dtype)
    ih = im.astype(dtype)
    return jnp.stack([rh, (re - rh.astype(re.dtype)).astype(dtype),
                      ih, (im - ih.astype(im.dtype)).astype(dtype)])


def dd_join_traceable(planes):
    """(4, ...) dd planes -> complex128 (traceable; each dd value
    rounds to its nearest f64 — the engine-boundary exit of the QUAD
    tier, which is why that tier needs an f64-storage env)."""
    rh, rl, ih, il = (planes[i].astype(jnp.float64) for i in range(4))
    return jax.lax.complex(rh + rl, ih + il)


def dd_relayout(planes, num_qubits: int, perm_before,
                perm_after) -> jnp.ndarray:
    """The layout planner's relayout on dd planes: one per-plane
    transpose of the ``(2,)*n`` view (the
    :func:`quest_tpu.parallel.layout.apply_relayout` choreography with
    a leading plane axis)."""
    n = num_qubits
    src = np.empty(n, dtype=np.int64)
    for l in range(n):
        src[n - 1 - int(perm_after[l])] = n - 1 - int(perm_before[l])
    out = planes.reshape((4,) + (2,) * n).transpose(
        (0,) + tuple(int(a) + 1 for a in src))
    return out.reshape(4, -1)


def dd_apply_kq_traced(planes, num_qubits: int, u, targets,
                       ctrl_mask: int = 0, flip_mask: int = 0):
    """Trace-time dense k-qubit (controlled) gate on dd planes: ``u``
    is a complex matrix in user bit order — a host constant OR a traced
    matrix (a bound Param gate), dd-split traceably. The batched QUAD
    engine's gate kernel."""
    from ..core.apply import permutation_to_sorted_desc
    targets = tuple(int(t) for t in targets)
    perm = permutation_to_sorted_desc(targets)
    if not np.array_equal(perm, np.arange(1 << len(targets))):
        u = u[perm][:, perm]
    desc = tuple(sorted(targets, reverse=True))
    u_dd = dd_split_traceable(u, jnp.dtype(planes.dtype))
    out = _dd_apply_kq_body(planes, u_dd, num_qubits, desc)
    if ctrl_mask:
        cond = _index_bits_cond(planes.shape[1], int(ctrl_mask),
                                int(ctrl_mask) ^ int(flip_mask))
        out = jnp.where(cond[None, :], out, planes)
    return out


def dd_apply_diag_traced(planes, num_qubits: int, factors,
                         targets_desc):
    """Trace-time diagonal factor on dd planes (framework axis order,
    qubits sorted descending); ``factors`` may be a traced tensor."""
    f_dd = dd_split_traceable(jnp.reshape(factors, (-1,)),
                              jnp.dtype(planes.dtype))
    return _dd_diag_traced(planes, f_dd, num_qubits,
                           tuple(int(q) for q in targets_desc))


# --- kernels ---------------------------------------------------------------

def _cplx_mul_acc(acc, u_re, u_im, z):
    """acc += u * z in dd complex arithmetic. ``u_re``/``u_im`` are dd
    scalars, ``z``/``acc`` are tuples of 4 dd-plane arrays
    (re_hi, re_lo, im_hi, im_lo)."""
    zrh, zrl, zih, zil = z
    # re: ur*zr - ui*zi
    t1 = _dd_mul(u_re[0], u_re[1], zrh, zrl)
    t2 = _dd_mul(u_im[0], u_im[1], zih, zil)
    re = _dd_add(*t1, *_dd_neg(*t2))
    # im: ur*zi + ui*zr
    t3 = _dd_mul(u_re[0], u_re[1], zih, zil)
    t4 = _dd_mul(u_im[0], u_im[1], zrh, zrl)
    im = _dd_add(*t3, *t4)
    if acc is None:
        return re + im                       # (rh, rl, ih, il)
    arh, arl, aih, ail = acc
    re = _dd_add(arh, arl, *re)
    im = _dd_add(aih, ail, *im)
    return re + im


@functools.partial(jax.jit, static_argnums=(2, 3))
def _dd_apply_1q_jit(planes, u_dd, num_qubits, target):
    """Fused dd 1q-gate kernel: one compiled pass over the planes (the ~30
    EFT primitives fuse under jit; eager dispatch would round-trip HBM per
    primitive). ``u_dd``: (4, 2, 2) f32 = [re_hi, re_lo, im_hi, im_lo]."""
    return _dd_apply_1q_body(planes, u_dd, num_qubits, target)


def dd_apply_1q(planes, num_qubits: int, u: np.ndarray, target: int):
    """Apply a 1-qubit unitary (f64 numpy, dd-split to the planes' dtype)
    to dd planes of shape (4, 2^n)."""
    u_dd = _dd_split_host(np.asarray(u, dtype=np.complex128),
                          np.dtype(planes.dtype))
    return _dd_apply_1q_jit(planes, jnp.asarray(u_dd), num_qubits, target)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _dd_apply_perm_1q_jit(planes, num_qubits, target, control):
    pre = 1 << (num_qubits - 1 - target)
    post = 1 << target
    t = planes.reshape(4, pre, 2, post)
    flipped = t[:, :, ::-1, :]
    if control < 0:
        return flipped.reshape(4, -1)
    n = num_qubits
    idx = jnp.arange(1 << n)
    cbit = (idx >> control) & 1
    out = jnp.where(cbit[None, :].astype(bool),
                    flipped.reshape(4, -1), planes.reshape(4, -1))
    return out


def dd_apply_perm_1q(planes, num_qubits: int, target: int, control: int = -1):
    """Error-free permutation gates: X on ``target`` (optionally controlled
    — CNOT). Pure index shuffling, no rounding at all."""
    if control == target:
        raise ValueError("the control qubit must differ from the target")
    return _dd_apply_perm_1q_jit(planes, num_qubits, target, control)


def _split_iotas(num_amps: int):
    """(hi, lo, lo_bits) int32 index-half iotas over [0, num_amps) — no
    64-bit index vector is ever materialised."""
    lo_bits = min(20, max(num_amps.bit_length() - 1, 0))
    nlo = 1 << lo_bits
    nhi = num_amps // nlo
    hi = jax.lax.broadcasted_iota(jnp.int32, (nhi, nlo), 0)
    lo = jax.lax.broadcasted_iota(jnp.int32, (nhi, nlo), 1)
    return hi, lo, lo_bits


def _index_bits_cond(num_amps: int, mask: int, pattern: int):
    """(idx & mask) == pattern over [0, num_amps), shape (num_amps,)."""
    hi, lo, lo_bits = _split_iotas(num_amps)
    nlo = 1 << lo_bits
    cond = ((hi & (mask >> lo_bits)) == (pattern >> lo_bits)) \
        & ((lo & (mask & (nlo - 1))) == (pattern & (nlo - 1)))
    return cond.reshape(num_amps)


def _dd_split_host(z: np.ndarray, dtype=np.float32) -> np.ndarray:
    """complex128 array -> (4, ...) dd planes (host-side)."""
    z = np.asarray(z, dtype=np.complex128)
    re_hi = z.real.astype(dtype)
    im_hi = z.imag.astype(dtype)
    return np.stack([re_hi, (z.real - re_hi).astype(dtype),
                     im_hi, (z.imag - im_hi).astype(dtype)])


def _dd_u1_traced(planes, u_dd, num_qubits, target, ctrl_mask, flip_mask):
    """Trace-time body of the (controlled) dd 1q dense gate."""
    out = _dd_apply_1q_body(planes, u_dd, num_qubits, target)
    if ctrl_mask:
        cond = _index_bits_cond(planes.shape[1], ctrl_mask,
                                ctrl_mask ^ flip_mask)
        out = jnp.where(cond[None, :], out, planes)
    return out


def _dd_apply_1q_body(planes, u_dd, num_qubits, target):
    pre = 1 << (num_qubits - 1 - target)
    post = 1 << target
    t = planes.reshape(4, pre, 2, post)
    z0 = tuple(t[i, :, 0, :] for i in range(4))
    z1 = tuple(t[i, :, 1, :] for i in range(4))
    rows = []
    for r in range(2):
        acc = None
        for c, z in ((0, z0), (1, z1)):
            u_re = (u_dd[0, r, c], u_dd[1, r, c])
            u_im = (u_dd[2, r, c], u_dd[3, r, c])
            acc = _cplx_mul_acc(acc, u_re, u_im, z)
        rows.append(acc)
    out = jnp.stack([jnp.stack([rows[0][i], rows[1][i]], axis=1)
                     for i in range(4)])
    return out.reshape(4, -1)


def _dd_diag_traced(planes, f_dd, num_qubits, targets_desc):
    """Multiply by a diagonal factor tensor (framework axis order: axis i
    indexed by the bit of ``targets_desc[i]``, qubits sorted descending).
    ``f_dd``: (4, 2^k) dd-split factors."""
    n_amps = planes.shape[1]
    k = len(targets_desc)
    hi, lo, lo_bits = _split_iotas(n_amps)
    gidx = jnp.zeros(hi.shape, jnp.int32)
    for i, q in enumerate(targets_desc):
        bit = ((hi >> (q - lo_bits)) if q >= lo_bits else (lo >> q)) & 1
        gidx = gidx | (bit << (k - 1 - i))
    f = f_dd[:, gidx.reshape(n_amps)]               # (4, n_amps)
    out = _cplx_mul_acc(None, (f[0], f[1]), (f[2], f[3]),
                        (planes[0], planes[1], planes[2], planes[3]))
    return jnp.stack(list(out))


def dd_apply_diag(planes, num_qubits: int, factors: np.ndarray,
                  targets_desc) -> jnp.ndarray:
    """Apply a static diagonal factor tensor in dd arithmetic (factors
    dd-split to the planes' dtype)."""
    f_dd = _dd_split_host(np.asarray(factors, np.complex128).reshape(-1),
                          np.dtype(planes.dtype))
    return _dd_diag_jit(planes, jnp.asarray(f_dd), num_qubits,
                        tuple(int(q) for q in targets_desc))


@functools.partial(jax.jit, static_argnums=(2, 3))
def _dd_diag_jit(planes, f_dd, num_qubits, targets_desc):
    return _dd_diag_traced(planes, f_dd, num_qubits, targets_desc)


# --- API-tier kernels (the QuEST_PREC=4 register mode) ---------------------
#
# The reference's quad build applies to EVERY op (``QuEST_precision.h:
# 53-65``); these kernels complete the dd gate set so a quad-precision
# register replays the whole golden corpus through the public API
# (VERDICT r3 Missing #4): k-qubit dense gates with arbitrary
# control/flip masks, collapse, and the scalar reductions.

def _dd_apply_kq_body(planes, u_dd, num_qubits, targets_desc):
    """Dense 2^k x 2^k gate in dd arithmetic. ``u_dd``: (4, 2^k, 2^k)
    dd-split matrix already reordered to sorted-descending bit order.

    Small k unrolls (fully fusable); k >= 3 runs a ``lax.scan`` over
    matrix rows/columns so the traced program is O(2^k) instead of
    O(4^k) — a 6-qubit fused superoperator would otherwise trace ~10^5
    primitives and stall compilation. Runtime flops are identical (each
    scan step is a full-width vector op)."""
    from ..core.apply import split_shape
    k = len(targets_desc)
    shape = split_shape(num_qubits, targets_desc)
    t = planes.reshape((4,) + shape)
    blocks = tuple(shape[2 * i] for i in range(k)) + (shape[-1],)

    def sub(m):
        idx = [slice(None)] * (len(shape) + 1)
        for i in range(k):
            idx[2 * i + 2] = (m >> (k - 1 - i)) & 1
        return t[tuple(idx)]                      # (4,) + blocks

    subs = jnp.stack([sub(m) for m in range(1 << k)])   # (2^k, 4, *blocks)

    if k <= 2:
        rows = []
        for r in range(1 << k):
            acc = None
            for c in range(1 << k):
                u_re = (u_dd[0, r, c], u_dd[1, r, c])
                u_im = (u_dd[2, r, c], u_dd[3, r, c])
                z = tuple(subs[c, i] for i in range(4))
                acc = _cplx_mul_acc(acc, u_re, u_im, z)
            rows.append(acc)
        stacked = jnp.stack([jnp.stack(list(row)) for row in rows])
    else:
        zeros = jnp.zeros(subs.shape[1:], subs.dtype)

        def col_step(acc, uc):
            u_sc, z = uc
            u_re = (u_sc[0], u_sc[1])
            u_im = (u_sc[2], u_sc[3])
            out = _cplx_mul_acc(tuple(acc[i] for i in range(4)),
                                u_re, u_im, tuple(z[i] for i in range(4)))
            return jnp.stack(list(out)), None

        def row_step(_, u_row):
            # u_row: (4, 2^k) dd entries of this row
            acc, _ = jax.lax.scan(col_step, zeros, (u_row.T, subs))
            return None, acc

        _, stacked = jax.lax.scan(row_step, None,
                                  jnp.moveaxis(u_dd, 1, 0))  # (2^k, 4, 2^k)

    stacked = stacked.reshape((2,) * k + (4,) + blocks)
    perm = [k]
    for i in range(k):
        perm.append(k + 1 + i)
        perm.append(i)
    perm.append(2 * k + 1)
    return stacked.transpose(perm).reshape(4, -1)


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5))
def _dd_apply_kq_jit(planes, u_dd, num_qubits, targets_desc, ctrl_mask,
                     flip_mask):
    out = _dd_apply_kq_body(planes, u_dd, num_qubits, targets_desc)
    if ctrl_mask:
        cond = _index_bits_cond(planes.shape[1], ctrl_mask,
                                ctrl_mask ^ flip_mask)
        out = jnp.where(cond[None, :], out, planes)
    return out


def dd_apply_kq(planes, num_qubits: int, u: np.ndarray, targets,
                ctrl_mask: int = 0, flip_mask: int = 0):
    """Apply a dense k-qubit (controlled) unitary to dd planes. ``u`` is
    host complex128 in user bit order (bit j of the index addresses
    ``targets[j]``, the ComplexMatrixN convention)."""
    from ..core.apply import permutation_to_sorted_desc
    targets = tuple(int(t) for t in targets)
    perm = permutation_to_sorted_desc(targets)
    u = np.asarray(u, dtype=np.complex128)
    if not np.array_equal(perm, np.arange(u.shape[0])):
        u = u[perm][:, perm]
    desc = tuple(sorted(targets, reverse=True))
    u_dd = jnp.asarray(_dd_split_host(u, np.dtype(planes.dtype)))
    return _dd_apply_kq_jit(planes, u_dd, num_qubits, desc,
                            int(ctrl_mask), int(flip_mask))


def _dd_scalar(x: float, dtype) -> tuple[float, float]:
    hi = np.dtype(dtype).type(x)
    return float(hi), float(np.float64(x) - np.float64(hi))


@functools.partial(jax.jit, static_argnums=(1, 2))
def _dd_prob_zero_sv_jit(planes, num_qubits, qubit):
    pre = 1 << (num_qubits - 1 - qubit)
    post = 1 << qubit
    s = planes.reshape(4, pre, 2, post)[:, :, 0, :]
    vals, errs = [], []
    for h, l in ((s[0], s[1]), (s[2], s[3])):
        p, e = _two_prod(h, h)
        e = e + 2.0 * h * l + l * l
        vals.append(p.reshape(-1))
        errs.append(e.reshape(-1))
    return (sum_pair(jnp.concatenate(vals)),
            sum_pair(jnp.concatenate(errs)))


def dd_prob_zero_sv(planes, num_qubits: int, qubit: int) -> float:
    (s, se), (t, te) = _dd_prob_zero_sv_jit(planes, num_qubits, qubit)
    return (float(s) + float(se)) + (float(t) + float(te))


@functools.partial(jax.jit, static_argnums=(1,))
def _dd_diag_pairs_dm(planes, num_qubits):
    dim = 1 << num_qubits
    d_hi = jnp.diagonal(planes[0].reshape(dim, dim))
    d_lo = jnp.diagonal(planes[1].reshape(dim, dim))
    return sum_pair(d_hi), sum_pair(d_lo)


def dd_total_prob_dm(planes, num_qubits: int) -> float:
    """Trace of a dd flat density vector (real diagonal sum)."""
    (s, se), (t, te) = _dd_diag_pairs_dm(planes, num_qubits)
    return (float(s) + float(se)) + (float(t) + float(te))


@functools.partial(jax.jit, static_argnums=(1, 2))
def _dd_prob_zero_dm_jit(planes, num_qubits, qubit):
    dim = 1 << num_qubits
    pre = 1 << (num_qubits - 1 - qubit)
    post = 1 << qubit
    pairs = []
    for plane in (planes[0], planes[1]):
        diag = jnp.diagonal(plane.reshape(dim, dim))
        sel = diag.reshape(pre, 2, post)[:, 0, :]
        pairs.append(sum_pair(sel.reshape(-1)))
    return pairs[0], pairs[1]


def dd_prob_zero_dm(planes, num_qubits: int, qubit: int) -> float:
    (s, se), (t, te) = _dd_prob_zero_dm_jit(planes, num_qubits, qubit)
    return (float(s) + float(se)) + (float(t) + float(te))


@functools.partial(jax.jit, static_argnums=(1, 3, 4))
def _dd_collapse_jit(planes, num_qubits, scale_dd, keep_mask, keep_pattern):
    """Zero amplitudes whose (mask) bits mismatch ``keep_pattern``; scale
    the survivors by the dd scalar ``scale_dd`` (renormalisation)."""
    sh, sl = scale_dd[0], scale_dd[1]
    out = []
    for h, l in ((planes[0], planes[1]), (planes[2], planes[3])):
        nh, nl = _dd_mul(h, l, sh, sl)
        out.extend([nh, nl])
    scaled = jnp.stack([out[0], out[1], out[2], out[3]])
    cond = _index_bits_cond(planes.shape[1], keep_mask, keep_pattern)
    return jnp.where(cond[None, :], scaled, jnp.zeros_like(planes))


def dd_collapse(planes, num_qubits: int, qubit: int, outcome: int,
                prob: float, density: bool = False):
    """Collapse-to-known-prob in dd: statevector renorm 1/sqrt(p)
    (``QuEST_cpu.c:3346``), density renorm 1/p with row AND column
    projection (``QuEST_cpu.c:790``)."""
    if density:
        n = num_qubits // 2
        mask = (1 << qubit) | (1 << (qubit + n))
        pattern = outcome * mask
        scale = 1.0 / prob
    else:
        mask = 1 << qubit
        pattern = outcome << qubit
        scale = 1.0 / np.sqrt(prob)
    s_dd = jnp.asarray(_dd_scalar(scale, planes.dtype),
                       dtype=planes.dtype)
    return _dd_collapse_jit(planes, num_qubits, s_dd, mask, pattern)


@functools.partial(jax.jit, static_argnums=(2,))
def _dd_vdot_pairs(a, b, conj_a):
    """sum conj(a) * b (or plain a*b) in dd; returns compensated pairs for
    (re, im) hi and lo streams."""
    sign = -1.0 if conj_a else 1.0
    arh, arl, aih, ail = a[0], a[1], sign * a[2], sign * a[3]
    brh, brl, bih, bil = b[0], b[1], b[2], b[3]
    re = _dd_add(*_dd_mul(arh, arl, brh, brl),
                 *_dd_neg(*_dd_mul(aih, ail, bih, bil)))
    im = _dd_add(*_dd_mul(arh, arl, bih, bil),
                 *_dd_mul(aih, ail, brh, brl))
    return (sum_pair(re[0].reshape(-1)), sum_pair(re[1].reshape(-1)),
            sum_pair(im[0].reshape(-1)), sum_pair(im[1].reshape(-1)))


def dd_vdot(a_planes, b_planes, conj_a: bool = True) -> complex:
    pr, pre_, pi, pie = _dd_vdot_pairs(a_planes, b_planes, conj_a)
    re = (float(pr[0]) + float(pr[1])) + (float(pre_[0]) + float(pre_[1]))
    im = (float(pi[0]) + float(pi[1])) + (float(pie[0]) + float(pie[1]))
    return complex(re, im)


@jax.jit
def _dd_weighted_jit(facs_dd, s1, s2, s3):
    """f1*s1 + f2*s2 + f3*s3 in dd complex arithmetic; ``facs_dd``:
    (3, 4) dd-split complex scalars."""
    acc = None
    for i, s in enumerate((s1, s2, s3)):
        z = (s[0], s[1], s[2], s[3])
        u_re = (facs_dd[i, 0], facs_dd[i, 1])
        u_im = (facs_dd[i, 2], facs_dd[i, 3])
        acc = _cplx_mul_acc(acc, u_re, u_im, z)
    return jnp.stack(list(acc))


@functools.partial(jax.jit, static_argnums=(1,))
def _dd_outer_jit(planes, conj_left):
    """(4, dim) psi -> (4, dim^2) outer-product flat vector with
    ``flat[r + c*dim] = left(psi_r) * right(psi_c)`` where ``conj_left``
    selects ``conj(psi_r) * psi_c`` (fidelity weights) vs
    ``psi_r * conj(psi_c)`` (|psi><psi| in the register's flat layout).
    Full dd arithmetic: the lo planes survive, so QUAD64 keeps its
    ~106-bit envelope through these ops."""
    rh, rl, ih, il = planes[0], planes[1], planes[2], planes[3]
    ls = -1.0 if conj_left else 1.0
    rs = 1.0 if conj_left else -1.0
    # r varies fastest in the flat index: r is the LAST axis
    u_re = (rh[:, None], rl[:, None])                 # c axis first
    u_im = (rs * ih[:, None], rs * il[:, None])
    z = (rh[None, :], rl[None, :], ls * ih[None, :], ls * il[None, :])
    out = _cplx_mul_acc(None, u_re, u_im, z)          # (dim_c, dim_r) each
    return jnp.stack([p.reshape(-1) for p in out])


def dd_outer(planes, conj_left: bool = False):
    return _dd_outer_jit(planes, bool(conj_left))


def dd_weighted(fac1, s1, fac2, s2, fac3, s3):
    """Weighted combination of three dd registers (setWeightedQureg /
    mixDensityMatrix analogue)."""
    dt = np.dtype(s1.dtype)
    facs = np.empty((3, 4), dtype=dt)
    for i, f in enumerate((fac1, fac2, fac3)):
        f = complex(f)
        facs[i, 0], facs[i, 1] = _dd_scalar(f.real, dt)
        facs[i, 2], facs[i, 3] = _dd_scalar(f.imag, dt)
    return _dd_weighted_jit(jnp.asarray(facs), s1, s2, s3)


_SWAP_MAT = np.array([[1, 0, 0, 0], [0, 0, 1, 0],
                      [0, 1, 0, 0], [0, 0, 0, 1]], dtype=np.complex128)
_X_MAT = np.array([[0, 1], [1, 0]], dtype=np.complex128)


class DDProgram:
    """A gate program compiled to the double-double amplitude path: the
    reference's quad-precision build analogue (``QuEST_precision.h:60-65``)
    for TPU hardware, as one jitted donated-buffer executable.

    Supported ops (raises ``ValueError`` at build time otherwise): static
    single-target dense gates with any control mask (X with one control
    lowers to the exactly-error-free permutation kernel), static diagonal
    gates on any qubit set (the phase family), and SWAP (decomposed into
    three CNOT permutations — exact). Parameterised gates and multi-target
    dense gates are native-precision-only for now.

    On a mesh environment the (4, 2^n) planes shard on the amplitude axis
    (same chunkId-prefix layout as every other register form) and a
    sharding constraint after each op keeps GSPMD from drifting the
    layout; cross-shard targets lower to XLA collectives exactly as in
    the native-precision path.

    Built via :meth:`quest_tpu.circuits.Circuit.compile_dd`.
    """

    def __init__(self, ops, num_qubits: int, sharding=None,
                 dtype=np.float32):
        self.num_qubits = num_qubits
        self.sharding = sharding
        # float32 planes: ~48-bit significand (TPU hardware);
        # float64 planes (CPU/x64): ~106 bits — the quad-build analogue
        self.dtype = np.dtype(dtype)
        if self.dtype == np.float64 and not jax.config.jax_enable_x64:
            raise ValueError(
                "float64 dd planes require jax_enable_x64; without it JAX "
                "would silently downcast to float32 and the quad-tier "
                "accuracy would quietly not exist")
        plan = []
        for op in ops:
            plan.extend(self._lower(op))
        self._plan = plan

        def run_body(planes):
            for step in plan:
                # the barrier stops XLA's algebraic simplifier from folding
                # the error-free transformations ACROSS op boundaries (with
                # producer ops visible it can prove e.g. a TwoSum error term
                # is "zero" and delete it — measured: 1.4e-6 instead of
                # 4e-13 final error on QFT-6 without barriers). Each op
                # still fuses internally; the program stays one executable.
                planes = step(planes)
                if sharding is not None:
                    planes = jax.lax.with_sharding_constraint(planes,
                                                              sharding)
                planes = jax.lax.optimization_barrier(planes)
            return planes

        self._jitted = jax.jit(run_body, donate_argnums=(0,))

        dt = jnp.dtype(self.dtype)

        def init_zero_body():
            return jnp.zeros((4, 1 << num_qubits), dt).at[0, 0].set(1.0)

        self._init_zero_jit = jax.jit(
            init_zero_body, out_shardings=sharding) if sharding is not None \
            else jax.jit(init_zero_body)

    def _lower(self, op):
        if not op.is_static:
            raise ValueError(
                "parameterised gates are not supported in dd mode")
        if op.kind == "diag":
            f_dd = jnp.asarray(_dd_split_host(
                np.asarray(op.diag, np.complex128).reshape(-1),
                self.dtype))
            desc = op.targets
            return [lambda p, f=f_dd, d=desc: _dd_diag_traced(
                p, f, self.num_qubits, d)]
        if op.kind != "u":
            raise ValueError(f"op kind {op.kind!r} unsupported in dd mode")
        if len(op.targets) == 2 and np.array_equal(op.mat, _SWAP_MAT) \
                and not op.ctrl_mask:
            a, b = op.targets
            seq = [(a, b), (b, a), (a, b)]
            return [lambda p, t=t, c=c: _dd_apply_perm_1q_jit(
                p, self.num_qubits, t, c) for t, c in seq]
        if len(op.targets) != 1:
            raise ValueError(
                "multi-target dense gates are not supported in dd mode")
        target = op.targets[0]
        if np.array_equal(op.mat, _X_MAT) and not op.flip_mask \
                and bin(op.ctrl_mask).count("1") <= 1:
            ctrl = op.ctrl_mask.bit_length() - 1 if op.ctrl_mask else -1
            return [lambda p, t=target, c=ctrl: _dd_apply_perm_1q_jit(
                p, self.num_qubits, t, c)]
        u_dd = jnp.asarray(_dd_split_host(op.mat, self.dtype))
        cm, fm = op.ctrl_mask, op.flip_mask
        return [lambda p, u=u_dd, t=target, c=cm, f=fm: _dd_u1_traced(
            p, u, self.num_qubits, t, c, f)]

    # -- execution --------------------------------------------------------

    def init_zero(self) -> jnp.ndarray:
        return self._init_zero_jit()

    def pack(self, host_state: np.ndarray) -> jnp.ndarray:
        planes = _dd_split_host(np.asarray(host_state, np.complex128),
                                self.dtype)
        if self.sharding is None:
            return jnp.asarray(planes)
        if jax.process_count() > 1:
            # multi-host: build only this process's addressable shards
            # (same pattern as Qureg.device_put, qureg.py)
            return jax.make_array_from_callback(
                planes.shape, self.sharding, lambda idx: planes[idx])
        # single-host: place the host array directly with the target
        # sharding — no staging of the full state through one device
        return jax.device_put(planes, self.sharding)

    def unpack(self, planes) -> np.ndarray:
        if self.sharding is not None and jax.process_count() > 1:
            from jax.experimental import multihost_utils
            planes = multihost_utils.process_allgather(planes, tiled=True)
        return dd_unpack(np.asarray(planes))

    def run(self, planes) -> jnp.ndarray:
        return self._jitted(planes)

    def total_prob(self, planes) -> float:
        return dd_total_prob(planes)


@jax.jit
def _dd_total_prob_pairs(planes):
    vals = []
    errs = []
    for h, l in ((planes[0], planes[1]), (planes[2], planes[3])):
        p, e = _two_prod(h, h)
        e = e + 2.0 * h * l + l * l
        vals.append(p.reshape(-1))
        errs.append(e.reshape(-1))
    return (sum_pair(jnp.concatenate(vals)),
            sum_pair(jnp.concatenate(errs)))


def dd_total_prob(planes):
    """sum |amp|^2 combined in host double precision: per-element dd square
    streams + compensated reduction — error ~2^-49 relative."""
    (s, se), (t, te) = _dd_total_prob_pairs(planes)
    return (float(s) + float(se)) + (float(t) + float(te))
