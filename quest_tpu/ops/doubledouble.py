"""Double-double (two-float32) amplitude arithmetic — high-precision mode.

The reference offers double and quad-precision builds (``QuEST_PREC`` ∈
{1,2,4}, ``QuEST_precision.h:28-65``) because deep circuits accumulate
per-gate rounding without bound. TPU hardware has no f64 ALU, so the
high-precision amplitude story is *double-double*: each amplitude component
is an unevaluated sum ``hi + lo`` of two float32 (~48 significand bits,
unit roundoff ~2^-49 ≈ 1.8e-15), stored as four planes
``(4, 2^n) = [re_hi, re_lo, im_hi, im_lo]``.

All primitives are branch-free elementwise VPU ops (Dekker/Knuth
error-free transformations, same family as ops/reductions.py):

- ``_two_sum``      exact a+b -> (fl(a+b), rounding error)
- ``_two_prod``     exact a*b via Veltkamp split partial products
- ``_dd_add/_dd_mul`` renormalising double-double add / multiply

Scope (prototype, VERDICT r2 item 3): the 1-qubit gate kernel (covers the
rotation/brickwork workloads that dominate depth), error-free permutation
gates (X / CNOT), and the summed probability. Measured in
``tests/test_doubledouble.py`` (table in docs/accuracy.md): after 1000
random 1q gates at f32 storage, max amplitude error vs an f64 oracle is
~6e-15 (plain f32: ~1.4e-7) and totalProb matches f64 to ~1e-16 — the
reference's double-build envelope reached with pure-f32 hardware
arithmetic at ~6x the flop count of the plain kernel (still memory-bound:
2x the bytes of a complex64 state).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .reductions import sum_pair, _split, _two_sum

__all__ = ["dd_pack", "dd_unpack", "dd_apply_1q", "dd_apply_perm_1q",
           "dd_total_prob"]


def _quick_two_sum(a, b):
    """Assumes |a| >= |b| (holds for renormalisation: b is an error term)."""
    s = a + b
    return s, b - (s - a)


def _two_prod(a, b):
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    e = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, e


def _dd_add(xh, xl, yh, yl):
    s, e = _two_sum(xh, yh)
    e = e + (xl + yl)
    return _quick_two_sum(s, e)


def _dd_mul(xh, xl, yh, yl):
    p, e = _two_prod(xh, yh)
    e = e + (xh * yl + xl * yh)
    return _quick_two_sum(p, e)


def _dd_neg(xh, xl):
    return -xh, -xl


# --- packing ---------------------------------------------------------------

def dd_pack(z: np.ndarray) -> jnp.ndarray:
    """complex128 host vector -> (4, n) float32 dd planes."""
    z = np.asarray(z, dtype=np.complex128)
    re_hi = z.real.astype(np.float32)
    re_lo = (z.real - re_hi).astype(np.float32)
    im_hi = z.imag.astype(np.float32)
    im_lo = (z.imag - im_hi).astype(np.float32)
    return jnp.asarray(np.stack([re_hi, re_lo, im_hi, im_lo]))


def dd_unpack(planes) -> np.ndarray:
    p = np.asarray(planes, dtype=np.float64)
    return (p[0] + p[1]) + 1j * (p[2] + p[3])


# --- kernels ---------------------------------------------------------------

def _cplx_mul_acc(acc, u_re, u_im, z):
    """acc += u * z in dd complex arithmetic. ``u_re``/``u_im`` are dd
    scalars, ``z``/``acc`` are tuples of 4 dd-plane arrays
    (re_hi, re_lo, im_hi, im_lo)."""
    zrh, zrl, zih, zil = z
    # re: ur*zr - ui*zi
    t1 = _dd_mul(u_re[0], u_re[1], zrh, zrl)
    t2 = _dd_mul(u_im[0], u_im[1], zih, zil)
    re = _dd_add(*t1, *_dd_neg(*t2))
    # im: ur*zi + ui*zr
    t3 = _dd_mul(u_re[0], u_re[1], zih, zil)
    t4 = _dd_mul(u_im[0], u_im[1], zrh, zrl)
    im = _dd_add(*t3, *t4)
    if acc is None:
        return re + im                       # (rh, rl, ih, il)
    arh, arl, aih, ail = acc
    re = _dd_add(arh, arl, *re)
    im = _dd_add(aih, ail, *im)
    return re + im


@functools.partial(jax.jit, static_argnums=(2, 3))
def _dd_apply_1q_jit(planes, u_dd, num_qubits, target):
    """Fused dd 1q-gate kernel: one compiled pass over the planes (the ~30
    EFT primitives fuse under jit; eager dispatch would round-trip HBM per
    primitive). ``u_dd``: (4, 2, 2) f32 = [re_hi, re_lo, im_hi, im_lo]."""
    pre = 1 << (num_qubits - 1 - target)
    post = 1 << target
    t = planes.reshape(4, pre, 2, post)
    z0 = tuple(t[i, :, 0, :] for i in range(4))
    z1 = tuple(t[i, :, 1, :] for i in range(4))
    rows = []
    for r in range(2):
        acc = None
        for c, z in ((0, z0), (1, z1)):
            u_re = (u_dd[0, r, c], u_dd[1, r, c])
            u_im = (u_dd[2, r, c], u_dd[3, r, c])
            acc = _cplx_mul_acc(acc, u_re, u_im, z)
        rows.append(acc)
    out = jnp.stack([jnp.stack([rows[0][i], rows[1][i]], axis=1)
                     for i in range(4)])
    return out.reshape(4, -1)


def dd_apply_1q(planes, num_qubits: int, u: np.ndarray, target: int):
    """Apply a 1-qubit unitary (f64 numpy, dd-split internally) to dd
    planes of shape (4, 2^n)."""
    u = np.asarray(u, dtype=np.complex128)
    re_hi = u.real.astype(np.float32)
    im_hi = u.imag.astype(np.float32)
    u_dd = np.stack([re_hi, (u.real - re_hi).astype(np.float32),
                     im_hi, (u.imag - im_hi).astype(np.float32)])
    return _dd_apply_1q_jit(planes, jnp.asarray(u_dd), num_qubits, target)


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _dd_apply_perm_1q_jit(planes, num_qubits, target, control):
    pre = 1 << (num_qubits - 1 - target)
    post = 1 << target
    t = planes.reshape(4, pre, 2, post)
    flipped = t[:, :, ::-1, :]
    if control < 0:
        return flipped.reshape(4, -1)
    n = num_qubits
    idx = jnp.arange(1 << n)
    cbit = (idx >> control) & 1
    out = jnp.where(cbit[None, :].astype(bool),
                    flipped.reshape(4, -1), planes.reshape(4, -1))
    return out


def dd_apply_perm_1q(planes, num_qubits: int, target: int, control: int = -1):
    """Error-free permutation gates: X on ``target`` (optionally controlled
    — CNOT). Pure index shuffling, no rounding at all."""
    if control == target:
        raise ValueError("the control qubit must differ from the target")
    return _dd_apply_perm_1q_jit(planes, num_qubits, target, control)


@jax.jit
def _dd_total_prob_pairs(planes):
    vals = []
    errs = []
    for h, l in ((planes[0], planes[1]), (planes[2], planes[3])):
        p, e = _two_prod(h, h)
        e = e + 2.0 * h * l + l * l
        vals.append(p.reshape(-1))
        errs.append(e.reshape(-1))
    return (sum_pair(jnp.concatenate(vals)),
            sum_pair(jnp.concatenate(errs)))


def dd_total_prob(planes):
    """sum |amp|^2 combined in host double precision: per-element dd square
    streams + compensated reduction — error ~2^-49 relative."""
    (s, se), (t, te) = _dd_total_prob_pairs(planes)
    return (float(s) + float(se)) + (float(t) + float(te))
