"""Kraus-operator sets for the built-in decoherence channels.

Every channel in the reference is (or is equivalent to) a Kraus map applied
through the superoperator path (``QuEST_common.c:540-604``, ``densmatr_mixPauli``
``QuEST_common.c:675-695``). These builders produce the Kraus sets; the
dephasing channels additionally have diagonal fast paths in
``ops.densmatr``.
"""

from __future__ import annotations

import itertools

import numpy as np

from ..core.matrices import PAULI_MATS

__all__ = [
    "damping_kraus",
    "pauli_kraus_traceable",
    "damping_kraus_traceable",
    "dephasing_kraus_traceable",
    "depolarising_kraus",
    "depolarising_kraus_traceable",
    "pauli_kraus",
    "two_qubit_dephasing_kraus",
    "two_qubit_depolarising_kraus",
]


def damping_kraus(prob: float) -> list[np.ndarray]:
    """Amplitude damping: K0 = diag(1, sqrt(1-p)), K1 = sqrt(p)|0><1|."""
    k0 = np.array([[1.0, 0.0], [0.0, np.sqrt(1.0 - prob)]], dtype=np.complex128)
    k1 = np.array([[0.0, np.sqrt(prob)], [0.0, 0.0]], dtype=np.complex128)
    return [k0, k1]


def pauli_kraus(prob_x: float, prob_y: float, prob_z: float) -> list[np.ndarray]:
    """rho -> (1-px-py-pz) rho + px X rho X + py Y rho Y + pz Z rho Z."""
    probs = (1.0 - prob_x - prob_y - prob_z, prob_x, prob_y, prob_z)
    return [np.sqrt(p) * m for p, m in zip(probs, PAULI_MATS)]


def depolarising_kraus(prob: float) -> list[np.ndarray]:
    """Homogeneous single-qubit depolarising: px=py=pz=p/3."""
    return pauli_kraus(prob / 3.0, prob / 3.0, prob / 3.0)


def two_qubit_dephasing_kraus(prob: float) -> list[np.ndarray]:
    """rho -> (1-p) rho + p/3 (Z1 rho Z1 + Z2 rho Z2 + Z1Z2 rho Z1Z2)
    (``mixTwoQubitDephasing`` semantics). Kraus index bit 0 addresses the
    first target, so Z on the first target is kron(I, Z)."""
    z = PAULI_MATS[3]
    i2 = PAULI_MATS[0]
    w = np.sqrt(prob / 3.0)
    return [np.sqrt(1.0 - prob) * np.eye(4, dtype=np.complex128),
            w * np.kron(i2, z),
            w * np.kron(z, i2),
            w * np.kron(z, z)]


def two_qubit_depolarising_kraus(prob: float) -> list[np.ndarray]:
    """rho -> (1-p) rho + p/15 sum over the 15 non-identity two-qubit Paulis.

    Kraus index bit 0 addresses the first target (matrix convention of
    ``densmatr_applyTwoQubitKrausSuperoperator``), so the kron order is
    (second (x) first).
    """
    ops = []
    for i, j in itertools.product(range(4), range(4)):
        w = (1.0 - prob) if (i == 0 and j == 0) else prob / 15.0
        ops.append(np.sqrt(w) * np.kron(PAULI_MATS[j], PAULI_MATS[i]))
    return ops


# -- traceable (jnp) variants: Kraus sets whose probability is a tracer ----
# (Circuit.dephase/damp/depolarise with a Param strength). Same math as
# the static builders above — keep the pairs in sync.

def damping_kraus_traceable(prob) -> list:
    import jax.numpy as jnp
    k0 = (jnp.asarray([[1.0, 0.0], [0.0, 0.0]], dtype=complex)
          + jnp.sqrt(1.0 - prob)
          * jnp.asarray([[0.0, 0.0], [0.0, 1.0]], dtype=complex))
    k1 = jnp.sqrt(prob) * jnp.asarray([[0.0, 1.0], [0.0, 0.0]],
                                      dtype=complex)
    return [k0, k1]


def dephasing_kraus_traceable(prob) -> list:
    import jax.numpy as jnp
    return [jnp.sqrt(1.0 - prob) * jnp.eye(2, dtype=complex),
            jnp.sqrt(prob) * jnp.asarray(PAULI_MATS[3])]


def depolarising_kraus_traceable(prob) -> list:
    import jax.numpy as jnp
    return [jnp.sqrt(1.0 - prob) * jnp.eye(2, dtype=complex)] + [
        jnp.sqrt(prob / 3.0) * jnp.asarray(PAULI_MATS[c])
        for c in (1, 2, 3)]


def pauli_kraus_traceable(prob_x, prob_y, prob_z) -> list:
    import jax.numpy as jnp
    probs = (1.0 - prob_x - prob_y - prob_z, prob_x, prob_y, prob_z)
    return [jnp.sqrt(p) * jnp.asarray(m)
            for p, m in zip(probs, PAULI_MATS)]
