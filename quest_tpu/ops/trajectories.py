"""Quantum-trajectory (Monte-Carlo wavefunction) unraveling of noisy
circuits: channels applied stochastically to a STATEVECTOR.

The reference can simulate noise only on density matrices — 2^(2n)
amplitudes per register (``mixDamping`` etc. on the flattened vector,
``QuEST_common.c:540-604``). The trajectory method simulates the same
channel as an ensemble of 2^n-amplitude pure states: at each Kraus
channel, one operator ``K_j`` is drawn with the physical probability
``p_j = <psi| K_j^dag K_j |psi>`` and applied with renormalisation.
Averaging ``|psi><psi|`` over trajectories converges to the exact
density evolution at O(1/sqrt(T)) — exponentially cheaper per
trajectory, embarrassingly parallel across them.

TPU-native shape: the whole stochastic program is ONE jitted function of
``(state planes, PRNG key, param vector)`` — channel probabilities come
from a single state pass that builds the targets' 2^t x 2^t reduced
density matrix (every ``p_j`` is then a tiny trace against the
``E_j = K_j^dag K_j`` stack), the draw is a categorical over log
probabilities, and the chosen operator is applied by dynamic indexing
into the Kraus stack (``apply_unitary`` takes a traced matrix).

The TRAJECTORY axis is the batched engine's batch axis (ISSUE 10):

- :meth:`TrajectoryProgram.trajectory_sweep` runs ``T`` draws through
  one keyed, LRU-bounded executable (the engine's
  ``_BoundedExecutableCache``), with the mesh sharding mode priced by
  :func:`quest_tpu.parallel.layout.choose_batch_sharding` —
  trajectory-parallel (state replicated, keys split, zero collectives)
  while the per-device working set fits, amplitude-sharded past the
  memory wall — and non-divisible trajectory counts padded-and-masked
  with the engine's one-time warning instead of a hard error;
- :meth:`TrajectoryProgram.expectation` lowers Pauli-sum observables to
  the on-device xor-gather masks (:mod:`quest_tpu.ops.reductions`) and
  runs the ensemble in WAVES with a device-resident running
  (count, mean, M2) triple — one executable and ONE device->host
  transfer per wave, and convergence-based early stopping against a
  caller-stated ``sampling_budget`` (the target standard error);
- parameterized circuits are first-class: Param gates AND Param /
  callable-Kraus channels bind per call exactly like the deterministic
  sweep path, so noisy-VQE parameter sweeps run as ``(B, T)`` programs
  (:meth:`TrajectoryProgram.expectation_batch` — the serving runtime's
  ``kind="trajectory"`` dispatch).
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.apply import apply_unitary, apply_diagonal
from ..core.packing import pack, unpack
from ..telemetry import profile as _profile
from . import reductions as red

__all__ = ["TrajectoryProgram", "DensityMaterialisationError",
           "plan_waves", "DENSITY_DEBUG_QUBITS_ENV"]

DENSITY_DEBUG_QUBITS_ENV = "QUEST_TPU_DENSITY_DEBUG_QUBITS"
_DENSITY_DEBUG_DEFAULT = 14


class DensityMaterialisationError(ValueError):
    """``average_density`` was asked to materialise a 2^n x 2^n matrix
    past the debug-scale bound (``QUEST_TPU_DENSITY_DEBUG_QUBITS``,
    default 14). The scalable alternatives keep everything at
    statevector cost: :meth:`TrajectoryProgram.expectation` for
    observables, :meth:`TrajectoryProgram.trajectory_sweep` for the raw
    ensemble."""


def plan_waves(max_trajectories: int, wave_size: int,
               device_multiple: int = 1):
    """The wave schedule one convergence loop executes: a list of
    ``(start, live)`` slices of the up-front key array, every wave
    dispatched at the SAME padded bucket (``wave_size`` rounded up to
    ``device_multiple``) so the whole loop reuses one executable and
    padded rows are masked out of the statistics exactly. Host-side and
    pure — ``tools/traj_trace.py`` replays it offline."""
    if max_trajectories < 1:
        raise ValueError("max_trajectories must be >= 1")
    if wave_size < 1:
        raise ValueError("wave_size must be >= 1")
    mult = max(1, int(device_multiple))
    bucket = -(-int(wave_size) // mult) * mult
    waves = []
    start = 0
    while start < max_trajectories:
        live = min(bucket, max_trajectories - start)
        waves.append((start, live))
        start += live
    return waves, bucket


class TrajectoryProgram:
    """A recorded circuit lowered to a stochastic pure-state program.

    ``apply(state_f, key, params=None)`` is pure and jitted: packed
    float planes + PRNG key (+ bound parameters) -> packed planes.
    Unitary/diagonal ops apply as in the deterministic path; each Kraus
    channel consumes one ``fold_in`` of the key. Parameterized gates and
    channels (Param strengths, callable Kraus sets) bind at call time —
    one compiled program serves every binding. Batch with
    :meth:`trajectory_sweep` / :meth:`run_batch`; estimate observables
    with :meth:`expectation` (convergence-based early stopping).
    """

    tier = None          # trajectory dispatches run at the env precision
    is_density = False   # the point: pure states at statevector cost
    _digest_cached = None   # lazy program_digest (content-addressed)

    @property
    def program_digest(self) -> str:
        """Stable content digest of the recorded circuit (the perf
        ledger / dispatch-profiler key, shared with the deterministic
        compile path's :attr:`CompiledCircuit.program_digest`)."""
        if self._digest_cached is None:
            from ..serve.warmcache import circuit_digest
            d = circuit_digest(self.circuit, False)
            self._digest_cached = d or f"id-{id(self):x}"
        return self._digest_cached

    def __init__(self, circuit, env, pallas=None):
        self.env = env
        self.circuit = circuit
        self.num_qubits = circuit.num_qubits
        self.param_names = tuple(circuit.param_names)
        ops = []
        n_channels = 0
        # reuse the host-side peephole fusion every other compile path
        # gets; kraus and parameterized ops match neither fusion branch,
        # so they act as barriers and pass through untouched
        for op in circuit._fused_ops():
            if op.kind == "kraus":
                if callable(op.kraus):
                    # parameterized channel: the Kraus stack is built at
                    # bind time (traceable, jnp) — no CPTP validation is
                    # possible for a function (same contract as the
                    # density path); out-of-range bound strengths
                    # surface as NaN planes at run time
                    ops.append(("kraus_fn", op.targets, op.kraus,
                                n_channels))
                else:
                    from .. import validation as val
                    val.validate_kraus_ops(op.kraus, len(op.targets),
                                           "TrajectoryProgram",
                                           env.precision.eps)
                    stack = np.stack([np.asarray(k, dtype=np.complex128)
                                      for k in op.kraus])
                    # E_j = K_j^dag K_j, precomputed: channel
                    # probabilities then need only the reduced density
                    # of the targets
                    estack = np.einsum("kba,kbc->kac", stack.conj(),
                                       stack)
                    ops.append(("kraus", op.targets, (stack, estack),
                                n_channels))
                n_channels += 1
            elif op.kind == "u":
                data = op.mat_fn if op.mat_fn is not None else op.mat
                kind = "u_fn" if op.mat_fn is not None else "u"
                ops.append((kind, op.targets, data,
                            (op.ctrl_mask, op.flip_mask)))
            else:
                data = op.diag_fn if op.diag_fn is not None else op.diag
                kind = "diag_fn" if op.diag_fn is not None else "diag"
                ops.append((kind, op.targets, data, None))
        self._ops = ops
        self.num_channels = n_channels
        self._apply = jax.jit(self._apply_core)

        # batched-engine state: the keyed executable cache (same
        # LRU-bounded class and env knob as CompiledCircuit._batched
        # _cache), pad-and-mask warning latch, batch stats, and the
        # last convergence-loop accounting — all read/written under one
        # lock because the serving dispatcher drives this program from
        # its background thread while callers read dispatch_stats()
        from ..circuits import _BoundedExecutableCache
        self._cache = _BoundedExecutableCache(
            int(os.environ.get("QUEST_TPU_BATCH_CACHE", "16")))
        self._stats_lock = threading.RLock()
        self._batch_stats: Optional[dict] = None
        self._warned_nondivisible = False
        self._last_traj_stats: dict = {}
        self._empty_vec = None
        self._cost_model_cached = False
        self._cost_model = None
        self._host_bits = 0
        if env.mesh is not None and env.num_devices > 1:
            from ..parallel.multihost import host_topology
            topo = host_topology(env.mesh)
            shard_bits = env.num_devices.bit_length() - 1
            self._host_bits = min(topo.host_bits, shard_bits) if topo \
                else 0

        # Pallas layer path for the WAVE LOOP (ROADMAP item 4: "the
        # trajectory amp-mode wave loop has no Pallas layer path"):
        # static gate runs between channels fuse into LayerOps applied
        # by the batch-gridded layer kernel (one HBM pass per run,
        # whole wave at once), and an eligible static channel (all
        # targets on lane qubits) runs the FUSED per-trajectory Kraus
        # draw + apply + renorm kernel instead of the plain-XLA
        # categorical-draw -> stacked-operator-gather chain. Same knob
        # semantics as Circuit.compile (None = auto on TPU backends,
        # "interpret" for tests, False off); active only in the
        # unsharded ("none") dispatch mode — mesh modes keep the XLA
        # twin (GSPMD has no pallas_call partitioning rule), so the
        # cache key carries the path token. NOTE the fused kernel draws
        # by inverse-CDF from the key stream's uniform rather than the
        # XLA path's Gumbel categorical: statistically identical,
        # bitwise different — the pallas-on path is its own draw
        # stream.
        if pallas is None:
            pallas = os.environ.get("QUEST_TPU_PALLAS", "auto")
        interpret = pallas == "interpret"
        self._pallas_interpret = interpret
        enabled = pallas not in (False, "0", "off") and (
            interpret or jax.default_backend() in ("tpu", "axon")) \
            and self.num_qubits >= 7
        self._pallas_items = self._build_pallas_items() if enabled \
            else None

    def _build_pallas_items(self):
        """The layered item stream for the batched Pallas walker:
        ``("layer", LayerOp)`` for fused static runs, ``("kraus_fused",
        targets, (stack, estack, lane-embedded stack), idx)`` for
        channels the fused draw+apply kernel covers, the plain op
        tuples otherwise. Channel order (and so key fold-in indices)
        matches ``self._ops``."""
        from ..circuits import _collect_layers
        from . import pallas_kernels as pk
        n = self.num_qubits
        layered = _collect_layers(list(self.circuit._fused_ops()), n)
        kraus_tuples = [t for t in self._ops
                        if t[0] in ("kraus", "kraus_fn")]
        items = []
        ki = 0
        for op in layered:
            kind = getattr(op, "kind", None)
            if kind == "layer":
                items.append(("layer", op))
            elif kind == "kraus":
                t = kraus_tuples[ki]
                ki += 1
                if t[0] == "kraus" and all(
                        q < pk.LANE_QUBITS for q in t[1]):
                    stack, estack = t[2]
                    kemb = np.stack([pk.embed_lane_matrix(k, t[1])
                                     for k in stack])
                    items.append(("kraus_fused", t[1],
                                  (stack, estack, kemb), t[3]))
                else:
                    items.append(t)
            elif kind == "u":
                data = op.mat_fn if op.mat_fn is not None else op.mat
                items.append(("u_fn" if op.mat_fn is not None else "u",
                              op.targets, data,
                              (op.ctrl_mask, op.flip_mask)))
            else:
                data = op.diag_fn if op.diag_fn is not None else op.diag
                items.append(
                    ("diag_fn" if op.diag_fn is not None else "diag",
                     op.targets, data, None))
        return items

    # -- the per-trajectory program ----------------------------------------

    def _channel_probs(self, psi, targets, estack):
        """``p_j = <psi| E_j |psi> = tr(E_j rho_T)``: ONE state pass
        builds the 2^t x 2^t reduced density of the targets, then every
        probability is a tiny trace. HIGHEST: these feed the
        renormalisation, so the TPU bf16 matmul default would drift
        every trajectory's norm (same reason as core/apply.py)."""
        n = self.num_qubits
        k = len(targets)
        axes_front = [n - 1 - targets[j] for j in reversed(range(k))]
        rest = [ax for ax in range(n) if ax not in axes_front]
        a = jnp.transpose(psi.reshape((2,) * n),
                          axes_front + rest).reshape(1 << k, -1)
        rho_t = jnp.matmul(a, a.conj().T,
                           precision=jax.lax.Precision.HIGHEST)
        return jnp.real(jnp.einsum(
            "kab,ba->k", estack, rho_t,
            precision=jax.lax.Precision.HIGHEST))

    def _op_step(self, psi, key, params, op):
        """One op of the per-trajectory program on an UNPACKED complex
        state (shared by the single-trajectory jit and the batched XLA
        fallback's vmapped walker)."""
        return self._op_step_lp(psi, None, key, params, op)[0]

    def _op_step_lp(self, psi, logq, key, params, op):
        """:meth:`_op_step` with draw log-probability accounting: when
        ``logq`` is not None, every channel draw adds its NORMALISED
        log-probability ``log(p_j / sum_k p_k)`` to the running total —
        the measure term the gradient wave loop's score-function
        surrogate (:func:`quest_tpu.ops.reductions.score_surrogate`)
        needs for unbiased trajectory gradients. The drawn operator
        index and the state update are BITWISE the value path's (the
        categorical reads the same unnormalised log weights), so
        gradient waves replay the exact draw stream of the value
        waves under the same key."""
        n = self.num_qubits
        cdtype = self.env.precision.complex_dtype
        kind, targets, data, extra = op
        if kind in ("u", "u_fn"):
            cmask, fmask = extra
            u = data(params) if kind == "u_fn" else data
            return apply_unitary(psi, n, jnp.asarray(u, cdtype),
                                 targets, cmask, fmask), logq
        if kind in ("diag", "diag_fn"):
            d = data(params) if kind == "diag_fn" else data
            return apply_diagonal(psi, n, targets,
                                  jnp.asarray(d, cdtype)), logq
        if kind == "kraus_fn":
            kstack = jnp.stack(
                [jnp.asarray(m).astype(cdtype)
                 for m in data(params)])
            estack = jnp.einsum(
                "kba,kbc->kac", jnp.conj(kstack), kstack,
                precision=jax.lax.Precision.HIGHEST)
        else:
            kstack = jnp.asarray(data[0], cdtype)
            estack = jnp.asarray(data[1], cdtype)
        sub = jax.random.fold_in(key, extra)
        probs = self._channel_probs(psi, targets, estack)
        # categorical draw over the physical channel probs
        # (log space; zero-prob branches get ~-inf)
        tiny = jnp.finfo(probs.dtype).tiny
        logp = jnp.log(jnp.maximum(probs, tiny))
        j = jax.random.categorical(sub, logp)
        if logq is not None:
            logq = logq + logp[j] - jnp.log(
                jnp.maximum(jnp.sum(probs), tiny))
        psi = apply_unitary(psi, n, kstack[j], targets)
        return psi * jax.lax.rsqrt(
            jnp.maximum(probs[j], tiny)).astype(psi.dtype), logq

    def _apply_core(self, state_f, key, param_vec=None):
        if param_vec is None:
            params = {}
        else:
            params = {nm: param_vec[i]
                      for i, nm in enumerate(self.param_names)}
        psi = unpack(state_f)
        for op in self._ops:
            if op[0] == "kraus_fused":
                op = ("kraus", op[1], op[2][:2], op[3])
            psi = self._op_step(psi, key, params, op)
        return pack(psi)

    def _apply_core_lp(self, state_f, key, param_vec):
        """The gradient walker's form of :meth:`_apply_core`: returns
        the UNPACKED final state plus the accumulated draw
        log-probability (the score-surrogate's measure term). Same op
        order, same key folds — the draw stream is the value path's."""
        params = {nm: param_vec[i]
                  for i, nm in enumerate(self.param_names)}
        psi = unpack(state_f)
        logq = jnp.zeros((), dtype=self.env.precision.real_dtype)
        for op in self._ops:
            psi, logq = self._op_step_lp(psi, logq, key, params, op)
        return psi, logq

    def _apply_batch(self, state_f, keys, flat_pv):
        """The PALLAS wave-loop walker: the whole trajectory batch
        advances item by item — fused static runs through the
        batch-gridded layer kernel (:func:`quest_tpu.ops.
        pallas_kernels.apply_layer_batched`, one HBM pass per run for
        the WHOLE wave), eligible channels through the fused
        draw+apply+renorm Kraus kernel, everything else through the
        vmapped XLA step. Returns the ``(T, 2^n)`` complex batch."""
        from . import pallas_kernels as pk
        n = self.num_qubits
        T = keys.shape[0]
        psi0 = unpack(state_f)
        states = jnp.broadcast_to(psi0, (T,) + psi0.shape)
        interp = self._pallas_interpret
        for op in self._pallas_items:
            kind = op[0]
            if kind == "layer":
                states = pk.apply_layer_batched(states, n, op[1],
                                                interpret=interp)
                continue
            if kind == "kraus_fused":
                _, targets, (stack, estack, kemb), idx = op
                cdtype = self.env.precision.complex_dtype
                es = jnp.asarray(estack, cdtype)
                probs = jax.vmap(
                    lambda s: self._channel_probs(s, targets, es))(
                    states)
                subs = jax.vmap(
                    lambda k: jax.random.fold_in(k, idx))(keys)
                u01 = jax.vmap(
                    lambda k: jax.random.uniform(
                        k, dtype=probs.dtype))(subs)
                states = pk.fused_kraus_apply_batched(
                    states, n, kemb, probs, u01, interpret=interp)
                continue

            def step(s, k, vec, _op=op):
                params = {nm: vec[i]
                          for i, nm in enumerate(self.param_names)}
                return self._op_step(s, k, params, _op)

            states = jax.vmap(step)(states, keys, flat_pv)
        return states

    # -- parameters / operands ---------------------------------------------

    def _param_vec(self, params):
        """Name->angle dict (or ordered vector) -> the program's
        parameter vector; all declared names must bind (mirrors
        ``CompiledCircuit._param_vec``)."""
        if params is not None and not isinstance(params, dict):
            vec = jnp.asarray(params,
                              dtype=self.env.precision.real_dtype)
            if vec.shape != (len(self.param_names),):
                raise ValueError(
                    f"parameter vector has shape {vec.shape}; expected "
                    f"({len(self.param_names)},) ordered like "
                    f"{list(self.param_names)}")
            return vec
        params = params or {}
        missing = [p for p in self.param_names if p not in params]
        if missing:
            raise ValueError(f"missing circuit parameters: {missing}")
        vals = [params[nm] for nm in self.param_names]
        if not vals:
            if self._empty_vec is None:
                self._empty_vec = jnp.zeros(
                    (0,), dtype=self.env.precision.real_dtype)
            return self._empty_vec
        return jnp.asarray(vals, dtype=self.env.precision.real_dtype)

    def _validated_pauli_terms(self, pauli_terms, coeffs):
        """The serving runtime's Hamiltonian validation hook (same
        shape as ``CompiledCircuit._validated_pauli_terms``)."""
        nq = self.num_qubits
        for t in pauli_terms:
            for q, code in t:
                if not 0 <= int(q) < nq:
                    raise ValueError(
                        f"pauli qubit {q} out of range [0, {nq})")
                if int(code) not in (0, 1, 2, 3):
                    raise ValueError(f"invalid pauli code {code}")
        terms = [tuple((int(q), int(c)) for q, c in t if int(c) != 0)
                 for t in pauli_terms]
        coeffs = np.asarray(coeffs, dtype=np.float64)
        if len(coeffs) != len(terms):
            raise ValueError(f"{len(terms)} pauli terms but "
                             f"{len(coeffs)} coefficients")
        return nq, terms, coeffs

    def _pauli_operands(self, terms, coeffs):
        """Validated terms -> bucketed on-device mask operands (the
        PR-3 xor-gather encoding, :func:`quest_tpu.ops.reductions.
        pauli_sum_operands`)."""
        nq = self.num_qubits
        T = len(terms)
        codes = np.zeros((max(T, 1), nq), np.int64)
        for t, term in enumerate(terms):
            for q, code in term:
                if codes[t, q]:
                    raise ValueError(
                        f"pauli term {t} repeats qubit {q} (a product "
                        "of Paulis on one qubit is not a Pauli string)")
                codes[t, q] = code
        coeffs = np.asarray(coeffs, dtype=np.float64)
        if T == 0:
            coeffs = np.zeros((1,), np.float64)
        xm, ym, zm, cf = red.pauli_sum_operands(
            codes.reshape(-1), nq, coeffs)
        return T, xm, ym, zm, cf

    # -- sharding policy ----------------------------------------------------

    def _comm_model(self):
        if not self._cost_model_cached:
            from ..profiling import comm_model
            self._cost_model = comm_model(self.env) \
                if self.env.mesh is not None else None
            self._cost_model_cached = True
        return self._cost_model

    def _policy(self, batch: int, mem_factor: float = 1.0) -> dict:
        """The priced sharding decision for a ``batch``-trajectory wave
        (:func:`quest_tpu.parallel.layout.choose_batch_sharding`):
        trajectory-parallel while the replicated working set fits,
        amplitude-sharded past the wall, with the amp fallback's
        per-trajectory collectives counted by
        :func:`~quest_tpu.parallel.layout.traj_cross_shard_ops`.
        ``mem_factor=2.0`` is the gradient wave loop's pricing
        (primal + cotangent live together through the reverse walk)."""
        if self.env.mesh is None or self.env.num_devices < 2:
            return {"mode": "none"}
        from ..parallel.layout import (choose_batch_sharding,
                                       traj_cross_shard_ops)
        paired = [targets for kind, targets, _, _ in self._ops
                  if not kind.startswith("diag")]
        est = traj_cross_shard_ops(paired, self.num_qubits,
                                   self.env.num_devices)
        return choose_batch_sharding(
            self.num_qubits, batch, self.env.num_devices,
            np.dtype(self.env.precision.real_dtype).itemsize, est,
            cost_model=self._comm_model(), host_bits=self._host_bits,
            mem_factor=mem_factor)

    def _device_multiple(self) -> int:
        return self.env.num_devices if (
            self.env.mesh is not None and self.env.num_devices > 1) else 1

    def _resolve_mode(self, batch: int, shard_trajectories,
                      mem_factor: float = 1.0) -> str:
        """``shard_trajectories``: None -> the priced policy; True ->
        force trajectory-parallel (mesh required); False -> force
        unsharded."""
        if shard_trajectories is True:
            if self.env.mesh is None or self.env.num_devices < 2:
                raise ValueError(
                    "shard_trajectories needs a multi-device mesh env")
            return "batch"
        if shard_trajectories is False:
            return "none"
        return self._policy(batch, mem_factor=mem_factor)["mode"]

    def _padded_keys(self, key, num: int, mode: str):
        """Split ``num`` per-trajectory keys and pad to the device
        multiple in trajectory-parallel mode. The first ``num`` keys are
        ALWAYS ``split(key, num)`` — padding duplicates ``keys[0]`` into
        throwaway rows rather than changing the split width, so results
        are bit-identical across modes and pad amounts. One-time
        warning, matching the engine's sweep behaviour."""
        keys = jax.random.split(key, num)
        pad = 0
        if mode == "batch":
            D = self.env.num_devices
            pad = (-num) % D
            if pad:
                with self._stats_lock:
                    warn_now = not self._warned_nondivisible
                    self._warned_nondivisible = True
                if warn_now:
                    warnings.warn(
                        f"trajectory batch of {num} is not divisible by "
                        f"the {D}-device mesh; padding to {num + pad} "
                        f"and masking the {pad} extra draws (earlier "
                        "releases rejected the batch outright)",
                        UserWarning, stacklevel=4)
                keys = jnp.concatenate([keys] + [keys[:1]] * pad)
        return keys, pad

    def _place(self, state_f, keys, mode: str):
        """Commit the wave inputs to the policy's layout so the
        executable starts from the right placement: trajectory-parallel
        splits the KEYS (state replicated), amp mode splits the
        amplitude axis of the shared state (keys replicated)."""
        if mode == "none" or self.env.mesh is None:
            return state_f, keys
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..env import AMP_AXIS
        mesh = self.env.mesh
        if mode == "batch":
            keys = jax.device_put(keys, NamedSharding(mesh, P(AMP_AXIS)))
            state_f = jax.device_put(state_f, NamedSharding(mesh, P()))
        else:
            state_f = jax.device_put(
                state_f, NamedSharding(mesh, P(None, AMP_AXIS)))
        return state_f, keys

    def _out_constraint(self, mode: str, ndim: int = 3):
        """The sharding constraint pinned on a batched executable's
        (T, 2, 2^n) output (leading-axis split in trajectory-parallel
        mode, amplitude-axis split in amp mode)."""
        if mode == "none" or self.env.mesh is None:
            return lambda z: z
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..env import AMP_AXIS
        spec = [None] * ndim
        spec[0 if mode == "batch" else ndim - 1] = AMP_AXIS
        sh = NamedSharding(self.env.mesh, P(*spec))
        return lambda z: jax.lax.with_sharding_constraint(z, sh)

    def _record_batch_stats(self, batch: int, mode: str,
                            host_syncs_avoided: int) -> None:
        with self._stats_lock:
            self._batch_stats = {"batch_size": batch,
                                 "batch_sharding_mode": mode,
                                 "host_syncs_avoided": host_syncs_avoided}

    # -- batched executables (keyed, LRU-bounded) ---------------------------

    def _dt_token(self) -> str:
        return str(np.dtype(self.env.precision.real_dtype))

    def _cached(self, key, builder):
        with self._stats_lock:
            fn = self._cache.get(key)
        if fn is not None:
            return fn
        fn = builder()
        with self._stats_lock:
            # quest: allow-cache-key(the key is built at the _cached()
            # call sites, which the QL002 rule checks individually --
            # trajectory keys carry form+mode+dtype+kernel-path (the
            # pallas/xla token: the two paths trace different programs);
            # the tier ladder is rejected at the trajectory submit
            # boundary, so no tier)
            self._cache[key] = fn
        return fn

    def _use_pallas(self, mode: str) -> bool:
        """The Pallas layer path runs in the unsharded mode only: mesh
        modes dispatch under GSPMD, which has no ``pallas_call``
        partitioning rule (it would replicate the wave exactly where a
        mesh mode was chosen for memory)."""
        return self._pallas_items is not None and mode == "none"

    def _path_token(self, mode: str) -> str:
        return "pallas" if self._use_pallas(mode) else "xla"

    def _sweep_fn(self, mode: str):
        """The trajectory-sweep executable for one sharding mode:
        vmapped draws over the key axis (or the batched Pallas walker
        in the unsharded mode), output pinned to the policy's layout."""
        constrain = self._out_constraint(mode)
        use_p = self._use_pallas(mode)

        def build():
            if use_p:
                def fn(state_f, keys, pv):
                    flat_pv = jnp.broadcast_to(
                        pv, (keys.shape[0],) + pv.shape)
                    z = self._apply_batch(state_f, keys, flat_pv)
                    out = jnp.stack([jnp.real(z), jnp.imag(z)], axis=1)
                    return constrain(out)
            else:
                def fn(state_f, keys, pv):
                    out = jax.vmap(
                        lambda k: self._apply_core(state_f, k, pv))(keys)
                    return constrain(out)
            return jax.jit(fn)

        return self._cached(("tsweep", mode, self._dt_token(),
                             self._path_token(mode)), build)

    def _wave_fn(self, mode: str):
        """One convergence-loop wave for the ``(B, W)`` request-batch
        form (``B = 1`` is the single-ensemble path): run B*W draws
        (row b binds parameter row b), lower the Pauli sum to the
        on-device masks, fold the wave into the device-resident running
        (count, mean, M2) rows. ONE executable, and the returned
        ``(3, B)`` carry is the only device->host transfer the stop
        decision needs."""
        constrain = self._out_constraint(mode)
        use_p = self._use_pallas(mode)
        rdt = jnp.float64 if np.dtype(
            self.env.precision.real_dtype) == np.float64 else jnp.float32

        def build():
            def fn(state_f, flat_keys, pm, mask, xm, ym, zm, cf, carry):
                B = pm.shape[0]
                W = flat_keys.shape[0] // B
                flat_pv = jnp.repeat(pm, W, axis=0)
                if use_p:
                    z = self._apply_batch(state_f, flat_keys, flat_pv)
                else:
                    planes = jax.vmap(
                        lambda k, pv_: self._apply_core(
                            state_f, k, pv_))(flat_keys, flat_pv)
                    planes = constrain(planes)
                    z = jax.lax.complex(planes[:, 0], planes[:, 1])
                vals = jax.vmap(lambda s: red.pauli_sum_total_sv(
                    s, xm, ym, zm, cf))(z)
                vals = vals.reshape(B, W).astype(rdt)
                n_w, m_w, s_w = red.welford_wave(vals, mask)
                n, m, s = red.welford_merge(
                    (carry[0], carry[1], carry[2]), (n_w, m_w, s_w))
                return jnp.stack([n, m, s])
            return jax.jit(fn, donate_argnums=(8,))
        return self._cached(("twave", mode, self._dt_token(),
                             self._path_token(mode)), build)

    def _grad_wave_fn(self, mode: str):
        """One GRADIENT wave for the ``(B, W)`` form: every trajectory
        is differentiated by ``jax.value_and_grad`` through the
        score-function surrogate (:func:`quest_tpu.ops.reductions.
        score_surrogate` — pathwise + measure term, so the wave mean
        is an unbiased estimate of the density-path gradient), and the
        per-trajectory ``(P + 1)``-component (value, grad...) rows fold
        into a device-resident ``(3, B, P+1)`` Welford carry. ONE
        executable per wave, the carry its only transfer — noisy-VQE
        gradients ride the same early-stopping machinery as values.
        Always the vmapped XLA walker (``jax.grad`` has no rule for a
        compiled ``pallas_call``), so the kernel-path token is pinned
        ``"xla"``."""
        # the (B*W, P+1) value rows split on the trajectory axis in
        # batch mode; in amp mode they are tiny per-trajectory scalars
        # (the STATE carries the sharding) — no constraint
        constrain = self._out_constraint(mode, ndim=2) \
            if mode == "batch" else (lambda z: z)
        rdt = jnp.float64 if np.dtype(
            self.env.precision.real_dtype) == np.float64 else jnp.float32

        def build():
            def fn(state_f, flat_keys, pm, mask, xm, ym, zm, cf, carry):
                B = pm.shape[0]
                W = flat_keys.shape[0] // B
                flat_pv = jnp.repeat(pm, W, axis=0)
                # REINFORCE baseline: the running mean VALUE of each
                # row's earlier waves (carry mean column 0; zero on the
                # first wave, where count is 0 and the mean row is the
                # init zeros). Independent of this wave's draws, so the
                # score term stays unbiased while its v-weights centre
                # — the variance-reduction satellite of ISSUE 18.
                flat_bl = jnp.repeat(
                    jax.lax.stop_gradient(carry[1][:, 0]), W)

                def one(k, vec, bl):
                    def surrogate(v):
                        psi, logq = self._apply_core_lp(state_f, k, v)
                        val = red.pauli_sum_total_sv(psi, xm, ym, zm,
                                                     cf)
                        return red.score_surrogate(
                            val, logq.astype(val.dtype),
                            baseline=bl.astype(val.dtype)), val

                    (_, val), g = jax.value_and_grad(
                        surrogate, has_aux=True)(vec)
                    return jnp.concatenate(
                        [jnp.reshape(val, (1,)).astype(g.dtype), g])

                vals = jax.vmap(one)(flat_keys, flat_pv,
                                     flat_bl)  # (B*W, P+1)
                vals = constrain(vals)
                C = vals.shape[1]
                vals = vals.reshape(B, W, C).transpose(0, 2, 1)
                n_w, m_w, s_w = red.welford_wave(vals.astype(rdt), mask)
                n, m, s = red.welford_merge(
                    (carry[0], carry[1], carry[2]), (n_w, m_w, s_w))
                return jnp.stack([n, m, s])
            return jax.jit(fn, donate_argnums=(8,))
        return self._cached(("tgradwave", mode, self._dt_token(),
                             "xla"), build)

    # -- execution ---------------------------------------------------------

    def apply(self, state_f, key, params=None):
        """Pure form: packed planes + key -> packed planes (one draw).
        ``params`` binds the circuit's Param gates/channels."""
        return self._apply(state_f, key, self._param_vec(params))

    def run(self, qureg, key: Optional[jax.Array] = None,
            params=None) -> None:
        """One trajectory in place on a statevector register; the env RNG
        stream advances when ``key`` is not given."""
        if qureg.is_density_matrix:
            raise ValueError("trajectory programs run on statevector "
                             "registers (that is the point)")
        if qureg.num_qubits_represented != self.num_qubits:
            raise ValueError(
                f"program has {self.num_qubits} qubits; register has "
                f"{qureg.num_qubits_represented}")
        pv = self._param_vec(params)
        if key is None:
            key = self.env.next_key()
        qureg.ensure_canonical()   # the program addresses canonical bits
        qureg.state = self._apply(qureg.state, key, pv)

    def _default_state(self):
        return jnp.zeros((2, 1 << self.num_qubits),
                         dtype=self.env.precision.real_dtype
                         ).at[0, 0].set(1.0)

    def trajectory_sweep(self, num_trajectories: int, params=None,
                         state_f=None, key: Optional[jax.Array] = None,
                         shard_trajectories: Optional[bool] = None):
        """``num_trajectories`` independent draws from one initial packed
        state — a ``(T, 2, 2^n)`` batch through ONE keyed executable
        (the engine's batch axis; ``dispatch_stats()`` carries the
        batch accounting).

        On a mesh env the trajectory axis shards per the priced policy
        (:meth:`_policy`): trajectory-parallel (state replicated, keys
        split — noise unraveling is embarrassingly parallel, throughput
        scales linearly with mesh size) while the per-device working
        set fits, amplitude-sharded past the memory wall so big-n
        ensembles still run. Results are bit-identical across modes —
        the key array, not the placement, decides every draw — and
        non-divisible counts pad-and-mask with a one-time warning.
        ``shard_trajectories`` overrides the policy (True forces
        trajectory-parallel, False forces unsharded).

        One caveat: with the Pallas wave path on (``pallas=`` at
        compile), the UNSHARDED mode's fused Kraus kernel draws by
        inverse-CDF where the XLA twin draws categorically — the two
        KERNEL paths are separate (statistically identical) draw
        streams, so cross-mode bit-identity holds within a kernel path,
        not across the pallas/xla boundary."""
        T = int(num_trajectories)
        if T < 1:
            raise ValueError("num_trajectories must be >= 1")
        mode = self._resolve_mode(T, shard_trajectories)
        pv = self._param_vec(params)
        if key is None:
            key = self.env.next_key()
        if state_f is None:
            state_f = self._default_state()
        keys, pad = self._padded_keys(key, T, mode)
        state_f, keys = self._place(state_f, keys, mode)
        out = self._sweep_fn(mode)(state_f, keys, pv)
        self._record_batch_stats(T, mode, T - 1)
        return out[:T] if pad else out

    def run_batch(self, state_f, num_trajectories: int,
                  key: Optional[jax.Array] = None,
                  shard_trajectories: Optional[bool] = None,
                  params=None):
        """Pre-engine spelling of :meth:`trajectory_sweep` (state first,
        policy-driven sharding by default)."""
        return self.trajectory_sweep(num_trajectories, params=params,
                                     state_f=state_f, key=key,
                                     shard_trajectories=shard_trajectories)

    # -- observables with convergence-based early stopping ------------------

    def _default_wave(self, max_trajectories: int) -> int:
        return min(int(max_trajectories),
                   max(32, self._device_multiple()))

    def expectation(self, pauli_terms, coeffs, state_f=None,
                    num_trajectories: int = None,
                    key: Optional[jax.Array] = None, *, params=None,
                    sampling_budget: Optional[float] = None,
                    wave_size: Optional[int] = None,
                    shard_trajectories: Optional[bool] = None
                    ) -> tuple[float, float]:
        """Monte-Carlo estimate of ``<H>`` under the noisy evolution,
        ``H = sum_j coeffs[j] * prod Pauli`` (terms as ``(qubit, code)``
        pairs, codes 1=X 2=Y 3=Z). Returns ``(mean, stderr)`` over the
        trajectory ensemble — the noisy-VQE objective at statevector
        cost.

        The ensemble runs in WAVES of ``wave_size`` draws (default
        ``max(32, device count)``), each wave ONE executable whose
        Pauli sum lowers to the PR-3 on-device bit masks and whose
        running (count, mean, M2) stays device-resident — one
        device->host transfer per wave, never one per trajectory.
        ``sampling_budget`` (target standard error of the mean) turns
        on convergence-based early stopping: the loop stops at the
        first wave whose standard error fits the budget, so typical
        requests execute a fraction of ``num_trajectories``. The stop
        decision is a pure function of the seeded key stream —
        identical results on every replay. The accounting
        (``trajectories_run``, ``early_stopped``, waves, stderr) lands
        in :attr:`last_traj_stats` and the serving metrics."""
        from .. import validation as val
        if num_trajectories is None or int(num_trajectories) < 2:
            raise ValueError("expectation needs >= 2 trajectories for a "
                             "standard error")
        if sampling_budget is not None and sampling_budget <= 0.0:
            raise ValueError("sampling_budget is a target standard "
                             "error and must be > 0")
        T = int(num_trajectories)
        terms = []
        for t in pauli_terms:
            term = tuple((int(q), int(code)) for q, code in t)
            for q, code in term:
                val.validate_target(self.num_qubits, q,
                                    "TrajectoryProgram.expectation")
            val.validate_pauli_codes([code for _, code in term],
                                     "TrajectoryProgram.expectation")
            terms.append(term)
        coeffs = [float(c) for c in coeffs]
        if state_f is None:
            state_f = self._default_state()
        pm = jnp.reshape(self._param_vec(params),
                         (1, len(self.param_names)))
        mean, err, info = self._converge(
            pm, terms, coeffs, state_f, T, key,
            sampling_budget=sampling_budget, wave_size=wave_size,
            shard_trajectories=shard_trajectories)
        return float(mean[0]), float(err[0])

    def expectation_batch(self, param_matrix, hamiltonian,
                          num_trajectories: int,
                          key: Optional[jax.Array] = None, *,
                          sampling_budget: Optional[float] = None,
                          wave_size: Optional[int] = None,
                          live_rows: Optional[int] = None,
                          state_f=None, progress=None):
        """The ``(B, T)`` form: one noisy-VQE ensemble per parameter
        row, all rows advancing through shared waves of one executable
        (the serving runtime's ``kind="trajectory"`` dispatch). Early
        stopping waits for EVERY live row's standard error to fit the
        budget (``live_rows`` excludes the coalescer's padded rows from
        the decision). Returns ``(means, stderrs, info)`` with ``(B,)``
        arrays."""
        pm = jnp.asarray(param_matrix,
                         dtype=self.env.precision.real_dtype)
        if pm.ndim != 2 or pm.shape[1] != len(self.param_names):
            raise ValueError(
                f"param_matrix must be (batch, {len(self.param_names)}); "
                f"got {pm.shape}")
        if int(num_trajectories) < 2:
            raise ValueError("expectation needs >= 2 trajectories for a "
                             "standard error")
        terms_in, coeffs_in = hamiltonian
        _, terms, coeffs = self._validated_pauli_terms(terms_in,
                                                       coeffs_in)
        if state_f is None:
            state_f = self._default_state()
        means, errs, info = self._converge(
            pm, terms, [float(c) for c in coeffs], state_f,
            int(num_trajectories), key,
            sampling_budget=sampling_budget, wave_size=wave_size,
            live_rows=live_rows, progress=progress)
        return means, errs, info

    def expectation_grad(self, pauli_terms, coeffs, state_f=None,
                         num_trajectories: int = None,
                         key: Optional[jax.Array] = None, *,
                         params=None,
                         sampling_budget: Optional[float] = None,
                         wave_size: Optional[int] = None,
                         shard_trajectories: Optional[bool] = None):
        """Monte-Carlo estimate of ``<H>`` AND its parameter gradient
        under the noisy evolution — the noisy-VQE objective and its
        derivative from ONE wave loop. Returns ``(value, grad,
        stderr)``: the scalar energy, the ``(P,)`` gradient, and the
        ``(P + 1,)`` standard errors (component 0 the value's).

        Each trajectory differentiates through the stochastic trace
        with the score-function correction
        (:func:`quest_tpu.ops.reductions.score_surrogate`), so the
        ensemble mean converges to the DENSITY-path gradient — channel
        draws are parameter-dependent measures, and the pathwise
        derivative alone would be biased. Early stopping
        (``sampling_budget``) waits for EVERY component's standard
        error to fit, and the stop decision is a pure function of the
        seeded key stream — identical on every replay, sharing the
        value loop's per-row streams."""
        if num_trajectories is None or int(num_trajectories) < 2:
            raise ValueError("expectation_grad needs >= 2 trajectories "
                             "for a standard error")
        if not self.param_names:
            raise ValueError(
                "this circuit declares no parameters; there is nothing "
                "to differentiate (record angles via Circuit.parameter "
                "/ Param placeholders)")
        pm = jnp.reshape(self._param_vec(params),
                         (1, len(self.param_names)))
        _, terms, cfs = self._validated_pauli_terms(pauli_terms, coeffs)
        if state_f is None:
            state_f = self._default_state()
        means, errs, _info = self._converge(
            pm, terms, cfs, state_f, int(num_trajectories), key,
            sampling_budget=sampling_budget, wave_size=wave_size,
            shard_trajectories=shard_trajectories, grad=True)
        # quest: allow-host-sync(result boundary: the convergence loop
        # already synced its carry; means is a host array here)
        return float(means[0, 0]), means[0, 1:], errs[0]

    def expectation_grad_batch(self, param_matrix, hamiltonian,
                               num_trajectories: int,
                               key: Optional[jax.Array] = None, *,
                               sampling_budget: Optional[float] = None,
                               wave_size: Optional[int] = None,
                               live_rows: Optional[int] = None,
                               state_f=None, progress=None):
        """The ``(B, T)`` gradient form — one noisy-VQE ensemble per
        parameter row, every row's value AND gradient advancing through
        shared gradient waves of one executable (the serving runtime's
        ``kind="gradient"`` dispatch for trajectory programs). Early
        stopping waits for every live row's every component. Returns
        ``(values, grads, stderrs, info)``: ``(B,)``, ``(B, P)``,
        ``(B, P+1)`` arrays and the convergence accounting."""
        if not self.param_names:
            # BEFORE the shape check: the dedicated typed rejection
            # must not be preempted by a confusing (batch, 0) message
            raise ValueError(
                "this circuit declares no parameters; there is nothing "
                "to differentiate (record angles via Circuit.parameter "
                "/ Param placeholders)")
        pm = jnp.asarray(param_matrix,
                         dtype=self.env.precision.real_dtype)
        if pm.ndim != 2 or pm.shape[1] != len(self.param_names):
            raise ValueError(
                f"param_matrix must be (batch, {len(self.param_names)}); "
                f"got {pm.shape}")
        if int(num_trajectories) < 2:
            raise ValueError("expectation_grad needs >= 2 trajectories "
                             "for a standard error")
        terms_in, coeffs_in = hamiltonian
        _, terms, coeffs = self._validated_pauli_terms(terms_in,
                                                       coeffs_in)
        if state_f is None:
            state_f = self._default_state()
        means, errs, info = self._converge(
            pm, terms, coeffs, state_f, int(num_trajectories), key,
            sampling_budget=sampling_budget, wave_size=wave_size,
            live_rows=live_rows, grad=True, progress=progress)
        return means[:, 0], means[:, 1:], errs, info

    def _converge(self, pm, terms, coeffs, state_f, max_trajectories,
                  key, sampling_budget=None, wave_size=None,
                  live_rows=None, shard_trajectories=None,
                  grad: bool = False, progress=None):
        """The shared convergence loop. ``pm``: ``(B, P)``; per row the
        keys are an up-front ``split`` of one fold of the base key, so
        wave boundaries never change any draw. ``grad=True`` runs the
        GRADIENT wave executable instead: the carry grows a
        ``P + 1``-component axis (value + every parameter gradient),
        the stop decision requires EVERY component's standard error to
        fit the budget, and the returned means/stderrs are
        ``(B, P+1)``."""
        B = pm.shape[0]
        T = max_trajectories
        live = B if live_rows is None else max(1, min(int(live_rows), B))
        num_terms, xm, ym, zm, cf = self._pauli_operands(terms, coeffs)
        if key is None:
            key = self.env.next_key()
        W = int(wave_size) if wave_size else self._default_wave(T)
        waves, bucket = plan_waves(T, W, self._device_multiple())
        mode = self._resolve_mode(B * bucket, shard_trajectories,
                                  mem_factor=2.0 if grad else 1.0)
        # per-row key streams: row b's trajectory t key is
        # split(fold_in(key, b), T)[t] — wave slicing never re-splits
        keys_rows = [jax.random.split(jax.random.fold_in(key, b), T)
                     for b in range(B)]
        rdt = np.float64 if np.dtype(
            self.env.precision.real_dtype) == np.float64 else np.float32
        carry = jnp.zeros(
            (3, B, len(self.param_names) + 1) if grad else (3, B),
            dtype=rdt)
        fn = self._grad_wave_fn(mode) if grad else self._wave_fn(mode)
        args_const = (jnp.asarray(xm), jnp.asarray(ym), jnp.asarray(zm),
                      jnp.asarray(cf, dtype=rdt))
        # the whole wave loop is one profiled dispatch: trajectory
        # waves get the same live roofline number every other mode has
        sp = _profile.profile_dispatch("trajectories.wave")
        run = 0
        waves_run = 0
        early = False
        stderr = np.full(carry.shape[1:], np.inf)
        snap = None
        for start, live_w in waves:
            mask = np.zeros((bucket,), dtype=bool)
            mask[:live_w] = True
            kslices = []
            for b in range(B):
                ks = keys_rows[b][start:start + live_w]
                if live_w < bucket:
                    ks = jnp.concatenate(
                        [ks] + [ks[:1]] * (bucket - live_w))
                kslices.append(ks)
            # row-major flat (B*bucket,) key axis: the trajectory-
            # parallel mode shards it even for a single-row ensemble
            keys = kslices[0] if B == 1 else jnp.concatenate(kslices)
            state_p, keys = self._place(state_f, keys, mode)
            carry = fn(state_p, keys, pm, jnp.asarray(mask),
                       *args_const, carry)
            run += live_w
            waves_run += 1
            snap = np.asarray(carry)           # the wave's ONE transfer
            stderr = red.welford_stderr(snap[0], snap[2])
            if progress is not None:
                # the per-wave signal (netserve streaming, notebooks):
                # reuses the wave's existing host snapshot — no extra
                # transfer, no extra sync
                try:
                    progress({"wave": int(waves_run),
                              "trajectories_run": int(run),
                              "max_trajectories": int(T),
                              # quest: allow-host-sync(stderr is the
                              # wave's existing host snapshot — no new
                              # device transfer)
                              "max_stderr": float(np.max(stderr[:live]))})
                # quest: allow-broad-except(progress listeners are
                # caller code; a sick listener must never kill the
                # wave loop)
                except Exception:
                    pass
            if sampling_budget is not None and \
                    np.all(snap[0][:live] >= 2.0) and \
                    np.all(stderr[:live] <= float(sampling_budget)):
                early = run < T
                break
        means = snap[1]
        info = {
            "max_trajectories": T,
            "trajectories_run": int(run),
            "early_stopped": bool(early),
            "waves": int(waves_run),
            "wave_size": int(bucket),
            "batch_rows": int(B),
            "sampling_budget": (float(sampling_budget)
                                if sampling_budget is not None else None),
            "max_stderr": float(np.max(stderr[:live])),
            "mode": mode,
            "num_terms": int(num_terms),
            "kind": "gradient" if grad else "value",
        }
        with self._stats_lock:
            self._last_traj_stats = dict(info)
        if sp is not None:
            itemsize = np.dtype(self.env.precision.real_dtype).itemsize
            state_bytes = 4.0 * itemsize * (1 << self.num_qubits)
            # the reverse walk streams every pass twice (primal +
            # cotangent), so a gradient wave's traffic doubles
            sp.done(snap, program=self.program_digest,
                    kind="gradient" if grad else "trajectory",
                    bucket=int(bucket), tier="env",
                    dtype=str(np.dtype(self.env.precision.real_dtype)),
                    sharding=mode,
                    bytes_per_pass=(2.0 if grad else 1.0)
                    * max(len(self._ops), 1) * B * run * state_bytes)
        # the engine-off path pays one device->host sync per trajectory
        # per row; the wave loop pays one per wave
        self._record_batch_stats(B * run, mode, B * run - waves_run)
        return np.asarray(means, dtype=np.float64), \
            np.asarray(stderr, dtype=np.float64), info

    @property
    def last_traj_stats(self) -> dict:
        """Accounting of the most recent convergence loop
        (``trajectories_run`` / ``early_stopped`` / waves / stderr) —
        the serving layer copies these onto its telemetry spans."""
        with self._stats_lock:
            return dict(self._last_traj_stats)

    # -- sampling / debug ---------------------------------------------------

    def sample(self, num_shots: int, num_trajectories: int, params=None,
               state_f=None, key: Optional[jax.Array] = None):
        """Basis samples from the noisy output MIXTURE: run the
        ensemble once, then draw ``num_shots`` outcomes stratified
        evenly over the trajectories (:func:`quest_tpu.parallel.
        sampling.sample_mixture`) — the physical shot statistics of the
        noisy circuit at statevector cost. Returns ``(indices int64
        [num_shots], totals (T,))``."""
        if int(num_shots) < 1:
            raise ValueError("num_shots must be >= 1")
        if key is None:
            key = self.env.next_key()
        skey, tkey = jax.random.split(key)
        planes = self.trajectory_sweep(num_trajectories, params=params,
                                       state_f=state_f, key=tkey)
        from ..parallel.sampling import sample_mixture
        return sample_mixture(planes, skey, int(num_shots))

    def average_density(self, state_f, num_trajectories: int,
                        key: Optional[jax.Array] = None,
                        params=None) -> np.ndarray:
        """Monte-Carlo estimate of the channel-evolved density matrix:
        mean of |psi><psi| over trajectories (host-side, debug/analysis
        scale — the 2^n x 2^n matrix is MATERIALISED). Refuses above
        ``QUEST_TPU_DENSITY_DEBUG_QUBITS`` (default 14) qubits with
        :class:`DensityMaterialisationError`; at scale use
        :meth:`expectation` (observables, device-resident) or
        :meth:`trajectory_sweep` (the raw 2^n ensemble) instead."""
        limit = int(os.environ.get(DENSITY_DEBUG_QUBITS_ENV,
                                   str(_DENSITY_DEBUG_DEFAULT)))
        if self.num_qubits > limit:
            raise DensityMaterialisationError(
                f"average_density would materialise a "
                f"2^{2 * self.num_qubits}-amplitude density matrix "
                f"({self.num_qubits} qubits > the "
                f"{DENSITY_DEBUG_QUBITS_ENV}={limit} debug bound); use "
                "expectation() for observables or trajectory_sweep() "
                "for the raw statevector ensemble — both stay at "
                "2^n cost")
        batch = np.asarray(self.run_batch(state_f, num_trajectories,
                                          key, params=params))
        psis = batch[:, 0] + 1j * batch[:, 1]
        return np.einsum("ti,tj->ij", psis, psis.conj()) / len(psis)

    # -- accounting ---------------------------------------------------------

    def dispatch_stats(self):
        """Engine-style dispatch accounting
        (:class:`quest_tpu.profiling.DispatchStats`): the batched
        trajectory engine's batch size / sharding mode /
        ``host_syncs_avoided`` (the one-transfer-per-wave observable)
        and the keyed executable cache's occupancy, next to the
        program's op counts."""
        from ..profiling import DispatchStats
        with self._stats_lock:
            bs = dict(self._batch_stats or {})
            cache_size = len(self._cache)
            cache_evictions = self._cache.evictions
        return DispatchStats(
            gates_in=len(self.circuit.ops),
            kernels_out=len(self._ops),
            relayouts=0,
            batch_size=bs.get("batch_size", 0),
            host_syncs_avoided=bs.get("host_syncs_avoided", 0),
            batch_sharding_mode=bs.get("batch_sharding_mode", "none"),
            batched_cache_size=cache_size,
            batched_cache_evictions=cache_evictions)
