"""Quantum-trajectory (Monte-Carlo wavefunction) unraveling of noisy
circuits: channels applied stochastically to a STATEVECTOR.

The reference can simulate noise only on density matrices — 2^(2n)
amplitudes per register (``mixDamping`` etc. on the flattened vector,
``QuEST_common.c:540-604``). The trajectory method simulates the same
channel as an ensemble of 2^n-amplitude pure states: at each Kraus
channel, one operator ``K_j`` is drawn with the physical probability
``p_j = <psi| K_j^dag K_j |psi>`` and applied with renormalisation.
Averaging ``|psi><psi|`` over trajectories converges to the exact
density evolution at O(1/sqrt(T)) — exponentially cheaper per
trajectory, embarrassingly parallel across them.

TPU-native shape: the whole stochastic program is ONE jitted function of
``(state planes, PRNG key)`` — channel probabilities come from a single
state pass that builds the targets' 2^t x 2^t reduced density matrix
(every ``p_j`` is then a tiny trace against the precomputed
``E_j = K_j^dag K_j`` stack), the draw is a categorical over log
probabilities, and the chosen operator is applied by dynamic indexing
into the Kraus stack (``apply_unitary`` takes a traced matrix). Batch
with ``jax.vmap`` over keys to run hundreds of trajectories in one
executable.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.apply import apply_unitary, apply_diagonal
from ..core.packing import pack, unpack

__all__ = ["TrajectoryProgram"]


class TrajectoryProgram:
    """A recorded circuit lowered to a stochastic pure-state program.

    ``apply(state_f, key)`` is pure and jitted: packed float planes +
    PRNG key -> packed planes. Unitary/diagonal ops apply as in the
    deterministic path; each Kraus channel consumes one ``fold_in`` of
    the key. Parameterized circuits are not supported (bind angles
    before recording); use :meth:`run_batch` for an ensemble.
    """

    def __init__(self, circuit, env):
        self.env = env
        self.num_qubits = circuit.num_qubits
        if any(op.kind == "kraus" and callable(op.kraus)
               for op in circuit.ops):
            raise ValueError(
                "parameterized channels (Circuit.kraus with a callable) "
                "are density-path only; trajectory unraveling precomputes "
                "static jump probabilities")
        if circuit.param_names or any(not op.is_static
                                      for op in circuit.ops):
            raise ValueError(
                "trajectory programs need a fully-bound static circuit "
                f"(unbound parameters: {list(circuit.param_names)})")
        ops = []
        n_channels = 0
        # reuse the host-side peephole fusion every other compile path
        # gets; kraus ops match neither fusion branch, so they act as
        # barriers and pass through untouched
        for op in circuit._fused_ops():
            if op.kind == "kraus":
                from .. import validation as val
                val.validate_kraus_ops(op.kraus, len(op.targets),
                                       "TrajectoryProgram",
                                       env.precision.eps)
                stack = np.stack([np.asarray(k, dtype=np.complex128)
                                  for k in op.kraus])
                # E_j = K_j^dag K_j, precomputed: channel probabilities
                # then need only the reduced density of the targets
                estack = np.einsum("kba,kbc->kac", stack.conj(), stack)
                ops.append(("kraus", op.targets, (stack, estack),
                            n_channels))
                n_channels += 1
            elif op.kind == "u":
                ops.append(("u", op.targets, op.mat,
                            (op.ctrl_mask, op.flip_mask)))
            else:
                ops.append(("diag", op.targets, op.diag, None))
        self._ops = ops
        self.num_channels = n_channels
        n = self.num_qubits
        cdtype = env.precision.complex_dtype

        def apply_fn(state_f, key):
            psi = unpack(state_f)
            for i, (kind, targets, data, extra) in enumerate(ops):
                if kind == "u":
                    cmask, fmask = extra
                    psi = apply_unitary(psi, n, jnp.asarray(data, cdtype),
                                        targets, cmask, fmask)
                elif kind == "diag":
                    psi = apply_diagonal(psi, n, targets,
                                         jnp.asarray(data, cdtype))
                else:
                    kstack = jnp.asarray(data[0], cdtype)
                    estack = jnp.asarray(data[1], cdtype)
                    sub = jax.random.fold_in(key, extra)
                    # p_j = <psi| E_j |psi> = tr(E_j rho_T): ONE state
                    # pass builds the 2^t x 2^t reduced density of the
                    # targets, then every probability is a tiny trace
                    k = len(targets)
                    axes_front = [n - 1 - targets[j]
                                  for j in reversed(range(k))]
                    rest = [ax for ax in range(n) if ax not in axes_front]
                    a = jnp.transpose(psi.reshape((2,) * n),
                                      axes_front + rest).reshape(1 << k, -1)
                    # HIGHEST: these feed the renormalisation, so the
                    # TPU bf16 matmul default would drift every
                    # trajectory's norm (same reason as core/apply.py)
                    rho_t = jnp.matmul(a, a.conj().T,
                                       precision=jax.lax.Precision.HIGHEST)
                    probs = jnp.real(jnp.einsum(
                        "kab,ba->k", estack, rho_t,
                        precision=jax.lax.Precision.HIGHEST))
                    # categorical draw over the physical channel probs
                    # (log space; zero-prob branches get ~-inf)
                    logp = jnp.log(jnp.maximum(
                        probs, jnp.finfo(probs.dtype).tiny))
                    j = jax.random.categorical(sub, logp)
                    psi = apply_unitary(psi, n, kstack[j], targets)
                    psi = psi * jax.lax.rsqrt(
                        jnp.maximum(probs[j],
                                    jnp.finfo(probs.dtype).tiny)
                    ).astype(psi.dtype)
            return pack(psi)

        self._apply = jax.jit(apply_fn)
        self._vmapped = jax.jit(jax.vmap(apply_fn, in_axes=(None, 0)))

    # -- execution ---------------------------------------------------------

    def apply(self, state_f, key):
        """Pure form: packed planes + key -> packed planes (one draw)."""
        return self._apply(state_f, key)

    def run(self, qureg, key: Optional[jax.Array] = None) -> None:
        """One trajectory in place on a statevector register; the env RNG
        stream advances when ``key`` is not given."""
        if qureg.is_density_matrix:
            raise ValueError("trajectory programs run on statevector "
                             "registers (that is the point)")
        if qureg.num_qubits_represented != self.num_qubits:
            raise ValueError(
                f"program has {self.num_qubits} qubits; register has "
                f"{qureg.num_qubits_represented}")
        if key is None:
            key = self.env.next_key()
        qureg.ensure_canonical()   # the program addresses canonical bits
        qureg.state = self._apply(qureg.state, key)

    def run_batch(self, state_f, num_trajectories: int,
                  key: Optional[jax.Array] = None,
                  shard_trajectories: bool = False):
        """``num_trajectories`` independent draws from one initial packed
        state — a ``(T, 2, 2^n)`` batch through ONE executable.

        ``shard_trajectories=True`` on a mesh env shards the TRAJECTORY
        axis over the devices (state replicated, keys split): noise
        simulation is embarrassingly parallel across draws, so throughput
        scales linearly with mesh size — the pod-scale noise workload the
        reference's density path cannot touch. Results are bit-identical
        to the unsharded batch (the key array, not the placement, decides
        every draw); requires ``num_trajectories`` divisible by the
        device count."""
        if shard_trajectories:
            # validate BEFORE consuming the env key, so a rejected call
            # leaves the RNG stream (and seed reproducibility) untouched
            mesh = self.env.mesh
            if mesh is None or self.env.num_devices < 2:
                raise ValueError(
                    "shard_trajectories needs a multi-device mesh env")
            if num_trajectories % self.env.num_devices:
                raise ValueError(
                    f"num_trajectories ({num_trajectories}) must divide "
                    f"evenly over {self.env.num_devices} devices")
        if key is None:
            key = self.env.next_key()
        keys = jax.random.split(key, num_trajectories)
        if shard_trajectories:
            from jax.sharding import NamedSharding, PartitionSpec as P
            axis = mesh.axis_names[0]
            keys = jax.device_put(keys, NamedSharding(mesh, P(axis)))
            state_f = jax.device_put(state_f, NamedSharding(mesh, P()))
        return self._vmapped(state_f, keys)

    def expectation(self, pauli_terms, coeffs, state_f,
                    num_trajectories: int,
                    key: Optional[jax.Array] = None) -> tuple[float, float]:
        """Monte-Carlo estimate of ``<H>`` under the noisy evolution,
        ``H = sum_j coeffs[j] * prod Pauli`` (terms as ``(qubit, code)``
        pairs, codes 1=X 2=Y 3=Z). Returns ``(mean, stderr)`` over the
        trajectory ensemble — the noisy-VQE objective at statevector
        cost."""
        from ..core import matrices as mats
        from .. import validation as val
        if num_trajectories < 2:
            raise ValueError("expectation needs >= 2 trajectories for a "
                             "standard error")
        n = self.num_qubits
        terms = []
        for t in pauli_terms:
            term = tuple((int(q), int(code)) for q, code in t)
            for q, code in term:
                val.validate_target(n, q, "TrajectoryProgram.expectation")
            val.validate_pauli_codes([code for _, code in term],
                                     "TrajectoryProgram.expectation")
            terms.append(term)
        coeffs = [float(c) for c in coeffs]
        batch = self.run_batch(state_f, num_trajectories, key)

        # per-trajectory values on device (reusing the jitted Pauli path
        # instead of hauling the (T, 2^n) batch to host)
        def one(planes):
            psi = unpack(planes)
            total = jnp.zeros((), dtype=jnp.float64 if psi.dtype ==
                              jnp.complex128 else jnp.float32)
            for term, c in zip(terms, coeffs):
                phi = psi
                for q, code in term:
                    phi = apply_unitary(phi, n, jnp.asarray(
                        mats.PAULI_MATS[code], psi.dtype), (q,))
                total = total + c * jnp.real(jnp.vdot(psi, phi))
            return total

        vals = np.asarray(jax.jit(jax.vmap(one))(batch), dtype=np.float64)
        return float(vals.mean()), float(vals.std(ddof=1)
                                         / np.sqrt(len(vals)))

    def average_density(self, state_f, num_trajectories: int,
                        key: Optional[jax.Array] = None) -> np.ndarray:
        """Monte-Carlo estimate of the channel-evolved density matrix:
        mean of |psi><psi| over trajectories (host-side, debug/analysis
        scale — the matrix is materialised)."""
        batch = np.asarray(self.run_batch(state_f, num_trajectories, key))
        psis = batch[:, 0] + 1j * batch[:, 1]
        return np.einsum("ti,tj->ij", psis, psis.conj()) / len(psis)
