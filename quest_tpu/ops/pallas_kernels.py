"""Pallas fused gate-layer kernel: one HBM pass for many gates.

The XLA path applies every gate as its own full-state pass (2^n amplitudes
read + written per gate) — the same roofline as the reference's per-gate CUDA
kernels (`QuEST_gpu.cu:667-1246`). But a *layer* of gates on distinct low
qubits is a single linear map acting block-locally, so one kernel can stream
the state through VMEM once and apply the whole layer: an L-gate layer costs
1 memory pass instead of L. XLA cannot do this fusion itself (each gate is a
differently-reshaped matmul), which makes it exactly the Pallas case flagged
in SURVEY.md §7.2.

Qubit classes, with the state viewed as ``(rows, 128)`` float planes:

- **lane qubits** (0..6): bits inside the 128-lane dimension. ANY static
  gate — controlled and multi-qubit included — whose targets and controls
  all live here is a 128x128 matrix on the lane axis (kron-embedded
  host-side); a whole run of them multiplies into ONE matrix applied by MXU
  matmuls. Diagonal (phase-family) ops embed as diagonal matrices.
- **mid qubits** (7..7+log2(R)-1): bits inside the per-block row dimension.
  Uncontrolled 1q gates pair rows at stride 2^(q-7); applied in-VMEM by
  leading-axis reshape + broadcasted 2x2 combine (VPU).
- **high qubits** (>= 7+log2(R)): pair across grid blocks; left to the
  XLA/collective path (they are the few top qubits only).

Complex arithmetic runs on split re/im planes (4 real matmuls per lane
matrix; see `core/packing.py` for why planes are the storage format anyway).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

LANE_QUBITS = 7          # 2^7 = 128 lanes
DEFAULT_BLOCK_ROWS = 1024

__all__ = ["LANE_QUBITS", "DEFAULT_BLOCK_ROWS", "LayerOp",
           "embed_lane_matrix", "lane_diag_matrix", "max_mid_qubit",
           "apply_layer"]


def embed_lane_matrix(u: np.ndarray, targets: Sequence[int],
                      ctrl_mask: int = 0, flip_mask: int = 0) -> np.ndarray:
    """Embed a gate on lane qubits into the full 128x128 lane operator
    (bit ``j`` of the gate's index addresses ``targets[j]``, the
    ComplexMatrixN convention; controls condition on 1 unless flipped)."""
    k = len(targets)
    dim = 1 << LANE_QUBITS
    full = np.zeros((dim, dim), dtype=np.complex128)
    t_mask = 0
    for t in targets:
        t_mask |= 1 << t
    want = ctrl_mask & ~flip_mask
    for col in range(dim):
        if (col & ctrl_mask) != want:
            full[col, col] = 1.0
            continue
        m = 0
        for j, t in enumerate(targets):
            if (col >> t) & 1:
                m |= 1 << j
        base = col & ~t_mask
        for m2 in range(1 << k):
            row = base
            for j, t in enumerate(targets):
                if (m2 >> j) & 1:
                    row |= 1 << t
            full[row, col] += u[m2, m]
    return full


def lane_diag_matrix(tensor: np.ndarray,
                     qubits_desc: Sequence[int]) -> np.ndarray:
    """Embed a diagonal factor tensor ((2,)*k, axes = qubits sorted desc)
    over lane qubits as a diagonal 128x128 operator."""
    dim = 1 << LANE_QUBITS
    d = np.ones(dim, dtype=np.complex128)
    k = len(qubits_desc)
    for lane in range(dim):
        idx = tuple((lane >> q) & 1 for q in qubits_desc)
        d[lane] = tensor[idx] if k else 1.0
    return np.diag(d)


def max_mid_qubit(block_rows: int) -> int:
    """Highest qubit index the kernel handles for a given block size."""
    return LANE_QUBITS + int(np.log2(block_rows)) - 1


class LayerOp:
    """A fused layer: one lane matrix + an ordered list of mid-qubit gates.

    ``mid_gates`` holds ``(qubit, u2x2)``; lane and mid sets act on disjoint
    qubits, so the kernel applies the lane matmul first regardless of the
    recorded interleaving. Quacks enough like circuits._Op for the layout
    planner (kind/targets/masks/is_static).
    """

    kind = "layer"
    ctrl_mask = 0
    flip_mask = 0
    is_static = True
    mat_fn = None
    diag_fn = None

    def __init__(self, num_qubits: int, members: int,
                 lane_matrix: Optional[np.ndarray],
                 mid_gates: list[tuple[int, np.ndarray]]):
        self.num_qubits = num_qubits
        self.members = members            # how many recorded ops were fused
        self.lane_matrix = lane_matrix    # 128x128 complex or None
        self.mid_gates = mid_gates
        self.targets = tuple(sorted(
            {q for q, _ in mid_gates}
            | (set(range(min(LANE_QUBITS, num_qubits)))
               if lane_matrix is not None else set())))


def _layer_kernel(re_ref, im_ref, mre_ref, mim_ref, ore_ref, oim_ref,
                  *, mid_static, use_lane):
    re = re_ref[:]
    im = im_ref[:]
    if use_lane:
        mre_t = mre_ref[:].T
        mim_t = mim_ref[:].T
        acc = re.dtype  # f32 accumulate on TPU; f64 under x64 interpret
        # out = v @ M^T (columns of M index the input lane), complex via 4
        # real MXU matmuls on (rows,128)x(128,128)
        new_re = (jnp.dot(re, mre_t, preferred_element_type=acc)
                  - jnp.dot(im, mim_t, preferred_element_type=acc))
        new_im = (jnp.dot(re, mim_t, preferred_element_type=acc)
                  + jnp.dot(im, mre_t, preferred_element_type=acc))
        re, im = new_re.astype(re.dtype), new_im.astype(im.dtype)
    rows = re.shape[0]
    for stride, (ar, ai, br, bi, cr, ci, dr, di) in mid_static:
        blocks = rows // (2 * stride)
        sre = re.reshape(blocks, 2, stride, 128)
        sim = im.reshape(blocks, 2, stride, 128)
        up_re, lo_re = sre[:, 0], sre[:, 1]
        up_im, lo_im = sim[:, 0], sim[:, 1]
        nu_re = ar * up_re - ai * up_im + br * lo_re - bi * lo_im
        nu_im = ar * up_im + ai * up_re + br * lo_im + bi * lo_re
        nl_re = cr * up_re - ci * up_im + dr * lo_re - di * lo_im
        nl_im = cr * up_im + ci * up_re + dr * lo_im + di * lo_re
        re = jnp.stack([nu_re, nl_re], axis=1).reshape(rows, 128)
        im = jnp.stack([nu_im, nl_im], axis=1).reshape(rows, 128)
    ore_ref[:] = re
    oim_ref[:] = im


def apply_layer(state: jnp.ndarray, num_qubits: int, layer: LayerOp,
                block_rows: int = DEFAULT_BLOCK_ROWS,
                interpret: bool = False) -> jnp.ndarray:
    """Apply a fused layer to a flat complex state (traceable; call under
    jit — the pallas_call compiles into the surrounding program)."""
    from jax.experimental import pallas as pl

    total_rows = (1 << num_qubits) // 128
    if total_rows < 1:
        raise ValueError("fused layers need at least 7 qubits")
    block_rows = min(block_rows, total_rows)
    hi = max_mid_qubit(block_rows)
    mid_static = []
    for q, u in layer.mid_gates:
        if not LANE_QUBITS <= q <= hi:
            raise ValueError(f"mid gate qubit {q} outside [{LANE_QUBITS}, {hi}]")
        mid_static.append((1 << (q - LANE_QUBITS),
                           (float(u[0, 0].real), float(u[0, 0].imag),
                            float(u[0, 1].real), float(u[0, 1].imag),
                            float(u[1, 0].real), float(u[1, 0].imag),
                            float(u[1, 1].real), float(u[1, 1].imag))))

    rdtype = jnp.float32 if state.dtype == jnp.complex64 else jnp.float64
    re = jnp.real(state).astype(rdtype).reshape(total_rows, 128)
    im = jnp.imag(state).astype(rdtype).reshape(total_rows, 128)
    use_lane = layer.lane_matrix is not None
    if use_lane:
        mre = jnp.asarray(np.ascontiguousarray(layer.lane_matrix.real), rdtype)
        mim = jnp.asarray(np.ascontiguousarray(layer.lane_matrix.imag), rdtype)
    else:
        mre = jnp.zeros((128, 128), rdtype)
        mim = jnp.zeros((128, 128), rdtype)

    kernel = functools.partial(_layer_kernel, mid_static=tuple(mid_static),
                               use_lane=use_lane)
    state_spec = pl.BlockSpec((block_rows, 128), lambda i: (i, 0))
    mat_spec = pl.BlockSpec((128, 128), lambda i: (0, 0))
    with jax.named_scope(f"pallas_layer_{layer.members}gates"):
        out_re, out_im = pl.pallas_call(
            kernel,
            grid=(total_rows // block_rows,),
            in_specs=[state_spec, state_spec, mat_spec, mat_spec],
            out_specs=[state_spec, state_spec],
            out_shape=[jax.ShapeDtypeStruct((total_rows, 128), rdtype)] * 2,
            interpret=interpret,
        )(re, im, mre, mim)
    return jax.lax.complex(out_re, out_im).reshape(-1).astype(state.dtype)
