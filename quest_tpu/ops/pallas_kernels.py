"""Pallas fused gate-layer kernel: one HBM pass for many gates.

The XLA path applies every gate as its own full-state pass (2^n amplitudes
read + written per gate) — the same roofline as the reference's per-gate CUDA
kernels (`QuEST_gpu.cu:667-1246`). But a *layer* of gates on distinct low
qubits is a single linear map acting block-locally, so one kernel can stream
the state through VMEM once and apply the whole layer: an L-gate layer costs
1 memory pass instead of L. XLA cannot do this fusion itself (each gate is a
differently-reshaped matmul), which makes it exactly the Pallas case flagged
in SURVEY.md §7.2.

Qubit classes, with the state viewed as ``(rows, 128)`` float planes:

- **lane qubits** (0..6): bits inside the 128-lane dimension. ANY static
  gate — controlled and multi-qubit included — whose targets all live here
  is a 128x128 matrix on the lane axis (kron-embedded host-side); runs of
  them multiply into ONE matrix applied by MXU matmuls. Diagonal
  (phase-family) ops embed as diagonal matrices.
- **row qubits** (>= 7): bits of the row index. Dense 1q gates whose target
  bit lies inside the kernel block pair rows at stride 2^(q-7) (VPU 2x2
  combine); diagonal factors over up to two row bits become per-row
  multiplicative tables; and gates CONTROLLED on row bits apply under an
  iota-derived row mask — the global row index (grid block base + local
  row) makes any row-bit control addressable, not just in-block ones.

A layer is an ordered list of STAGES (see :class:`LayerOp`); adjacent
compatible stages are merged by the collector (`circuits._collect_layers`),
and the whole list executes inside one ``pallas_call`` — one read + one
write of the state regardless of stage count.

Complex arithmetic runs on split re/im planes (4 real MXU matmuls per lane
matrix; see `core/packing.py` for why planes are the storage format anyway).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

LANE_QUBITS = 7          # 2^7 = 128 lanes
DEFAULT_BLOCK_ROWS = 1024

__all__ = ["LANE_QUBITS", "DEFAULT_BLOCK_ROWS", "LayerOp",
           "embed_lane_matrix", "lane_diag_matrix", "lane_diag_vector",
           "max_mid_qubit", "apply_layer", "apply_layer_batched",
           "mxu_group_matrix", "apply_mxu_tile",
           "fused_kraus_apply_batched"]


def embed_lane_matrix(u: np.ndarray, targets: Sequence[int],
                      ctrl_mask: int = 0, flip_mask: int = 0) -> np.ndarray:
    """Embed a gate on lane qubits into the full 128x128 lane operator
    (bit ``j`` of the gate's index addresses ``targets[j]``, the
    ComplexMatrixN convention; controls condition on 1 unless flipped)."""
    k = len(targets)
    dim = 1 << LANE_QUBITS
    full = np.zeros((dim, dim), dtype=np.complex128)
    t_mask = 0
    for t in targets:
        t_mask |= 1 << t
    want = ctrl_mask & ~flip_mask
    for col in range(dim):
        if (col & ctrl_mask) != want:
            full[col, col] = 1.0
            continue
        m = 0
        for j, t in enumerate(targets):
            if (col >> t) & 1:
                m |= 1 << j
        base = col & ~t_mask
        for m2 in range(1 << k):
            row = base
            for j, t in enumerate(targets):
                if (m2 >> j) & 1:
                    row |= 1 << t
            full[row, col] += u[m2, m]
    return full


def lane_diag_vector(tensor: np.ndarray,
                     qubits_desc: Sequence[int]) -> np.ndarray:
    """Evaluate a diagonal factor tensor ((2,)*k, axes = lane qubits sorted
    desc) into a per-lane factor vector of length 128."""
    dim = 1 << LANE_QUBITS
    d = np.ones(dim, dtype=np.complex128)
    k = len(qubits_desc)
    for lane in range(dim):
        idx = tuple((lane >> q) & 1 for q in qubits_desc)
        d[lane] = tensor[idx] if k else tensor[()] if tensor.ndim == 0 else 1.0
    return d


def lane_diag_matrix(tensor: np.ndarray,
                     qubits_desc: Sequence[int]) -> np.ndarray:
    """Embed a diagonal factor tensor ((2,)*k, axes = qubits sorted desc)
    over lane qubits as a diagonal 128x128 operator."""
    return np.diag(lane_diag_vector(tensor, qubits_desc))


def max_mid_qubit(block_rows: int) -> int:
    """Highest qubit index a dense (row-pairing) gate can target for a
    given block size. Controls and diagonal factors address ANY row bit
    (they read the global row index), so this bounds targets only."""
    return LANE_QUBITS + int(np.log2(block_rows)) - 1


def mxu_group_matrix(u: np.ndarray, targets: Sequence[int],
                     row_bits_asc: Sequence[int]) -> np.ndarray:
    """Embed a dense (uncontrolled) gate into the MXU-tile contraction
    operator over ``(lane qubits 0..6) + (row bits + 7)``: a
    ``(2^j * 128, 2^j * 128)`` matrix whose index bit ``l < 7`` is lane
    bit ``l`` and bit ``7 + m`` is row bit ``row_bits_asc[m]`` — exactly
    the flat ``b * 128 + lane`` axis the ``rowmxu`` kernel stage
    contracts after regrouping. ``targets`` are the gate's physical
    qubit positions (lane and row positions mixed freely)."""
    from ..core import matrices as mats
    sup = tuple(range(LANE_QUBITS)) + tuple(
        int(b) + LANE_QUBITS for b in row_bits_asc)
    # quest: allow-host-sync(compile-time operand prep: u is a host
    # matrix, never a device array)
    return mats.embed_in_support(np.asarray(u, np.complex128), targets,
                                 sup)


def mxu_expand(m: np.ndarray, prev_bits: Sequence[int],
               union_bits: Sequence[int]) -> np.ndarray:
    """Expand an MXU-tile operator over ``(lanes + prev_bits)`` to the
    superset support ``(lanes + union_bits)`` (identity on the new row
    bits) — vectorized, so merging adjacent ``rowmxu`` stages with
    different row-bit sets stays cheap at compile time."""
    prev_bits = tuple(int(b) for b in prev_bits)
    union_bits = tuple(int(b) for b in union_bits)
    dim_u = (1 << len(union_bits)) * (1 << LANE_QUBITS)
    idx = np.arange(dim_u)
    a_p = idx & ((1 << LANE_QUBITS) - 1)
    a_e = np.zeros_like(idx)
    e = 0
    for mpos, b in enumerate(union_bits):
        bit = (idx >> (LANE_QUBITS + mpos)) & 1
        if b in prev_bits:
            a_p = a_p | (bit << (LANE_QUBITS + prev_bits.index(b)))
        else:
            a_e = a_e | (bit << e)
            e += 1
    # quest: allow-host-sync(compile-time operand prep: m is the host
    # tile matrix, never a device array)
    return np.asarray(m)[a_p[:, None], a_p[None, :]] \
        * (a_e[:, None] == a_e[None, :])


class LayerOp:
    """A fused layer: an ordered list of stages applied in one HBM pass.

    Stage forms (``q``/mask bit positions are the KERNEL's physical qubit
    positions — the collector has already mapped logical->physical):

    - ``("lane", M)`` — unconditional 128x128 complex matrix on the lane
      axis (a merged run of lane-qubit gates, dense and diagonal).
    - ``("clane", M, row_mask, row_want)`` — lane matrix applied only to
      rows whose global row index matches ``(row & row_mask) == row_want``
      (masks in row-bit coordinates: bit ``p`` = qubit ``p+7``).
    - ``("row", q, u2x2, lane_mask, lane_want, row_mask, row_want)`` —
      dense 2x2 on row-bit target ``q`` (>= 7), conditioned on lane
      controls (mask over the 128-lane index) and/or row controls.
    - ``("rowdiag", table, row_bits)`` — multiplicative per-amplitude
      factor: ``table`` is complex ``(2^k, 128)``; the factor row is
      selected by the bits of the global row index at ``row_bits``
      (ascending positions, in row-bit coordinates).
    - ``("rowmxu", row_bits, M)`` — MXU-shaped fused contraction: the
      ``j`` row bits (ascending, row-bit coordinates) pack with the
      128-lane axis into one ``(2^j * 128)``-dim contraction and ``M``
      is the complex operator over that combined axis (bit ``l < 7`` =
      lane bit ``l``, bit ``7 + m`` = ``row_bits[m]``; see
      :func:`mxu_group_matrix`). One systolic-array matmul serves the
      whole fused dense group — the FAST bf16 tier rides the MXU here
      instead of the VPU row path. Uncontrolled groups only; selection
      is the modeled crossover
      :func:`quest_tpu.parallel.layout.choose_mxu_contraction`.

    Quacks enough like circuits._Op for the executors (kind/targets/
    masks/is_static).
    """

    kind = "layer"
    ctrl_mask = 0
    flip_mask = 0
    is_static = True
    mat_fn = None
    diag_fn = None

    def __init__(self, num_qubits: int, members: int, stages: list,
                 support: Optional[set] = None):
        self.num_qubits = num_qubits
        self.members = members            # how many recorded ops were fused
        self.stages = stages
        if support is None:
            support = set()
            for st in stages:
                if st[0] in ("lane", "clane"):
                    support |= set(range(min(LANE_QUBITS, num_qubits)))
                elif st[0] == "row":
                    support.add(st[1])
                elif st[0] in ("rowk", "rowmxu"):
                    if st[0] == "rowmxu":
                        support |= set(range(min(LANE_QUBITS,
                                                 num_qubits)))
                    support |= {b + LANE_QUBITS for b in st[1]}
                else:
                    support |= {b + LANE_QUBITS for b in st[2]}
        self.targets = tuple(sorted(support))

    # -- legacy views (round-4 shape: one lane matrix + uncontrolled mids) --

    @property
    def lane_matrix(self):
        for st in self.stages:
            if st[0] == "lane":
                return st[1]
        return None

    @property
    def mid_gates(self):
        return [(st[1], st[2]) for st in self.stages
                if st[0] == "row" and st[3] == 0 and st[5] == 0]


def _global_row(base, shape, axis):
    """Global row index, broadcast over ``shape`` along ``axis``."""
    return base + jax.lax.broadcasted_iota(jnp.int32, shape, axis)


def _mxu_matmuls(re, im, mre_t, mim_t, acc, fast: bool):
    """The shared complex contraction ``(v_re + i v_im) @ (M_re + i
    M_im)^T`` as 4 real MXU matmuls — HIGHEST-precision f32 passes, or
    the FAST tier's bf16-split compensated form (state splits error-free
    into a bf16 hi plane + f32 residual, residual partials combine
    first; same trade as the lane stage, see the comment there)."""
    acc_dt = acc
    if fast:
        lp = jax.lax.Precision.DEFAULT

        def _fdot(v, m):
            hi = v.astype(jnp.bfloat16).astype(acc_dt)
            lo = (v - hi).astype(acc_dt)
            return (jnp.dot(hi, m, preferred_element_type=acc_dt,
                            precision=lp),
                    jnp.dot(lo, m, preferred_element_type=acc_dt,
                            precision=lp))

        rr_h, rr_l = _fdot(re, mre_t)
        ii_h, ii_l = _fdot(im, mim_t)
        ri_h, ri_l = _fdot(re, mim_t)
        ir_h, ir_l = _fdot(im, mre_t)
        return ((rr_h - ii_h) + (rr_l - ii_l),
                (ri_h + ir_h) + (ri_l + ir_l))
    hp = jax.lax.Precision.HIGHEST
    new_re = (jnp.dot(re, mre_t, preferred_element_type=acc_dt,
                      precision=hp)
              - jnp.dot(im, mim_t, preferred_element_type=acc_dt,
                        precision=hp))
    new_im = (jnp.dot(re, mim_t, preferred_element_type=acc_dt,
                      precision=hp)
              + jnp.dot(im, mre_t, preferred_element_type=acc_dt,
                        precision=hp))
    return new_re, new_im


def _row_regroup_plan(rows: int, bits: tuple):
    """Static reshape/transpose plan bringing the row ``bits`` adjacent:
    ``(dims, perm, inv_perm, groups, dim)`` such that reshaping to
    ``dims + (128,)``, transposing by ``perm + (last,)`` and flattening
    yields ``(groups, dim, 128)`` with combined-axis bit ``m`` = row bit
    ``bits[m]`` (the ``rowk`` choreography, factored for reuse)."""
    k = len(bits)
    dim = 1 << k
    rlog = int(np.log2(rows))
    dims = []
    prev = rlog
    for b in reversed(bits):
        dims += [1 << (prev - b - 1), 2]
        prev = b
    dims.append(1 << prev)
    two_axes = [2 * i + 1 for i in range(k)]       # bits[k-1]..bits[0]
    other_axes = [a for a in range(len(dims)) if a not in two_axes]
    perm = other_axes + two_axes
    inv = [0] * len(dims)
    for pos, a in enumerate(perm):
        inv[a] = pos
    return tuple(dims), tuple(perm), tuple(inv), rows // dim, dim


def _layer_kernel(re_ref, im_ref, mre_ref, mim_ref, tre_ref, tim_ref,
                  xre_ref, xim_ref, ore_ref, oim_ref, *, stages,
                  block_rows, batched: bool = False, fast: bool = False):
    from jax.experimental import pallas as pl

    # batched form: the grid grows a LEADING batch dimension and state
    # blocks carry a unit batch axis — grid (B, row_blocks), block
    # (1, block_rows, 128). The row base comes from grid axis 1, so every
    # row-indexed stage (controls, rowdiag tables, rowk regroups) sees the
    # same per-STATE row coordinates as the unbatched kernel.
    if batched:
        re = re_ref[0]
        im = im_ref[0]
        rows = block_rows
        base = pl.program_id(1) * rows
    else:
        re = re_ref[:]
        im = im_ref[:]
        rows = block_rows
        base = pl.program_id(0) * rows
    acc = re.dtype  # f32 accumulate on TPU; f64 under x64 interpret
    for st in stages:
        tag = st[0]
        if tag in ("lane", "clane"):
            _, mi, row_mask, row_want = st
            mre_t = mre_ref[mi, :, :].T
            mim_t = mim_ref[mi, :, :].T
            # out = v @ M^T (columns of M index the input lane), complex
            # via 4 real MXU matmuls on (rows,128)x(128,128).
            # Precision.HIGHEST: the TPU MXU defaults to bf16 inputs,
            # which costs ~1e-4 per layer (measured 7.0e-5 amp deviation
            # on the r5 silicon smoke); HIGHEST selects the f32 passes.
            # FAST tier: Precision.DEFAULT (one bf16-input MXU pass
            # where HIGHEST pays six) with bf16-split compensated
            # accumulation — the STATE operand splits error-free into a
            # bf16 hi plane plus the f32 residual, each rides its own
            # cheap pass, and the small residual partial sums combine
            # FIRST so their correction lands in one f32 add instead of
            # drowning term-by-term in the dominant sums. The remaining
            # drift is the per-gate MATRIX rounding the tier error
            # model budgets conservatively at 5e-4/gate
            # (docs/accuracy.md "Precision tiers").
            if fast:
                lp = jax.lax.Precision.DEFAULT

                def _fdot(v, m):
                    hi = v.astype(jnp.bfloat16).astype(acc)
                    lo = (v - hi).astype(acc)
                    return (jnp.dot(hi, m, preferred_element_type=acc,
                                    precision=lp),
                            jnp.dot(lo, m, preferred_element_type=acc,
                                    precision=lp))

                rr_h, rr_l = _fdot(re, mre_t)
                ii_h, ii_l = _fdot(im, mim_t)
                ri_h, ri_l = _fdot(re, mim_t)
                ir_h, ir_l = _fdot(im, mre_t)
                new_re = (rr_h - ii_h) + (rr_l - ii_l)
                new_im = (ri_h + ir_h) + (ri_l + ir_l)
            else:
                hp = jax.lax.Precision.HIGHEST
                new_re = (jnp.dot(re, mre_t, preferred_element_type=acc,
                                  precision=hp)
                          - jnp.dot(im, mim_t, preferred_element_type=acc,
                                    precision=hp))
                new_im = (jnp.dot(re, mim_t, preferred_element_type=acc,
                                  precision=hp)
                          + jnp.dot(im, mre_t, preferred_element_type=acc,
                                    precision=hp))
            new_re = new_re.astype(re.dtype)
            new_im = new_im.astype(im.dtype)
            if row_mask:
                # the row index is already in row-bit coordinates (bit p
                # of the row index = qubit p+7); masks were shifted down
                # by LANE_QUBITS at collection time
                g = _global_row(base, (rows, 1), 0)
                cond = (g & row_mask) == row_want
                re = jnp.where(cond, new_re, re)
                im = jnp.where(cond, new_im, im)
            else:
                re, im = new_re, new_im
        elif tag == "row":
            (_, stride, (ar, ai, br, bi, cr, ci, dr, di),
             lane_mask, lane_want, row_mask, row_want) = st
            blocks = rows // (2 * stride)
            sre = re.reshape(blocks, 2, stride, 128)
            sim = im.reshape(blocks, 2, stride, 128)
            up_re, lo_re = sre[:, 0], sre[:, 1]
            up_im, lo_im = sim[:, 0], sim[:, 1]
            nu_re = ar * up_re - ai * up_im + br * lo_re - bi * lo_im
            nu_im = ar * up_im + ai * up_re + br * lo_im + bi * lo_re
            nl_re = cr * up_re - ci * up_im + dr * lo_re - di * lo_im
            nl_im = cr * up_im + ci * up_re + dr * lo_im + di * lo_re
            if lane_mask or row_mask:
                shape = (blocks, stride, 128)
                cond = None
                if row_mask:
                    # row index of the UP half; the target bit is 0 there
                    # and control masks never include the target bit, so
                    # the condition holds for both halves of the pair
                    blk = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
                    s = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
                    g_up = base + blk * (2 * stride) + s
                    cond = (g_up & row_mask) == row_want
                if lane_mask:
                    lane = jax.lax.broadcasted_iota(jnp.int32, shape, 2)
                    lcond = (lane & lane_mask) == lane_want
                    cond = lcond if cond is None else cond & lcond
                nu_re = jnp.where(cond, nu_re, up_re)
                nu_im = jnp.where(cond, nu_im, up_im)
                nl_re = jnp.where(cond, nl_re, lo_re)
                nl_im = jnp.where(cond, nl_im, lo_im)
            re = jnp.stack([nu_re, nl_re], axis=1).reshape(rows, 128)
            im = jnp.stack([nu_im, nl_im], axis=1).reshape(rows, 128)
        elif tag == "rowk":
            # k-qubit dense gate on row bits (the
            # multiControlledMultiQubitUnitaryLocal analogue,
            # QuEST_cpu.c:1820-1901): static reshape/transpose brings the
            # k target bits adjacent, then 2^k x 2^k unrolled complex
            # MACs mix the groups. bits ascend; gate-index bit j = bits[j].
            (_, bits, uflat, lane_mask, lane_want,
             row_mask, row_want) = st
            k = len(bits)
            dim = 1 << k
            rlog = int(np.log2(rows))
            # split rows at the target bits: dims MSB->LSB, '2' axes at
            # positions 1, 3, ... (for bits[k-1], bits[k-2], ...)
            dims = []
            prev = rlog
            for b in reversed(bits):
                dims += [1 << (prev - b - 1), 2]
                prev = b
            dims.append(1 << prev)
            two_axes = [2 * i + 1 for i in range(k)]   # bits[k-1]..bits[0]
            other_axes = [a for a in range(len(dims)) if a not in two_axes]
            perm = other_axes + two_axes
            groups = rows // dim

            def regroup(x):
                x = x.reshape(*dims, 128)
                x = jnp.transpose(x, tuple(perm) + (len(dims),))
                return x.reshape(groups, dim, 128)

            def ungroup(x):
                inv = [0] * len(dims)
                for pos, a in enumerate(perm):
                    inv[a] = pos
                x = x.reshape(*[dims[a] for a in perm], 128)
                x = jnp.transpose(x, tuple(inv) + (len(dims),))
                return x.reshape(rows, 128)

            gre, gim = regroup(re), regroup(im)
            slre = [gre[:, g, :] for g in range(dim)]
            slim = [gim[:, g, :] for g in range(dim)]
            nre, nim = [], []
            for gp in range(dim):
                ar = ai = None
                for g in range(dim):
                    ur, ui = uflat[gp * dim + g]
                    if ur == 0.0 and ui == 0.0:
                        continue
                    tr = ur * slre[g] - ui * slim[g]
                    ti = ur * slim[g] + ui * slre[g]
                    ar = tr if ar is None else ar + tr
                    ai = ti if ai is None else ai + ti
                z = jnp.zeros((groups, 128), re.dtype)
                nre.append(z if ar is None else ar)
                nim.append(z if ai is None else ai)
            if lane_mask or row_mask:
                cond = None
                if row_mask:
                    # reconstruct the row index with target bits zeroed
                    # (controls never include targets) from the group
                    # index: bit m of the group enumerates the m-th
                    # non-target row bit, ascending
                    gidx = jax.lax.broadcasted_iota(
                        jnp.int32, (groups, 128), 0)
                    nontgt = [p for p in range(rlog) if p not in bits]
                    row0 = jnp.zeros((groups, 128), jnp.int32)
                    for m, p in enumerate(nontgt):
                        row0 = row0 | (((gidx >> m) & 1) << p)
                    cond = ((base + row0) & row_mask) == row_want
                if lane_mask:
                    lane = jax.lax.broadcasted_iota(
                        jnp.int32, (groups, 128), 1)
                    lcond = (lane & lane_mask) == lane_want
                    cond = lcond if cond is None else cond & lcond
                nre = [jnp.where(cond, nre[g], slre[g])
                       for g in range(dim)]
                nim = [jnp.where(cond, nim[g], slim[g])
                       for g in range(dim)]
            re = ungroup(jnp.stack(nre, axis=1))
            im = ungroup(jnp.stack(nim, axis=1))
        elif tag == "rowmxu":
            # MXU-shaped fused contraction: the j row target bits pack
            # with the 128-lane axis into one (2^j * 128)-dim axis and
            # the whole fused group is a single systolic-array matmul
            # over it — (groups, 2^j*128) x (2^j*128, 2^j*128) — where
            # the row/rowk stages pay 2^k VPU MACs per amplitude
            # (ROADMAP item 4: the FAST bf16 tier rides the MXU).
            _, bits, xi, xdim = st
            dims, perm, inv, groups, gdim = _row_regroup_plan(rows, bits)
            flat = gdim * 128

            def mx_regroup(x):
                x = x.reshape(*dims, 128)
                x = jnp.transpose(x, perm + (len(dims),))
                return x.reshape(groups, flat)

            def mx_ungroup(x):
                x = x.reshape(*[dims[a] for a in perm], 128)
                x = jnp.transpose(x, inv + (len(dims),))
                return x.reshape(rows, 128)

            mre_t = xre_ref[xi, :xdim, :xdim].T
            mim_t = xim_ref[xi, :xdim, :xdim].T
            new_re, new_im = _mxu_matmuls(mx_regroup(re), mx_regroup(im),
                                          mre_t, mim_t, acc, fast)
            re = mx_ungroup(new_re.astype(re.dtype))
            im = mx_ungroup(new_im.astype(im.dtype))
        else:  # rowdiag
            _, toff, bits = st
            g = _global_row(base, (rows, 1), 0)
            cfg = jnp.zeros((rows, 1), jnp.int32)
            for j, b in enumerate(bits):
                cfg = cfg | (((g >> b) & 1) << j)
            fre = jnp.zeros((rows, 128), re.dtype)
            fim = jnp.zeros((rows, 128), im.dtype)
            for c in range(1 << len(bits)):
                sel = cfg == c
                fre = jnp.where(sel, tre_ref[toff + c, :][None, :], fre)
                fim = jnp.where(sel, tim_ref[toff + c, :][None, :], fim)
            new_re = re * fre - im * fim
            new_im = re * fim + im * fre
            re, im = new_re, new_im
    if batched:
        ore_ref[0] = re
        oim_ref[0] = im
    else:
        ore_ref[:] = re
        oim_ref[:] = im


def layer_kernel_plan(layer: LayerOp, num_qubits: int,
                      block_rows: int = DEFAULT_BLOCK_ROWS):
    """The static kernel plan for one fused layer: validated stage
    descriptors plus the stacked matrix/table operands. Shared by
    :func:`apply_layer` and the VMEM-budget tests (which need the EXACT
    per-chip stage chains the collector emits, without tracing).

    Returns ``(kstages, mats, tables, xmats, block_rows, total_rows)``
    — ``xmats`` are the MXU-tile contraction operators of the layer's
    ``rowmxu`` stages (variable dim; stacked zero-padded by the
    operand prep).
    """
    total_rows = (1 << num_qubits) // 128
    if total_rows < 1:
        raise ValueError("fused layers need at least 7 qubits")
    block_rows = min(block_rows, total_rows)
    hi = max_mid_qubit(block_rows)

    # static stage plan + stacked matrix/table operands
    mats: list[np.ndarray] = []
    tables: list[np.ndarray] = []
    xmats: list[np.ndarray] = []
    kstages: list[tuple] = []
    for st in layer.stages:
        if st[0] in ("lane", "clane"):
            if st[0] == "lane":
                m, row_mask, row_want = st[1], 0, 0
            else:
                _, m, row_mask, row_want = st
            kstages.append(("lane", len(mats), int(row_mask), int(row_want)))
            mats.append(np.ascontiguousarray(m))
        elif st[0] == "row":
            _, q, u, lane_mask, lane_want, row_mask, row_want = st
            if not LANE_QUBITS <= q <= hi:
                raise ValueError(
                    f"row-gate target {q} outside [{LANE_QUBITS}, {hi}]")
            u = np.asarray(u)
            kstages.append((
                "row", 1 << (q - LANE_QUBITS),
                (float(u[0, 0].real), float(u[0, 0].imag),
                 float(u[0, 1].real), float(u[0, 1].imag),
                 float(u[1, 0].real), float(u[1, 0].imag),
                 float(u[1, 1].real), float(u[1, 1].imag)),
                int(lane_mask), int(lane_want),
                int(row_mask), int(row_want)))
        elif st[0] == "rowk":
            _, bits, u, lane_mask, lane_want, row_mask, row_want = st
            bits = tuple(int(b) for b in bits)
            if bits and bits[-1] + LANE_QUBITS > hi:
                raise ValueError(
                    f"rowk bit {bits[-1]} outside block row range")
            u = np.asarray(u)
            kstages.append((
                "rowk", bits,
                tuple((float(z.real), float(z.imag)) for z in u.reshape(-1)),
                int(lane_mask), int(lane_want),
                int(row_mask), int(row_want)))
        elif st[0] == "rowmxu":
            _, bits, m = st
            bits = tuple(int(b) for b in bits)
            if bits and bits[-1] + LANE_QUBITS > hi:
                raise ValueError(
                    f"rowmxu bit {bits[-1]} outside block row range")
            # quest: allow-host-sync(static stage plan: host matrix)
            m = np.asarray(m)
            dim = (1 << len(bits)) * (1 << LANE_QUBITS)
            if m.shape != (dim, dim):
                raise ValueError(
                    f"rowmxu matrix shape {m.shape} != {(dim, dim)}")
            kstages.append(("rowmxu", bits, len(xmats), dim))
            xmats.append(np.ascontiguousarray(m))
        else:
            _, table, bits = st
            kstages.append(("rowdiag", len(tables), tuple(int(b)
                                                          for b in bits)))
            tables.extend(np.asarray(table))
    return kstages, mats, tables, xmats, block_rows, total_rows


def choose_block_rows(kstages, mstack, tstack, block_rows: int,
                      itemsize: int, vmem_limit: int,
                      xstack=None) -> tuple[int, int]:
    """Shrink ``block_rows`` until the Mosaic working-set estimate fits
    ``vmem_limit`` (halving trades grid steps for VMEM), respecting the
    pairing floor: a row stage pairing rows at ``stride`` needs its whole
    ``2*stride`` pair group inside one block — never shrink below that
    (the collector validated targets against the PRE-shrink hi).

    Returns ``(block_rows, estimated_bytes)`` — the estimate may still
    exceed the limit when the floor binds.
    """
    min_block = max([2 * st[1] for st in kstages if st[0] == "row"]
                    + [2 << st[1][-1] for st in kstages
                       if st[0] in ("rowk", "rowmxu") and st[1]],
                    default=8)
    est = _vmem_estimate(block_rows, kstages, mstack, tstack, itemsize,
                         xstack)
    while block_rows > max(8, min_block) and est > vmem_limit:
        block_rows //= 2
        est = _vmem_estimate(block_rows, kstages, mstack, tstack,
                             itemsize, xstack)
    return block_rows, est


def _layer_operands(layer: LayerOp, num_qubits: int, block_rows: int,
                    rdtype):
    """Shared operand prep for the (batched and unbatched) layer calls:
    validated stage plan, stacked matrix/table operands as split-plane
    jnp arrays, and the VMEM-fitted block size.

    Mosaic scoped-VMEM budget: the stage chain keeps ~2 live (rows,128)
    plane pairs per stage (Mosaic does not fully reuse buffers across
    stage boundaries); a 15-stage 22q brickwork layer measured 21.8 MB
    against the 16 MB default limit on real v5e silicon (r5 tunnel,
    HTTP-500 from the compile helper). Raise the limit toward the
    chip's real VMEM and, if the estimate still exceeds it, halve the
    block until it fits (choose_block_rows).
    """
    kstages, mats, tables, xmats, block_rows, total_rows = \
        layer_kernel_plan(layer, num_qubits, block_rows)
    mstack = (np.stack(mats) if mats
              else np.zeros((1, 128, 128), np.complex128))
    tstack = (np.stack(tables) if tables
              else np.zeros((1, 128), np.complex128))
    if xmats:
        # the MXU-tile operators may mix dims (one per row-bit count);
        # stack zero-padded to the max — the kernel slices [:dim, :dim]
        xdim = max(m.shape[0] for m in xmats)
        xstack = np.zeros((len(xmats), xdim, xdim), np.complex128)
        for i, m in enumerate(xmats):
            xstack[i, :m.shape[0], :m.shape[1]] = m
    else:
        xstack = np.zeros((1, 8, 8), np.complex128)
    mre = jnp.asarray(mstack.real, rdtype)
    mim = jnp.asarray(mstack.imag, rdtype)
    tre = jnp.asarray(tstack.real, rdtype)
    tim = jnp.asarray(tstack.imag, rdtype)
    xre = jnp.asarray(xstack.real, rdtype)
    xim = jnp.asarray(xstack.imag, rdtype)
    itemsize = np.dtype(rdtype).itemsize
    vmem_limit = int(os.environ.get("QUEST_PALLAS_VMEM_LIMIT",
                                    100 * 1024 * 1024))
    block_rows, _ = choose_block_rows(kstages, mstack, tstack, block_rows,
                                      itemsize, vmem_limit, xstack)
    return (kstages, mstack, tstack, xstack, mre, mim, tre, tim, xre,
            xim, block_rows, total_rows, vmem_limit)


def _compiler_kwargs(interpret: bool, vmem_limit: int) -> dict:
    if interpret:
        return {}
    from jax.experimental.pallas import tpu as pltpu
    return {"compiler_params": pltpu.CompilerParams(
        vmem_limit_bytes=vmem_limit)}


def apply_layer(state: jnp.ndarray, num_qubits: int, layer: LayerOp,
                block_rows: int = DEFAULT_BLOCK_ROWS,
                interpret: bool = False,
                fast: bool = False) -> jnp.ndarray:
    """Apply a fused layer to a flat complex state (traceable; call under
    jit — the pallas_call compiles into the surrounding program).

    ``fast=True`` selects the FAST precision tier's lane stage:
    bf16-input (``Precision.DEFAULT``) MXU matmuls with bf16-split
    compensated f32 accumulation instead of the full-f32 ``HIGHEST``
    passes — the per-tier trade the budget API prices
    (:func:`quest_tpu.profiling.choose_tier`)."""
    from jax.experimental import pallas as pl

    rdtype = jnp.float32 if state.dtype == jnp.complex64 else jnp.float64
    (kstages, mstack, tstack, xstack, mre, mim, tre, tim, xre, xim,
     block_rows, total_rows, vmem_limit) = _layer_operands(
        layer, num_qubits, block_rows, rdtype)
    re = jnp.real(state).astype(rdtype).reshape(total_rows, 128)
    im = jnp.imag(state).astype(rdtype).reshape(total_rows, 128)
    kernel = functools.partial(_layer_kernel, stages=tuple(kstages),
                               block_rows=block_rows, fast=fast)
    state_spec = pl.BlockSpec((block_rows, 128), lambda i: (i, 0))
    mat_spec = pl.BlockSpec(mstack.shape, lambda i: (0, 0, 0))
    tab_spec = pl.BlockSpec(tstack.shape, lambda i: (0, 0))
    xmat_spec = pl.BlockSpec(xstack.shape, lambda i: (0, 0, 0))
    with jax.named_scope(f"pallas_layer_{layer.members}gates"):
        out_re, out_im = pl.pallas_call(
            kernel,
            grid=(total_rows // block_rows,),
            in_specs=[state_spec, state_spec, mat_spec, mat_spec,
                      tab_spec, tab_spec, xmat_spec, xmat_spec],
            out_specs=[state_spec, state_spec],
            out_shape=[jax.ShapeDtypeStruct((total_rows, 128), rdtype)] * 2,
            interpret=interpret,
            **_compiler_kwargs(interpret, vmem_limit),
        )(re, im, mre, mim, tre, tim, xre, xim)
    return jax.lax.complex(out_re, out_im).reshape(-1).astype(state.dtype)


def apply_layer_batched(states: jnp.ndarray, num_qubits: int, layer: LayerOp,
                        block_rows: int = DEFAULT_BLOCK_ROWS,
                        interpret: bool = False,
                        fast: bool = False) -> jnp.ndarray:
    """Apply a fused layer to a BATCH of flat complex states
    ``(batch, 2^n)`` in one ``pallas_call``.

    The kernel grid grows a leading batch dimension — ``(batch,
    row_blocks)`` with state blocks of ``(1, block_rows, 128)`` — so the
    batched ensemble engine keeps the fused-layer pass instead of
    falling back to the per-gate XLA twin (``jax.vmap`` has no batching
    rule for a compiled ``pallas_call``; growing the grid is the
    TPU-native answer). Per-grid-step VMEM working set is identical to
    the unbatched kernel: the batch axis only adds grid steps."""
    from jax.experimental import pallas as pl

    batch = states.shape[0]
    rdtype = jnp.float32 if states.dtype == jnp.complex64 else jnp.float64
    (kstages, mstack, tstack, xstack, mre, mim, tre, tim, xre, xim,
     block_rows, total_rows, vmem_limit) = _layer_operands(
        layer, num_qubits, block_rows, rdtype)
    re = jnp.real(states).astype(rdtype).reshape(batch, total_rows, 128)
    im = jnp.imag(states).astype(rdtype).reshape(batch, total_rows, 128)
    kernel = functools.partial(_layer_kernel, stages=tuple(kstages),
                               block_rows=block_rows, batched=True,
                               fast=fast)
    state_spec = pl.BlockSpec((1, block_rows, 128), lambda b, i: (b, i, 0))
    mat_spec = pl.BlockSpec(mstack.shape, lambda b, i: (0, 0, 0))
    tab_spec = pl.BlockSpec(tstack.shape, lambda b, i: (0, 0))
    xmat_spec = pl.BlockSpec(xstack.shape, lambda b, i: (0, 0, 0))
    with jax.named_scope(
            f"pallas_layer_b{batch}_{layer.members}gates"):
        out_re, out_im = pl.pallas_call(
            kernel,
            grid=(batch, total_rows // block_rows),
            in_specs=[state_spec, state_spec, mat_spec, mat_spec,
                      tab_spec, tab_spec, xmat_spec, xmat_spec],
            out_specs=[state_spec, state_spec],
            out_shape=[jax.ShapeDtypeStruct((batch, total_rows, 128),
                                            rdtype)] * 2,
            interpret=interpret,
            **_compiler_kwargs(interpret, vmem_limit),
        )(re, im, mre, mim, tre, tim, xre, xim)
    return jax.lax.complex(out_re, out_im).reshape(batch, -1).astype(
        states.dtype)


class _ExecCache:
    """Tiny thread-safe keyed executable cache for the standalone
    kernel entries below — the same ``_cached(key, builder)`` idiom
    (and the same LRU bound class) as the engine caches, so quest-lint
    QL002 checks these insertions' key completeness too."""

    def __init__(self, maxsize: int = 16):
        import threading
        self._lock = threading.Lock()
        self._maxsize = maxsize
        self._c = None

    def _cached(self, key, builder):
        from ..circuits import _BoundedExecutableCache
        with self._lock:
            if self._c is None:
                self._c = _BoundedExecutableCache(self._maxsize)
            fn = self._c.get(key)
        if fn is not None:
            return fn
        fn = builder()
        with self._lock:
            self._c[key] = fn
        return fn


_MXU_EXEC = _ExecCache(int(os.environ.get("QUEST_TPU_MXU_TILE_CACHE",
                                          "16")))


def apply_mxu_tile(state: jnp.ndarray, num_qubits: int, u: np.ndarray,
                   targets: Sequence[int], fast: bool = False,
                   interpret: bool = False,
                   block_rows: int = DEFAULT_BLOCK_ROWS) -> jnp.ndarray:
    """Apply ONE dense uncontrolled gate as an MXU-shaped contraction:
    the gate (static host matrix, any mix of lane and row targets within
    the block range) embeds over ``(lane qubits + its row bits)`` into a
    ``(2^j * 128)``-tile operator and runs as systolic-array matmuls in
    one HBM pass — the standalone form of the ``rowmxu`` layer stage
    (bench off/on rows and parity tests drive it directly; compiled
    programs get the same shape through the layer collector).

    The jitted executable is cached per ``(geometry, dtype, tier mode)``
    — the MATRIX is an argument, so one executable serves every gate of
    the same shape."""
    from jax.experimental import pallas as pl

    n = int(num_qubits)
    targets = tuple(int(t) for t in targets)
    bits = tuple(sorted(t - LANE_QUBITS for t in targets
                        if t >= LANE_QUBITS))
    total_rows = (1 << n) // 128
    if total_rows < 1:
        raise ValueError("MXU tiles need at least 7 qubits")
    block_rows = min(block_rows, total_rows)
    if bits and bits[-1] + LANE_QUBITS > max_mid_qubit(block_rows):
        raise ValueError(
            f"row target {bits[-1] + LANE_QUBITS} outside the "
            f"{block_rows}-row block range")
    m = mxu_group_matrix(u, targets, bits)
    dim = m.shape[0]
    rdtype = jnp.float32 if state.dtype == jnp.complex64 else jnp.float64
    dt_token = str(np.dtype(rdtype))
    tier_tok = "fast" if fast else "highest"
    vmem_limit = int(os.environ.get("QUEST_PALLAS_VMEM_LIMIT",
                                    100 * 1024 * 1024))

    def build():
        kernel = functools.partial(
            _layer_kernel, stages=(("rowmxu", bits, 0, dim),),
            block_rows=block_rows, fast=fast)
        state_spec = pl.BlockSpec((block_rows, 128), lambda i: (i, 0))
        dummy_spec = pl.BlockSpec((1, 1, 1), lambda i: (0, 0, 0))
        tab_spec = pl.BlockSpec((1, 1), lambda i: (0, 0))
        xmat_spec = pl.BlockSpec((1, dim, dim), lambda i: (0, 0, 0))

        def fn(re, im, xre, xim):
            z = jnp.zeros((1, 1, 1), rdtype)
            zt = jnp.zeros((1, 1), rdtype)
            return pl.pallas_call(
                kernel,
                grid=(total_rows // block_rows,),
                in_specs=[state_spec, state_spec, dummy_spec, dummy_spec,
                          tab_spec, tab_spec, xmat_spec, xmat_spec],
                out_specs=[state_spec, state_spec],
                out_shape=[jax.ShapeDtypeStruct((total_rows, 128),
                                                rdtype)] * 2,
                interpret=interpret,
                **_compiler_kwargs(interpret, vmem_limit),
            )(re, im, z, z, zt, zt, xre, xim)

        return jax.jit(fn)

    call = _MXU_EXEC._cached(
        ("mxu_tile", n, bits, block_rows, dt_token, tier_tok,
         bool(interpret)), build)
    re = jnp.real(state).astype(rdtype).reshape(total_rows, 128)
    im = jnp.imag(state).astype(rdtype).reshape(total_rows, 128)
    xre = jnp.asarray(m.real, rdtype)[None]
    xim = jnp.asarray(m.imag, rdtype)[None]
    with jax.named_scope(f"pallas_mxu_tile_{dim}"):
        out_re, out_im = call(re, im, xre, xim)
    return jax.lax.complex(out_re, out_im).reshape(-1).astype(state.dtype)


def _kraus_kernel(re_ref, im_ref, kre_ref, kim_ref, p_ref, u_ref,
                  ore_ref, oim_ref, *, num_ops, block_rows):
    """Fused per-trajectory Kraus draw + apply + renormalise: ONE kernel
    replaces the XLA chain categorical-draw -> stacked-operator gather
    -> apply -> rsqrt renorm. The draw is inverse-CDF over the channel
    probabilities against the trajectory's uniform (scalar unrolled —
    K is the Kraus count, 2-4 for every physical channel), the selected
    lane-embedded operator is blended by exact one-hot weights, the
    renormalisation ``1/sqrt(p_j)`` folds into the operator, and the
    state streams through VMEM exactly once."""
    re = re_ref[0]
    im = im_ref[0]
    acc = re.dtype
    total = p_ref[0, 0]
    for k in range(1, num_ops):
        total = total + p_ref[0, k]
    # cap the threshold STRICTLY below the total: fl(u * total) can
    # round up to `total` at u -> 1, where every prefix would count and
    # the clamp would select branch K-1 even at p_{K-1} == 0 — a
    # zero-probability draw the XLA categorical never makes, rsqrt'd
    # into a garbage trajectory. With uu < total the selected branch
    # (the first prefix sum exceeding uu) always carries positive
    # probability; prefixes that EQUAL uu are counted as used up, so a
    # leading zero-probability branch is skipped at u == 0 too.
    uu = jnp.minimum(u_ref[0, 0] * total,
                     total - total * jnp.finfo(acc).eps)
    cum = p_ref[0, 0] * 0.0
    cnt = jnp.int32(0)
    for k in range(num_ops):
        cum = cum + p_ref[0, k]
        cnt = cnt + (cum <= uu).astype(jnp.int32)
    jidx = jnp.minimum(cnt, num_ops - 1)
    psel = p_ref[0, 0] * 0.0
    for k in range(num_ops):
        psel = psel + (jidx == k).astype(acc) * p_ref[0, k]
    scale = jax.lax.rsqrt(jnp.maximum(psel, jnp.finfo(acc).tiny))
    mre = (jidx == 0).astype(acc) * kre_ref[0]
    mim = (jidx == 0).astype(acc) * kim_ref[0]
    for k in range(1, num_ops):
        w = (jidx == k).astype(acc)
        mre = mre + w * kre_ref[k]
        mim = mim + w * kim_ref[k]
    mre = mre * scale
    mim = mim * scale
    new_re, new_im = _mxu_matmuls(re, im, mre.T, mim.T, acc, False)
    ore_ref[0] = new_re.astype(re.dtype)
    oim_ref[0] = new_im.astype(im.dtype)


def fused_kraus_apply_batched(states: jnp.ndarray, num_qubits: int,
                              kstack: np.ndarray, probs: jnp.ndarray,
                              u01: jnp.ndarray,
                              block_rows: int = DEFAULT_BLOCK_ROWS,
                              interpret: bool = False) -> jnp.ndarray:
    """Draw + apply one Kraus channel for a whole trajectory batch in
    ONE ``pallas_call``: ``states`` is the ``(T, 2^n)`` complex batch,
    ``kstack`` the ``(K, 128, 128)`` LANE-EMBEDDED operator stack (all
    channel targets below qubit 7 — :func:`embed_lane_matrix` per
    operator), ``probs`` the ``(T, K)`` physical channel probabilities
    (one reduced-density pass, computed upstream), and ``u01`` the
    ``(T,)`` per-trajectory uniforms driving the inverse-CDF draw.
    Grid ``(T, row_blocks)``; traceable — call under jit."""
    from jax.experimental import pallas as pl

    T = states.shape[0]
    n = int(num_qubits)
    K = int(kstack.shape[0])
    total_rows = (1 << n) // 128
    if total_rows < 1:
        raise ValueError("the fused Kraus kernel needs at least 7 qubits")
    block_rows = min(block_rows, total_rows)
    rdtype = jnp.float32 if states.dtype == jnp.complex64 \
        else jnp.float64
    vmem_limit = int(os.environ.get("QUEST_PALLAS_VMEM_LIMIT",
                                    100 * 1024 * 1024))
    re = jnp.real(states).astype(rdtype).reshape(T, total_rows, 128)
    im = jnp.imag(states).astype(rdtype).reshape(T, total_rows, 128)
    kre = jnp.asarray(np.ascontiguousarray(kstack.real), rdtype)
    kim = jnp.asarray(np.ascontiguousarray(kstack.imag), rdtype)
    p2 = jnp.asarray(probs, rdtype).reshape(T, K)
    u2 = jnp.asarray(u01, rdtype).reshape(T, 1)
    kernel = functools.partial(_kraus_kernel, num_ops=K,
                               block_rows=block_rows)
    state_spec = pl.BlockSpec((1, block_rows, 128), lambda t, i: (t, i, 0))
    k_spec = pl.BlockSpec((K, 128, 128), lambda t, i: (0, 0, 0))
    p_spec = pl.BlockSpec((1, K), lambda t, i: (t, 0))
    u_spec = pl.BlockSpec((1, 1), lambda t, i: (t, 0))
    with jax.named_scope(f"pallas_kraus_t{T}_k{K}"):
        out_re, out_im = pl.pallas_call(
            kernel,
            grid=(T, total_rows // block_rows),
            in_specs=[state_spec, state_spec, k_spec, k_spec, p_spec,
                      u_spec],
            out_specs=[state_spec, state_spec],
            out_shape=[jax.ShapeDtypeStruct((T, total_rows, 128),
                                            rdtype)] * 2,
            interpret=interpret,
            **_compiler_kwargs(interpret, vmem_limit),
        )(re, im, kre, kim, p2, u2)
    return jax.lax.complex(out_re, out_im).reshape(T, -1).astype(
        states.dtype)


def _vmem_estimate(block_rows: int, kstages, mstack, tstack,
                   itemsize: int, xstack=None) -> int:
    """Conservative Mosaic working-set model for one grid step: in + out
    plane pairs with double-buffering (x2), ~2 extra live plane pairs per
    stage (a rowk stage keeps its 2^k group slices live, so it weighs
    2^(k-1) plain stages; a rowmxu stage keeps its regrouped planes —
    one full pair — live next to the contraction), plus the stacked
    operand buffers (the MXU-tile stack included)."""
    plane_pair = 2 * block_rows * 128 * itemsize
    weight = sum((1 << len(st[1])) // 2 if st[0] == "rowk"
                 else 2 if st[0] == "rowmxu" else 1
                 for st in kstages)
    xbytes = 2 * int(np.prod(xstack.shape)) * itemsize \
        if xstack is not None else 0
    return (4 * plane_pair + 2 * weight * plane_pair
            + 2 * int(np.prod(mstack.shape)) * itemsize
            + 2 * int(np.prod(tstack.shape)) * itemsize + xbytes)
