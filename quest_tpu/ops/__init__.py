from . import statevec, densmatr, channels, reductions  # noqa: F401
