from . import statevec, densmatr, channels  # noqa: F401
