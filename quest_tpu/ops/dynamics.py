"""Device-resident Hamiltonian dynamics kernels (ISSUE 18).

Trotterised real-time evolution and imaginary-time / Lanczos
ground-state search as PURE traceable step kernels over the Pauli-sum
bit-mask machinery (:mod:`quest_tpu.ops.reductions`):

- a Pauli string is three integer masks; ``exp(-i theta P)`` is the
  exact two-term rotation ``cos(theta) z - i sin(theta) (P z)``
  (``P^2 = I``), one xor-gather pass per term
  (:func:`~quest_tpu.ops.reductions.pauli_apply_sv`);
- a first-order Trotter step is one ascending ``lax.scan`` over the
  term masks; a second-order (Strang) step is a half-angle forward
  sweep followed by a half-angle REVERSE sweep
  (``lax.scan(..., reverse=True)``) — the mirror symmetry that buys
  the O(dt^2) -> O(dt^3) local error;
- imaginary time replaces the rotation with the exact hyperbolic form
  ``cosh(tau c) z - sinh(tau c) (P z)`` plus on-device
  renormalisation — power iteration toward the ground state;
- :func:`lanczos_ground` is the Krylov option: a fixed-m on-device
  Lanczos recursion (H·v through
  :func:`~quest_tpu.ops.reductions.pauli_sum_apply_sv`), an ``(m, m)``
  tridiagonal ``jnp.linalg.eigh``, and the Ritz vector — with the
  residual bound ``beta_m |y_m|`` as a device-resident convergence
  signal.

Masks and coefficients are DATA (traced arguments), never trace
constants: one compiled executable serves every Hamiltonian of a given
term bucket, exactly like the energy executables. Zero-coefficient
identity padding terms (:func:`~quest_tpu.ops.reductions.
pauli_term_bucket`) are EXACT no-ops in every kernel here
(``cos(0) = cosh(0) = 1``, ``sin(0) = sinh(0) = 0``).

The batched, serving-facing executables live in
:meth:`quest_tpu.circuits.CompiledCircuit.evolve_sweep` /
``ground_sweep`` — they run these kernels inside ``lax.scan`` step
loops and return ONE packed real block per request batch (energies +
Welford carry + final planes), so a whole checkpointed segment costs a
single device->host transfer. The pack/unpack layout helpers are
defined HERE, one definition for the engine and the serving fan-out.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax.numpy as jnp
from jax import lax

from . import reductions as red

__all__ = ["EvolveSpec", "GroundSpec", "trotter_sweep", "trotter_step",
           "imag_time_step", "lanczos_ground", "evolve_block_width",
           "ground_block_width", "pack_evolve_block",
           "unpack_evolve_block", "pack_ground_block",
           "unpack_ground_block"]


# ---------------------------------------------------------------------------
# request contracts (the serving layer's coalescing / digest payloads)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EvolveSpec:
    """One real-time evolution contract: evolve by ``exp(-i H t)`` in
    ``steps`` Trotter steps of order ``order`` (1 or 2), recording the
    Pauli-sum energy after every step. ``dt = t / steps`` is the data
    the executable sees; ``(steps, order)`` are static (part of the
    executable cache key — the scan length is a trace constant)."""

    t: float
    steps: int
    order: int = 2

    def __post_init__(self):
        if self.steps < 1:
            raise ValueError("steps must be >= 1")
        if self.order not in (1, 2):
            raise ValueError("Trotter order must be 1 or 2")
        if not np.isfinite(self.t):
            raise ValueError("evolution time must be finite")

    @property
    def dt(self) -> float:
        # quest: allow-host-sync(spec fields are plain Python floats —
        # dataclass arithmetic, never a device value)
        return float(self.t) / float(self.steps)

    def contract(self) -> tuple:
        """The hashable convergence-contract tail of a coalesce key:
        requests sharing a compiled program AND this contract batch
        into one fused step loop."""
        # quest: allow-host-sync(hashable key from plain Python
        # dataclass fields, never a device value)
        return (float(self.t), int(self.steps), int(self.order))


@dataclasses.dataclass(frozen=True)
class GroundSpec:
    """One ground-state search contract. ``method`` is ``"power"``
    (imaginary-time Trotter power iteration, ``steps`` iterations per
    segment at time-step ``tau``) or ``"lanczos"`` (a fixed-``steps``
    Krylov recursion — ``tau`` unused). ``tol`` is the convergence
    residual the serving handle stops at: per-segment energy drift for
    power iteration, the ``beta_m |y_m|`` Ritz bound for Lanczos."""

    steps: int = 16
    tau: float = 0.1
    method: str = "power"
    tol: float = 1e-9

    def __post_init__(self):
        if self.steps < 1:
            raise ValueError("steps must be >= 1")
        if self.method not in ("power", "lanczos"):
            raise ValueError("method must be 'power' or 'lanczos'")
        if not (self.tau > 0.0 and np.isfinite(self.tau)):
            raise ValueError("tau must be finite and > 0")
        if not (self.tol >= 0.0):
            raise ValueError("tol must be >= 0")

    def contract(self) -> tuple:
        # quest: allow-host-sync(hashable key from plain Python
        # dataclass fields, never a device value)
        tau, tol = float(self.tau), float(self.tol)
        return (int(self.steps), tau, str(self.method), tol)


# ---------------------------------------------------------------------------
# step kernels (traceable; masks/coefficients/angles are data)
# ---------------------------------------------------------------------------


def trotter_sweep(z, xmask, ymask, zmask, coeffs, theta, reverse=False):
    """One ordered product sweep ``prod_t exp(-i theta c_t P_t) |z>``
    (ascending term order; ``reverse=True`` descends — the mirror half
    of a Strang step). Each term is the exact Pauli rotation
    ``cos(a) z - i sin(a) (P z)`` with ``a = theta * c_t`` (``P^2 = I``
    makes the two-term form exact, not an approximation): one
    xor-gather pass, no per-qubit gate loop. Zero-coefficient padding
    terms are exact identities."""
    rdt = jnp.real(z).dtype

    def body(state, operands):
        xm, ym, zm, c = operands
        a = (jnp.asarray(theta, rdt) * c.astype(rdt))
        pz = red.pauli_apply_sv(state, xm, ym, zm)
        ca, sa = jnp.cos(a), jnp.sin(a)
        return state * ca.astype(state.dtype) \
            + pz * lax.complex(jnp.zeros_like(sa), -sa).astype(state.dtype), \
            None

    z, _ = lax.scan(body, z,
                    (jnp.asarray(xmask), jnp.asarray(ymask),
                     jnp.asarray(zmask), jnp.asarray(coeffs)),
                    reverse=bool(reverse))
    return z


def trotter_step(z, xmask, ymask, zmask, coeffs, dt, order: int = 2):
    """One Trotter step of ``exp(-i H dt)``. ``order=1`` is the plain
    ascending sweep at full ``dt`` (local error O(dt^2)); ``order=2``
    is the Strang splitting — a half-``dt`` forward sweep mirrored by a
    half-``dt`` reverse sweep (local error O(dt^3), global O(t dt^2)).
    ``order`` is static; ``dt`` is data."""
    if order == 1:
        return trotter_sweep(z, xmask, ymask, zmask, coeffs, dt)
    if order != 2:
        raise ValueError("Trotter order must be 1 or 2")
    half = jnp.asarray(dt) * 0.5
    z = trotter_sweep(z, xmask, ymask, zmask, coeffs, half)
    return trotter_sweep(z, xmask, ymask, zmask, coeffs, half,
                         reverse=True)


def imag_time_step(z, xmask, ymask, zmask, coeffs, tau):
    """One imaginary-time Trotter step ``~ exp(-tau H) |z>``, followed
    by on-device renormalisation: per term the exact hyperbolic form
    ``cosh(a) z - sinh(a) (P z)`` with ``a = tau * c_t`` (again
    ``P^2 = I``). Repeated application is power iteration toward the
    dominant eigenvector of ``exp(-tau H)`` — the ground state of
    ``H`` — with the norm renormalised every step so the iterate never
    under/overflows."""
    rdt = jnp.real(z).dtype

    def body(state, operands):
        xm, ym, zm, c = operands
        a = (jnp.asarray(tau, rdt) * c.astype(rdt))
        pz = red.pauli_apply_sv(state, xm, ym, zm)
        return state * jnp.cosh(a).astype(state.dtype) \
            - pz * jnp.sinh(a).astype(state.dtype), None

    z, _ = lax.scan(body, z,
                    (jnp.asarray(xmask), jnp.asarray(ymask),
                     jnp.asarray(zmask), jnp.asarray(coeffs)))
    norm = jnp.sqrt(jnp.sum(jnp.real(z) ** 2 + jnp.imag(z) ** 2))
    return z / jnp.maximum(norm, jnp.asarray(1e-300, rdt)).astype(z.dtype)


def lanczos_ground(z, xmask, ymask, zmask, coeffs, num_vectors: int = 24):
    """Fixed-``num_vectors`` Lanczos recursion toward the ground state,
    entirely on device: Krylov basis by the three-term recurrence
    (``H v`` through :func:`~quest_tpu.ops.reductions.
    pauli_sum_apply_sv`), an ``(m, m)`` tridiagonal ``jnp.linalg.eigh``
    (a tiny host-free dense solve), and the Ritz vector of the lowest
    Ritz value. Returns ``(ritz_vector, energy, residual)`` with
    ``residual = |beta_m * y_m|`` — the classical Lanczos bound on
    ``||H x - E x||``, a device-resident convergence signal the serving
    handle reads WITHOUT materialising the state.

    An exhausted Krylov space (breakdown: ``beta ~ 0`` — e.g. the start
    vector already an eigenvector) zeroes the remaining basis vectors
    and pins their diagonal entries far ABOVE the spectrum, so the
    spurious decoupled block can never pose as the minimum Ritz
    value."""
    if num_vectors < 2:
        raise ValueError("lanczos needs num_vectors >= 2")
    rdt = jnp.real(z).dtype
    cutoff = jnp.asarray(1e-12, rdt)
    xm, ym, zm = (jnp.asarray(m) for m in (xmask, ymask, zmask))
    cf = jnp.asarray(coeffs)

    def _norm(v):
        return jnp.sqrt(jnp.sum(jnp.real(v) ** 2 + jnp.imag(v) ** 2))

    n0 = _norm(z)
    v0 = z / jnp.maximum(n0, jnp.asarray(1e-300, rdt)).astype(z.dtype)

    def body(carry, _):
        v_prev, v_cur, beta_prev, alive = carry
        w = red.pauli_sum_apply_sv(v_cur, xm, ym, zm, cf)
        w = w - beta_prev.astype(z.dtype) * v_prev
        alpha = jnp.sum(jnp.real(jnp.conj(v_cur) * w))
        w = w - alpha.astype(z.dtype) * v_cur
        beta = _norm(w)
        ok = alive & (beta > cutoff)
        v_next = jnp.where(
            ok, w / jnp.maximum(beta, cutoff).astype(z.dtype),
            jnp.zeros_like(w))
        beta_out = jnp.where(ok, beta, jnp.zeros_like(beta))
        return (v_cur, v_next, beta_out, ok), \
            (v_cur, alpha, beta_out, alive)

    init = (jnp.zeros_like(v0), v0, jnp.zeros((), rdt),
            jnp.asarray(True))
    _, (basis, alphas, betas, alive) = lax.scan(
        body, init, None, length=int(num_vectors))
    # dead steps sit far above any physical coefficient scale: the
    # eigensolver's minimum can only come from the live block
    shift = (jnp.sum(jnp.abs(cf)).astype(rdt) + 1.0) * 1e6
    diag = jnp.where(alive, alphas, shift)
    tri = jnp.diag(diag) + jnp.diag(betas[:-1], 1) + jnp.diag(betas[:-1], -1)
    evals, evecs = jnp.linalg.eigh(tri)
    y = evecs[:, 0]
    ritz = jnp.sum(y.astype(z.dtype)[:, None] * basis, axis=0)
    rn = _norm(ritz)
    ritz = ritz / jnp.maximum(rn, jnp.asarray(1e-300, rdt)).astype(z.dtype)
    residual = jnp.abs(betas[-1] * y[-1])
    return ritz, evals[0], residual


# ---------------------------------------------------------------------------
# packed segment blocks (the one-transfer-per-segment contract)
# ---------------------------------------------------------------------------
#
# An evolve/ground executable returns its WHOLE segment as one flat real
# row per request: the per-step energies, the device-folded Welford
# (count, mean, M2) carry over those energies, [ground only: the
# convergence residual,] and the final packed state planes. ONE layout
# definition here keeps the engine's pack and the serving layer's
# unpack from desynchronising — a drifted offset would hand callers
# amplitudes as energies.


def evolve_block_width(num_qubits: int, steps: int) -> int:
    """Flat row width of one packed evolve segment: ``steps`` energies
    + 3 Welford components + ``2 * 2^n`` plane entries."""
    return int(steps) + 3 + (1 << (int(num_qubits) + 1))


def ground_block_width(num_qubits: int, steps: int) -> int:
    """Evolve width + 1 (the convergence residual column)."""
    return evolve_block_width(num_qubits, steps) + 1


def pack_evolve_block(energies, welford, planes):
    """``(S,)`` energies + ``(3,)`` Welford + ``(2, 2^n)`` planes ->
    one flat real row (traceable; the executable's return value)."""
    rdt = planes.dtype
    return jnp.concatenate([energies.astype(rdt), welford.astype(rdt),
                            planes.reshape(-1)])


def unpack_evolve_block(block, num_qubits: int, steps: int):
    """Inverse of :func:`pack_evolve_block` over a leading batch axis:
    ``(B, W)`` -> dict of ``energies (B, S)``, ``welford (B, 3)``,
    ``planes (B, 2, 2^n)`` (host numpy in, host numpy out)."""
    # quest: allow-host-sync(host-side unpack by contract: the caller
    # already paid the segment's ONE device->host transfer)
    block = np.asarray(block)
    S = int(steps)
    if block.ndim != 2 or block.shape[1] != evolve_block_width(
            num_qubits, S):
        raise ValueError(
            f"packed evolve block must be (B, "
            f"{evolve_block_width(num_qubits, S)}); got {block.shape}")
    return {"energies": block[:, :S],
            "welford": block[:, S:S + 3],
            "planes": block[:, S + 3:].reshape(
                block.shape[0], 2, 1 << int(num_qubits))}


def pack_ground_block(energies, residual, welford, planes):
    """Ground variant: the residual column sits between the energies
    and the Welford carry."""
    rdt = planes.dtype
    return jnp.concatenate([energies.astype(rdt),
                            jnp.reshape(residual, (1,)).astype(rdt),
                            welford.astype(rdt), planes.reshape(-1)])


def unpack_ground_block(block, num_qubits: int, steps: int):
    """``(B, W)`` -> dict of ``energies (B, S)``, ``residual (B,)``,
    ``welford (B, 3)``, ``planes (B, 2, 2^n)``."""
    # quest: allow-host-sync(host-side unpack by contract: the caller
    # already paid the segment's ONE device->host transfer)
    block = np.asarray(block)
    S = int(steps)
    if block.ndim != 2 or block.shape[1] != ground_block_width(
            num_qubits, S):
        raise ValueError(
            f"packed ground block must be (B, "
            f"{ground_block_width(num_qubits, S)}); got {block.shape}")
    return {"energies": block[:, :S],
            "residual": block[:, S],
            "welford": block[:, S + 1:S + 4],
            "planes": block[:, S + 4:].reshape(
                block.shape[0], 2, 1 << int(num_qubits))}
