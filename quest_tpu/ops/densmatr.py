"""Pure-functional density-matrix operations on the flat 2n-qubit vector.

The reference flattens an n-qubit density matrix into a 2n-qubit vector with
``flat[r + c*2^n] = rho[r, c]`` and reuses the statevector kernels on it
(``QuEST.c:8-10``). We keep that layout: unitaries act as ``U`` on the row
qubits then ``conj(U)`` on the column qubits ``q+n`` (handled in the api
layer), while the ops here are the genuinely density-specific ones
(``QuEST_internal.h:57-101``).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.apply import apply_diagonal, apply_unitary, split_shape

__all__ = [
    "init_pure_state",
    "calc_total_prob",
    "calc_prob_of_outcome",
    "collapse_to_known_prob_outcome",
    "calc_purity",
    "calc_fidelity",
    "calc_inner_product",
    "calc_hilbert_schmidt_distance",
    "mix_density_matrix",
    "mix_dephasing",
    "mix_two_qubit_dephasing",
    "dephasing_factors",
    "two_qubit_dephasing_factors",
    "apply_kraus_superoperator",
    "kraus_superoperator",
]


def _as_matrix(flat, num_qubits):
    """View flat density vector as mat[c, r] = rho[r, c] (column axis leads
    because columns occupy the high index bits)."""
    dim = 1 << num_qubits
    return flat.reshape(dim, dim)


def init_pure_state(pure_state) -> jnp.ndarray:
    """rho = |psi><psi|: flat[r + c*2^n] = psi_r * conj(psi_c)
    (``QuEST_cpu.c:1189``)."""
    return jnp.outer(jnp.conj(pure_state), pure_state).reshape(-1)


def calc_total_prob(flat, num_qubits: int) -> jnp.ndarray:
    """Trace: sum of real diagonal entries (``densmatr_calcTotalProb``)."""
    return jnp.sum(jnp.real(jnp.diagonal(_as_matrix(flat, num_qubits))))


def calc_prob_of_outcome(flat, num_qubits: int, qubit: int, outcome: int) -> jnp.ndarray:
    """Sum diagonal entries whose basis state has ``qubit``==0, complemented
    for outcome 1 (``densmatr_findProbabilityOfZeroLocal``
    ``QuEST_cpu.c:3117``)."""
    diag = jnp.real(jnp.diagonal(_as_matrix(flat, num_qubits)))
    shape = split_shape(num_qubits, (qubit,))
    zero_prob = jnp.sum(diag.reshape(shape)[:, 0, :])
    return zero_prob if outcome == 0 else 1.0 - zero_prob


def collapse_to_known_prob_outcome(flat, num_qubits, qubit, outcome, prob):
    """Keep only elements with row *and* column qubit == outcome, scaled 1/prob
    (``QuEST_cpu.c:790``)."""
    fac = jnp.zeros((2, 2), dtype=flat.dtype).at[outcome, outcome].set(
        (1.0 / prob).astype(flat.dtype) if hasattr(prob, "dtype") else 1.0 / prob
    )
    # qubit in rows is bit `qubit`; in columns bit `qubit + n`
    return apply_diagonal(flat, 2 * num_qubits, (qubit + num_qubits, qubit), fac)


def calc_purity(flat) -> jnp.ndarray:
    """Tr(rho^2) = sum |rho_ij|^2 (``densmatr_calcPurityLocal``)."""
    return jnp.sum(jnp.real(flat) ** 2 + jnp.imag(flat) ** 2)


def calc_fidelity(flat, num_qubits: int, pure_state) -> jnp.ndarray:
    """<psi|rho|psi> (``densmatr_calcFidelityLocal`` ``QuEST_cpu.c:995``)."""
    mat = _as_matrix(flat, num_qubits)  # mat[c, r] = rho[r, c]
    val = jnp.einsum("cr,r,c->", mat, jnp.conj(pure_state), pure_state,
                     precision=jax.lax.Precision.HIGHEST)
    return jnp.real(val)


def calc_inner_product(flat_a, flat_b) -> jnp.ndarray:
    """real(Tr(a^dag b)) (``densmatr_calcInnerProductLocal``
    ``QuEST_cpu.c:963``)."""
    return jnp.real(jnp.vdot(flat_a, flat_b))


def calc_hilbert_schmidt_distance(flat_a, flat_b) -> jnp.ndarray:
    """sqrt(sum |a-b|^2) (``QuEST_cpu.c:928``)."""
    d = flat_a - flat_b
    return jnp.sqrt(jnp.sum(jnp.real(d) ** 2 + jnp.imag(d) ** 2))


def mix_density_matrix(flat_combine, other_prob, flat_other):
    """combine = (1-p)*combine + p*other (``QuEST_cpu.c:895``)."""
    p = jnp.asarray(other_prob, dtype=flat_combine.dtype)
    return (1.0 - p) * flat_combine + p * flat_other


# ---------------------------------------------------------------------------
# decoherence channels
# ---------------------------------------------------------------------------
#
# All channels are Kraus maps. The reference builds a superoperator
# S[(i,k),(j,l)] = sum_n conj(K_n[i,j]) K_n[k,l] and applies it as a 2k-qubit
# "unitary" on targets (t, t+n) of the flat vector
# (``QuEST_common.c:540-604``). We keep that single code path, with the
# dephasing channels special-cased to diagonal multiplies (the reference's
# ``densmatr_oneQubitDegradeOffDiagonal`` fast path, ``QuEST_cpu.c:48``).


def kraus_superoperator(ops) -> np.ndarray:
    """S = sum_n conj(K_n) (x) K_n with row (i,k), col (j,l); i,j the column-
    (bra-)side indices (``macro_populateKrausOperator``
    ``QuEST_common.c:543-563``)."""
    ops = [np.asarray(op, dtype=np.complex128) for op in ops]
    d = ops[0].shape[0]
    s = np.zeros((d * d, d * d), dtype=np.complex128)
    for op in ops:
        s += np.kron(np.conj(op), op)
    return s


def kraus_superoperator_traceable(ops) -> jnp.ndarray:
    """Traceable (jnp) form of :func:`kraus_superoperator`, for
    PARAMETERIZED channels whose Kraus operators are built from tracers
    (``Circuit.kraus`` with a callable)."""
    s = None
    for op in ops:
        term = jnp.kron(jnp.conj(op), op)
        s = term if s is None else s + term
    return s


def apply_kraus_superoperator(flat, num_qubits, targets, superop):
    """Apply a superoperator to targets of the flat density vector.

    Matrix bit order: targets (row side, low bits) then targets+n (column
    side, high bits) — ``densmatr_applyMultiQubitKrausSuperoperator``
    (``QuEST_common.c:598-604``)."""
    all_targets = tuple(targets) + tuple(t + num_qubits for t in targets)
    return apply_unitary(flat, 2 * num_qubits, superop, all_targets)


def dephasing_factors(prob: float) -> np.ndarray:
    """(2, 2) off-diagonal retain tensor of 1q dephasing, axes
    (column bit, row bit) — shared by the GSPMD, lazy-sharded and dd
    paths."""
    retain = 1.0 - 2.0 * prob
    return np.array([[1.0, retain], [retain, 1.0]], dtype=np.complex128)


def two_qubit_dephasing_factors(prob: float) -> np.ndarray:
    """(2, 2, 2, 2) retain tensor of 2q dephasing, axes
    (c_hi, c_lo, r_hi, r_lo): any row/column mismatch scales by 1-4p/3."""
    retain = 1.0 - (4.0 * prob) / 3.0
    fac = np.ones((2, 2, 2, 2), dtype=np.complex128)
    for chi in range(2):
        for clo in range(2):
            for rhi in range(2):
                for rlo in range(2):
                    if chi != rhi or clo != rlo:
                        fac[chi, clo, rhi, rlo] = retain
    return fac


def mix_dephasing(flat, num_qubits, target, prob):
    """rho -> (1-p) rho + p Z rho Z: off-diagonals (in ``target``) scaled by
    1-2p (``densmatr_mixDephasing`` with dephase=2p, ``QuEST.c:907``)."""
    fac = dephasing_factors(prob)
    return apply_diagonal(flat, 2 * num_qubits, (target + num_qubits, target), fac)


def mix_two_qubit_dephasing(flat, num_qubits, q1, q2, prob):
    """Z error on either/both qubits, total prob p: any row/col mismatch in
    q1 or q2 scales by 1-4p/3 (``densmatr_mixTwoQubitDephasing``)."""
    qs = sorted((q1 + num_qubits, q2 + num_qubits, q2, q1), reverse=True)
    # tensor indexed by bits of sorted-desc positions: (c2, c1, r2, r1)
    # when q2 > q1
    return apply_diagonal(flat, 2 * num_qubits, qs,
                          two_qubit_dephasing_factors(prob))
