"""Compensated reductions for single-precision accuracy parity.

The reference leans on Kahan summation for its distributed probability
reductions (``statevec_calcTotalProb``, ``QuEST_cpu_distributed.c:87-109``)
and offers a quad-precision build when double isn't enough
(``QuEST_precision.h:28-65``). On TPU, float64 is unavailable in hardware,
so single-precision registers need error-compensated reductions to approach
the reference's 1e-10-class accuracy for scalar results.

Three error-free transformations, all branch-free vector ops (VPU-friendly,
no loop-carried dependency — sequential Kahan would serialise under XLA):

1. **TwoSum cascade** (`sum_compensated`): log2(n) halving levels; each
   level recovers the exact rounding error of every pairwise add (Knuth
   TwoSum) into a correction stream. Total extra memory traffic ~1x input.
2. **Veltkamp split products** (`_split` / `dot_pair`): a*b is computed as
   four exactly-representable partial products (12-bit x 12-bit significand
   pieces), so dot products and |amp|^2 sums accumulate true products, not
   f32-rounded ones.
3. **Pair-return** (`*_pair` functions): the final (sum, error) pair is
   returned unadded; the API layer combines the two floats in host double
   precision, dodging the final f32 rounding (~6e-8 relative) entirely.

Measured (tools/accuracy_table.py): naive f32 totalProb at 2^20 amps is
~1e-7 off; the pair path is exact to the f32 state's true sum (<1e-15),
leaving per-gate amplitude drift as the only residual vs an f64 golden.

Under a sharded mesh everything here is elementwise + reduce, so it runs
shard-local with the last log2(n_devices) cascade levels lowering to XLA
collectives — the same psum-replaces-MPI_Allreduce story as plain sums.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["sum_compensated", "sum_pair", "dot_pair", "vdot_pair",
           "vdot_compensated"]


def _two_sum(a, b):
    """Knuth TwoSum: s = fl(a+b) and the exact rounding error e
    (a + b == s + e in exact arithmetic). Branch-free."""
    s = a + b
    b_virtual = s - a
    a_virtual = s - b_virtual
    e = (a - a_virtual) + (b - b_virtual)
    return s, e


def _split(x):
    """Veltkamp split: x == hi + lo with hi, lo each carrying at most half
    of the significand bits, so pairwise products of pieces are exact."""
    bits = 12 if x.dtype == jnp.float32 else 27
    c = x * float((1 << bits) + 1)
    hi = c - (c - x)
    return hi, x - hi


def sum_pair(x):
    """Compensated sum of a real array; returns the unadded (sum, err) pair
    so callers can combine at higher precision."""
    x = x.reshape(-1)
    err = jnp.zeros((), dtype=x.dtype)
    while x.shape[0] > 1:
        n = x.shape[0]
        if n % 2:
            x = jnp.concatenate([x, jnp.zeros((1,), dtype=x.dtype)])
        s, e = _two_sum(x[0::2], x[1::2])
        # the e's are O(eps)·|s| each; their naive sum contributes only a
        # second-order O(eps²·n) error to the final result
        err = err + jnp.sum(e)
        x = s
    return x[0], err


def sum_compensated(x) -> jnp.ndarray:
    """Compensated sum of a real 1-D array (shape static under jit)."""
    s, e = sum_pair(x)
    return s + e


def dot_pair(a, b):
    """sum(a*b) for real arrays with exact partial products: returns the
    (sum, err) pair. 4x the memory traffic of a naive dot — the price of
    error-free f32 accumulation."""
    a_hi, a_lo = _split(a)
    b_hi, b_lo = _split(b)
    streams = jnp.concatenate([
        (a_hi * b_hi).reshape(-1), (a_hi * b_lo).reshape(-1),
        (a_lo * b_hi).reshape(-1), (a_lo * b_lo).reshape(-1)])
    return sum_pair(streams)


def vdot_pair(a, b):
    """<a|b> for complex vectors; returns ((re, re_err), (im, im_err))."""
    ar, ai = jnp.real(a), jnp.imag(a)
    br, bi = jnp.real(b), jnp.imag(b)
    re_s1, re_e1 = dot_pair(ar, br)
    re_s2, re_e2 = dot_pair(ai, bi)
    im_s1, im_e1 = dot_pair(ar, bi)
    im_s2, im_e2 = dot_pair(ai, br)
    re, re_c = _two_sum(re_s1, re_s2)
    im, im_c = _two_sum(im_s1, -im_s2)
    return (re, re_c + re_e1 + re_e2), (im, im_c + im_e1 - im_e2)


def vdot_compensated(a, b) -> jnp.ndarray:
    """<a|b> with compensated accumulation, collapsed back to the input
    dtype (jit-internal use; the pair API is the full-accuracy path)."""
    (re, re_e), (im, im_e) = vdot_pair(a, b)
    return jnp.asarray((re + re_e) + 1j * (im + im_e), dtype=a.dtype)
