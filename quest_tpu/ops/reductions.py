"""Compensated reductions for single-precision accuracy parity.

The reference leans on Kahan summation for its distributed probability
reductions (``statevec_calcTotalProb``, ``QuEST_cpu_distributed.c:87-109``)
and offers a quad-precision build when double isn't enough
(``QuEST_precision.h:28-65``). On TPU, float64 is unavailable in hardware,
so single-precision registers need error-compensated reductions to approach
the reference's 1e-10-class accuracy for scalar results.

Three error-free transformations, all branch-free vector ops (VPU-friendly,
no loop-carried dependency — sequential Kahan would serialise under XLA):

1. **TwoSum cascade** (`sum_compensated`): log2(n) halving levels; each
   level recovers the exact rounding error of every pairwise add (Knuth
   TwoSum) into a correction stream. Total extra memory traffic ~1x input.
2. **Veltkamp split products** (`_split` / `dot_pair`): a*b is computed as
   four exactly-representable partial products (12-bit x 12-bit significand
   pieces), so dot products and |amp|^2 sums accumulate true products, not
   f32-rounded ones.
3. **Pair-return** (`*_pair` functions): the final (sum, error) pair is
   returned unadded; the API layer combines the two floats in host double
   precision, dodging the final f32 rounding (~6e-8 relative) entirely.

Measured (tools/accuracy_table.py): naive f32 totalProb at 2^20 amps is
~1e-7 off; the pair path is exact to the f32 state's true sum (<1e-15),
leaving per-gate amplitude drift as the only residual vs an f64 golden.

Under a sharded mesh everything here is elementwise + reduce, so it runs
shard-local with the last log2(n_devices) cascade levels lowering to XLA
collectives — the same psum-replaces-MPI_Allreduce story as plain sums.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

__all__ = ["sum_compensated", "sum_pair", "dot_pair", "vdot_pair",
           "vdot_compensated", "pauli_masks", "pauli_term_bucket",
           "pauli_sum_operands", "pauli_sum_expvals_sv",
           "pauli_sum_expvals_dm", "pauli_sum_total_sv",
           "pauli_sum_total_dm", "pauli_apply_sv", "pauli_sum_apply_sv",
           "welford_wave", "welford_merge",
           "welford_stderr", "score_surrogate"]


def _two_sum(a, b):
    """Knuth TwoSum: s = fl(a+b) and the exact rounding error e
    (a + b == s + e in exact arithmetic). Branch-free."""
    s = a + b
    b_virtual = s - a
    a_virtual = s - b_virtual
    e = (a - a_virtual) + (b - b_virtual)
    return s, e


def _split(x):
    """Veltkamp split: x == hi + lo with hi, lo each carrying at most half
    of the significand bits, so pairwise products of pieces are exact."""
    bits = 12 if x.dtype == jnp.float32 else 27
    c = x * float((1 << bits) + 1)
    hi = c - (c - x)
    return hi, x - hi


def sum_pair(x):
    """Compensated sum of a real array; returns the unadded (sum, err) pair
    so callers can combine at higher precision."""
    x = x.reshape(-1)
    err = jnp.zeros((), dtype=x.dtype)
    while x.shape[0] > 1:
        n = x.shape[0]
        if n % 2:
            x = jnp.concatenate([x, jnp.zeros((1,), dtype=x.dtype)])
        s, e = _two_sum(x[0::2], x[1::2])
        # the e's are O(eps)·|s| each; their naive sum contributes only a
        # second-order O(eps²·n) error to the final result
        err = err + jnp.sum(e)
        x = s
    return x[0], err


def sum_compensated(x) -> jnp.ndarray:
    """Compensated sum of a real 1-D array (shape static under jit)."""
    s, e = sum_pair(x)
    return s + e


def dot_pair(a, b):
    """sum(a*b) for real arrays with exact partial products: returns the
    (sum, err) pair. 4x the memory traffic of a naive dot — the price of
    error-free f32 accumulation."""
    a_hi, a_lo = _split(a)
    b_hi, b_lo = _split(b)
    streams = jnp.concatenate([
        (a_hi * b_hi).reshape(-1), (a_hi * b_lo).reshape(-1),
        (a_lo * b_hi).reshape(-1), (a_lo * b_lo).reshape(-1)])
    return sum_pair(streams)


def vdot_pair(a, b):
    """<a|b> for complex vectors; returns ((re, re_err), (im, im_err))."""
    ar, ai = jnp.real(a), jnp.imag(a)
    br, bi = jnp.real(b), jnp.imag(b)
    re_s1, re_e1 = dot_pair(ar, br)
    re_s2, re_e2 = dot_pair(ai, bi)
    im_s1, im_e1 = dot_pair(ar, bi)
    im_s2, im_e2 = dot_pair(ai, br)
    re, re_c = _two_sum(re_s1, re_s2)
    im, im_c = _two_sum(im_s1, -im_s2)
    return (re, re_c + re_e1 + re_e2), (im, im_c + im_e1 - im_e2)


def vdot_compensated(a, b) -> jnp.ndarray:
    """<a|b> with compensated accumulation, collapsed back to the input
    dtype (jit-internal use; the pair API is the full-accuracy path)."""
    (re, re_e), (im, im_e) = vdot_pair(a, b)
    return jnp.asarray((re + re_e) + 1j * (im + im_e), dtype=a.dtype)


# ---------------------------------------------------------------------------
# term-batched Pauli-sum reduction (device-resident observables)
# ---------------------------------------------------------------------------
#
# A Pauli string P = prod_q sigma_{c_q} is fully described by three bit
# masks (x, y, z); its action on a basis state is
#
#     P |k> = i^|y| (-1)^{popcount(k & (y|z))} |k ^ (x|y)>,
#
# so <psi|P|psi> is ONE xor-gather + sign-flip + reduce pass — no gate
# applications, no per-term workspace state. The masks are plain integer
# DATA, not static trace arguments: one compiled executable serves every
# Hamiltonian of a given (bucketed) term count, the term loop is a
# ``lax.map`` (sequential scan, no unrolled trace, compile time O(1) in
# the term count), and the whole sum leaves the device as a single
# scalar. This is what lets ``calcExpecPauliSum`` and
# ``CompiledCircuit.expectation_sweep`` evaluate a 100-term Hamiltonian
# over a 64-point sweep with ONE device->host transfer, where the
# reference pays one workspace round-trip per term per point
# (``QuEST_common.c:464-491``).


def pauli_masks(codes_flat, num_qubits: int):
    """Flat pauli codes (term-major, code of qubit q of term t at
    ``codes_flat[t*n + q]``; 0=I 1=X 2=Y 3=Z) -> (xmask, ymask, zmask)
    int64 arrays of shape ``(num_terms,)``. Host-side."""
    codes = np.asarray(codes_flat, dtype=np.int64).reshape(-1, num_qubits)
    bits = np.int64(1) << np.arange(num_qubits, dtype=np.int64)
    return ((codes == 1) @ bits, (codes == 2) @ bits, (codes == 3) @ bits)


def pauli_term_bucket(num_terms: int) -> int:
    """Static term-count bucket: next power of two at or above (floor 8).
    Term masks are data, so the only recompile key left is the mask
    array's SHAPE — bucketing it means one executable per power-of-two
    band of Hamiltonian sizes. Padding terms are all-identity with
    coefficient zero (their expectation, the state norm, is multiplied
    away exactly)."""
    b = 8
    while b < num_terms:
        b <<= 1
    return b


def pauli_sum_operands(codes_flat, num_qubits: int, coeffs):
    """The full device-operand set for a Pauli-sum reduction: masks from
    :func:`pauli_masks`, term count padded to :func:`pauli_term_bucket`
    with zero-coefficient identity terms. ONE encoder for every consumer
    (``calcExpecPauliSum``, ``CompiledCircuit.expectation_sweep``), so
    the mask convention cannot desynchronise between call sites.
    Returns ``(xmask, ymask, zmask, coeffs)`` numpy arrays of the
    bucketed length."""
    xm, ym, zm = pauli_masks(codes_flat, num_qubits)
    num_terms = xm.shape[0]
    bucket = pauli_term_bucket(num_terms)
    coeffs = np.pad(np.asarray(coeffs, dtype=np.float64)[:num_terms],
                    (0, bucket - num_terms))
    if bucket > num_terms:
        xm, ym, zm = (np.pad(m, (0, bucket - num_terms))
                      for m in (xm, ym, zm))
    return xm, ym, zm, coeffs


def _phase_weight(ymask, dtype):
    """(re, im) of i^popcount(y) — the Pauli string's global unit."""
    ph = lax.population_count(ymask) % 4
    wr = jnp.asarray([1.0, 0.0, -1.0, 0.0], dtype)[ph]
    wi = jnp.asarray([0.0, 1.0, 0.0, -1.0], dtype)[ph]
    return wr, wi


def pauli_sum_expvals_sv(z, xmask, ymask, zmask, compensated: bool = False):
    """Per-term <z|P_t|z> for a flat complex statevector ``z`` and mask
    arrays of shape ``(T,)``. Returns a real ``(T,)`` vector; traceable,
    masks are data. Each term is one xor-gather pass over the state.

    ``compensated=True`` accumulates each term through the
    Veltkamp-split/TwoSum pair machinery (:func:`dot_pair`) instead of a
    naive f32 reduce — the SINGLE-compensated precision tier's
    observable path (~4x the memory traffic per term; exact to the f32
    state's true sum, docs/accuracy.md §1). The FAST tier takes the
    naive branch: its budget already absorbs the ~1e-7 reduction error."""
    idx = jnp.arange(z.shape[0])
    rdtype = jnp.real(z).dtype
    zr, zi = jnp.real(z), jnp.imag(z)

    def one(masks):
        xm, ym, zm = (m.astype(idx.dtype) for m in masks)
        j = idx ^ (xm | ym)
        sign = (1 - 2 * (lax.population_count(j & (ym | zm)) & 1)
                ).astype(rdtype)
        if compensated:
            # acc = sum(conj(z) * z[j] * sign), each real dot error-free
            zjr, zji = zr[j] * sign, zi[j] * sign
            re_s1, re_e1 = dot_pair(zr, zjr)
            re_s2, re_e2 = dot_pair(zi, zji)
            im_s1, im_e1 = dot_pair(zr, zji)
            im_s2, im_e2 = dot_pair(zi, zjr)
            acc_re = (re_s1 + re_s2) + (re_e1 + re_e2)
            acc_im = (im_s1 - im_s2) + (im_e1 - im_e2)
        else:
            acc = jnp.sum(jnp.conj(z) * z[j] * sign)
            acc_re, acc_im = jnp.real(acc), jnp.imag(acc)
        wr, wi = _phase_weight(ym, rdtype)
        return wr * acc_re - wi * acc_im

    return lax.map(one, (xmask, ymask, zmask))


def pauli_sum_expvals_dm(flat, num_qubits: int, xmask, ymask, zmask,
                         compensated: bool = False):
    """Per-term Tr(P_t rho) for a flat density vector
    (``flat[r + c*2^n]``, columns on the high bits). Each term reads only
    the ``2^n`` entries ``rho[r^m, r]`` — a diagonal-sized gather, NOT a
    full ``2^(2n)`` pass (the round-2 path applied P as gates to the
    whole flat vector per term). ``compensated=True`` runs the
    diagonal-sized sum through the TwoSum cascade (:func:`sum_pair`;
    the SINGLE-compensated tier — no split products needed: the gather
    entries are used unmultiplied)."""
    dim = 1 << num_qubits
    mat = flat.reshape(dim, dim)      # mat[c, r] = rho[r, c]
    rows = jnp.arange(dim)
    rdtype = jnp.real(flat).dtype

    def one(masks):
        xm, ym, zm = (m.astype(rows.dtype) for m in masks)
        j = rows ^ (xm | ym)          # r ^ m: the paired row index
        sign = (1 - 2 * (lax.population_count(j & (ym | zm)) & 1)
                ).astype(rdtype)
        picked = mat[rows, j] * sign          # sum_r rho[r^m, r] * sign
        if compensated:
            re_s, re_e = sum_pair(jnp.real(picked))
            im_s, im_e = sum_pair(jnp.imag(picked))
            acc_re, acc_im = re_s + re_e, im_s + im_e
        else:
            acc = jnp.sum(picked)
            acc_re, acc_im = jnp.real(acc), jnp.imag(acc)
        wr, wi = _phase_weight(ym, rdtype)
        return wr * acc_re - wi * acc_im

    return lax.map(one, (xmask, ymask, zmask))


def pauli_apply_sv(z, xmask, ymask, zmask):
    """``P|z>`` for ONE Pauli string given as scalar bit masks: the same
    xor-gather + sign + ``i^|y|`` convention as
    :func:`pauli_sum_expvals_sv` (one definition of the mask action —
    the expectation of the applied state reproduces the reduction's
    value bit for bit), but returning the full transformed statevector
    instead of the scalar. One gather pass, no per-qubit gate loop —
    the Trotter-step kernel (:mod:`quest_tpu.ops.dynamics`) composes
    ``exp(-i theta P)`` from this plus the identity. Masks are DATA
    (traced scalars), so one compiled step serves every Hamiltonian of
    a given term bucket."""
    idx = jnp.arange(z.shape[0])
    rdtype = jnp.real(z).dtype
    xm, ym, zm = (jnp.asarray(m).astype(idx.dtype)
                  for m in (xmask, ymask, zmask))
    j = idx ^ (xm | ym)
    # (P z)[k] = i^|y| (-1)^{popcount(j & (y|z))} z[j] with
    # j = k ^ (x|y) — the source basis state carries the Z/Y parity,
    # the same ``j``-side popcount the expvals kernel takes, so
    # <z|pauli_apply_sv(z)> == pauli_sum_expvals_sv bit for bit
    sign = (1 - 2 * (lax.population_count(j & (ym | zm)) & 1)
            ).astype(rdtype)
    wr, wi = _phase_weight(ym, rdtype)
    return z[j] * sign * lax.complex(wr, wi).astype(z.dtype)


def pauli_sum_apply_sv(z, xmask, ymask, zmask, coeffs):
    """``H|z> = sum_t coeffs[t] * P_t|z>`` — one xor-gather pass per
    term through a ``lax.scan`` accumulator (sequential, compile time
    O(1) in the term count; masks are data). The Lanczos ground-state
    kernel's matrix-vector product."""

    def body(acc, operands):
        xm, ym, zm, c = operands
        return acc + c.astype(jnp.real(z).dtype) * pauli_apply_sv(
            z, xm, ym, zm), None

    init = jnp.zeros_like(z)
    acc, _ = lax.scan(body, init,
                      (jnp.asarray(xmask), jnp.asarray(ymask),
                       jnp.asarray(zmask), jnp.asarray(coeffs)))
    return acc


def pauli_sum_total_sv(z, xmask, ymask, zmask, coeffs,
                       compensated: bool = False):
    """sum_t coeffs[t] * <z|P_t|z> (real scalar, device-resident)."""
    vals = pauli_sum_expvals_sv(z, xmask, ymask, zmask,
                                compensated=compensated)
    return jnp.sum(vals.astype(coeffs.dtype) * coeffs)


def pauli_sum_total_dm(flat, num_qubits: int, xmask, ymask, zmask, coeffs,
                       compensated: bool = False):
    """sum_t coeffs[t] * Tr(P_t rho) (real scalar, device-resident)."""
    vals = pauli_sum_expvals_dm(flat, num_qubits, xmask, ymask, zmask,
                                compensated=compensated)
    return jnp.sum(vals.astype(coeffs.dtype) * coeffs)


# ---------------------------------------------------------------------------
# device-resident running statistics (trajectory convergence loop)
# ---------------------------------------------------------------------------
#
# The trajectory engine (ops/trajectories.py) runs stochastic ensembles
# in WAVES and stops when the standard error of the running mean fits the
# caller's sampling budget. The running (count, mean, M2) triple lives on
# the device — each wave executable folds its new per-trajectory values
# in with Chan's parallel-merge rule, so the only device->host traffic
# per wave is the 3-scalar (per row) carry the stop decision reads.
# Padded rows (device-multiple wave buckets) carry weight 0 and drop out
# of the statistics EXACTLY, not approximately.


def welford_wave(vals, weights):
    """(count, mean, M2) of one wave of per-trajectory values under a
    0/1 ``weights`` mask (padded wave rows contribute nothing). ``vals``
    may be ``(W,)``, ``(B, W)``, or ``(B, C, W)`` (the gradient wave
    loop's per-component form: C = params + 1) — always reduced over
    the last axis; weights broadcast against it."""
    w = jnp.broadcast_to(weights.astype(vals.dtype), vals.shape)
    n = jnp.sum(w, axis=-1)
    safe = jnp.maximum(n, 1.0)
    mean = jnp.sum(vals * w, axis=-1) / safe
    m2 = jnp.sum(w * (vals - mean[..., None]) ** 2, axis=-1)
    return n, mean, m2


def welford_merge(a, b):
    """Chan's parallel combine of two (count, mean, M2) triples (scalar
    or elementwise over matching shapes): exact pooled statistics, no
    pass over the underlying samples."""
    na, ma, sa = a
    nb, mb, sb = b
    n = na + nb
    safe = jnp.maximum(n, 1.0)
    delta = mb - ma
    mean = ma + delta * nb / safe
    m2 = sa + sb + delta * delta * na * nb / safe
    return n, mean, m2


def score_surrogate(value, logq, baseline=0.0):
    """The differentiation surrogate for a stochastic-trajectory
    estimator: ``value + (stop_grad(value) - stop_grad(baseline)) *
    (logq - stop_grad(logq))``.

    A trajectory's value ``v_j(theta)`` is drawn with a
    parameter-dependent measure ``p_j(theta)`` (the Kraus draw
    probabilities read the evolving state), so the pathwise derivative
    alone — ``E[dv_j]`` — misses the measure term ``sum_j v_j dp_j``
    and is a BIASED estimate of ``d/dtheta E[v]``. The surrogate's
    primal is exactly ``value`` (the added term is identically zero),
    while its gradient is the pathwise term PLUS the score-function
    (REINFORCE) correction ``v_j * d log p_j`` — together the unbiased
    total derivative, so the trajectory-gradient mean converges to the
    density-path gradient at the usual O(1/sqrt(T)). ``logq`` is the
    accumulated log-probability of every channel draw the trajectory
    took (normalised per channel).

    ``baseline`` is the standard REINFORCE variance-reduction control
    variate: any value independent of THIS draw (the gradient wave
    loop passes the running mean of earlier waves) leaves the
    expectation of the score term unchanged — ``E[b * dlogp] = b *
    d(sum_j p_j) = 0`` — while centring the ``v_j`` weights, which
    shrinks the score term's variance roughly by ``Var[v - b] /
    Var[v]``. Always wrapped in ``stop_gradient``: the baseline must
    never contribute a pathwise derivative of its own."""
    sg = lax.stop_gradient
    return value + (sg(value) - sg(baseline)) * (logq - sg(logq))


def welford_stderr(n, m2):
    """Standard error of the mean from a (count, M2) pair (inf below two
    samples — a one-draw ensemble carries no error estimate). Works on
    scalars or arrays (numpy or jnp)."""
    n = np.asarray(n, dtype=np.float64)
    m2 = np.asarray(m2, dtype=np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        se = np.sqrt(m2 / np.maximum(n - 1.0, 1e-300) / np.maximum(n, 1.0))
    return np.where(n >= 2.0, se, np.inf)
