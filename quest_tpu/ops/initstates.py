"""Device-side, sharded state initialisation.

The reference allocates and fills amplitudes *per chunk* — each MPI rank
touches only its ``2^n / numRanks`` slice (``QuEST_cpu.c:1284-1320``, init
bodies ``:1372-1597``), so host memory never holds the full register. The
TPU-native equivalent: every canned init state is a tiny jitted program with
``out_shardings`` set to the register's mesh sharding, so XLA materialises
each shard directly in its device's HBM. No O(2^n) host array exists at any
point; a 34-qubit ``initZeroState`` costs the host nothing.

Index arithmetic (the debug-state ``(2k)/10`` ramp, ``QuEST_cpu.c:1565``,
and single-qubit-outcome bit masks) is built from two int32 iotas (high/low
index halves) so no 64-bit integer index vector is ever materialised.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["blank", "zero", "plus", "classical", "debug",
           "single_qubit_outcome"]

# low-half width of the split-iota index; 2^20 lanes keeps every per-plane
# intermediate comfortably int32 while supporting registers past 2^31 amps
_LO_BITS = 20


def _split_shape(num_amps: int) -> tuple[int, int]:
    lo_bits = min(_LO_BITS, max(num_amps.bit_length() - 1, 0))
    nlo = 1 << lo_bits
    return num_amps // nlo, nlo


def _index_bit(num_amps: int, qubit: int) -> jnp.ndarray:
    """Bit ``qubit`` of each index k, shape (num_amps,), int32."""
    nhi, nlo = _split_shape(num_amps)
    lo_bits = nlo.bit_length() - 1
    if qubit < lo_bits:
        src, shift = 1, qubit
    else:
        src, shift = 0, qubit - lo_bits
    bits = (lax.broadcasted_iota(jnp.int32, (nhi, nlo), src) >> shift) & 1
    return bits


def _dd_const(x: float, dt) -> tuple[float, float]:
    from .doubledouble import _dd_scalar
    return _dd_scalar(x, dt)


@functools.lru_cache(maxsize=None)
def _compiled(kind: str, num_amps: int, real_dtype: str, sharding,
              extra: tuple = (), quad: bool = False):
    """One cached executable per (init kind, register geometry, mesh).

    ``quad=True`` builds (4, 2^n) double-double planes instead — the
    QUAD-tier register form (ops/doubledouble.py) — still device-side and
    sharded, with dd-split constants so the lo planes carry the part of
    each amplitude the hi dtype cannot."""
    dt = jnp.dtype(real_dtype)
    n_planes = 4 if quad else 2

    def build(*dyn):
        if kind == "blank":
            return jnp.zeros((n_planes, num_amps), dt)
        if kind == "zero":
            return jnp.zeros((n_planes, num_amps), dt).at[0, 0].set(1.0)
        if kind == "plus":
            if quad:
                amp_hi, amp_lo = extra
                return jnp.stack(
                    [jnp.full((num_amps,), amp_hi, dt),
                     jnp.full((num_amps,), amp_lo, dt),
                     jnp.zeros((num_amps,), dt),
                     jnp.zeros((num_amps,), dt)])
            (amp,) = extra
            re = jnp.full((num_amps,), amp, dt)
            return jnp.stack([re, jnp.zeros((num_amps,), dt)])
        if kind == "classical":
            (idx,) = dyn
            return jnp.zeros((n_planes, num_amps), dt).at[0, idx].set(1.0)
        if kind == "debug":
            # amp[k] = (2k + i(2k+1))/10 (QuEST_cpu.c:1591-1593); k is
            # recombined from the split iotas in the target float dtype
            nhi, nlo = _split_shape(num_amps)
            hi = lax.broadcasted_iota(jnp.int32, (nhi, nlo), 0).astype(dt)
            lo = lax.broadcasted_iota(jnp.int32, (nhi, nlo), 1).astype(dt)
            k = (hi * nlo + lo).reshape(num_amps)
            if quad:
                # dd: re = k * dd(0.2); im = k * dd(0.2) + dd(0.1) — the
                # constants carry the bits 1/10 loses in the hi dtype
                from .doubledouble import _dd_add, _dd_mul
                c2h, c2l, c1h, c1l = extra
                zero = jnp.zeros_like(k)
                re_h, re_l = _dd_mul(k, zero, jnp.full_like(k, c2h),
                                     jnp.full_like(k, c2l))
                im_h, im_l = _dd_add(re_h, re_l, jnp.full_like(k, c1h),
                                     jnp.full_like(k, c1l))
                return jnp.stack([re_h, re_l, im_h, im_l])
            return jnp.stack([(2.0 * k) / 10.0, (2.0 * k + 1.0) / 10.0])
        if kind == "single_qubit_outcome":
            if quad:
                qubit, outcome, amp_hi, amp_lo = extra
            else:
                qubit, outcome = extra
                amp_hi = 1.0 / np.sqrt(num_amps // 2)
            cond = _index_bit(num_amps, qubit) == outcome
            re = jnp.where(cond, amp_hi, 0.0).astype(dt).reshape(num_amps)
            if quad:
                re_l = jnp.where(cond, amp_lo,
                                 0.0).astype(dt).reshape(num_amps)
                z = jnp.zeros((num_amps,), dt)
                return jnp.stack([re, re_l, z, z])
            return jnp.stack([re, jnp.zeros((num_amps,), dt)])
        raise ValueError(kind)

    if sharding is not None:
        return jax.jit(build, out_shardings=sharding)
    return jax.jit(build)


def _dt_name(real_dtype) -> str:
    return np.dtype(real_dtype).name


def blank(num_amps, real_dtype, sharding, quad: bool = False):
    return _compiled("blank", num_amps, _dt_name(real_dtype), sharding,
                     quad=quad)()


def zero(num_amps, real_dtype, sharding, quad: bool = False):
    return _compiled("zero", num_amps, _dt_name(real_dtype), sharding,
                     quad=quad)()


def plus(num_amps, real_dtype, sharding, amp: float, quad: bool = False):
    extra = _dd_const(amp, real_dtype) if quad else (float(amp),)
    return _compiled("plus", num_amps, _dt_name(real_dtype), sharding,
                     extra, quad=quad)()


def classical(num_amps, real_dtype, sharding, index: int,
              quad: bool = False):
    idx_dt = jnp.int64 if (index > np.iinfo(np.int32).max
                           and jax.config.jax_enable_x64) else jnp.int32
    return _compiled("classical", num_amps, _dt_name(real_dtype),
                     sharding, quad=quad)(jnp.asarray(index, idx_dt))


def debug(num_amps, real_dtype, sharding, quad: bool = False):
    extra = (_dd_const(0.2, real_dtype) + _dd_const(0.1, real_dtype)) \
        if quad else ()
    return _compiled("debug", num_amps, _dt_name(real_dtype), sharding,
                     extra, quad=quad)()


def single_qubit_outcome(num_amps, real_dtype, sharding, qubit: int,
                         outcome: int, quad: bool = False):
    amp = 1.0 / np.sqrt(num_amps // 2)
    extra = (int(qubit), int(outcome)) + (_dd_const(amp, real_dtype)
                                          if quad else ())
    return _compiled("single_qubit_outcome", num_amps, _dt_name(real_dtype),
                     sharding, extra, quad=quad)()
