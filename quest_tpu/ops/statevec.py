"""Pure-functional statevector operations.

This is the TPU implementation of the reference's backend contract for
``statevec_*`` ops (``QuEST_internal.h:108-246``): every function takes a flat
amplitude array plus static qubit metadata and returns a new array. Under jit,
XLA fuses these into single memory passes; under a sharded mesh the same code
lowers to ICI collectives.

All ops are dtype-preserving and jit-compatible (static ints/tuples only).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax.numpy as jnp

from ..core.apply import apply_unitary, apply_diagonal, split_shape
from ..core import matrices as mats

__all__ = [
    "init_blank_state",
    "init_zero_state",
    "init_plus_state",
    "init_classical_state",
    "init_debug_state",
    "init_state_of_single_qubit",
    "unitary",
    "compact_unitary",
    "pauli_x",
    "pauli_y",
    "pauli_z",
    "hadamard",
    "s_gate",
    "t_gate",
    "phase_shift",
    "controlled_phase_shift",
    "multi_controlled_phase_shift",
    "controlled_phase_flip",
    "multi_controlled_phase_flip",
    "multi_rotate_z",
    "swap_amps",
    "calc_total_prob",
    "calc_inner_product",
    "calc_prob_of_outcome",
    "collapse_to_known_prob_outcome",
    "set_weighted",
    "get_amp",
]


# ---------------------------------------------------------------------------
# initialisation (QuEST_cpu.c:1372-1598)
# ---------------------------------------------------------------------------

def init_blank_state(num_qubits: int, dtype) -> jnp.ndarray:
    return jnp.zeros(1 << num_qubits, dtype=dtype)


def init_zero_state(num_qubits: int, dtype) -> jnp.ndarray:
    return init_classical_state(num_qubits, 0, dtype)


def init_plus_state(num_qubits: int, dtype) -> jnp.ndarray:
    dim = 1 << num_qubits
    amp = 1.0 / np.sqrt(dim)
    return jnp.full(dim, amp, dtype=dtype)


def init_classical_state(num_qubits: int, state_ind: int, dtype) -> jnp.ndarray:
    dim = 1 << num_qubits
    return jnp.zeros(dim, dtype=dtype).at[state_ind].set(1.0)


def init_debug_state(num_qubits: int, dtype) -> jnp.ndarray:
    """amp[i] = (2i + i(2i+1))/10 — deterministic unnormalised fixture
    (``QuEST_cpu.c:1565-1592``)."""
    dim = 1 << num_qubits
    idx = np.arange(dim, dtype=np.float64)
    re = (2.0 * idx) / 10.0
    im = (2.0 * idx + 1.0) / 10.0
    return jnp.asarray(re + 1j * im, dtype=dtype)


def init_state_of_single_qubit(num_qubits: int, qubit: int, outcome: int, dtype) -> jnp.ndarray:
    """Qubit fixed to ``outcome``; the rest in uniform superposition
    (``QuEST_cpu.c:1519``)."""
    shape = split_shape(num_qubits, (qubit,))
    norm = 1.0 / np.sqrt(1 << (num_qubits - 1))
    col = np.zeros((1, 2, 1), dtype=np.complex128)
    col[0, outcome, 0] = norm
    return jnp.broadcast_to(jnp.asarray(col, dtype=dtype), shape).reshape(-1)


# ---------------------------------------------------------------------------
# unitaries
# ---------------------------------------------------------------------------

def unitary(
    state, num_qubits: int, u, targets: Sequence[int],
    ctrl_mask: int = 0, flip_mask: int = 0,
) -> jnp.ndarray:
    """General k-qubit (multi-controlled) unitary — subsumes the reference's
    unitary/controlledUnitary/multiControlledUnitary/twoQubitUnitary/
    multiQubitUnitary kernel family."""
    return apply_unitary(state, num_qubits, u, tuple(targets), ctrl_mask, flip_mask)


def compact_unitary(state, num_qubits, alpha, beta, target, ctrl_mask=0):
    return unitary(state, num_qubits, mats.compact_unitary(alpha, beta), (target,), ctrl_mask)


def pauli_x(state, num_qubits, target, ctrl_mask=0):
    return unitary(state, num_qubits, mats.pauli_x(), (target,), ctrl_mask)


def pauli_y(state, num_qubits, target, ctrl_mask=0, conj=False):
    return unitary(state, num_qubits, mats.pauli_y(conj), (target,), ctrl_mask)


def hadamard(state, num_qubits, target):
    return unitary(state, num_qubits, mats.hadamard(), (target,))


def _diag_on(state, num_qubits, qubits, one_factors):
    """Diagonal gate: qubit ``qubits[i]``'s |1> component scaled by
    ``one_factors[i]`` multiplicatively (outer product over qubits)."""
    qs = sorted(qubits, reverse=True)
    tensor = np.ones((2,) * len(qs), dtype=np.complex128)
    for i, q in enumerate(qs):
        f = one_factors[qubits.index(q)]
        sl = [slice(None)] * len(qs)
        sl[i] = 1
        tensor[tuple(sl)] *= f
    return apply_diagonal(state, num_qubits, qs, tensor)


def pauli_z(state, num_qubits, target):
    return _diag_on(state, num_qubits, (target,), (-1.0,))


def s_gate(state, num_qubits, target, conj=False):
    return _diag_on(state, num_qubits, (target,), (-1j if conj else 1j,))


def t_gate(state, num_qubits, target, conj=False):
    ph = np.exp(-1j * np.pi / 4) if conj else np.exp(1j * np.pi / 4)
    return _diag_on(state, num_qubits, (target,), (ph,))


def phase_shift(state, num_qubits, target, angle):
    return _diag_on(state, num_qubits, (target,), (np.exp(1j * angle),))


def controlled_phase_shift(state, num_qubits, q1, q2, angle):
    return multi_controlled_phase_shift(state, num_qubits, (q1, q2), angle)


def multi_controlled_phase_shift(state, num_qubits, qubits, angle):
    """exp(i angle) phase on amplitudes where *all* listed qubits are 1
    (``QuEST_cpu.c:3025``)."""
    qs = tuple(sorted(qubits, reverse=True))
    tensor = np.ones((2,) * len(qs), dtype=np.complex128)
    tensor[(1,) * len(qs)] = np.exp(1j * angle)
    return apply_diagonal(state, num_qubits, qs, tensor)


def controlled_phase_flip(state, num_qubits, q1, q2):
    return multi_controlled_phase_flip(state, num_qubits, (q1, q2))


def multi_controlled_phase_flip(state, num_qubits, qubits):
    qs = tuple(sorted(qubits, reverse=True))
    tensor = np.ones((2,) * len(qs), dtype=np.complex128)
    tensor[(1,) * len(qs)] = -1.0
    return apply_diagonal(state, num_qubits, qs, tensor)


def multi_rotate_z_diag(k: int, angle: float) -> np.ndarray:
    """(2,)*k parity-phase tensor: even-parity bit patterns get
    exp(-i angle/2), odd get exp(+i angle/2) (``QuEST_cpu.c:3075-3114``)."""
    idx = np.arange(1 << k)
    parity = np.zeros(1 << k, dtype=np.int64)
    for b in range(k):
        parity ^= (idx >> b) & 1
    fac = np.where(parity == 0, np.exp(-0.5j * angle), np.exp(0.5j * angle))
    return fac.reshape((2,) * k)


def multi_rotate_z(state, num_qubits, qubits, angle):
    """amp *= exp(-i angle/2 * (-1)^parity(bits))."""
    qs = tuple(sorted(qubits, reverse=True))
    return apply_diagonal(state, num_qubits, qs, multi_rotate_z_diag(len(qs), angle))


def swap_amps(state, num_qubits, q1, q2):
    """SWAP via axis transpose — pure data movement, no arithmetic
    (vs ``statevec_swapQubitAmps`` ``QuEST_cpu.c:3502``)."""
    hi, lo = max(q1, q2), min(q1, q2)
    shape = split_shape(num_qubits, (hi, lo))
    return state.reshape(shape).transpose(0, 3, 2, 1, 4).reshape(-1)


# ---------------------------------------------------------------------------
# reductions & collapse (QuEST_cpu.c:3117-3494, QuEST_cpu_distributed.c:34-116)
# ---------------------------------------------------------------------------

def calc_total_prob(state) -> jnp.ndarray:
    """Sum of |amp|^2. XLA owns the reduction tree (no hand-rolled Kahan as in
    ``QuEST_cpu_distributed.c:96-109``; accumulation is float32/float64 per
    the register precision)."""
    return jnp.sum(jnp.real(state) ** 2 + jnp.imag(state) ** 2)


def calc_inner_product(bra, ket) -> jnp.ndarray:
    """<bra|ket> (conjugates bra, as ``calcInnerProductLocal``
    ``QuEST_cpu.c:1076``)."""
    return jnp.vdot(bra, ket)


def calc_prob_of_outcome(state, num_qubits: int, qubit: int, outcome: int) -> jnp.ndarray:
    shape = split_shape(num_qubits, (qubit,))
    arr = state.reshape(shape)
    sub = arr[:, 0, :] if outcome == 0 else arr[:, 1, :]
    return jnp.sum(jnp.real(sub) ** 2 + jnp.imag(sub) ** 2)


def collapse_to_known_prob_outcome(state, num_qubits, qubit, outcome, prob):
    """Zero the non-outcome half, renormalise the outcome half by 1/sqrt(prob)
    (``QuEST_cpu.c:3346-3494``). ``prob`` may be traced."""
    shape = split_shape(num_qubits, (qubit,))
    renorm = (1.0 / jnp.sqrt(prob)).astype(state.dtype)
    fac = jnp.zeros((1, 2, 1), dtype=state.dtype).at[0, outcome, 0].set(renorm)
    return (state.reshape(shape) * fac).reshape(-1)


def set_weighted(fac1, state1, fac2, state2, fac_out, out):
    """out = fac1*state1 + fac2*state2 + facOut*out (``QuEST_cpu.c:3585``)."""
    f1 = jnp.asarray(fac1, dtype=out.dtype)
    f2 = jnp.asarray(fac2, dtype=out.dtype)
    fo = jnp.asarray(fac_out, dtype=out.dtype)
    return f1 * state1 + f2 * state2 + fo * out


def get_amp(state, index):
    return state[index]
