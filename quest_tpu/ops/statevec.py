"""Pure-functional statevector operations (jit-internal backend layer).

TPU implementation of the reduction / data-movement / collapse slice of the
reference's ``statevec_*`` backend contract (``QuEST_internal.h:108-246``).
Every function takes a flat complex amplitude array plus static qubit
metadata and returns a new array; under jit XLA fuses these into single
memory passes, and under a sharded mesh the same code lowers to ICI
collectives.

Unitary/diagonal gate application does NOT live here: gates route through
the axis-contraction engine (``core/apply.py``) via the API layer and the
circuit compiler — one engine subsumes the reference's entire per-gate
kernel family (``QuEST_cpu.c:1662-3114``). State initialisation is host-side
in the API layer (``api.py:initZeroState`` etc.): inits are one-time
host→device transfers, not compiled kernels.

All ops are dtype-preserving and jit-compatible (static ints/tuples only).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.apply import split_shape

__all__ = [
    "multi_rotate_z_diag",
    "swap_amps",
    "calc_total_prob",
    "calc_inner_product",
    "calc_prob_of_outcome",
    "collapse_to_known_prob_outcome",
    "set_weighted",
]


def multi_rotate_z_diag(k: int, angle: float) -> np.ndarray:
    """(2,)*k parity-phase tensor: even-parity bit patterns get
    exp(-i angle/2), odd get exp(+i angle/2) (``QuEST_cpu.c:3075-3114``)."""
    idx = np.arange(1 << k)
    parity = np.zeros(1 << k, dtype=np.int64)
    for b in range(k):
        parity ^= (idx >> b) & 1
    fac = np.where(parity == 0, np.exp(-0.5j * angle), np.exp(0.5j * angle))
    return fac.reshape((2,) * k)


def swap_amps(state, num_qubits, q1, q2):
    """SWAP via axis transpose — pure data movement, no arithmetic
    (vs ``statevec_swapQubitAmps`` ``QuEST_cpu.c:3502``)."""
    hi, lo = max(q1, q2), min(q1, q2)
    shape = split_shape(num_qubits, (hi, lo))
    return state.reshape(shape).transpose(0, 3, 2, 1, 4).reshape(-1)


# ---------------------------------------------------------------------------
# reductions & collapse (QuEST_cpu.c:3117-3494, QuEST_cpu_distributed.c:34-116)
# ---------------------------------------------------------------------------

def calc_total_prob(state) -> jnp.ndarray:
    """Sum of |amp|^2. XLA owns the reduction tree; the error-compensated
    route (the reference's Kahan analogue,
    ``QuEST_cpu_distributed.c:96-109``) lives in ``ops.reductions`` and is
    selected by the API layer via ``env.compensated``."""
    return jnp.sum(jnp.real(state) ** 2 + jnp.imag(state) ** 2)


def calc_inner_product(bra, ket) -> jnp.ndarray:
    """<bra|ket> (conjugates bra, as ``calcInnerProductLocal``
    ``QuEST_cpu.c:1076``)."""
    return jnp.vdot(bra, ket)


def calc_prob_of_outcome(state, num_qubits: int, qubit: int, outcome: int) -> jnp.ndarray:
    """P(outcome 0) summed directly; P(outcome 1) as its complement 1-P0 —
    the reference's exact semantics (``statevec_calcProbOfOutcome``
    ``QuEST_cpu_local.c:279-285``), observable on unnormalised registers
    (debug state): summing the outcome-1 amplitudes would differ."""
    shape = split_shape(num_qubits, (qubit,))
    sub = state.reshape(shape)[:, 0, :]
    zero_prob = jnp.sum(jnp.real(sub) ** 2 + jnp.imag(sub) ** 2)
    return zero_prob if outcome == 0 else 1.0 - zero_prob


def collapse_to_known_prob_outcome(state, num_qubits, qubit, outcome, prob):
    """Zero the non-outcome half, renormalise the outcome half by 1/sqrt(prob)
    (``QuEST_cpu.c:3346-3494``). ``prob`` may be traced."""
    shape = split_shape(num_qubits, (qubit,))
    renorm = (1.0 / jnp.sqrt(prob)).astype(state.dtype)
    fac = jnp.zeros((1, 2, 1), dtype=state.dtype).at[0, outcome, 0].set(renorm)
    return (state.reshape(shape) * fac).reshape(-1)


def set_weighted(fac1, state1, fac2, state2, fac_out, out):
    """out = fac1*state1 + fac2*state2 + facOut*out (``QuEST_cpu.c:3585``)."""
    f1 = jnp.asarray(fac1, dtype=out.dtype)
    f2 = jnp.asarray(fac2, dtype=out.dtype)
    fo = jnp.asarray(fac_out, dtype=out.dtype)
    return f1 * state1 + f2 * state2 + fo * out
