"""The quantum register: one (shardable) flat jax.Array of amplitudes.

TPU-native replacement for the ``Qureg`` struct (``QuEST.h:161-192``): the
split real/imag malloc'd chunks plus ``pairStateVec`` collapse into a single
complex ``jax.Array`` that JAX shards over the environment mesh on its
leading (high-qubit) axis — the same chunkId-prefix layout as the reference's
MPI amplitude sharding, with no mirror buffer (XLA stages exchanges itself).

Density matrices reuse the statevector storage as a flat 2n-qubit vector
(``QuEST.c:8-10``); ``flat[r + c*2^n] = rho[r, c]``.

The object is a thin mutable handle (state is swapped, never mutated) so the
user-facing API can stay imperative like the reference while every kernel
underneath is pure.

Storage is a *float* array of shape ``(2, 2^N)`` — split real/imag planes,
like the reference's ``stateVec.real``/``stateVec.imag`` — because the TPU
PJRT backend forbids complex device buffers at executable boundaries (and the
split layout is the faster one regardless); see ``core/packing.py``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .core.packing import pack_host, unpack_host
from .env import QuESTEnv
from .qasm import QASMLogger

__all__ = ["Qureg"]


class Qureg:
    """A state-vector or density-matrix register bound to an environment."""

    def __init__(self, num_qubits: int, env: QuESTEnv, is_density: bool = False):
        self.env = env
        self.is_density_matrix = is_density
        self.num_qubits_represented = num_qubits
        self.num_qubits_in_state_vec = (2 * num_qubits) if is_density else num_qubits
        self.num_amps_total = 1 << self.num_qubits_in_state_vec
        self.qasm_log = QASMLogger(num_qubits)
        self._state: Optional[jax.Array] = None
        # lazy logical->physical qubit permutation over the state-vector
        # positions (None = identity). Maintained only by the sharded
        # per-gate path (parallel/pergate.py): swaps become metadata and
        # swap-to-local relayouts defer their swap-back until a reader
        # needs canonical order (ensure_canonical).
        self.layout: Optional[np.ndarray] = None
        # opt-in imperative gate fusion (api.startGateFusion): while
        # active, gate calls buffer here and flush — contracted through
        # core/fusion.py — at the first state read
        self._fusion_buffer = None

    # -- reference struct-field aliases (QuEST.h:161-192 spellings, used
    #    by the reference's own test drivers, e.g. createQureg.test) ------

    @property
    def isDensityMatrix(self) -> bool:
        return self.is_density_matrix

    @property
    def numQubitsRepresented(self) -> int:
        return self.num_qubits_represented

    @property
    def numQubitsInStateVec(self) -> int:
        return self.num_qubits_in_state_vec

    @property
    def numAmpsTotal(self) -> int:
        return self.num_amps_total

    # -- state plumbing ----------------------------------------------------

    @property
    def state(self) -> jax.Array:
        buf = self._fusion_buffer
        if buf is not None and buf.pending and not buf.flushing:
            buf.flush()     # every reader sees buffered gates applied
        return self._state

    @state.setter
    def state(self, new_state: jax.Array) -> None:
        buf = self._fusion_buffer
        if buf is not None and buf.pending and not buf.flushing:
            # a full overwrite supersedes pending gates (writers that
            # read-modify-write flushed at the read; the flush's own
            # writes are fenced by buf.flushing)
            buf.discard()
        self._state = new_state

    def flush_gates(self) -> None:
        """Apply any gates buffered by the opt-in imperative fusion path
        (``api.startGateFusion``). No-op otherwise."""
        buf = self._fusion_buffer
        if buf is not None:
            buf.flush()

    @property
    def dtype(self):
        """Logical (complex) dtype of the amplitudes."""
        return self.env.precision.complex_dtype

    @property
    def real_dtype(self):
        """Storage dtype of the split re/im planes."""
        return self.env.precision.real_dtype

    @property
    def is_quad(self) -> bool:
        """True for QUAD registers: (4, 2^N) double-double planes
        (``ops/doubledouble.py``), the QuEST_PREC=4 analogue."""
        return self.env.precision.quest_prec == 4

    def sharding(self):
        """Amplitude sharding for this register: the env mesh sharding, or
        None when the register has fewer amplitudes than the mesh has devices
        (a 1-qubit density register on an 8-device env stays replicated —
        the analogue of the reference's numRanks <= 2^n requirement,
        ``QuEST_cpu.c:1287``, relaxed to a fallback instead of an error)."""
        if self.num_amps_total < self.env.num_devices:
            return None
        return self.env.sharding()

    def sharding_flat(self):
        """Same decision for the flat (2^N,) jit-internal complex form."""
        if self.num_amps_total < self.env.num_devices:
            return None
        return self.env.sharding_flat()

    def device_put(self, host_array: np.ndarray) -> None:
        """Place a host complex array as the register state (packed to float
        planes), sharded over the mesh."""
        host_array = np.asarray(host_array)
        if host_array.shape != (self.num_amps_total,):
            raise ValueError(
                f"state array has shape {host_array.shape}; this register "
                f"holds {self.num_amps_total} amplitudes")
        buf = self._fusion_buffer
        if buf is not None and buf.pending and not buf.flushing:
            buf.discard()        # overwrite supersedes buffered gates,
        self.layout = None       # exactly like the state setter
        # full overwrite in canonical order
        if self.is_quad:
            from .ops.doubledouble import _dd_split_host
            arr = _dd_split_host(host_array, self.real_dtype)
        else:
            arr = pack_host(host_array, self.real_dtype)
        sharding = self.sharding()
        if sharding is not None and self.env.is_multihost:
            # multi-host: each process materialises only ITS addressable
            # shards from the (replicated) host array — the analogue of the
            # reference's per-rank chunk fill (QuEST_cpu.c:1284-1320); a
            # plain device_put of a global array is invalid across hosts
            self._state = jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])
            return
        arr = jnp.asarray(arr)
        self._state = jax.device_put(arr, sharding) if sharding is not None else arr

    # -- convenience mirrors of the reference struct fields ---------------

    @property
    def num_amps_per_chunk(self) -> int:
        return self.num_amps_total // self.env.num_devices

    @property
    def num_chunks(self) -> int:
        return self.env.num_devices

    def ensure_canonical(self) -> None:
        """Restore the identity qubit layout (one batched exchange) so the
        raw state array can be read positionally. No-op off the sharded
        per-gate path. Drains the imperative fusion buffer first, so a
        compiled run or host read never races buffered gates."""
        self.flush_gates()
        if self.layout is not None:
            from .parallel.pergate import canonicalise
            canonicalise(self)

    def to_numpy(self) -> np.ndarray:
        """Gather the FULL state to host as a complex vector — debug/test
        seam ONLY: this is O(2^n) host memory and tunnel bandwidth. Use
        ``getAmp``/``getProbAmp`` (shard-local single-element reads) or
        ``calc*`` reductions in real programs. Transfers the float planes
        (complex transfers are unsupported on the TPU backend) and
        recombines host-side. Multi-host: shards on other processes are
        not addressable, so the state is allgathered first (every process
        must call this collectively, as with the reference's
        ``copyVecIntoMatrixPairState`` replication)."""
        self.ensure_canonical()
        if self.env.is_multihost and self.sharding() is not None:
            # replicated (unsharded) registers are already host-local;
            # only sharded states need the cross-process gather
            from jax.experimental import multihost_utils
            gathered = multihost_utils.process_allgather(self._state,
                                                         tiled=True)
            host = np.asarray(gathered)
        else:
            host = np.asarray(self._state)
        if self.is_quad:
            from .ops.doubledouble import dd_unpack
            return dd_unpack(host)
        return unpack_host(host)

    def density_matrix_numpy(self) -> np.ndarray:
        """rho[r, c] view of a density register (host-side)."""
        dim = 1 << self.num_qubits_represented
        return self.to_numpy().reshape(dim, dim).T

    def __repr__(self) -> str:
        kind = "density-matrix" if self.is_density_matrix else "state-vector"
        return (f"Qureg({kind}, qubits={self.num_qubits_represented}, "
                f"amps={self.num_amps_total}, dtype={self.dtype}, "
                f"devices={self.env.num_devices})")
