// Native CPU statevector executor: one call runs a whole gate program.
//
// This is the framework's CPU analogue of the reference's native CPU backend
// (QuEST_cpu.c's per-gate OpenMP kernels, QuEST_cpu_local.c's dispatch) with
// a different architecture: instead of ~30 hand-written per-gate functions
// dispatched one library call at a time, the Python layer lowers a recorded
// circuit to a flat descriptor program (kind / targets / control masks /
// matrix table) and this executor streams the state through every op in a
// single foreign call — no per-gate binding overhead, and the instruction
// set is just two ops (dense k-qubit unitary, diagonal factor table) because
// every gate in the API lowers to one of them.
//
// Layout: split re/im planes (two contiguous f64 arrays), bit q of the
// amplitude index = computational value of qubit q — the same indexing the
// JAX engine uses (core/apply.py), so states move between the two executors
// with a reshape, not a permutation.
//
// Parallelism: optional std::thread fork/join over contiguous index ranges
// (threads=1 reproduces the serial reference build's conditions exactly).
// Each task owns a disjoint slice of the iteration space, so there are no
// races by construction.

#include <cstdint>
#include <thread>
#include <vector>

namespace {

constexpr int kMaxDenseQubits = 8;   // 2^8 amps gathered per task, on stack

struct DenseOp {
  int k;
  int64_t ctrl_mask, ctrl_want;     // want = mask & ~flip
  int64_t offsets[1 << kMaxDenseQubits];  // index offset of each gate row
  const double* mat;                // interleaved re,im, row-major 2^k x 2^k
};

// Enumerate indices with zero bits at the (ascending) target positions:
// expand j by inserting a 0 bit at each position.
inline int64_t expand_index(int64_t j, const int* pos_asc, int k) {
  int64_t idx = j;
  for (int i = 0; i < k; ++i) {
    const int64_t low = idx & ((int64_t(1) << pos_asc[i]) - 1);
    idx = ((idx >> pos_asc[i]) << (pos_asc[i] + 1)) | low;
  }
  return idx;
}

void dense_range(double* re, double* im, const DenseOp& op,
                 const int* pos_asc, int64_t j_lo, int64_t j_hi) {
  const int K = 1 << op.k;
  double ar[1 << kMaxDenseQubits], ai[1 << kMaxDenseQubits];
  for (int64_t j = j_lo; j < j_hi; ++j) {
    const int64_t base = expand_index(j, pos_asc, op.k);
    if ((base & op.ctrl_mask) != op.ctrl_want) continue;
    for (int m = 0; m < K; ++m) {
      const int64_t idx = base | op.offsets[m];
      ar[m] = re[idx];
      ai[m] = im[idx];
    }
    for (int m2 = 0; m2 < K; ++m2) {
      double sr = 0.0, si = 0.0;
      const double* row = op.mat + 2 * int64_t(m2) * K;
      for (int m = 0; m < K; ++m) {
        const double ur = row[2 * m], ui = row[2 * m + 1];
        sr += ur * ar[m] - ui * ai[m];
        si += ur * ai[m] + ui * ar[m];
      }
      const int64_t idx = base | op.offsets[m2];
      re[idx] = sr;
      im[idx] = si;
    }
  }
}

// 1-qubit fast path: the whole simulator's hot loop. Pair (i, i+2^q),
// iterated as j over 2^(n-1). Runs of j sharing the same high bits give
// CONTIGUOUS i0/i1 ranges, so the inner loop is written over those runs
// with restrict-qualified pointers — the compiler auto-vectorizes it
// (AVX-512 on this host), which the old computed-index single loop
// defeated.
void dense1_range(double* __restrict re, double* __restrict im,
                  const DenseOp& op, int target,
                  int64_t j_lo, int64_t j_hi) {
  const int64_t stride = int64_t(1) << target;
  const int64_t lo_mask = stride - 1;
  const double u00r = op.mat[0], u00i = op.mat[1];
  const double u01r = op.mat[2], u01i = op.mat[3];
  const double u10r = op.mat[4], u10i = op.mat[5];
  const double u11r = op.mat[6], u11i = op.mat[7];
  const bool ctrl = op.ctrl_mask != 0;
  int64_t j = j_lo;
  while (j < j_hi) {
    const int64_t t0 = j & lo_mask;
    int64_t run = stride - t0;
    if (run > j_hi - j) run = j_hi - j;
    const int64_t i0base = ((j & ~lo_mask) << 1) | t0;
    double* __restrict re0 = re + i0base;
    double* __restrict im0 = im + i0base;
    double* __restrict re1 = re + (i0base | stride);
    double* __restrict im1 = im + (i0base | stride);
    if (!ctrl) {
      for (int64_t t = 0; t < run; ++t) {
        const double xr = re0[t], xi = im0[t];
        const double yr = re1[t], yi = im1[t];
        re0[t] = u00r * xr - u00i * xi + u01r * yr - u01i * yi;
        im0[t] = u00r * xi + u00i * xr + u01r * yi + u01i * yr;
        re1[t] = u10r * xr - u10i * xi + u11r * yr - u11i * yi;
        im1[t] = u10r * xi + u10i * xr + u11r * yi + u11i * yr;
      }
    } else {
      for (int64_t t = 0; t < run; ++t) {
        if (((i0base + t) & op.ctrl_mask) != op.ctrl_want) continue;
        const double xr = re0[t], xi = im0[t];
        const double yr = re1[t], yi = im1[t];
        re0[t] = u00r * xr - u00i * xi + u01r * yr - u01i * yi;
        im0[t] = u00r * xi + u00i * xr + u01r * yi + u01i * yr;
        re1[t] = u10r * xr - u10i * xi + u11r * yr - u11i * yi;
        im1[t] = u10r * xi + u10i * xr + u11r * yi + u11i * yr;
      }
    }
    j += run;
  }
}

// 2-qubit fast path (t1 < t2, uncontrolled-or-controlled): every density
// register's 1q gate lowers to a fused 2q superoperator, so this loop is
// the density path's hot kernel. Runs of consecutive base indices below
// t1 give four CONTIGUOUS amplitude streams; the 4x4 combine over them
// auto-vectorizes like dense1_range.
void dense2_range(double* __restrict re, double* __restrict im,
                  const DenseOp& op, int t1, int t2,
                  int64_t j_lo, int64_t j_hi) {
  const int64_t s1 = int64_t(1) << t1, s2 = int64_t(1) << t2;
  const int64_t lo_mask = s1 - 1;
  const int64_t mid_mask = ((s2 >> 1) - 1) & ~lo_mask;
  double ur[16], ui[16];
  for (int m = 0; m < 16; ++m) {
    ur[m] = op.mat[2 * m];
    ui[m] = op.mat[2 * m + 1];
  }
  const bool ctrl = op.ctrl_mask != 0;
  int64_t j = j_lo;
  while (j < j_hi) {
    const int64_t t0 = j & lo_mask;
    int64_t run = s1 - t0;
    if (run > j_hi - j) run = j_hi - j;
    // expand j (bits below t1 | bits t1..t2-2 | rest) into the base index
    const int64_t mid = j & mid_mask;
    const int64_t hi = j & ~(mid_mask | lo_mask);
    const int64_t base = (hi << 2) | (mid << 1) | t0;
    double* __restrict p[4][2];
    for (int m = 0; m < 4; ++m) {
      const int64_t off = op.offsets[m];
      p[m][0] = re + base + off;
      p[m][1] = im + base + off;
    }
    for (int64_t t = 0; t < run; ++t) {
      if (ctrl && ((base + t) & op.ctrl_mask) != op.ctrl_want) continue;
      double ar[4], ai[4];
      for (int m = 0; m < 4; ++m) {
        ar[m] = p[m][0][t];
        ai[m] = p[m][1][t];
      }
      for (int m2 = 0; m2 < 4; ++m2) {
        double sr = 0.0, si = 0.0;
        for (int m = 0; m < 4; ++m) {
          sr += ur[4 * m2 + m] * ar[m] - ui[4 * m2 + m] * ai[m];
          si += ur[4 * m2 + m] * ai[m] + ui[4 * m2 + m] * ar[m];
        }
        p[m2][0][t] = sr;
        p[m2][1][t] = si;
      }
    }
    j += run;
  }
}

struct DiagOp {
  int k;
  int64_t ctrl_mask, ctrl_want;
  int targets[16];                  // diag supports up to 16 qubits
  const double* table;              // interleaved re,im, 2^k entries
};

void diag_range(double* __restrict re, double* __restrict im,
                const DiagOp& op, int64_t i_lo, int64_t i_hi) {
  // All indices sharing the bits above the LOWEST target/control bit see
  // the same table entry and control verdict, so the multiply runs over
  // contiguous blocks with a constant factor — auto-vectorizable (the
  // per-element bit-gather of the old loop was not).
  int64_t relevant = op.ctrl_mask;
  for (int b = 0; b < op.k; ++b) relevant |= int64_t(1) << op.targets[b];
  const int min_bit = relevant ? __builtin_ctzll(uint64_t(relevant)) : 62;
  const int64_t blk = int64_t(1) << min_bit;
  int64_t i = i_lo;
  while (i < i_hi) {
    const int64_t off = i & (blk - 1);
    int64_t run = blk - off;
    if (run > i_hi - i) run = i_hi - i;
    if ((i & op.ctrl_mask) == op.ctrl_want) {
      int m = 0;
      for (int b = 0; b < op.k; ++b)
        m |= int((i >> op.targets[b]) & 1) << b;
      const double dr = op.table[2 * m], di = op.table[2 * m + 1];
      double* __restrict r = re + i;
      double* __restrict x = im + i;
      for (int64_t t = 0; t < run; ++t) {
        const double xr = r[t], xi = x[t];
        r[t] = dr * xr - di * xi;
        x[t] = dr * xi + di * xr;
      }
    }
    i += run;
  }
}

template <typename Fn>
void parallel_for(int64_t n, int threads, Fn&& body) {
  if (threads <= 1 || n < (int64_t(1) << 16)) {
    body(0, n);
    return;
  }
  std::vector<std::thread> pool;
  const int64_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * chunk;
    const int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    pool.emplace_back([&body, lo, hi] { body(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Run a lowered gate program in place. Returns 0 on success, negative on a
// malformed descriptor. Arrays:
//   kinds[i]        0 = dense unitary, 1 = diagonal table
//   ks[i]           number of target qubits
//   ctrl_masks[i]   OR of 1<<q over control qubits (0 = uncontrolled)
//   flip_masks[i]   controls conditioning on |0> instead of |1>
//   t_off[i]        offset of this op's targets in targets_flat
//   m_off[i]        offset (in doubles) of this op's matrix/table in mats
// Matrix convention: bit j of a dense matrix index addresses
// targets_flat[t_off+j] (ComplexMatrixN bit order); diagonal tables use the
// same bit order.
int qtk_run_f64(double* re, double* im, int n_qubits, int n_ops,
                const int32_t* kinds, const int32_t* ks,
                const int64_t* ctrl_masks, const int64_t* flip_masks,
                const int32_t* t_off, const int32_t* targets_flat,
                const int64_t* m_off, const double* mats, int threads) {
  if (n_qubits < 1 || n_qubits > 62) return -1;
  const int64_t size = int64_t(1) << n_qubits;
  for (int i = 0; i < n_ops; ++i) {
    const int k = ks[i];
    const int32_t* targets = targets_flat + t_off[i];
    if (kinds[i] == 0) {
      if (k < 1 || k > kMaxDenseQubits) return -2;
      DenseOp op;
      op.k = k;
      op.ctrl_mask = ctrl_masks[i];
      op.ctrl_want = ctrl_masks[i] & ~flip_masks[i];
      op.mat = mats + m_off[i];
      for (int m = 0; m < (1 << k); ++m) {
        int64_t off = 0;
        for (int j = 0; j < k; ++j)
          if ((m >> j) & 1) off |= int64_t(1) << targets[j];
        op.offsets[m] = off;
      }
      if (k == 1) {
        const int target = targets[0];
        parallel_for(size >> 1, threads, [&](int64_t lo, int64_t hi) {
          dense1_range(re, im, op, target, lo, hi);
        });
      } else if (k == 2) {
        const int t1 = targets[0] < targets[1] ? targets[0] : targets[1];
        const int t2 = targets[0] < targets[1] ? targets[1] : targets[0];
        parallel_for(size >> 2, threads, [&](int64_t lo, int64_t hi) {
          dense2_range(re, im, op, t1, t2, lo, hi);
        });
      } else {
        int pos_asc[kMaxDenseQubits];
        for (int j = 0; j < k; ++j) pos_asc[j] = targets[j];
        for (int a = 1; a < k; ++a)  // insertion sort (k <= 8)
          for (int b = a; b > 0 && pos_asc[b] < pos_asc[b - 1]; --b) {
            const int tmp = pos_asc[b];
            pos_asc[b] = pos_asc[b - 1];
            pos_asc[b - 1] = tmp;
          }
        parallel_for(size >> k, threads, [&](int64_t lo, int64_t hi) {
          dense_range(re, im, op, pos_asc, lo, hi);
        });
      }
    } else if (kinds[i] == 1) {
      if (k < 0 || k > 16) return -3;
      DiagOp op;
      op.k = k;
      op.ctrl_mask = ctrl_masks[i];
      op.ctrl_want = ctrl_masks[i] & ~flip_masks[i];
      op.table = mats + m_off[i];
      for (int j = 0; j < k; ++j) op.targets[j] = targets[j];
      parallel_for(size, threads, [&](int64_t lo, int64_t hi) {
        diag_range(re, im, op, lo, hi);
      });
    } else {
      return -4;
    }
  }
  return 0;
}

}  // extern "C"
