// libquest_tpu.so — C-ABI shim over the quest_tpu Python framework.
//
// Embeds CPython: every C call marshals into the corresponding
// quest_tpu.api function (include/QuEST.h documents the covered
// surface). Registers are Python objects behind integer handles; the
// C-side Qureg/QuESTEnv structs carry only the handle plus the
// introspection fields user code reads directly.
//
// Error contract: a Python-side QuESTError prints the reference-style
// message and exits(1) — the reference's default fatal
// invalidQuESTInputError behavior (QuEST_validation.c:126-137).
//
// Build: native/Makefile target `cshim` (links libpython).

#include <Python.h>

#include <dlfcn.h>

#include <cstdio>
#include <cstdlib>
#include <map>
#include <string>

#include "../../include/QuEST.h"

namespace {

PyObject *g_qt = nullptr;                 // quest_tpu module
std::map<int, PyObject *> g_objects;      // handle -> env/qureg
int g_next_handle = 1;
PyObject *g_first_env = nullptr;          // for implicit-env C calls

void fatal_py(const char *where) {
    std::fprintf(stderr, "QuEST-TPU shim error in %s:\n", where);
    PyErr_Print();
    std::exit(1);
}

void ensure_python() {
    if (g_qt != nullptr) return;
    if (!Py_IsInitialized()) Py_Initialize();
    // backend selection before jax import: QUEST_TPU_C_PLATFORM only,
    // default cpu. Deliberately NOT honoring JAX_PLATFORMS: this image
    // exports JAX_PLATFORMS=axon globally, and an embedded user binary
    // must not hang on a tunneled-TPU probe unless explicitly asked
    // (include/QuEST.h documents the knob).
    int rc = PyRun_SimpleString(
        "import os\n"
        "_plat = os.environ.get('QUEST_TPU_C_PLATFORM') or 'cpu'\n"
        "os.environ['JAX_PLATFORMS'] = _plat\n"
        "import jax\n"
        "jax.config.update('jax_platforms', _plat)\n"
        "jax.config.update('jax_enable_x64', True)\n");
    if (rc != 0) fatal_py("python bootstrap");
    // the shim ships inside quest_tpu/native/ — put the package root
    // (two directories up from this .so) on sys.path so an embedded
    // interpreter finds the framework without an installed wheel
    Dl_info info;
    if (dladdr(reinterpret_cast<void *>(&ensure_python), &info)
        && info.dli_fname != nullptr) {
        std::string root(info.dli_fname);
        for (int up = 0; up < 3; ++up) {
            auto cut = root.find_last_of('/');
            if (cut == std::string::npos) break;
            root.erase(cut);
        }
        // no string-spliced code: a path containing quotes must not
        // become a syntax error
        PyObject *path = PySys_GetObject("path");  // borrowed
        PyObject *entry = PyUnicode_FromString(root.c_str());
        if (path == nullptr || entry == nullptr
            || PyList_Insert(path, 0, entry) != 0)
            fatal_py("sys.path bootstrap");
        Py_DECREF(entry);
    }
    g_qt = PyImport_ImportModule("quest_tpu");
    if (g_qt == nullptr) fatal_py("import quest_tpu");
}

int store(PyObject *obj) {
    int h = g_next_handle++;
    g_objects[h] = obj;
    return h;
}

PyObject *lookup(int handle, const char *where) {
    auto it = g_objects.find(handle);
    if (it == g_objects.end()) {
        std::fprintf(stderr,
                     "QuEST-TPU shim: stale/unknown handle %d in %s\n",
                     handle, where);
        std::exit(1);
    }
    return it->second;
}

// call qt.<name>(...) with a ready argument tuple; returns new ref
PyObject *call(const char *name, PyObject *args) {
    ensure_python();
    PyObject *fn = PyObject_GetAttrString(g_qt, name);
    if (fn == nullptr) fatal_py(name);
    PyObject *out = PyObject_CallObject(fn, args);
    Py_DECREF(fn);
    Py_XDECREF(args);
    if (out == nullptr) fatal_py(name);
    return out;
}

void call_void(const char *name, PyObject *args) {
    Py_DECREF(call(name, args));
}

double call_real(const char *name, PyObject *args) {
    PyObject *out = call(name, args);
    double v = PyFloat_AsDouble(out);
    Py_DECREF(out);
    if (PyErr_Occurred()) fatal_py(name);
    return v;
}

long long call_int(const char *name, PyObject *args) {
    PyObject *out = call(name, args);
    long long v = PyLong_AsLongLong(out);
    Py_DECREF(out);
    if (PyErr_Occurred()) fatal_py(name);
    return v;
}

Complex call_complex(const char *name, PyObject *args) {
    PyObject *out = call(name, args);
    Py_complex c = PyComplex_AsCComplex(out);
    Py_DECREF(out);
    if (PyErr_Occurred()) fatal_py(name);
    return Complex{c.real, c.imag};
}

PyObject *py_qureg(Qureg q) { return lookup(q.handle, "qureg"); }
PyObject *py_env(QuESTEnv e) { return lookup(e.handle, "env"); }

PyObject *py_complex(Complex c) {
    return PyComplex_FromDoubles(c.real, c.imag);
}

PyObject *py_int_list(const int *xs, int n) {
    PyObject *lst = PyList_New(n);
    for (int i = 0; i < n; ++i)
        PyList_SET_ITEM(lst, i, PyLong_FromLong(xs[i]));
    return lst;
}

// dim x dim complex matrix as list-of-lists from separate re/im tables
template <typename Get>
PyObject *py_matrix(int dim, Get at) {
    PyObject *rows = PyList_New(dim);
    for (int r = 0; r < dim; ++r) {
        PyObject *row = PyList_New(dim);
        for (int c = 0; c < dim; ++c)
            PyList_SET_ITEM(row, c, at(r, c));
        PyList_SET_ITEM(rows, r, row);
    }
    return rows;
}

PyObject *py_m2(ComplexMatrix2 u) {
    return py_matrix(2, [&](int r, int c) {
        return PyComplex_FromDoubles(u.real[r][c], u.imag[r][c]);
    });
}

PyObject *py_m4(ComplexMatrix4 u) {
    return py_matrix(4, [&](int r, int c) {
        return PyComplex_FromDoubles(u.real[r][c], u.imag[r][c]);
    });
}

PyObject *py_mn(ComplexMatrixN u) {
    int dim = 1 << u.numQubits;
    return py_matrix(dim, [&](int r, int c) {
        return PyComplex_FromDoubles(u.real[r][c], u.imag[r][c]);
    });
}

PyObject *py_axis(Vector v) {
    return Py_BuildValue("(ddd)", v.x, v.y, v.z);
}

}  // namespace

extern "C" {

QuESTEnv createQuESTEnv(void) {
    ensure_python();
    PyObject *env = call("createQuESTEnv", nullptr);
    if (g_first_env == nullptr) g_first_env = env;
    QuESTEnv out;
    out.handle = store(env);
    out.numRanks = 1;
    return out;
}

void destroyQuESTEnv(QuESTEnv env) {
    PyObject *e = py_env(env);
    call_void("destroyQuESTEnv", Py_BuildValue("(O)", e));
    g_objects.erase(env.handle);
    if (g_first_env == e) g_first_env = nullptr;
    Py_DECREF(e);
}

void reportQuESTEnv(QuESTEnv env) {
    call_void("reportQuESTEnv", Py_BuildValue("(O)", py_env(env)));
}

void seedQuEST(unsigned long int *seedArray, int numSeeds) {
    ensure_python();
    PyObject *seeds = PyList_New(numSeeds);
    for (int i = 0; i < numSeeds; ++i)
        PyList_SET_ITEM(seeds, i,
                        PyLong_FromUnsignedLong(seedArray[i]));
    // framework spelling: seedQuEST(env, seeds); the C API's implicit
    // global env is the program's first-created env (single-env
    // programs, the reference's own model)
    if (g_first_env == nullptr) {
        std::fprintf(stderr, "seedQuEST before createQuESTEnv\n");
        std::exit(1);
    }
    call_void("seedQuEST", Py_BuildValue("(ON)", g_first_env, seeds));
}

static Qureg make_qureg(const char *ctor, int numQubits, QuESTEnv env) {
    PyObject *q = call(ctor, Py_BuildValue("(iO)", numQubits, py_env(env)));
    Qureg out;
    out.handle = store(q);
    out.numQubitsRepresented = numQubits;
    PyObject *isdm = PyObject_GetAttrString(q, "is_density_matrix");
    if (isdm == nullptr) fatal_py(ctor);
    out.isDensityMatrix = PyObject_IsTrue(isdm);
    Py_DECREF(isdm);
    out.numQubitsInStateVec =
        out.isDensityMatrix ? 2 * numQubits : numQubits;
    out.numAmpsTotal = 1LL << out.numQubitsInStateVec;
    return out;
}

Qureg createQureg(int numQubits, QuESTEnv env) {
    return make_qureg("createQureg", numQubits, env);
}

Qureg createDensityQureg(int numQubits, QuESTEnv env) {
    return make_qureg("createDensityQureg", numQubits, env);
}

void destroyQureg(Qureg qureg, QuESTEnv env) {
    PyObject *q = py_qureg(qureg);
    call_void("destroyQureg", Py_BuildValue("(OO)", q, py_env(env)));
    g_objects.erase(qureg.handle);
    Py_DECREF(q);
}

void reportQuregParams(Qureg qureg) {
    call_void("reportQuregParams", Py_BuildValue("(O)", py_qureg(qureg)));
}

void reportStateToScreen(Qureg qureg, QuESTEnv env, int reportRank) {
    call_void("reportStateToScreen",
              Py_BuildValue("(OOi)", py_qureg(qureg), py_env(env),
                            reportRank));
}

ComplexMatrixN createComplexMatrixN(int numQubits) {
    int dim = 1 << numQubits;
    ComplexMatrixN m;
    m.numQubits = numQubits;
    m.real = static_cast<qreal **>(std::calloc(dim, sizeof(qreal *)));
    m.imag = static_cast<qreal **>(std::calloc(dim, sizeof(qreal *)));
    for (int r = 0; r < dim; ++r) {
        m.real[r] = static_cast<qreal *>(std::calloc(dim, sizeof(qreal)));
        m.imag[r] = static_cast<qreal *>(std::calloc(dim, sizeof(qreal)));
    }
    return m;
}

void destroyComplexMatrixN(ComplexMatrixN m) {
    int dim = 1 << m.numQubits;
    for (int r = 0; r < dim; ++r) {
        std::free(m.real[r]);
        std::free(m.imag[r]);
    }
    std::free(m.real);
    std::free(m.imag);
}

void initZeroState(Qureg q) {
    call_void("initZeroState", Py_BuildValue("(O)", py_qureg(q)));
}
void initPlusState(Qureg q) {
    call_void("initPlusState", Py_BuildValue("(O)", py_qureg(q)));
}
void initDebugState(Qureg q) {
    call_void("initDebugState", Py_BuildValue("(O)", py_qureg(q)));
}
void initClassicalState(Qureg q, long long int stateInd) {
    call_void("initClassicalState",
              Py_BuildValue("(OL)", py_qureg(q), stateInd));
}
void initPureState(Qureg q, Qureg pure) {
    call_void("initPureState",
              Py_BuildValue("(OO)", py_qureg(q), py_qureg(pure)));
}

#define SHIM_1Q(name) \
    void name(Qureg q, int t) { \
        call_void(#name, Py_BuildValue("(Oi)", py_qureg(q), t)); }
SHIM_1Q(hadamard)
SHIM_1Q(pauliX)
SHIM_1Q(pauliY)
SHIM_1Q(pauliZ)
SHIM_1Q(sGate)
SHIM_1Q(tGate)
#undef SHIM_1Q

#define SHIM_1Q_ANGLE(name) \
    void name(Qureg q, int t, qreal angle) { \
        call_void(#name, Py_BuildValue("(Oid)", py_qureg(q), t, angle)); }
SHIM_1Q_ANGLE(phaseShift)
SHIM_1Q_ANGLE(rotateX)
SHIM_1Q_ANGLE(rotateY)
SHIM_1Q_ANGLE(rotateZ)
#undef SHIM_1Q_ANGLE

void rotateAroundAxis(Qureg q, int t, qreal angle, Vector axis) {
    call_void("rotateAroundAxis",
              Py_BuildValue("(OidN)", py_qureg(q), t, angle, py_axis(axis)));
}

void compactUnitary(Qureg q, int t, Complex alpha, Complex beta) {
    call_void("compactUnitary",
              Py_BuildValue("(OiNN)", py_qureg(q), t, py_complex(alpha),
                            py_complex(beta)));
}

void unitary(Qureg q, int t, ComplexMatrix2 u) {
    call_void("unitary",
              Py_BuildValue("(OiN)", py_qureg(q), t, py_m2(u)));
}

#define SHIM_C1Q(name) \
    void name(Qureg q, int c, int t) { \
        call_void(#name, Py_BuildValue("(Oii)", py_qureg(q), c, t)); }
SHIM_C1Q(controlledNot)
SHIM_C1Q(controlledPauliY)
SHIM_C1Q(controlledPhaseFlip)
SHIM_C1Q(swapGate)
#undef SHIM_C1Q

#define SHIM_C1Q_ANGLE(name) \
    void name(Qureg q, int c, int t, qreal angle) { \
        call_void(#name, Py_BuildValue("(Oiid)", py_qureg(q), c, t, angle)); }
SHIM_C1Q_ANGLE(controlledPhaseShift)
SHIM_C1Q_ANGLE(controlledRotateX)
SHIM_C1Q_ANGLE(controlledRotateY)
SHIM_C1Q_ANGLE(controlledRotateZ)
#undef SHIM_C1Q_ANGLE

void controlledRotateAroundAxis(Qureg q, int c, int t, qreal angle,
                                Vector axis) {
    call_void("controlledRotateAroundAxis",
              Py_BuildValue("(OiidN)", py_qureg(q), c, t, angle,
                            py_axis(axis)));
}

void controlledCompactUnitary(Qureg q, int c, int t, Complex alpha,
                              Complex beta) {
    call_void("controlledCompactUnitary",
              Py_BuildValue("(OiiNN)", py_qureg(q), c, t,
                            py_complex(alpha), py_complex(beta)));
}

void controlledUnitary(Qureg q, int c, int t, ComplexMatrix2 u) {
    call_void("controlledUnitary",
              Py_BuildValue("(OiiN)", py_qureg(q), c, t, py_m2(u)));
}

void multiControlledPhaseFlip(Qureg q, int *ctrls, int n) {
    call_void("multiControlledPhaseFlip",
              Py_BuildValue("(ON)", py_qureg(q), py_int_list(ctrls, n)));
}

void multiControlledPhaseShift(Qureg q, int *ctrls, int n, qreal angle) {
    call_void("multiControlledPhaseShift",
              Py_BuildValue("(ONd)", py_qureg(q), py_int_list(ctrls, n),
                            angle));
}

void multiControlledUnitary(Qureg q, int *ctrls, int n, int t,
                            ComplexMatrix2 u) {
    call_void("multiControlledUnitary",
              Py_BuildValue("(ONiN)", py_qureg(q), py_int_list(ctrls, n),
                            t, py_m2(u)));
}

void twoQubitUnitary(Qureg q, int t1, int t2, ComplexMatrix4 u) {
    call_void("twoQubitUnitary",
              Py_BuildValue("(OiiN)", py_qureg(q), t1, t2, py_m4(u)));
}

void multiQubitUnitary(Qureg q, int *targs, int numTargs, ComplexMatrixN u) {
    call_void("multiQubitUnitary",
              Py_BuildValue("(ONN)", py_qureg(q),
                            py_int_list(targs, numTargs), py_mn(u)));
}

#define SHIM_NOISE(name) \
    void name(Qureg q, int t, qreal prob) { \
        call_void(#name, Py_BuildValue("(Oid)", py_qureg(q), t, prob)); }
SHIM_NOISE(mixDephasing)
SHIM_NOISE(mixDepolarising)
SHIM_NOISE(mixDamping)
#undef SHIM_NOISE

int measure(Qureg q, int t) {
    return static_cast<int>(
        call_int("measure", Py_BuildValue("(Oi)", py_qureg(q), t)));
}

int measureWithStats(Qureg q, int t, qreal *outcomeProb) {
    PyObject *out = call("measureWithStats",
                         Py_BuildValue("(Oi)", py_qureg(q), t));
    int outcome = static_cast<int>(
        PyLong_AsLongLong(PyTuple_GetItem(out, 0)));
    *outcomeProb = PyFloat_AsDouble(PyTuple_GetItem(out, 1));
    Py_DECREF(out);
    if (PyErr_Occurred()) fatal_py("measureWithStats");
    return outcome;
}

qreal collapseToOutcome(Qureg q, int t, int outcome) {
    return call_real("collapseToOutcome",
                     Py_BuildValue("(Oii)", py_qureg(q), t, outcome));
}

qreal calcTotalProb(Qureg q) {
    return call_real("calcTotalProb", Py_BuildValue("(O)", py_qureg(q)));
}

qreal calcProbOfOutcome(Qureg q, int t, int outcome) {
    return call_real("calcProbOfOutcome",
                     Py_BuildValue("(Oii)", py_qureg(q), t, outcome));
}

qreal calcPurity(Qureg q) {
    return call_real("calcPurity", Py_BuildValue("(O)", py_qureg(q)));
}

qreal calcFidelity(Qureg q, Qureg pure) {
    return call_real("calcFidelity",
                     Py_BuildValue("(OO)", py_qureg(q), py_qureg(pure)));
}

Complex calcInnerProduct(Qureg bra, Qureg ket) {
    return call_complex("calcInnerProduct",
                        Py_BuildValue("(OO)", py_qureg(bra), py_qureg(ket)));
}

Complex getAmp(Qureg q, long long int index) {
    return call_complex("getAmp",
                        Py_BuildValue("(OL)", py_qureg(q), index));
}

Complex getDensityAmp(Qureg q, long long int row, long long int col) {
    return call_complex("getDensityAmp",
                        Py_BuildValue("(OLL)", py_qureg(q), row, col));
}

qreal getRealAmp(Qureg q, long long int index) {
    return call_real("getRealAmp",
                     Py_BuildValue("(OL)", py_qureg(q), index));
}

qreal getImagAmp(Qureg q, long long int index) {
    return call_real("getImagAmp",
                     Py_BuildValue("(OL)", py_qureg(q), index));
}

qreal getProbAmp(Qureg q, long long int index) {
    return call_real("getProbAmp",
                     Py_BuildValue("(OL)", py_qureg(q), index));
}

int getNumQubits(Qureg q) {
    return static_cast<int>(
        call_int("getNumQubits", Py_BuildValue("(O)", py_qureg(q))));
}

long long int getNumAmps(Qureg q) {
    return call_int("getNumAmps", Py_BuildValue("(O)", py_qureg(q)));
}

}  // extern "C"
