// quest_sched — native circuit graph-builder and scheduler.
//
// The TPU framework's counterpart of the reference's native runtime layer:
// where QuEST's dispatch/backend split decides per gate, at run time, whether
// an op is chunk-local or needs communication (QuEST_cpu_distributed.c:
// halfMatrixBlockFitsInChunk :353, getChunkPairId :300, swap-to-local
// :1420-1461), this library plans the *whole program* ahead of time:
//
//   1. graph build: gates stream in through a C ABI (ctypes-friendly);
//   2. peephole fusion: adjacent static unitaries on the same target/control
//      set are matrix-multiplied host-side; adjacent static diagonal ops are
//      merged over the union of their qubits (cap 6);
//   3. layout planning: a logical->physical qubit permutation is tracked; a
//      paired gate whose target sits on a sharded position triggers ONE
//      batched relayout (Belady eviction over a lookahead window) instead of
//      per-gate exchanges.
//   4. (cost-aware mode, qsched_set_cost_model) communication-aware
//      planning under a linear alpha+beta*bytes collective model: SWAP
//      gates are absorbed into the permutation (zero bytes), a lone
//      sharded 1q gate rides a whole-chunk pair exchange ("xshard" item)
//      when modeled cheaper than localise+restore, and adjacent relayouts
//      compose into one exchange when the intervening ops stay executable
//      under the composed permutation and the composed collective is
//      modeled no slower than the pair.
//   5. (multi-host mode, qsched_set_cost_model2) two-tier pricing — a
//      separate (alpha, beta) for collectives whose exchanged device
//      bits include one of the top host_bits inter-host positions — and
//      the mpiQulacs hot-qubit reordering pass: each relayout's evicted
//      qubits are re-paired with the vacated device slots so the coldest
//      victim (fewest remaining paired uses, then farthest next use)
//      takes the most-inter-host slot. host_bits == 0 reproduces the
//      single-host plans bit-for-bit.
//
// Output is a schedule of items — ops at physical positions, relayout
// permutations, cross-shard exchanges — that the Python/JAX side lowers
// into a single XLA program. Semantics must match
// quest_tpu/parallel/layout.py (tested for equality, in both modes).
//
// Build: native/Makefile -> quest_tpu/native/libquest_sched.so

#include <algorithm>
#include <complex>
#include <cstdint>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

namespace {

using cplx = std::complex<double>;

constexpr int KIND_U = 0;           // static unitary (matrix owned here)
constexpr int KIND_DIAG = 1;        // static diagonal (tensor owned here)
constexpr int KIND_U_PARAM = 2;     // parameterized unitary (opaque)
constexpr int KIND_DIAG_PARAM = 3;  // parameterized diagonal (opaque)

constexpr int MAX_DIAG_FUSE_QUBITS = 6;

struct Op {
  int kind;
  std::vector<int> targets;   // user bit order (u) / sorted desc (diag)
  int64_t ctrl_mask = 0;
  int64_t flip_mask = 0;
  std::vector<cplx> data;     // (2^k)^2 matrix or 2^k diagonal tensor
  int source_index;           // index of the (first) source op, for param fns
};

constexpr int ITEM_OP = 0;
constexpr int ITEM_RELAYOUT = 1;
constexpr int ITEM_XSHARD = 2;      // cross-shard 1q pair exchange

struct Item {
  int kind = ITEM_OP;
  // op / xshard item
  int op_index = -1;                  // into fused op table
  std::vector<int> phys_targets;
  int64_t phys_ctrl_mask = 0;
  int64_t phys_flip_mask = 0;
  std::vector<int> axis_order;        // diag tensor transpose (desc order)
  // relayout item
  std::vector<int> perm_before, perm_after;
};

struct Sched {
  std::vector<Op> ops;        // as recorded
  std::vector<Op> fused;      // after peephole fusion
  std::vector<Item> items;    // final schedule
  int num_qubits = 0;
  int shard_bits = 0;
  int num_relayouts = 0;
  // communication-aware mode (mirrors quest_tpu/parallel/layout.py)
  bool cost_aware = false;
  double alpha = 0.0;          // per-collective latency, seconds
  double beta = 0.0;           // seconds per byte
  double chunk_bytes = 0.0;    // per-device chunk payload
  // multi-host (two-tier) mode: negative inter values = same as intra
  double inter_alpha = -1.0;   // inter-host per-collective latency
  double inter_beta = -1.0;    // inter-host seconds per byte
  int host_bits = 0;           // top device bits crossing the host edge
  bool reorder = true;         // hot-qubit-local eviction re-pairing
  int num_xshard = 0;
  int swaps_absorbed = 0;
  int fused_collectives = 0;
  std::string error;
};

// ---------------------------------------------------------------------------
// fusion pass (mirrors Circuit._fused_ops)
// ---------------------------------------------------------------------------

bool same_masks(const Op& a, const Op& b) {
  return a.ctrl_mask == b.ctrl_mask && a.flip_mask == b.flip_mask;
}

// c = b . a applied as "a first, then b"  =>  matrix product b*a
std::vector<cplx> matmul(const std::vector<cplx>& b, const std::vector<cplx>& a,
                         int dim) {
  std::vector<cplx> out(static_cast<size_t>(dim) * dim, cplx(0.0, 0.0));
  for (int i = 0; i < dim; ++i)
    for (int k = 0; k < dim; ++k) {
      cplx bik = b[static_cast<size_t>(i) * dim + k];
      if (bik == cplx(0.0, 0.0)) continue;
      for (int j = 0; j < dim; ++j)
        out[static_cast<size_t>(i) * dim + j] +=
            bik * a[static_cast<size_t>(k) * dim + j];
    }
  return out;
}

// expand a diag tensor over `from_q` (sorted desc) onto union `to_q` (sorted
// desc, superset): broadcast over the axes not in from_q
std::vector<cplx> expand_diag(const std::vector<cplx>& t,
                              const std::vector<int>& from_q,
                              const std::vector<int>& to_q) {
  int K = static_cast<int>(to_q.size());
  std::vector<int> src_axis(K, -1);  // axis in from_q per to_q axis
  for (int i = 0; i < K; ++i)
    for (size_t j = 0; j < from_q.size(); ++j)
      if (to_q[i] == from_q[j]) src_axis[i] = static_cast<int>(j);
  std::vector<cplx> out(size_t{1} << K);
  int k_from = static_cast<int>(from_q.size());
  for (int64_t m = 0; m < (int64_t{1} << K); ++m) {
    int64_t src = 0;
    for (int i = 0; i < K; ++i) {
      if (src_axis[i] < 0) continue;
      // bit of axis i in m (axis 0 = most significant)
      int bit = (m >> (K - 1 - i)) & 1;
      if (bit) src |= int64_t{1} << (k_from - 1 - src_axis[i]);
    }
    out[static_cast<size_t>(m)] = t[static_cast<size_t>(src)];
  }
  return out;
}

void fuse(Sched& s, int diag_row_cap) {
  s.fused.clear();
  for (const Op& op : s.ops) {
    bool merged = false;
    if (!s.fused.empty() &&
        (op.kind == KIND_U || op.kind == KIND_DIAG)) {
      Op& prev = s.fused.back();
      if (op.kind == KIND_U && prev.kind == KIND_U &&
          op.targets == prev.targets && same_masks(op, prev)) {
        int dim = 1 << op.targets.size();
        prev.data = matmul(op.data, prev.data, dim);
        merged = true;
      } else if (op.kind == KIND_DIAG && prev.kind == KIND_DIAG) {
        std::vector<int> uni;
        for (int q : prev.targets) uni.push_back(q);
        for (int q : op.targets)
          if (std::find(uni.begin(), uni.end(), q) == uni.end())
            uni.push_back(q);
        std::sort(uni.begin(), uni.end(), std::greater<int>());
        int row_bits = 0;
        for (int q : uni)
          if (q >= 7) ++row_bits;  // lane/row split of the layer kernel
        if (static_cast<int>(uni.size()) <= MAX_DIAG_FUSE_QUBITS &&
            (diag_row_cap < 0 || row_bits <= diag_row_cap)) {
          std::vector<cplx> a = expand_diag(prev.data, prev.targets, uni);
          std::vector<cplx> b = expand_diag(op.data, op.targets, uni);
          for (size_t i = 0; i < a.size(); ++i) a[i] *= b[i];
          prev.data = std::move(a);
          prev.targets = uni;
          merged = true;
        }
      }
    }
    if (!merged) s.fused.push_back(op);
  }
}

// ---------------------------------------------------------------------------
// layout planning (mirrors quest_tpu/parallel/layout.py::plan_layout)
// ---------------------------------------------------------------------------

bool is_paired(const Op& op) {
  return op.kind == KIND_U || op.kind == KIND_U_PARAM;
}

// static uncontrolled 2q SWAP (the ops the cost-aware planner absorbs
// into the permutation); tolerance mirrors layout.py::is_swap_op
bool is_swap(const Op& op) {
  if (op.kind != KIND_U || op.ctrl_mask != 0 || op.targets.size() != 2 ||
      op.data.size() != 16)
    return false;
  static const double SWAP_RE[16] = {1, 0, 0, 0, 0, 0, 1, 0,
                                     0, 1, 0, 0, 0, 0, 0, 1};
  for (int i = 0; i < 16; ++i) {
    if (std::abs(op.data[i].real() - SWAP_RE[i]) > 1e-12) return false;
    if (std::abs(op.data[i].imag()) > 1e-12) return false;
  }
  return true;
}

// physical permutation a relayout realizes: perm_before[l] -> perm_after[l]
std::vector<int> relayout_sigma(const std::vector<int>& before,
                                const std::vector<int>& after, int n) {
  std::vector<int> sigma(n);
  for (int l = 0; l < n; ++l) sigma[before[l]] = after[l];
  return sigma;
}

// (alpha, beta) of one pricing tier: the inter-host values when the
// collective crosses hosts and a tier is calibrated, else intra
// (mirrors CommCostModel.tier)
void tier_of(const Sched& s, bool inter, double* alpha, double* beta) {
  *alpha = (inter && s.inter_alpha >= 0.0) ? s.inter_alpha : s.alpha;
  *beta = (inter && s.inter_beta >= 0.0) ? s.inter_beta : s.beta;
}

double a2a_seconds(const Sched& s, int k, bool inter = false) {
  if (k <= 0) return 0.0;
  double a, b;
  tier_of(s, inter, &a, &b);
  return a + b * (s.chunk_bytes *
                  ((double)((1 << k) - 1) / (double)(1 << k)));
}

double ppermute_seconds(const Sched& s, bool inter = false) {
  double a, b;
  tier_of(s, inter, &a, &b);
  return a + b * s.chunk_bytes;
}

// modeled seconds for one relayout, mirroring
// layout.py::relayout_comm_tiered: one all_to_all over the k exchanged
// bits (inter tier iff an exchanged device slot is one of the top
// host_bits positions) plus a whole-chunk ppermute iff a residual
// device-bit permutation remains (inter tier, conservatively, iff ANY
// inter-host slot participates in the relayout)
double relayout_seconds(const Sched& s, const std::vector<int>& sigma,
                        int lt) {
  int n = (int)sigma.size();
  int hb = std::max(0, std::min(s.host_bits, n - lt));
  int inter_lo = n - hb;
  int k = 0;
  bool residual = false, a2a_inter = false, res_inter = false;
  for (int p = 0; p < lt; ++p)
    if (sigma[p] >= lt) {
      ++k;
      if (sigma[sigma[p]] >= lt) residual = true;
    }
  for (int d = lt; d < n; ++d) {
    if (sigma[d] >= lt && sigma[d] != d) residual = true;
    if (hb > 0 && sigma[d] < lt && d >= inter_lo) a2a_inter = true;
  }
  if (hb > 0)
    for (int p = inter_lo; p < n; ++p)
      if (sigma[p] != p) res_inter = true;
  double sec = 0.0;
  if (k) sec += a2a_seconds(s, k, a2a_inter);
  if (residual) sec += ppermute_seconds(s, res_inter);
  return sec;
}

int64_t remap_mask(int64_t mask, const std::vector<int>& delta) {
  int64_t out = 0;
  for (int p = 0; mask != 0; ++p, mask >>= 1)
    if (mask & 1) out |= int64_t{1} << delta[p];
  return out;
}

// rewrite an op/xshard item's physical coordinates through delta
void remap_item(Item& it, const std::vector<int>& delta) {
  if (it.kind == ITEM_XSHARD || it.axis_order.empty()) {
    for (int& p : it.phys_targets) p = delta[p];
    it.phys_ctrl_mask = remap_mask(it.phys_ctrl_mask, delta);
    it.phys_flip_mask = remap_mask(it.phys_flip_mask, delta);
    return;
  }
  // diagonal: remap positions, re-sort descending, compose axis order
  size_t k = it.phys_targets.size();
  std::vector<std::pair<int, int>> pairs(k);
  for (size_t i = 0; i < k; ++i)
    pairs[i] = {delta[it.phys_targets[i]], it.axis_order[i]};
  std::sort(pairs.begin(), pairs.end(),
            std::greater<std::pair<int, int>>());
  for (size_t i = 0; i < k; ++i) {
    it.phys_targets[i] = pairs[i].first;
    it.axis_order[i] = pairs[i].second;
  }
}

// merge adjacent relayouts (layout.py::_compose_relayouts): R2's
// permutation applies early (composed into R1) when every item between
// stays executable under it and the composed collective is modeled no
// slower than the pair. Returns relayouts removed; counts merges.
int compose_relayouts(Sched& s, int lt) {
  int n = s.num_qubits;
  int removed = 0;
  bool changed = true;
  while (changed) {
    changed = false;
    std::vector<int> idxs;
    for (int j = 0; j < (int)s.items.size(); ++j)
      if (s.items[j].kind == ITEM_RELAYOUT) idxs.push_back(j);
    for (size_t t = 0; t + 1 < idxs.size(); ++t) {
      int a = idxs[t], b = idxs[t + 1];
      std::vector<int> delta = relayout_sigma(s.items[b].perm_before,
                                              s.items[b].perm_after, n);
      bool ok = true;
      for (int j = a + 1; j < b; ++j) {
        const Item& it = s.items[j];
        if (it.kind == ITEM_OP) {
          if (it.axis_order.empty()) {
            for (int p : it.phys_targets)
              if (delta[p] >= lt) { ok = false; break; }
            if (!ok) break;
          }
        } else if (it.kind == ITEM_XSHARD) {
          if (delta[it.phys_targets[0]] < lt) { ok = false; break; }
        } else {
          ok = false;
          break;
        }
      }
      if (!ok) continue;
      const std::vector<int>& before = s.items[a].perm_before;
      const std::vector<int>& after = s.items[a].perm_after;
      std::vector<int> new_after(n);
      for (int l = 0; l < n; ++l) new_after[l] = delta[after[l]];
      double c1 = relayout_seconds(s, relayout_sigma(before, after, n), lt);
      double c2 = relayout_seconds(s, delta, lt);
      double cc = relayout_seconds(s, relayout_sigma(before, new_after, n),
                                   lt);
      if (cc > c1 + c2) continue;
      for (int j = a + 1; j < b; ++j) remap_item(s.items[j], delta);
      bool identity = true;
      for (int l = 0; l < n; ++l)
        if (before[l] != new_after[l]) { identity = false; break; }
      s.items.erase(s.items.begin() + b);
      if (identity) {
        s.items.erase(s.items.begin() + a);
        removed += 2;
      } else {
        s.items[a].perm_after = new_after;
        removed += 1;
      }
      ++s.fused_collectives;
      changed = true;
      break;
    }
  }
  return removed;
}

Item op_item(int idx, const Op& op, const std::vector<int>& perm) {
  Item it;
  it.kind = ITEM_OP;
  it.op_index = idx;
  if (is_paired(op)) {
    for (int t : op.targets) it.phys_targets.push_back(perm[t]);
    int64_t m = op.ctrl_mask;
    for (int q = 0; m != 0; ++q, m >>= 1) {
      if (m & 1) {
        it.phys_ctrl_mask |= int64_t{1} << perm[q];
        if ((op.flip_mask >> q) & 1) it.phys_flip_mask |= int64_t{1} << perm[q];
      }
    }
  } else {
    // diag: targets stored sorted desc (logical); map and re-sort desc,
    // recording the tensor axis order
    size_t k = op.targets.size();
    std::vector<int> phys(k);
    for (size_t i = 0; i < k; ++i) phys[i] = perm[op.targets[i]];
    std::vector<int> order(k);
    for (size_t i = 0; i < k; ++i) order[i] = static_cast<int>(i);
    std::stable_sort(order.begin(), order.end(),
                     [&](int a, int b) { return phys[a] > phys[b]; });
    for (int o : order) it.phys_targets.push_back(phys[o]);
    it.axis_order.assign(order.begin(), order.end());
  }
  return it;
}

void plan(Sched& s, int lookahead) {
  const int n = s.num_qubits;
  const int S = s.shard_bits;
  const int local_top = n - S;
  auto& ops = s.fused;
  s.items.clear();
  s.num_relayouts = 0;
  s.num_xshard = 0;
  s.swaps_absorbed = 0;
  s.fused_collectives = 0;
  const bool comm_aware = s.cost_aware && S > 0;

  std::vector<int> perm(n);
  for (int i = 0; i < n; ++i) perm[i] = i;

  if (S == 0) {
    for (size_t i = 0; i < ops.size(); ++i)
      s.items.push_back(op_item(static_cast<int>(i), ops[i], perm));
    return;
  }

  std::vector<char> absorbable(ops.size(), 0);
  if (comm_aware)
    for (size_t i = 0; i < ops.size(); ++i)
      absorbable[i] = is_swap(ops[i]) ? 1 : 0;

  int max_k = 0;
  for (size_t i = 0; i < ops.size(); ++i)
    if (is_paired(ops[i]) && !absorbable[i])
      max_k = std::max(max_k, (int)ops[i].targets.size());
  if (max_k > local_top) {
    s.error = "a " + std::to_string(max_k) +
              "-qubit unitary cannot be localised with " +
              std::to_string(local_top) + " local qubit positions";
    return;
  }

  // qubits a paired op needs local: its targets only. Controls are
  // position-free — the shard_map executor turns a device-bit control into
  // a lax.cond on lax.axis_index (quest_tpu/parallel/exchange.py), the
  // distributed control-skip of QuEST_cpu_distributed.c:888-908.
  auto used_qubits = [](const Op& op) {
    std::vector<int> qs;
    if (!is_paired(op)) return qs;
    return op.targets;
  };

  const int64_t INF = static_cast<int64_t>(ops.size()) + 1;
  // next use (as a target of a paired op; absorbed SWAPs never demand
  // locality so they are not uses), next_use[i][q]
  std::vector<std::vector<int64_t>> next_use(ops.size() + 1,
                                             std::vector<int64_t>(n, INF));
  for (int64_t i = static_cast<int64_t>(ops.size()) - 1; i >= 0; --i) {
    next_use[i] = next_use[i + 1];
    if (!absorbable[i])
      for (int q : used_qubits(ops[i])) next_use[i][q] = i;
  }

  // upcoming-use counts (the reordering pass's hotness metric):
  // rem_uses[i][q] = paired uses of q at ops >= i (layout.py mirror)
  const int hb = std::max(0, std::min(s.host_bits, S));
  const bool reorder_on = comm_aware && hb > 0 && s.reorder;
  std::vector<std::vector<int64_t>> rem_uses;
  if (reorder_on) {
    rem_uses.assign(ops.size() + 1, std::vector<int64_t>(n, 0));
    for (int64_t i = static_cast<int64_t>(ops.size()) - 1; i >= 0; --i) {
      rem_uses[i] = rem_uses[i + 1];
      if (!absorbable[i])
        for (int q : used_qubits(ops[i])) ++rem_uses[i][q];
    }
  }

  auto contains = [](const std::vector<int>& v, int q) {
    return std::find(v.begin(), v.end(), q) != v.end();
  };

  for (size_t i = 0; i < ops.size(); ++i) {
    const Op& op = ops[i];
    if (absorbable[i]) {
      // SWAP = pure relabeling: exchange the two physical positions in
      // the bookkeeping, move zero amplitudes (layout.py mirror).
      std::swap(perm[op.targets[0]], perm[op.targets[1]]);
      ++s.swaps_absorbed;
      continue;
    }
    // lone sharded 1q gate: whole-chunk ppermute vs localise+restore
    // relayout pair — only when it is the SOLE sharded demand in the
    // lookahead window (any other sharded use means a relayout is
    // coming anyway and amortizes; layout.py mirror)
    auto try_xshard = [&]() -> bool {
      // any paired 1q op qualifies — including KIND_U_PARAM: the
      // executor resolves mat_fn at trace time (layout.py parity: the
      // Python condition is kind == "u" with no staticness check)
      if (!comm_aware || !is_paired(op) || op.targets.size() != 1 ||
          perm[op.targets[0]] < local_top)
        return false;
      int t = op.targets[0];
      size_t wend = std::min(i + static_cast<size_t>(lookahead),
                             ops.size());
      bool sole = true;
      // scratch perm applies the window's absorbed SWAPs as they pass
      // (layout.py mirror): later gates' locality is judged where their
      // labels will sit THEN
      std::vector<int> wp = perm;
      for (size_t j = i; j < wend && sole; ++j) {
        if (absorbable[j]) {
          std::swap(wp[ops[j].targets[0]], wp[ops[j].targets[1]]);
          continue;
        }
        for (int q : used_qubits(ops[j]))
          if (wp[q] >= local_top && (j != i || q != t)) {
            sole = false;
            break;
          }
      }
      // both candidates ride the same device bit, so both price at that
      // bit's tier (inter when the position crosses hosts)
      int hb = std::max(0, std::min(s.host_bits, S));
      bool x_inter = hb > 0 && perm[t] >= n - hb;
      if (!sole || ppermute_seconds(s, x_inter) >
                       2.0 * a2a_seconds(s, 1, x_inter))
        return false;
      Item it;
      it.kind = ITEM_XSHARD;
      it.op_index = static_cast<int>(i);
      it.phys_targets.push_back(perm[t]);
      int64_t m = op.ctrl_mask;
      for (int q = 0; m != 0; ++q, m >>= 1) {
        if (m & 1) {
          it.phys_ctrl_mask |= int64_t{1} << perm[q];
          if ((op.flip_mask >> q) & 1)
            it.phys_flip_mask |= int64_t{1} << perm[q];
        }
      }
      s.items.push_back(std::move(it));
      ++s.num_xshard;
      return true;
    };
    if (try_xshard()) continue;
    std::vector<int> used = used_qubits(op);
    bool offending = false;
    for (int q : used)
      if (perm[q] >= local_top) offending = true;
    if (offending) {
      // everything needed now (the op's sharded targets)
      std::vector<int> need_now;
      for (int t : op.targets)
        if (perm[t] >= local_top) need_now.push_back(t);
      // sharded DATA used in the lookahead window (prefetch), scanned
      // under a scratch perm that applies absorbed SWAPs as they pass —
      // the data serving a future gate is whatever CURRENT label
      // occupies that future position (layout.py mirror; reduces to the
      // legacy label scan when nothing is absorbable)
      std::vector<std::pair<int, int64_t>> window_hot;  // (label, use idx)
      std::vector<int> wp = perm;
      std::vector<int> inv(n);
      for (int l = 0; l < n; ++l) inv[perm[l]] = l;
      std::vector<char> seen(n, 0);
      for (int q : need_now) seen[q] = 1;
      size_t wend = std::min(i + static_cast<size_t>(lookahead), ops.size());
      for (size_t j = i; j < wend; ++j) {
        if (absorbable[j]) {
          std::swap(wp[ops[j].targets[0]], wp[ops[j].targets[1]]);
          continue;
        }
        for (int q : used_qubits(ops[j]))
          if (wp[q] >= local_top) {
            int hot = inv[wp[q]];
            if (!seen[hot]) {
              window_hot.emplace_back(hot, static_cast<int64_t>(j));
              seen[hot] = 1;
            }
          }
      }
      // victims: local positions not used by this op, farthest next use
      // first (Belady)
      std::vector<std::pair<int64_t, int>> locals_;
      for (int l = 0; l < n; ++l) {
        if (perm[l] >= local_top) continue;
        if (contains(used, l)) continue;
        locals_.emplace_back(next_use[i][l], l);
      }
      std::sort(locals_.begin(), locals_.end(),
                std::greater<std::pair<int64_t, int>>());
      std::vector<std::pair<int, int64_t>> bring;
      for (int q : need_now) bring.emplace_back(q, int64_t{-1});
      for (auto& h : window_hot) bring.push_back(h);

      // phase 1 — victim selection (Belady order, layout.py mirror)
      std::vector<std::pair<int, int>> pairs_sel;  // (incoming q, victim)
      for (auto [q, nu_q] : bring) {
        if (pairs_sel.size() >= locals_.size()) break;
        auto [nu_victim, victim] = locals_[pairs_sel.size()];
        if (!contains(need_now, q) && nu_q >= nu_victim) continue;
        pairs_sel.emplace_back(q, victim);
      }
      // device-slot assignment for the evicted victims: by default
      // victim i takes the slot its incoming qubit vacates; the
      // hot-qubit reordering pass re-pairs so the COLDEST victim
      // (fewest remaining paired uses, then farthest next use, then
      // label) takes the most-inter-host slot (layout.py mirror)
      std::vector<int> vacated;
      for (auto& [q, v] : pairs_sel) vacated.push_back(perm[q]);
      std::vector<int> dest(n, -1);
      for (size_t j = 0; j < pairs_sel.size(); ++j)
        dest[pairs_sel[j].second] = vacated[j];
      if (reorder_on && pairs_sel.size() > 1) {
        std::vector<int> cold_first;
        for (auto& [q, v] : pairs_sel) cold_first.push_back(v);
        std::sort(cold_first.begin(), cold_first.end(),
                  [&](int a, int b) {
                    if (rem_uses[i][a] != rem_uses[i][b])
                      return rem_uses[i][a] < rem_uses[i][b];
                    if (next_use[i][a] != next_use[i][b])
                      return next_use[i][a] > next_use[i][b];
                    return a < b;
                  });
        std::vector<int> slots = vacated;
        std::sort(slots.begin(), slots.end(), std::greater<int>());
        for (size_t j = 0; j < cold_first.size(); ++j)
          dest[cold_first[j]] = slots[j];
      }
      // phase 2 — three-way rotation landing each incoming qubit at a
      // TOP local position (the all_to_all staging slot): q -> stage,
      // the qubit at stage -> the victim's slot, victim -> its assigned
      // device position — so the exchange's post-transpose vanishes
      // (layout.py mirror).
      std::vector<int> new_perm = perm;
      for (size_t vi = 0; vi < pairs_sel.size(); ++vi) {
        auto [q, victim] = pairs_sel[vi];
        int stage = local_top - 1 - static_cast<int>(vi);
        int x = -1;
        for (int l = 0; l < n; ++l)
          if (new_perm[l] == stage) { x = l; break; }
        int vic_pos = new_perm[victim];
        new_perm[q] = stage;
        if (x != victim) new_perm[x] = vic_pos;
        new_perm[victim] = dest[victim];
      }
      Item r;
      r.kind = ITEM_RELAYOUT;
      r.perm_before = perm;
      r.perm_after = new_perm;
      s.items.push_back(std::move(r));
      ++s.num_relayouts;
      perm = new_perm;
    }
    s.items.push_back(op_item(static_cast<int>(i), op, perm));
  }

  bool identity = true;
  for (int l = 0; l < n; ++l)
    if (perm[l] != l) { identity = false; break; }
  if (!identity) {
    Item r;
    r.kind = ITEM_RELAYOUT;
    r.perm_before = perm;
    r.perm_after.resize(n);
    for (int l = 0; l < n; ++l) r.perm_after[l] = l;
    s.items.push_back(std::move(r));
    ++s.num_relayouts;
  }

  if (comm_aware)
    s.num_relayouts -= compose_relayouts(s, local_top);
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* qsched_create() { return new Sched(); }

void qsched_destroy(void* h) { delete static_cast<Sched*>(h); }

// data: interleaved re,im; for KIND_U (2^k)^2 entries, KIND_DIAG 2^k entries,
// param kinds: data ignored (may be null)
int qsched_add_op(void* h, int kind, int num_targets, const int* targets,
                  int64_t ctrl_mask, int64_t flip_mask, const double* data,
                  int source_index) {
  Sched& s = *static_cast<Sched*>(h);
  Op op;
  op.kind = kind;
  op.targets.assign(targets, targets + num_targets);
  op.ctrl_mask = ctrl_mask;
  op.flip_mask = flip_mask;
  op.source_index = source_index;
  if (kind == KIND_U) {
    size_t dim = size_t{1} << num_targets;
    op.data.resize(dim * dim);
    for (size_t i = 0; i < dim * dim; ++i)
      op.data[i] = cplx(data[2 * i], data[2 * i + 1]);
  } else if (kind == KIND_DIAG) {
    size_t dim = size_t{1} << num_targets;
    op.data.resize(dim);
    for (size_t i = 0; i < dim; ++i)
      op.data[i] = cplx(data[2 * i], data[2 * i + 1]);
  }
  s.ops.push_back(std::move(op));
  return static_cast<int>(s.ops.size()) - 1;
}

// enable the communication-aware planner with a linear collective cost
// model (seconds = alpha + beta * bytes; chunk_bytes = per-device chunk)
void qsched_set_cost_model(void* h, double alpha, double beta,
                           double chunk_bytes) {
  Sched& s = *static_cast<Sched*>(h);
  s.cost_aware = true;
  s.alpha = alpha;
  s.beta = beta;
  s.chunk_bytes = chunk_bytes;
  s.inter_alpha = -1.0;
  s.inter_beta = -1.0;
  s.host_bits = 0;
  s.reorder = true;
}

// two-tier (multi-host) cost model: separate (alpha, beta) for
// collectives crossing the host boundary (negative inter values fall
// back to the intra tier), the number of inter-host device bits, and
// the hot-qubit reordering switch
void qsched_set_cost_model2(void* h, double alpha, double beta,
                            double inter_alpha, double inter_beta,
                            double chunk_bytes, int host_bits,
                            int reorder) {
  Sched& s = *static_cast<Sched*>(h);
  s.cost_aware = true;
  s.alpha = alpha;
  s.beta = beta;
  s.inter_alpha = inter_alpha;
  s.inter_beta = inter_beta;
  s.chunk_bytes = chunk_bytes;
  s.host_bits = host_bits;
  s.reorder = reorder != 0;
}

// run fusion + planning; returns 0 on success, nonzero on error
int qsched_compile(void* h, int num_qubits, int shard_bits, int lookahead,
                   int enable_fusion, int diag_row_cap) {
  Sched& s = *static_cast<Sched*>(h);
  s.num_qubits = num_qubits;
  s.shard_bits = shard_bits;
  s.error.clear();
  if (enable_fusion) {
    fuse(s, diag_row_cap);
  } else {
    s.fused = s.ops;
  }
  plan(s, lookahead);
  return s.error.empty() ? 0 : 1;
}

const char* qsched_error(void* h) {
  return static_cast<Sched*>(h)->error.c_str();
}

int qsched_num_fused(void* h) {
  return static_cast<int>(static_cast<Sched*>(h)->fused.size());
}

// fused-op metadata: returns kind; fills counts
int qsched_fused_info(void* h, int idx, int* num_targets, int64_t* ctrl_mask,
                      int64_t* flip_mask, int* source_index) {
  const Op& op = static_cast<Sched*>(h)->fused[idx];
  *num_targets = static_cast<int>(op.targets.size());
  *ctrl_mask = op.ctrl_mask;
  *flip_mask = op.flip_mask;
  *source_index = op.source_index;
  return op.kind;
}

void qsched_fused_targets(void* h, int idx, int* out) {
  const Op& op = static_cast<Sched*>(h)->fused[idx];
  std::memcpy(out, op.targets.data(), op.targets.size() * sizeof(int));
}

// copies interleaved re,im doubles; caller sizes from kind+num_targets
void qsched_fused_data(void* h, int idx, double* out) {
  const Op& op = static_cast<Sched*>(h)->fused[idx];
  for (size_t i = 0; i < op.data.size(); ++i) {
    out[2 * i] = op.data[i].real();
    out[2 * i + 1] = op.data[i].imag();
  }
}

int qsched_num_items(void* h) {
  return static_cast<int>(static_cast<Sched*>(h)->items.size());
}

int qsched_num_relayouts(void* h) {
  return static_cast<Sched*>(h)->num_relayouts;
}

int qsched_num_xshard(void* h) {
  return static_cast<Sched*>(h)->num_xshard;
}

int qsched_num_swaps_absorbed(void* h) {
  return static_cast<Sched*>(h)->swaps_absorbed;
}

int qsched_num_fused_collectives(void* h) {
  return static_cast<Sched*>(h)->fused_collectives;
}

// returns the item kind (0 op, 1 relayout, 2 cross-shard exchange); for
// op/xshard items fills op_index, num phys targets, masks; for relayouts
// fills nothing here
int qsched_item_info(void* h, int i, int* op_index, int* num_targets,
                     int64_t* ctrl_mask, int64_t* flip_mask) {
  const Item& it = static_cast<Sched*>(h)->items[i];
  if (it.kind == ITEM_RELAYOUT) return ITEM_RELAYOUT;
  *op_index = it.op_index;
  *num_targets = static_cast<int>(it.phys_targets.size());
  *ctrl_mask = it.phys_ctrl_mask;
  *flip_mask = it.phys_flip_mask;
  return it.kind;
}

void qsched_item_targets(void* h, int i, int* targets, int* axis_order) {
  const Item& it = static_cast<Sched*>(h)->items[i];
  std::memcpy(targets, it.phys_targets.data(),
              it.phys_targets.size() * sizeof(int));
  if (!it.axis_order.empty())
    std::memcpy(axis_order, it.axis_order.data(),
                it.axis_order.size() * sizeof(int));
}

void qsched_item_perms(void* h, int i, int* before, int* after) {
  const Item& it = static_cast<Sched*>(h)->items[i];
  std::memcpy(before, it.perm_before.data(),
              it.perm_before.size() * sizeof(int));
  std::memcpy(after, it.perm_after.data(),
              it.perm_after.size() * sizeof(int));
}

}  // extern "C"
