"""Algorithm-level goldens from the reference binary (QFT + Grover).

The reference's algorithm tier (`tests/algor/QFT.test`) checks whole-circuit
final states, not single gates. This tool drives the SAME gate sequences as
``quest_tpu.algorithms.qft``/``grover`` through the locally-built reference
libQuEST (gate-for-gate: hadamard, controlledPhaseShift, swapGate, pauliX,
multiControlledPhaseFlip) and stores the full final statevectors in
``tests/golden_ref/algor.json``; ``tests/test_golden_ref.py`` replays the
framework's *compiled-circuit* path (the TPU fast path, including supergate
fusion and the Pallas layer collector) against them at 1e-10.

Usage::

    sh tools/build_reference.sh
    python tools/ref_algor_gen.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ref_golden_gen import LIB_PATH, Ref, _ints, _load  # noqa: E402


def ref_qft(ref: Ref, n: int, qtype: str) -> np.ndarray:
    q = ref.prepare(qtype, n)
    lib = ref.lib
    for t in range(n - 1, -1, -1):
        lib.hadamard(q, t)
        for k, ctrl in enumerate(range(t - 1, -1, -1), start=2):
            lib.controlledPhaseShift(q, ctrl, t, 2.0 * np.pi / (1 << k))
    for t in range(n // 2):
        lib.swapGate(q, t, n - 1 - t)
    state = ref.state(q)
    lib.destroyQureg(q, ref.env)
    return state


def ref_grover(ref: Ref, n: int, marked: int, iters: int) -> np.ndarray:
    """Oracle/diffusion with X-sandwiched multiControlledPhaseFlip — exactly
    equivalent (in floating point too: X permutes, the flip negates) to the
    framework's flipped-control formulation."""
    lib = ref.lib
    q = lib.createQureg(n, ref.env)
    lib.initZeroState(q)
    all_qubits = _ints(range(n))
    for t in range(n):
        lib.hadamard(q, t)
    for _ in range(iters):
        zero_bits = [b for b in range(n) if not (marked >> b) & 1]
        for b in zero_bits:
            lib.pauliX(q, b)
        lib.multiControlledPhaseFlip(q, all_qubits, n)
        for b in zero_bits:
            lib.pauliX(q, b)
        for t in range(n):
            lib.hadamard(q, t)
        for t in range(n):
            lib.pauliX(q, t)
        lib.multiControlledPhaseFlip(q, all_qubits, n)
        for t in range(n):
            lib.pauliX(q, t)
        for t in range(n):
            lib.hadamard(q, t)
    state = ref.state(q)
    lib.destroyQureg(q, ref.env)
    return state


def main(out_path: str) -> None:
    ref = Ref(_load(LIB_PATH))
    entries = []
    for n in (3, 5, 7):
        for qtype in "zpd":
            entries.append({
                "algorithm": "qft", "n": n, "qtype": qtype,
                "state": [[a.real, a.imag] for a in ref_qft(ref, n, qtype)],
            })
    for n, marked, iters in ((3, 5, 2), (5, 19, 4), (7, 100, 6)):
        entries.append({
            "algorithm": "grover", "n": n, "marked": marked, "iters": iters,
            "state": [[a.real, a.imag]
                      for a in ref_grover(ref, n, marked, iters)],
        })
    with open(out_path, "w") as f:
        json.dump(entries, f)
    print(f"wrote {out_path} ({len(entries)} states)")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "golden_ref", "algor.json"))
