"""Shared output helper for the ``tools/*_trace.py`` dumpers.

Every trace tool (comm, serve, chaos, precision, obs console ``--json``)
emits ONE JSON document. Before this helper each tool invented its own
top-level shape, so downstream consumers (dashboards, the obs console,
regression diffs) had no way to tell which tool — or which VERSION of
which tool — produced a file. Now every dump starts with the same
versioned header:

``{"schema": "quest_tpu.trace/1", "kind": "<tool>",
"generated_wall": <epoch seconds>, ...tool payload...}``

and every tool grows the same ``--out FILE`` flag (default: stdout),
via :func:`add_output_argument` + :func:`emit`. Bump the schema suffix
when a BREAKING payload change ships; additive keys don't bump it.
"""

from __future__ import annotations

import json
import sys
import time

TRACE_SCHEMA = "quest_tpu.trace/1"

__all__ = ["TRACE_SCHEMA", "add_output_argument", "wrap", "emit"]


def add_output_argument(parser) -> None:
    """The shared ``--out`` flag (written atomically enough for a tool:
    one open/write/close; default stdout)."""
    parser.add_argument("--out", default=None, metavar="FILE",
                        help="write the JSON dump to FILE instead of "
                             "stdout")


def wrap(doc: dict, kind: str) -> dict:
    """The versioned header, prepended (header keys win on collision so
    a payload can never masquerade as a different schema/kind)."""
    payload = {k: v for k, v in doc.items()
               if k not in ("schema", "kind", "generated_wall")}
    return {"schema": TRACE_SCHEMA, "kind": kind,
            "generated_wall": round(time.time(), 6), **payload}


def emit(doc: dict, kind: str, out=None, indent: int = 2) -> dict:
    """Wrap ``doc`` with the schema header and write it to ``out``
    (a path from the ``--out`` flag) or stdout. Returns the wrapped
    document."""
    wrapped = wrap(doc, kind)
    if out:
        with open(out, "w") as fh:
            json.dump(wrapped, fh, indent=indent, default=str)
            fh.write("\n")
    else:
        json.dump(wrapped, sys.stdout, indent=indent, default=str)
        print()
    return wrapped
