"""Standalone TPU liveness probe + mini-benchmark for the axon tunnel.

Run (optionally in the background):  python tools/tpu_probe.py
Prints timestamped progress lines so a log tail shows exactly how far
backend init got (the r1/r2 failure mode was an indefinite hang inside
``jax.devices()`` when no chip grant arrives).
"""

import os
import sys
import time

T0 = time.time()


def log(msg):
    print(f"[{time.time() - T0:8.1f}s] {msg}", flush=True)


def main():
    log(f"python {sys.version.split()[0]}; JAX_PLATFORMS="
        f"{os.environ.get('JAX_PLATFORMS')}")
    import jax
    log(f"jax {jax.__version__} imported; calling jax.devices() ...")
    d = jax.devices()
    log(f"devices: {d} (platform={d[0].platform})")

    import jax.numpy as jnp
    t = time.time()
    x = jnp.ones((2048, 2048), jnp.bfloat16)
    y = (x @ x).block_until_ready()
    log(f"2048^2 bf16 matmul (compile+run): {time.time() - t:.1f}s")

    t = time.time()
    y = (x @ x).block_until_ready()
    log(f"matmul again (cached): {time.time() - t:.3f}s")

    # mini gate-layer benchmark: 20-qubit statevector, f32 planes
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import quest_tpu as qt
    env = qt.createQuESTEnv(num_devices=1, seed=[7])
    n = int(os.environ.get("PROBE_QUBITS", "20"))
    q = qt.createQureg(n, env)
    t = time.time()
    qt.initZeroState(q)
    q.state.block_until_ready()
    log(f"initZeroState({n}) device-side: {time.time() - t:.1f}s")

    from bench import build_bench_circuit
    circ, n_gates = build_bench_circuit(n, 1)
    t = time.time()
    cc = circ.compile(env)
    cc.run(q)
    q.state.block_until_ready()
    log(f"compile+first-run {n_gates}-gate layer at {n}q: {time.time() - t:.1f}s")

    t = time.time()
    trials = 5
    for _ in range(trials):
        cc.run(q)
    q.state.block_until_ready()
    dt = time.time() - t
    log(f"{trials} trials: {dt:.3f}s -> {n_gates * trials / dt:,.0f} gates/s")


if __name__ == "__main__":
    main()
