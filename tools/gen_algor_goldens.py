"""Generate the algorithm-tier golden files (reference: tests/algor/).

QFT.test mirrors the reference's QFTtests data file
(`/root/reference/tests/algor/QFT.test:26-38`): the zero-state register is
QFT-transformed twice, with the full state stored after each transform.
grover.test stores the marked-state hit probability after each Grover
iteration. Both files are replayed by tests/test_algor.py on every
configuration (single device + 8-device mesh).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import quest_tpu as qt  # noqa: E402
from quest_tpu import algorithms as alg  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "..", "tests", "golden", "algor")
N_QFT = 5
N_GROVER = 6
MARKED = 41


def write_state(f, q):
    for a in q.to_numpy():
        f.write(f"{float(a.real)!r} {float(a.imag)!r}\n")


def main():
    os.makedirs(OUT, exist_ok=True)
    env = qt.createQuESTEnv(num_devices=1, seed=[12345])

    q = qt.createQureg(N_QFT, env)
    qt.initZeroState(q)
    qft = alg.qft(N_QFT).compile(env)
    with open(os.path.join(OUT, "QFT.test"), "w") as f:
        f.write(f"# golden-algor QFT\n{N_QFT}\n")
        qft.run(q)
        write_state(f, q)
        qft.run(q)
        write_state(f, q)

    with open(os.path.join(OUT, "grover.test"), "w") as f:
        f.write(f"# golden-algor grover\n{N_GROVER} {MARKED}\n")
        for iters in range(1, 7):
            q = qt.createQureg(N_GROVER, env)
            qt.initZeroState(q)
            alg.grover(N_GROVER, MARKED, num_iterations=iters).compile(env).run(q)
            f.write(f"{qt.getProbAmp(q, MARKED)!r}\n")

    print("wrote", os.path.join(OUT, "QFT.test"), "and grover.test")


if __name__ == "__main__":
    main()
