#!/usr/bin/env python
"""Diff two performance snapshots and gate regressions.

The bench trajectory was never persisted: each ``bench.py`` run printed
JSON rows and exited, so "did this PR make serving slower" had no
machine answer. This tool closes that gap. It reads two snapshots —
each either

- a perf-ledger directory (``bench.py --ledger DIR`` /
  ``$QUEST_BENCH_LEDGER_DIR``; rows live in ``DIR/bench.jsonl`` with
  the ``quest_tpu.perf/1`` schema),
- a ``BENCH_*.json`` file (the driver's JSON-lines relay), or
- any ``.jsonl``/``.json`` file of bench result rows

— matches rows by their ``metric`` name, and exits nonzero when any
compared metric regressed by more than ``--threshold`` percent
(default 20). Units decide direction: ``s`` (and other pure-time
units) regress UP, throughput units (``*/sec``) regress DOWN. Rows
with value 0.0 (error/skip/heartbeat sentinels) and ``repeat: true``
headline re-emissions are ignored. ``--metric SUBSTR`` (repeatable)
restricts the comparison to named metrics.

Pure stdlib — runs in CI without jax (wired as a smoke step in
``.github/workflows/ci.yml``).

Usage::

    python tools/perf_compare.py BENCH_old.json BENCH_new.json
    python tools/perf_compare.py ledger_main/ ledger_pr/ --threshold 10
    python tools/perf_compare.py old.json new.json --metric requests/sec
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# units where a LOWER value is better (everything else is throughput-
# shaped: higher is better)
_LOWER_BETTER_UNITS = ("s", "seconds", "ms", "us")


def load_rows(path: str) -> list:
    """Bench result rows from a ledger dir, a JSON-lines file, or a
    JSON list/dict file."""
    if os.path.isdir(path):
        path = os.path.join(path, "bench.jsonl")
    rows = []
    with open(path) as fh:
        text = fh.read()
    text = text.strip()
    if not text:
        return rows
    if text.startswith("["):
        try:
            doc = json.loads(text)
            return [r for r in doc if isinstance(r, dict)]
        except ValueError:
            pass
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw:
            continue
        try:
            row = json.loads(raw)
        except ValueError:
            continue              # torn/noise line: skip, never crash
        if isinstance(row, dict):
            rows.append(row)
    # a ledger dir accumulates every `bench.py --ledger` run: keep only
    # the LATEST run's rows (bench_run is parent-stamped per
    # invocation), or an older faster row would mask a fresh regression
    # through the best-of-duplicates pick below
    runs = {str(r["bench_run"]) for r in rows if r.get("bench_run")}
    if runs:
        latest = max(runs)
        rows = [r for r in rows
                if str(r.get("bench_run", latest)) == latest]
    return rows


def index_metrics(rows: list) -> dict:
    """``{metric: (value, unit)}`` over the real result rows (value >
    0, not a ``repeat`` re-emission). A metric emitted twice keeps its
    BEST value — re-runs in one stream are retries, and scheduler noise
    only ever adds time."""
    out: dict = {}
    for row in rows:
        try:
            metric = str(row["metric"])
            value = float(row.get("value", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        if value <= 0.0 or row.get("repeat"):
            continue
        unit = str(row.get("unit", ""))
        prev = out.get(metric)
        if prev is None:
            out[metric] = (value, unit)
        else:
            lower = prev[1] in _LOWER_BETTER_UNITS
            better = value < prev[0] if lower else value > prev[0]
            if better:
                out[metric] = (value, unit)
    return out


def compare(old: dict, new: dict, threshold_pct: float,
            metric_filters=()) -> dict:
    """``{"compared": [...], "regressions": [...], "only_old": [...],
    "only_new": [...]}`` — one entry per common metric with the signed
    percent change (positive = improved)."""
    common = sorted(set(old) & set(new))
    if metric_filters:
        common = [m for m in common
                  if any(f.lower() in m.lower() for f in metric_filters)]
    compared = []
    regressions = []
    for metric in common:
        ov, unit = old[metric]
        nv, _ = new[metric]
        lower = unit in _LOWER_BETTER_UNITS
        # signed improvement: positive is better in BOTH directions
        change_pct = ((ov - nv) / ov if lower else (nv - ov) / ov) * 100.0
        entry = {"metric": metric, "unit": unit, "old": ov, "new": nv,
                 "change_pct": round(change_pct, 2),
                 "lower_is_better": lower,
                 "regressed": change_pct < -threshold_pct}
        compared.append(entry)
        if entry["regressed"]:
            regressions.append(entry)
    return {"compared": compared, "regressions": regressions,
            "only_old": sorted(set(old) - set(new)),
            "only_new": sorted(set(new) - set(old)),
            "threshold_pct": threshold_pct}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline snapshot: ledger dir, "
                                "BENCH_*.json, or .jsonl of rows")
    ap.add_argument("new", help="candidate snapshot (same forms)")
    ap.add_argument("--threshold", type=float, default=20.0,
                    metavar="PCT",
                    help="regression gate: fail when any compared "
                         "metric is worse by more than PCT percent "
                         "(default 20)")
    ap.add_argument("--metric", action="append", default=[],
                    metavar="SUBSTR",
                    help="compare only metrics whose name contains "
                         "SUBSTR (repeatable; default: all common)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full comparison as JSON")
    args = ap.parse_args(argv)

    try:
        old = index_metrics(load_rows(args.old))
        new = index_metrics(load_rows(args.new))
    except OSError as e:
        print(f"perf_compare: cannot read snapshot: {e}",
              file=sys.stderr)
        return 2
    result = compare(old, new, args.threshold, args.metric)
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        for e in result["compared"]:
            flag = "REGRESSED" if e["regressed"] else "ok"
            print(f"{flag:>9}  {e['change_pct']:+7.1f}%  "
                  f"{e['old']:.4g} -> {e['new']:.4g} {e['unit']}  "
                  f"{e['metric']}")
        if result["only_old"]:
            print(f"only in old ({len(result['only_old'])}): "
                  + "; ".join(result["only_old"][:5]))
        if result["only_new"]:
            print(f"only in new ({len(result['only_new'])}): "
                  + "; ".join(result["only_new"][:5]))
    if not result["compared"]:
        print("perf_compare: no common metrics to compare",
              file=sys.stderr)
        return 2
    if result["regressions"]:
        print(f"perf_compare: {len(result['regressions'])} metric(s) "
              f"regressed past {args.threshold:g}%", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
