#!/usr/bin/env python
"""Dump the planned precision-tier decision for a circuit/budget as JSON.

Offline inspection for the precision-tier budget API (quest_tpu/config
``PrecisionTier`` + quest_tpu/profiling ``choose_tier``): for a given
circuit and error budget, print the full ladder with each tier's modeled
per-run error, availability on this environment, and runtime fidelity
tolerance; the chosen tier; and the bounded escalation path the serving
runtime would walk on repeated fidelity violations. No device work:
tier selection is a host-side model evaluation, so the tool runs
anywhere (the ``comm_trace``/``chaos_trace`` pattern).

Usage::

    python tools/precision_trace.py --qubits 16 --circuit hea --budget 1e-2
    python tools/precision_trace.py --circuit qft --budget 1e-6
    python tools/precision_trace.py --circuit grover --tier fast
"""

from __future__ import annotations

import argparse
import sys


def trace_tiers(circ, env, budget=None, tier=None) -> dict:
    """The tier decision for one recorded circuit as a plain dict
    (JSON-ready): the modeled ladder, the budget's choice (or the
    pinned tier), and the escalation path up the engine ladder."""
    import quest_tpu as qt
    from quest_tpu.profiling import (choose_tier, engine_tiers,
                                     modeled_tier_error, tier_error_model,
                                     tier_runtime_tol)

    num_gates = max(len(circ.ops), 1)
    # ONE resolved model for the whole report: the ladder rows, the
    # selection, and the tolerances must all use the same constants
    # (the env-calibrated model when one exists), or a row could show
    # modeled_error <= budget for a tier the selector rejected
    model = tier_error_model(env)
    avail = engine_tiers(env)
    avail_names = {t.name for t in avail}
    ladder = []
    for t in qt.TIER_LADDER:
        ladder.append({
            "tier": t.name,
            "rank": t.rank,
            "drift_per_gate": model.drift_per_gate.get(
                t.name, t.drift_per_gate),
            "modeled_error": modeled_tier_error(t, num_gates, model),
            "matmul_precision": t.matmul_precision,
            "compensated": t.compensated,
            "real_dtype": str(t.real_dtype),
            "engine_available": t.name in avail_names,
            "runtime_tol": tier_runtime_tol(t, num_gates, model),
        })
    chosen = None
    rejected = None
    if tier is not None:
        chosen = qt.tier_by_name(tier)
    elif budget is not None:
        try:
            chosen = choose_tier(float(budget), num_gates, env,
                                 model=model)
        except ValueError as e:
            rejected = str(e)
    escalation = []
    if chosen is not None:
        escalation = [t.name for t in avail if t.rank > chosen.rank]
    out = {
        "num_qubits": circ.num_qubits,
        "num_gates": num_gates,
        "error_budget": budget,
        "tier_model_source": model.source,
        "ladder": ladder,
        "chosen_tier": chosen.name if chosen is not None else None,
        "modeled_error": (modeled_tier_error(chosen, num_gates, model)
                          if chosen is not None else None),
        "runtime_tol": (tier_runtime_tol(chosen, num_gates, model)
                        if chosen is not None else None),
        # the serving runtime's bounded recovery walk: one rung per
        # fidelity violation, typed failure past the top
        "escalation_path": escalation,
    }
    if rejected is not None:
        out["budget_rejected"] = rejected
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--qubits", type=int, default=16)
    ap.add_argument("--circuit", choices=("qft", "grover", "hea"),
                    default="hea")
    ap.add_argument("--budget", type=float, default=None,
                    help="error budget (max amplitude error); the tool "
                         "reports the cheapest tier whose modeled error "
                         "fits, or the typed rejection")
    ap.add_argument("--tier", default=None,
                    help="pin a tier by name instead of budget-selecting")
    ap.add_argument("--layers", type=int, default=2,
                    help="HEA layers (hea circuit only)")
    import os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _trace_io
    _trace_io.add_output_argument(ap)
    args = ap.parse_args(argv)
    if args.budget is None and args.tier is None:
        args.budget = 1e-2

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("QUEST_TPU_TIER_MODEL", "default")
    repo_root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             os.pardir)
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)

    import quest_tpu as qt
    from quest_tpu import algorithms as alg

    env = qt.createQuESTEnv(num_devices=1, seed=[0])
    if args.circuit == "qft":
        circ = alg.qft(args.qubits)
    elif args.circuit == "grover":
        circ = alg.grover(args.qubits, marked=(1 << args.qubits) - 3,
                          num_iterations=2)
    else:
        from bench import build_hea_circuit
        circ, _, _ = build_hea_circuit(args.qubits, args.layers)
    _trace_io.emit(trace_tiers(circ, env, budget=args.budget,
                               tier=args.tier),
                   kind="precision", out=args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
