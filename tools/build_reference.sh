#!/bin/sh
# Build the reference QuEST as a serial, double-precision shared library
# (out of tree -- nothing is written under /root/reference).
# Used only to REGENERATE tests/golden_ref/; the committed golden files
# replay without it.
set -e
REF=${1:-/root/reference}
OUT=${2:-/tmp/refbuild}
mkdir -p "$OUT"
gcc -O2 -fPIC -shared -DQuEST_PREC=2 \
  -I"$REF/QuEST/include" -I"$REF/QuEST/src" \
  "$REF/QuEST/src/QuEST.c" \
  "$REF/QuEST/src/QuEST_common.c" \
  "$REF/QuEST/src/QuEST_validation.c" \
  "$REF/QuEST/src/QuEST_qasm.c" \
  "$REF/QuEST/src/mt19937ar.c" \
  "$REF/QuEST/src/CPU/QuEST_cpu.c" \
  "$REF/QuEST/src/CPU/QuEST_cpu_local.c" \
  -lm -o "$OUT/libquest_ref.so"
echo "$OUT/libquest_ref.so"
