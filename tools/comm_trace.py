#!/usr/bin/env python
"""Dump a compiled circuit's planned collective schedule as JSON.

Offline inspection for the communication-aware planner
(quest_tpu/parallel/layout.py): every collective the compiled program
will launch — relayout ``all_to_all``/``ppermute`` exchanges and
cross-shard 1q pair exchanges — with modeled bytes, exchanged-bit count,
and the fused-group (op item) index it serves, plus the plan's dispatch
stats and comm totals. No device work: planning is host-side, so the
tool runs anywhere (the virtual-mesh flag is set before JAX loads).

Usage::

    python tools/comm_trace.py --qubits 18 --devices 8 --circuit qft
    python tools/comm_trace.py --circuit grover --planner off
    python tools/comm_trace.py --hosts 2 --reorder off

``--planner off`` traces the count-based legacy plan for comparison.
``--hosts H`` plans as if the mesh spanned ``H`` controller processes
(``QUEST_TPU_FORCE_HOSTS``; quest_tpu/parallel/multihost.py): every
collective is annotated with the interconnect tier it rides
(``intra``/``inter`` host) and the dump carries per-tier byte totals —
the observable the hot-qubit reordering pass (``--reorder off`` for its
baseline) is graded on.
"""

from __future__ import annotations

import argparse
import sys


def trace_schedule(cc) -> dict:
    """The planned collective schedule of a CompiledCircuit as a plain
    dict (JSON-ready): one event per plan item that moves data. On a
    multi-host mesh (or under ``--hosts``) every event carries the
    interconnect tier it rides (``intra``/``inter``) and the totals
    split per tier."""
    from quest_tpu.parallel.layout import (_relayout_sigma,
                                           relayout_comm_tiered,
                                           plan_comm_stats)
    from quest_tpu.profiling import DEFAULT_COMM_MODEL

    plan = cc.plan
    n = plan.num_qubits
    lt = n - plan.shard_bits
    model = getattr(cc, "_cost_model", None) or DEFAULT_COMM_MODEL
    chunk_bytes = getattr(cc, "_chunk_bytes", 16.0 * (1 << lt))
    num_devices = cc.env.num_devices
    host_bits = getattr(cc, "_host_bits", 0)
    from quest_tpu.parallel.multihost import inter_host_positions
    inter_pos = set(inter_host_positions(n, plan.shard_bits, host_bits))

    def serves(idx: int):
        """Index (into plan.items) of the first op the collective
        localises — the fused group it serves."""
        for j in range(idx + 1, len(plan.items)):
            if plan.items[j][0] in ("op", "xshard"):
                return j
        return None

    events = []
    for idx, it in enumerate(plan.items):
        if it[0] == "relayout":
            sigma = _relayout_sigma(it[1], it[2], n)
            t = relayout_comm_tiered(sigma, lt, chunk_bytes, model,
                                     host_bits=host_bits)
            k = sum(1 for p in range(lt) if sigma[p] >= lt)
            events.append({
                "item": idx, "kind": "relayout",
                "exchanged_bits": int(k),
                "collectives": int(t["launches"]),
                "bytes_per_device": t["bytes"],
                "mesh_bytes": t["bytes"] * num_devices,
                "modeled_seconds": t["seconds"],
                "tier": "inter" if t["inter_launches"] else "intra",
                "inter_collectives": int(t["inter_launches"]),
                "inter_mesh_bytes": t["inter_bytes"] * num_devices,
                "fused_group": serves(idx),
            })
        elif it[0] == "xshard":
            x_inter = int(it[2][0]) in inter_pos
            events.append({
                "item": idx, "kind": "pair_exchange",
                "exchanged_bits": 1,
                "collectives": 1,
                "bytes_per_device": model.ppermute_bytes(chunk_bytes),
                "mesh_bytes": model.ppermute_bytes(chunk_bytes)
                * num_devices,
                "modeled_seconds": model.ppermute_seconds(
                    chunk_bytes, inter=x_inter),
                "tier": "inter" if x_inter else "intra",
                "inter_collectives": int(x_inter),
                "inter_mesh_bytes": model.ppermute_bytes(chunk_bytes)
                * num_devices if x_inter else 0.0,
                "fused_group": idx,
                "op_index": it[1],
                "position": int(it[2][0]),
            })
    totals = plan_comm_stats(plan, chunk_bytes, model, num_devices,
                             host_bits=host_bits)
    totals["intra_bytes"] = totals["bytes"] - totals["inter_bytes"]
    inter_a, inter_b = model.tier(inter=True)
    return {
        "num_qubits": n,
        "shard_bits": plan.shard_bits,
        "num_devices": num_devices,
        "num_hosts": getattr(cc, "_num_hosts", 1),
        "host_bits": host_bits,
        "chunk_bytes": chunk_bytes,
        "cost_model": {"alpha_s": model.alpha_s,
                       "beta_s_per_byte": model.beta_s_per_byte,
                       "inter_alpha_s": inter_a,
                       "inter_beta_s_per_byte": inter_b,
                       "source": model.source},
        "events": events,
        "totals": totals,
        "dispatch_stats": cc.dispatch_stats().as_dict(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--qubits", type=int, default=18)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--circuit", choices=("qft", "grover", "bench"),
                    default="qft")
    ap.add_argument("--planner", choices=("on", "off"), default="on")
    ap.add_argument("--hosts", type=int, default=None,
                    help="plan as if the mesh spanned H controller "
                         "processes (QUEST_TPU_FORCE_HOSTS): events gain "
                         "intra/inter tier annotations and per-tier "
                         "totals")
    ap.add_argument("--reorder", choices=("on", "off"), default="on",
                    help="hot-qubit-local reordering pass (off = the "
                         "tier-priced but tier-blind baseline)")
    ap.add_argument("--lookahead", type=int, default=32)
    ap.add_argument("--fusion", type=int, default=None,
                    help="gate-fusion cap k (default: compile default)")
    import os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _trace_io
    _trace_io.add_output_argument(ap)
    args = ap.parse_args(argv)

    # virtual mesh before the first JAX import, so the tool runs on any
    # host (planning is host-side; no kernels execute)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{args.devices}").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.hosts is not None:
        # deterministic two-tier planning without a multi-process launch
        os.environ["QUEST_TPU_FORCE_HOSTS"] = str(args.hosts)
        os.environ.setdefault("QUEST_TPU_COMM_MODEL", "default")

    repo_root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             os.pardir)
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)

    import quest_tpu as qt
    from quest_tpu import algorithms as alg

    env = qt.createQuESTEnv(num_devices=args.devices, seed=[0])
    if args.circuit == "qft":
        circ = alg.qft(args.qubits)
    elif args.circuit == "grover":
        circ = alg.grover(args.qubits, marked=(1 << args.qubits) - 3,
                          num_iterations=4)
    else:
        from bench import build_bench_circuit
        circ, _ = build_bench_circuit(args.qubits, 1)
    kw = {}
    if args.fusion is not None:
        kw["fusion"] = args.fusion
    cc = circ.compile(env, pallas="off",
                      comm_planner=(args.planner == "on"),
                      reorder=(args.reorder == "on"),
                      lookahead=args.lookahead, **kw)
    _trace_io.emit(trace_schedule(cc), kind="comm", out=args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
