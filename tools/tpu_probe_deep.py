"""Opportunistic live-TPU deep probe: more evidence than bench.py's sweep.

bench.py is budget-shaped for the driver's bounded grant window; this tool
assumes a LIVE tunnel and digs: large-state HBM-bound throughput (where the
roofline argument actually bites), Pallas-vs-XLA at sizes past VMEM
residency, and a real-silicon replay of the Pallas layer parity oracle that
`tests/test_pallas_layers.py` can only run in interpret mode on CPU.

Each probe emits one JSON row (same schema as bench.py) and flushes, so a
tunnel death mid-run still leaves every completed row on stdout.

Usage:  python tools/tpu_probe_deep.py [probe ...]
        probes: big pallas_scale parity density  (default: all)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def emit(row: dict) -> None:
    row.setdefault("unix_ts", round(time.time(), 1))
    print(json.dumps(row), flush=True)


def probe_big(qt, platform: str) -> None:
    """Large statevectors: 24..29 qubits. Past ~24q the state exceeds
    VMEM, so gates pay real HBM passes — this is the regime where the
    reference's A100 numbers live (BASELINE.json 38q is multi-GPU; the
    per-device slice is what one chip sees)."""
    import bench
    env = qt.createQuESTEnv(num_devices=1, seed=[2026])
    for nq in (24, 26, 28, 29):
        try:
            t0 = time.perf_counter()
            row = bench.bench_gate_throughput(
                qt, env, platform, nq, layers=2, trials=3,
                metric="1q+CNOT sustained gate throughput", pallas="off")
            row["compile_plus_run_s"] = round(time.perf_counter() - t0, 1)
            emit(row)
        # quest: allow-broad-except(probe boundary: every failure
        # is emitted as an error row, the probe keeps going)
        except Exception as e:
            emit({"metric": f"big {nq}q (error)", "value": 0.0,
                  "unit": "gates/sec", "vs_baseline": 0.0,
                  "errors": [f"{type(e).__name__}: {e}"[:300]]})
            break    # OOM at nq likely implies OOM at nq+1 too


def probe_pallas_scale(qt, platform: str) -> None:
    """Pallas fused layers vs per-gate XLA at sizes where the state no
    longer sits in VMEM: the fusion's 1-pass-per-layer economy should
    show as a bandwidth multiple, not just dispatch-overhead removal."""
    import bench
    env = qt.createQuESTEnv(num_devices=1, seed=[2026])
    for nq in (22, 24, 26):
        try:
            emit(bench.bench_pallas_compare(qt, env, platform, nq, trials=3))
        # quest: allow-broad-except(probe boundary: every failure
        # is emitted as an error row, the probe keeps going)
        except Exception as e:
            emit({"metric": f"pallas scale {nq}q (error)", "value": 0.0,
                  "unit": "gates/sec", "vs_baseline": 0.0,
                  "errors": [f"{type(e).__name__}: {e}"[:300]]})
            break


def probe_parity(qt, platform: str) -> None:
    """Real-silicon replay of the interpret-mode Pallas oracle: random
    brickwork through the layer collector with pallas on vs off, compared
    at complex64 tolerance. This is `tests/test_pallas_layers.py`'s oracle
    executed through Mosaic instead of interpret mode."""
    from quest_tpu.circuits import Circuit
    rng = np.random.default_rng(7)
    worst = 0.0
    cases = 0
    for nq in (8, 10, 12, 14):
        env = qt.createQuESTEnv(num_devices=1, seed=[11])
        c = Circuit(nq)
        for layer in range(4):
            for q in range(nq):
                c.rotate(q, float(rng.uniform(0, 2 * np.pi)),
                         rng.normal(size=3))
            for q in range(layer % 2, nq - 1, 2):
                c.cnot(q, q + 1)
            c.phase(nq - 1, float(rng.uniform(0, np.pi)))
        ref = qt.createQureg(nq, env)
        c.compile(env, pallas=False).run(ref)
        got = qt.createQureg(nq, env)
        c.compile(env, pallas=True).run(got)
        dev = float(np.max(np.abs(ref.to_numpy() - got.to_numpy())))
        worst = max(worst, dev)
        cases += 1
    emit({"metric": f"pallas real-silicon parity, {cases} brickwork "
                    f"circuits 8-14q ({platform})",
          "value": worst, "unit": "max-amp-deviation",
          "vs_baseline": 0.0, "pass": bool(worst < 1e-5)})


def probe_density(qt, platform: str) -> None:
    """Density-matrix + channel throughput — the mixed-state path's
    behavior on silicon."""
    import bench
    env = qt.createQuESTEnv(num_devices=1, seed=[2026])
    try:
        emit(bench.bench_density_noise(qt, env, platform))
    # quest: allow-broad-except(probe boundary: every failure is
    # emitted as an error row, the probe keeps going)
    except Exception as e:
        emit({"metric": "density probe (error)", "value": 0.0,
              "unit": "gates/sec", "vs_baseline": 0.0,
              "errors": [f"{type(e).__name__}: {e}"[:300]]})


PROBES = {"big": probe_big, "pallas_scale": probe_pallas_scale,
          "parity": probe_parity, "density": probe_density}


def main() -> None:
    import jax
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # quest: allow-broad-except(probe boundary: cache knobs are
    # best-effort on whatever jax version the probe runs against)
    except Exception:
        pass
    platform = jax.devices()[0].platform
    emit({"metric": "tpu_probe_deep start", "value": 1.0, "unit": "session",
          "vs_baseline": 0.0, "platform": platform,
          "device": str(jax.devices()[0])})
    import quest_tpu as qt
    names = sys.argv[1:] or list(PROBES)
    for name in names:
        PROBES[name](qt, platform)


if __name__ == "__main__":
    main()
