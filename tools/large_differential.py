"""Large-register differential: the native executor vs the reference
binary at 28 qubits (or ``--qubits N``), full-state compare.

Reproduces the figure recorded in README.md ("28-qubit spot differential
... bit-identical"): |+>^N through low/mid/top-qubit gates including a
3-qubit dense unitary, every one of the 2^N amplitudes compared. Needs
the locally-built reference library (tools/build_reference.sh; ~8 GB RAM
at 28 qubits for the two f64 states).

Run: python tools/large_differential.py [--qubits 28]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ref_golden_gen import Ref, _load, ADAPTERS  # noqa: E402
from quest_tpu.circuits import Circuit  # noqa: E402

LIB = os.environ.get("QUEST_REF_LIB", "/tmp/refbuild/libquest_ref.so")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--qubits", type=int, default=28)
    n = ap.parse_args().qubits

    if not os.path.exists(LIB):
        import subprocess
        subprocess.run(["sh", os.path.join(os.path.dirname(__file__),
                                           "build_reference.sh")],
                       check=True, capture_output=True, timeout=300)
    ref = Ref(_load(LIB))
    rq = ref.prepare("p", n)

    rng = np.random.default_rng(3)
    c = Circuit(n)
    moves = []
    c.h(0)
    moves.append(("hadamard", (0,)))
    c.h(n - 1)
    moves.append(("hadamard", (n - 1,)))
    th = float(rng.uniform(0, 2 * np.pi))
    al, be = complex(np.cos(th), 0), complex(np.sin(th), 0)
    c.gate(np.array([[al, -np.conj(be)], [be, np.conj(al)]]), (n // 2,))
    moves.append(("compactUnitary", (n // 2, al, be)))
    c.cnot(2, n - 2)
    moves.append(("controlledNot", (2, n - 2)))
    c.phase(n - 8, 1.1)
    moves.append(("phaseShift", (n - 8, 1.1)))
    m = rng.normal(size=(8, 8)) + 1j * rng.normal(size=(8, 8))
    u3, _ = np.linalg.qr(m)
    c.gate(u3, (5, n // 2 + 1, n - 1))
    moves.append(("multiQubitUnitary", ((5, n // 2 + 1, n - 1), u3)))
    c.cphase(1, n - 3, 0.7)
    moves.append(("controlledPhaseShift", (1, n - 3, 0.7)))

    t0 = time.perf_counter()
    for name, args in moves:
        ADAPTERS[name](ref, rq, args)
    print(f"reference: {len(moves)} ops in "
          f"{time.perf_counter() - t0:.1f} s")

    prog = c.compile_native(threads=1)
    re, im = prog.init_plus()
    t0 = time.perf_counter()
    prog.run(re, im)
    print(f"native:    {len(moves)} ops in "
          f"{time.perf_counter() - t0:.1f} s")

    err = float(np.max(np.abs((re + 1j * im) - ref.state(rq))))
    print(f"{n}-qubit differential: worst |delta| = {err:.3e} "
          f"over {1 << n:,} amplitudes")
    ref.lib.destroyQureg(rq, ref.env)
    assert err < 1e-12, err
    print("PASS")


if __name__ == "__main__":
    main()
