#!/usr/bin/env python
"""Dump the planned gradient/optimizer schedule as JSON.

Offline inspection for the gradient serving stack (ISSUE 15): replays
the SAME policies the live path uses — the coalescer's padded batch
bucket (:func:`quest_tpu.serve.coalesce.batch_bucket`) for a ``B``-
request gradient group, the priced sharding decision
(:func:`quest_tpu.parallel.layout.choose_batch_sharding` at the
gradient executables' ``mem_factor=2.0`` — primal + cotangent resident
together), the trajectory-gradient wave plan
(:func:`quest_tpu.ops.trajectories.plan_waves`) when ``--trajectories``
is given, and a modeled optimizer convergence schedule: iterate values
decay geometrically at ``--rate`` toward the stated floor, and the
decision point is the first iterate whose modeled ``|Δvalue|`` fits
``--tol`` (the live loop measures; the planner can only be told). Pure
host-side planning: no device work, no gradients run.

Usage::

    python tools/grad_trace.py --qubits 16 --params 32 --batch 64 \\
        --max-iters 50 --tol 1e-4 --rate 0.8
    python tools/grad_trace.py --qubits 20 --params 16 --devices 8 \\
        --trajectories 1024 --budget 0.02
"""

from __future__ import annotations

import argparse
import os
import sys


def trace_schedule(num_qubits: int, num_params: int, batch: int,
                   num_devices: int, itemsize: int,
                   num_relayouts: int = 0,
                   trajectories: int = 0, wave_size: int = 0,
                   sampling_budget=None, sigma: float = 1.0,
                   max_iters: int = 0, tol: float = 0.0,
                   rate: float = 0.9, v0: float = 1.0,
                   v_floor: float = 0.0) -> dict:
    """The planned gradient schedule + optimizer decision points,
    JSON-ready."""
    from quest_tpu.parallel.layout import choose_batch_sharding
    from quest_tpu.serve.coalesce import batch_bucket

    mult = num_devices if num_devices > 1 else 1
    # trajectory gradients coalesce at the plain power-of-two bucket
    # (the trajectory axis owns the mesh); deterministic gradients pad
    # to the device multiple like energy sweeps
    bucket = batch_bucket(batch, floor=1 if trajectories else mult)
    # the sharded axis: request rows for the adjoint path, request
    # rows x wave draws for the trajectory path (estimated at the
    # request bucket — the wave bucket multiplies in below)
    policy = choose_batch_sharding(
        num_qubits, bucket, num_devices, itemsize, num_relayouts,
        mem_factor=2.0)
    doc = {
        "num_qubits": num_qubits,
        "num_params": num_params,
        "num_devices": num_devices,
        "batch_requests": batch,
        "batch_bucket": bucket,
        "padded_rows": bucket - batch,
        "transfer_block": [bucket, num_params + 1],
        # what the one-executable path collapses: the parameter-shift
        # client pays (2P+1) energy dispatches per row
        "host_syncs_avoided": bucket * (2 * num_params + 1) - 1,
        "sharding": {
            "mode": policy["mode"],
            "mem_factor": 2.0,
            "per_device_bytes": policy.get("per_device_bytes", 0.0),
            "amp_comm_seconds": policy.get("amp_comm_seconds", 0.0),
        },
    }
    if trajectories:
        from quest_tpu.ops.trajectories import plan_waves
        if wave_size < 1:
            wave_size = min(trajectories, max(32, mult))
        waves, wbucket = plan_waves(trajectories, wave_size, mult)
        # all P+1 components must fit the budget; the value component
        # converges at sigma/sqrt(n) under the stated spread
        n_star = None
        if sampling_budget:
            import math
            n_star = max(2, math.ceil(
                (sigma / float(sampling_budget)) ** 2))
        wave_events = []
        cum = 0
        stop = None
        for i, (start, live) in enumerate(waves):
            cum += live
            stops = n_star is not None and cum >= n_star and stop is None
            if stops:
                stop = i
            wave_events.append({
                "wave": i, "start": start, "live": live,
                "bucket": wbucket, "cumulative": cum,
                "early_stop": bool(stops),
            })
        doc["trajectory_grad"] = {
            "max_trajectories": trajectories,
            "wave_bucket": wbucket,
            "components": num_params + 1,
            "sampling_budget": (float(sampling_budget)
                                if sampling_budget else None),
            "projected_stop_after": n_star,
            "early_stop_wave": stop,
            "waves": wave_events,
        }
    if max_iters:
        events = []
        v_prev = None
        decided = None
        v = float(v0)
        for k in range(max_iters):
            delta = None if v_prev is None else abs(v - v_prev)
            converged = (decided is None and delta is not None
                         and delta <= tol)
            if converged:
                decided = k
            events.append({
                "iteration": k, "modeled_value": round(v, 12),
                "modeled_delta": (round(delta, 12)
                                  if delta is not None else None),
                "converged": bool(converged),
            })
            v_prev = v
            v = v_floor + (v - v_floor) * float(rate)
            if decided is not None:
                break
        doc["optimizer"] = {
            "max_iters": max_iters,
            "tol": tol,
            "rate": float(rate),
            "decision_iteration": decided,
            "projected_iterations": len(events),
            "projected_gradient_dispatches": len(events),
            "events": events,
        }
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--qubits", type=int, default=16)
    ap.add_argument("--params", type=int, default=32,
                    help="declared circuit parameters P (the gradient "
                         "width; the transfer block is (B, P+1))")
    ap.add_argument("--batch", type=int, default=64,
                    help="coalesced gradient requests per dispatch")
    ap.add_argument("--devices", type=int, default=1)
    ap.add_argument("--itemsize", type=int, default=8,
                    help="bytes per real amplitude component")
    ap.add_argument("--relayouts", type=int, default=0,
                    help="planned relayouts (the amp-mode collective "
                         "count per batch row)")
    ap.add_argument("--trajectories", type=int, default=0,
                    help="max draws for a TRAJECTORY gradient (0 = "
                         "deterministic adjoint path)")
    ap.add_argument("--wave", type=int, default=0,
                    help="wave size (0 = the engine's default bucket)")
    ap.add_argument("--budget", type=float, default=None,
                    help="sampling budget (target standard error, all "
                         "P+1 components)")
    ap.add_argument("--sigma", type=float, default=1.0,
                    help="per-trajectory standard deviation estimate")
    ap.add_argument("--max-iters", type=int, default=0,
                    help="model an optimizer run of this many iterates")
    ap.add_argument("--tol", type=float, default=1e-6,
                    help="convergence tolerance on |delta value|")
    ap.add_argument("--rate", type=float, default=0.9,
                    help="modeled geometric convergence rate per "
                         "iterate")
    ap.add_argument("--v0", type=float, default=1.0,
                    help="modeled starting objective value")
    ap.add_argument("--floor", type=float, default=0.0,
                    help="modeled objective floor the iterates decay "
                         "toward")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _trace_io
    _trace_io.add_output_argument(ap)
    args = ap.parse_args(argv)

    repo_root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             os.pardir)
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    # the planner is pure host-side policy; keep even an accidental
    # backend probe off the TPU tunnel
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    doc = trace_schedule(args.qubits, args.params, args.batch,
                         args.devices, args.itemsize,
                         num_relayouts=args.relayouts,
                         trajectories=args.trajectories,
                         wave_size=args.wave,
                         sampling_budget=args.budget, sigma=args.sigma,
                         max_iters=args.max_iters, tol=args.tol,
                         rate=args.rate, v0=args.v0,
                         v_floor=args.floor)
    _trace_io.emit(doc, kind="grad", out=args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
