#!/usr/bin/env python
"""Replay a serving trace under a seeded fault schedule; dump the
recovery timeline as JSON.

Chaos testing for the fault-tolerant serving runtime
(quest_tpu/resilience + quest_tpu/serve): builds a hardware-efficient
ansatz, submits a deterministic request trace to a
:class:`SimulationService` with a seeded
:class:`~quest_tpu.resilience.FaultInjector` installed at the dispatch
boundaries, and prints everything an incident review needs:

- the **recovery timeline** (the service's event ring: faults,
  retries with backoff, quarantine bisections, breaker transitions,
  degraded-mode entries, poisoned-row isolations, watchdog stalls);
- the **injection accounting** (per-site/per-kind counts — every
  injected fault must be visible next to the recovery it caused);
- per-request **outcomes** (completed vs typed failure, by exception
  class) and — with ``--oracle`` — energy parity against the
  sequential fault-free loop, asserting NO silent wrong answers;
- the full service metrics snapshot.

With ``--replicas N`` (N >= 2) the trace runs through a
:class:`~quest_tpu.serve.router.ServiceRouter` instead of a bare
service, and the replica-level fault kinds come alive:
``replica_crash`` / ``replica_stall`` fire at the ``router.route``
boundary and are applied to the replica the router was about to pick
(the supervisor must quarantine it, fail traffic over, restart it, and
readmit it through the half-open probe). The dump then carries the
router metrics, per-replica service snapshots, and the router event
timeline next to the per-replica ones.

Usage::

    python tools/chaos_trace.py --requests 64 --fault-rate 0.05
    python tools/chaos_trace.py --kinds transient,oom,nan --seed 11
    python tools/chaos_trace.py --requests 128 --sites 'serve.*' --oracle
    python tools/chaos_trace.py --replicas 2 --kinds replica_crash \
        --sites router.route --at-calls 9 --oracle

Deterministic: same arguments -> same schedule -> same timeline shape.
Runs on the CPU backend by default (``--backend default`` uses whatever
JAX picks).
"""

from __future__ import annotations

import argparse
import os
import sys


def build_trace(args) -> dict:
    import numpy as np
    import quest_tpu as qt
    from quest_tpu.circuits import Circuit
    from quest_tpu.resilience import (FaultInjector, FaultSpec,
                                      SupervisorPolicy, inject)
    from quest_tpu.serve import ServiceRouter, SimulationService, \
        replica_envs

    replicated = args.replicas > 1
    env = qt.createQuESTEnv(num_devices=args.devices, seed=[args.seed])
    n = args.qubits
    c = Circuit(n)
    for q in range(n):
        c.ry(q, c.parameter(f"y{q}"))
    for q in range(n - 1):
        c.cnot(q, q + 1)
    cc = c.compile(env)
    rng = np.random.default_rng(args.seed)
    pm = rng.uniform(0.0, 2.0 * np.pi, size=(args.requests, n))
    terms = [[(q, 3)] for q in range(n)]          # sum_q Z_q
    coeffs = [1.0] * n
    ham = (terms, coeffs)

    kinds = [k for k in args.kinds.split(",") if k]
    at_calls = tuple(int(i) for i in args.at_calls.split(",") if i)
    specs = []
    for j, k in enumerate(kinds):
        # explicit call indices round-robin over the kinds (only the
        # first matching spec fires per call, so handing every kind the
        # same schedule would shadow all but the first)
        mine = tuple(c for i, c in enumerate(at_calls)
                     if i % len(kinds) == j)
        specs.append(FaultSpec(kind=k, site=args.sites,
                               probability=args.fault_rate,
                               at_calls=mine))
    inj = FaultInjector(specs, seed=args.seed, stall_s=args.stall_s)

    policy = qt.ResiliencePolicy(
        seed=args.seed, backoff_base_s=1e-3, backoff_cap_s=0.05,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=0.05, degrade_after=args.degrade_after,
        degrade_cooldown_s=0.1, watchdog_timeout_s=args.watchdog_s)
    svc_kwargs = dict(
        max_batch=args.max_batch, max_wait_s=2e-3,
        max_queue=args.requests + args.max_batch,
        request_timeout_s=args.timeout_s, max_retries=args.max_retries,
        resilience=policy, record_events=4 * args.requests + 64)
    if replicated:
        envs = replica_envs(args.replicas,
                            devices_per_replica=args.devices,
                            seed=[args.seed])
        svc = ServiceRouter(
            envs, supervisor=SupervisorPolicy(
                poll_s=0.01, stall_timeout_s=max(0.4, 4 * args.stall_s),
                restart_backoff_s=0.02),
            warm_cache=False, **svc_kwargs)
        # warm every bucket the trace can hit so only injected faults
        # perturb the schedule (and failover dispatches stay cheap)
        bs, sizes = 1, []
        while bs <= args.max_batch:
            sizes.append(bs)
            bs *= 2
        svc.warm(c, batch_sizes=sizes, observables=ham)
        submit_to = c          # route by the recorded circuit
    else:
        svc = SimulationService(env, **svc_kwargs)
        submit_to = cc

    outcomes = []
    with inject(inj):
        if not replicated:
            svc.pause()
        futs = [svc.submit(submit_to, dict(zip(cc.param_names, row)),
                           observables=ham) for row in pm]
        if not replicated:
            svc.resume()
        for f in futs:
            try:
                outcomes.append(("ok", float(f.result(
                    timeout=args.timeout_s + 30))))
            # quest: allow-broad-except(replay boundary: the dump
            # RECORDS every failure class -- that is the tool's job)
            except Exception as e:  # typed failure — record its class
                outcomes.append((type(e).__name__, None))
        stats = svc.dispatch_stats()
        # timeline() warns once when the service was built with
        # record_events=0 — this tool's whole output is that ring
        timeline = svc.timeline()
    svc.close()

    by_error: dict = {}
    for kind, _ in outcomes:
        if kind != "ok":
            by_error[kind] = by_error.get(kind, 0) + 1
    completed = sum(1 for k, _ in outcomes if k == "ok")

    doc = {
        "config": {
            "requests": args.requests, "qubits": n,
            "devices": args.devices, "replicas": args.replicas,
            "seed": args.seed,
            "fault_rate": args.fault_rate, "kinds": args.kinds,
            "sites": args.sites, "max_batch": args.max_batch,
            "max_retries": args.max_retries,
        },
        "fault_injection": inj.snapshot(),
        "outcomes": {
            "completed": completed,
            "typed_failures": by_error,
            "unaccounted": args.requests - completed
            - sum(by_error.values()),
        },
        "timeline": timeline,
    }
    if replicated:
        doc["router"] = stats.get("router", {})
        doc["replicas"] = stats.get("replicas", [])
    else:
        doc["service"] = stats.get("service", {})
        doc["resilience"] = stats.get("resilience", {})

    if args.oracle:
        # sequential fault-free loop: injector is uninstalled here, so
        # these are the true energies; every COMPLETED request must
        # match (typed failures are allowed; silent wrong answers not)
        codes_flat = []
        for t in range(len(terms)):
            row = [0] * n
            for q, code in terms[t]:
                row[q] = code
            codes_flat.extend(row)
        failures = 0
        max_dev = 0.0
        for i, (kind, got) in enumerate(outcomes):
            if kind != "ok":
                continue
            q = qt.createQureg(n, env)
            qt.initZeroState(q)
            cc.run(q, dict(zip(cc.param_names, pm[i])))
            want = qt.calcExpecPauliSum(q, codes_flat, coeffs)
            dev = abs(got - want)
            max_dev = max(max_dev, dev)
            if dev > args.parity_tol:
                failures += 1
        doc["parity"] = {"checked": completed, "failures": failures,
                         "max_deviation": max_dev,
                         "tol": args.parity_tol}
    return doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--qubits", type=int, default=4)
    p.add_argument("--devices", type=int, default=1,
                   help="devices per env (with --replicas: per replica)")
    p.add_argument("--replicas", type=int, default=1,
                   help=">= 2 routes the trace through a ServiceRouter "
                        "(replica_crash/replica_stall fault kinds need "
                        "this)")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--fault-rate", type=float, default=0.05,
                   help="per-dispatch injection probability per kind")
    p.add_argument("--at-calls", default="",
                   help="comma list of exact call indices to fault "
                        "(deterministic schedule, round-robin over "
                        "--kinds; composes with --fault-rate)")
    p.add_argument("--kinds", default="transient,nan",
                   help="comma list of transient|oom|nan|stall|"
                        "replica_crash|replica_stall")
    p.add_argument("--sites", default="serve.execute",
                   help="fnmatch pattern over fault sites "
                        "(e.g. '*', 'circuits.*', 'router.route')")
    p.add_argument("--stall-s", type=float, default=0.02)
    p.add_argument("--max-batch", type=int, default=8)
    p.add_argument("--max-retries", type=int, default=3)
    p.add_argument("--breaker-threshold", type=int, default=6)
    p.add_argument("--degrade-after", type=int, default=4)
    p.add_argument("--watchdog-s", type=float, default=5.0)
    p.add_argument("--timeout-s", type=float, default=120.0)
    p.add_argument("--parity-tol", type=float, default=1e-10)
    p.add_argument("--oracle", action="store_true",
                   help="verify completed energies against the "
                        "sequential fault-free loop")
    p.add_argument("--backend", default="cpu",
                   help="'cpu' (default, deterministic) or 'default'")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _trace_io
    _trace_io.add_output_argument(p)
    args = p.parse_args(argv)

    repo_root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             os.pardir)
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    if args.backend == "cpu":
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        jax.config.update("jax_platforms", "cpu")

    doc = build_trace(args)
    _trace_io.emit(doc, kind="chaos", out=args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
