"""Measure single-precision accuracy vs circuit depth (VERDICT r1 #6).

Runs the same random brickwork circuit (bench.py's workload) at f32 and f64
on CPU, and reports per-depth:
  - max |amp_f32 - amp_f64| over the full state (per-gate rounding drift);
  - calcTotalProb absolute error in f32, naive vs compensated reduction,
    against the f64 value.

Usage: python tools/accuracy_table.py [num_qubits] [depths...]
Writes a markdown table to stdout (pasted into docs/accuracy.md).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from bench import build_bench_circuit  # noqa: E402


def run(num_qubits: int, layers: int, precision, compensated: bool):
    env = qt.createQuESTEnv(num_devices=1, seed=[2026], precision=precision,
                            compensated=compensated)
    q = qt.createQureg(num_qubits, env)
    qt.initPlusState(q)
    circ, n_gates = build_bench_circuit(num_qubits, layers)
    circ.compile(env).run(q)
    return q.to_numpy(), qt.calcTotalProb(q), n_gates


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    layer_list = [int(a) for a in sys.argv[2:]] or [2, 8, 32, 64]
    print(f"| gates (at {n}q) | max state |Δ| f32 vs f64 "
          f"| reduction err, naive f32 | reduction err, compensated f32 "
          f"| totalProb err vs f64 golden (comp) |")
    print("|---|---|---|---|---|")
    for layers in layer_list:
        ref, p_ref, n_gates = run(n, layers, qt.DOUBLE, False)
        s_naive, p_naive, _ = run(n, layers, qt.SINGLE, False)
        _, p_comp, _ = run(n, layers, qt.SINGLE, True)
        state_err = float(np.max(np.abs(s_naive - ref)))
        # exact (f64 host) totalProb of the *same* f32 state isolates
        # reduction error from per-gate amplitude drift
        p_exact_f32 = float(np.sum(np.abs(s_naive.astype(np.complex128)) ** 2))
        print(f"| {n_gates} | {state_err:.2e} "
              f"| {abs(p_naive - p_exact_f32):.2e} "
              f"| {abs(p_comp - p_exact_f32):.2e} "
              f"| {abs(p_comp - p_ref):.2e} |")


if __name__ == "__main__":
    main()
