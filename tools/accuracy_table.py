"""Measure single-precision accuracy vs circuit depth (VERDICT r1 #6),
plus the FAST-tier (bf16-input matmul) drift envelope that seeds the
precision-tier error model (ISSUE 8).

Table 1 — the same random brickwork circuit (bench.py's workload) at f32
and f64 on CPU, reporting per-depth:
  - max |amp_f32 - amp_f64| over the full state (per-gate rounding drift);
  - calcTotalProb absolute error in f32, naive vs compensated reduction,
    against the f64 value.

Table 2 — the FAST tier's lane-matmul drift, measured on the Pallas
layer kernel's exact lane-stage shape ((rows, 128) state x 128x128
unitaries): bf16-rounded inputs emulate the MXU's Precision.DEFAULT
passes on any host, comparing NAIVE bf16 accumulation against the FAST
tier's bf16-split COMPENSATED form (state split error-free into a bf16
hi plane plus residual, two bf16 passes, residual partial sums combined
small-to-large in f32 — ops/pallas_kernels.py). The per-gate constants
in quest_tpu/config.TIER_LADDER are seeded from this table.

Usage: python tools/accuracy_table.py [num_qubits] [depths...]
Writes markdown tables to stdout (pasted into docs/accuracy.md).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import quest_tpu as qt  # noqa: E402
from bench import build_bench_circuit  # noqa: E402


def run(num_qubits: int, layers: int, precision, compensated: bool):
    env = qt.createQuESTEnv(num_devices=1, seed=[2026], precision=precision,
                            compensated=compensated)
    q = qt.createQureg(num_qubits, env)
    qt.initPlusState(q)
    circ, n_gates = build_bench_circuit(num_qubits, layers)
    circ.compile(env).run(q)
    return q.to_numpy(), qt.calcTotalProb(q), n_gates


def f32_table(n: int, layer_list) -> None:
    print(f"| gates (at {n}q) | max state |Δ| f32 vs f64 "
          f"| reduction err, naive f32 | reduction err, compensated f32 "
          f"| totalProb err vs f64 golden (comp) |")
    print("|---|---|---|---|---|")
    for layers in layer_list:
        ref, p_ref, n_gates = run(n, layers, qt.DOUBLE, False)
        s_naive, p_naive, _ = run(n, layers, qt.SINGLE, False)
        _, p_comp, _ = run(n, layers, qt.SINGLE, True)
        state_err = float(np.max(np.abs(s_naive - ref)))
        # exact (f64 host) totalProb of the *same* f32 state isolates
        # reduction error from per-gate amplitude drift
        p_exact_f32 = float(np.sum(np.abs(s_naive.astype(np.complex128)) ** 2))
        print(f"| {n_gates} | {state_err:.2e} "
              f"| {abs(p_naive - p_exact_f32):.2e} "
              f"| {abs(p_comp - p_exact_f32):.2e} "
              f"| {abs(p_comp - p_ref):.2e} |")


# ---------------------------------------------------------------------------
# FAST-tier (bf16 lane matmul) drift — the tier error model's seed
# ---------------------------------------------------------------------------

def _bf16(x):
    """Round f32 operands to bf16 — the rounding the MXU applies to
    Precision.DEFAULT inputs, reproducible on any backend."""
    return x.astype(jnp.bfloat16)


def _lane_step(re, im, mr, mi, mode):
    """One lane-stage complex matmul (ops/pallas_kernels._layer_kernel's
    math) at one precision mode."""
    f32 = jnp.float32
    if mode == "f64":
        return re @ mr - im @ mi, re @ mi + im @ mr
    if mode == "naive":
        def dot(a, b):
            return jnp.dot(_bf16(a), _bf16(b), preferred_element_type=f32)
        return (dot(re, mr) - dot(im, mi), dot(re, mi) + dot(im, mr))

    # "compensated": the FAST tier's bf16-split form — the state operand
    # splits error-free into a bf16 hi plane plus the f32 residual (two
    # bf16 passes whose f32 partial sums recover the state's value),
    # and the small residual partials combine FIRST so their correction
    # lands in one f32 add (ops/pallas_kernels.py's fast lane stage)
    def cdot(v, m):
        hi = _bf16(v).astype(f32)
        lo = v - hi
        mb = _bf16(m)
        return (jnp.dot(_bf16(hi), mb, preferred_element_type=f32),
                jnp.dot(_bf16(lo), mb, preferred_element_type=f32))

    rr_h, rr_l = cdot(re, mr)
    ii_h, ii_l = cdot(im, mi)
    ri_h, ri_l = cdot(re, mi)
    ir_h, ir_l = cdot(im, mr)
    return ((rr_h - ii_h) + (rr_l - ii_l),
            (ri_h + ir_h) + (ri_l + ir_l))


def fast_tier_table(num_qubits: int, layer_list) -> None:
    """Per-depth max amplitude drift of the bf16 lane stage, naive vs
    FAST-tier compensated, against the f64 run of the SAME unitaries."""
    rng = np.random.default_rng(2026)
    rows = (1 << num_qubits) // 128
    z = rng.normal(size=(rows, 128)) + 1j * rng.normal(size=(rows, 128))
    z /= np.linalg.norm(z)
    print(f"| lane matmuls (at {num_qubits}q) "
          f"| max amp |Δ| bf16 naive | bf16-split compensated (FAST) "
          f"| naive/gate | compensated/gate |")
    print("|---|---|---|---|---|")
    max_layers = max(layer_list)
    states = {
        "f64": (jnp.asarray(z.real), jnp.asarray(z.imag)),
        "naive": (jnp.asarray(z.real, jnp.float32),
                  jnp.asarray(z.imag, jnp.float32)),
        "comp": (jnp.asarray(z.real, jnp.float32),
                 jnp.asarray(z.imag, jnp.float32)),
    }
    done = 0
    for layers in sorted(layer_list):
        for _ in range(layers - done):
            u = np.linalg.qr(rng.normal(size=(128, 128))
                             + 1j * rng.normal(size=(128, 128)))[0]
            ops = {"f64": (jnp.asarray(u.real), jnp.asarray(u.imag))}
            ops["naive"] = ops["comp"] = (
                jnp.asarray(u.real, jnp.float32),
                jnp.asarray(u.imag, jnp.float32))
            for mode, (re, im) in states.items():
                mr, mi = ops[mode]
                states[mode] = _lane_step(
                    re, im, mr, mi,
                    "compensated" if mode == "comp" else mode)
        done = layers
        ref = (np.asarray(states["f64"][0])
               + 1j * np.asarray(states["f64"][1]))
        devs = {}
        for mode in ("naive", "comp"):
            got = (np.asarray(states[mode][0], np.float64)
                   + 1j * np.asarray(states[mode][1], np.float64))
            devs[mode] = float(np.max(np.abs(got - ref)))
        print(f"| {layers} | {devs['naive']:.2e} | {devs['comp']:.2e} "
              f"| {devs['naive'] / layers:.2e} "
              f"| {devs['comp'] / layers:.2e} |")


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    layer_list = [int(a) for a in sys.argv[2:]] or [2, 8, 32, 64]
    f32_table(n, layer_list)
    print()
    print("FAST tier (bf16-input lane matmuls), same depth ladder:")
    print()
    fast_tier_table(min(n, 16), layer_list)


if __name__ == "__main__":
    main()
