"""The reference's own benchmark procedure, reproduced exactly.

Mirrors ``/root/reference/tests/benchmarks/rotate_benchmark.test:10-60``:
an n-qubit zero register, ``nTrials`` timed ``compactUnitary`` calls per
target qubit (same alpha/beta derived from the same angle triple), logging
``qubit, mean, stdev, max-mean, mean-min`` per target — apples-to-apples
with the reference binary for the per-gate (imperative-dispatch) path.
A second sweep times the same probe through a compiled single-gate circuit
(parameter-free, one cached executable per target) to show the dispatch
overhead the compiled path removes.

Usage: python tools/rotate_benchmark.py [nQubits] [nTrials]
(the reference uses 29 qubits / 20 trials; defaults here are 24/20 so the
CPU fallback finishes quickly — pass 29 on a real chip)
"""

import os
import statistics
import sys
import time
from math import cos, sin


def timed_sweep(apply_once, n_trials):
    """One untimed warm-up (excludes the per-shape jit trace the
    reference's C kernels never pay), then n_trials timed calls;
    returns (mean, stdev, max, min)."""
    apply_once()
    timing = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        apply_once()
        timing.append(time.perf_counter() - t0)
    return (statistics.mean(timing), statistics.stdev(timing),
            max(timing), min(timing))

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    n_qubits = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    n_trials = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    if n_trials < 2:
        sys.exit("nTrials must be >= 2 (stdev needs two data points)")

    import jax
    if os.environ.get("ROTBENCH_FORCE_CPU", "0") == "1":
        jax.config.update("jax_platforms", "cpu")
    import quest_tpu as qt

    env = qt.createQuESTEnv(num_devices=1, seed=[2026])
    q = qt.createQureg(n_qubits, env)
    qt.initZeroState(q)

    ang = [1.2320, 0.4230, -0.6523]          # angles[0] of the reference
    alpha = complex(cos(ang[0]) * cos(ang[1]), cos(ang[0]) * sin(ang[1]))
    beta = complex(sin(ang[0]) * cos(ang[2]), sin(ang[0]) * sin(ang[2]))

    print(qt.getEnvironmentString(env))
    print(f"Rotating ({n_qubits} qubits, {n_trials} trials/target)")
    print("qubit, mean, stdev, max-mean, mean-min   [imperative per-gate]")
    for target in range(n_qubits):
        def once(t=target):
            qt.compactUnitary(q, t, alpha, beta)
            q.state.block_until_ready()
        mean, sd, mx, mn = timed_sweep(once, n_trials)
        print(f"{target}, {mean:.6e}, {sd:.6e}, "
              f"{mx - mean:.6e}, {mean - mn:.6e}")
    print("Done Rotating")
    print(f"Total probability conservation : {qt.calcTotalProb(q)}")

    # compiled-path sweep: one cached executable per target
    from quest_tpu.circuits import Circuit
    print("qubit, mean, stdev, max-mean, mean-min   [compiled circuit]")
    for target in range(n_qubits):
        c = Circuit(n_qubits)
        c.gate(
            [[alpha, -beta.conjugate()], [beta, alpha.conjugate()]],
            (target,))
        cc = c.compile(env)

        def once():
            cc.run(q)
            q.state.block_until_ready()
        mean, sd, mx, mn = timed_sweep(once, n_trials)
        print(f"{target}, {mean:.6e}, {sd:.6e}, "
              f"{mx - mean:.6e}, {mean - mn:.6e}")
    print("Done Rotating (compiled)")
    print(f"Total probability conservation : {qt.calcTotalProb(q)}")


if __name__ == "__main__":
    main()
