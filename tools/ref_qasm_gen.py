"""Regenerate tests/golden_ref/qasm_ref.txt from the reference binary.

Drives the reference's own QASM logger (libQuEST built by
``tools/build_reference.sh``) through the exact gate sequence of
``tests/test_qasm_parity.py::record_sequence`` and writes the transcript
the parity test compares against. Keep the two sequences in lockstep.

Usage::

    sh tools/build_reference.sh
    python tools/ref_qasm_gen.py
"""

from __future__ import annotations

import ctypes as ct
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ref_golden_gen import (  # noqa: E402
    LIB_PATH, Ref, Complex, Vector, _ints, _load, _m2)

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "golden_ref", "qasm_ref.txt")


def main() -> None:
    lib = _load(LIB_PATH)
    lib.startRecordingQASM.restype = None
    lib.writeRecordedQASMToFile.restype = None

    ref = Ref(lib)
    q = ref.prepare("z", 4)
    lib.startRecordingQASM(q)
    u = _m2(np.exp(0.4j) * np.array([[0.6, 0.8], [-0.8, 0.6]], complex))
    lib.hadamard(q, 0)
    lib.controlledNot(q, 0, 1)
    lib.rotateY(q, 2, ct.c_double(0.31))
    lib.rotateX(q, 3, ct.c_double(-1.2))
    lib.sGate(q, 1)
    lib.tGate(q, 0)
    lib.pauliX(q, 2)
    lib.pauliY(q, 3)
    lib.pauliZ(q, 0)
    lib.phaseShift(q, 1, ct.c_double(0.5))
    lib.controlledPhaseShift(q, 0, 2, ct.c_double(0.25))
    lib.multiControlledPhaseShift(q, _ints([0, 1]), 2, ct.c_double(0.75))
    lib.controlledPhaseFlip(q, 1, 3)
    lib.multiControlledPhaseFlip(q, _ints([0, 2, 3]), 3)
    lib.unitary(q, 1, u)
    lib.controlledUnitary(q, 0, 2, u)
    lib.multiControlledUnitary(q, _ints([1, 3]), 2, 2, u)
    lib.multiStateControlledUnitary(q, _ints([0, 3]), _ints([0, 1]), 2, 1, u)
    lib.compactUnitary(q, 0, Complex(0.6, 0.0), Complex(0.0, 0.8))
    lib.controlledCompactUnitary(q, 1, 0, Complex(0.6, 0.0),
                                 Complex(0.0, 0.8))
    lib.rotateAroundAxis(q, 1, ct.c_double(0.7), Vector(1.0, -2.0, 0.5))
    lib.controlledRotateAroundAxis(q, 2, 1, ct.c_double(0.7),
                                   Vector(1.0, -2.0, 0.5))
    lib.controlledRotateZ(q, 3, 0, ct.c_double(0.9))
    lib.swapGate(q, 0, 3)
    lib.sqrtSwapGate(q, 1, 2)
    lib.measure(q, 2)
    lib.writeRecordedQASMToFile(q, OUT.encode())
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
