"""quest-lint: repo-invariant static analysis for quest_tpu.

The stack enforces its correctness story by convention — tiers must key
every executable cache, every dispatch boundary must carry a fault hook
and a trace annotation, hot paths must avoid host syncs, 19 locks across
11 modules must keep a consistent acquisition order. This package turns
those conventions into *checked* named rules (QuEST itself dedicates a
whole layer to machine-checked preconditions — ``QuEST_validation.c``,
arXiv:1802.08032; quest-lint is that layer for THIS repo's invariants):

========  ============================================================
 rule      invariant
========  ============================================================
 QL001     no host sync (``float()`` / ``.item()`` / ``np.asarray()``
           / ``.block_until_ready()``) on a dispatch hot path
 QL002     every executable-cache insertion keys on tier + dtype +
           form (the PR-8 invariant)
 QL003     no bare ``except Exception`` outside the annotated
           allowlist
 QL004     every dispatch boundary fires a ``resilience.faults`` hook
           AND carries a trace annotation; no ``faults.SITES`` entry
           loses its ``fire()`` call
 QL005     every ``tools/*_trace.py`` emits the ``quest_tpu.trace/1``
           header through ``tools/_trace_io.py``
 QL006     the static lock-acquisition graph is a DAG, and no blocking
           call runs under a registry/metrics lock
 QL007     the planner constant tables mirrored between
           ``parallel/layout.py`` / ``profiling.py`` and
           ``native/src/scheduler.cc`` move together (mirror lock)
========  ============================================================

Pre-existing debt lives in a checked-in per-rule/per-file ratchet
baseline (``baseline.json``): the linter exits nonzero only on NEW
violations or a STALE baseline entry, so the bar can only tighten.
Suppression grammar: ``# quest: allow-<slug>(reason)`` on the violating
line or the line above (see ``docs/dev.md``).

Run ``python -m tools.quest_lint`` (or the ``quest-lint`` entry point);
``--update-baseline`` re-ratchets, ``--update-mirror`` re-locks QL007.
"""

__version__ = "1.0"
