"""QL007: native-mirror drift guard.

The layout planner's pricing rules live twice — once in Python
(``quest_tpu/parallel/layout.py`` + the cost model in
``quest_tpu/profiling.py``) and once in the native scheduler
(``native/src/scheduler.cc``), which must produce bit-identical plans
(``tests/test_native_sched.py`` checks behavior, but only for the cases
it enumerates). mpiQulacs-style hand-mirrored comm schedules are
exactly the drift hazard (PAPERS.md: arXiv 2203.16044): one side gets a
constant tweak, the twin silently keeps the old table, and plans
diverge only on inputs the parity tests never generate.

This guard makes the mirror *lockstep by construction*: named extracts
(functions / constant tables) are cut from both sides, normalized
(comments and whitespace dropped), hashed, and compared against the
checked-in ``mirror_lock.json``. ANY drift — either side — fails QL007
until the author re-locks with ``python -m tools.quest_lint
--update-mirror``, which is the attestation that the twin was reviewed.
A one-sided change therefore cannot merge unnoticed: it either fails
lint or carries an explicit re-lock in the same diff.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re

from .engine import Violation

HERE = os.path.dirname(os.path.abspath(__file__))
LOCK_PATH = os.path.join(HERE, "mirror_lock.json")

# Each group names ONE mirrored surface; its members must re-lock
# together. Python extracts address ``file::qualname`` (ast-resolved);
# C++ extracts address ``file::re:<start>..<end>`` line spans.
MIRROR_GROUPS = {
    "swap-absorption": (
        ("quest_tpu/parallel/layout.py", "py", "_SWAP_MAT"),
        ("quest_tpu/parallel/layout.py", "py", "is_swap_op"),
        ("native/src/scheduler.cc", "cc",
         (r"^bool is_swap\(", r"^\}")),
    ),
    "plan-item-kinds": (
        ("quest_tpu/native/__init__.py", "py", "KIND_U"),
        ("native/src/scheduler.cc", "cc",
         (r"^constexpr int KIND_U = ",
          r"^constexpr int KIND_DIAG_PARAM = ")),
        ("native/src/scheduler.cc", "cc",
         (r"^constexpr int ITEM_OP = ",
          r"^constexpr int ITEM_XSHARD = ")),
    ),
    "comm-cost-model": (
        ("quest_tpu/profiling.py", "py", "CommCostModel.tier"),
        ("quest_tpu/profiling.py", "py", "CommCostModel.all_to_all_bytes"),
        ("quest_tpu/profiling.py", "py", "CommCostModel.ppermute_bytes"),
        ("quest_tpu/profiling.py", "py", "DEFAULT_COMM_MODEL"),
        ("native/src/scheduler.cc", "cc",
         (r"^void tier_of\(", r"^\}")),
        ("native/src/scheduler.cc", "cc",
         (r"^double a2a_seconds\(", r"^\}")),
        ("native/src/scheduler.cc", "cc",
         (r"^double ppermute_seconds\(", r"^\}")),
    ),
    "relayout-pricing": (
        ("quest_tpu/parallel/layout.py", "py", "relayout_comm_tiered"),
        ("native/src/scheduler.cc", "cc",
         (r"^double relayout_seconds\(", r"^\}")),
    ),
}


def _normalize(lines) -> str:
    """Whitespace- and comment-insensitive canonical form: formatting
    churn must never read as drift."""
    out = []
    for ln in lines:
        ln = re.sub(r"//.*$", "", ln)
        ln = re.sub(r"(?<!['\"])#.*$", "", ln)
        ln = re.sub(r"\s+", " ", ln).strip()
        if ln:
            out.append(ln)
    return "\n".join(out)


def _py_segment(text: str, qualname: str):
    """Source lines of a module-level function/class-method/assignment
    named ``qualname`` (``Class.method`` or plain name)."""
    tree = ast.parse(text)
    parts = qualname.split(".")

    def find(body, name):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node.name == name:
                return node
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        return node
                    if isinstance(tgt, ast.Tuple) and any(
                            isinstance(e, ast.Name) and e.id == name
                            for e in tgt.elts):
                        return node
        return None

    node, body = None, tree.body
    for part in parts:
        node = find(body, part)
        if node is None:
            return None
        body = getattr(node, "body", [])
    lines = text.splitlines()
    # include decorators; end_lineno covers the whole statement
    start = min([node.lineno] + [d.lineno for d in getattr(
        node, "decorator_list", [])])
    return lines[start - 1:node.end_lineno]


def _cc_segment(text: str, start_re: str, end_re: str):
    """Inclusive line span from the first ``start_re`` match to the
    first subsequent ``end_re`` match."""
    lines = text.splitlines()
    start = None
    for i, ln in enumerate(lines):
        if start is None:
            if re.search(start_re, ln):
                start = i
        elif re.search(end_re, ln):
            return lines[start:i + 1]
    return None


def _member_key(spec) -> str:
    path, kind, sel = spec
    if kind == "py":
        return f"{path}::{sel}"
    return f"{path}::re:{sel[0]}"


def current_digests(root: str, groups=None) -> tuple:
    """``({group: {member_key: digest}}, [missing member messages])``"""
    groups = groups if groups is not None else MIRROR_GROUPS
    out: dict = {}
    missing: list = []
    cache: dict = {}
    for gname, members in groups.items():
        out[gname] = {}
        for spec in members:
            path, kind, sel = spec
            abspath = os.path.join(root, path)
            if path not in cache:
                try:
                    with open(abspath, "r", encoding="utf-8") as fh:
                        cache[path] = fh.read()
                except OSError:
                    cache[path] = None
            text = cache[path]
            key = _member_key(spec)
            if text is None:
                missing.append((gname, key, f"{path} is unreadable"))
                continue
            seg = _py_segment(text, sel) if kind == "py" else \
                _cc_segment(text, sel[0], sel[1])
            if seg is None:
                missing.append((gname, key,
                                f"extract {key} not found — the "
                                f"mirrored definition moved or was "
                                f"renamed"))
                continue
            digest = hashlib.sha256(
                _normalize(seg).encode()).hexdigest()[:16]
            out[gname][key] = digest
    return out, missing


def load_lock(path: str = LOCK_PATH) -> dict:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            return json.load(fh).get("groups", {})
    except OSError:
        return {}


def save_lock(root: str, path: str = LOCK_PATH) -> dict:
    digests, _missing = current_digests(root)
    doc = {
        "comment": "QL007 mirror lock: digests of the planner surfaces "
                   "mirrored between the Python layout/cost model and "
                   "native/src/scheduler.cc. Any drift on either side "
                   "fails lint until re-locked (python -m "
                   "tools.quest_lint --update-mirror) — re-locking "
                   "attests that the twin side was reviewed.",
        "version": 1,
        "groups": {g: dict(sorted(m.items()))
                   for g, m in sorted(digests.items())},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return digests


def check_mirror(root: str, lock_path: str = LOCK_PATH,
                 groups=None) -> list:
    digests, missing = current_digests(root, groups)
    locked = load_lock(lock_path)
    out = []
    for gname, key, msg in missing:
        out.append(Violation("QL007", "tools/quest_lint/mirror.py", 1,
                             f"native-mirror: [{gname}] {msg}"))
    if not locked:
        out.append(Violation(
            "QL007", "tools/quest_lint/mirror_lock.json", 1,
            "native-mirror: mirror_lock.json is missing or empty — "
            "run python -m tools.quest_lint --update-mirror and "
            "commit it"))
        return out
    for gname, members in digests.items():
        lock_members = locked.get(gname, {})
        drifted = sorted(k for k, d in members.items()
                         if lock_members.get(k) != d)
        stale = sorted(k for k in lock_members if k not in members)
        if drifted or stale:
            twins = sorted(set(members) - set(drifted))
            out.append(Violation(
                "QL007", drifted[0].split("::")[0] if drifted
                else "tools/quest_lint/mirror_lock.json", 1,
                f"native-mirror: mirrored surface [{gname}] drifted in "
                f"{', '.join(drifted + stale)}; this table is "
                f"hand-mirrored — update the twin side(s) "
                f"({', '.join(twins) or 'none'}) to match, then "
                f"re-lock with --update-mirror"))
    return out
